"""Extract the reference's per-task default_task_config literals via AST.

Walks every module under the reference checkout (default /root/reference/
cluster_tools), finds ``default_task_config`` staticmethods, and records the
dict literal passed to ``config.update({...})`` together with its
``task_name`` and file:line provenance.  Output: a frozen JSON consumed by
tests/test_config_parity.py — regenerate with

    python tools/extract_reference_defaults.py > tests/data/reference_task_defaults.json

Only literal keys/values are kept (the reference uses pure literals in these
dicts), so no reference code is executed.
"""

from __future__ import annotations

import ast
import json
import os
import sys

REFERENCE_ROOT = os.environ.get("CTT_REFERENCE", "/root/reference/cluster_tools")


def _literal(node):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return f"<non-literal:{ast.dump(node)[:40]}>"


def extract_file(path: str, rel: str):
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = []
    for cls in [n for n in tree.body if isinstance(n, ast.ClassDef)]:
        task_name = None
        for stmt in cls.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "task_name"
                and isinstance(stmt.value, ast.Constant)
            ):
                task_name = stmt.value.value
        fn = next(
            (
                s
                for s in cls.body
                if isinstance(s, ast.FunctionDef)
                and s.name == "default_task_config"
            ),
            None,
        )
        if fn is None or task_name is None:
            continue
        defaults = {}
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "update"
                and node.args
                and isinstance(node.args[0], ast.Dict)
            ):
                for k, v in zip(node.args[0].keys, node.args[0].values):
                    if isinstance(k, ast.Constant):
                        defaults[k.value] = _literal(v)
        out.append(
            {
                "task_name": task_name,
                "class": cls.name,
                "source": f"{rel}:{fn.lineno}",
                "defaults": defaults,
            }
        )
    return out


def main():
    records = []
    for dirpath, _, filenames in sorted(os.walk(REFERENCE_ROOT)):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, REFERENCE_ROOT)
            try:
                records.extend(extract_file(path, rel))
            except SyntaxError as e:
                print(f"skip {rel}: {e}", file=sys.stderr)
    json.dump(records, sys.stdout, indent=1, sort_keys=True)
    print()


if __name__ == "__main__":
    main()
