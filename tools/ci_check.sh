#!/usr/bin/env bash
# CI gate: static analysis first (fast, catches invariant violations before
# any test runs), then the tier-1 test selection from ROADMAP.md.
#
# Usage: tools/ci_check.sh            (from the repo root or anywhere)
set -uo pipefail

repo_root="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$repo_root"

echo "== ctt-lint (python -m cluster_tools_tpu.analysis --fail-on-findings) =="
JAX_PLATFORMS=cpu python -m cluster_tools_tpu.analysis --fail-on-findings
lint_rc=$?
if [ "$lint_rc" -ne 0 ]; then
    echo "ctt-lint failed (rc=$lint_rc) — fix the findings or suppress" \
         "documented false positives with '# ctt: noqa[CTTxxx] reason'" >&2
    exit "$lint_rc"
fi

echo "== tier-1 tests (ROADMAP.md) =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit "$rc"
