#!/usr/bin/env bash
# CI gate: static analysis first (fast, catches invariant violations before
# any test runs), then the tier-1 test selection from ROADMAP.md.
#
# Usage: tools/ci_check.sh            (from the repo root or anywhere)
set -uo pipefail

repo_root="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$repo_root"

echo "== ctt-lint (python -m cluster_tools_tpu.analysis --fail-on-findings) =="
JAX_PLATFORMS=cpu python -m cluster_tools_tpu.analysis --fail-on-findings
lint_rc=$?
if [ "$lint_rc" -ne 0 ]; then
    echo "ctt-lint failed (rc=$lint_rc) — fix the findings or suppress" \
         "documented false positives with '# ctt: noqa[CTTxxx] reason'" >&2
    exit "$lint_rc"
fi

echo "== ctt-obs smoke (traced workflow -> summarize; malformed -> nonzero) =="
obs_tmp="$(mktemp -d)"
trap 'rm -rf "$obs_tmp"' EXIT
JAX_PLATFORMS=cpu CTT_TRACE_DIR="$obs_tmp/trace" CTT_RUN_ID=ci_smoke \
    python - <<'PY'
import numpy as np
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import UniqueWorkflow
import os, tempfile
td = tempfile.mkdtemp()
path = os.path.join(td, "d.n5")
rng = np.random.default_rng(0)
file_reader(path).create_dataset(
    "seg", data=rng.integers(0, 50, (8, 16, 16)).astype(np.uint64),
    chunks=(4, 8, 8),
)
config_dir = os.path.join(td, "configs")
cfg.write_global_config(config_dir, {"block_shape": [4, 8, 8]})
wf = UniqueWorkflow(os.path.join(td, "tmp"), config_dir,
                    input_path=path, input_key="seg",
                    output_path=path, output_key="u")
assert build([wf])
PY
smoke_rc=$?
if [ "$smoke_rc" -ne 0 ]; then
    echo "obs smoke workflow failed (rc=$smoke_rc)" >&2
    exit "$smoke_rc"
fi
# summarize exits 0 only when the run holds >= 1 task span
JAX_PLATFORMS=cpu python -m cluster_tools_tpu.obs summarize \
    "$obs_tmp/trace/ci_smoke"
sum_rc=$?
if [ "$sum_rc" -ne 0 ]; then
    echo "obs summarize failed (rc=$sum_rc): traced run has no task spans" \
         "or is malformed" >&2
    exit "$sum_rc"
fi
# a malformed event file must exit nonzero (truncated/corrupt traces fail
# loudly instead of summarizing garbage)
echo "not json" >> "$obs_tmp/trace/ci_smoke/$(ls "$obs_tmp/trace/ci_smoke" \
    | grep '^spans\.' | head -1)"
if JAX_PLATFORMS=cpu python -m cluster_tools_tpu.obs summarize \
    "$obs_tmp/trace/ci_smoke" >/dev/null 2>&1; then
    echo "obs summarize accepted a malformed event file" >&2
    exit 1
fi

echo "== ctt-io pipeline smoke (depth-3 staged dispatch -> stage counters) =="
JAX_PLATFORMS=cpu CTT_TRACE_DIR="$obs_tmp/trace" CTT_RUN_ID=ci_pipeline \
    python - <<'PY'
import json, os, tempfile
import numpy as np
from cluster_tools_tpu.obs import metrics as obs_metrics, trace as obs_trace
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.tasks.threshold import ThresholdTask
from cluster_tools_tpu.utils import file_reader

td = tempfile.mkdtemp()
path = os.path.join(td, "d.n5")
rng = np.random.default_rng(0)
file_reader(path).create_dataset(
    "x", data=rng.random((16, 16, 16)).astype("float32"), chunks=(4, 8, 8)
)
config_dir = os.path.join(td, "configs")
cfg.write_global_config(
    config_dir,
    {"block_shape": [4, 8, 8], "target": "tpu", "device_batch_size": 1,
     "devices": [0], "pipeline_depth": 3},
)
t = ThresholdTask(os.path.join(td, "tmp"), config_dir,
                  input_path=path, input_key="x",
                  output_path=path, output_key="y")
assert build([t])
snap = obs_metrics.snapshot()["counters"]
stage_keys = [k for k in snap if k.startswith("executor.stage_")]
missing = [k for k in (
    "executor.stage_batches", "executor.stage_read_s",
    "executor.stage_compute_s", "executor.stage_write_s",
) if snap.get(k, 0) <= 0]
assert not missing, f"stage counters absent/zero: {missing} (have {stage_keys})"
obs_trace.flush()
print("pipeline smoke ok:",
      json.dumps({k: round(snap[k], 4) for k in sorted(stage_keys)}))
PY
pipe_rc=$?
if [ "$pipe_rc" -ne 0 ]; then
    echo "pipeline smoke failed (rc=$pipe_rc): depth-3 staged dispatch did" \
         "not run or stage counters missing" >&2
    exit "$pipe_rc"
fi
# the traced pipeline run must summarize cleanly too
JAX_PLATFORMS=cpu python -m cluster_tools_tpu.obs summarize \
    "$obs_tmp/trace/ci_pipeline"
pipe_sum_rc=$?
if [ "$pipe_sum_rc" -ne 0 ]; then
    echo "obs summarize failed on the pipeline smoke run (rc=$pipe_sum_rc)" >&2
    exit "$pipe_sum_rc"
fi

echo "== ctt-fault chaos smoke (seeded store faults + killed worker job) =="
chaos_tmp="$(mktemp -d)"
JAX_PLATFORMS=cpu CTT_TRACE_DIR="$obs_tmp/trace" CTT_RUN_ID=ci_chaos \
CTT_FAULTS="store.write:io_error:p=0.15;store.read:io_error:p=0.05;store.write:torn:once;worker.job:kill:ids=0,once;seed=42" \
CTT_FAULT_STATE_DIR="$chaos_tmp/fault_state" \
    python - "$chaos_tmp" <<'PY'
import hashlib, json, os, stat, sys

# the baseline run must be fault-free INCLUDING its worker subprocesses,
# which inherit this process's environment — pop the spec, re-arm later
CHAOS_SPEC = os.environ.pop("CTT_FAULTS")

import numpy as np
from scipy import ndimage

from cluster_tools_tpu import faults
from cluster_tools_tpu.obs import metrics as obs_metrics, trace as obs_trace
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows.watershed import WatershedWorkflow

td = sys.argv[1]

# stub scheduler (the fake-sbatch seam from tests/test_cluster_executor.py)
sched = os.path.join(td, "sched")
os.makedirs(sched, exist_ok=True)
submit, queue = os.path.join(sched, "submit"), os.path.join(sched, "queue")
with open(submit, "w") as f:
    f.write('#!/bin/bash\nscript="${@: -1}"\nbash "$script" >/dev/null 2>&1\n'
            'echo "Submitted batch job 1"\n')
with open(queue, "w") as f:
    f.write("#!/bin/bash\nexit 0\n")
for p in (submit, queue):
    os.chmod(p, os.stat(p).st_mode | stat.S_IEXEC)

rng = np.random.default_rng(0)
raw = ndimage.gaussian_filter(rng.random((24, 48, 48)), (1.0, 2.0, 2.0))
raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")


def run_ws(key, spec=None):
    if spec is None:
        os.environ.pop("CTT_FAULTS", None)
    else:
        os.environ["CTT_FAULTS"] = spec
    faults.configure()
    path = os.path.join(td, f"{key}.n5")
    file_reader(path).create_dataset("bnd", data=raw, chunks=(12, 24, 24))
    config_dir = os.path.join(td, f"configs_{key}")
    cfg.write_global_config(config_dir, {
        "block_shape": [12, 24, 24], "target": "slurm", "max_jobs": 3,
        "max_num_retries": 3, "retry_failure_fraction": 0.7,
        "poll_interval_s": 0.05, "sbatch_cmd": submit, "squeue_cmd": queue,
        "worker_env": {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"},
    })
    cfg.write_config(config_dir, "watershed", {
        "threshold": 0.5, "sigma_seeds": 1.6, "size_filter": 10,
        "halo": [2, 6, 6],
    })
    wf = WatershedWorkflow(
        os.path.join(td, f"tmp_{key}"), config_dir, max_jobs=3,
        input_path=path, input_key="bnd",
        output_path=path, output_key="ws",
    )
    try:
        assert build([wf]), f"{key} watershed build failed"
    finally:
        faults.reset()
        os.environ.pop("CTT_FAULTS", None)
    return path


def digest(root):
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


ref = run_ws("ref")
chaos = run_ws("chaos", CHAOS_SPEC)

np.testing.assert_array_equal(
    file_reader(chaos, "r")["ws"][:], file_reader(ref, "r")["ws"][:]
)
assert digest(os.path.join(chaos, "ws")) == digest(os.path.join(ref, "ws")), \
    "chaos output not byte-identical to the fault-free run"

# recovery must be VISIBLE: sum counters over the driver + every worker
obs_metrics.flush()
totals = {}
run_dir = obs_trace.run_dir()
for name in os.listdir(run_dir):
    if name.startswith("metrics.p"):
        with open(os.path.join(run_dir, name)) as f:
            for k, v in json.load(f)["counters"].items():
                totals[k] = totals.get(k, 0) + v
assert totals.get("faults.injected", 0) > 0, f"no faults injected: {totals}"
assert totals.get("store.io_retries", 0) > 0, f"no IO retries: {totals}"
# the killed worker job really died (latched once across resubmissions)
latches = os.listdir(os.environ["CTT_FAULT_STATE_DIR"])
assert any(l.startswith("worker.job.") for l in latches), latches
print("chaos smoke ok:", json.dumps({
    k: round(v, 2) for k, v in sorted(totals.items())
    if k.startswith(("faults.", "store.io_retries"))
}))
PY
chaos_rc=$?
rm -rf "$chaos_tmp"
if [ "$chaos_rc" -ne 0 ]; then
    echo "chaos smoke failed (rc=$chaos_rc): fault-injected watershed run" \
         "did not recover to a byte-identical output" >&2
    exit "$chaos_rc"
fi

echo "== ctt-watch smoke (live watch during a stub-scheduler run; kill -> stall) =="
watch_tmp="$obs_tmp/watch"
mkdir -p "$watch_tmp"
cat > "$obs_tmp/watch_driver.py" <<'PY'
import os, stat, sys
import numpy as np
from scipy import ndimage
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows.watershed import WatershedWorkflow

td = sys.argv[1]
sched = os.path.join(td, "sched")
os.makedirs(sched, exist_ok=True)
submit, queue = os.path.join(sched, "submit"), os.path.join(sched, "queue")
with open(submit, "w") as f:
    f.write('#!/bin/bash\nscript="${@: -1}"\nbash "$script" >/dev/null 2>&1\n'
            'echo "Submitted batch job 1"\n')
with open(queue, "w") as f:
    f.write("#!/bin/bash\nexit 0\n")
for p in (submit, queue):
    os.chmod(p, os.stat(p).st_mode | stat.S_IEXEC)

rng = np.random.default_rng(0)
raw = ndimage.gaussian_filter(rng.random((16, 32, 32)), (1.0, 2.0, 2.0))
raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")
path = os.path.join(td, "ws.n5")
file_reader(path).create_dataset("bnd", data=raw, chunks=(8, 16, 16))
config_dir = os.path.join(td, "configs")
cfg.write_global_config(config_dir, {
    "block_shape": [8, 16, 16], "target": "slurm", "max_jobs": 2,
    "max_num_retries": 3, "retry_failure_fraction": 0.9,
    "poll_interval_s": 0.05, "sbatch_cmd": submit, "squeue_cmd": queue,
    "worker_env": {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"},
})
cfg.write_config(config_dir, "watershed", {
    "threshold": 0.5, "sigma_seeds": 1.6, "size_filter": 10,
    "halo": [2, 4, 4],
})
wf = WatershedWorkflow(
    os.path.join(td, "tmp"), config_dir, max_jobs=2,
    input_path=path, input_key="bnd",
    output_path=path, output_key="ws",
)
assert build([wf]), "watch smoke watershed build failed"
PY

# 1) healthy run in the background; `watch --once` must observe nonzero
#    progress (exit 0) while/after it runs — the live contract
JAX_PLATFORMS=cpu PYTHONPATH="$repo_root${PYTHONPATH:+:$PYTHONPATH}" \
CTT_TRACE_DIR="$obs_tmp/trace" CTT_RUN_ID=ci_watch CTT_HEARTBEAT_S=0.2 \
    python "$obs_tmp/watch_driver.py" "$watch_tmp/healthy" \
    > "$watch_tmp/driver.log" 2>&1 &
watch_driver_pid=$!
watch_ok=1
for _ in $(seq 1 240); do
    if JAX_PLATFORMS=cpu python -m cluster_tools_tpu.obs watch --once \
        "$obs_tmp/trace/ci_watch" >/dev/null 2>&1; then
        watch_ok=0
        break
    fi
    sleep 0.5
done
wait "$watch_driver_pid"
watch_run_rc=$?
if [ "$watch_run_rc" -ne 0 ]; then
    cat "$watch_tmp/driver.log" >&2
    echo "watch smoke watershed run failed (rc=$watch_run_rc)" >&2
    exit "$watch_run_rc"
fi
if [ "$watch_ok" -ne 0 ]; then
    echo "obs watch --once never observed progress during the run" >&2
    exit 1
fi
JAX_PLATFORMS=cpu python -m cluster_tools_tpu.obs watch --once \
    "$obs_tmp/trace/ci_watch"
# the OpenMetrics exposition must parse (prometheus_client if available,
# grammar check otherwise) — via a file: a heredoc would steal the
# validator's stdin from the pipe
JAX_PLATFORMS=cpu python -m cluster_tools_tpu.obs prom \
    "$obs_tmp/trace/ci_watch" > "$watch_tmp/exposition.txt"
prom_gen_rc=$?
if [ "$prom_gen_rc" -ne 0 ]; then
    echo "obs prom failed (rc=$prom_gen_rc)" >&2
    exit "$prom_gen_rc"
fi
python - "$watch_tmp/exposition.txt" <<'PY'
import re, sys
with open(sys.argv[1]) as f:
    text = f.read()
lines = text.splitlines()
assert lines and lines[-1] == "# EOF", "exposition must end with # EOF"
try:
    from prometheus_client.openmetrics.parser import (
        text_string_to_metric_families,
    )
    families = list(text_string_to_metric_families(text))
    assert families, "no metric families in exposition"
except ImportError:
    sample = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.+eEinfa]+$")
    meta = re.compile(r"^# (TYPE [a-zA-Z_:][a-zA-Z0-9_:]* \w+|HELP .+|EOF)$")
    for line in lines:
        assert sample.match(line) or meta.match(line), f"bad line: {line}"
print("prom exposition ok")
PY
prom_rc=$?
if [ "$prom_rc" -ne 0 ]; then
    echo "obs prom output is not valid OpenMetrics (rc=$prom_rc)" >&2
    exit "$prom_rc"
fi

# 2) worker-kill run (ctt-fault and ctt-watch validating each other): the
#    killed job's heartbeat goes stale and `--fail-on-stall` must exit 4 —
#    polled DURING the run (the flag should land before task completion;
#    the stale file persists, so a post-run check is the deterministic
#    fallback if the run finishes between polls)
JAX_PLATFORMS=cpu PYTHONPATH="$repo_root${PYTHONPATH:+:$PYTHONPATH}" \
CTT_TRACE_DIR="$obs_tmp/trace" CTT_RUN_ID=ci_watch_kill CTT_HEARTBEAT_S=0.2 \
CTT_FAULTS="worker.job:kill:ids=0,once;seed=7" \
CTT_FAULT_STATE_DIR="$watch_tmp/fault_state" \
    python "$obs_tmp/watch_driver.py" "$watch_tmp/kill" \
    > "$watch_tmp/kill_driver.log" 2>&1 &
kill_driver_pid=$!
stall_seen=1
while kill -0 "$kill_driver_pid" 2>/dev/null; do
    JAX_PLATFORMS=cpu python -m cluster_tools_tpu.obs watch --once \
        --fail-on-stall "$obs_tmp/trace/ci_watch_kill" >/dev/null 2>&1
    if [ $? -eq 4 ]; then
        stall_seen=0
        echo "stale worker flagged while the run was still in flight"
        break
    fi
    sleep 0.5
done
wait "$kill_driver_pid"
kill_rc=$?
if [ "$kill_rc" -ne 0 ]; then
    cat "$watch_tmp/kill_driver.log" >&2
    echo "worker-kill watershed run did not recover (rc=$kill_rc)" >&2
    exit "$kill_rc"
fi
JAX_PLATFORMS=cpu python -m cluster_tools_tpu.obs watch --once \
    --fail-on-stall "$obs_tmp/trace/ci_watch_kill"
stall_rc=$?
if [ "$stall_rc" -ne 4 ]; then
    echo "obs watch --fail-on-stall exited $stall_rc (wanted 4): the" \
         "killed worker's stale heartbeat was not flagged" >&2
    exit 1
fi
if [ "$stall_seen" -ne 0 ]; then
    echo "note: stall only flagged post-run (run finished between polls)"
fi
echo "watch smoke ok: progress seen live, prom parsed, stale worker -> rc 4"

echo "== ctt-cc smoke (coarse kernel parity + tile-bounded rounds) =="
JAX_PLATFORMS=cpu PYTHONPATH="$repo_root${PYTHONPATH:+:$PYTHONPATH}" \
    python - <<'PY'
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from cluster_tools_tpu.ops import cc

# parity: the coarse kernel must be BIT-exact with the numpy oracle on the
# serpentine worst case and a random fixture
for mask in (
    cc.serpentine_mask((4, 64, 64)),
    np.random.default_rng(0).random((12, 24, 24)) < 0.5,
):
    ref, n_ref = cc.connected_components_np(mask)
    got, n = cc.connected_components(jnp.asarray(mask), coarse_tile=(4, 16, 16))
    assert int(n) == n_ref, (int(n), n_ref)
    np.testing.assert_array_equal(np.asarray(got), ref)

# iteration contract: tile-bounded rounds strictly below the flat kernel's
# diameter-bounded count on the serpentine corridor
serp = jnp.asarray(cc.serpentine_mask((4, 64, 64)))
_, it_flat = cc.connected_components_raw_with_iters(serp)
_, stats = cc.connected_components_coarse_raw(serp, 1, None, False, (4, 16, 16))
it_coarse = int(stats["fixpoint_iters"])
assert it_coarse < int(it_flat), (it_coarse, int(it_flat))
print(f"cc smoke ok: parity exact, serpentine rounds {int(it_flat)} -> {it_coarse}")
PY
cc_rc=$?
if [ "$cc_rc" -ne 0 ]; then
    echo "ctt-cc smoke failed (rc=$cc_rc): coarse kernel parity or the" \
         "round contract regressed" >&2
    exit "$cc_rc"
fi

echo "== ctt-stream smoke (fused chain parity + lower store reads) =="
stream_tmp="$(mktemp -d)"
cat > "$stream_tmp/stream_driver.py" <<'PY'
import os, stat, sys
import numpy as np
from scipy import ndimage
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import StreamingSegmentationWorkflow

td, tag, fused = sys.argv[1], sys.argv[2], sys.argv[3] == "fused"
sched = os.path.join(td, "sched")
os.makedirs(sched, exist_ok=True)
submit, queue = os.path.join(sched, "submit"), os.path.join(sched, "queue")
with open(submit, "w") as f:
    f.write('#!/bin/bash\nscript="${@: -1}"\nbash "$script" >/dev/null 2>&1\n'
            'echo "Submitted batch job 1"\n')
with open(queue, "w") as f:
    f.write("#!/bin/bash\nexit 0\n")
for p in (submit, queue):
    os.chmod(p, os.stat(p).st_mode | stat.S_IEXEC)

rng = np.random.default_rng(0)
raw = ndimage.gaussian_filter(rng.random((24, 48, 48)), (1.0, 2.0, 2.0))
raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")
path = os.path.join(td, f"{tag}.n5")
file_reader(path).create_dataset("raw", data=raw, chunks=(12, 24, 24))
config_dir = os.path.join(td, f"configs_{tag}")
cfg.write_global_config(config_dir, {
    "block_shape": [12, 24, 24], "target": "slurm", "max_jobs": 2,
    # batches spanning whole z-slab rows maximize the one-superslab-read
    # win (a 1-block batch degenerates to per-block halo'd reads)
    "stream_fusion": fused, "device_batch_size": 4,
    "poll_interval_s": 0.05, "sbatch_cmd": submit, "squeue_cmd": queue,
    "worker_env": {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"},
})
cfg.write_config(config_dir, "threshold", {"threshold": 0.55})
cfg.write_config(config_dir, "watershed", {
    "threshold": 0.5, "sigma_seeds": 1.6, "size_filter": 10,
    "halo": [2, 6, 6],
})
wf = StreamingSegmentationWorkflow(
    os.path.join(td, f"tmp_{tag}"), config_dir, max_jobs=2,
    input_path=path, input_key="raw",
    output_path=path, output_key="cc",
)
assert build([wf]), f"streaming workflow failed ({tag})"
PY

# the decoded-chunk LRU would hide exactly the cross-task re-reads the
# fusion removes at this fixture size — byte counts come from the codec
# boundary in both runs
JAX_PLATFORMS=cpu PYTHONPATH="$repo_root${PYTHONPATH:+:$PYTHONPATH}" \
CTT_CHUNK_CACHE_MB=0 CTT_TRACE_DIR="$obs_tmp/trace" \
CTT_RUN_ID=ci_stream_unfused \
    python "$stream_tmp/stream_driver.py" "$stream_tmp/unfused" u unfused
unfused_rc=$?
JAX_PLATFORMS=cpu PYTHONPATH="$repo_root${PYTHONPATH:+:$PYTHONPATH}" \
CTT_CHUNK_CACHE_MB=0 CTT_TRACE_DIR="$obs_tmp/trace" \
CTT_RUN_ID=ci_stream_fused \
    python "$stream_tmp/stream_driver.py" "$stream_tmp/fused" f fused
fused_rc=$?
if [ "$unfused_rc" -ne 0 ] || [ "$fused_rc" -ne 0 ]; then
    echo "streaming smoke runs failed (unfused rc=$unfused_rc," \
         "fused rc=$fused_rc)" >&2
    exit 1
fi
JAX_PLATFORMS=cpu python - "$stream_tmp" "$obs_tmp/trace" <<'PY'
import json, os, sys
import numpy as np
from cluster_tools_tpu.utils import file_reader

td, trace = sys.argv[1], sys.argv[2]
f_un = file_reader(os.path.join(td, "unfused", "u.n5"), "r")
f_fu = file_reader(os.path.join(td, "fused", "f.n5"), "r")
np.testing.assert_array_equal(f_fu["cc"][:], f_un["cc"][:])
np.testing.assert_array_equal(f_fu["cc_ws"][:], f_un["cc_ws"][:])
assert "cc_mask" in f_un, "unfused run must materialize the mask"
assert "cc_mask" not in f_fu, "fused run must elide the mask"


def totals(run_id):
    out = {}
    rdir = os.path.join(trace, run_id)
    for name in os.listdir(rdir):
        if name.startswith("metrics.p"):
            with open(os.path.join(rdir, name)) as fh:
                for k, v in json.load(fh)["counters"].items():
                    out[k] = out.get(k, 0) + v
    return out


t_un, t_fu = totals("ci_stream_unfused"), totals("ci_stream_fused")
r_un, r_fu = t_un.get("store.bytes_read", 0), t_fu.get("store.bytes_read", 0)
assert r_un > 0 and r_fu > 0, (r_un, r_fu)
assert r_fu < r_un, f"fused read bytes {r_fu} not < unfused {r_un}"
assert t_fu.get("stream.chains", 0) >= 1, t_fu
assert t_fu.get("stream.elided_bytes", 0) > 0, t_fu
print("stream smoke ok:", json.dumps({
    "bytes_read_unfused": round(r_un), "bytes_read_fused": round(r_fu),
    "reduction": round(r_un / r_fu, 2),
    "slabs": t_fu.get("stream.slabs"),
}))
PY
stream_rc=$?
rm -rf "$stream_tmp"
if [ "$stream_rc" -ne 0 ]; then
    echo "streaming smoke failed (rc=$stream_rc): fused chain output or" \
         "store-read reduction regressed" >&2
    exit "$stream_rc"
fi
# the fused trace must summarize cleanly (spans + chain tags well-formed)
JAX_PLATFORMS=cpu python -m cluster_tools_tpu.obs summarize \
    "$obs_tmp/trace/ci_stream_fused"
stream_sum_rc=$?
if [ "$stream_sum_rc" -ne 0 ]; then
    echo "obs summarize failed on the fused streaming trace" \
         "(rc=$stream_sum_rc)" >&2
    exit "$stream_sum_rc"
fi

echo "== ctt-steal smoke (worker kill -> lease requeue, digest == static run) =="
steal_tmp="$(mktemp -d)"
JAX_PLATFORMS=cpu CTT_TRACE_DIR="$obs_tmp/trace" CTT_RUN_ID=ci_steal \
CTT_FAULT_STATE_DIR="$steal_tmp/fault_state" \
    python - "$steal_tmp" <<'PY'
import hashlib, json, os, stat, sys

# the chaos spec must reach only the STEALING run's workers (the static
# baseline stays fault-free); armed per-run below via worker_env-inherited
# process environment
CHAOS_SPEC = "executor.block:kill:ids=2,once;seed=21"

import numpy as np
from scipy import ndimage

from cluster_tools_tpu.obs import trace as obs_trace
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows.watershed import WatershedWorkflow

td = sys.argv[1]
sched = os.path.join(td, "sched")
os.makedirs(sched, exist_ok=True)
submit, queue = os.path.join(sched, "submit"), os.path.join(sched, "queue")
with open(submit, "w") as f:
    f.write('#!/bin/bash\nscript="${@: -1}"\nbash "$script" >/dev/null 2>&1\n'
            'echo "Submitted batch job 1"\n')
with open(queue, "w") as f:
    f.write("#!/bin/bash\nexit 0\n")
for p in (submit, queue):
    os.chmod(p, os.stat(p).st_mode | stat.S_IEXEC)

rng = np.random.default_rng(0)
raw = ndimage.gaussian_filter(rng.random((16, 32, 32)), (1.0, 2.0, 2.0))
raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")


def run_ws(key, sched_mode, spec=None):
    if spec is None:
        os.environ.pop("CTT_FAULTS", None)
    else:
        os.environ["CTT_FAULTS"] = spec
    path = os.path.join(td, f"{key}.n5")
    file_reader(path).create_dataset("bnd", data=raw, chunks=(8, 16, 16))
    config_dir = os.path.join(td, f"configs_{key}")
    cfg.write_global_config(config_dir, {
        "block_shape": [8, 16, 16], "target": "slurm", "max_jobs": 3,
        "sched": sched_mode, "steal_lease_s": 0.2, "steal_batch_size": 2,
        "max_num_retries": 2, "retry_failure_fraction": 0.9,
        "poll_interval_s": 0.05, "sbatch_cmd": submit, "squeue_cmd": queue,
        "worker_env": {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"},
    })
    cfg.write_config(config_dir, "watershed", {
        "threshold": 0.5, "sigma_seeds": 1.6, "size_filter": 10,
        "halo": [2, 4, 4],
    })
    wf = WatershedWorkflow(
        os.path.join(td, f"tmp_{key}"), config_dir, max_jobs=3,
        input_path=path, input_key="bnd",
        output_path=path, output_key="ws",
    )
    try:
        assert build([wf]), f"{key} watershed build failed"
    finally:
        os.environ.pop("CTT_FAULTS", None)
    return path


def digest(root):
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


static = run_ws("static", "static")
steal = run_ws("steal", "steal", CHAOS_SPEC)

np.testing.assert_array_equal(
    file_reader(steal, "r")["ws"][:], file_reader(static, "r")["ws"][:]
)
assert digest(os.path.join(steal, "ws")) == digest(
    os.path.join(static, "ws")
), "stealing chaos output not byte-identical to the static run"

# the kill latched (a worker really died mid-item, once across processes)
latches = os.listdir(os.environ["CTT_FAULT_STATE_DIR"])
assert any(l.startswith("executor.block") for l in latches), latches

# recovery went through lease requeue, NOT a task-level retry round
status = json.load(open(os.path.join(
    td, "tmp_steal", "status", "watershed.status.json")))
assert status["complete"] and len(status["block_runtimes"]) == 1, status

from cluster_tools_tpu.obs import metrics as obs_metrics

obs_metrics.flush()  # the driver's own counters (task.blocks_retried) too
totals = {}
run_dir = obs_trace.run_dir()
for name in os.listdir(run_dir):
    if name.startswith("metrics.p"):
        with open(os.path.join(run_dir, name)) as f:
            for k, v in json.load(f)["counters"].items():
                totals[k] = totals.get(k, 0) + v
assert totals.get("sched.leases_expired", 0) >= 1, totals
assert totals.get("sched.leases_requeued", 0) >= 1, totals
assert totals.get("task.blocks_retried", 0) == 0, totals
print("steal smoke ok:", json.dumps({
    k: round(v, 2) for k, v in sorted(totals.items())
    if k.startswith("sched.")
}))
PY
steal_rc=$?
rm -rf "$steal_tmp"
if [ "$steal_rc" -ne 0 ]; then
    echo "steal smoke failed (rc=$steal_rc): killed worker did not" \
         "self-heal via lease requeue to a byte-identical output" >&2
    exit "$steal_rc"
fi

echo "== ctt-serve smoke (two jobs -> warm hit, /metrics parses, SIGTERM drain) =="
serve_tmp="$(mktemp -d)"
JAX_PLATFORMS=cpu PYTHONPATH="$repo_root${PYTHONPATH:+:$PYTHONPATH}" \
    python - "$serve_tmp" <<'PY'
import json, os, re, signal, subprocess, sys, time

td = sys.argv[1]
state_dir = os.path.join(td, "state")
env = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
       "CTT_HEARTBEAT_S": "0.2"}
for k in ("CTT_TRACE_DIR", "CTT_RUN_ID"):
    env.pop(k, None)

import numpy as np
from cluster_tools_tpu.serve import JobQueue, ServeClient
from cluster_tools_tpu.utils import file_reader

path = os.path.join(td, "d.n5")
rng = np.random.default_rng(0)
file_reader(path).create_dataset(
    "seg", data=rng.integers(0, 50, (8, 16, 16)).astype(np.uint64),
    chunks=(4, 8, 8),
)

daemon = subprocess.Popen(
    [sys.executable, "-m", "cluster_tools_tpu.serve",
     "--state-dir", state_dir, "--lease-s", "0.5"],
    env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
)
try:
    client = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        assert daemon.poll() is None, daemon.stderr.read()
        try:
            client = ServeClient(state_dir=state_dir)
            client.healthz()
            break
        except Exception:
            time.sleep(0.1)
    assert client is not None, "daemon never became healthy"

    # two small workflows back-to-back: the second must be served from
    # the daemon's warm compile state
    states = []
    for i in (1, 2):
        states.append(client.submit_and_wait(
            "UniqueWorkflow",
            {"tmp_folder": os.path.join(td, f"tmp{i}"),
             "config_dir": os.path.join(td, "configs"),
             "input_path": path, "input_key": "seg",
             "output_path": path, "output_key": f"u{i}"},
            configs={"global": {"block_shape": [4, 8, 8]}},
            timeout_s=300,
        ))
    assert states[0]["result"]["ok"] and states[1]["result"]["ok"]
    assert not states[0]["result"]["warm"], states[0]["result"]
    assert states[1]["result"]["warm"], states[1]["result"]

    text = client.metrics_text()
    with open(os.path.join(td, "exposition.txt"), "w") as f:
        f.write(text)
    vals = {
        ln.rsplit(" ", 1)[0]: float(ln.rsplit(" ", 1)[1])
        for ln in text.splitlines()
        if ln and not ln.startswith("#")
    }
    assert vals.get("ctt_serve_warm_compile_jobs_total", 0) >= 1, vals
    assert vals.get("ctt_serve_jobs_done_total", 0) >= 2, vals

    # SIGTERM -> drain: clean exit, heartbeat flags the drain
    daemon.send_signal(signal.SIGTERM)
    rc = daemon.wait(timeout=120)
    assert rc == 0, (rc, daemon.stderr.read()[-2000:])
    ep = json.load(open(os.path.join(state_dir, "serve.json")))
    run_dir = os.path.join(state_dir, "trace", ep["run_id"])
    hbs = [n for n in os.listdir(run_dir) if n.startswith("hb.p")]
    assert hbs, os.listdir(run_dir)
    hb = json.load(open(os.path.join(run_dir, hbs[0])))
    assert hb["draining"] is True and hb["exiting"] is True, hb
    # nothing queued was lost (both jobs completed pre-drain)
    q = JobQueue(os.path.join(state_dir, "jobs"), lease_s=0.5)
    assert all(j["state"] == "done" for j in q.list()), q.list()
    print("serve smoke ok: cold->warm accounting, drain clean")
finally:
    if daemon.poll() is None:
        daemon.kill()
        daemon.wait(timeout=30)
PY
serve_rc=$?
if [ "$serve_rc" -ne 0 ]; then
    rm -rf "$serve_tmp"
    echo "serve smoke failed (rc=$serve_rc): daemon warm-compile" \
         "accounting, /metrics, or SIGTERM drain regressed" >&2
    exit "$serve_rc"
fi
# the daemon's exposition must be valid OpenMetrics (same validator as
# the watch smoke)
python - "$serve_tmp/exposition.txt" <<'PY'
import re, sys
with open(sys.argv[1]) as f:
    text = f.read()
lines = text.splitlines()
assert lines and lines[-1] == "# EOF", "exposition must end with # EOF"
try:
    from prometheus_client.openmetrics.parser import (
        text_string_to_metric_families,
    )
    families = list(text_string_to_metric_families(text))
    assert families, "no metric families in exposition"
except ImportError:
    sample = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.+eEinfa]+$")
    meta = re.compile(r"^# (TYPE [a-zA-Z_:][a-zA-Z0-9_:]* \w+|HELP .+|EOF)$")
    for line in lines:
        assert sample.match(line) or meta.match(line), f"bad line: {line}"
print("serve prom exposition ok")
PY
serve_prom_rc=$?
rm -rf "$serve_tmp"
if [ "$serve_prom_rc" -ne 0 ]; then
    echo "serve /metrics output is not valid OpenMetrics" \
         "(rc=$serve_prom_rc)" >&2
    exit "$serve_prom_rc"
fi

echo "== ctt-cloud smoke (serve daemon against the stub object store, 5% request chaos) =="
# the deployability gate: the ctt-serve daemon executes a watershed whose
# input AND output live in an object store (the tests/objstub.py stub,
# injecting 5% request failures), and the result is byte-identical —
# chunk digests included — to an in-process POSIX run, with the daemon's
# /metrics showing nonzero remote IO and absorbed retries.
cloud_tmp="$(mktemp -d)"
JAX_PLATFORMS=cpu PYTHONPATH="$repo_root${PYTHONPATH:+:$PYTHONPATH}" \
    python - "$cloud_tmp" <<'PY'
import hashlib, json, os, signal, subprocess, sys, time

td = sys.argv[1]
repo_root = os.environ.get("PYTHONPATH", "").split(os.pathsep)[0] or "."
env = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
       "CTT_HEARTBEAT_S": "0.2"}
for k in ("CTT_TRACE_DIR", "CTT_RUN_ID"):
    env.pop(k, None)

import numpy as np
from scipy import ndimage

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.serve import ServeClient
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import WatershedWorkflow

rng = np.random.default_rng(0)
base = ndimage.gaussian_filter(rng.random((16, 64, 64)), (1.0, 2.0, 2.0))
vol = ((base - base.min()) / (base.max() - base.min())).astype("float32")
ws_conf = {"threshold": 0.5, "sigma_seeds": 1.6, "size_filter": 10,
           "halo": [2, 4, 4]}
gconf = {"block_shape": [8, 32, 32], "target": "tpu", "pipeline_depth": 3}


def digest(root):
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


# POSIX reference, in-process
local = os.path.join(td, "local.n5")
file_reader(local).create_dataset(
    "bnd", data=vol, chunks=(8, 32, 32), compression="gzip"
)
config_dir = os.path.join(td, "configs_local")
cfg.write_global_config(config_dir, gconf)
cfg.write_config(config_dir, "watershed", ws_conf)
assert build([WatershedWorkflow(
    os.path.join(td, "tmp_local"), config_dir,
    input_path=local, input_key="bnd",
    output_path=local, output_key="ws",
)]), "posix reference run failed"

# stub object store with 5% injected request failures
objroot = os.path.join(td, "objroot")
os.makedirs(objroot)
served = os.path.join(objroot, "data.n5")
file_reader(served).create_dataset(
    "bnd", data=vol, chunks=(8, 32, 32), compression="gzip"
)
port_file = os.path.join(td, "stub.port")
stub = subprocess.Popen([
    sys.executable, os.path.join(repo_root, "tests", "objstub.py"),
    "--root", objroot, "--port-file", port_file,
    "--fail-rate", "0.05", "--seed", "7",
], env=env)
daemon = None
try:
    deadline = time.monotonic() + 30
    while not os.path.exists(port_file):
        assert stub.poll() is None, "objstub died on startup"
        assert time.monotonic() < deadline, "objstub never came up"
        time.sleep(0.05)
    url = f"http://127.0.0.1:{open(port_file).read().strip()}"

    state_dir = os.path.join(td, "state")
    daemon = subprocess.Popen(
        [sys.executable, "-m", "cluster_tools_tpu.serve",
         "--state-dir", state_dir],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    client = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        assert daemon.poll() is None, daemon.stderr.read()
        try:
            client = ServeClient(state_dir=state_dir)
            client.healthz()
            break
        except Exception:
            time.sleep(0.1)
    assert client is not None, "daemon never became healthy"

    state = client.submit_and_wait(
        "WatershedWorkflow",
        {"tmp_folder": os.path.join(td, "tmp_remote"),
         "config_dir": os.path.join(td, "configs_remote"),
         "input_path": f"{url}/data.n5", "input_key": "bnd",
         "output_path": f"{url}/data.n5", "output_key": "ws"},
        configs={"global": dict(gconf), "watershed": dict(ws_conf)},
        timeout_s=600,
    )
    assert state["result"]["ok"], state

    # byte-identity: the store the stub served now holds the SAME chunk
    # files as the POSIX run
    assert digest(os.path.join(local, "ws")) == digest(
        os.path.join(served, "ws")
    ), "remote watershed output is not byte-identical to the POSIX run"

    # remote counters visible through the daemon's own exposition
    vals = {
        ln.rsplit(" ", 1)[0]: float(ln.rsplit(" ", 1)[1])
        for ln in client.metrics_text().splitlines()
        if ln and not ln.startswith("#")
    }
    assert vals.get("ctt_store_remote_reads_total", 0) > 0, vals
    assert vals.get("ctt_store_remote_writes_total", 0) > 0, vals
    assert vals.get("ctt_store_remote_retries_total", 0) > 0, (
        "5% request chaos never forced a retry", vals,
    )
    print("cloud smoke ok:", json.dumps({
        "remote_reads": vals.get("ctt_store_remote_reads_total"),
        "remote_writes": vals.get("ctt_store_remote_writes_total"),
        "remote_retries": vals.get("ctt_store_remote_retries_total"),
    }))
finally:
    if daemon is not None:
        daemon.send_signal(signal.SIGTERM)
        try:
            daemon.wait(timeout=60)
        except subprocess.TimeoutExpired:
            daemon.kill()
            daemon.wait(timeout=30)
    stub.terminate()
    stub.wait(timeout=30)
PY
cloud_rc=$?
rm -rf "$cloud_tmp"
if [ "$cloud_rc" -ne 0 ]; then
    echo "cloud smoke failed (rc=$cloud_rc): the serve daemon could not" \
         "produce a byte-identical watershed against the stub object" \
         "store under 5% request chaos" >&2
    exit "$cloud_rc"
fi

echo "== ctt-hbm smoke (serve daemon: second job zero upload bytes, fused dispatches < blocks, byte-identical) =="
hbm_tmp="$(mktemp -d)"
JAX_PLATFORMS=cpu PYTHONPATH="$repo_root${PYTHONPATH:+:$PYTHONPATH}" \
    python - "$hbm_tmp" <<'PY'
import hashlib, json, os, signal, subprocess, sys, time

td = sys.argv[1]
state_dir = os.path.join(td, "state")
env = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
for k in ("CTT_TRACE_DIR", "CTT_RUN_ID"):
    env.pop(k, None)

import numpy as np
from scipy import ndimage
from cluster_tools_tpu.serve import ServeClient
from cluster_tools_tpu.utils import file_reader

path = os.path.join(td, "d.n5")
rng = np.random.default_rng(0)
raw = ndimage.gaussian_filter(rng.random((8, 32, 32)), (1.0, 2.0, 2.0))
raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")
file_reader(path).create_dataset("bnd", data=raw, chunks=(4, 8, 8))
n_blocks = 2 * 4 * 4

daemon = subprocess.Popen(
    [sys.executable, "-m", "cluster_tools_tpu.serve",
     "--state-dir", state_dir],
    env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
)
try:
    client = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        assert daemon.poll() is None, daemon.stderr.read()
        try:
            client = ServeClient(state_dir=state_dir)
            client.healthz()
            break
        except Exception:
            time.sleep(0.1)
    assert client is not None, "daemon never became healthy"

    def scrape():
        return {
            ln.rsplit(" ", 1)[0]: float(ln.rsplit(" ", 1)[1])
            for ln in client.metrics_text().splitlines()
            if ln and not ln.startswith("#")
        }

    # the same small watershed twice (fresh tmp/output per job): the
    # second job must be served entirely from the warm HBM buffer cache
    def submit(tag):
        return client.submit_and_wait(
            "WatershedWorkflow",
            {"tmp_folder": os.path.join(td, f"tmp_{tag}"),
             "config_dir": os.path.join(td, f"configs_{tag}"),
             "input_path": path, "input_key": "bnd",
             "output_path": path, "output_key": f"ws_{tag}"},
            configs={
                "global": {"block_shape": [4, 8, 8], "target": "tpu",
                           "device_batch_size": 1, "pipeline_depth": 3,
                           "hbm_stack": 4},
                "watershed": {"threshold": 0.5, "sigma_seeds": 1.6,
                              "size_filter": 10, "halo": [2, 4, 4]},
            },
            timeout_s=300,
        )

    m0 = scrape()
    s1 = submit("j1")
    m1 = scrape()
    s2 = submit("j2")
    m2 = scrape()
    assert s1["result"]["ok"] and s2["result"]["ok"]

    def delta(a, b, name):
        return b.get(name, 0.0) - a.get(name, 0.0)

    up = "ctt_device_upload_bytes_total"
    assert delta(m0, m1, up) > 0, (m0, m1)
    # second job: ZERO new upload bytes (warm HBM), >= 1 skip
    assert delta(m1, m2, up) == 0, (m1, m2)
    assert delta(m1, m2, "ctt_device_uploads_skipped_total") >= 1
    # aggregated dispatch: fused dispatch count < block count
    disp = delta(m1, m2, "ctt_device_dispatches_total")
    assert 0 < disp < n_blocks, (disp, n_blocks)
    assert delta(m0, m1, "ctt_device_fused_blocks_total") > 0

    # byte-identity incl. chunk digests between the two jobs' outputs
    f = file_reader(path, "r")
    assert np.array_equal(f["ws_j1"][:], f["ws_j2"][:])

    def digest(root):
        h = hashlib.sha256()
        for dp, dns, fns in os.walk(root):
            dns.sort()
            for n in sorted(fns):
                p = os.path.join(dp, n)
                h.update(os.path.relpath(p, root).encode())
                h.update(open(p, "rb").read())
        return h.hexdigest()

    assert digest(os.path.join(path, "ws_j1")) == digest(
        os.path.join(path, "ws_j2")
    )
    print("hbm smoke ok: warm job zero upload bytes,",
          int(disp), "fused dispatches for", n_blocks,
          "blocks, chunk digests identical")
finally:
    daemon.send_signal(signal.SIGTERM)
    try:
        daemon.wait(timeout=60)
    except subprocess.TimeoutExpired:
        daemon.kill()
        daemon.wait(timeout=30)
PY
hbm_rc=$?
rm -rf "$hbm_tmp"
if [ "$hbm_rc" -ne 0 ]; then
    echo "hbm smoke failed (rc=$hbm_rc): warm-HBM upload accounting," \
         "dispatch aggregation, or byte-identity regressed" >&2
    exit "$hbm_rc"
fi

echo "== ctt-hier smoke (daemon hierarchy build, 3-threshold warm sweep, parity vs fresh re-runs, zero warm upload bytes) =="
hier_tmp="$(mktemp -d)"
JAX_PLATFORMS=cpu PYTHONPATH="$repo_root${PYTHONPATH:+:$PYTHONPATH}" \
    python - "$hier_tmp" <<'PY'
import os, signal, subprocess, sys, time

td = sys.argv[1]
state_dir = os.path.join(td, "state")
env = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
for k in ("CTT_TRACE_DIR", "CTT_RUN_ID"):
    env.pop(k, None)

import numpy as np
from scipy import ndimage
from cluster_tools_tpu.ops import hier as hier_ops
from cluster_tools_tpu.serve import ServeClient
from cluster_tools_tpu.utils import file_reader

path = os.path.join(td, "d.n5")
rng = np.random.default_rng(0)
raw = ndimage.gaussian_filter(rng.random((8, 32, 32)), (1.0, 2.0, 2.0))
raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")
file_reader(path).create_dataset("bnd", data=raw, chunks=(4, 16, 16))
gconf = {"block_shape": [4, 16, 16], "target": "tpu",
         "device_batch_size": 1, "pipeline_depth": 2}
bconf = {"threshold": 0.5, "sigma_seeds": 1.6, "size_filter": 10}

daemon = subprocess.Popen(
    [sys.executable, "-m", "cluster_tools_tpu.serve",
     "--state-dir", state_dir],
    env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
)
try:
    client = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        assert daemon.poll() is None, daemon.stderr.read()
        try:
            client = ServeClient(state_dir=state_dir)
            client.healthz()
            break
        except Exception:
            time.sleep(0.1)
    assert client is not None, "daemon never became healthy"

    def scrape():
        return {
            ln.rsplit(" ", 1)[0]: float(ln.rsplit(" ", 1)[1])
            for ln in client.metrics_text().splitlines()
            if ln and not ln.startswith("#")
        }

    def build_job(tag, out_key):
        return client.submit_and_wait(
            "HierarchyWorkflow",
            {"tmp_folder": os.path.join(td, f"tmp_{tag}"),
             "config_dir": os.path.join(td, f"configs_{tag}"),
             "input_path": path, "input_key": "bnd",
             "output_path": path, "output_key": out_key},
            configs={"global": dict(gconf), "hierarchy_blocks": dict(bconf)},
            timeout_s=600,
        )

    def reseg(tag, labels_key, out_key, t, write_volume):
        job = client.resegment(
            hierarchy=os.path.join(path, f"{labels_key}_hierarchy.npz"),
            labels_path=path, labels_key=labels_key,
            output_path=path, output_key=out_key,
            threshold=t, write_volume=write_volume,
            tmp_folder=os.path.join(td, f"tmp_{tag}"),
            config_dir=os.path.join(td, f"configs_{tag}"),
            configs={"global": dict(gconf)},
        )
        st = client.wait(job, timeout_s=600)
        assert st["result"]["ok"], st
        return st

    s = build_job("build", "seg")
    assert s["result"]["ok"], s
    art = hier_ops.load_hierarchy(os.path.join(path, "seg_hierarchy.npz"))
    ts = [float(t) for t in np.quantile(art["saddle"], (0.25, 0.5, 0.75))]
    # warm the HBM cache + compiles, then the measured sweep window
    reseg("warm", "seg", "seg_warm", ts[0], True)
    m1 = scrape()
    for i, t in enumerate(ts):
        reseg(f"sweep{i}", "seg", f"cut{i}", t, False)
    reseg("commit", "seg", "seg_commit", ts[1], True)
    m2 = scrape()
    up = "ctt_device_upload_bytes_total"
    delta = m2.get(up, 0.0) - m1.get(up, 0.0)
    assert delta == 0, f"warm sweep uploaded {delta} bytes"
    assert m2.get("ctt_hier_resegment_jobs_total", 0) >= 5

    # parity vs fresh full re-runs at every swept threshold
    from cluster_tools_tpu.ops.evaluation import rand_scores
    from cluster_tools_tpu.ops.segment import contingency_table

    f = file_reader(path, "r")
    seg = f["seg"][:]
    for i, t in enumerate(ts):
        assert build_job(f"full{i}", f"seg_f{i}")["result"]["ok"]
        reseg(f"fullcut{i}", f"seg_f{i}", f"seg_f{i}_t", t, True)
        cut = hier_ops.load_cut_table(
            os.path.join(path, f"cut{i}_cut.npz"))
        swept = hier_ops.apply_cut_np(seg, cut["vals"], cut["roots"])
        ia, ib, counts = contingency_table(
            swept.astype(np.uint64), f[f"seg_f{i}_t"][:])
        ri = rand_scores(ia, ib, counts)["rand_index"]
        assert ri == 1.0, (t, ri)
    print("hier smoke ok: 3-threshold warm sweep, zero upload bytes,",
          "RI == 1.0 vs fresh full re-runs at every threshold")
finally:
    daemon.send_signal(signal.SIGTERM)
    try:
        daemon.wait(timeout=60)
    except subprocess.TimeoutExpired:
        daemon.kill()
        daemon.wait(timeout=30)
PY
hier_rc=$?
rm -rf "$hier_tmp"
if [ "$hier_rc" -ne 0 ]; then
    echo "hier smoke failed (rc=$hier_rc): hierarchy build, warm sweep" \
         "upload accounting, or re-cut parity regressed" >&2
    exit "$hier_rc"
fi

echo "== ctt-fleet chaos smoke (2 daemons over the stub object store, SIGKILL one mid-job -> zero loss, fast reclaim) =="
# the fleet gate: two serve daemons share one state dir, executing a
# 6-job burst whose volumes live in the stub object store; one daemon is
# SIGKILLed mid-job.  Every job must still publish an ok result, the
# recovered job's output must be byte-identical to a single-daemon
# reference run, recovery must ride the fleet-heartbeat fast path (not
# the 3 x lease_s staleness window), and the survivor's /metrics must
# parse as OpenMetrics with ctt_serve_jobs_reclaimed_total >= 1.
fleet_tmp="$(mktemp -d)"
JAX_PLATFORMS=cpu PYTHONPATH="$repo_root${PYTHONPATH:+:$PYTHONPATH}" \
    python - "$fleet_tmp" <<'PY'
import hashlib, json, os, re, subprocess, sys, time

td = sys.argv[1]
repo_root = os.environ.get("PYTHONPATH", "").split(os.pathsep)[0] or "."
env = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
       "CTT_HEARTBEAT_S": "0.2"}
for k in ("CTT_TRACE_DIR", "CTT_RUN_ID"):
    env.pop(k, None)

import numpy as np

from cluster_tools_tpu.serve import ServeClient
from cluster_tools_tpu.utils import file_reader


def digest(root):
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def sleep_job(root_td, data_root, tag, sleep_s, phase):
    # the ctt-steal calibrated-cost fixture task: one block,
    # deterministic output (input * 2 + 1), every block costs sleep_s —
    # so the reference run can be fast while staying byte-identical.
    # tmp/config dirs are per phase: a shared checkpoint folder would let
    # the fleet run skip blocks the reference run already marked done
    return {
        "workflow": "bench_e2e_lib:SkewedCostTask",
        "kwargs": {
            "tmp_folder": os.path.join(root_td, f"tmp_{phase}_{tag}"),
            "config_dir": os.path.join(root_td, f"configs_{phase}_{tag}"),
            "input_path": f"{data_root}/{tag}.n5", "input_key": "x",
            "output_path": f"{data_root}/{tag}.n5", "output_key": "y",
        },
        "configs": {
            "global": {"block_shape": [2, 8, 8]},
            "skewed_cost": {
                "hot_z_end": 0, "base_s": float(sleep_s), "hot_s": 99.0,
            },
        },
        "tenant": tag,
    }


def spawn(state_dir, daemon_id):
    proc = subprocess.Popen(
        [sys.executable, "-m", "cluster_tools_tpu.serve",
         "--state-dir", state_dir, "--lease-s", "5",
         "--daemon-id", daemon_id],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    proc.stdout.readline()  # listening banner
    ep_line = proc.stdout.readline()  # per-daemon endpoint JSON
    assert ep_line, f"{daemon_id} died at startup:\n{proc.stderr.read()}"
    ep = json.loads(ep_line)
    client = ServeClient(endpoint=f"http://{ep['host']}:{ep['port']}",
                         token=ep["token"])
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            client.healthz()
            return proc, client
        except Exception:
            assert proc.poll() is None, (
                f"{daemon_id} died:\n{proc.stderr.read()}")
            time.sleep(0.1)
    raise AssertionError(f"{daemon_id} never became healthy")


tags = [f"k{i}" for i in range(6)]

# single-daemon reference run (POSIX volumes, zero job sleep)
ref_root = os.path.join(td, "ref")
os.makedirs(ref_root)
for tag in tags:
    file_reader(os.path.join(ref_root, f"{tag}.n5")).create_dataset(
        "x", data=np.ones((2, 8, 8), dtype="float32"), chunks=(2, 8, 8))
ref, ref_client = spawn(os.path.join(td, "state_ref"), "ref")
try:
    jobs = [ref_client.submit(**sleep_job(td, ref_root, t, 0.01, "ref"))
            for t in tags]
    for jid in jobs:
        assert ref_client.wait(jid, timeout_s=300)["result"]["ok"]
finally:
    ref.kill()
    ref.wait(timeout=30)

# the fleet run: volumes on the stub object store, two daemons, SIGKILL
objroot = os.path.join(td, "objroot")
os.makedirs(objroot)
for tag in tags:
    file_reader(os.path.join(objroot, f"{tag}.n5")).create_dataset(
        "x", data=np.ones((2, 8, 8), dtype="float32"), chunks=(2, 8, 8))
port_file = os.path.join(td, "stub.port")
stub = subprocess.Popen([
    sys.executable, os.path.join(repo_root, "tests", "objstub.py"),
    "--root", objroot, "--port-file", port_file,
], env=env)
proc_a = proc_b = None
try:
    deadline = time.monotonic() + 30
    while not os.path.exists(port_file):
        assert stub.poll() is None, "objstub died on startup"
        assert time.monotonic() < deadline, "objstub never came up"
        time.sleep(0.05)
    url = f"http://127.0.0.1:{open(port_file).read().strip()}"

    state_dir = os.path.join(td, "state_fleet")
    proc_a, client_a = spawn(state_dir, "dA")
    proc_b, client_b = spawn(state_dir, "dB")
    jobs = []
    for i, tag in enumerate(tags):
        cl = client_a if i % 2 == 0 else client_b
        jobs.append(cl.submit(**sleep_job(td, url, tag, 2.0, "fleet")))

    # SIGKILL dA once its own fleet beat reports a job in flight
    beat = os.path.join(state_dir, "daemon.dA.json")
    deadline = time.monotonic() + 60
    running = 0
    while time.monotonic() < deadline and running < 1:
        try:
            running = json.load(open(beat)).get("running_jobs", 0)
        except Exception:
            pass
        time.sleep(0.05)
    assert running >= 1, "dA never started executing"
    proc_a.kill()
    proc_a.wait(timeout=30)
    t_kill = time.time()

    # zero loss: every job publishes an ok result via the survivor
    for jid in jobs:
        assert client_b.wait(jid, timeout_s=300)["result"]["ok"], jid
    from cluster_tools_tpu.serve import JobQueue
    q = JobQueue(os.path.join(state_dir, "jobs"), lease_s=5.0)
    results = [q.get(j)["result"] for j in jobs]
    requeued = [r for r in results if r["gen"] > 0]
    assert requeued, "the killed daemon's job never requeued"
    for r in requeued:
        # heartbeat-bounded recovery (3 x 0.2s detection + one 2s
        # re-execution), far inside the 15s lease-staleness window
        assert r["finished_wall"] - t_kill < 12.0, r

    # byte-identity vs the single-daemon reference, recovered job included
    for tag in tags:
        assert digest(os.path.join(objroot, f"{tag}.n5", "y")) == digest(
            os.path.join(ref_root, f"{tag}.n5", "y")
        ), f"{tag} output differs from the single-daemon run"

    # the survivor's ledger: fast-path reclaim counted, /metrics parses
    text = client_b.metrics_text()
    lines = text.splitlines()
    assert lines and lines[-1] == "# EOF", "exposition must end with # EOF"
    try:
        from prometheus_client.openmetrics.parser import (
            text_string_to_metric_families,
        )
        assert list(text_string_to_metric_families(text))
    except ImportError:
        sample = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.+eEinfa]+$")
        meta = re.compile(
            r"^# (TYPE [a-zA-Z_:][a-zA-Z0-9_:]* \w+|HELP .+|EOF)$")
        for line in lines:
            assert sample.match(line) or meta.match(line), line
    vals = {
        ln.rsplit(" ", 1)[0]: float(ln.rsplit(" ", 1)[1])
        for ln in lines if ln and not ln.startswith("#")
    }
    assert vals.get("ctt_serve_jobs_reclaimed_total", 0) >= 1, vals
    assert vals.get("ctt_serve_jobs_quarantined_total", 0) == 0, vals
    print("fleet smoke ok:", json.dumps({
        "requeued": len(requeued),
        "reclaim_latency_s": round(
            min(r["finished_wall"] for r in requeued) - t_kill, 2),
        "jobs_reclaimed": vals.get("ctt_serve_jobs_reclaimed_total"),
    }))
finally:
    for proc in (proc_a, proc_b):
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    stub.terminate()
    stub.wait(timeout=30)
PY
fleet_rc=$?
if [ "$fleet_rc" -eq 0 ]; then
    # ctt-proto: the SIGKILL-survivor state dir is exactly what the
    # artifact registry describes — every surviving file must match a
    # registered schema (protocol conformance IS the recovery contract)
    echo "== ctt-proto conformance (fleet-chaos state dir vs the artifact registry) =="
    JAX_PLATFORMS=cpu python -m cluster_tools_tpu.analysis conformance \
        "$fleet_tmp/state_fleet"
    fleet_rc=$?
    if [ "$fleet_rc" -ne 0 ]; then
        echo "conformance failed (rc=$fleet_rc): the fleet smoke left" \
             "behind files the registry does not describe — update" \
             "analysis/protocols.py or fix the writer" >&2
    fi
fi
rm -rf "$fleet_tmp"
if [ "$fleet_rc" -ne 0 ]; then
    echo "fleet smoke failed (rc=$fleet_rc): the two-daemon fleet lost a" \
         "job, recovered slower than the heartbeat bound, or broke" \
         "byte-identity after a SIGKILL" >&2
    exit "$fleet_rc"
fi

echo "== ctt-events smoke (daemon event_batch, scipy parity, quota 429 under burst, OpenMetrics events counters) =="
# the events gate: one serve daemon at a tiny admission envelope builds
# events for a frame stack (must match scipy.ndimage.label + numpy
# property reduction exactly), a submission burst past the envelope must
# draw CLEAN 429s, and /metrics must still parse as OpenMetrics with a
# nonzero ctt_events_frames_total afterwards.
events_tmp="$(mktemp -d)"
JAX_PLATFORMS=cpu PYTHONPATH="$repo_root${PYTHONPATH:+:$PYTHONPATH}" \
    python - "$events_tmp" <<'PY'
import os, signal, subprocess, sys, time

td = sys.argv[1]
state_dir = os.path.join(td, "state")
env = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
for k in ("CTT_TRACE_DIR", "CTT_RUN_ID"):
    env.pop(k, None)

import numpy as np
from cluster_tools_tpu.ops import events as events_ops
from cluster_tools_tpu.serve import QuotaRejected, ServeClient
from cluster_tools_tpu.utils import file_reader

path = os.path.join(td, "d.n5")
rng = np.random.default_rng(0)
frames = np.where(rng.random((6, 32, 32)) > 0.97,
                  rng.random((6, 32, 32)) + 1.0, 0.0).astype("float32")
file_reader(path).create_dataset("frames", data=frames,
                                 chunks=(2, 32, 32))
gconf = {"block_shape": [2, 32, 32], "target": "tpu",
         "device_batch_size": 2, "pipeline_depth": 2}

daemon = subprocess.Popen(
    [sys.executable, "-m", "cluster_tools_tpu.serve",
     "--state-dir", state_dir, "--concurrency", "1",
     "--tenant-quota", "2", "--max-queue-depth", "4"],
    env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
)
try:
    client = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        assert daemon.poll() is None, daemon.stderr.read()
        try:
            client = ServeClient(state_dir=state_dir)
            client.healthz()
            break
        except Exception:
            time.sleep(0.1)
    assert client is not None, "daemon never became healthy"

    def submit(tag):
        return client.event_batch(
            input_path=path, input_key="frames",
            output_path=path, output_key=f"ev_{tag}",
            tmp_folder=os.path.join(td, f"tmp_{tag}"),
            config_dir=os.path.join(td, f"configs_{tag}"),
            threshold=0.5, configs={"global": dict(gconf)},
        )

    job = submit("main")
    st = client.wait(job, timeout_s=300)
    assert st["result"]["ok"], st

    # scipy parity: daemon labels volume == per-frame host oracle
    ref_l, ref_c, _ = events_ops.build_events_np(frames, threshold=0.5)
    srv = file_reader(path, "r")["ev_main"][:]
    assert np.array_equal(srv, ref_l), "daemon labels != scipy oracle"
    from cluster_tools_tpu.tasks.events import read_event_tables
    rows = read_event_tables(path, "ev_main", 3)
    assert len(rows) == int(ref_c.sum()), (len(rows), int(ref_c.sum()))

    # burst past the admission envelope: CLEAN 429s, no socket errors
    accepted, rejected = [], 0
    for i in range(32):
        try:
            accepted.append(submit(f"burst{i}"))
        except QuotaRejected:
            rejected += 1
    assert rejected > 0, "no 429 observed under a 32-submission burst"
    for j in accepted:
        assert client.wait(j, timeout_s=300)["result"]["ok"]

    text = client.metrics_text()
    lines = {
        parts[0]: float(parts[1])
        for parts in (ln.split() for ln in text.splitlines())
        if len(parts) == 2 and not parts[0].startswith("#")
    }
    assert lines.get("ctt_events_frames_total", 0) >= len(frames)
    assert lines.get("ctt_events_clusters_total", 0) > 0
    assert lines.get("ctt_serve_quota_rejections_total", 0) >= rejected
    try:
        from prometheus_client.openmetrics.parser import (
            text_string_to_metric_families,
        )
        fams = {f.name for f in text_string_to_metric_families(text)}
        assert any(n.startswith("ctt_events_frames") for n in fams), fams
    except ImportError:
        assert text.rstrip().endswith("# EOF"), "metrics lost # EOF"
    print("events smoke ok: scipy parity exact,",
          f"{rejected} clean 429s in burst, events counters on /metrics")
finally:
    daemon.send_signal(signal.SIGTERM)
    try:
        daemon.wait(timeout=60)
    except subprocess.TimeoutExpired:
        daemon.kill()
        daemon.wait(timeout=30)
PY
events_rc=$?
rm -rf "$events_tmp"
if [ "$events_rc" -ne 0 ]; then
    echo "events smoke failed (rc=$events_rc): daemon event_batch parity," \
         "quota 429 behaviour, or the events /metrics counters regressed" >&2
    exit "$events_rc"
fi

echo "== ctt-microbatch smoke (12-job mixed-tenant burst -> stacked dispatch, byte-identity vs window-0, kill-poison fails alone) =="
# the microbatch gate: a short-window daemon must coalesce a 12-job
# mixed-tenant event_batch burst into stacked dispatches (>= 2x
# aggregation on ctt_serve_microbatch_batches_total), the outputs must
# be byte-identical to a window-0 daemon, and an executor.block:kill
# poisoned member must burn its own retry budget alone — its
# batchmates from the same window publish ok.
microbatch_tmp="$(mktemp -d)"
JAX_PLATFORMS=cpu PYTHONPATH="$repo_root${PYTHONPATH:+:$PYTHONPATH}" \
    python - "$microbatch_tmp" <<'PY'
import hashlib, os, signal, subprocess, sys, time

td = sys.argv[1]
env = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
       "CTT_HEARTBEAT_S": "0.2"}
for k in ("CTT_TRACE_DIR", "CTT_RUN_ID"):
    env.pop(k, None)

import numpy as np
from scipy import ndimage
from cluster_tools_tpu.serve import ServeClient
from cluster_tools_tpu.utils import file_reader

gconf = {"block_shape": [2, 16, 16], "target": "local"}


def frames(seed, n=4):
    rng = np.random.default_rng(seed)
    raw = ndimage.gaussian_filter(
        rng.random((n, 16, 16)), (0.0, 1.0, 1.0)
    ).astype("float32")
    return np.where(raw > np.quantile(raw, 0.9), raw, 0.0).astype("float32")


def write_frames(tag, seed, n=4):
    path = os.path.join(td, f"{tag}.n5")
    file_reader(path).create_dataset("frames", data=frames(seed, n=n),
                                     chunks=(2, 16, 16))
    return path


def digest(root):
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def spawn(state_dir, *extra_args, extra_env=None):
    daemon = subprocess.Popen(
        [sys.executable, "-m", "cluster_tools_tpu.serve",
         "--state-dir", state_dir, "--concurrency", "1", *extra_args],
        env={**env, **(extra_env or {})},
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        assert daemon.poll() is None, daemon.stderr.read()
        try:
            client = ServeClient(state_dir=state_dir)
            client.healthz()
            return daemon, client
        except Exception:
            time.sleep(0.1)
    daemon.kill()
    raise AssertionError("daemon never became healthy")


def stop(daemon):
    if daemon.poll() is None:
        daemon.send_signal(signal.SIGTERM)
        try:
            daemon.wait(timeout=60)
        except subprocess.TimeoutExpired:
            daemon.kill()
            daemon.wait(timeout=30)


def submit(client, path, tag, tenant, priority=0):
    return client.event_batch(
        input_path=path, input_key="frames",
        output_path=path, output_key=f"ev_{tag}",
        tmp_folder=os.path.join(td, f"tmp_{tag}"),
        config_dir=os.path.join(td, f"configs_{tag}"),
        threshold=0.1, configs={"global": dict(gconf)},
        tenant=tenant, priority=priority,
    )


# -- leg 1: short window, 12-job mixed-tenant burst -> stacked dispatch
burst_path = write_frames("burst", seed=7)
daemon, client = spawn(os.path.join(td, "state_mb"),
                       "--microbatch-window-s", "2.0",
                       "--microbatch-max-jobs", "12")
try:
    jobs = [submit(client, burst_path, f"mb{i}", tenant=f"t{i % 4}")
            for i in range(12)]
    for j in jobs:
        st = client.wait(j, timeout_s=300)
        assert st["result"]["ok"], st
        assert st["result"].get("microbatch"), (
            "burst member missing the microbatch annotation", st)
    text = client.metrics_text()
    vals = {
        parts[0]: float(parts[1])
        for parts in (ln.split() for ln in text.splitlines())
        if len(parts) == 2 and not parts[0].startswith("#")
    }
    batches = vals.get("ctt_serve_microbatch_batches_total", 0)
    batched = vals.get("ctt_serve_microbatch_jobs_batched_total", 0)
    assert batches >= 1, "no stacked dispatch under a 12-job burst"
    assert batched / batches >= 2, (
        f"aggregation below 2x: {batched} jobs over {batches} batches")
finally:
    stop(daemon)

# -- leg 2: window 0 = exact per-job dispatch; outputs byte-identical
daemon, client = spawn(os.path.join(td, "state_solo"),
                       "--microbatch-window-s", "0")
try:
    solo = [submit(client, burst_path, f"solo{i}", tenant=f"t{i % 4}")
            for i in range(12)]
    for j in solo:
        st = client.wait(j, timeout_s=300)
        assert st["result"]["ok"], st
        assert "microbatch" not in st["result"], (
            "window-0 daemon must not aggregate", st)
finally:
    stop(daemon)
for i in range(12):
    a = digest(os.path.join(burst_path, f"ev_mb{i}"))
    b = digest(os.path.join(burst_path, f"ev_solo{i}"))
    assert a == b, f"stacked output not byte-identical for job {i}"

# -- leg 3: executor.block:kill poison — the culprit (6 frames = blocks
# 0..2, fault targets id 2) kills the daemon mid-batch; across respawns
# the batchmates (2 frames = block 0 only) publish ok while only the
# culprit burns its retry budget and quarantines
culprit_path = write_frames("culprit", seed=11, n=6)
mate_path = write_frames("mates", seed=13, n=2)
kill_state = os.path.join(td, "state_kill")
kill_args = ("--lease-s", "5", "--max-job-gens", "2",
             "--microbatch-window-s", "2.0", "--microbatch-max-jobs", "3")
poison = {"CTT_FAULTS": "executor.block:kill:ids=2"}
daemon, client = spawn(kill_state, *kill_args, extra_env=poison)
culprit = submit(client, culprit_path, "culprit", tenant="bad")
mates = [submit(client, mate_path, f"mate{i}", tenant=f"t{i}", priority=5)
         for i in range(2)]
assert daemon.wait(timeout=120) == 17, "poisoned batch never killed m0"
daemon, client = spawn(kill_state, *kill_args, extra_env=poison)
assert daemon.wait(timeout=120) == 17, "gen-1 solo culprit never killed m1"
daemon, client = spawn(kill_state, *kill_args)
try:
    deadline = time.monotonic() + 120
    res = None
    while time.monotonic() < deadline:
        st = client.status(culprit)
        if st["state"] == "failed":
            res = st["result"]
            break
        time.sleep(0.2)
    assert res is not None, "poison member never quarantined"
    assert res.get("quarantined") is True, res
    for j in mates:
        st = client.wait(j, timeout_s=180)
        assert st["result"]["ok"], f"batchmate lost to the kill: {st}"
finally:
    stop(daemon)
print("microbatch smoke ok:",
      f"{batched:.0f} jobs over {batches:.0f} stacked dispatches,",
      "byte-identical to window-0, kill-poisoned culprit failed alone")
PY
microbatch_rc=$?
rm -rf "$microbatch_tmp"
if [ "$microbatch_rc" -ne 0 ]; then
    echo "microbatch smoke failed (rc=$microbatch_rc): the aggregation" \
         "window under-batched a mixed-tenant burst, broke byte-identity" \
         "vs per-job dispatch, or let a kill-poisoned member hurt its" \
         "batchmates" >&2
    exit "$microbatch_rc"
fi

echo "== ctt-ingest chaos smoke (stream a growing volume through the daemon, SIGKILL mid-stream -> successor resumes from carry, byte-identical) =="
# the ingest gate: the control plane (manifest, slab markers, carry
# records, frontier) lives on the flaky stub object store while the
# volume grows on POSIX; a serve daemon runs the long-lived ingest job,
# is SIGKILLed after the first slab commits, and a successor daemon must
# reclaim the burned generation, restore the persisted carry, finish the
# stream byte-identical (chunk digests) to a batch run over the finished
# volume, and report ctt_ingest_resumes_total >= 1 on /metrics.
ingest_tmp="$(mktemp -d)"
JAX_PLATFORMS=cpu PYTHONPATH="$repo_root${PYTHONPATH:+:$PYTHONPATH}" \
    python - "$ingest_tmp" <<'PY'
import hashlib, json, os, subprocess, sys, time

td = sys.argv[1]
repo_root = os.environ.get("PYTHONPATH", "").split(os.pathsep)[0] or "."
env = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
       "CTT_HEARTBEAT_S": "0.2"}
for k in ("CTT_TRACE_DIR", "CTT_RUN_ID"):
    env.pop(k, None)

import numpy as np
from scipy import ndimage

from cluster_tools_tpu.ingest import publish_manifest, publish_slab
from cluster_tools_tpu.ingest.runner import FRONTIER_NAME, carry_record_name
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.serve import ServeClient
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import StreamingSegmentationWorkflow

SHAPE, SLAB_DEPTH, THRESHOLD = (24, 32, 32), 8, 0.55
GCONF = {"block_shape": [8, 16, 16], "target": "tpu",
         "device_batch_size": 4, "devices": [0], "max_num_retries": 0}


def digest(root):
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


rng = np.random.default_rng(7)
raw = ndimage.gaussian_filter(rng.random(SHAPE), 1.0)
vol = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")

path = os.path.join(td, "data.n5")
f = file_reader(path)
f.create_dataset("raw", data=vol, chunks=(8, 16, 16))
f.create_dataset("raw_live", shape=vol.shape, dtype=vol.dtype,
                 chunks=(8, 16, 16))

# batch reference over the finished volume (in-process, same configs)
config_dir = os.path.join(td, "configs_batch")
cfg.write_global_config(config_dir, dict(GCONF))
cfg.write_config(config_dir, "threshold", {"threshold": THRESHOLD})
wf = StreamingSegmentationWorkflow(
    os.path.join(td, "tmp_batch"), config_dir,
    input_path=path, input_key="raw",
    output_path=path, output_key="cc_batch", watershed=False,
)
assert build([wf]), "batch reference failed"

objroot = os.path.join(td, "objroot")
os.makedirs(objroot)
port_file = os.path.join(td, "stub.port")
stub = subprocess.Popen([
    sys.executable, os.path.join(repo_root, "tests", "objstub.py"),
    "--root", objroot, "--port-file", port_file,
    "--fail-rate", "0.05", "--seed", "7",
], env=env)
daemons = []
state_dir = os.path.join(td, "state")


def spawn():
    proc = subprocess.Popen(
        [sys.executable, "-m", "cluster_tools_tpu.serve",
         "--state-dir", state_dir, "--lease-s", "0.5"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    daemons.append(proc)
    proc.stdout.readline()  # listening banner
    ep_line = proc.stdout.readline()  # endpoint JSON
    assert ep_line, f"daemon died at startup:\n{proc.stderr.read()}"
    ep = json.loads(ep_line)
    client = ServeClient(endpoint=f"http://{ep['host']}:{ep['port']}",
                         token=ep["token"])
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            client.healthz()
            return proc, client
        except Exception:
            assert proc.poll() is None, (
                f"daemon died:\n{proc.stderr.read()}")
            time.sleep(0.1)
    raise AssertionError("daemon never became healthy")


try:
    deadline = time.monotonic() + 30
    while not os.path.exists(port_file):
        assert stub.poll() is None, "objstub died on startup"
        assert time.monotonic() < deadline, "objstub never came up"
        time.sleep(0.05)
    url = f"http://127.0.0.1:{open(port_file).read().strip()}"
    control = url + "/ingest_ctl"
    assert publish_manifest(control, SHAPE, SLAB_DEPTH)

    d1, client1 = spawn()
    job = client1.ingest(
        control_dir=control,
        input_path=path, input_key="raw_live",
        output_path=path, output_key="cc_live",
        tmp_folder=os.path.join(td, "tmp_live"),
        config_dir=os.path.join(td, "configs_live"),
        watershed=False, poll_s=0.05, timeout_s=300.0,
        configs={"global": dict(GCONF),
                 "threshold": {"threshold": THRESHOLD}},
    )

    # the acquisition: slab data to POSIX, THEN its marker to the stub
    # store (the protocol's commit order); slab 2 withheld until after
    # the kill so the takeover provably happens mid-stream
    ds = file_reader(path)["raw_live"]
    for s in (0, 1):
        z0, z1 = s * SLAB_DEPTH, (s + 1) * SLAB_DEPTH
        ds[z0:z1] = vol[z0:z1]
        assert publish_slab(control, s)

    # SIGKILL once the first carry record commits (the stub serves from
    # objroot, so the remote control dir is observable on local disk)
    carry0 = os.path.join(objroot, "ingest_ctl", carry_record_name(0))
    deadline = time.monotonic() + 180
    while not os.path.exists(carry0):
        assert d1.poll() is None, f"daemon died:\n{d1.stderr.read()}"
        assert time.monotonic() < deadline, "first carry never landed"
        time.sleep(0.05)
    d1.kill()
    d1.wait(timeout=30)

    # land the final slab; the successor reclaims the burned generation
    # (lease staleness, 3 x 0.5s) and resumes from the persisted carry
    ds[2 * SLAB_DEPTH:] = vol[2 * SLAB_DEPTH:]
    assert publish_slab(control, 2)
    d2, client2 = spawn()
    st = client2.wait(job, timeout_s=300)
    assert st["result"]["ok"], st
    assert st["result"]["gen"] >= 1, st  # the takeover generation

    f = file_reader(path, "r")
    assert np.array_equal(f["cc_live"][:], f["cc_batch"][:]), (
        "ingest labels differ from the batch run")
    assert digest(os.path.join(path, "cc_live")) == digest(
        os.path.join(path, "cc_batch")
    ), "ingest chunk bytes differ from the batch run"

    frontier = json.load(open(
        os.path.join(objroot, "ingest_ctl", FRONTIER_NAME)))
    assert frontier["slabs_done"] == frontier["slabs_total"] == 3, frontier
    assert frontier["resumes"] >= 1, frontier

    text = client2.metrics_text()
    vals = {
        ln.rsplit(" ", 1)[0]: float(ln.rsplit(" ", 1)[1])
        for ln in text.splitlines() if ln and not ln.startswith("#")
    }
    assert vals.get("ctt_ingest_resumes_total", 0) >= 1, vals
    assert vals.get("ctt_ingest_slabs_ingested_total", 0) >= 1, vals
    print("ingest smoke ok:", json.dumps({
        "gen": st["result"]["gen"],
        "resumes": vals.get("ctt_ingest_resumes_total"),
        "successor_slabs": vals.get("ctt_ingest_slabs_ingested_total"),
    }))
finally:
    for proc in daemons:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    stub.terminate()
    stub.wait(timeout=30)
PY
ingest_rc=$?
if [ "$ingest_rc" -eq 0 ]; then
    # ctt-proto: the stream's whole control plane (manifest, slab
    # markers, carry records, frontier) plus the SIGKILL-survivor state
    # dir must match the artifact registry — resumability IS the schema
    echo "== ctt-proto conformance (ingest control + state dirs vs the artifact registry) =="
    JAX_PLATFORMS=cpu python -m cluster_tools_tpu.analysis conformance \
        "$ingest_tmp/objroot/ingest_ctl" \
    && JAX_PLATFORMS=cpu python -m cluster_tools_tpu.analysis conformance \
        "$ingest_tmp/state"
    ingest_rc=$?
    if [ "$ingest_rc" -ne 0 ]; then
        echo "conformance failed (rc=$ingest_rc): the ingest smoke left" \
             "behind files the registry does not describe — update" \
             "analysis/protocols.py or fix the writer" >&2
    fi
fi
rm -rf "$ingest_tmp"
if [ "$ingest_rc" -ne 0 ]; then
    echo "ingest smoke failed (rc=$ingest_rc): the streaming ingest lost" \
         "byte-identity vs the batch run, the successor never resumed" \
         "from the carry, or the control-plane artifacts drifted from" \
         "the registry" >&2
    exit "$ingest_rc"
fi

echo "== ctt-diskless chaos smoke (supervisor-autoscaled 1->3->1 fleet on a SigV4 stub store, SIGKILL daemon + supervisor mid-burst -> zero loss) =="
# the diskless gate: a serve fleet whose ONLY shared state is an object
# store prefix (SigV4-verified requests, 5% seeded request chaos).  A
# supervisor autoscales 1->3 under a 12-job burst; one daemon AND the
# supervisor are SIGKILLed mid-burst; a restarted supervisor re-adopts
# the fleet from beats alone.  Every job must publish an ok result,
# outputs must be byte-identical to a single-daemon POSIX-state
# reference run, the fleet must drain back to 1, /metrics must show a
# fast-path reclaim and supervisor activity, and the surviving remote
# state dir must pass protocol conformance.
diskless_tmp="$(mktemp -d)"
JAX_PLATFORMS=cpu PYTHONPATH="$repo_root${PYTHONPATH:+:$PYTHONPATH}" \
AWS_ACCESS_KEY_ID=ctt-ci-access AWS_SECRET_ACCESS_KEY=ctt-ci-secret \
CTT_S3_SIGN=1 CTT_HEARTBEAT_S=1.0 \
CTT_TRACE_DIR="$diskless_tmp/trace" CTT_RUN_ID=ci_diskless \
    python - "$diskless_tmp" <<'PY'
import hashlib, json, os, signal, subprocess, sys, time

td = sys.argv[1]
repo_root = os.environ.get("PYTHONPATH", "").split(os.pathsep)[0] or "."
env = {**os.environ, "PALLAS_AXON_POOL_IPS": ""}

import numpy as np

from cluster_tools_tpu.serve import ServeClient
from cluster_tools_tpu.serve.client import read_endpoint
from cluster_tools_tpu.serve.fleet import FleetView, read_peers
from cluster_tools_tpu.utils import file_reader


def digest(root):
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def sleep_job(root_td, data_root, tag, sleep_s, phase):
    # the calibrated-cost fixture task (deterministic input * 2 + 1):
    # the reference run is fast while staying byte-identical
    return {
        "workflow": "bench_e2e_lib:SkewedCostTask",
        "kwargs": {
            "tmp_folder": os.path.join(root_td, f"tmp_{phase}_{tag}"),
            "config_dir": os.path.join(root_td, f"configs_{phase}_{tag}"),
            "input_path": f"{data_root}/{tag}.n5", "input_key": "x",
            "output_path": f"{data_root}/{tag}.n5", "output_key": "y",
        },
        "configs": {
            "global": {"block_shape": [2, 8, 8]},
            "skewed_cost": {
                "hot_z_end": 0, "base_s": float(sleep_s), "hot_s": 99.0,
            },
        },
        "tenant": tag,
    }


tags = [f"k{i}" for i in range(12)]

# -- single-daemon POSIX reference run (the digest oracle) ----------------
ref_root = os.path.join(td, "ref")
os.makedirs(ref_root)
for tag in tags:
    file_reader(os.path.join(ref_root, f"{tag}.n5")).create_dataset(
        "x", data=np.ones((2, 8, 8), dtype="float32"), chunks=(2, 8, 8))
ref = subprocess.Popen(
    [sys.executable, "-m", "cluster_tools_tpu.serve",
     "--state-dir", os.path.join(td, "state_ref"), "--daemon-id", "ref"],
    env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
)
ref.stdout.readline()
ep = json.loads(ref.stdout.readline())
try:
    ref_client = ServeClient(endpoint=f"http://{ep['host']}:{ep['port']}",
                             token=ep["token"])
    jobs = [ref_client.submit(**sleep_job(td, ref_root, t, 0.01, "ref"))
            for t in tags]
    for jid in jobs:
        assert ref_client.wait(jid, timeout_s=300)["result"]["ok"]
finally:
    ref.kill()
    ref.wait(timeout=30)

# -- the diskless fleet: SigV4 stub store, 5% chaos, supervisor ------------
objroot = os.path.join(td, "objroot")
os.makedirs(objroot)
for tag in tags:
    file_reader(os.path.join(objroot, f"{tag}.n5")).create_dataset(
        "x", data=np.ones((2, 8, 8), dtype="float32"), chunks=(2, 8, 8))
port_file = os.path.join(td, "stub.port")
stub = subprocess.Popen([
    sys.executable, os.path.join(repo_root, "tests", "objstub.py"),
    "--root", objroot, "--port-file", port_file,
    "--fail-rate", "0.05", "--seed", "23",
    "--sigv4-access-key", env["AWS_ACCESS_KEY_ID"],
    "--sigv4-secret-key", env["AWS_SECRET_ACCESS_KEY"],
], env=env)
sup = sup2 = None
sup_log = open(os.path.join(td, "supervisor.log"), "w")
try:
    deadline = time.monotonic() + 30
    while not os.path.exists(port_file):
        assert stub.poll() is None, "objstub died on startup"
        assert time.monotonic() < deadline, "objstub never came up"
        time.sleep(0.05)
    url = f"http://127.0.0.1:{open(port_file).read().strip()}"
    state_url = f"{url}/state"

    # acceptance: an UNSIGNED request against the SigV4 store is a
    # retryable auth error (EACCES), never a silent miss
    probe_env = {k: v for k, v in env.items()
                 if k not in ("AWS_ACCESS_KEY_ID", "AWS_SECRET_ACCESS_KEY",
                              "CTT_S3_SIGN")}
    probe_env["CTT_IO_RETRIES"] = "1"
    probe_env["CTT_IO_BACKOFF_BASE_S"] = "0.001"
    probe = subprocess.run(
        [sys.executable, "-c", (
            "import errno, sys\n"
            "from cluster_tools_tpu.utils.store_backend import backend_for\n"
            f"b = backend_for({url!r})\n"
            "try:\n"
            f"    b.read_bytes({url!r} + '/state/serve.json')\n"
            "except FileNotFoundError:\n"
            "    sys.exit(3)  # silent auth downgrade\n"
            "except OSError as e:\n"
            "    sys.exit(0 if e.errno == errno.EACCES else 4)\n"
            "sys.exit(5)\n"
        )], env=probe_env,
    )
    assert probe.returncode == 0, (
        f"unsigned request not a retryable auth error (rc={probe.returncode})")

    def spawn_supervisor():
        return subprocess.Popen(
            [sys.executable, "-m", "cluster_tools_tpu.serve.supervisor",
             "--state-dir", state_url, "--min", "1", "--max", "3",
             "--poll-s", "0.5",
             "--daemon-arg=--lease-s", "--daemon-arg=5",
             "--daemon-arg=--concurrency", "--daemon-arg=2"],
            env=env, stdout=sup_log, stderr=sup_log,
        )

    def live_ids():
        try:
            return sorted(FleetView(state_url).live())
        except OSError:
            return []

    def endpoint_client():
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            try:
                ep = read_endpoint(state_url)
                client = ServeClient(
                    endpoint=f"http://{ep['host']}:{ep['port']}",
                    token=ep["token"])
                client.healthz()
                return client, int(ep["pid"])
            except Exception:
                time.sleep(0.2)
        raise AssertionError("no healthy endpoint over the remote state dir")

    sup = spawn_supervisor()
    client, ep_pid = endpoint_client()  # min-floor daemon came up

    jobs = [client.submit(**sleep_job(td, url, t, 4.0, "fleet"))
            for t in tags]

    # burst pressure scales the fleet to the ceiling (capture the
    # observation: on a loaded host a re-read can transiently flicker)
    n_live = 0
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and n_live != 3:
        assert sup.poll() is None, "supervisor died during scale-up"
        n_live = len(live_ids())
        time.sleep(0.2)
    assert n_live == 3, f"never scaled to 3: {live_ids()}"

    # SIGKILL a non-endpoint daemon once its beat proves a job in
    # flight, and SIGKILL the supervisor in the same breath
    client, ep_pid = endpoint_client()
    victim_pid = None
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline and victim_pid is None:
        for did, rec in read_peers(state_url).items():
            if rec.get("torn") or rec.get("exiting"):
                continue
            pid = int(rec.get("pid") or 0)
            if pid and pid != ep_pid and rec.get("running_jobs", 0) >= 1:
                victim_pid = pid
                break
        time.sleep(0.1)
    assert victim_pid is not None, "no non-endpoint daemon went busy"
    os.kill(victim_pid, signal.SIGKILL)
    sup.kill()
    sup.wait(timeout=30)
    t_kill = time.time()

    # a RESTARTED supervisor re-adopts the fleet from beats alone
    sup2 = spawn_supervisor()

    # zero loss: every job publishes an ok result
    for jid in jobs:
        done = None
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            try:
                client, ep_pid = endpoint_client()
                done = client.wait(jid, timeout_s=60)
                break
            except Exception:
                time.sleep(0.5)
        assert done is not None and done["result"]["ok"], jid

    from cluster_tools_tpu.serve import JobQueue
    q = JobQueue(f"{state_url}/jobs", lease_s=5.0)
    results = [q.get(j)["result"] for j in jobs]
    requeued = [r for r in results if r["gen"] > 0]
    assert requeued, "the killed daemon's job never requeued"

    # byte-identity vs the single-daemon POSIX reference, reclaim incl.
    for tag in tags:
        assert digest(os.path.join(objroot, f"{tag}.n5", "y")) == digest(
            os.path.join(ref_root, f"{tag}.n5", "y")
        ), f"{tag} output differs from the single-daemon run"

    # shared-run /metrics: the fleet reclaimed the killed daemon's job
    # and the supervisors' action ledger moved (spawns + re-adoptions)
    vals = {}
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            client, ep_pid = endpoint_client()
            text = client.metrics_text()
        except Exception:
            time.sleep(0.5)
            continue
        vals = {
            ln.rsplit(" ", 1)[0]: float(ln.rsplit(" ", 1)[1])
            for ln in text.splitlines()
            if ln and not ln.startswith("#")
        }
        if (vals.get("ctt_serve_jobs_reclaimed_total", 0) >= 1
                and vals.get("ctt_serve_supervisor_spawns_total", 0) >= 1
                and vals.get("ctt_serve_supervisor_adoptions_total", 0) >= 1):
            break
        time.sleep(0.5)
    assert vals.get("ctt_serve_jobs_reclaimed_total", 0) >= 1, vals
    assert vals.get("ctt_serve_supervisor_spawns_total", 0) >= 1, vals
    assert vals.get("ctt_serve_supervisor_adoptions_total", 0) >= 1, vals

    # idle fleet drains back to the floor
    n_live = 99
    deadline = time.monotonic() + 150
    while time.monotonic() < deadline and n_live != 1:
        assert sup2.poll() is None, "restarted supervisor died"
        n_live = len(live_ids())
        time.sleep(0.3)
    assert n_live == 1, f"never drained to 1: {live_ids()}"

    # protocol conformance over the SURVIVING REMOTE state dir
    conf = subprocess.run(
        [sys.executable, "-m", "cluster_tools_tpu.analysis",
         "conformance", state_url], env=env,
    )
    assert conf.returncode == 0, (
        f"remote-state conformance failed (rc={conf.returncode})")

    print("diskless smoke ok:", json.dumps({
        "requeued": len(requeued),
        "reclaim_latency_s": round(
            min(r["finished_wall"] for r in requeued) - t_kill, 2),
        "supervisor_spawns": vals.get("ctt_serve_supervisor_spawns_total"),
        "supervisor_adoptions": vals.get(
            "ctt_serve_supervisor_adoptions_total"),
    }))
finally:
    for proc in (sup, sup2):
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    # orphaned daemons (their supervisors were SIGKILLed): sweep by beat
    try:
        for did, rec in read_peers(state_url).items():
            pid = int(rec.get("pid") or 0)
            if pid:
                try:
                    os.kill(pid, signal.SIGKILL)
                except OSError:
                    pass
    except Exception:
        pass
    stub.terminate()
    stub.wait(timeout=30)
    sup_log.close()
PY
diskless_rc=$?
if [ "$diskless_rc" -ne 0 ]; then
    echo "--- supervisor log tail ---" >&2
    tail -40 "$diskless_tmp/supervisor.log" >&2 || true
fi
rm -rf "$diskless_tmp"
if [ "$diskless_rc" -ne 0 ]; then
    echo "diskless smoke failed (rc=$diskless_rc): the supervisor-scaled" \
         "fleet over the SigV4 object store lost a job, broke" \
         "byte-identity, failed to re-adopt after the supervisor kill," \
         "never autoscaled 1->3->1, or left a non-conformant remote" \
         "state dir" >&2
    exit "$diskless_rc"
fi

echo "== ctt-slo smoke (mixed-priority burst -> journey phases, fleet rollup parses, slo gate 0/4) =="
# the request-grain observability gate: a 12-job mixed-priority burst
# through one short-window daemon, then the three post-hoc verbs against
# the surviving state dir alone — `obs journey` must render every phase
# (admission/queue_wait/window_wait/execution/publish/e2e), `obs fleet`
# must emit OpenMetrics the prometheus_client parser accepts, and
# `obs slo` must exit 0 on a generous objective and 4 on an impossible
# one under --fail-on-violation.
slo_tmp="$(mktemp -d)"
JAX_PLATFORMS=cpu PYTHONPATH="$repo_root${PYTHONPATH:+:$PYTHONPATH}" \
    python - "$slo_tmp" <<'PY'
import os, signal, subprocess, sys, time

td = sys.argv[1]
env = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
       "CTT_HEARTBEAT_S": "0.2"}
for k in ("CTT_TRACE_DIR", "CTT_RUN_ID"):
    env.pop(k, None)

import numpy as np
from scipy import ndimage
from cluster_tools_tpu.serve import ServeClient
from cluster_tools_tpu.utils import file_reader

gconf = {"block_shape": [2, 16, 16], "target": "local"}
rng = np.random.default_rng(3)
raw = ndimage.gaussian_filter(
    rng.random((4, 16, 16)), (0.0, 1.0, 1.0)
).astype("float32")
data = np.where(raw > np.quantile(raw, 0.9), raw, 0.0).astype("float32")
path = os.path.join(td, "burst.n5")
file_reader(path).create_dataset("frames", data=data, chunks=(2, 16, 16))

state = os.path.join(td, "state")
daemon = subprocess.Popen(
    [sys.executable, "-m", "cluster_tools_tpu.serve",
     "--state-dir", state, "--concurrency", "1",
     "--microbatch-window-s", "1.0", "--microbatch-max-jobs", "4"],
    env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
)
deadline = time.monotonic() + 120
client = None
while time.monotonic() < deadline:
    assert daemon.poll() is None, daemon.stderr.read()
    try:
        client = ServeClient(state_dir=state)
        client.healthz()
        break
    except Exception:
        time.sleep(0.1)
assert client is not None, "daemon never became healthy"

try:
    jobs = [
        client.event_batch(
            input_path=path, input_key="frames",
            output_path=path, output_key=f"ev_{i}",
            tmp_folder=os.path.join(td, f"tmp_{i}"),
            config_dir=os.path.join(td, f"configs_{i}"),
            threshold=0.1, configs={"global": dict(gconf)},
            tenant=f"t{i % 3}", priority=(i % 3) * 5,
        )
        for i in range(12)
    ]
    for j in jobs:
        st = client.wait(j, timeout_s=300)
        assert st["result"]["ok"], st
finally:
    # SIGTERM drain: run() teardown publishes the final snap.<id>.json
    if daemon.poll() is None:
        daemon.send_signal(signal.SIGTERM)
        try:
            daemon.wait(timeout=60)
        except subprocess.TimeoutExpired:
            daemon.kill()
            daemon.wait(timeout=30)

obs = [sys.executable, "-m", "cluster_tools_tpu.obs"]

# 1) journey: a job that rode the window renders every phase, purely
#    from the state-dir records (the daemon is gone)
out = subprocess.run(obs + ["journey", state, jobs[0]], env=env,
                     capture_output=True, text=True)
assert out.returncode == 0, (out.returncode, out.stderr)
for phase in ("admission", "queue_wait", "window_wait",
              "execution", "publish", "e2e"):
    assert phase in out.stdout, (f"journey missing phase {phase}",
                                 out.stdout)

# 2) fleet: the merged rollup is parser-grade OpenMetrics
fleet = subprocess.run(obs + ["fleet", state], env=env,
                       capture_output=True, text=True)
assert fleet.returncode == 0, (fleet.returncode, fleet.stderr)
from prometheus_client.openmetrics.parser import (
    text_string_to_metric_families,
)
families = {f.name for f in text_string_to_metric_families(fleet.stdout)}
assert any("serve_latency_e2e" in name for name in families), families

# 3) slo gate: generous objective met (0), impossible one violated (4)
met = subprocess.run(
    obs + ["slo", state, "--objective", "e2e_p99_s=300",
           "--fail-on-violation"],
    env=env, capture_output=True, text=True)
assert met.returncode == 0, (met.returncode, met.stdout, met.stderr)
assert "MET" in met.stdout, met.stdout
violated = subprocess.run(
    obs + ["slo", state, "--objective", "e2e_p99_s=0.000001",
           "--fail-on-violation"],
    env=env, capture_output=True, text=True)
assert violated.returncode == 4, (violated.returncode, violated.stdout,
                                  violated.stderr)
assert "VIOLATED" in violated.stdout, violated.stdout

print("slo smoke ok: journey rendered all 6 phases,",
      f"fleet rollup parsed ({len(families)} families),",
      "slo gate 0 on generous / 4 on impossible")
PY
slo_rc=$?
rm -rf "$slo_tmp"
if [ "$slo_rc" -ne 0 ]; then
    echo "slo smoke failed (rc=$slo_rc): the journey timeline lost a" \
         "phase, the fleet rollup was not parser-grade OpenMetrics, or" \
         "the slo gate exit codes broke their 0/4 contract" >&2
    exit "$slo_rc"
fi

echo "== tier-1 tests (ROADMAP.md) =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit "$rc"
