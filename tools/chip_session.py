#!/usr/bin/env python
"""One-shot TPU session: probe → validate → pin modes → bench.

The axon tunnel admits ONE jax client at a time and wedges on killed
clients, so a chip session must be a single, careful, sequential run:

    python tools/chip_session.py            # full session
    python tools/chip_session.py --dry      # probe only

Steps:
  1. cheap TCP probe of the tunnel endpoint (no jax client, no wedge risk);
  2. disposable-subprocess jax probe (600 s) requiring a real TPU device;
  3. tools/tpu_validate.py (assoc-vs-seq, Pallas flood + Pallas CC
     lowering/exactness/perf, device RAG) → tools/tpu_validate.json;
  4. derive the production mode pins (CTT_SWEEP_MODE / CTT_FLOOD_MODE /
     CTT_CC_MODE / CTT_DTWS_MODE) from the measurements
     → tools/chip_modes.json;
  5. bench.py (driver mode) with those pins exported → the BENCH JSON line
     on stdout (the last line, as the driver expects).

Both artifacts (tools/tpu_validate.json, tools/chip_modes.json) are MEANT
to be committed: the validate record is the audit trail of what ran on
silicon, and the backend-tagged pin file is how plain `python bench.py` and
production runs inherit the measured mode winners (ops/_backend.py loads
it; env vars override; non-matching backends ignore it).
"""

import json
import os
import socket
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def port_open(host="127.0.0.1", port=8083, timeout=3.0) -> bool:
    s = socket.socket()
    s.settimeout(timeout)
    try:
        s.connect((host, port))
        s.close()
        return True
    except OSError:
        return False


def jax_probe(timeout: float = 600.0) -> bool:
    """Disposable-subprocess probe requiring a real TPU device.

    Generous timeout + SIGTERM-first escalation: a SIGKILLed jax client can
    wedge the tunnel (see the axon memory note), so give a slow-but-alive
    endpoint every chance to answer and let the child exit cleanly."""
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import sys, jax; jax.devices(); "
         "sys.exit(0 if jax.default_backend() == 'tpu' else 3)"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        return proc.wait(timeout=timeout) == 0
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        return False


def derive_modes(results: dict) -> dict:
    """Production mode pins from tpu_validate measurements.

    CTT_SWEEP_MODE is one global switch consumed by BOTH the watershed
    sweeps and the CC sweeps — pin it on their combined time and report a
    disagreement rather than letting dtws alone decide."""
    modes = {}
    if all(k in results for k in
           ("dtws_assoc_ms", "dtws_seq_ms", "cc_assoc_ms", "cc_seq_ms")):
        assoc = results["dtws_assoc_ms"] + results["cc_assoc_ms"]
        seq = results["dtws_seq_ms"] + results["cc_seq_ms"]
        modes["CTT_SWEEP_MODE"] = "assoc" if assoc <= seq else "seq"
        dtws_pick = results["dtws_assoc_ms"] <= results["dtws_seq_ms"]
        cc_pick = results["cc_assoc_ms"] <= results["cc_seq_ms"]
        if dtws_pick != cc_pick:
            log("NOTE: dtws and cc prefer different sweep modes "
                f"(dtws→{'assoc' if dtws_pick else 'seq'}, "
                f"cc→{'assoc' if cc_pick else 'seq'}); pinned by total")
    elif "dtws_assoc_ms" in results and "dtws_seq_ms" in results:
        modes["CTT_SWEEP_MODE"] = (
            "assoc" if results["dtws_assoc_ms"] <= results["dtws_seq_ms"]
            else "seq"
        )
    if results.get("pallas_flood_exact") and results.get("pallas_flood_wins"):
        modes["CTT_FLOOD_MODE"] = "pallas"
    if results.get("pallas_cc_exact") and results.get("pallas_cc_wins"):
        modes["CTT_CC_MODE"] = "pallas"
    elif (
        results.get("cc_slices_exact")
        and "cc_slices_ms" in results
        and "cc_assoc_ms" in results
        and "cc_seq_ms" in results
        and results["cc_slices_ms"]
        < min(results["cc_assoc_ms"], results["cc_seq_ms"])
    ):
        modes["CTT_CC_MODE"] = "slices"
    if results.get("pallas_dtws_exact") and results.get("pallas_dtws_wins"):
        modes["CTT_DTWS_MODE"] = "pallas"
    if "best_device_batch" in results:
        modes["CTT_DEVICE_BATCH"] = str(results["best_device_batch"])
    # ctt-hbm aggregated dispatch: pin a measured stack depth only where
    # stacking k payloads into one dispatch won by >= 1.1x on this backend
    # (work-bound backends keep the per-batch dispatch shape); the pin
    # makes aggregation the DEFAULT via runtime/hbm.py::hbm_stack, same
    # precedence as CTT_DEVICE_BATCH (env > pin file > off)
    if (
        results.get("best_hbm_stack", 1) > 1
        and results.get("hbm_stack_speedup", 0.0) >= 1.1
    ):
        modes["CTT_HBM_STACK"] = str(results["best_hbm_stack"])
    # graph-domain MWS: route to the device kernel only when it measurably
    # beats the host C++ on this backend; pin host explicitly otherwise so
    # the measured default is recorded either way (VERDICT r4 item 4)
    if "mws_device_ms" in results and "mws_host_ms" in results:
        modes["CTT_MWS_MODE"] = (
            "device" if results.get("mws_device_wins") else "host"
        )
    return modes


def main():
    if not port_open():
        log("tunnel endpoint 127.0.0.1:8083 not listening — nothing to do")
        return 2
    log("port open; probing jax (disposable subprocess, 600 s cap)")
    if "--dry" in sys.argv:
        alive = jax_probe()
        log(f"jax probe: {'TPU alive' if alive else 'unreachable'}")
        return 0 if alive else 2
    if not jax_probe():
        log("port open but no TPU device behind it — aborting")
        return 2

    log("== tpu_validate ==")
    # SIGTERM-first timeout (a SIGKILLed jax client can wedge the tunnel);
    # tpu_validate checkpoints its JSON after every section, so even a
    # timed-out run leaves pins to derive from.  Remove any artifact from
    # a previous round first: deriving pins from a stale file measured
    # against old kernel code would masquerade as a fresh measurement.
    stale = os.path.join(HERE, "tpu_validate.json")
    if os.path.exists(stale):
        os.replace(stale, stale + ".prev")
        log("moved previous tpu_validate.json aside (-> .prev)")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(HERE, "tpu_validate.py")], cwd=ROOT
    )
    try:
        rc = proc.wait(timeout=1800)
    except subprocess.TimeoutExpired:
        log("tpu_validate over its 1800 s budget; terminating (checkpointed "
            "sections survive)")
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        rc = -1
    modes = {}
    if rc != 0:
        log(f"tpu_validate failed (rc={rc}); deriving pins from whatever "
            "sections checkpointed")
    try:
        with open(os.path.join(HERE, "tpu_validate.json")) as f:
            results = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        log(f"tpu_validate.json unreadable ({e}); bench runs unpinned")
    else:
        modes = derive_modes(results)
        # backend-tagged pin file: ops/_backend.py loads it as the
        # default mode source (env vars still override) ONLY when the
        # running backend matches — so the driver's plain `python
        # bench.py` and production runs get the measured winners
        # without leaking TPU pins into CPU runs.
        with open(os.path.join(HERE, "chip_modes.json"), "w") as f:
            json.dump(
                {"backend": results.get("backend", "tpu"),
                 "modes": modes}, f, indent=2)
        log(f"mode pins: {modes}")

    log("== bench (driver mode) ==")
    env = dict(os.environ, **modes)
    bench = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")], cwd=ROOT, env=env
    )
    return bench.returncode


if __name__ == "__main__":
    sys.exit(main())
