#!/usr/bin/env python
"""One-shot TPU validation of the backend-dependent kernel choices.

The flood and CC kernels pick between log-depth ``lax.associative_scan``
sweeps and sequential ``lax.scan`` / neighbor propagation by backend
(assoc on TPU, seq on CPU) — equivalence is CPU-tested, but the *perf* of
the assoc path needs real hardware.  Run this when the chip is reachable:

    python tools/tpu_validate.py

It times both sweep modes for the flood and CC, the fused DT-watershed, the
Pallas per-slice flood (Mosaic lowering + exactness + perf vs the XLA flood),
and the device RAG kernel, prints a table, and writes tools/tpu_validate.json.
Exactly one jax-on-axon process may run at a time (see the memory note on
tunnel fragility) — run nothing else against the chip concurrently.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
from scipy import ndimage

# repo root on sys.path; bench.timeit owns the distinct-input timing scheme
# (variant 0 = sacrificial warmup, one fresh variant per timed round — see its
# docstring for the axon execution-cache rationale)
from bench import (  # noqa: E402
    _rolled,
    fetch_floor_s,
    rolled_pair_variants,
    timeit,
)

REPEATS = 3
SPAN = REPEATS + 1  # warmup + timed rounds — one disjoint span per sweep mode


def main():
    import jax
    import jax.numpy as jnp

    print(f"backend: {jax.default_backend()}, devices: {jax.devices()}")
    results = {"backend": jax.default_backend()}
    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tpu_validate.json"
    )

    def save():
        # checkpoint after every section: a tunnel drop mid-run must not
        # lose the measurements already taken (same unlosable-contract
        # rule as bench.py driver mode).  Atomic via temp + os.replace —
        # chip_session's SIGTERM on timeout must never catch a truncating
        # in-place write and destroy the checkpoints it exists to keep
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(results, f, indent=2)
        os.replace(tmp, out_path)
    # additive per-call floor of the host-fetch completion barrier every
    # timeit round ends in (tunnel RTT; ~0 on a local device) — subtract
    # from sub-10ms entries when comparing kernels
    results["fetch_floor_ms"] = round(fetch_floor_s() * 1e3, 2)
    print(f"fetch floor: {results['fetch_floor_ms']} ms")

    rng = np.random.default_rng(0)
    shape = (32, 256, 256)
    raw = ndimage.gaussian_filter(rng.random(shape), (1.0, 4.0, 4.0))
    raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype(np.float32)
    x = jnp.asarray(raw)
    raws = _rolled(raw, 2 * SPAN)
    xs = [jnp.asarray(v) for v in raws]
    masks = [jnp.asarray(v < 0.5) for v in raws]

    # -- flood + CC: assoc vs seq -------------------------------------------
    from cluster_tools_tpu.ops import _backend
    from cluster_tools_tpu.ops import cc as C
    from cluster_tools_tpu.ops.watershed import dt_watershed

    for i, mode in enumerate(("assoc", "seq")):
      span = slice(i * SPAN, (i + 1) * SPAN)
      with _backend.force_sweep_mode(mode):
        t = timeit(
            None, REPEATS,
            sync=lambda r: r[0].block_until_ready(),
            variants=[
                (lambda v: lambda: dt_watershed(v, threshold=0.5))(v)
                for v in xs[span]
            ],
        )
        results[f"dtws_{mode}_ms"] = round(t * 1e3, 1)
        print(f"dt_watershed[{mode}]: {t*1e3:.1f} ms "
              f"({x.size/t/1e6:.1f} Mvox/s)")
        t = timeit(
            None, REPEATS,
            sync=lambda r: r[0].block_until_ready(),
            variants=[
                (lambda m: lambda: C.connected_components(m))(m)
                for m in masks[span]
            ],
        )
        results[f"cc_{mode}_ms"] = round(t * 1e3, 1)
        print(f"connected_components[{mode}]: {t*1e3:.1f} ms")
        save()

    save()

    # -- XLA slices+z-merge CC mode (CTT_CC_MODE=slices) --------------------
    # structure of the Pallas path in plain XLA; measured 5x SLOWER on the
    # 1-core CPU fallback (both stages are round-bound) — only pinned if
    # the chip's bandwidth flips it.  Baseline pinned to the default XLA
    # path (a live pin file could otherwise make the reference the slices
    # path itself); timing runs on a FRESH disjoint input span.
    with _backend.force_cc_mode("xla"):
        want_l, want_n = C.connected_components(masks[0])
    slices_masks = [
        jnp.asarray(v < 0.5) for v in _rolled(raw, SPAN, start=2 * SPAN)
    ]
    with _backend.force_cc_mode("slices"):
        got_l, got_n = C.connected_components(masks[0])
        slices_agree = bool(jnp.array_equal(got_l, want_l)) and int(
            got_n) == int(want_n)
        results["cc_slices_exact"] = slices_agree
        t = timeit(
            None, REPEATS,
            sync=lambda r: r[0].block_until_ready(),
            variants=[
                (lambda m: lambda: C.connected_components(m))(m)
                for m in slices_masks
            ],
        )
        results["cc_slices_ms"] = round(t * 1e3, 1)
        print(f"connected_components[slices]: {t*1e3:.1f} ms "
              f"(exact={slices_agree})")

    # -- Pallas per-slice flood: Mosaic lowering + perf vs the XLA flood ----
    # (the only place the real-hardware lowering of ops/pallas_flood.py is
    # exercised — the CPU interpreter covers correctness, not Mosaic)
    from cluster_tools_tpu.ops.pallas_flood import flood_slices
    from cluster_tools_tpu.ops.watershed import (
        _seeded_watershed_scan,
        dt_seeds,
    )
    from cluster_tools_tpu.ops.dt import distance_transform_2d_stack

    fg = jnp.asarray(raw < 0.5)
    dt_f = distance_transform_2d_stack(fg)
    seeds_f, _ = dt_seeds(dt_f, sigma=2.0, per_slice=True)
    hmaps = [jnp.asarray(0.8 * v + 0.2) for v in raws]
    try:
        ref_out = _seeded_watershed_scan(hmaps[0], seeds_f, fg, per_slice=True)
        got = flood_slices(hmaps[0], seeds_f, fg)
        agree = bool(jnp.array_equal(got, ref_out))
        results["pallas_flood_exact"] = agree
        t_p = timeit(
            None, REPEATS,
            sync=lambda r: r.block_until_ready(),
            variants=[
                (lambda h: lambda: flood_slices(h, seeds_f, fg))(h)
                for h in hmaps[:SPAN]
            ],
        )
        t_x = timeit(
            None, REPEATS,
            sync=lambda r: r.block_until_ready(),
            variants=[
                (lambda h: lambda: _seeded_watershed_scan(
                    h, seeds_f, fg, per_slice=True))(h)
                for h in hmaps[SPAN : 2 * SPAN]
            ],
        )
        results["pallas_flood_ms"] = round(t_p * 1e3, 1)
        results["xla_flood_ms"] = round(t_x * 1e3, 1)
        results["pallas_flood_wins"] = t_p < t_x
        print(f"pallas flood: {t_p*1e3:.1f} ms (exact={agree}), "
              f"xla flood: {t_x*1e3:.1f} ms")
    except Exception as e:  # Mosaic lowering / runtime failure: record, go on
        results["pallas_flood_error"] = f"{type(e).__name__}: {e}"[:500]
        print(f"pallas flood FAILED to lower/run: {e}")

    # -- fused Pallas DT-watershed vs the XLA pipeline ----------------------
    from cluster_tools_tpu.ops.pallas_dtws import pallas_dt_watershed
    from cluster_tools_tpu.ops.watershed import dt_watershed as _dtws

    try:
        # reference pinned to the XLA path: with CTT_DTWS_MODE=pallas in the
        # environment the gated dt_watershed would compare Pallas to itself
        with _backend.force_dtws_mode("xla"):
            want_l, want_n = _dtws(xs[0], threshold=0.5)
        got_l, got_n = pallas_dt_watershed(xs[0], threshold=0.5)
        dtws_agree = bool(jnp.array_equal(got_l, want_l)) and int(
            got_n
        ) == int(want_n)
        results["pallas_dtws_exact"] = dtws_agree
        t_p = timeit(
            None, REPEATS,
            sync=lambda r: r[0].block_until_ready(),
            # device-resident inputs, like the XLA baselines — a host array
            # here would bill the H2D transfer to the kernel
            variants=[
                (lambda v: lambda: pallas_dt_watershed(v, threshold=0.5))(v)
                for v in xs[SPAN : 2 * SPAN]
            ],
        )
        results["pallas_dtws_ms"] = round(t_p * 1e3, 1)
        results["pallas_dtws_wins"] = (
            results["pallas_dtws_ms"]
            < min(results["dtws_assoc_ms"], results["dtws_seq_ms"])
        )
        print(f"pallas dtws: {t_p*1e3:.1f} ms (exact={dtws_agree})")
    except Exception as e:  # Mosaic lowering / runtime failure: record, go on
        results["pallas_dtws_error"] = f"{type(e).__name__}: {e}"[:500]
        print(f"pallas dtws FAILED to lower/run: {e}")

    save()

    # -- Pallas per-slice CC + z-merge vs the XLA CC ------------------------
    from cluster_tools_tpu.ops.pallas_cc import pallas_connected_components

    try:
        want_l, want_n = C.connected_components(masks[0])
        got_l, got_n = pallas_connected_components(masks[0])
        cc_agree = bool(jnp.array_equal(got_l, want_l)) and int(got_n) == int(
            want_n
        )
        results["pallas_cc_exact"] = cc_agree
        t_p = timeit(
            None, REPEATS,
            sync=lambda r: r[0].block_until_ready(),
            variants=[
                (lambda m: lambda: pallas_connected_components(m))(m)
                for m in masks[:SPAN]
            ],
        )
        results["pallas_cc_ms"] = round(t_p * 1e3, 1)
        results["pallas_cc_wins"] = (
            results["pallas_cc_ms"]
            < min(results["cc_assoc_ms"], results["cc_seq_ms"])
        )
        print(f"pallas cc: {t_p*1e3:.1f} ms (exact={cc_agree})")
    except Exception as e:  # Mosaic lowering / runtime failure: record, go on
        results["pallas_cc_error"] = f"{type(e).__name__}: {e}"[:500]
        print(f"pallas cc FAILED to lower/run: {e}")

    save()

    # -- device RAG kernel vs numpy -----------------------------------------
    from cluster_tools_tpu import native
    from cluster_tools_tpu.ops import rag

    labels, _ = native.dt_watershed_cpu(raw, threshold=0.5)
    # the production wrapper packs the sort key whenever the compact label
    # space fits 15 bits AND compacts valid face rows before the sort —
    # measure the same path (cap maxed over the rolled variants, whose
    # wrap seams add boundary faces)
    packed = int(labels.max()) <= rag.PACK_MAX_ID
    lab32 = labels.astype(np.int32)
    cap = rag.sample_capacity(max(
        rag.count_boundary_samples(np.roll(lab32, 7 * i, axis=1) if i else lab32)
        for i in range(SPAN)
    ))
    t_dev = timeit(
        None, REPEATS,
        sync=lambda r: r[0].block_until_ready(),
        variants=rolled_pair_variants(
            raw, lab32, SPAN,
            lambda l, v: rag.boundary_edge_features_device(
                l, v, max_edges=65536, packed=packed, max_samples=cap),
        ),
    )
    results["rag_packed"] = bool(packed)
    results["rag_sample_cap"] = int(cap)
    t0 = time.perf_counter()
    rag.boundary_edge_features(labels.astype(np.uint64), raw)
    t_host = time.perf_counter() - t0
    results["rag_device_ms"] = round(t_dev * 1e3, 1)
    results["rag_numpy_ms"] = round(t_host * 1e3, 1)
    print(f"rag device: {t_dev*1e3:.1f} ms, numpy: {t_host*1e3:.1f} ms")

    save()

    # -- device MWS vs host C++ (CTT_MWS_MODE pin) --------------------------
    # the graph-domain device kernel on the bench's realistic bimodal
    # affinity problem (doomed-pair discard keeps rounds low since r5);
    # the winner decides whether per-block MWS solves route to the device
    try:
        from scipy import ndimage as _ndi

        from cluster_tools_tpu.ops.mws import _affinity_edge_lists
        from cluster_tools_tpu.ops.mws_device import (
            mutex_watershed_device, mutex_watershed_device_rounds,
        )

        offsets = [[-1, 0, 0], [0, -1, 0], [0, 0, -1],
                   [-2, 0, 0], [0, -4, 0], [0, 0, -4]]
        mws_shape = (8, 32, 32)
        mws_rng = np.random.default_rng(1)
        affs = _ndi.gaussian_filter(
            mws_rng.random((len(offsets),) + mws_shape).astype(np.float32),
            (0, 1, 2, 2),
        )
        n_mws = int(np.prod(mws_shape))
        # one problem per rolled affinity volume: distinct inputs per timed
        # round (tunnel result caches), conversions prepared OUTSIDE the
        # timed window, and the pin decided by timeit like every other
        # pin-deciding section — one RTT spike must not flip CTT_MWS_MODE
        problems = []
        for i in range(SPAN):
            a_i = np.roll(affs, 3 * i, axis=2) if i else affs
            us, vs, ws_l, at_l = _affinity_edge_lists(
                a_i, offsets, [1, 2, 2], False, 0.0,
                np.random.default_rng(0), 3,
            )
            uv = np.stack([np.concatenate(us), np.concatenate(vs)], axis=1)
            w = np.concatenate(ws_l).astype(np.float32)
            at = np.concatenate(at_l).astype(bool)
            problems.append(
                (uv, w, at, uv.astype(np.int64), w.astype(np.float64),
                 at.astype(np.uint8))
            )
        results["mws_device_rounds"] = mutex_watershed_device_rounds(
            n_mws, *problems[0][:3]
        )
        t_mws_dev = timeit(
            None, REPEATS,
            variants=[
                (lambda p: lambda: mutex_watershed_device(n_mws, *p[:3]))(p)
                for p in problems
            ],
        )
        t_mws_host = timeit(
            None, REPEATS,
            variants=[
                (lambda p: lambda: native.mutex_watershed(n_mws, *p[3:]))(p)
                for p in problems
            ],
        )
        results["mws_device_ms"] = round(t_mws_dev * 1e3, 1)
        results["mws_host_ms"] = round(t_mws_host * 1e3, 1)
        results["mws_device_wins"] = t_mws_dev < t_mws_host
        print(f"mws device: {t_mws_dev*1e3:.1f} ms "
              f"({results['mws_device_rounds']} rounds), "
              f"host C++: {t_mws_host*1e3:.1f} ms")
    except Exception as e:
        results["mws_device_error"] = f"{type(e).__name__}: {e}"[:500]
        print(f"mws device FAILED: {e}")

    save()

    # -- device batch-size sweep (CTT_DEVICE_BATCH pin) ---------------------
    # per-block voxel rate of the vmapped DT-watershed at several batch
    # sizes: a batch amortizes dispatch/tunnel latency but vmap can
    # serialize while_loop rounds across the batch (max-over-batch) — only
    # measurement can pick the winner for a backend
    block = raw[:16, :128, :128]
    best_rate, best_bs = -1.0, 1
    for bs in (1, 4, 8, 16):
        fn = jax.jit(jax.vmap(lambda v: dt_watershed(v, threshold=0.5)[0]))
        stacks = [
            jnp.asarray(np.stack([
                np.roll(v, j + 1, axis=1) for j in range(bs)
            ]))
            for v in _rolled(block, SPAN)
        ]
        try:
            t = timeit(
                None, REPEATS,
                sync=lambda r: r.block_until_ready(),
                variants=[(lambda s: lambda: fn(s))(s) for s in stacks],
            )
        except Exception as e:
            results[f"batch{bs}_error"] = f"{type(e).__name__}: {e}"[:200]
            continue
        rate = bs * block.size / t / 1e6
        results[f"batch{bs}_mvox_s"] = round(rate, 1)
        print(f"batch sweep x{bs}: {t*1e3:.1f} ms ({rate:.1f} Mvox/s)")
        if rate > best_rate:
            best_rate, best_bs = rate, bs
    if best_rate > 0:  # never pin from an all-errored sweep
        results["best_device_batch"] = best_bs

    save()

    # -- aggregated dispatch sweep (CTT_HBM_STACK pin, ctt-hbm) -------------
    # k read payloads stacked into ONE (k*B, ...) dispatch vs k separate
    # dispatches of the same vmapped kernel: aggregation amortizes
    # dispatch/tunnel latency on a compute-light (dispatch-bound) kernel —
    # the threshold shape, the workload hbm_stack targets.  Pinned (by
    # chip_session.derive_modes) only where the measured win is >= 1.1x,
    # so work-bound backends keep the per-batch dispatch shape.
    try:
        thr_block = raw[:8, :64, :64]
        thr_fn = jax.jit(jax.vmap(lambda v: (v > 0.5).astype(jnp.uint8)))
        stack_k, stack_b = 8, 4
        singles = [
            [
                jnp.asarray(np.stack([
                    np.roll(v, 3 * j + k + 1, axis=1)
                    for j in range(stack_b)
                ]))
                for k in range(stack_k)
            ]
            for v in _rolled(thr_block, SPAN)
        ]
        stacks = [
            jnp.concatenate(parts, axis=0) for parts in singles
        ]
        t_single = timeit(
            None, REPEATS,
            sync=lambda r: r[-1].block_until_ready(),
            variants=[
                (lambda parts: lambda: [thr_fn(p) for p in parts])(parts)
                for parts in singles
            ],
        )
        t_stacked = timeit(
            None, REPEATS,
            sync=lambda r: r.block_until_ready(),
            variants=[(lambda s: lambda: thr_fn(s))(s) for s in stacks],
        )
        results["hbm_single_ms"] = round(t_single * 1e3, 2)
        results["hbm_stacked_ms"] = round(t_stacked * 1e3, 2)
        speedup = t_single / max(t_stacked, 1e-9)
        results["hbm_stack_speedup"] = round(speedup, 2)
        results["best_hbm_stack"] = stack_k if speedup >= 1.1 else 1
        print(f"hbm stack x{stack_k}: {t_single*1e3:.2f} ms separate -> "
              f"{t_stacked*1e3:.2f} ms stacked ({speedup:.2f}x)")
    except Exception as e:
        results["hbm_stack_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"hbm stack sweep FAILED: {e}")

    save()

    # -- verdicts ------------------------------------------------------------
    results["flood_assoc_wins"] = results["dtws_assoc_ms"] < results["dtws_seq_ms"]
    results["cc_assoc_wins"] = results["cc_assoc_ms"] < results["cc_seq_ms"]
    results["rag_device_wins"] = results["rag_device_ms"] < results["rag_numpy_ms"]
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "tpu_validate.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps(results))
    print(f"-> {out}")
    if not results["flood_assoc_wins"] or not results["cc_assoc_wins"]:
        print("NOTE: an assoc path lost on this backend — consider flipping "
              "the default in _use_assoc() for it.")


if __name__ == "__main__":
    main()
