#!/usr/bin/env python
"""One-shot TPU validation of the backend-dependent kernel choices.

The flood and CC kernels pick between log-depth ``lax.associative_scan``
sweeps and sequential ``lax.scan`` / neighbor propagation by backend
(assoc on TPU, seq on CPU) — equivalence is CPU-tested, but the *perf* of
the assoc path needs real hardware.  Run this when the chip is reachable:

    python tools/tpu_validate.py

It times both modes for the flood and CC, the fused DT-watershed, and the
device RAG kernel, prints a table, and writes tools/tpu_validate.json.
Exactly one jax-on-axon process may run at a time (see the memory note on
tunnel fragility) — run nothing else against the chip concurrently.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
from scipy import ndimage

# repo root on sys.path; bench.timeit owns the distinct-input timing scheme
# (variant 0 = sacrificial warmup, one fresh variant per timed round — see its
# docstring for the axon execution-cache rationale)
from bench import timeit, _rolled, rolled_pair_variants  # noqa: E402

REPEATS = 3
SPAN = REPEATS + 1  # warmup + timed rounds — one disjoint span per sweep mode


def main():
    import jax
    import jax.numpy as jnp

    print(f"backend: {jax.default_backend()}, devices: {jax.devices()}")
    results = {"backend": jax.default_backend()}

    rng = np.random.default_rng(0)
    shape = (32, 256, 256)
    raw = ndimage.gaussian_filter(rng.random(shape), (1.0, 4.0, 4.0))
    raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype(np.float32)
    x = jnp.asarray(raw)
    raws = _rolled(raw, 2 * SPAN)
    xs = [jnp.asarray(v) for v in raws]
    masks = [jnp.asarray(v < 0.5) for v in raws]

    # -- flood + CC: assoc vs seq -------------------------------------------
    from cluster_tools_tpu.ops import _backend
    from cluster_tools_tpu.ops import cc as C
    from cluster_tools_tpu.ops.watershed import dt_watershed

    for i, mode in enumerate(("assoc", "seq")):
      span = slice(i * SPAN, (i + 1) * SPAN)
      with _backend.force_sweep_mode(mode):
        t = timeit(
            None, REPEATS,
            sync=lambda r: r[0].block_until_ready(),
            variants=[
                (lambda v: lambda: dt_watershed(v, threshold=0.5))(v)
                for v in xs[span]
            ],
        )
        results[f"dtws_{mode}_ms"] = round(t * 1e3, 1)
        print(f"dt_watershed[{mode}]: {t*1e3:.1f} ms "
              f"({x.size/t/1e6:.1f} Mvox/s)")
        t = timeit(
            None, REPEATS,
            sync=lambda r: r[0].block_until_ready(),
            variants=[
                (lambda m: lambda: C.connected_components(m))(m)
                for m in masks[span]
            ],
        )
        results[f"cc_{mode}_ms"] = round(t * 1e3, 1)
        print(f"connected_components[{mode}]: {t*1e3:.1f} ms")

    # -- device RAG kernel vs numpy -----------------------------------------
    from cluster_tools_tpu import native
    from cluster_tools_tpu.ops import rag

    labels, _ = native.dt_watershed_cpu(raw, threshold=0.5)
    t_dev = timeit(
        None, REPEATS,
        sync=lambda r: r[0].block_until_ready(),
        variants=rolled_pair_variants(
            raw, labels.astype(np.int32), SPAN,
            lambda l, v: rag.boundary_edge_features_device(l, v, max_edges=65536),
        ),
    )
    t0 = time.perf_counter()
    rag.boundary_edge_features(labels.astype(np.uint64), raw)
    t_host = time.perf_counter() - t0
    results["rag_device_ms"] = round(t_dev * 1e3, 1)
    results["rag_numpy_ms"] = round(t_host * 1e3, 1)
    print(f"rag device: {t_dev*1e3:.1f} ms, numpy: {t_host*1e3:.1f} ms")

    # -- verdicts ------------------------------------------------------------
    results["flood_assoc_wins"] = results["dtws_assoc_ms"] < results["dtws_seq_ms"]
    results["cc_assoc_wins"] = results["cc_assoc_ms"] < results["cc_seq_ms"]
    results["rag_device_wins"] = results["rag_device_ms"] < results["rag_numpy_ms"]
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "tpu_validate.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps(results))
    print(f"-> {out}")
    if not results["flood_assoc_wins"] or not results["cc_assoc_wins"]:
        print("NOTE: an assoc path lost on this backend — consider flipping "
              "the default in _use_assoc() for it.")


if __name__ == "__main__":
    main()
