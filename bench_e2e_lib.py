"""End-to-end multicut pipeline for bench.py (config 5 of BASELINE.md).

Shared by the device run (in-process) and the host-CPU baseline (subprocess
with JAX_PLATFORMS=cpu): the full MulticutSegmentationWorkflow —
watershed → graph → features → costs → multicut → write (reference
workflows.py:203-233) — on a synthetic CREMI-like boundary volume.
Returns the workflow wall-clock in seconds (data staging excluded).
"""

import os
import sys
import tempfile
import time

import numpy as np


# the benchmarked watershed task config — ONE definition so the ws-only
# benchmark measures exactly the workload the full pipeline's first stage
# runs (run_pipeline and run_ws_pipeline must not drift apart)
WS_TASK_CONFIG = {
    "threshold": 0.5, "sigma_seeds": 2.0, "size_filter": 25,
    "halo": [2, 4, 4],
}
# the collective (whole-volume) watershed variants take the same kernel
# knobs minus the block-only halo, PLUS the per-slice mode flags matching
# the block pipeline's default (apply_dt_2d/apply_ws_2d default True
# there) — the collective 2d kernel is embarrassingly parallel over the
# z-shards and measures the same algorithm the baseline runs
SHARDED_WS_CONFIG = {
    **{k: v for k, v in WS_TASK_CONFIG.items() if k != "halo"},
    "apply_dt_2d": True,
    "apply_ws_2d": True,
}


def flood_rounds_probe(x, tile=(8, 64, 64)):
    """Flood fixpoint round counts — flat vs ctt-cc tile-warm-started — on
    the bench fixture's own DT-WS fields (threshold/sigma from
    WS_TASK_CONFIG, per-slice production mode).  Rounds, not walls: the
    crop is small and the point is the hierarchical-flood structural
    contract (ops.watershed._flood_scan_impl), recorded alongside the ws
    e2e walls in bench.py's extras."""
    import jax.numpy as jnp

    from cluster_tools_tpu.ops import watershed as ws_ops
    from cluster_tools_tpu.ops.cc import resolve_coarse_tile
    from cluster_tools_tpu.ops.dt import distance_transform_2d_stack

    xv = jnp.asarray(np.asarray(x)[:8], jnp.float32)
    fg = xv < WS_TASK_CONFIG["threshold"]
    dt = distance_transform_2d_stack(fg, pixel_pitch=None)
    seeds, _ = ws_ops.dt_seeds(
        dt, WS_TASK_CONFIG["sigma_seeds"], per_slice=True
    )
    hmap = ws_ops.make_hmap(
        xv, dt, 0.8, WS_TASK_CONFIG["sigma_seeds"], per_slice=True
    )
    out = {}
    for tag, t in (
        ("flat", None), ("tiled", resolve_coarse_tile(xv.shape, tile))
    ):
        _, _, stats = ws_ops.flood_with_stats(
            hmap, seeds, fg, per_slice=True, tile=t
        )
        out[f"ws_flood_alt_iters_{tag}"] = int(stats["flood_alt_iters"])
        out[f"ws_flood_assign_iters_{tag}"] = int(
            stats["flood_assign_iters"]
        )
        if t is not None:
            out["ws_flood_tile_iters"] = int(stats["flood_tile_iters"])
    return out


def stage_breakdown(tmp_folder):
    """Per-stage pipeline seconds summed over a run's status files — the
    three-stage executor's ``stage_{read,compute,write}_total`` records
    (one aggregate per dispatch round).  Empty dict when no staged dispatch
    ran (local target, sharded single-shot tasks, pipeline_depth 1)."""
    import json

    totals = {"read": 0.0, "compute": 0.0, "write": 0.0}
    found = False
    sdir = os.path.join(tmp_folder, "status")
    if not os.path.isdir(sdir):
        return {}
    for name in sorted(os.listdir(sdir)):
        if not name.endswith(".status.json"):
            continue
        try:
            with open(os.path.join(sdir, name)) as fh:
                st = json.load(fh)
        except (OSError, ValueError):
            continue
        for rec in st.get("timings", []):
            label = str(rec.get("label", ""))
            if label.startswith("stage_") and label.endswith("_total"):
                key = label[len("stage_"):-len("_total")]
                if key in totals:
                    totals[key] += float(rec.get("seconds", 0.0))
                    found = True
    if not found:
        return {}
    return {f"stage_{k}_s": round(v, 3) for k, v in totals.items()}


def _stage_volume(td, vol_path, shape, block_shape, warm):
    """Load the benchmark volume into a fresh n5 container; with ``warm``
    also stage a DISTINCT (z-rolled) copy for the jit-cache-warm rerun."""
    from cluster_tools_tpu.utils import file_reader

    vol = np.load(vol_path).astype(np.float32)
    assert vol.shape == tuple(shape)
    data_path = os.path.join(td, "data.n5")
    f = file_reader(data_path)
    f.create_dataset("bnd", data=vol, chunks=tuple(block_shape))
    if warm:
        f.create_dataset(
            "bnd_warm", data=np.roll(vol, 7, axis=1),
            chunks=tuple(block_shape),
        )
    return data_path


def run_pipeline(vol_path, shape, block_shape, target, sharded_problem=False,
                 sharded_ws=False, warm=False, seg_export=None):
    """Wall-clock of the full pipeline; ``sharded_problem=True`` swaps the
    block-wise graph+features extraction for the one-program collective
    path (ShardedProblemTask + global solve); ``sharded_ws=True``
    additionally fuses the watershed into that collective session
    (ShardedWsProblemTask: the boundary volume crosses host→device ONCE
    and stays resident through watershed and RAG — since round 5 the
    bench's sharded configuration measures THIS path).

    ``warm=True`` runs the pipeline a second time in fresh scratch folders
    on a DISTINCT (z-rolled) copy of the volume and returns
    ``(cold_wall, warm_wall)``: same shapes → every jit cache is reused,
    different data → no dispatch can be served from the axon tunnel's
    execution-result cache (which replays identical programs on identical
    inputs in ~0 ms — the warm number must be steady-state compute, the rate
    a production sweep over many ROIs pays)."""
    from cluster_tools_tpu.runtime import build, config as cfg
    from cluster_tools_tpu.workflows import MulticutSegmentationWorkflow

    with tempfile.TemporaryDirectory() as td:
        data_path = _stage_volume(td, vol_path, shape, block_shape, warm)

        def task_breakdown(tmp_folder):
            """Per-task busy seconds from the status files — the data behind
            'where did the e2e wall go' (printed to stderr for the cold AND
            warm runs; cold-minus-warm per task isolates compile cost).

            Counts one aggregate per dispatch round: the local executor's
            "blocks_total" records (its companion "block_max" is a max, not
            an addend) and the tpu executor's per-batch "batch_*" walls.
            Batch walls can overlap under ``pipeline_depth`` > 1, so a
            task's busy seconds may legitimately exceed its wall share."""
            import json

            out = {}
            sdir = os.path.join(tmp_folder, "status")
            if not os.path.isdir(sdir):
                return out
            for name in sorted(os.listdir(sdir)):
                if not name.endswith(".status.json"):
                    continue
                try:
                    with open(os.path.join(sdir, name)) as fh:
                        st = json.load(fh)
                except (OSError, ValueError):
                    continue
                disp = sum(
                    t.get("seconds", 0.0) for t in st.get("timings", [])
                    if t.get("label") == "blocks_total"
                    or str(t.get("label", "")).startswith("batch_")
                )
                blk = sum(float(r) for r in st.get("block_runtimes", []))
                # sum, don't assign: multi-host topologies write one status
                # file PER PROCESS (<task>.p<pid>.status.json) under the
                # same task identifier
                key = st.get("task", name)
                out[key] = round(out.get(key, 0.0) + max(disp, blk), 3)
            return out

        def one_run(tag, input_key):
            config_dir = os.path.join(td, f"configs{tag}")
            tmp_folder = os.path.join(td, f"tmp{tag}")
            cfg.write_global_config(
                config_dir,
                {"block_shape": list(block_shape), "target": target},
            )
            cfg.write_config(config_dir, "watershed", dict(WS_TASK_CONFIG))
            cfg.write_config(
                config_dir, "sharded_problem", {"max_edges": 1 << 17}
            )
            cfg.write_config(
                config_dir, "sharded_ws_problem",
                {"max_edges": 1 << 17, **SHARDED_WS_CONFIG},
            )
            wf = MulticutSegmentationWorkflow(
                tmp_folder, config_dir,
                input_path=data_path, input_key=input_key,
                ws_path=data_path, ws_key=f"ws{tag}",
                output_path=data_path, output_key=f"seg{tag}",
                n_scales=1,
                sharded_problem=sharded_problem,
                sharded_ws=sharded_ws,
            )
            t0 = time.perf_counter()
            ok = build([wf])
            wall = time.perf_counter() - t0
            if not ok:
                raise RuntimeError(f"e2e multicut workflow failed ({tag})")
            return wall, task_breakdown(tmp_folder)

        def show(tag, wall_s, breakdown):
            accounted = round(sum(breakdown.values()), 2)
            print(f"[e2e breakdown {tag}, wall {wall_s:.2f} s, task-busy "
                  f"{accounted} s] "
                  + " ".join(f"{k}={v}" for k, v in sorted(
                      breakdown.items(), key=lambda kv: -kv[1])),
                  file=sys.stderr, flush=True)

        wall, cold_breakdown = one_run("", "bnd")
        if seg_export is not None:
            # the cold run's final segmentation, for cross-target Rand/VoI
            # parity (BASELINE.md: "Rand-Index / VoI parity vs 'local'")
            from cluster_tools_tpu.utils import file_reader

            with file_reader(data_path, "r") as f:
                np.save(seg_export, f["seg"][:])
        if not warm:
            return wall
        # cold-vs-warm per task separates compile cost (cold only) from
        # steady-state compute — the data behind cold-wall attribution
        show("cold", wall, cold_breakdown)
        warm_wall, breakdown = one_run("_warm", "bnd_warm")
        show("warm", warm_wall, breakdown)
    return wall, warm_wall


def run_stream_pipeline(vol_path, shape, block_shape, target):
    """ctt-stream contract: the StreamingSegmentationWorkflow (threshold →
    block CC → watershed over one raw volume) run fused (one streaming
    pass, mask elided, offsets/faces from carried state) AND task-at-a-time,
    with ``store.bytes_read`` / ``store.bytes_written`` recorded from the
    obs store counters for both — the round-trip reduction lands in the
    bench JSON rather than only in wall clock.

    Byte counts are taken with the decoded-chunk LRU disabled: at bench
    scale the 64 MB cache holds the whole fixture and would hide exactly
    the cross-task re-reads the fusion removes (production volumes dwarf
    the cache, so codec-boundary traffic is the honest scale model).  Warm
    walls follow the run_ws_pipeline discipline: cold on ``bnd``, warm on
    the distinct z-rolled copy, same shapes → jit caches reused.
    """
    from cluster_tools_tpu.obs import metrics as obs_metrics, trace as obs_trace
    from cluster_tools_tpu.runtime import build, config as cfg
    from cluster_tools_tpu.utils import file_reader, store as store_mod
    from cluster_tools_tpu.workflows import StreamingSegmentationWorkflow

    with tempfile.TemporaryDirectory() as td:
        data_path = _stage_volume(td, vol_path, shape, block_shape, True)
        trace_was_on = obs_trace.enabled()
        if not trace_was_on:
            obs_trace.enable(
                os.path.join(td, "trace"), "stream_bench", export_env=False
            )
        prev_budget = store_mod.set_chunk_cache_budget(0)
        try:
            def one(tag, fused, input_key):
                config_dir = os.path.join(td, f"configs_{tag}")
                cfg.write_global_config(
                    config_dir,
                    {"block_shape": list(block_shape), "target": target,
                     "stream_fusion": fused},
                )
                cfg.write_config(config_dir, "threshold", {"threshold": 0.5})
                cfg.write_config(
                    config_dir, "watershed", dict(WS_TASK_CONFIG)
                )
                wf = StreamingSegmentationWorkflow(
                    os.path.join(td, f"tmp_{tag}"), config_dir,
                    input_path=data_path, input_key=input_key,
                    output_path=data_path, output_key=f"cc_{tag}",
                )
                before = obs_metrics.snapshot()["counters"]
                t0 = time.perf_counter()
                ok = build([wf])
                wall = time.perf_counter() - t0
                after = obs_metrics.snapshot()["counters"]
                if not ok:
                    raise RuntimeError(f"stream pipeline failed ({tag})")

                def delta(name):
                    return after.get(name, 0.0) - before.get(name, 0.0)

                return (wall, delta("store.bytes_read"),
                        delta("store.bytes_written"))

            one("un_cold", False, "bnd")
            un_warm, un_read, un_written = one("un_warm", False, "bnd_warm")
            one("f_cold", True, "bnd")
            f_warm, f_read, f_written = one("f_warm", True, "bnd_warm")

            with file_reader(data_path, "r") as f:
                parity = bool(
                    np.array_equal(f["cc_un_warm"][:], f["cc_f_warm"][:])
                    and np.array_equal(
                        f["cc_un_warm_ws"][:], f["cc_f_warm_ws"][:]
                    )
                )
        finally:
            store_mod.set_chunk_cache_budget(prev_budget)
            if not trace_was_on:
                obs_trace.disable()
    return {
        "ws_e2e_store_bytes_read": int(un_read),
        "ws_e2e_store_bytes_written": int(un_written),
        "ws_e2e_stream_store_bytes_read": int(f_read),
        "ws_e2e_stream_store_bytes_written": int(f_written),
        "ws_e2e_stream_read_reduction": round(un_read / max(f_read, 1.0), 2),
        "ws_e2e_stream_warm_wall_s": round(f_warm, 2),
        "ws_e2e_stream_unfused_warm_wall_s": round(un_warm, 2),
        "ws_e2e_stream_parity": parity,
    }


_SKEWED_TASK_CLS = None


def _skewed_cost_task_cls():
    """Build (once) the skewed-cost fixture task for the scheduler A/B
    bench.  Defined lazily so importing bench_e2e_lib stays free of
    cluster_tools_tpu imports (the cpu-baseline subprocess imports this
    module before pinning its jax platform), but published as module
    attribute ``SkewedCostTask`` (via the PEP 562 ``__getattr__`` below)
    so the driver can pickle it to ``task.pkl`` and scheduler workers can
    unpickle it by reference."""
    global _SKEWED_TASK_CLS
    if _SKEWED_TASK_CLS is not None:
        return _SKEWED_TASK_CLS
    from cluster_tools_tpu.tasks.base import VolumeTask

    class SkewedCostTask(VolumeTask):
        """Every block writes a deterministic transform of its input
        (byte-comparable across scheduling modes); per-block cost is a
        calibrated stall — blocks whose z-origin falls in the hot z-slab
        cost ``hot_s`` seconds, the rest ``base_s`` (the ~8x hot-slab
        skew).  A sleep, not a compute loop, so the measured walls
        isolate SCHEDULING (assignment + queue mechanics) from kernel
        throughput and CPU contention between the worker processes."""

        task_name = "skewed_cost"
        output_dtype = "float32"

        def process_block(self, block_id, blocking, config):
            bb = blocking.block(block_id)
            x = self.input_ds()[bb.slicing]
            hot = bb.begin[0] < int(config.get("hot_z_end", 0))
            time.sleep(
                float(config["hot_s"]) if hot else float(config["base_s"])
            )
            self.output_ds()[bb.slicing] = (
                np.asarray(x, dtype="float32") * 2.0 + 1.0
            )

    SkewedCostTask.__module__ = __name__
    SkewedCostTask.__qualname__ = "SkewedCostTask"
    _SKEWED_TASK_CLS = SkewedCostTask
    return SkewedCostTask


def __getattr__(name):
    # PEP 562: lets pickle resolve bench_e2e_lib.SkewedCostTask in worker
    # processes without paying the cluster_tools_tpu import at module load
    if name == "SkewedCostTask":
        return _skewed_cost_task_cls()
    raise AttributeError(name)


def _write_async_stub_scheduler(folder, piddir):
    """sbatch/squeue stand-in that runs jobs in the BACKGROUND (unlike the
    test suite's synchronous stub): submission returns immediately and the
    queue command reports one line per still-running job pid — so n_jobs
    workers really execute concurrently, which is the whole point of a
    scheduler bench."""
    os.makedirs(folder, exist_ok=True)
    os.makedirs(piddir, exist_ok=True)
    submit = os.path.join(folder, "stub_submit")
    with open(submit, "w") as f:
        f.write(
            "#!/bin/bash\n"
            'script="${@: -1}"\n'
            'bash "$script" >/dev/null 2>&1 &\n'
            f'echo "$!" >> {piddir}/pids\n'
            'echo "Submitted batch job $!"\n'
        )
    queue = os.path.join(folder, "stub_queue")
    with open(queue, "w") as f:
        f.write(
            "#!/bin/bash\n"
            f'[ -f {piddir}/pids ] || exit 0\n'
            "while read -r p; do\n"
            '  kill -0 "$p" 2>/dev/null && echo RUNNING\n'
            f"done < {piddir}/pids\n"
            "exit 0\n"
        )
    import stat as _stat

    for p in (submit, queue):
        os.chmod(p, os.stat(p).st_mode | _stat.S_IEXEC)
    return submit, queue


def run_steal_pipeline(n_jobs=4, n_z_blocks=25, base_s=1.5, hot_s=12.0):
    """ctt-steal contract: static round-robin vs work-stealing wall clock
    on the async stub scheduler, over a skewed-cost fixture — a hot
    z-slab whose block costs ``hot_s / base_s`` (~8x) as much as the
    rest.  Geometry makes the skew bite the frozen split the way a hot
    volume region bites a real run: slab-blocks (one block per z-slab),
    so ``ids[0::n_jobs]`` pins the hot slab AND an equal share of cold
    slabs on job 0 while its siblings go idle — the stealing queue
    redistributes the cold tail and the wall collapses toward the hot
    block's own cost.  Both paths must be byte-identical
    (``ws_e2e_steal_parity``)."""
    from cluster_tools_tpu.runtime import build, config as cfg
    from cluster_tools_tpu.utils import file_reader

    task_cls = _skewed_cost_task_cls()
    rng = np.random.default_rng(0)
    bz, ny, nx = 2, 16, 16
    vol = rng.random((n_z_blocks * bz, ny, nx)).astype("float32")

    with tempfile.TemporaryDirectory() as td:
        walls = {}
        outputs = {}
        for tag, sched in (("static", "static"), ("steal", "steal")):
            submit, queue = _write_async_stub_scheduler(
                os.path.join(td, f"sched_{tag}"),
                os.path.join(td, f"pids_{tag}"),
            )
            path = os.path.join(td, f"{tag}.n5")
            file_reader(path).create_dataset(
                "x", data=vol, chunks=(bz, ny, nx)
            )
            config_dir = os.path.join(td, f"configs_{tag}")
            cfg.write_global_config(config_dir, {
                "block_shape": [bz, ny, nx],
                "target": "slurm",
                "max_jobs": n_jobs,
                "sched": sched,
                # one block per lease: the finest redistribution grain,
                # matching the one-block-per-slab fixture
                "steal_batch_size": 1,
                "steal_lease_s": 0.5,
                # A/B purity: the hot block is legitimately 8x, not a dead
                # straggler — duplication would re-run it on an idle
                # worker whose (harmless, losing) copy keeps its job alive
                # past the owner's finish and pads the measured wall
                "steal_duplicate": False,
                "poll_interval_s": 0.2,
                "sbatch_cmd": submit,
                "squeue_cmd": queue,
                "worker_env": {
                    "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
                },
            })
            cfg.write_config(config_dir, "skewed_cost", {
                "hot_z_end": bz,  # the first z-slab is the hot one
                "base_s": float(base_s),
                "hot_s": float(hot_s),
            })
            task = task_cls(
                os.path.join(td, f"tmp_{tag}"), config_dir,
                max_jobs=n_jobs,
                input_path=path, input_key="x",
                output_path=path, output_key="y",
            )
            t0 = time.perf_counter()
            ok = build([task])
            walls[tag] = time.perf_counter() - t0
            if not ok:
                raise RuntimeError(f"steal bench run failed ({tag})")
            outputs[tag] = path

        with file_reader(outputs["static"], "r") as fs, \
                file_reader(outputs["steal"], "r") as fw:
            parity = bool(np.array_equal(fs["y"][:], fw["y"][:]))

    return {
        "ws_e2e_steal_static_wall_s": round(walls["static"], 2),
        "ws_e2e_steal_wall_s": round(walls["steal"], 2),
        "ws_e2e_steal_speedup": round(
            walls["static"] / max(walls["steal"], 1e-9), 2
        ),
        "ws_e2e_steal_parity": parity,
    }


def run_serve_pipeline(n_jobs=6, shape=(8, 32, 32), block_shape=(8, 16, 16)):
    """ctt-serve contract: N back-to-back small watershed workflows,
    cold-process vs daemon-submitted — the amortization headline.

    The cold path is the pre-serve deployment: each workflow runs in a
    FRESH python process (interpreter + jax import + cache loads + build),
    sequentially — what a sweep of small user submissions used to cost.
    The serve path starts ONE ``python -m cluster_tools_tpu.serve`` daemon
    and submits the same N workflows back-to-back over its HTTP API; the
    daemon's warm ExecutionContext (in-process jit caches, devices, chunk
    LRU) makes every job after the first marginal-cost.

    Discipline: both paths share the persistent on-disk compile cache
    and each runs one UNTIMED warmup workflow first (the warm-vs-warm
    convention of this suite — the disk cache is equally hot for both, so
    the measured gap is process amortization, not disk-cache luck).  Each
    of the N jobs gets its OWN volume (z-rolled copies), identical
    between the paths, and every output must be byte-identical
    (``ws_e2e_serve_parity``: arrays + chunk-file digests).  Runs pinned
    to JAX_PLATFORMS=cpu like the steal bench: the quantity under test is
    scheduling/setup amortization, not kernel throughput."""
    import hashlib
    import signal
    import subprocess

    from cluster_tools_tpu.serve import ServeClient

    here = os.path.dirname(os.path.abspath(__file__))
    rng = np.random.default_rng(0)
    from scipy import ndimage

    base = ndimage.gaussian_filter(rng.random(shape), (1.0, 2.0, 2.0))
    base = ((base - base.min()) / (base.max() - base.min())).astype(
        "float32"
    )
    ws_conf = {"threshold": 0.5, "sigma_seeds": 1.6, "size_filter": 10,
               "halo": [2, 4, 4]}
    gconf = {"block_shape": list(block_shape), "target": "tpu"}
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": ""}
    for k in ("CTT_TRACE_DIR", "CTT_RUN_ID"):
        env.pop(k, None)

    def digest(root):
        h = hashlib.sha256()
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames.sort()
            for name in sorted(filenames):
                p = os.path.join(dirpath, name)
                h.update(os.path.relpath(p, root).encode())
                with open(p, "rb") as f:
                    h.update(f.read())
        return h.hexdigest()

    with tempfile.TemporaryDirectory() as td:
        from cluster_tools_tpu.utils import file_reader

        vols = {}
        for i in range(-1, n_jobs):  # -1 = the untimed warmup volume
            vols[i] = np.roll(base, 3 * (i + 1), axis=1)
            for side in ("cold", "serve"):
                file_reader(
                    os.path.join(td, f"{side}_{i}.n5")
                ).create_dataset(
                    "bnd", data=vols[i], chunks=tuple(block_shape)
                )

        driver = os.path.join(td, "cold_driver.py")
        with open(driver, "w") as f:
            f.write(
                "import os, sys\n"
                "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
                f"sys.path.insert(0, {here!r})\n"
                "import jax\n"
                "jax.config.update('jax_platforms', 'cpu')\n"
                "from cluster_tools_tpu.runtime import build, config as cfg\n"
                "from cluster_tools_tpu.workflows import WatershedWorkflow\n"
                "data_path, tag, td = sys.argv[1:4]\n"
                "config_dir = os.path.join(td, 'configs_' + tag)\n"
                f"cfg.write_global_config(config_dir, {gconf!r})\n"
                f"cfg.write_config(config_dir, 'watershed', {ws_conf!r})\n"
                "wf = WatershedWorkflow(\n"
                "    os.path.join(td, 'tmp_' + tag), config_dir,\n"
                "    input_path=data_path, input_key='bnd',\n"
                "    output_path=data_path, output_key='ws')\n"
                "assert build([wf])\n"
            )

        def one_cold(i, tag):
            proc = subprocess.run(
                [sys.executable, driver,
                 os.path.join(td, f"cold_{i}.n5"), tag, td],
                capture_output=True, text=True, env=env, timeout=600,
            )
            if proc.returncode != 0:
                raise RuntimeError(
                    f"cold run {tag} failed:\n{proc.stderr[-2000:]}"
                )

        one_cold(-1, "warmup")  # disk compile cache hot for BOTH paths
        t0 = time.perf_counter()
        for i in range(n_jobs):
            one_cold(i, f"c{i}")
        cold_wall = time.perf_counter() - t0

        state_dir = os.path.join(td, "serve_state")
        daemon = subprocess.Popen(
            [sys.executable, "-m", "cluster_tools_tpu.serve",
             "--state-dir", state_dir],
            env=env, cwd=here,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            deadline = time.perf_counter() + 120
            client = None
            while time.perf_counter() < deadline:
                if daemon.poll() is not None:
                    raise RuntimeError(
                        f"serve daemon died:\n{daemon.stderr.read()[-2000:]}"
                    )
                try:
                    client = ServeClient(state_dir=state_dir)
                    client.healthz()
                    break
                except Exception:
                    time.sleep(0.1)
            if client is None:
                raise RuntimeError("serve daemon never became healthy")

            def submit(i, tag):
                data_path = os.path.join(td, f"serve_{i}.n5")
                return client.submit(
                    "WatershedWorkflow",
                    {
                        "tmp_folder": os.path.join(td, f"tmp_s_{tag}"),
                        "config_dir": os.path.join(td, f"configs_s_{tag}"),
                        "input_path": data_path, "input_key": "bnd",
                        "output_path": data_path, "output_key": "ws",
                    },
                    configs={"global": dict(gconf),
                             "watershed": dict(ws_conf)},
                )

            client.wait(submit(-1, "warmup"), timeout_s=600)
            t0 = time.perf_counter()
            job_ids = [submit(i, f"s{i}") for i in range(n_jobs)]
            for jid in job_ids:
                client.wait(jid, timeout_s=600)
            serve_wall = time.perf_counter() - t0
        finally:
            daemon.send_signal(signal.SIGTERM)
            try:
                daemon.wait(timeout=60)
            except subprocess.TimeoutExpired:
                daemon.kill()
                daemon.wait(timeout=30)

        parity = True
        for i in range(n_jobs):
            cold_path = os.path.join(td, f"cold_{i}.n5")
            serve_path = os.path.join(td, f"serve_{i}.n5")
            with file_reader(cold_path, "r") as fc, \
                    file_reader(serve_path, "r") as fs:
                if not np.array_equal(fc["ws"][:], fs["ws"][:]):
                    parity = False
            if digest(os.path.join(cold_path, "ws")) != digest(
                os.path.join(serve_path, "ws")
            ):
                parity = False

    return {
        "ws_e2e_serve_jobs": int(n_jobs),
        "ws_e2e_serve_cold_wall_s": round(cold_wall, 2),
        "ws_e2e_serve_wall_s": round(serve_wall, 2),
        "ws_e2e_serve_speedup": round(cold_wall / max(serve_wall, 1e-9), 2),
        "ws_e2e_serve_parity": parity,
    }


def run_hbm_pipeline(shape=(48, 384, 384), block_shape=(8, 32, 32),
                     warm_reps=3):
    """ctt-hbm contract: back-to-back serve jobs on the SAME volume —
    warm HBM (device-buffer cache + aggregated dispatch + double-buffered
    upload stage) vs the PR 9/10 serve warm path, through one daemon each.

    Two daemons over the same input volume, each warm-vs-warm:

      * **hbm** — ``hbm_cache_mb`` default (512), ``hbm_stack: 8``,
        transfer stage on.  Job 1 is the cold-HBM measurement (uploads
        cross), job 2 the warm one: every batch is signature-validated
        HBM-resident, so uploads AND host input reads are skipped.
      * **base** — ``hbm_cache_mb: 0``, ``hbm_stack: 1``,
        ``hbm_prefetch: false``: the exact pre-hbm execution (the
        ctt-cloud LRU prefetch stays on — the honest PR 10 baseline).

    The fixture is a threshold sweep (compute-light, transfer/dispatch-
    bound — the workload shape the HBM levers target; a flood-heavy
    kernel measures the device kernel instead, see ws_e2e_warm_wall_s).
    Both daemons share the disk compile cache and run one untimed warmup
    job; the gated records are the per-job `/metrics` deltas of
    ``ctt_device_upload_bytes_total`` (warm ≈ 0 vs nonzero cold), the
    warm job's dispatch count (aggregation: << block count), the upload
    seconds hidden behind compute on the cold job, and the warm-vs-warm
    wall ratio.  Outputs of all four jobs must be byte-identical
    including chunk digests.  Pinned to JAX_PLATFORMS=cpu like the other
    scheduling benches: the quantity under test is transfer/dispatch
    economics, not kernel throughput."""
    import hashlib
    import signal
    import subprocess

    from cluster_tools_tpu.serve import ServeClient

    here = os.path.dirname(os.path.abspath(__file__))
    rng = np.random.default_rng(0)
    vol = rng.random(shape).astype("float32")
    n_blocks = 1
    for s, b in zip(shape, block_shape):
        n_blocks *= -(-s // b)
    thr_conf = {"threshold": 0.5}
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": ""}
    for k in ("CTT_TRACE_DIR", "CTT_RUN_ID"):
        env.pop(k, None)

    def digest(root):
        h = hashlib.sha256()
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames.sort()
            for name in sorted(filenames):
                p = os.path.join(dirpath, name)
                h.update(os.path.relpath(p, root).encode())
                with open(p, "rb") as f:
                    h.update(f.read())
        return h.hexdigest()

    def scrape(client):
        text = client.metrics_text()
        out = {}
        for line in text.splitlines():
            if line and not line.startswith("#") and " " in line:
                name, val = line.split(" ", 1)
                try:
                    out[name] = float(val)
                except ValueError:
                    pass
        return out

    with tempfile.TemporaryDirectory() as td:
        from cluster_tools_tpu.runtime import config as cfg_mod
        from cluster_tools_tpu.utils import file_reader

        data_path = os.path.join(td, "vol.n5")
        file_reader(data_path).create_dataset(
            "bnd", data=vol, chunks=tuple(block_shape)
        )
        # the warmup job gets its OWN volume: it exists to heat the disk
        # compile cache for both daemons — running it on the measured
        # volume would leave job 1 HBM-warm and erase the cold
        # upload-bytes record
        warm_path = os.path.join(td, "vol_warmup.n5")
        file_reader(warm_path).create_dataset(
            "bnd", data=np.roll(vol, 7, axis=1), chunks=tuple(block_shape)
        )
        stats = {}
        for side, gextra, sconf in (
            ("hbm", {"hbm_stack": 8}, {}),
            ("base", {"hbm_stack": 1, "hbm_prefetch": False},
             {"hbm_cache_mb": 0}),
        ):
            state_dir = os.path.join(td, f"state_{side}")
            if sconf:
                cfg_mod.write_config(state_dir, "serve", sconf)
            daemon = subprocess.Popen(
                [sys.executable, "-m", "cluster_tools_tpu.serve",
                 "--state-dir", state_dir],
                env=env, cwd=here,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            try:
                deadline = time.perf_counter() + 120
                client = None
                while time.perf_counter() < deadline:
                    if daemon.poll() is not None:
                        raise RuntimeError(
                            "hbm bench daemon died:\n"
                            f"{daemon.stderr.read()[-2000:]}"
                        )
                    try:
                        client = ServeClient(state_dir=state_dir)
                        client.healthz()
                        break
                    except Exception:
                        time.sleep(0.1)
                if client is None:
                    raise RuntimeError("hbm bench daemon never came up")

                def submit(tag):
                    out_path = os.path.join(td, f"out_{side}.n5")
                    src = warm_path if tag == "warmup" else data_path
                    return client.submit_and_wait(
                        "cluster_tools_tpu.tasks.threshold:ThresholdTask",
                        {
                            "tmp_folder": os.path.join(
                                td, f"tmp_{side}_{tag}"),
                            "config_dir": os.path.join(
                                td, f"configs_{side}_{tag}"),
                            "input_path": src, "input_key": "bnd",
                            "output_path": out_path,
                            "output_key": f"thr_{tag}",
                        },
                        configs={
                            "global": {
                                "block_shape": list(block_shape),
                                "target": "tpu", "pipeline_depth": 3,
                                **gextra,
                            },
                            "threshold": dict(thr_conf),
                        },
                        timeout_s=600,
                    )

                submit("warmup")  # untimed: disk compile cache hot
                m0 = scrape(client)
                s1 = submit("j1")
                m1 = scrape(client)
                # several warm reps, median wall: the jobs are seconds-
                # scale, so one burst of host load must not decide the A/B
                warm_walls = []
                for rep in range(max(int(warm_reps), 1)):
                    s2 = submit(f"j2r{rep}")
                    warm_walls.append(float(s2["result"]["seconds"]))
                m2 = scrape(client)
                stats[side] = {
                    "cold_s": float(s1["result"]["seconds"]),
                    "warm_s": float(np.median(warm_walls)),
                    "m0": m0, "m1": m1, "m2": m2,
                }
            finally:
                daemon.send_signal(signal.SIGTERM)
                try:
                    daemon.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    daemon.kill()
                    daemon.wait(timeout=30)

        parity = True
        fa = file_reader(os.path.join(td, "out_hbm.n5"), "r")
        fb = file_reader(os.path.join(td, "out_base.n5"), "r")
        tags = ["j1"] + [f"j2r{r}" for r in range(max(int(warm_reps), 1))]
        for tag in tags:
            if not np.array_equal(fa[f"thr_{tag}"][:], fb[f"thr_{tag}"][:]):
                parity = False
            if digest(os.path.join(td, "out_hbm.n5", f"thr_{tag}")) != \
                    digest(os.path.join(td, "out_base.n5", f"thr_{tag}")):
                parity = False

        def delta(side, a, b, name):
            return stats[side][b].get(name, 0.0) - stats[side][a].get(
                name, 0.0
            )

        up = "ctt_device_upload_bytes_total"
        cold_upload = delta("hbm", "m0", "m1", up)
        # warm window spans warm_reps jobs: bytes stay 0 in total, the
        # dispatch record normalizes to one job
        warm_upload = delta("hbm", "m1", "m2", up)
        warm_dispatches = delta(
            "hbm", "m1", "m2", "ctt_device_dispatches_total"
        ) / max(int(warm_reps), 1)
        # seconds of host→HBM transfer the double-buffered stage ran on
        # the transfer thread — i.e. moved OFF the in-order dispatch
        # thread's critical path — during the cold (upload-heavy) job
        overlap = delta("hbm", "m0", "m1",
                        "ctt_executor_stage_upload_s_total")

    return {
        "ws_e2e_hbm_blocks": int(n_blocks),
        "ws_e2e_hbm_upload_bytes_cold": int(cold_upload),
        "ws_e2e_hbm_upload_bytes_warm": int(warm_upload),
        "ws_e2e_hbm_dispatches": int(warm_dispatches),
        "ws_e2e_hbm_overlap_s": round(overlap, 3),
        "ws_e2e_hbm_warm_wall_s": round(stats["hbm"]["warm_s"], 3),
        "ws_e2e_hbm_base_warm_wall_s": round(stats["base"]["warm_s"], 3),
        "ws_e2e_hbm_warm_speedup": round(
            stats["base"]["warm_s"] / max(stats["hbm"]["warm_s"], 1e-9), 2
        ),
        "ws_e2e_hbm_parity": parity,
    }


def run_hier_pipeline(shape=(48, 384, 384), block_shape=(8, 64, 64),
                      n_thresholds=3):
    """ctt-hier contract: build the merge hierarchy ONCE through a serve
    daemon, then sweep merge thresholds as warm ``resegment`` jobs against
    the same daemon — vs a FULL pipeline re-run per threshold.

    The sweep step is the interactive mode (``write_volume: false``): the
    job loads the (daemon-warm) artifact, thresholds the sorted saddle
    column, runs ONE value-space union-find pass and persists the relabel
    table — what a proofreading slider applies to its current view.  The
    comparator is what the reference stack does for every slider move: a
    complete re-run (hierarchy build + volume re-cut) at the same
    threshold, itself WARM (same daemon, hot jit caches — charitable to
    the baseline).  One volume-mode warm re-cut is also measured (the
    "commit this threshold" job; its reads ride the warm ctt-hbm
    DeviceBufferCache — the gated record asserts zero upload bytes across
    the whole warm window).

    Parity: at every swept threshold the persisted table applied to the
    labels volume must equal the full re-run's re-cut volume as a label
    PARTITION (RI == 1.0).  Pinned to JAX_PLATFORMS=cpu like the other
    scheduling benches — the quantity under test is amortization
    structure, not kernel throughput."""
    import signal
    import subprocess

    from cluster_tools_tpu.serve import ServeClient

    here = os.path.dirname(os.path.abspath(__file__))
    rng = np.random.default_rng(0)
    from scipy import ndimage

    raw = ndimage.gaussian_filter(rng.random(shape), (1.0, 2.0, 2.0))
    raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")
    gconf = {"block_shape": list(block_shape), "target": "tpu",
             "pipeline_depth": 3}
    blocks_conf = {"threshold": 0.5, "sigma_seeds": 1.6, "size_filter": 10}
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": ""}
    for k in ("CTT_TRACE_DIR", "CTT_RUN_ID"):
        env.pop(k, None)

    def scrape(client):
        out = {}
        for line in client.metrics_text().splitlines():
            if line and not line.startswith("#") and " " in line:
                name, val = line.rsplit(" ", 1)
                try:
                    out[name] = float(val)
                except ValueError:
                    pass
        return out

    def partition_ri(a, b):
        from cluster_tools_tpu.ops.evaluation import rand_scores
        from cluster_tools_tpu.ops.segment import contingency_table

        ia, ib, counts = contingency_table(
            np.asarray(a, np.uint64), np.asarray(b, np.uint64)
        )
        return rand_scores(ia, ib, counts)["rand_index"]

    with tempfile.TemporaryDirectory() as td:
        from cluster_tools_tpu.ops import hier as hier_ops
        from cluster_tools_tpu.utils import file_reader

        data_path = os.path.join(td, "vol.n5")
        file_reader(data_path).create_dataset(
            "bnd", data=raw, chunks=tuple(block_shape)
        )
        state_dir = os.path.join(td, "state")
        daemon = subprocess.Popen(
            [sys.executable, "-m", "cluster_tools_tpu.serve",
             "--state-dir", state_dir],
            env=env, cwd=here,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            deadline = time.perf_counter() + 120
            client = None
            while time.perf_counter() < deadline:
                if daemon.poll() is not None:
                    raise RuntimeError(
                        "hier bench daemon died:\n"
                        f"{daemon.stderr.read()[-2000:]}"
                    )
                try:
                    client = ServeClient(state_dir=state_dir)
                    client.healthz()
                    break
                except Exception:
                    time.sleep(0.1)
            if client is None:
                raise RuntimeError("hier bench daemon never came up")

            def build_job(tag, out_key):
                return client.submit_and_wait(
                    "HierarchyWorkflow",
                    {
                        "tmp_folder": os.path.join(td, f"tmp_{tag}"),
                        "config_dir": os.path.join(td, f"configs_{tag}"),
                        "input_path": data_path, "input_key": "bnd",
                        "output_path": data_path, "output_key": out_key,
                    },
                    configs={"global": dict(gconf),
                             "hierarchy_blocks": dict(blocks_conf)},
                    timeout_s=1200,
                )

            def reseg_job(tag, labels_key, out_key, t, write_volume):
                job = client.resegment(
                    hierarchy=os.path.join(
                        data_path, f"{labels_key}_hierarchy.npz"
                    ),
                    labels_path=data_path, labels_key=labels_key,
                    output_path=data_path, output_key=out_key,
                    threshold=t, write_volume=write_volume,
                    tmp_folder=os.path.join(td, f"tmp_{tag}"),
                    config_dir=os.path.join(td, f"configs_{tag}"),
                    configs={"global": dict(gconf)},
                )
                return client.wait(job, timeout_s=1200)

            # the one-time hierarchy build (cold: first flood + compiles)
            s_build = build_job("build", "seg")
            build_wall = float(s_build["result"]["seconds"])
            art = hier_ops.load_hierarchy(
                os.path.join(data_path, "seg_hierarchy.npz")
            )
            qs = np.linspace(0.25, 0.75, max(int(n_thresholds), 1))
            ts = [float(t) for t in np.quantile(art["saddle"], qs)]

            # untimed warmups: one volume re-cut (warms the HBM cache +
            # gather compiles) and one table cut (warms the union-find
            # shape buckets) — the sweep measures steady state
            reseg_job("warm_vol", "seg", "seg_wv", ts[0], True)
            reseg_job("warm_tab", "seg", "seg_wt", ts[len(ts) // 2],
                      False)

            m1 = scrape(client)
            sweep_walls = []
            for i, t in enumerate(ts):
                st = reseg_job(f"sweep{i}", "seg", f"cut{i}", t, False)
                sweep_walls.append(float(st["result"]["seconds"]))
            s_vol = reseg_job(
                "commit", "seg", "seg_commit", ts[len(ts) // 2], True
            )
            m2 = scrape(client)
            warm_upload = m2.get(
                "ctt_device_upload_bytes_total", 0.0
            ) - m1.get("ctt_device_upload_bytes_total", 0.0)

            # the baseline: a FULL pipeline re-run per threshold (fresh
            # tmp folders, same daemon = warm compiles for it too)
            full_walls = []
            for i, t in enumerate(ts):
                sb = build_job(f"full{i}", f"seg_f{i}")
                sr = reseg_job(
                    f"fullcut{i}", f"seg_f{i}", f"seg_f{i}_t", t, True
                )
                full_walls.append(
                    float(sb["result"]["seconds"])
                    + float(sr["result"]["seconds"])
                )

            # parity: the sweep's relabel table applied to the labels
            # volume == the full re-run's re-cut volume, as a partition
            f = file_reader(data_path, "r")
            seg = f["seg"][:]
            parity = True
            for i, t in enumerate(ts):
                cut = hier_ops.load_cut_table(
                    os.path.join(data_path, f"cut{i}_cut.npz")
                )
                swept = hier_ops.apply_cut_np(
                    seg, cut["vals"], cut["roots"]
                )
                full = f[f"seg_f{i}_t"][:]
                if partition_ri(swept, full) != 1.0:
                    parity = False
        finally:
            daemon.send_signal(signal.SIGTERM)
            try:
                daemon.wait(timeout=60)
            except subprocess.TimeoutExpired:
                daemon.kill()
                daemon.wait(timeout=30)

    return {
        "ws_e2e_hier_blocks": int(np.prod([
            -(-s // b) for s, b in zip(shape, block_shape)
        ])),
        "ws_e2e_hier_edges": int(art["a"].size),
        "ws_e2e_hier_build_wall_s": round(build_wall, 2),
        "ws_e2e_hier_sweep_ms_warm": round(
            float(np.median(sweep_walls)) * 1e3, 1
        ),
        "ws_e2e_hier_recut_volume_s": round(
            float(s_vol["result"]["seconds"]), 3
        ),
        "ws_e2e_hier_full_rerun_s": round(float(np.mean(full_walls)), 2),
        "ws_e2e_hier_sweep_speedup": round(
            float(np.mean(full_walls))
            / max(float(np.median(sweep_walls)), 1e-9), 1
        ),
        "ws_e2e_hier_upload_bytes_warm": int(warm_upload),
        "ws_e2e_hier_parity": parity,
    }


def run_events_pipeline(n_frames=64, frame_shape=(512, 512),
                        soak_submissions=1000):
    """ctt-events contract, both legs of the acceptance gate.

    Throughput: ONE batched ``build_events`` dispatch over an
    ``(n_frames, h, w)`` detector stack vs the per-frame host baseline
    (``scipy.ndimage.label`` + numpy property reduction — exactly what a
    pre-batching event builder runs per frame).  Gate: >= 10x frames/s
    with EXACT label/count parity and close props.

    Soak: an in-process serve daemon at a deliberately tiny admission
    envelope (tenant_quota 2, queue depth 4) takes a burst of
    ``soak_submissions`` ``event_batch`` submissions — the "millions of
    users" request shape scaled to CI.  Past-capacity submissions must
    be CLEAN 429s, every accepted job must finish ok, /metrics must stay
    parseable mid-burst, and the process must return to its pre-burst
    thread/fd baseline with zero lease-renewer threads left — the
    serve-path per-request allocation audit, benched."""
    import threading

    from scipy import ndimage

    from cluster_tools_tpu.ops import events as events_ops

    rng = np.random.default_rng(0)
    raw = ndimage.gaussian_filter(
        rng.random((n_frames,) + tuple(frame_shape)), (0.0, 1.0, 1.0)
    ).astype("float32")
    # ~1% occupancy of compact blobs — the Timepix-like regime the
    # throughput gate is specified against
    frames = np.where(raw > np.quantile(raw, 0.99), raw, 0.0).astype(
        "float32"
    )
    hits = rng.random(frames.shape) > 0.999
    frames[hits] = (rng.random(int(hits.sum())) + 1.0).astype("float32")

    # -- throughput leg ----------------------------------------------------
    compiles0 = events_ops.kernel_cache_size()
    labels, counts, props = events_ops.build_events(frames)  # warm/compile
    compiles = events_ops.kernel_cache_size() - compiles0
    dev_walls = []
    for _ in range(3):
        t0 = time.perf_counter()
        labels, counts, props = events_ops.build_events(frames)
        dev_walls.append(time.perf_counter() - t0)
    dev_wall = float(np.median(dev_walls))

    t0 = time.perf_counter()
    ref_l, ref_c, ref_p = events_ops.build_events_np(frames)
    scipy_wall = time.perf_counter() - t0

    parity = bool(
        np.array_equal(counts, ref_c) and np.array_equal(labels, ref_l)
    )
    if parity:
        for f in range(n_frames):
            k = int(counts[f])
            if not np.allclose(props[f, :k], ref_p[f, :k],
                               rtol=1e-4, atol=1e-4):
                parity = False
                break

    res = {
        "ws_e2e_events_frames": int(n_frames),
        "ws_e2e_events_frame_shape": list(frame_shape),
        "ws_e2e_events_clusters": int(counts.sum()),
        "ws_e2e_events_compiles": int(compiles),
        "ws_e2e_events_frames_per_s": round(n_frames / dev_wall, 1),
        "ws_e2e_events_scipy_frames_per_s": round(
            n_frames / scipy_wall, 1
        ),
        "ws_e2e_events_speedup": round(scipy_wall / dev_wall, 1),
        "ws_e2e_events_parity": parity,
    }

    # -- serve soak leg ----------------------------------------------------
    from cluster_tools_tpu.serve import (
        QuotaRejected, ServeClient, ServeDaemon,
    )
    from cluster_tools_tpu.utils import file_reader

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "soak.n5")
        file_reader(path).create_dataset(
            "frames", data=frames[:4, :16, :16].copy(),
            chunks=(2, 16, 16),
        )
        gconf = {"block_shape": [2, 16, 16], "target": "tpu",
                 "device_batch_size": 2, "devices": [0],
                 "pipeline_depth": 2}
        daemon = ServeDaemon(
            os.path.join(td, "state"),
            config={"tenant_quota": 2, "max_queue_depth": 4},
        )
        daemon.start()
        try:
            client = ServeClient(state_dir=os.path.join(td, "state"))

            def submit(i):
                return client.event_batch(
                    input_path=path, input_key="frames",
                    output_path=path, output_key=f"ev_{i}",
                    tmp_folder=os.path.join(td, f"tmp_{i}"),
                    config_dir=os.path.join(td, f"configs_{i}"),
                    configs={"global": dict(gconf)},
                )

            # warm-up job: compiles + pool threads + store handles, so
            # the baseline below is steady state, not cold start
            client.wait(submit(0), timeout_s=600)

            def renewers():
                return [t for t in threading.enumerate()
                        if t.name == "ctt-serve-lease" and t.is_alive()]

            deadline = time.monotonic() + 10
            while renewers() and time.monotonic() < deadline:
                time.sleep(0.05)
            threads_before = threading.active_count()
            fds_before = len(os.listdir("/proc/self/fd"))

            accepted, rejected, metrics_ok = [], 0, True
            t0 = time.perf_counter()
            for i in range(1, soak_submissions + 1):
                try:
                    accepted.append(submit(i))
                except QuotaRejected:
                    rejected += 1
                if i % 200 == 0:  # /metrics must answer mid-burst
                    try:
                        if "# EOF" not in client.metrics_text():
                            metrics_ok = False
                    except Exception:
                        metrics_ok = False
            for jid in accepted:
                state = client.wait(jid, timeout_s=600)
                if not state["result"]["ok"]:
                    metrics_ok = False
            soak_wall = time.perf_counter() - t0

            leases_clean = True
            deadline = time.monotonic() + 15
            while renewers() and time.monotonic() < deadline:
                time.sleep(0.05)
            if renewers():
                leases_clean = False
            thread_parity = fd_parity = False
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                thread_parity = (
                    threading.active_count() <= threads_before
                )
                fd_parity = (
                    len(os.listdir("/proc/self/fd")) <= fds_before
                )
                if thread_parity and fd_parity:
                    break
                time.sleep(0.1)
            if "# EOF" not in client.metrics_text():
                metrics_ok = False
        finally:
            daemon.request_drain()
            if daemon._httpd is not None:
                daemon._httpd.shutdown()
                daemon._httpd.server_close()
            for t in daemon._threads:
                if t.name.startswith("ctt-serve-exec"):
                    t.join(timeout=60)

    res.update({
        "ws_e2e_events_soak_submissions": int(soak_submissions),
        "ws_e2e_events_soak_accepted": len(accepted) + 1,  # + warm-up
        "ws_e2e_events_soak_rejections": int(rejected),
        "ws_e2e_events_soak_wall_s": round(soak_wall, 2),
        "ws_e2e_events_soak_thread_parity": bool(thread_parity),
        "ws_e2e_events_soak_fd_parity": bool(fd_parity),
        "ws_e2e_events_soak_leases_clean": bool(leases_clean),
        "ws_e2e_events_soak_metrics_ok": bool(metrics_ok),
    })
    return res


def run_microbatch_pipeline(n_jobs=1000, n_tenants=4, window_s=0.25,
                            max_jobs=32, frame_n=2, frame_hw=16):
    """ctt-microbatch contract: a mixed-tenant burst of ``n_jobs`` small
    ``event_batch`` jobs through ONE daemon, aggregation window on vs
    window 0 (exact per-job dispatch).

    Both legs pre-fill the durable queue, then start the daemon and
    measure wall-to-last-result — so the comparison is pure executor
    economics (per-job claim scans + builds + dispatches vs amortized
    multi-claims and stacked dispatches), not HTTP submission overhead.
    Gates: ``ws_e2e_microbatch_speedup`` >= 3; outputs byte-identical
    per job (labels + event-table chunk digests); per-tenant ok counts
    sum exactly to the window-0 control; p99 admission-to-result of the
    aggregated leg bounded by the control's p99 + the window (the window
    may delay a job, never by more than itself); zero splits (no member
    failed out of a batch).  ctt-slo (BENCH_r14): the aggregated leg
    also reports ``ws_e2e_mb_e2e_p50_s``/``ws_e2e_mb_e2e_p99_s`` from
    the daemon's own ``serve.latency.e2e`` histograms, cross-checked
    against the client stopwatch within the log2 bucket resolution."""
    import hashlib

    from cluster_tools_tpu.obs import hist as obs_hist
    from cluster_tools_tpu.obs import metrics as obs_metrics
    from cluster_tools_tpu.serve import JobQueue, ServeDaemon
    from cluster_tools_tpu.serve import protocol as serve_protocol
    from cluster_tools_tpu.utils import file_reader

    gconf = {"block_shape": [2, frame_hw, frame_hw], "target": "tpu",
             "device_batch_size": 2, "devices": [0], "pipeline_depth": 2}
    rng = np.random.default_rng(0)
    frames = rng.random((frame_n, frame_hw, frame_hw)).astype("float32")
    frames[frames < 0.9] = 0.0

    def _drain(daemon):
        daemon.request_drain()
        if daemon._httpd is not None:
            daemon._httpd.shutdown()
            daemon._httpd.server_close()
        for t in daemon._threads:
            if t.name.startswith("ctt-serve-exec"):
                t.join(timeout=120)

    def _digest(root):
        h = hashlib.sha256()
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames.sort()
            for name in sorted(filenames):
                p = os.path.join(dirpath, name)
                h.update(os.path.relpath(p, root).encode())
                with open(p, "rb") as f:
                    h.update(f.read())
        return h.hexdigest()

    def _e2e_buckets(snap):
        # ctt-slo: sum the serve.latency.e2e buckets across tenant/
        # priority labels — fixed edges make the aggregation exact
        acc = [0] * (len(obs_hist.EDGES) + 1)
        for s in snap.get("hists") or []:
            if s.get("name") == "serve.latency.e2e":
                for i, c in enumerate(s["buckets"]):
                    acc[i] += int(c)
        return acc

    def _leg(td, path, tag, window):
        state = os.path.join(td, f"state_{tag}")
        q = JobQueue(os.path.join(state, "jobs"))
        job_ids = []
        for i in range(n_jobs):
            rec = serve_protocol.validate_submission({
                "type": "event_batch",
                "input_path": path, "input_key": "frames",
                "output_path": path, "output_key": f"ev_{tag}_{i}",
                "tmp_folder": os.path.join(td, f"tmp_{tag}_{i}"),
                "config_dir": os.path.join(td, f"configs_{tag}_{i}"),
                "threshold": 0.5,
                "configs": {"global": dict(gconf)},
                "tenant": f"t{i % n_tenants}",
            })
            job_ids.append(q.submit(rec))
        before = dict(obs_metrics.snapshot()["counters"])
        # ctt-slo: the daemon runs in-process, so its latency histograms
        # accumulate in THIS process — a before/after bucket delta
        # isolates the leg (reset() would clobber the run's flush file)
        hist_before = _e2e_buckets(obs_hist.snapshot())
        t0 = time.perf_counter()
        daemon = ServeDaemon(state, config={
            "microbatch_window_s": float(window),
            "microbatch_max_jobs": int(max_jobs),
            "max_queue_depth": None, "tenant_quota": None,
        })
        daemon.start()
        try:
            results_dir = os.path.join(state, "jobs")
            deadline = time.monotonic() + 1800
            while time.monotonic() < deadline:
                n_done = sum(
                    1 for n in os.listdir(results_dir)
                    if n.startswith("result.")
                )
                if n_done >= n_jobs:
                    break
                time.sleep(0.05)
            wall = time.perf_counter() - t0
        finally:
            _drain(daemon)
        obs_metrics.flush()
        after = dict(obs_metrics.snapshot()["counters"])
        hist_after = _e2e_buckets(obs_hist.snapshot())
        e2e_buckets = [b - a for a, b in zip(hist_before, hist_after)]
        per_tenant, latencies, all_ok = {}, [], True
        for jid in job_ids:
            st = q.get(jid)
            res = st["result"] or {}
            if not res.get("ok"):
                all_ok = False
                continue
            tenant = res.get("tenant") or "?"
            per_tenant[tenant] = per_tenant.get(tenant, 0) + 1
            latencies.append(
                res["finished_wall"] - st["record"]["submit_wall"]
            )

        def delta(name):
            return after.get(name, 0.0) - before.get(name, 0.0)

        return {
            "wall": wall, "ok": all_ok, "per_tenant": per_tenant,
            "p50": float(np.percentile(latencies, 50)),
            "p99": float(np.percentile(latencies, 99)),
            "e2e_buckets": e2e_buckets,
            "jobs_done": delta("serve.jobs_done"),
            "batches": delta("serve.microbatch_batches"),
            "jobs_batched": delta("serve.microbatch_jobs_batched"),
            "splits": delta("serve.microbatch_splits"),
        }

    import shutil

    # manual mkdtemp: an in-process daemon's heartbeat thread may still
    # stamp beat files while a TemporaryDirectory teardown walks the tree
    td = tempfile.mkdtemp()
    try:
        path = os.path.join(td, "burst.n5")
        file_reader(path).create_dataset(
            "frames", data=frames, chunks=(2, frame_hw, frame_hw)
        )
        # warm-up: pay the event-kernel compiles before EITHER timed leg
        # (leg order must not hand one side the warm cache for free).
        # Each leg dispatches its own frame-stack shapes — the solo leg
        # one job at a time, the aggregated leg full and tail job stacks
        # — and the pow2-padded kernels compile once per shape, an
        # O(log stream) one-time cost by design (ctt-events); warming
        # every shape with the leg's real frame content keeps the A/B a
        # throughput measurement, not a compile-count one.
        from cluster_tools_tpu.ops import events as events_ops

        tail = n_jobs % max_jobs
        for stack in {1, max_jobs, tail} - {0}:
            events_ops.build_events(
                np.tile(frames, (stack, 1, 1)), threshold=0.5
            )
        solo = _leg(td, path, "solo", 0.0)
        mb = _leg(td, path, "mb", window_s)

        # byte-identity per job vs the window-0 control: labels AND the
        # ragged event tables, chunk-for-chunk
        parity = solo["ok"] and mb["ok"]
        if parity:
            for i in range(n_jobs):
                if _digest(
                    os.path.join(path, f"ev_mb_{i}")
                ) != _digest(
                    os.path.join(path, f"ev_solo_{i}")
                ) or _digest(
                    os.path.join(path, f"ev_mb_{i}_events")
                ) != _digest(
                    os.path.join(path, f"ev_solo_{i}_events")
                ):
                    parity = False
                    break
    finally:
        shutil.rmtree(td, ignore_errors=True)

    jobs_per_dispatch = (
        mb["jobs_batched"] / mb["batches"] if mb["batches"] else 0.0
    )

    # ctt-slo (BENCH_r14): the aggregated leg's e2e percentiles as the
    # DAEMON's serve.latency.e2e histograms saw them, cross-checked
    # against the client stopwatch — both span submit->publish, so they
    # must agree within the log2 bucket resolution (adjacent-edge
    # ratio == 2)
    mb_hist_p50 = obs_hist.quantile(mb["e2e_buckets"], 0.50)
    mb_hist_p99 = obs_hist.quantile(mb["e2e_buckets"], 0.99)

    def _hist_close(h, c):
        return (h is not None and h > 0.0 and c > 0.0
                and max(h, c) / min(h, c) <= 2.0000001)

    return {
        "ws_e2e_microbatch_jobs": int(n_jobs),
        "ws_e2e_microbatch_tenants": int(n_tenants),
        "ws_e2e_microbatch_window_s": float(window_s),
        "ws_e2e_microbatch_max_jobs": int(max_jobs),
        "ws_e2e_microbatch_solo_wall_s": round(solo["wall"], 2),
        "ws_e2e_microbatch_wall_s": round(mb["wall"], 2),
        "ws_e2e_microbatch_speedup": round(solo["wall"] / mb["wall"], 2),
        "ws_e2e_microbatch_batches": int(mb["batches"]),
        "ws_e2e_microbatch_jobs_batched": int(mb["jobs_batched"]),
        "ws_e2e_microbatch_jobs_per_dispatch": round(jobs_per_dispatch, 1),
        "ws_e2e_microbatch_splits": int(mb["splits"]),
        "ws_e2e_microbatch_solo_p99_s": round(solo["p99"], 3),
        "ws_e2e_microbatch_p99_s": round(mb["p99"], 3),
        "ws_e2e_microbatch_p99_bounded": bool(
            mb["p99"] <= solo["p99"] + window_s
        ),
        "ws_e2e_mb_e2e_p50_s": round(mb_hist_p50 or 0.0, 4),
        "ws_e2e_mb_e2e_p99_s": round(mb_hist_p99 or 0.0, 4),
        "ws_e2e_mb_e2e_samples": int(sum(mb["e2e_buckets"])),
        "ws_e2e_mb_e2e_hist_consistent": bool(
            _hist_close(mb_hist_p50, mb["p50"])
            and _hist_close(mb_hist_p99, mb["p99"])
        ),
        "ws_e2e_microbatch_tenant_sums_match": bool(
            solo["per_tenant"] == mb["per_tenant"]
            and sum(solo["per_tenant"].values()) == n_jobs
        ),
        "ws_e2e_microbatch_parity": bool(parity),
    }


def run_remote_pipeline(vol_path, shape, block_shape, target):
    """ctt-cloud contract: the WatershedWorkflow run against the local
    stub object server (tests/objstub.py, spawned as a SUBPROCESS so its
    request handling never shares the GIL with compute) vs the POSIX
    store — cold + warm remote walls, the host-IO seconds the pipeline
    hid on the warm remote run, and byte parity (arrays AND chunk-file
    digests; gzip chunks are deterministic, so a remote run must produce
    the exact same files).

    Discipline matches run_ws_pipeline: cold on ``bnd``, warm on the
    DISTINCT z-rolled ``bnd_warm`` copy in fresh scratch — jit caches
    reused, no result-cache replay.  The fault-free timing run is the
    honest latency model (chaos byte-identity rides the test suite and
    the ci_check cloud smoke); the gate is the warm remote wall within
    1.5x of the warm POSIX wall with parity true."""
    import subprocess

    from cluster_tools_tpu.obs import metrics as obs_metrics
    from cluster_tools_tpu.obs import trace as obs_trace
    from cluster_tools_tpu.runtime import build, config as cfg
    from cluster_tools_tpu.utils import file_reader
    from cluster_tools_tpu.workflows import WatershedWorkflow

    here = os.path.dirname(os.path.abspath(__file__))

    def digest(root):
        import hashlib

        h = hashlib.sha256()
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames.sort()
            for name in sorted(filenames):
                p = os.path.join(dirpath, name)
                h.update(os.path.relpath(p, root).encode())
                with open(p, "rb") as f:
                    h.update(f.read())
        return h.hexdigest()

    with tempfile.TemporaryDirectory() as td:
        data_path = _stage_volume(td, vol_path, shape, block_shape, True)
        objroot = os.path.join(td, "objroot")
        served = os.path.join(objroot, "data.n5")
        vol = np.load(vol_path).astype(np.float32)
        f = file_reader(served)
        f.create_dataset("bnd", data=vol, chunks=tuple(block_shape))
        f.create_dataset(
            "bnd_warm", data=np.roll(vol, 7, axis=1),
            chunks=tuple(block_shape),
        )

        port_file = os.path.join(td, "stub.port")
        stub = subprocess.Popen([
            sys.executable, os.path.join(here, "tests", "objstub.py"),
            "--root", objroot, "--port-file", port_file,
        ])
        trace_was_on = obs_trace.enabled()
        if not trace_was_on:
            obs_trace.enable(
                os.path.join(td, "trace"), "remote_bench", export_env=False
            )
        try:
            deadline = time.perf_counter() + 30
            while not os.path.exists(port_file):
                if stub.poll() is not None:
                    raise RuntimeError("objstub died on startup")
                if time.perf_counter() > deadline:
                    raise RuntimeError("objstub never came up")
                time.sleep(0.05)
            url = f"http://127.0.0.1:{open(port_file).read().strip()}"

            def one_run(tag, path, input_key, out_key):
                config_dir = os.path.join(td, f"configs_{tag}")
                cfg.write_global_config(
                    config_dir,
                    {"block_shape": list(block_shape), "target": target,
                     "pipeline_depth": 3},
                )
                cfg.write_config(
                    config_dir, "watershed", dict(WS_TASK_CONFIG)
                )
                wf = WatershedWorkflow(
                    os.path.join(td, f"tmp_{tag}"), config_dir,
                    input_path=path, input_key=input_key,
                    output_path=path, output_key=out_key,
                )
                before = obs_metrics.snapshot()["counters"]
                t0 = time.perf_counter()
                ok = build([wf])
                wall = time.perf_counter() - t0
                after = obs_metrics.snapshot()["counters"]
                if not ok:
                    raise RuntimeError(f"remote bench run failed ({tag})")
                hidden = after.get("executor.stage_hidden_io_s", 0.0) \
                    - before.get("executor.stage_hidden_io_s", 0.0)
                return wall, hidden

            local_cold, _ = one_run("l_cold", data_path, "bnd", "ws_cold")
            local_warm, _ = one_run("l_warm", data_path, "bnd_warm", "ws")
            remote_cold, _ = one_run(
                "r_cold", f"{url}/data.n5", "bnd", "ws_cold"
            )
            remote_warm, hidden = one_run(
                "r_warm", f"{url}/data.n5", "bnd_warm", "ws"
            )

            with file_reader(data_path, "r") as fl, \
                    file_reader(served, "r") as fr:
                parity = bool(np.array_equal(fl["ws"][:], fr["ws"][:]))
            if digest(os.path.join(data_path, "ws")) != digest(
                os.path.join(served, "ws")
            ):
                parity = False
        finally:
            if not trace_was_on:
                obs_trace.disable()
            stub.terminate()
            stub.wait(timeout=30)

    return {
        "ws_e2e_remote_cold_wall_s": round(remote_cold, 2),
        "ws_e2e_remote_warm_wall_s": round(remote_warm, 2),
        "ws_e2e_remote_posix_warm_wall_s": round(local_warm, 2),
        "ws_e2e_remote_vs_posix_warm": round(
            remote_warm / max(local_warm, 1e-9), 2
        ),
        "ws_e2e_remote_read_hidden_s": round(hidden, 3),
        "ws_e2e_remote_parity": parity,
    }


def run_ws_pipeline(vol_path, shape, block_shape, target, warm=False,
                    sharded=False):
    """Wall-clock of the WatershedWorkflow alone — the BASELINE.md north
    star is "≥10x wall-clock vs target='local' on CREMI sample-A
    DT-watershed", i.e. THIS workload (block reads → fused DT-WS program →
    label writes), not the full multicut pipeline whose host-bound merge
    and solve stages dilute the device speedup.  Same cold/warm and
    distinct-volume discipline as ``run_pipeline``.

    ``sharded=True`` runs the collective whole-volume watershed
    (WatershedWorkflow(sharded=True): one upload, one program over the
    mesh, one label write) instead of the block pipeline.  Since round 5
    SHARDED_WS_CONFIG selects the per-slice (2d) collective kernel — the
    SAME algorithm the block pipeline and the cpu-local baseline run
    (apples-to-apples), zero cross-shard collectives; rounds before that
    measured the 3d collective.

    With ``warm=True`` returns ``(cold_wall, warm_wall, stages)`` where
    ``stages`` carries the warm run's three-stage pipeline breakdown
    (``stage_breakdown``; empty when no staged dispatch ran)."""
    from cluster_tools_tpu.runtime import build, config as cfg
    from cluster_tools_tpu.workflows import WatershedWorkflow

    with tempfile.TemporaryDirectory() as td:
        data_path = _stage_volume(td, vol_path, shape, block_shape, warm)

        def one_run(tag, input_key):
            config_dir = os.path.join(td, f"configs{tag}")
            cfg.write_global_config(
                config_dir,
                {"block_shape": list(block_shape), "target": target},
            )
            cfg.write_config(config_dir, "watershed", dict(WS_TASK_CONFIG))
            cfg.write_config(
                config_dir, "sharded_watershed", dict(SHARDED_WS_CONFIG)
            )
            wf = WatershedWorkflow(
                os.path.join(td, f"tmp{tag}"), config_dir,
                input_path=data_path, input_key=input_key,
                output_path=data_path, output_key=f"ws{tag}",
                sharded=sharded,
            )
            t0 = time.perf_counter()
            ok = build([wf])
            wall = time.perf_counter() - t0
            if not ok:
                raise RuntimeError(f"watershed workflow failed ({tag})")
            return wall

        wall = one_run("", "bnd")
        if not warm:
            return wall
        warm_wall = one_run("_warm", "bnd_warm")
        stages = stage_breakdown(os.path.join(td, "tmp_warm"))
    return wall, warm_wall, stages
