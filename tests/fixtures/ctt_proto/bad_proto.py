"""CTT2xx protocol-rule fixture: every construct below violates a
shared-state protocol rule (module-scope-independent subset — CTT201/202/
206 need a producer-module path and are exercised inline in
tests/test_ctt_proto.py).  Linted by the CLI contract test; never
imported."""

from cluster_tools_tpu import faults


def park(path, payload):
    publish_once(path, payload)  # CTT203: won/lost return discarded


def is_stale(age, lease_s):
    return age > 3.0 * lease_s  # CTT204: literal multiple of a cadence


def retry_policy(stale_intervals=3.0):  # CTT204: constant re-declared
    return stale_intervals


def fire():
    faults.check("sched.not_a_site")  # CTT205: typo'd site never fires
