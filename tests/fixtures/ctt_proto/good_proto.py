"""CTT2xx negative fixture: the same protocol shapes written correctly —
the CLI contract test asserts this file lints clean.  Never imported."""

from cluster_tools_tpu import faults
from cluster_tools_tpu.runtime.queue import STALE_INTERVALS


def park(path, payload):
    if publish_once(path, payload):
        return True
    return False  # a peer already parked a record there


def is_stale(age, lease_s):
    return age > STALE_INTERVALS * lease_s


def fire():
    faults.check("sched.claim")
