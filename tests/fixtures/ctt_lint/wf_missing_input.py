"""ctt-lint fixture: a task consuming a dataset that no upstream task
produces and that is not a workflow input (CTT102)."""

from typing import Optional, Sequence

from cluster_tools_tpu.runtime.task import SimpleTask
from cluster_tools_tpu.runtime.workflow import WorkflowBase


class _FixtureProducer(SimpleTask):
    task_name = "fixture_producer"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None,
                 dependencies: Sequence = (), output_path=None,
                 output_key=None):
        super().__init__(tmp_folder, config_dir, max_jobs, dependencies)
        self.output_path = output_path
        self.output_key = output_key


class _FixtureConsumer(SimpleTask):
    task_name = "fixture_consumer"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None,
                 dependencies: Sequence = (), input_path=None, input_key=None,
                 output_path=None, output_key=None):
        super().__init__(tmp_folder, config_dir, max_jobs, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key


class MissingInputWorkflow(WorkflowBase):
    """The consumer reads ``fragments_interm`` which the producer never
    writes (its output key is ``fragments``) — the wiring typo CTT102
    exists to catch."""

    task_name = "fixture_missing_input_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None,
                 target=None, input_path=None, input_key=None,
                 output_path=None, output_key=None, dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key

    def requires(self):
        producer = _FixtureProducer(
            self.tmp_folder, self.config_dir,
            output_path=self.output_path, output_key="fragments",
        )
        consumer = _FixtureConsumer(
            self.tmp_folder, self.config_dir, dependencies=[producer],
            input_path=self.output_path, input_key="fragments_interm",
            output_path=self.output_path, output_key=self.output_key,
        )
        return [consumer]
