"""ctt-lint fixture: a task reading a misspelled config key (CTT103)."""

from cluster_tools_tpu.runtime.task import SimpleTask
from cluster_tools_tpu.runtime.workflow import WorkflowBase


class _FixtureTypoTask(SimpleTask):
    task_name = "fixture_typo_task"

    def run_impl(self) -> None:
        config = self.get_task_config()
        block_shape = config.get("block_shpae")  # typo of block_shape
        del block_shape


class ConfigTypoWorkflow(WorkflowBase):
    task_name = "fixture_config_typo_workflow"

    def requires(self):
        return [_FixtureTypoTask(self.tmp_folder, self.config_dir)]
