"""ctt-lint fixture: one violation of every AST invariant rule.  This file
is linted, never imported/executed — the undefined names are deliberate."""

import time

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial


@jax.jit
def host_call_in_jit(x):
    labels = np.unique(x)  # CTT001: host materialization of a tracer
    return labels


@partial(jax.jit, static_argnames=())
def clock_in_jit(x):
    return x + time.time()  # CTT002: wall clock baked into the program


def collective_outside_parallel(x):
    return jax.lax.psum(x, axis_name="data")  # CTT003: not in parallel/


@jax.jit
def wide_dtype_in_jit(x):
    return x.astype(jnp.float64)  # CTT004: 64-bit dtype in device code


def set_order_leak(edges):
    nodes = set()
    for u, v in edges:
        nodes.add(u)
        nodes.add(v)
    order = []
    for n in nodes:  # CTT005: hash-order iteration feeding constructed state
        order.append(n)
    return order


def bad_suppression(x):
    return x + 1  # ctt: noqa[CTT999] CTT007: unknown rule id in noqa
