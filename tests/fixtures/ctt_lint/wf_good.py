"""ctt-lint fixture: a correctly wired workflow — zero findings expected."""

from typing import Sequence

from cluster_tools_tpu.runtime.task import SimpleTask
from cluster_tools_tpu.runtime.workflow import WorkflowBase


class _GoodProducer(SimpleTask):
    task_name = "fixture_good_producer"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None,
                 dependencies: Sequence = (), input_path=None, input_key=None,
                 output_path=None, output_key=None):
        super().__init__(tmp_folder, config_dir, max_jobs, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key

    def run_impl(self) -> None:
        config = self.get_task_config()
        threads = config.get("threads_per_job", 1)
        del threads


class _GoodConsumer(SimpleTask):
    task_name = "fixture_good_consumer"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None,
                 dependencies: Sequence = (), input_path=None, input_key=None,
                 output_path=None, output_key=None):
        super().__init__(tmp_folder, config_dir, max_jobs, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key


class GoodWorkflow(WorkflowBase):
    task_name = "fixture_good_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None,
                 target=None, input_path=None, input_key=None,
                 output_path=None, output_key=None, dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key

    def requires(self):
        producer = _GoodProducer(
            self.tmp_folder, self.config_dir,
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key="fragments",
        )
        consumer = _GoodConsumer(
            self.tmp_folder, self.config_dir, dependencies=[producer],
            input_path=self.output_path, input_key="fragments",
            output_path=self.output_path, output_key=self.output_key,
        )
        return [consumer]
