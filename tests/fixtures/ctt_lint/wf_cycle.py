"""ctt-lint fixture: a workflow whose task DAG contains a cycle (CTT101).

Never imported by tests directly — loaded by the workflow-graph validator.
"""

from cluster_tools_tpu.runtime.task import SimpleTask
from cluster_tools_tpu.runtime.workflow import WorkflowBase


class _CycleTaskA(SimpleTask):
    task_name = "fixture_cycle_a"


class _CycleTaskB(SimpleTask):
    task_name = "fixture_cycle_b"


class CycleWorkflow(WorkflowBase):
    task_name = "fixture_cycle_workflow"

    def requires(self):
        a = _CycleTaskA(self.tmp_folder, self.config_dir)
        b = _CycleTaskB(self.tmp_folder, self.config_dir, dependencies=[a])
        a.dependencies.append(b)  # a -> b -> a
        return [b]
