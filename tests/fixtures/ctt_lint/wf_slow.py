"""ctt-lint fixture: a ``slow = True`` task reachable from a workflow not
itself marked slow (CTT104) — plus the acknowledged negative case."""

from cluster_tools_tpu.runtime.task import SimpleTask
from cluster_tools_tpu.runtime.workflow import WorkflowBase


class _FixtureSlowTask(SimpleTask):
    task_name = "fixture_slow_task"
    slow = True


class UnmarkedSlowWorkflow(WorkflowBase):
    task_name = "fixture_unmarked_slow_workflow"

    def requires(self):
        return [_FixtureSlowTask(self.tmp_folder, self.config_dir)]


class MarkedSlowWorkflow(WorkflowBase):
    task_name = "fixture_marked_slow_workflow"
    slow = True

    def requires(self):
        return [_FixtureSlowTask(self.tmp_folder, self.config_dir)]
