"""ctt-lint fixture: fused-chain contract violations (CTT011).

Three findings expected on BadStreamWorkflow:
  1. a chain member that is not a fusable split-protocol task;
  2. an in-chain consumer of a produced pair without fused_read_batch;
  3. an out-of-chain task consuming the elided intermediate.
"""

from typing import Sequence

from cluster_tools_tpu.runtime.stream import FusedChain
from cluster_tools_tpu.runtime.workflow import WorkflowBase
from cluster_tools_tpu.tasks.base import VolumeTask


class _BadProducer(VolumeTask):
    task_name = "fixture_bad_stream_producer"
    output_dtype = "uint8"
    fusable = True

    def read_batch(self, block_ids, blocking, config):
        return block_ids

    def compute_batch(self, payload, blocking, config):
        return payload

    def write_batch(self, result, blocking, config):
        pass


class _NoProtocolMember(VolumeTask):
    """fusable claimed but the split protocol is missing."""

    task_name = "fixture_bad_stream_noproto"
    output_dtype = "uint64"
    fusable = True


class _LazyConsumer(VolumeTask):
    """Consumes the in-chain product without fused_read_batch."""

    task_name = "fixture_bad_stream_lazy"
    output_dtype = "uint64"
    fusable = True

    def read_batch(self, block_ids, blocking, config):
        return block_ids

    def compute_batch(self, payload, blocking, config):
        return payload

    def write_batch(self, result, blocking, config):
        pass


class _OutsideConsumer(VolumeTask):
    """Out of chain, reads the elided mask — it will never exist."""

    task_name = "fixture_bad_stream_outside"
    output_dtype = "uint64"


class BadStreamWorkflow(WorkflowBase):
    task_name = "fixture_stream_bad_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None,
                 target=None, input_path=None, input_key=None,
                 output_path=None, output_key=None,
                 dependencies: Sequence = ()):
        super().__init__(tmp_folder, config_dir, max_jobs, target,
                         dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key

    def _tasks(self):
        mask_key = self.output_key + "_m"
        producer = _BadProducer(
            self.tmp_folder, self.config_dir,
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=mask_key,
        )
        noproto = _NoProtocolMember(
            self.tmp_folder, self.config_dir, dependencies=[producer],
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=self.output_key + "_x",
        )
        lazy = _LazyConsumer(
            self.tmp_folder, self.config_dir, dependencies=[producer],
            input_path=self.output_path, input_key=mask_key,
            output_path=self.output_path, output_key=self.output_key + "_y",
        )
        outside = _OutsideConsumer(
            self.tmp_folder, self.config_dir, dependencies=[lazy],
            input_path=self.output_path, input_key=mask_key,
            output_path=self.output_path, output_key=self.output_key,
        )
        return producer, noproto, lazy, outside

    def requires(self):
        _, _, _, outside = self._tasks()
        return [outside]

    def fused_chains(self):
        producer, noproto, lazy, _ = self._tasks()
        return [FusedChain(
            name="fixture_stream_bad",
            members=[producer, noproto, lazy],
            elide={producer.identifier},
        )]
