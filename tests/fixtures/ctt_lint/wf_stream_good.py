"""ctt-lint fixture: a correctly declared fused streaming chain (CTT011) —
zero findings expected.  Mirrors StreamingSegmentationWorkflow's shape:
fusable split-protocol members, elided intermediate consumed only
in-chain via fused_read_batch."""

from cluster_tools_tpu.runtime.stream import FusedChain
from cluster_tools_tpu.runtime.workflow import WorkflowBase
from cluster_tools_tpu.tasks.base import VolumeTask


class _StreamProducer(VolumeTask):
    task_name = "fixture_stream_producer"
    output_dtype = "uint8"
    fusable = True

    def read_batch(self, block_ids, blocking, config):
        return block_ids

    def compute_batch(self, payload, blocking, config):
        return payload

    def write_batch(self, result, blocking, config):
        pass


class _StreamConsumer(VolumeTask):
    task_name = "fixture_stream_consumer"
    output_dtype = "uint64"
    fusable = True

    def read_batch(self, block_ids, blocking, config):
        return block_ids

    def fused_read_batch(self, handoffs, block_ids, blocking, config):
        return handoffs[(self.input_path, self.input_key)]

    def compute_batch(self, payload, blocking, config):
        return payload

    def write_batch(self, result, blocking, config):
        pass


class GoodStreamWorkflow(WorkflowBase):
    task_name = "fixture_stream_good_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None,
                 target=None, input_path=None, input_key=None,
                 output_path=None, output_key=None, dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target,
                         dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key

    def _tasks(self):
        producer = _StreamProducer(
            self.tmp_folder, self.config_dir,
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=self.output_key + "_m",
        )
        consumer = _StreamConsumer(
            self.tmp_folder, self.config_dir, dependencies=[producer],
            input_path=self.output_path, input_key=self.output_key + "_m",
            output_path=self.output_path, output_key=self.output_key,
        )
        return producer, consumer

    def requires(self):
        _, consumer = self._tasks()
        return [consumer]

    def fused_chains(self):
        producer, consumer = self._tasks()
        return [FusedChain(
            name="fixture_stream_good",
            members=[producer, consumer],
            elide={producer.identifier},
        )]
