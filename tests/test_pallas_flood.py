"""Pallas per-slice flood: exact equivalence with the XLA flood fixpoint.

Runs the kernel through the Pallas CPU interpreter (Mosaic lowering itself
needs hardware — tools/tpu_validate.py covers that); equivalence here is
*exact label equality*, since both paths compute the same lexicographic
(pass-height, hops, label) fixpoint with identical tie-breaking.
"""

import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu.ops.pallas_flood import flood_slices
from cluster_tools_tpu.ops.watershed import (
    _seeded_watershed_scan,
    dt_seeds,
)
import jax.numpy as jnp


def _volume(shape, seed):
    rng = np.random.default_rng(seed)
    raw = ndimage.gaussian_filter(rng.random(shape), (0.5, 2.0, 2.0))
    return ((raw - raw.min()) / (raw.max() - raw.min())).astype(np.float32)


@pytest.mark.parametrize("shape,seed", [((3, 16, 128), 0), ((2, 32, 128), 5)])
def test_flood_slices_matches_xla_fixpoint(shape, seed, rng):
    hmap = _volume(shape, seed)
    fg = hmap < 0.6
    from cluster_tools_tpu.ops.dt import distance_transform_2d_stack

    dt = distance_transform_2d_stack(jnp.asarray(fg))
    seeds, _ = dt_seeds(dt, sigma=1.0, per_slice=True)

    ref = np.asarray(
        _seeded_watershed_scan(
            jnp.asarray(hmap), seeds, jnp.asarray(fg), per_slice=True
        )
    )
    got = np.asarray(
        flood_slices(jnp.asarray(hmap), seeds, jnp.asarray(fg), interpret=True)
    )
    np.testing.assert_array_equal(got, ref)


def test_flood_slices_mask_and_empty_slices(rng):
    # a slice with no seeds, a fully-masked slice, and plateaus
    hmap = np.ones((3, 16, 128), dtype=np.float32) * 0.5
    seeds = np.zeros((3, 16, 128), dtype=np.int32)
    mask = np.ones((3, 16, 128), dtype=bool)
    seeds[0, 2, 3] = 1
    seeds[0, 12, 100] = 2
    mask[1] = False  # fully masked
    # slice 2: seeds but split mask
    seeds[2, 3, 10] = 5
    seeds[2, 3, 90] = 4
    mask[2, :, 60:64] = False

    ref = np.asarray(
        _seeded_watershed_scan(
            jnp.asarray(hmap), jnp.asarray(seeds), jnp.asarray(mask),
            per_slice=True,
        )
    )
    got = np.asarray(
        flood_slices(
            jnp.asarray(hmap), jnp.asarray(seeds), jnp.asarray(mask),
            interpret=True,
        )
    )
    np.testing.assert_array_equal(got, ref)
    assert (got[1] == 0).all()
    # mask wall: right side of slice 2 floods from seed 4 only
    assert (got[2, :, 64:][got[2, :, 64:] > 0] == 4).all()


def test_pallas_gate_requires_optin(monkeypatch):
    from cluster_tools_tpu.ops.pallas_flood import pallas_flood_available

    monkeypatch.delenv("CTT_FLOOD_MODE", raising=False)
    assert not pallas_flood_available((8, 16, 128), True)
    monkeypatch.setenv("CTT_FLOOD_MODE", "pallas")
    # CPU backend in tests -> still gated off; alignment + mode checks apply
    assert not pallas_flood_available((8, 16, 128), False)
    assert not pallas_flood_available((8, 17, 128), True)
    assert not pallas_flood_available((8, 16, 100), True)


def test_flood_serpentine_corridor_converges():
    """Banded serpentine corridor (Θ(H·W) directional segments): the kernel
    must still reach the XLA fixpoint — the case a capped round loop
    silently truncates."""
    h, w = 16, 128
    mask = np.zeros((1, h, w), dtype=bool)
    for c in range(0, w - 2, 2):
        mask[0, :, c] = True
        mask[0, 0 if (c // 2) % 2 else h - 1, c + 1] = True
    hmap = np.full((1, h, w), 0.5, dtype=np.float32)
    seeds = np.zeros((1, h, w), dtype=np.int32)
    seeds[0, 0, 0] = 1  # one seed at the corridor's start: must flood it all
    ref = np.asarray(
        _seeded_watershed_scan(
            jnp.asarray(hmap), jnp.asarray(seeds), jnp.asarray(mask),
            per_slice=True,
        )
    )
    got = np.asarray(
        flood_slices(
            jnp.asarray(hmap), jnp.asarray(seeds), jnp.asarray(mask),
            interpret=True,
        )
    )
    np.testing.assert_array_equal(got, ref)
    assert (got[mask] == 1).all()  # the whole corridor is reached
