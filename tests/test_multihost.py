"""Multi-process (DCN-analog) validation of the collective kernels.

Spawns 2 worker processes x 4 virtual CPU devices each, joined through
``parallel.mesh.init_distributed`` (jax.distributed / Gloo on CPU — the
CPU stand-in for cross-host DCN), and runs the real collective kernels over
the GLOBAL 8-device mesh:

  * sharded_connected_components — partition must match scipy;
  * sharded_seeded_watershed — must match the single-device flood bitwise.

Every process holds the full host volume (the shared-storage model: each
host reads from the chunked store) and materializes only its addressable
shards via ``put_global``; results come back through ``fetch_local`` and
each worker asserts ITS local slab, so a silent wrong-shard placement fails
loudly.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = r"""
import sys
import jax

jax.config.update("jax_platforms", "cpu")

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
import os
os.environ["CTT_COORDINATOR"] = f"127.0.0.1:{port}"
os.environ["CTT_NUM_PROCESSES"] = str(nproc)
os.environ["CTT_PROCESS_ID"] = str(pid)

from cluster_tools_tpu.parallel import mesh as mesh_mod

assert mesh_mod.init_distributed()
devs = mesh_mod.resolve_devices({"devices": "global"})
assert len(devs) == 8, len(devs)
mesh = mesh_mod.get_mesh(devs)

import numpy as np
from scipy import ndimage

rng = np.random.default_rng(0)
shape = (16, 16, 32)
raw = ndimage.gaussian_filter(rng.random(shape), 1.0)
raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")
mask = raw > 0.5

from cluster_tools_tpu.parallel.sharded import (
    sharded_connected_components,
    sharded_seeded_watershed,
)

labels = sharded_connected_components(mask, mesh=mesh)
z0, local = mesh_mod.fetch_local(labels)
want, _ = ndimage.label(mask)
want_local = want[z0 : z0 + local.shape[0]]
mask_local = mask[z0 : z0 + local.shape[0]]
got = np.where(local < 0, 0, local + 1)
pairs = np.unique(
    np.stack([got[mask_local], want_local[mask_local]], axis=1), axis=0
)
assert len(pairs) == len(np.unique(got[mask_local])) == len(
    np.unique(want_local[mask_local])
), f"p{pid}: CC partition mismatch"
print(f"[p{pid}] sharded CC over 2x4 devices OK "
      f"(z {z0}..{z0+local.shape[0]})", flush=True)

seeds = np.zeros(shape, dtype="int32")
seeds[0, 0, 0] = 1
seeds[-1, -1, -1] = 2
flood = sharded_seeded_watershed(raw, seeds, mesh=mesh)
z0f, flocal = mesh_mod.fetch_local(flood)

from cluster_tools_tpu.ops.watershed import seeded_watershed
import jax.numpy as jnp

ref = np.asarray(seeded_watershed(jnp.asarray(raw), jnp.asarray(seeds)))
assert (flocal == ref[z0f : z0f + flocal.shape[0]]).all(), (
    f"p{pid}: flood mismatch"
)
print(f"[p{pid}] sharded flood bitwise == 1-device flood", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_collective_kernels_across_processes(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        # a deadlocked collective is this test's characteristic failure —
        # never leave the peer (and its coordinator port) running
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert "sharded CC over 2x4 devices OK" in out
        assert "bitwise == 1-device flood" in out


TASK_WORKER = r"""
import sys
import jax

jax.config.update("jax_platforms", "cpu")

pid, nproc, port, root = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
)
import os
os.environ["CTT_COORDINATOR"] = f"127.0.0.1:{port}"
os.environ["CTT_NUM_PROCESSES"] = str(nproc)
os.environ["CTT_PROCESS_ID"] = str(pid)

from cluster_tools_tpu.parallel import mesh as mesh_mod

assert mesh_mod.init_distributed()  # BEFORE any backend use

import numpy as np
from scipy import ndimage

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.tasks.thresholded_components import (
    ShardedComponentsTask,
)
from cluster_tools_tpu.utils import file_reader

path = os.path.join(root, "d.n5")
if pid == 0:
    rng = np.random.default_rng(0)
    raw = rng.random((16, 16, 16)).astype("float32")
    file_reader(path).create_dataset("raw", data=raw, chunks=(8, 16, 16))
    cfg.write_global_config(
        os.path.join(root, "configs"),
        {"block_shape": [8, 16, 16], "devices": "global"},
    )
    open(os.path.join(root, "ready"), "w").write("1")
else:
    import time

    while not os.path.exists(os.path.join(root, "ready")):
        time.sleep(0.1)

task = ShardedComponentsTask(
    os.path.join(root, "tmp"), os.path.join(root, "configs"),
    input_path=path, input_key="raw",
    output_path=path, output_key="cc",
)
assert build([task])
if pid == 0:
    raw = file_reader(path, "r")["raw"][:]
    got = file_reader(path, "r")["cc"][:]
    want, n_want = ndimage.label(raw > 0.5)
    pairs = np.unique(
        np.stack([got[raw > 0.5], want[raw > 0.5]], axis=1), axis=0
    )
    assert len(pairs) == n_want == len(np.unique(got[got > 0]))
print(f"[p{pid}] collective task build OK over "
      f"{jax.device_count()} devices / {jax.process_count()} processes",
      flush=True)
"""


def test_collective_task_layer_across_processes(tmp_path):
    """build([ShardedComponentsTask]) under a 2-process global mesh: every
    process enters the collective program (SimpleTask.collective), process 0
    writes output + status, peers complete via the status barrier."""
    worker = tmp_path / "task_worker.py"
    worker.write_text(TASK_WORKER)
    root = tmp_path / "run"
    root.mkdir()
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    port = _free_port()
    procs = []
    for pid in range(2):
        penv = dict(env, CTT_PROCESS_ID=str(pid))
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker), str(pid), "2", str(port),
                 str(root)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=penv,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert "collective task build OK" in out
