"""Multi-process (DCN-analog) validation of the collective kernels.

Spawns 2 worker processes x 4 virtual CPU devices each, joined through
``parallel.mesh.init_distributed`` (jax.distributed / Gloo on CPU — the
CPU stand-in for cross-host DCN), and runs the real collective kernels over
the GLOBAL 8-device mesh:

  * sharded_connected_components — partition must match scipy;
  * sharded_seeded_watershed — must match the single-device flood bitwise.

Every process holds the full host volume (the shared-storage model: each
host reads from the chunked store) and materializes only its addressable
shards via ``put_global``; results come back through ``fetch_local`` and
each worker asserts ITS local slab, so a silent wrong-shard placement fails
loudly.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = r"""
import sys
import jax

jax.config.update("jax_platforms", "cpu")

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
import os
os.environ["CTT_COORDINATOR"] = f"127.0.0.1:{port}"
os.environ["CTT_NUM_PROCESSES"] = str(nproc)
os.environ["CTT_PROCESS_ID"] = str(pid)

from cluster_tools_tpu.parallel import mesh as mesh_mod

assert mesh_mod.init_distributed()
devs = mesh_mod.resolve_devices({"devices": "global"})
assert len(devs) == 8, len(devs)
mesh = mesh_mod.get_mesh(devs)

import numpy as np
from scipy import ndimage

rng = np.random.default_rng(0)
shape = (16, 16, 32)
raw = ndimage.gaussian_filter(rng.random(shape), 1.0)
raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")
mask = raw > 0.5

from cluster_tools_tpu.parallel.sharded import (
    sharded_connected_components,
    sharded_seeded_watershed,
)

labels = sharded_connected_components(mask, mesh=mesh)
z0, local = mesh_mod.fetch_local(labels)
want, _ = ndimage.label(mask)
want_local = want[z0 : z0 + local.shape[0]]
mask_local = mask[z0 : z0 + local.shape[0]]
got = np.where(local < 0, 0, local + 1)
pairs = np.unique(
    np.stack([got[mask_local], want_local[mask_local]], axis=1), axis=0
)
assert len(pairs) == len(np.unique(got[mask_local])) == len(
    np.unique(want_local[mask_local])
), f"p{pid}: CC partition mismatch"
print(f"[p{pid}] sharded CC over 2x4 devices OK "
      f"(z {z0}..{z0+local.shape[0]})", flush=True)

seeds = np.zeros(shape, dtype="int32")
seeds[0, 0, 0] = 1
seeds[-1, -1, -1] = 2
flood = sharded_seeded_watershed(raw, seeds, mesh=mesh)
z0f, flocal = mesh_mod.fetch_local(flood)

from cluster_tools_tpu.ops.watershed import seeded_watershed
import jax.numpy as jnp

ref = np.asarray(seeded_watershed(jnp.asarray(raw), jnp.asarray(seeds)))
assert (flocal == ref[z0f : z0f + flocal.shape[0]]).all(), (
    f"p{pid}: flood mismatch"
)
print(f"[p{pid}] sharded flood bitwise == 1-device flood", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_collective_kernels_across_processes(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        # a deadlocked collective is this test's characteristic failure —
        # never leave the peer (and its coordinator port) running
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert "sharded CC over 2x4 devices OK" in out
        assert "bitwise == 1-device flood" in out


TASK_WORKER = r"""
import sys
import jax

jax.config.update("jax_platforms", "cpu")

pid, nproc, port, root = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
)
import os
os.environ["CTT_COORDINATOR"] = f"127.0.0.1:{port}"
os.environ["CTT_NUM_PROCESSES"] = str(nproc)
os.environ["CTT_PROCESS_ID"] = str(pid)

from cluster_tools_tpu.parallel import mesh as mesh_mod

assert mesh_mod.init_distributed()  # BEFORE any backend use

import numpy as np
from scipy import ndimage

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.tasks.thresholded_components import (
    ShardedComponentsTask,
)
from cluster_tools_tpu.utils import file_reader

path = os.path.join(root, "d.n5")
if pid == 0:
    rng = np.random.default_rng(0)
    raw = rng.random((16, 16, 16)).astype("float32")
    file_reader(path).create_dataset("raw", data=raw, chunks=(8, 16, 16))
    cfg.write_global_config(
        os.path.join(root, "configs"),
        {"block_shape": [8, 16, 16], "devices": "global"},
    )
    open(os.path.join(root, "ready"), "w").write("1")
else:
    import time

    while not os.path.exists(os.path.join(root, "ready")):
        time.sleep(0.1)

task = ShardedComponentsTask(
    os.path.join(root, "tmp"), os.path.join(root, "configs"),
    input_path=path, input_key="raw",
    output_path=path, output_key="cc",
)
assert build([task])
if pid == 0:
    raw = file_reader(path, "r")["raw"][:]
    got = file_reader(path, "r")["cc"][:]
    want, n_want = ndimage.label(raw > 0.5)
    pairs = np.unique(
        np.stack([got[raw > 0.5], want[raw > 0.5]], axis=1), axis=0
    )
    assert len(pairs) == n_want == len(np.unique(got[got > 0]))
print(f"[p{pid}] collective task build OK over "
      f"{jax.device_count()} devices / {jax.process_count()} processes",
      flush=True)
"""


def test_collective_task_layer_across_processes(tmp_path):
    """build([ShardedComponentsTask]) under a 2-process global mesh: every
    process enters the collective program (SimpleTask.collective), process 0
    writes output + status, peers complete via the status barrier."""
    worker = tmp_path / "task_worker.py"
    worker.write_text(TASK_WORKER)
    root = tmp_path / "run"
    root.mkdir()
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    port = _free_port()
    procs, outs = _spawn(worker, 2, env, extra_args=[port, root], timeout=420)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert "collective task build OK" in out


FUSED_WORKER = r"""
import sys
import jax

jax.config.update("jax_platforms", "cpu")

pid, nproc, port, root = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
)
import os
os.environ["CTT_COORDINATOR"] = f"127.0.0.1:{port}"
os.environ["CTT_NUM_PROCESSES"] = str(nproc)
os.environ["CTT_PROCESS_ID"] = str(pid)

from cluster_tools_tpu.parallel import mesh as mesh_mod

assert mesh_mod.init_distributed()  # BEFORE any backend use

import numpy as np
from scipy import ndimage

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.tasks.features import ShardedWsProblemTask
from cluster_tools_tpu.utils import file_reader

path = os.path.join(root, "d.n5")
if pid == 0:
    rng = np.random.default_rng(3)
    raw = ndimage.gaussian_filter(rng.random((16, 24, 24)), (1.0, 2.0, 2.0))
    raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")
    file_reader(path).create_dataset("bnd", data=raw, chunks=(8, 24, 24))
    cfg.write_global_config(
        os.path.join(root, "configs"),
        {"block_shape": [8, 24, 24], "devices": "global"},
    )
    cfg.write_config(
        os.path.join(root, "configs"), "sharded_ws_problem",
        {"threshold": 0.6, "sigma_seeds": 1.0, "size_filter": 5,
         "max_edges": 2048},
    )
    open(os.path.join(root, "ready"), "w").write("1")
else:
    import time

    while not os.path.exists(os.path.join(root, "ready")):
        time.sleep(0.1)

task = ShardedWsProblemTask(
    os.path.join(root, "tmp"), os.path.join(root, "configs"),
    input_path=path, input_key="bnd",
    output_path=path, output_key="ws",
)
assert build([task])
if pid == 0:
    from cluster_tools_tpu.tasks.base import scratch_store_path

    ws = file_reader(path, "r")["ws"][:]
    n_frag = len(np.unique(ws[ws > 0]))
    assert n_frag > 2, n_frag
    scratch = file_reader(scratch_store_path(os.path.join(root, "tmp")), "r")
    edges = scratch["graph/edges"][:]
    feats = scratch["features/edges"][:]
    assert edges.shape[0] == feats.shape[0] > 0
    assert scratch["graph/edges"].attrs["n_nodes"] == n_frag
    # edges reference real fragments and counts are positive
    assert edges.max() < n_frag and (feats[:, 9] > 0).all()
print(f"[p{pid}] fused ws+problem collective build OK", flush=True)
"""


def test_fused_ws_problem_across_processes(tmp_path):
    """The round-5 fused device-resident front under a 2-process global
    mesh: every process enters the collective watershed AND the collective
    RAG; process 0 owns the ws + scratch writes."""
    worker = tmp_path / "fused_worker.py"
    worker.write_text(FUSED_WORKER)
    root = tmp_path / "runf"
    root.mkdir()
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    port = _free_port()
    procs, outs = _spawn(worker, 2, env, extra_args=[port, root], timeout=420)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert "fused ws+problem collective build OK" in out


def _spawn(worker_path, n_procs, env, extra_args=(), timeout=600):
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker_path), str(pid), str(n_procs)]
            + [str(a) for a in extra_args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=dict(env, CTT_PROCESS_ID=str(pid)),
        )
        for pid in range(n_procs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return procs, outs


def test_collective_task_four_processes_uneven_z(tmp_path):
    """VERDICT r3 item 4: ≥4-process topology AND a z extent (19) that does
    not divide the 8-device global mesh — the task layer must pad the shards
    (put_from_store pad_to) and produce the exact scipy partition."""
    worker = tmp_path / "task_worker4.py"
    worker.write_text(
        TASK_WORKER.replace("(16, 16, 16)", "(19, 8, 8)")
        .replace('chunks=(8, 16, 16)', 'chunks=(5, 8, 8)')
        .replace('"block_shape": [8, 16, 16]', '"block_shape": [5, 8, 8]')
    )
    root = tmp_path / "run4"
    root.mkdir()
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    port = _free_port()
    procs, outs = _spawn(worker, 4, env, extra_args=[port, root], timeout=600)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert "collective task build OK over 8 devices / 4 processes" in out


ABORT_WORKER = r"""
import os
import sys
import time

pid, nproc, root, mode = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
)
os.environ["CTT_NUM_PROCESSES"] = str(nproc)
os.environ["CTT_PROCESS_ID"] = str(pid)

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.runtime.task import SimpleTask


class MultiHostVictim(SimpleTask):
    task_name = "victim"

    def run_impl(self):
        if mode == "raise":
            time.sleep(2.0)
            raise RuntimeError("injected p0 failure")
        time.sleep(300.0)  # 'hung' p0 — the test SIGKILLs this process


config_dir = os.path.join(root, "configs")
if pid == 0:
    cfg.write_global_config(
        config_dir,
        {"num_processes": nproc, "peer_wait_timeout_s": 10.0},
    )
    open(os.path.join(root, "ready"), "w").write("1")
    print("p0 entering task", flush=True)
else:
    while not os.path.exists(os.path.join(root, "ready")):
        time.sleep(0.05)

t0 = time.time()
try:
    build([MultiHostVictim(os.path.join(root, "tmp"), config_dir)],
          raise_on_failure=True)
except Exception as e:
    print(f"[p{pid}] FAILED after {time.time()-t0:.1f}s: "
          f"{type(e).__name__}: {e}", flush=True)
    sys.exit(17)
print(f"[p{pid}] completed", flush=True)
"""


def _abort_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    return env


def test_cross_process_abort_propagates(tmp_path):
    """A p0 exception mid-task must fail waiting peers FAST via the abort
    record (COMPONENTS.md §5; reference failure-semantics anchor
    cluster_tasks.py:114-159) — well before the peer-wait timeout."""
    import time as _time

    worker = tmp_path / "abort_worker.py"
    worker.write_text(ABORT_WORKER)
    root = tmp_path / "runa"
    root.mkdir()
    t0 = _time.time()
    procs, outs = _spawn(
        worker, 3, _abort_env(), extra_args=[root, "raise"], timeout=120
    )
    elapsed = _time.time() - t0
    assert procs[0].returncode == 17, outs[0][-2000:]
    assert "injected p0 failure" in outs[0]
    for pid in (1, 2):
        assert procs[pid].returncode == 17, outs[pid][-2000:]
        assert "peer process aborted" in outs[pid], outs[pid][-2000:]
        assert "injected p0 failure" in outs[pid]
    # peers failed via the abort record, not by burning the 10 s timeout
    # after p0's 2 s sleep — total stays well under spawn+timeout worst case
    assert elapsed < 60, elapsed


def test_killed_peer_bounded_by_wait_timeout(tmp_path):
    """SIGKILLed p0 writes no abort record; peers must still fail within the
    configured peer_wait_timeout_s instead of hanging."""
    import signal
    import time as _time

    worker = tmp_path / "kill_worker.py"
    worker.write_text(ABORT_WORKER)
    root = tmp_path / "runk"
    root.mkdir()
    env = _abort_env()
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), "2", str(root), "hang"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=dict(env, CTT_PROCESS_ID=str(pid)),
        )
        for pid in range(2)
    ]
    try:
        # wait for p0 to be inside the task, then kill it hard
        t0 = _time.time()
        while _time.time() - t0 < 60:
            if os.path.exists(os.path.join(root, "ready")):
                break
            _time.sleep(0.1)
        _time.sleep(1.0)
        procs[0].send_signal(signal.SIGKILL)
        out1, _ = procs[1].communicate(timeout=90)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    assert procs[1].returncode == 17, out1[-2000:]
    assert "timed out" in out1, out1[-2000:]
