"""Persistent compilation cache wiring (utils/compile_cache.py)."""

import os

from cluster_tools_tpu.utils import compile_cache


def test_disabled_by_env(monkeypatch):
    monkeypatch.setenv("CTT_COMPILE_CACHE", "0")
    monkeypatch.setattr(compile_cache, "_ACTIVE_DIR", None)
    assert compile_cache.enable_compile_cache() is None


def test_enable_points_jax_at_the_dir(tmp_path, monkeypatch):
    import jax

    target = str(tmp_path / "xla")
    monkeypatch.setenv("CTT_COMPILE_CACHE", target)
    prev = jax.config.jax_compilation_cache_dir
    prev_active = compile_cache._ACTIVE_DIR
    compile_cache._ACTIVE_DIR = None
    try:
        got = compile_cache.enable_compile_cache()
        assert got == target
        assert os.path.isdir(target)
        assert jax.config.jax_compilation_cache_dir == target
        # once enabled, later calls return the ACTIVE dir even when asked
        # for another (re-pointing a live cache is unsupported)
        assert compile_cache.enable_compile_cache("/elsewhere") == target
    finally:
        compile_cache._ACTIVE_DIR = prev_active
        jax.config.update("jax_compilation_cache_dir", prev)
