"""Node-label cost overrides (reference costs/probs_to_costs.py:116-152).

Unit oracle for the three override modes plus an end-to-end check that
ProbsToCostsTask applies them on top of the transformed costs with the
5×min / 5×max bounds of the reference (probs_to_costs.py:219-220).
"""

import os

import numpy as np
import pytest

from cluster_tools_tpu.ops.multicut import (
    apply_node_label_costs,
    transform_probabilities_to_costs,
)
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


class TestApplyNodeLabelCosts:
    # endpoint label combos: both labeled / one labeled / none / equal>0 /
    # different>0
    EP = np.array(
        [[1, 1], [1, 0], [0, 0], [2, 2], [1, 2]], dtype=np.int64
    )

    def test_ignore(self):
        costs = np.zeros(5)
        out = apply_node_label_costs(costs, self.EP, "ignore", -10.0, 10.0)
        # every edge touching a labeled node is max repulsive
        np.testing.assert_array_equal(out, [-10, -10, 0, -10, -10])

    def test_isolate(self):
        costs = np.zeros(5)
        out = apply_node_label_costs(costs, self.EP, "isolate", -10.0, 10.0)
        # both labeled → attractive, exactly one → repulsive
        np.testing.assert_array_equal(out, [10, -10, 0, 10, 10])

    def test_ignore_transition(self):
        costs = np.zeros(5)
        out = apply_node_label_costs(
            costs, self.EP, "ignore_transition", -10.0, 10.0
        )
        # differing label values (incl. label↔0) → repulsive
        np.testing.assert_array_equal(out, [0, -10, 0, 0, -10])

    def test_invalid_mode_raises(self):
        with pytest.raises(ValueError, match="invalid node-label mode"):
            apply_node_label_costs(np.zeros(5), self.EP, "bogus", -1.0, 1.0)

    def test_does_not_mutate_input(self):
        costs = np.zeros(5)
        apply_node_label_costs(costs, self.EP, "ignore", -10.0, 10.0)
        assert (costs == 0).all()


class TestProbsToCostsNodeLabels:
    def _problem(self, tmp_path, rng, name, seed=0):
        from cluster_tools_tpu.workflows import (
            EdgeFeaturesWorkflow,
            GraphWorkflow,
        )

        rng = np.random.default_rng(seed)  # same volume for every `name`
        labels = rng.integers(1, 25, (8, 16, 16)).astype("uint64")
        bnd = rng.random((8, 16, 16)).astype("float32")
        path = str(tmp_path / f"{name}.n5")
        f = file_reader(path)
        f.create_dataset("ws", data=labels, chunks=(4, 8, 8))
        f.create_dataset("bnd", data=bnd, chunks=(4, 8, 8))
        config_dir = str(tmp_path / f"configs_{name}")
        tmp_folder = str(tmp_path / f"tmp_{name}")
        cfg.write_global_config(config_dir, {"block_shape": [4, 8, 8]})
        graph = GraphWorkflow(
            tmp_folder, config_dir, input_path=path, input_key="ws"
        )
        feats = EdgeFeaturesWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="bnd",
            labels_path=path, labels_key="ws",
            dependencies=[graph],
        )
        return tmp_folder, config_dir, feats

    def test_override_matches_manual_application(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.costs import COSTS_NAME, ProbsToCostsTask

        # base run without overrides
        tmp_a, cfg_a, feats_a = self._problem(tmp_path, rng, "base")
        base = ProbsToCostsTask(tmp_a, cfg_a, dependencies=[feats_a])
        assert build([base])
        base_costs = np.load(os.path.join(tmp_a, COSTS_NAME))

        store = file_reader(os.path.join(tmp_a, "data.zarr"), "r")
        nodes = store["graph/nodes"][:]
        edges = store["graph/edges"][:]

        # binary node-label table indexed by fragment id
        table = np.zeros(int(nodes.max()) + 1, dtype=np.uint32)
        table[nodes[rng.random(nodes.size) < 0.4]] = 1
        label_path = str(tmp_path / "node_labels.npy")
        np.save(label_path, table)

        # identical problem, this time with the isolate override
        tmp_b, cfg_b, feats_b = self._problem(tmp_path, rng, "override")
        task = ProbsToCostsTask(
            tmp_b, cfg_b, dependencies=[feats_b],
            node_label_dict={"isolate": label_path},
        )
        assert build([task])
        got = np.load(os.path.join(tmp_b, COSTS_NAME))

        want = apply_node_label_costs(
            base_costs,
            table[nodes[edges]],
            "isolate",
            5.0 * base_costs.min(),
            5.0 * base_costs.max(),
        )
        np.testing.assert_allclose(got, want)
        assert not np.allclose(got, base_costs)  # the override did something

    def test_store_dataset_source_and_bad_mode(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.costs import COSTS_NAME, ProbsToCostsTask

        tmp_folder, config_dir, feats = self._problem(tmp_path, rng, "ds")
        # labels provided as a chunked-store dataset instead of .npy
        label_store = str(tmp_path / "labels.n5")
        # size: fragment ids are < 25 by construction
        table = np.zeros(25, dtype=np.uint64)
        table[rng.integers(1, 25, 8)] = 3
        file_reader(label_store).create_dataset(
            "node_labels", data=table, chunks=(25,)
        )
        task = ProbsToCostsTask(
            tmp_folder, config_dir, dependencies=[feats],
            node_label_dict={"ignore_transition": (label_store, "node_labels")},
        )
        assert build([task])
        costs = np.load(os.path.join(tmp_folder, COSTS_NAME))
        store = file_reader(os.path.join(tmp_folder, "data.zarr"), "r")
        nodes = store["graph/nodes"][:]
        edges = store["graph/edges"][:]
        ep = table[nodes[edges]]
        transition = ep[:, 0] != ep[:, 1]
        if transition.any():
            rep = costs[transition]
            assert (rep == rep[0]).all() and rep[0] < costs.min() / 4.9

    def test_invalid_mode_rejected_at_construction(self, tmp_path):
        from cluster_tools_tpu.tasks.costs import ProbsToCostsTask

        with pytest.raises(ValueError, match="invalid node-label modes"):
            ProbsToCostsTask(
                str(tmp_path / "tmp_bad"), str(tmp_path / "cfg"),
                dependencies=[],
                node_label_dict={"bogus": "labels.npy"},
            )

    def test_short_label_table_rejected_with_diagnostic(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.costs import ProbsToCostsTask

        tmp_folder, config_dir, feats = self._problem(tmp_path, rng, "short")
        label_path = str(tmp_path / "short_labels.npy")
        np.save(label_path, np.zeros(2, dtype=np.uint32))  # far too short
        task = ProbsToCostsTask(
            tmp_folder, config_dir, dependencies=[feats],
            node_label_dict={"ignore": label_path},
        )
        # the failure must name the offending table, not be a bare IndexError
        with pytest.raises(ValueError, match="node-label table"):
            build([task])

    def test_identifier_distinguishes_override_dicts(self, tmp_path):
        from cluster_tools_tpu.tasks.costs import ProbsToCostsTask

        mk = lambda nld: ProbsToCostsTask(
            str(tmp_path / "t"), str(tmp_path / "c"),
            dependencies=[], node_label_dict=nld,
        ).identifier
        a = mk({"ignore": "a.npy"})
        b = mk({"ignore": "b.npy"})
        c = mk({"isolate": "a.npy"})
        d = mk({"ignore": ("store.n5", "key")})
        assert len({a, b, c, d}) == 4
        assert mk(None) == "probs_to_costs"

    def test_workflow_plumbs_node_label_dict(self, tmp_path, rng):
        """MulticutSegmentationWorkflow(node_label_dict=...) must isolate the
        labeled fragments in the final segmentation."""
        from cluster_tools_tpu.workflows import MulticutSegmentationWorkflow
        from scipy import ndimage

        # fixed seed: the reference's max_repulsive = 5*min(cost)
        # (probs_to_costs.py:219) only isolates when min(cost) < 0, which
        # holds for this volume but not for arbitrary noise draws
        rng = np.random.default_rng(0)
        labels_gt = rng.integers(1, 8, (4, 8, 8)).astype("uint64")
        labels_gt = np.kron(labels_gt, np.ones((2, 2, 2), dtype=np.uint64))
        bnd = ndimage.gaussian_filter(
            rng.random(labels_gt.shape), 1.0
        ).astype("float32")
        path = str(tmp_path / "wf.n5")
        f = file_reader(path)
        f.create_dataset("bnd", data=bnd, chunks=(4, 8, 8))
        config_dir = str(tmp_path / "configs_wf")
        tmp_folder = str(tmp_path / "tmp_wf")
        cfg.write_global_config(config_dir, {"block_shape": [4, 8, 8]})
        cfg.write_config(
            config_dir, "watershed",
            {"threshold": 0.6, "sigma_seeds": 1.0, "size_filter": 0},
        )
        # first run watershed-only to learn fragment ids, via a plain workflow
        wf = MulticutSegmentationWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="bnd",
            ws_path=path, ws_key="ws",
            output_path=path, output_key="seg_plain",
        )
        assert build([wf])
        store = file_reader(os.path.join(tmp_folder, "data.zarr"), "r")
        nodes = store["graph/nodes"][:]
        # mark one fragment for isolation
        marked = int(nodes[0])
        table = np.zeros(int(nodes.max()) + 1, dtype=np.uint32)
        table[marked] = 1
        label_path = str(tmp_path / "wf_labels.npy")
        np.save(label_path, table)

        tmp2 = str(tmp_path / "tmp_wf2")
        wf2 = MulticutSegmentationWorkflow(
            tmp2, config_dir,
            input_path=path, input_key="bnd",
            ws_path=path, ws_key="ws2",
            output_path=path, output_key="seg_iso",
            node_label_dict={"ignore": label_path},
        )
        assert build([wf2])
        ws = file_reader(path, "r")["ws2"][:]
        seg = file_reader(path, "r")["seg_iso"][:]
        # the marked fragment's segment id must not be shared by any other
        # fragment: all its edges were maximally repulsive
        seg_ids = np.unique(seg[ws == marked])
        assert seg_ids.size == 1
        others = seg[(ws != marked) & (ws > 0)]
        assert seg_ids[0] not in others
