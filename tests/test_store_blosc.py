"""Blosc codec support in the chunked store (VERDICT r4 missing item 2).

The zarr ecosystem's de-facto default chunk codec is blosc (zarr-python:
``Blosc(cname='lz4', clevel=5, shuffle=SHUFFLE)``); the reference reads such
volumes through z5py's bundled c-blosc (reference utils/volume_utils.py:21-22).
We bind the *system* libblosc (the identical library numcodecs wraps), so
bit-compatibility holds by construction; these tests additionally verify it
end-to-end by synthesizing stores exactly as zarr-python / n5-blosc lay them
out — metadata written by hand, chunks compressed by direct libblosc calls,
never through our own writer — and reading them back through ``file_reader``.
"""

import json
import os

import numpy as np
import pytest

from cluster_tools_tpu.utils import blosc
from cluster_tools_tpu.utils.store import file_reader

pytestmark = pytest.mark.skipif(
    not blosc.available(), reason="no system libblosc"
)


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _write_zarr_python_style(path, data, chunks, cname="lz4", shuffle=1):
    """Lay out a zarr v2 array byte-for-byte the way zarr-python does:
    canonical .zarray, one file per chunk, payload = blosc frame of the
    C-order chunk bytes (edge chunks padded to full shape with fill 0)."""
    os.makedirs(path)
    zarray = {
        "zarr_format": 2,
        "shape": list(data.shape),
        "chunks": list(chunks),
        "dtype": data.dtype.str,
        "compressor": {
            "id": "blosc", "cname": cname, "clevel": 5,
            "shuffle": shuffle, "blocksize": 0,
        },
        "fill_value": 0,
        "order": "C",
        "filters": None,
    }
    with open(os.path.join(path, ".zarray"), "w") as f:
        json.dump(zarray, f)
    grid = [range(-(-s // c)) for s, c in zip(data.shape, chunks)]
    for i in grid[0]:
        for j in grid[1]:
            for k in grid[2]:
                sel = tuple(
                    slice(g * c, min((g + 1) * c, s))
                    for g, c, s in zip((i, j, k), chunks, data.shape)
                )
                block = data[sel]
                full = np.zeros(chunks, dtype=data.dtype)
                full[tuple(slice(0, d) for d in block.shape)] = block
                payload = blosc.compress(
                    full.tobytes(), data.dtype.itemsize, cname=cname,
                    clevel=5, shuffle=shuffle,
                )
                with open(os.path.join(path, f"{i}.{j}.{k}"), "wb") as f:
                    f.write(payload)


def _write_n5_blosc_style(path, data, chunks):
    """n5 layout with blosc compression as z5/n5-blosc writes it: reversed
    dims in attributes.json, mode-0 big-endian chunk header, blosc frame."""
    import struct

    os.makedirs(path)
    attrs = {
        "dimensions": list(reversed(data.shape)),
        "blockSize": list(reversed(chunks)),
        "dataType": data.dtype.name,
        "compression": {
            "type": "blosc", "cname": "lz4", "clevel": 5,
            "shuffle": 1, "blocksize": 0, "nthreads": 1,
        },
    }
    with open(os.path.join(path, "attributes.json"), "w") as f:
        json.dump(attrs, f)
    be = {"uint32": ">u4", "float32": ">f4", "uint64": ">u8"}[data.dtype.name]
    grid = [range(-(-s // c)) for s, c in zip(data.shape, chunks)]
    for i in grid[0]:
        for j in grid[1]:
            for k in grid[2]:
                sel = tuple(
                    slice(g * c, min((g + 1) * c, s))
                    for g, c, s in zip((i, j, k), chunks, data.shape)
                )
                block = np.ascontiguousarray(data[sel]).astype(be)
                header = struct.pack(">HH", 0, 3) + struct.pack(
                    ">3I", *reversed(block.shape)
                )
                payload = blosc.compress(
                    block.tobytes(), block.dtype.itemsize, cname="lz4",
                    clevel=5, shuffle=1,
                )
                cdir = os.path.join(path, str(k), str(j))
                os.makedirs(cdir, exist_ok=True)
                with open(os.path.join(cdir, str(i)), "wb") as f:
                    f.write(header + payload)


@pytest.mark.parametrize("dtype", ["uint8", "uint32", "float32", "uint64"])
@pytest.mark.parametrize("cname", ["lz4", "blosclz", "zstd", "zlib"])
def test_zarr_python_chunk_reads_back_bitexact(tmp_path, rng, dtype, cname):
    data = (rng.random((13, 17, 9)) * 200).astype(dtype)
    path = str(tmp_path / "ext.zarr")
    _write_zarr_python_style(path, data, chunks=(8, 8, 8), cname=cname)
    with file_reader(path, "r") as f:
        ds = f["."] if hasattr(f, "__getitem__") else f
        got = ds[:]
    assert got.dtype == data.dtype
    np.testing.assert_array_equal(got, data)


def test_zarr_bitshuffle_reads_back(tmp_path, rng):
    data = (rng.random((10, 10, 10)) * 1000).astype(np.uint16)
    path = str(tmp_path / "bits.zarr")
    _write_zarr_python_style(path, data, chunks=(6, 6, 6), shuffle=2)
    with file_reader(path, "r") as f:
        np.testing.assert_array_equal(f["."][:], data)


@pytest.mark.parametrize("dtype", ["uint32", "float32"])
def test_n5_blosc_chunk_reads_back_bitexact(tmp_path, rng, dtype):
    data = (rng.random((11, 14, 9)) * 100).astype(dtype)
    path = str(tmp_path / "ext.n5")
    _write_n5_blosc_style(path, data, chunks=(8, 8, 8))
    with file_reader(path, "r") as f:
        np.testing.assert_array_equal(f["."][:], data)


@pytest.mark.parametrize("ext", ["zarr", "n5"])
def test_blosc_roundtrip_through_store(tmp_path, rng, ext):
    """Our own writer with compression='blosc' -> ecosystem-standard
    metadata + frames our reader (and any zarr/z5 impl) opens."""
    data = (rng.random((20, 33, 12)) * 255).astype(np.uint64)
    path = str(tmp_path / f"own.{ext}")
    with file_reader(path, "a") as f:
        f.create_dataset(
            "seg", data=data, chunks=(8, 16, 8), compression="blosc"
        )
    meta_name = ".zarray" if ext == "zarr" else "attributes.json"
    meta = json.load(open(os.path.join(path, "seg", meta_name)))
    comp = meta["compressor"] if ext == "zarr" else meta["compression"]
    assert comp["cname"] == "lz4" and comp["clevel"] == 5
    assert comp["shuffle"] == 1
    with file_reader(path, "r") as f:
        np.testing.assert_array_equal(f["seg"][:], data)
    # a raw chunk file really is a blosc frame (decompressible standalone)
    chunk_files = []
    for root, _, files in os.walk(os.path.join(path, "seg")):
        chunk_files += [
            os.path.join(root, x) for x in files
            if x not in (".zarray", "attributes.json")
        ]
    payload = open(chunk_files[0], "rb").read()
    if ext == "n5":
        payload = payload[16:]  # mode-0 header: 4 + 3*4 bytes
    assert len(blosc.decompress(payload)) > 0


def test_region_rmw_on_blosc_dataset(tmp_path, rng):
    """Partial-chunk read-modify-write through the blosc codec."""
    path = str(tmp_path / "rmw.zarr")
    with file_reader(path, "a") as f:
        ds = f.create_dataset(
            "x", shape=(32, 32, 32), dtype="float32", chunks=(16, 16, 16),
            compression="blosc",
        )
        patch = rng.random((10, 20, 7)).astype(np.float32)
        ds[5:15, 3:23, 11:18] = patch
    with file_reader(path, "r") as f:
        got = f["x"][5:15, 3:23, 11:18]
        np.testing.assert_array_equal(got, patch)
        assert float(f["x"][0, 0, 0]) == 0.0


def test_varlen_chunks_on_blosc_n5(tmp_path, rng):
    """Mode-1 (varlength) chunks must round-trip through the blosc codec —
    the paintera/label-multiset serializations use them."""
    path = str(tmp_path / "var.n5")
    with file_reader(path, "a") as f:
        ds = f.create_dataset(
            "m", shape=(16, 16, 16), dtype="uint64", chunks=(8, 8, 8),
            compression="blosc",
        )
        payload = (rng.random(37) * 1e6).astype(np.uint64)
        ds.write_chunk_varlen((0, 1, 0), payload)
    with file_reader(path, "r") as f:
        got = f["m"].read_chunk_varlen((0, 1, 0))
        np.testing.assert_array_equal(got, payload)


def test_blosc_create_dataset_validates_before_overwrite(tmp_path, monkeypatch):
    """A failing blosc spec must not have destroyed the existing array."""
    path = str(tmp_path / "keep.zarr")
    data = np.arange(64, dtype=np.uint32).reshape(4, 4, 4)
    with file_reader(path, "a") as f:
        f.create_dataset("x", data=data, compression="gzip")
    import cluster_tools_tpu.utils.blosc as bl
    monkeypatch.setattr(bl, "available", lambda: False)
    with file_reader(path, "a") as f:
        with pytest.raises(RuntimeError):
            f.create_dataset(
                "x", data=data, compression="blosc", exist_ok=True
            )
        np.testing.assert_array_equal(f["x"][:], data)  # still intact


def test_corrupt_blosc_chunk_raises(tmp_path, rng):
    data = np.arange(8 * 8 * 8, dtype=np.uint32).reshape(8, 8, 8)
    path = str(tmp_path / "bad.zarr")
    _write_zarr_python_style(path, data, chunks=(8, 8, 8))
    with open(os.path.join(path, "0.0.0"), "wb") as f:
        f.write(b"definitely-not-a-blosc-frame")
    with file_reader(path, "r") as f:
        with pytest.raises(ValueError):
            f["."][:]
