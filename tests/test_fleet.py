"""ctt-fleet: fault-tolerant multi-daemon serve fleet tests.

Covers the fleet hardening end to end:

  * fleet heartbeats + peer liveness: the 3 x promised-cadence dead rule,
    ``exiting`` fast exit, three-valued verdicts (no beat = unknown, NOT
    dead), torn ``daemon.<id>.json`` beats (``fleet.write`` chaos)
    degrading to mtime ageing;
  * peer failover: an orphan lease whose owner's beat proves it dead is
    expired at heartbeat staleness, not lease staleness — including a
    fabricated orphan from the claim-to-first-renewal window (the daemon
    id is stamped at claim time); no beat at all falls back to the slow
    rule;
  * retry budgets: a poison job burns exactly ``max_job_gens``
    generations, then parks as a quarantined failed result carrying every
    generation's lease stamp; between-generation backoff rides
    ``utils.retry.backoff_delay_s``;
  * fleet-consistent admission: k daemons over one state dir cannot
    jointly overshoot ``max_queue_depth`` or a tenant quota (the
    two-phase recount regression), and ``/healthz`` exports the decision
    inputs;
  * cross-host work stealing: the block-grain ``WorkQueue`` runs over an
    HTTP object store (conditional-PUT ``publish_once``), exactly-once
    under ``sched.claim`` stall chaos + seeded 503s;
  * zero-loss chaos gate (subprocess): two real daemons, mid-run SIGKILL
    — every job completes byte-identically and recovery is bounded by
    the heartbeat rule (not the 3 x lease_s window).
"""

import hashlib
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from objstub import StubObjectStore

from cluster_tools_tpu import faults
from cluster_tools_tpu.obs import metrics as obs_metrics
from cluster_tools_tpu.obs import trace as obs_trace
from cluster_tools_tpu.runtime.queue import (
    STALE_INTERVALS, WorkQueue, publish_once,
)
from cluster_tools_tpu.serve import (
    JobQueue, QuotaRejected, ServeClient, ServeDaemon,
)
from cluster_tools_tpu.serve.fleet import (
    FleetBeat, FleetView, beat_path, default_daemon_id, read_peers,
    scale_advice,
)
from cluster_tools_tpu.utils import file_reader

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _digest(root):
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def _sleep_vol_job(td, tag, sleep_s, tenant="default", priority=0):
    """A submission payload for a calibrated-cost job (the ctt-steal
    skewed-cost fixture task): one block, deterministic output
    (input * 2 + 1), every block costs ``sleep_s``."""
    path = os.path.join(td, f"{tag}.n5")
    if not os.path.exists(path):
        file_reader(path).create_dataset(
            "x", data=np.ones((2, 8, 8), dtype="float32"), chunks=(2, 8, 8)
        )
    return {
        "workflow": "bench_e2e_lib:SkewedCostTask",
        "kwargs": {
            "tmp_folder": os.path.join(td, f"tmp_{tag}"),
            "config_dir": os.path.join(td, f"configs_{tag}"),
            "input_path": path, "input_key": "x",
            "output_path": path, "output_key": "y",
        },
        "configs": {
            "global": {"block_shape": [2, 8, 8]},
            "skewed_cost": {
                "hot_z_end": 0, "base_s": float(sleep_s), "hot_s": 99.0,
            },
        },
        "tenant": tenant,
        "priority": priority,
    }


def _submit_kw(payload):
    return {
        "workflow": payload["workflow"],
        "kwargs": payload["kwargs"],
        "configs": payload["configs"],
        "tenant": payload["tenant"],
        "priority": payload["priority"],
    }


def _backdate(path, seconds):
    """Age a lease/beat file's wall stamp (and mtime) into the past —
    deterministic staleness without real sleeps."""
    rec = json.load(open(path))
    rec["wall"] = rec.get("wall", time.time()) - seconds
    with open(path, "w") as f:
        json.dump(rec, f)
    past = time.time() - seconds
    os.utime(path, (past, past))


@pytest.fixture
def traced(tmp_path):
    """Counters move only while tracing is on (the one ctt-obs switch)."""
    was_on = obs_trace.enabled()
    if not was_on:
        obs_trace.enable(str(tmp_path / "trace"), "fleet_unit",
                         export_env=False)
    try:
        yield obs_metrics
    finally:
        if not was_on:
            obs_trace.disable()


@pytest.fixture
def daemon_factory(tmp_path):
    """In-process daemons with tracing scoped to this test."""
    was_on = obs_trace.enabled()
    if not was_on:
        obs_trace.enable(str(tmp_path / "trace"), "fleet_test",
                         export_env=False)
    daemons = []

    def make(state_dir, **conf):
        d = ServeDaemon(str(state_dir), config=conf)
        d.start()
        daemons.append(d)
        return d

    yield make
    for d in daemons:
        d.request_drain()
        if d._httpd is not None:
            d._httpd.shutdown()
            d._httpd.server_close()
        for t in d._threads:
            if t.name.startswith("ctt-serve-exec"):
                t.join(timeout=60)
        d._fleet_beat.stop(final=True)
    if not was_on:
        obs_trace.disable()


# --------------------------------------------------------------------------
# fleet heartbeats + peer liveness


class TestFleetLiveness:
    def test_beat_publishes_and_carries_info(self, tmp_path):
        b = FleetBeat(str(tmp_path), "d1", interval_s=5.0,
                      info_fn=lambda: {"concurrency": 3, "queued": 2})
        b.beat()
        peers = read_peers(str(tmp_path))
        rec = peers["d1"]
        assert rec["id"] == "d1" and rec["pid"] == os.getpid()
        assert rec["interval_s"] == 5.0 and rec["seq"] == 0
        assert rec["concurrency"] == 3 and rec["queued"] == 2
        assert not rec["exiting"]
        b.beat()
        assert read_peers(str(tmp_path))["d1"]["seq"] == 1

    def test_three_valued_liveness(self, tmp_path):
        b = FleetBeat(str(tmp_path), "d1", interval_s=1.0)
        b.beat()
        view = FleetView(str(tmp_path), self_id="me", cache_ttl_s=0.0)
        # fresh beat: provably alive
        assert view.is_dead("d1") is False
        # no beat ever published: UNKNOWN, never "dead" — callers must
        # fall back to the slow lease-staleness rule
        assert view.is_dead("stranger") is None
        # a daemon never declares itself dead, whatever its beat says
        _backdate(beat_path(str(tmp_path), "d1"),
                  STALE_INTERVALS * 1.0 + 5.0)
        assert FleetView(str(tmp_path), self_id="d1").is_dead("d1") is False
        # aged past 3 x its PROMISED cadence: dead
        assert view.is_dead("d1") is True

    def test_exiting_beat_is_immediate_death(self, tmp_path):
        b = FleetBeat(str(tmp_path), "d1", interval_s=30.0)
        b.start()
        view = FleetView(str(tmp_path), self_id="me", cache_ttl_s=0.0)
        assert view.is_dead("d1") is False
        b.stop(final=True)  # terminal ``exiting`` stamp
        # dead within one read, no 3x-cadence ageing required
        assert view.is_dead("d1") is True
        assert "d1" not in view.live()

    def test_torn_beat_degrades_to_mtime_ageing(self, tmp_path):
        """``fleet.write`` chaos: a truncated daemon.<id>.json must not
        crash a reader NOR misdeclare the (fresh) writer dead — it ages
        from file mtime, the torn-lease convention."""
        b = FleetBeat(str(tmp_path), "d1", interval_s=1.0)
        faults.configure("fleet.write:torn:bytes=5;seed=1")
        try:
            b.beat()
        finally:
            faults.reset()
        raw = open(beat_path(str(tmp_path), "d1"), "rb").read()
        assert len(raw) == 5
        with pytest.raises(json.JSONDecodeError):
            json.loads(raw)
        assert read_peers(str(tmp_path))["d1"].get("torn") is True
        view = FleetView(str(tmp_path), self_id="me", cache_ttl_s=0.0)
        # fresh mtime: alive (the promised cadence is unreadable, so the
        # reader falls back to the ambient heartbeat default)
        assert view.is_dead("d1") is False
        past = time.time() - 3600.0
        os.utime(beat_path(str(tmp_path), "d1"), (past, past))
        assert view.is_dead("d1") is True

    def test_scale_advice(self, tmp_path):
        view = FleetView(str(tmp_path), cache_ttl_s=0.0)
        # backlog with no live capacity: spawn
        adv = scale_advice(str(tmp_path),
                           stats={"queued": 4, "running": 0}, view=view)
        assert adv["action"] == "spawn" and adv["capacity"] == 0
        # two idle daemons: drain one
        for i, conc in ((0, 2), (1, 2)):
            FleetBeat(str(tmp_path), f"d{i}", interval_s=5.0,
                      info_fn=lambda c=conc: {"concurrency": c}).beat()
        adv = scale_advice(str(tmp_path),
                           stats={"queued": 0, "running": 0}, view=view)
        assert adv["action"] == "drain" and adv["capacity"] == 4
        # backlog within capacity: hold
        adv = scale_advice(str(tmp_path),
                           stats={"queued": 3, "running": 4}, view=view)
        assert adv["action"] == "hold"
        # advice only — nothing was spawned or killed
        assert set(read_peers(str(tmp_path))) == {"d0", "d1"}


# --------------------------------------------------------------------------
# peer failover at job grain


class TestPeerFailover:
    def test_claim_stamps_daemon_id_at_claim_time(self, tmp_path):
        """The claim-to-first-renewal window: the very first lease write
        (the exclusive link itself) must carry the daemon id — a daemon
        SIGKILLed before its first renewal still leaves an attributable
        lease."""
        q = JobQueue(str(tmp_path / "jobs"), lease_s=30.0, daemon_id="dA")
        q.submit({"workflow": "W", "tenant": "t"})
        claim = q.claim_next()
        lease = json.load(open(claim.lease_path))
        assert lease["daemon"] == "dA" and lease["gen"] == 0

    def test_orphan_lease_expires_at_heartbeat_not_lease_staleness(
        self, tmp_path, traced
    ):
        """The tentpole latency contract: a dead daemon's lease (lease_s
        30 => 90s slow window) is reclaimed as soon as its beat proves it
        gone, and counts as serve.jobs_reclaimed."""
        state = str(tmp_path / "state")
        os.makedirs(state)
        # the ghost daemon beats once (cadence 1s), claims, and dies
        FleetBeat(state, "ghost", interval_s=1.0).beat()
        qg = JobQueue(os.path.join(state, "jobs"), lease_s=30.0,
                      daemon_id="ghost")
        jid = qg.submit({"workflow": "W", "tenant": "t"})
        dead_claim = qg.claim_next()
        assert dead_claim is not None
        # a peer sees a FRESH lease and a fresh beat: nothing to steal
        view = FleetView(state, self_id="peer", cache_ttl_s=0.0)
        qp = JobQueue(os.path.join(state, "jobs"), lease_s=30.0,
                      daemon_id="peer", fleet=view)
        assert qp.claim_next() is None
        # the ghost's beat ages past 3 x its cadence; the lease (aged 2s
        # past the tiny inter-generation backoff) is still DECADES inside
        # its own 90s staleness window
        _backdate(beat_path(state, "ghost"), STALE_INTERVALS * 1.0 + 2.0)
        _backdate(dead_claim.lease_path, 2.0)
        before = obs_metrics.snapshot()["counters"]
        takeover = qp.claim_next()
        assert takeover is not None and takeover.job_id == jid
        assert takeover.gen == 1
        after = obs_metrics.snapshot()["counters"]
        assert after.get("serve.jobs_reclaimed", 0) > before.get(
            "serve.jobs_reclaimed", 0
        )
        assert after.get("serve.leases_requeued", 0) > before.get(
            "serve.leases_requeued", 0
        )
        # the fast path never fires without the view: a fleet-blind peer
        # keeps honoring the lease window
        q_blind = JobQueue(os.path.join(state, "jobs"), lease_s=30.0,
                           daemon_id="blind")
        assert q_blind.claim_next() is None

    def test_no_beat_falls_back_to_slow_rule(self, tmp_path):
        """An owner that never published a beat (pre-fleet daemon) is
        UNKNOWN, not dead: its live lease must not be stolen."""
        state = str(tmp_path / "state")
        q = JobQueue(os.path.join(state, "jobs"), lease_s=30.0,
                     daemon_id="old-daemon")
        q.submit({"workflow": "W", "tenant": "t"})
        assert q.claim_next() is not None
        view = FleetView(state, self_id="peer", cache_ttl_s=0.0)
        qp = JobQueue(os.path.join(state, "jobs"), lease_s=30.0,
                      daemon_id="peer", fleet=view)
        assert qp.claim_next() is None  # fresh lease, unknown owner


# --------------------------------------------------------------------------
# retry budgets + poison-job quarantine


class TestRetryBudget:
    def test_quarantine_after_exactly_max_job_gens(self, tmp_path, traced):
        q = JobQueue(str(tmp_path / "jobs"), lease_s=0.5, daemon_id="d1",
                     max_job_gens=3)
        jid = q.submit({"workflow": "W", "tenant": "acme"})
        # three generations claim it and "die" (their leases go stale)
        for expected_gen in range(3):
            claim = q.claim_next()
            assert claim is not None and claim.gen == expected_gen
            _backdate(claim.lease_path, 3600.0)
        before = obs_metrics.snapshot()["counters"]
        # the would-be gen 3 claim quarantines instead of executing
        assert q.claim_next() is None
        after = obs_metrics.snapshot()["counters"]
        assert after.get("serve.jobs_quarantined", 0) > before.get(
            "serve.jobs_quarantined", 0
        )
        st = q.get(jid)
        assert st["state"] == "failed"
        res = st["result"]
        assert res["quarantined"] is True and res["ok"] is False
        assert res["gen"] == 3 and res["tenant"] == "acme"
        assert "retry budget" in res["error"]
        # the failure log carries EVERY generation's last lease stamp
        assert [e["gen"] for e in res["failure_log"]] == [0, 1, 2]
        assert all(e["daemon"] == "d1" for e in res["failure_log"])
        # quarantine parks the job, it does not take down the queue: a
        # fresh submission still claims and completes normally
        j2 = q.submit({"workflow": "W", "tenant": "acme"})
        c2 = q.claim_next()
        assert c2 is not None and c2.job_id == j2
        assert q.complete(c2, {"ok": True, "seconds": 0.0})
        # first-writer-wins: re-scanning never duplicates the quarantine
        assert q.claim_next() is None
        assert q.get(jid)["result"]["failure_log"] == res["failure_log"]

    def test_max_job_gens_zero_disables_budget(self, tmp_path):
        q = JobQueue(str(tmp_path / "jobs"), lease_s=0.5, daemon_id="d1",
                     max_job_gens=0)
        q.submit({"workflow": "W", "tenant": "t"})
        for expected_gen in range(6):  # far past the default budget
            claim = q.claim_next()
            assert claim is not None and claim.gen == expected_gen
            _backdate(claim.lease_path, 3600.0)

    def test_generation_backoff_gates_takeover(self, tmp_path, monkeypatch):
        """Between generations the queue waits out backoff_delay_s(gen):
        an expired-but-recent lease is in backoff, not claimable — the
        decelerating burn for poison jobs."""
        monkeypatch.setenv("CTT_IO_BACKOFF_BASE_S", "30.0")
        monkeypatch.setenv("CTT_IO_BACKOFF_MAX_S", "120.0")
        q = JobQueue(str(tmp_path / "jobs"), lease_s=0.5, daemon_id="d1")
        jid = q.submit({"workflow": "W", "tenant": "t"})
        claim = q.claim_next()
        assert claim.gen == 0
        # stale (age 5s > 3 x 0.5s) but inside backoff_delay_s(0) = 30s
        _backdate(claim.lease_path, 5.0)
        assert q.claim_next() is None
        assert q.get(jid)["state"] == "queued"  # expired, awaiting backoff
        # past the backoff: claimable at gen 1
        _backdate(claim.lease_path, 3600.0)
        takeover = q.claim_next()
        assert takeover is not None and takeover.gen == 1


# --------------------------------------------------------------------------
# fleet-consistent admission (the k-daemon overshoot regression)


class TestFleetAdmission:
    def _burst(self, clients, payloads):
        """Submit payloads concurrently round-robin over clients;
        returns (accepted job ids, rejection reasons)."""
        accepted, rejected = [], []
        lock = threading.Lock()

        def one(i, payload):
            try:
                jid = clients[i % len(clients)].submit(**_submit_kw(payload))
                with lock:
                    accepted.append(jid)
            except QuotaRejected as e:
                with lock:
                    rejected.append(str(e))

        threads = [
            threading.Thread(target=one, args=(i, p))
            for i, p in enumerate(payloads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        return accepted, rejected

    def test_k_daemons_cannot_overshoot_queue_depth(
        self, tmp_path, daemon_factory
    ):
        """The regression the shared-dir recount exists for: before the
        two-phase admit, each daemon's private check-then-act let k
        daemons admit up to k x max_queue_depth together."""
        state = tmp_path / "state"
        daemon_factory(state, max_queue_depth=3, tenant_quota=100)
        daemon_factory(state, max_queue_depth=3, tenant_quota=100)
        clients = [ServeClient(state_dir=str(state))]
        # target both daemons explicitly (serve.json is last-writer)
        td = str(tmp_path)
        payloads = [
            _sleep_vol_job(td, f"ov{i}", 3.0, tenant=f"t{i}")
            for i in range(8)
        ]
        peers = read_peers(str(state))
        assert len(peers) == 2, peers
        accepted, rejected = self._burst(clients, payloads)
        assert len(accepted) == 3, (accepted, rejected)
        assert len(rejected) == 5
        assert all("queue full" in r for r in rejected)
        # zero loss on the admitted side: each runs to a real result
        for jid in accepted:
            st = clients[0].wait(jid, timeout_s=180)
            assert st["result"]["ok"]

    def test_tenant_quota_holds_fleet_wide(self, tmp_path, daemon_factory):
        state = tmp_path / "state"
        d1 = daemon_factory(state, max_queue_depth=100, tenant_quota=2)
        d2 = daemon_factory(state, max_queue_depth=100, tenant_quota=2)
        td = str(tmp_path)
        c1 = ServeClient(endpoint=f"http://127.0.0.1:{d1.port}",
                         token=d1.token)
        c2 = ServeClient(endpoint=f"http://127.0.0.1:{d2.port}",
                         token=d2.token)
        payloads = [
            _sleep_vol_job(td, f"tq{i}", 3.0, tenant="noisy")
            for i in range(6)
        ]
        accepted, rejected = self._burst([c1, c2], payloads)
        # 2 daemons x quota 2 would be 4 under per-daemon admission;
        # fleet-wide it is exactly the one quota
        assert len(accepted) == 2, (accepted, rejected)
        assert all("quota" in r for r in rejected)
        for jid in accepted:
            st = c1.wait(jid, timeout_s=180)
            assert st["result"]["ok"]

    def test_healthz_exports_admission_inputs_and_fleet(
        self, tmp_path, daemon_factory
    ):
        state = tmp_path / "state"
        d = daemon_factory(state, max_queue_depth=7, tenant_quota=4,
                           daemon_id="hz-daemon")
        client = ServeClient(state_dir=str(state))
        jid = client.submit(**_submit_kw(
            _sleep_vol_job(str(tmp_path), "hz", 1.5, tenant="acme")))
        hz = client.healthz()
        adm = hz["admission"]
        assert adm["max_queue_depth"] == 7 and adm["tenant_quota"] == 4
        assert adm["in_flight"] == 1 and adm["per_tenant"] == {"acme": 1}
        assert "queued" in adm
        fl = hz["fleet"]
        assert fl["id"] == "hz-daemon" and hz["daemon_id"] == "hz-daemon"
        assert fl["peers"] == 1 and fl["daemons"] == ["hz-daemon"]
        assert fl["scale_advice"]["action"] in ("spawn", "drain", "hold")
        assert d.daemon_id == "hz-daemon"
        client.wait(jid, timeout_s=180)

    def test_late_joining_daemon_drains_backlog(
        self, tmp_path, daemon_factory
    ):
        """The elastic story: one daemon saturates, scale_advice says
        spawn, a late joiner over the same state dir picks up queued
        work with no handshake."""
        state = tmp_path / "state"
        td = str(tmp_path)
        d1 = daemon_factory(state, daemon_id="first", tenant_quota=100)
        client = ServeClient(endpoint=f"http://127.0.0.1:{d1.port}",
                             token=d1.token)
        blocker = client.submit(**_submit_kw(
            _sleep_vol_job(td, "el_block", 3.0)))
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if client.status(blocker)["state"] == "running":
                break
            time.sleep(0.05)
        queued = [
            client.submit(**_submit_kw(
                _sleep_vol_job(td, f"el{i}", 0.3, tenant=f"t{i}")))
            for i in range(4)
        ]
        adv = client.fleet()["scale_advice"]
        assert adv["action"] == "spawn", adv  # backlog 4 > capacity 1
        d2 = daemon_factory(state, daemon_id="late", tenant_quota=100)
        for jid in [blocker] + queued:
            st = client.wait(jid, timeout_s=180)
            assert st["result"]["ok"]
        q = JobQueue(str(state / "jobs"))
        owners = {q.get(j)["result"]["daemon"] for j in queued}
        assert "late" in owners, (
            f"the late joiner never executed anything: {owners}"
        )
        assert d2.daemon_id == "late"
        # drained: the advice stops asking for capacity
        adv = client.fleet()["scale_advice"]
        assert adv["action"] in ("drain", "hold"), adv


# --------------------------------------------------------------------------
# cross-host work stealing: WorkQueue over an object store


class TestWorkQueueObjectStore:
    def test_publish_once_is_create_only_put(self, tmp_path):
        with StubObjectStore(str(tmp_path / "root")) as srv:
            key = f"{srv.url}/q/lease.0.g0.json"
            assert publish_once(key, b"first") is True
            assert publish_once(key, b"second") is False  # 412, lost race
            from cluster_tools_tpu.utils.store_backend import backend_for
            assert backend_for(key).read_bytes(key) == b"first"

    def test_exactly_once_over_object_store_with_chaos(self, tmp_path):
        """Two WorkQueue handles over ONE remote queue dir, seeded 503s
        on the store AND injected sched.claim stalls widening the
        selection->PUT window: conditional-PUT exclusivity must hand
        every item to exactly one owner."""
        with StubObjectStore(str(tmp_path / "root"), fail_rate=0.05,
                             seed=7) as srv:
            qdir = f"{srv.url}/jobdir_queue"
            q = WorkQueue.create(qdir, "t", list(range(12)), 2, 5.0,
                                 duplicate=False)
            assert q.task == "t"
            workers = [WorkQueue(qdir), WorkQueue(qdir)]
            owned = {0: [], 1: []}
            faults.configure("sched.claim:stall:p=0.4,s=0.01;seed=3")
            try:
                def drain_one(w):
                    wq = workers[w]
                    while True:
                        claim = wq.claim(job_id=w)
                        if claim is None:
                            break
                        owned[w].append(claim.item)
                        wq.complete(claim, claim.block_ids, [], {}, 0.001,
                                    job_id=w)

                threads = [
                    threading.Thread(target=drain_one, args=(w,))
                    for w in (0, 1)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=120)
            finally:
                faults.reset()
            assert not (set(owned[0]) & set(owned[1]))  # exclusive claims
            assert sorted(owned[0] + owned[1]) == list(range(len(q.items)))
            done, failed, errors, _ = q.aggregate()
            assert failed == [] and errors == {}
            assert sorted(done) == sorted(
                b for item in q.items for b in item
            )
            # every lease is gen 0: nothing was lost OR doubly executed
            names = workers[0]._backend.listdir(qdir)
            leases = [n for n in names if n.startswith("lease.")]
            assert len(leases) == len(q.items)
            assert all(n.endswith(".g0.json") for n in leases)

    def test_steal_queue_url_routes_queue_to_store(self, tmp_path):
        """The config seam cluster_executor rides: steal_queue_url puts
        the queue dir on the object store, named after the job dir."""
        from cluster_tools_tpu.runtime.cluster_executor import (
            ClusterExecutor,
        )

        with StubObjectStore(str(tmp_path / "root")) as srv:
            job_dir = str(tmp_path / "tmp_x" / "myjob")
            os.makedirs(job_dir)

            class _Task:
                identifier = "t"

            conf = {"steal_queue_url": srv.url}
            # _create_queue never touches self — exercise the seam
            # without standing up a scheduler
            q = ClusterExecutor._create_queue(
                None, _Task(), job_dir, list(range(4)), conf, 2)
            assert q.dir == f"{srv.url}/myjob_queue"
            assert q.claim(job_id=0) is not None
            # stale re-create rebuilds the remote dir (fresh leases)
            q2 = ClusterExecutor._create_queue(
                None, _Task(), job_dir, list(range(4)), conf, 2)
            assert q2.claim(job_id=0) is not None


# --------------------------------------------------------------------------
# chaos gate: SIGKILL a daemon mid-run, zero loss, fast recovery


def _spawn_daemon(state_dir, daemon_id, extra_env=None, args=()):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": "", "CTT_HEARTBEAT_S": "0.2"}
    env.pop("CTT_TRACE_DIR", None)
    env.pop("CTT_RUN_ID", None)
    if extra_env:
        env.update(extra_env)
    proc = subprocess.Popen(
        [sys.executable, "-m", "cluster_tools_tpu.serve",
         "--state-dir", str(state_dir), "--lease-s", "5",
         "--daemon-id", daemon_id, *args],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    # line 1 is the listening banner, line 2 the endpoint JSON — per-
    # daemon discovery (serve.json in a shared state dir is last-writer)
    proc.stdout.readline()
    ep_line = proc.stdout.readline()
    if not ep_line:
        raise AssertionError(
            f"daemon {daemon_id} died at startup:\n{proc.stderr.read()}"
        )
    ep = json.loads(ep_line)
    client = ServeClient(endpoint=f"http://{ep['host']}:{ep['port']}",
                         token=ep["token"])
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            client.healthz()
            return proc, client, ep
        except Exception:
            if proc.poll() is not None:
                raise AssertionError(
                    f"daemon {daemon_id} died:\n{proc.stderr.read()}"
                ) from None
            time.sleep(0.1)
    proc.kill()
    raise AssertionError(f"daemon {daemon_id} never became healthy")


def _read_beat(state_dir, daemon_id):
    try:
        return json.load(open(beat_path(str(state_dir), daemon_id)))
    except (OSError, json.JSONDecodeError):
        return {}


@pytest.mark.timeout(300)
class TestFleetChaos:
    def test_sigkill_mid_run_zero_loss_byte_identical(self, tmp_path):
        """The acceptance gate: two real daemons, a 6-job burst, SIGKILL
        one mid-job.  Every job publishes an ok result, the recovered
        job re-executes byte-identically, and recovery latency is
        bounded by the heartbeat rule (3 x 0.2s cadence) — NOT the
        15s lease-staleness window (--lease-s 5)."""
        state = tmp_path / "state"
        td = str(tmp_path)
        proc_a = proc_b = None
        try:
            proc_a, client_a, _ = _spawn_daemon(state, "dA")
            proc_b, client_b, _ = _spawn_daemon(state, "dB")
            jobs = []
            for i in range(6):
                cl = client_a if i % 2 == 0 else client_b
                jobs.append(cl.submit(**_submit_kw(
                    _sleep_vol_job(td, f"k{i}", 2.0, tenant=f"t{i}"))))
            # wait until dA's own beat reports a job in flight ...
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if _read_beat(state, "dA").get("running_jobs", 0) >= 1:
                    break
                time.sleep(0.05)
            assert _read_beat(state, "dA").get("running_jobs", 0) >= 1
            # ... and SIGKILL it mid-job: no drain, no exiting beat
            proc_a.kill()
            proc_a.wait(timeout=30)
            t_kill = time.time()
            # zero loss: every job reaches an ok result via the survivor
            for jid in jobs:
                st = client_b.wait(jid, timeout_s=180)
                assert st["result"]["ok"], st
            q = JobQueue(str(state / "jobs"), lease_s=5.0)
            results = [q.get(j)["result"] for j in jobs]
            requeued = [r for r in results if r["gen"] > 0]
            assert requeued, "the killed daemon's job never requeued"
            for r in requeued:
                assert r["daemon"] == "dB"
                # heartbeat-bounded recovery: detect at ~0.6s, re-execute
                # 2s — far inside the 15s the lease rule alone would take
                assert r["finished_wall"] - t_kill < 12.0, r
            # byte-identical recovery: all 6 outputs (same input) match,
            # including the re-executed one
            digests = {
                _digest(os.path.join(td, f"k{i}.n5", "y"))
                for i in range(6)
            }
            assert len(digests) == 1, digests
            # the survivor's ledger shows the fast-path reclaim
            text = client_b.metrics_text()
            vals = {
                ln.split(" ")[0]: float(ln.split(" ")[1])
                for ln in text.splitlines()
                if ln and not ln.startswith("#") and " " in ln
            }
            assert vals.get("ctt_serve_jobs_reclaimed_total", 0) >= 1
            assert vals.get("ctt_serve_jobs_quarantined_total", 0) == 0
        finally:
            for proc in (proc_a, proc_b):
                if proc is not None and proc.poll() is None:
                    proc.kill()
                    proc.wait(timeout=30)

    @pytest.mark.slow
    @pytest.mark.timeout(600)
    def test_poison_job_quarantined_across_respawns(self, tmp_path):
        """A job that kills every daemon that executes it (CTT_FAULTS
        executor kill) burns exactly max_job_gens generations across
        respawned daemons, then parks as quarantined — and the next
        (healthy) daemon keeps serving other work."""
        state = tmp_path / "state"
        td = str(tmp_path)
        poison_env = {"CTT_FAULTS": "executor.block:kill:once;seed=1"}
        gens_args = ("--max-job-gens", "2")
        proc = None
        try:
            proc, client, _ = _spawn_daemon(
                state, "p0", extra_env=poison_env, args=gens_args)
            jid = client.submit(**_submit_kw(
                _sleep_vol_job(td, "poison", 0.01)))
            proc.wait(timeout=120)  # gen 0 kills the daemon
            proc, client, _ = _spawn_daemon(
                state, "p1", extra_env=poison_env, args=gens_args)
            proc.wait(timeout=120)  # gen 1 kills its successor too
            # budget burned: a healthy daemon quarantines instead of dying
            proc, client, _ = _spawn_daemon(state, "p2", args=gens_args)
            deadline = time.monotonic() + 120
            res = None
            while time.monotonic() < deadline:
                st = client.status(jid)
                if st["state"] == "failed":
                    res = st["result"]
                    break
                time.sleep(0.2)
            assert res is not None, "poison job never quarantined"
            assert res["quarantined"] is True
            assert [e["gen"] for e in res["failure_log"]] == [0, 1]
            assert {e["daemon"] for e in res["failure_log"]} == {"p0", "p1"}
            # the daemon that quarantined is alive and still serves
            st = client.submit(**_submit_kw(
                _sleep_vol_job(td, "healthy", 0.01)))
            assert client.wait(st, timeout_s=180)["result"]["ok"]
            text = client.metrics_text()
            vals = {
                ln.split(" ")[0]: float(ln.split(" ")[1])
                for ln in text.splitlines()
                if ln and not ln.startswith("#") and " " in ln
            }
            assert vals.get("ctt_serve_jobs_quarantined_total", 0) >= 1
        finally:
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
