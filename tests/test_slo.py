"""ctt-slo: latency histograms, job journeys, fleet rollup, SLO gate.

Covers the request-grain observability contract:
  * histogram bucket placement + Prometheus-style quantile math
    (interpolation inside the crossing bucket, +Inf clamp, empty=None);
  * the exactness theorem the subsystem stands on: merging two
    daemons' histograms is bit-identical to one process observing the
    union — in memory, across REAL processes via ``hist.p*.json``, and
    at the ``snap.<daemon>.json`` fleet grain;
  * ``obs journey`` reconstructing a SIGKILL-failover timeline (gen 0
    owner died, gen 1 finished) purely from fabricated state-dir
    records — no live daemon;
  * the ``obs slo`` CLI exit-code contract (0 met / 1 no data /
    4 violated under --fail-on-violation / 2 malformed spec);
  * ``obs fleet`` emitting parser-grade OpenMetrics with summed
    counters and exact histogram families (foreign edges -> exit 2);
  * the ``obs watch`` ``lat:`` line appearing exactly when a histogram
    snapshot exists (runs without one stay byte-identical).
"""

import json
import os
import subprocess
import sys

import pytest

from cluster_tools_tpu.obs import hist, metrics, trace
from cluster_tools_tpu.obs import journey as journey_mod
from cluster_tools_tpu.obs import slo as slo_mod
from cluster_tools_tpu.obs.__main__ import main as obs_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def traced(tmp_path):
    """Enable tracing (histograms gate on it) for one test."""
    metrics.reset()
    hist.reset()
    run_id = trace.enable(str(tmp_path / "trace"), "t_slo",
                          export_env=False)
    yield os.path.join(str(tmp_path / "trace"), run_id)
    trace.disable()
    hist.reset()
    metrics.reset()


def _snap_of(values_by_series):
    """Build a histogram snapshot through the real observe path."""
    hist.reset()
    for (name, labels), values in values_by_series.items():
        for v in values:
            hist.observe(name, v, **dict(labels))
    snap = hist.snapshot()
    hist.reset()
    return snap


# exact under float addition in any order (powers of two), spanning
# several buckets including the +Inf overflow
_VALS_A = [0.5, 1.5, 0.25, 0.000001, 128.0]
_VALS_B = [2.0, 0.125, 4.0, 0.5, 0.5]


# --------------------------------------------------------------------------
# quantile math


def test_observe_places_buckets_and_counts(traced):
    hist.observe("serve.latency.e2e", 0.3, tenant="a")
    hist.observe("serve.latency.e2e", 0.3, tenant="a")
    hist.observe("serve.latency.e2e", 100.0, tenant="a")  # > 64 s: +Inf
    snap = hist.snapshot()
    (s,) = snap["hists"]
    assert s["name"] == "serve.latency.e2e"
    assert s["labels"] == {"tenant": "a"}
    assert s["count"] == 3
    assert s["sum"] == pytest.approx(100.6)
    assert sum(s["buckets"]) == 3
    assert s["buckets"][-1] == 1  # the overflow observation
    # 0.3 lands in the (0.25, 0.5] bucket under cumulative-le semantics
    idx = list(hist.EDGES).index(0.5)
    assert s["buckets"][idx] == 2


def test_quantile_interpolates_inside_crossing_bucket():
    buckets = [0] * (len(hist.EDGES) + 1)
    idx = list(hist.EDGES).index(2.0)  # bucket spans (1.0, 2.0]
    buckets[idx] = 100
    assert hist.quantile(buckets, 0.5) == pytest.approx(1.5)
    assert hist.quantile(buckets, 0.99) == pytest.approx(1.99)


def test_quantile_empty_and_overflow_clamp():
    assert hist.quantile([0] * (len(hist.EDGES) + 1), 0.99) is None
    only_inf = [0] * (len(hist.EDGES) + 1)
    only_inf[-1] = 10
    assert hist.quantile(only_inf, 0.99) == hist.EDGES[-1]


# --------------------------------------------------------------------------
# the exactness theorem: fleet merge == single process


def test_merge_two_snapshots_equals_single_process(traced):
    series = ("serve.latency.e2e", (("tenant", "a"), ("priority", "5")))
    snap_a = _snap_of({series: _VALS_A})
    snap_b = _snap_of({series: _VALS_B})
    single = _snap_of({series: _VALS_A + _VALS_B})
    merged = hist.merge_snapshots([snap_a, snap_b])
    assert merged == single  # buckets, sums, counts — bit-identical


def test_merge_rejects_foreign_edges():
    with pytest.raises(ValueError, match="foreign bucket edges"):
        hist.merge_into({}, {"edges": [1.0, 2.0, 3.0], "hists": []})


def test_two_real_processes_flush_merge_exactly(tmp_path, traced):
    """Two REAL processes flush hist.p<pid>.json into one run dir; the
    cross-process merge equals a single process observing the union."""
    run_dir = str(tmp_path / "run")
    prog = (
        "import json, sys\n"
        "from cluster_tools_tpu.obs import hist, trace\n"
        "trace.enable(sys.argv[1], 'merged', export_env=False)\n"
        "for v in json.loads(sys.argv[2]):\n"
        "    hist.observe('serve.latency.e2e', v, tenant='a')\n"
        "hist.flush()\n"
    )
    for vals in (_VALS_A, _VALS_B):
        r = subprocess.run(
            [sys.executable, "-c", prog, str(tmp_path), json.dumps(vals)],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert r.returncode == 0, r.stderr
    run_dir = os.path.join(str(tmp_path), "merged")
    files = [n for n in os.listdir(run_dir)
             if n.startswith(hist.HIST_FILE_PREFIX)]
    assert len(files) == 2, files  # one snapshot per pid
    merged = hist.load_run_hists(run_dir)
    single = _snap_of({
        ("serve.latency.e2e", (("tenant", "a"),)): _VALS_A + _VALS_B,
    })
    assert merged == single


def _write_snap(state_dir, daemon, counters, hists_snap, gauges=None):
    os.makedirs(state_dir, exist_ok=True)
    rec = {"schema": 1, "daemon": daemon, "pid": 1, "wall": 0.0,
           "counters": counters, "gauges": gauges or {},
           "hists": hists_snap}
    with open(os.path.join(state_dir, f"snap.{daemon}.json"), "w") as f:
        json.dump(rec, f)


def test_fleet_merge_equals_single_process(tmp_path, traced):
    """The acceptance theorem at the snap.<daemon>.json grain: two
    daemons' snapshots merge to exactly the single-process histogram,
    with counters summed and gauges last-writer deterministic."""
    sd = str(tmp_path / "state")
    key = ("serve.latency.e2e", (("priority", "0"), ("tenant", "a")))
    _write_snap(sd, "m0", {"serve.jobs_done": 3}, _snap_of({key: _VALS_A}),
                gauges={"serve.peers": 1})
    _write_snap(sd, "m1", {"serve.jobs_done": 4}, _snap_of({key: _VALS_B}),
                gauges={"serve.peers": 2})
    merged = slo_mod.load_fleet(sd)
    assert merged["daemons"] == ["m0", "m1"]
    assert merged["counters"] == {"serve.jobs_done": 7.0}
    assert merged["gauges"] == {"serve.peers": 2}  # sorted last writer
    single = _snap_of({key: _VALS_A + _VALS_B})
    assert merged["hists"] == single


# --------------------------------------------------------------------------
# journey: the SIGKILL-failover timeline, purely from disk


def _write_failover_state(sd):
    """gen 0 owner SIGKILLed after claiming; gen 1 claims, rides a
    microbatch window, and publishes — the acceptance scenario."""
    os.makedirs(sd, exist_ok=True)

    def put(name, rec):
        with open(os.path.join(sd, name), "w") as f:
            json.dump(rec, f)

    put("job.j000001.json", {
        "id": "j000001", "seq": 1, "submit_wall": 1000.0,
        "tenant": "acme", "priority": 5, "workflow": "event_batch",
    })
    put("admit.j000001.json", {"id": "j000001", "wall": 1000.01,
                               "daemon": "m0"})
    put("lease.j000001.g0.json", {"job": "j000001", "gen": 0,
                                  "daemon": "m0", "claim_wall": 1000.2})
    put("lease.j000001.g1.json", {"job": "j000001", "gen": 1,
                                  "daemon": "m1", "claim_wall": 1001.5,
                                  "dispatch_wall": 1001.6})
    put("result.j000001.json", {
        "id": "j000001", "ok": True, "gen": 1, "daemon": "m1",
        "claimed_wall": 1001.5, "dispatch_wall": 1001.6,
        "seconds": 0.5, "published_wall": 1002.13,
        "finished_wall": 1002.13,
        "microbatch": {"jobs": 4, "index": 2},
    })


def test_journey_reconstructs_sigkill_failover(tmp_path):
    sd = str(tmp_path / "state")
    _write_failover_state(sd)
    j = journey_mod.load_journey(sd, "j000001")
    assert j is not None and j["state"] == "done"

    outcomes = {g["gen"]: g["outcome"] for g in j["generations"]}
    assert outcomes[0] == "expired (owner presumed dead)"
    assert outcomes[1] == "won"

    phases = j["phases"]
    assert phases["admission"] == pytest.approx(0.01, abs=1e-9)
    assert phases["queue_wait"] == pytest.approx(1.49, abs=1e-9)
    assert phases["window_wait"] == pytest.approx(0.1, abs=1e-9)
    assert phases["execution"] == pytest.approx(0.5)
    assert phases["publish"] == pytest.approx(0.03, abs=1e-9)
    assert phases["e2e"] == pytest.approx(2.13, abs=1e-9)

    text = journey_mod.format_journey(j)
    for needle in ("gen 0", "expired (owner presumed dead)", "gen 1",
                   "won", "microbatch: rode a 4-job stacked dispatch",
                   "admission", "queue_wait", "window_wait", "execution",
                   "publish", "e2e"):
        assert needle in text, (needle, text)


def test_journey_resolves_jobs_subdir(tmp_path):
    sd = str(tmp_path / "state")
    _write_failover_state(os.path.join(sd, "jobs"))
    j = journey_mod.load_journey(sd, "j000001")
    assert j is not None and j["phases"]["e2e"] > 0


def test_journey_quarantine_backfills_torn_lease(tmp_path):
    sd = str(tmp_path / "state")
    os.makedirs(sd)
    with open(os.path.join(sd, "job.j000002.json"), "w") as f:
        json.dump({"id": "j000002", "submit_wall": 1000.0}, f)
    # gen 0's lease file was torn by the death that burned it — the
    # quarantine verdict's failure_log is the durable record
    with open(os.path.join(sd, "result.j000002.json"), "w") as f:
        json.dump({
            "id": "j000002", "quarantined": True, "ok": False,
            "failure_log": [
                {"gen": 0, "daemon": "m0", "claim_wall": 1000.2},
                {"gen": 1, "daemon": "m1", "claim_wall": 1001.0},
            ],
        }, f)
    j = journey_mod.load_journey(sd, "j000002")
    assert j["state"] == "quarantined"
    assert [g["daemon"] for g in j["generations"]] == ["m0", "m1"]
    assert all(g["outcome"] == "died (burned a generation)"
               for g in j["generations"])
    assert j["phases"] == {}  # no executed result: no phase breakdown


def test_journey_cli_missing_job_exits_one(tmp_path, capsys):
    assert obs_main(["journey", str(tmp_path), "j000042"]) == 1
    assert "no job j000042" in capsys.readouterr().err


# --------------------------------------------------------------------------
# slo gate: exit-code contract


def _latency_state(tmp_path):
    sd = str(tmp_path / "state")
    key = ("serve.latency.e2e", (("priority", "5"), ("tenant", "a")))
    _write_snap(sd, "m0", {}, _snap_of({key: [0.5, 0.5, 1.5, 0.25]}))
    return sd


def test_slo_met_exits_zero(tmp_path, traced, capsys):
    sd = _latency_state(tmp_path)
    rc = obs_main(["slo", sd, "--objective", "e2e_p99_s=300",
                   "--fail-on-violation"])
    assert rc == 0
    assert "MET" in capsys.readouterr().out


def test_slo_violated_exits_four_only_with_flag(tmp_path, traced, capsys):
    sd = _latency_state(tmp_path)
    spec = "e2e_p99_s=0.000001@tenant=a"
    assert obs_main(["slo", sd, "--objective", spec,
                     "--fail-on-violation"]) == 4
    assert "VIOLATED" in capsys.readouterr().out
    # without the flag a violation reports but does not gate
    assert obs_main(["slo", sd, "--objective", spec]) == 0


def test_slo_no_matching_data_exits_one(tmp_path, traced, capsys):
    sd = _latency_state(tmp_path)
    assert obs_main(["slo", sd, "--objective", "admission_p50_s=1"]) == 1
    assert "NO DATA" in capsys.readouterr().out


def test_slo_bad_spec_exits_two(tmp_path, traced, capsys):
    sd = _latency_state(tmp_path)
    assert obs_main(["slo", sd, "--objective", "p99=2.0"]) == 2
    assert "bad objective" in capsys.readouterr().err


def test_parse_objective_grammar():
    obj = slo_mod.parse_objective("e2e_p999_s=2.5@priority=5,tenant=a")
    assert obj["phase"] == "e2e"
    assert obj["pname"] == "p999"
    assert obj["quantile"] == pytest.approx(0.999)
    assert obj["threshold_s"] == 2.5
    assert obj["labels"] == {"priority": "5", "tenant": "a"}
    for bad in ("e2e_p99_s", "nope_p99_s=1", "e2e_p0_s=1", "e2e_p99_s=x"):
        with pytest.raises(ValueError):
            slo_mod.parse_objective(bad)


def test_slo_label_constraint_selects_series(tmp_path, traced):
    sd = str(tmp_path / "state")
    fast = ("serve.latency.e2e", (("priority", "5"),))
    slow = ("serve.latency.e2e", (("priority", "0"),))
    _write_snap(sd, "m0", {}, _snap_of({fast: [0.25] * 10,
                                        slow: [32.0] * 10}))
    rows = slo_mod.evaluate(slo_mod.load_hists_any(sd), [
        slo_mod.parse_objective("e2e_p50_s=1.0@priority=5"),
        slo_mod.parse_objective("e2e_p50_s=1.0@priority=0"),
    ])
    assert [r["status"] for r in rows] == ["met", "violated"]


# --------------------------------------------------------------------------
# fleet exposition


def test_fleet_cli_parses_and_sums(tmp_path, traced, capsys):
    sd = str(tmp_path / "state")
    key = ("serve.latency.e2e", (("tenant", "a"),))
    _write_snap(sd, "m0", {"serve.jobs_done": 3}, _snap_of({key: _VALS_A}))
    _write_snap(sd, "m1", {"serve.jobs_done": 4}, _snap_of({key: _VALS_B}))
    assert obs_main(["fleet", sd]) == 0
    text = capsys.readouterr().out
    assert text.endswith("# EOF\n")
    assert "ctt_serve_jobs_done_total 7.0" in text
    assert "ctt_fleet_daemons 2.0" in text
    assert "ctt_fleet_latency_p99_seconds" in text
    try:
        from prometheus_client.openmetrics.parser import (
            text_string_to_metric_families,
        )
    except ImportError:
        pytest.skip("prometheus_client not installed")
    fams = {f.name: f for f in text_string_to_metric_families(text)}
    assert "ctt_serve_jobs_done" in fams
    hist_fam = fams["ctt_serve_latency_e2e_seconds"]
    counts = [s for s in hist_fam.samples
              if s.name.endswith("_count")]
    assert counts and counts[0].value == len(_VALS_A) + len(_VALS_B)


def test_fleet_cli_no_snapshots_exits_one(tmp_path, capsys):
    assert obs_main(["fleet", str(tmp_path)]) == 1
    assert "no daemon snapshots" in capsys.readouterr().err


def test_fleet_cli_foreign_edges_exit_two(tmp_path, capsys):
    sd = str(tmp_path / "state")
    _write_snap(sd, "m0", {}, {"schema": 1, "edges": [1.0, 2.0],
                               "hists": [{"name": "serve.latency.e2e",
                                          "labels": {}, "buckets": [1, 0],
                                          "sum": 0.5, "count": 1}]})
    assert obs_main(["fleet", sd]) == 2
    assert "foreign" in capsys.readouterr().err


# --------------------------------------------------------------------------
# watch lat: line


def _watch_run(run_dir):
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "spans.p1.t1.jsonl"), "w") as f:
        f.write(json.dumps({
            "type": "header", "run": "w", "pid": 1, "tid": 1,
            "host": "synth", "wall": 1000.0, "mono": 10.0,
        }) + "\n")
    with open(os.path.join(run_dir, "metrics.p1.json"), "w") as f:
        json.dump({"counters": {"serve.jobs_done": 2}, "gauges": {}}, f)


def test_watch_lat_line_only_with_histograms(tmp_path, traced):
    from cluster_tools_tpu.obs.live import LiveRun, format_watch

    run_dir = str(tmp_path / "runA")
    _watch_run(run_dir)
    base = format_watch(LiveRun(run_dir).poll())
    assert "lat:" not in base  # no histograms: output unchanged

    snap = _snap_of({
        ("serve.latency.e2e", (("priority", "5"),)): [0.25] * 8,
        ("serve.latency.e2e", (("priority", "0"),)): [1.5] * 8,
    })
    with open(os.path.join(run_dir, f"{hist.HIST_FILE_PREFIX}1.json"),
              "w") as f:
        json.dump(snap, f)
    withlat = format_watch(LiveRun(run_dir).poll())
    (lat_line,) = [ln for ln in withlat.splitlines() if "lat:" in ln]
    # numeric priority classes render highest first
    assert lat_line.index("prio 5") < lat_line.index("prio 0")
    assert withlat.replace(lat_line + "\n", "") == base
