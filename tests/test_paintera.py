"""Label multisets, paintera conversion, bigcat export."""

import os

import numpy as np
import pytest

from cluster_tools_tpu.ops import label_multiset as lms
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


class TestMultisetOps:
    def test_roundtrip(self, rng):
        labels = rng.integers(0, 6, (4, 6, 6)).astype("uint64")
        m = lms.create_multiset_from_labels(labels)
        np.testing.assert_array_equal(m.argmax.reshape(labels.shape), labels)
        ser = lms.serialize_multiset(m)
        m2 = lms.deserialize_multiset(ser, labels.shape)
        np.testing.assert_array_equal(
            m2.argmax.reshape(labels.shape), labels
        )
        for v in range(labels.size):
            i1, c1 = m.voxel_entries(v)
            i2, c2 = m2.voxel_entries(v)
            np.testing.assert_array_equal(i1, i2)
            np.testing.assert_array_equal(c1, c2)

    def test_downsample_counts(self, rng):
        labels = rng.integers(0, 4, (4, 4, 4)).astype("uint64")
        m = lms.create_multiset_from_labels(labels)
        d = lms.downsample_multiset(m, [2, 2, 2])
        assert d.shape == (2, 2, 2)
        for coarse in np.ndindex(2, 2, 2):
            v = int(np.ravel_multi_index(coarse, (2, 2, 2)))
            ids, counts = d.voxel_entries(v)
            window = labels[
                2 * coarse[0] : 2 * coarse[0] + 2,
                2 * coarse[1] : 2 * coarse[1] + 2,
                2 * coarse[2] : 2 * coarse[2] + 2,
            ]
            want_ids, want_counts = np.unique(window, return_counts=True)
            np.testing.assert_array_equal(np.sort(ids), want_ids)
            assert counts.sum() == 8

    def test_restrict_set(self, rng):
        labels = np.arange(8, dtype="uint64").reshape(2, 2, 2)
        m = lms.create_multiset_from_labels(labels)
        d = lms.downsample_multiset(m, [2, 2, 2], restrict_set=3)
        ids, counts = d.voxel_entries(0)
        assert ids.size == 3


class TestMultisetWorkflow:
    def test_pyramid(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.label_multisets import read_multiset_region
        from cluster_tools_tpu.workflows.paintera import LabelMultisetWorkflow

        labels = rng.integers(0, 50, (16, 32, 32)).astype("uint64")
        path = str(tmp_path / "lm.n5")
        ds = file_reader(path).create_dataset(
            "seg", data=labels, chunks=(8, 16, 16)
        )
        ds.attrs["maxId"] = int(labels.max())
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        wf = LabelMultisetWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="seg",
            output_path=path, output_prefix="paintera/data",
            scale_factors=[2, 2], restrict_sets=[-1, 10],
        )
        assert build([wf])
        f = file_reader(path, "r")
        s0 = f["paintera/data/s0"]
        assert s0.attrs["isLabelMultiset"] is True
        assert s0.attrs["maxId"] == int(labels.max())
        # scale-0 multiset reproduces the labels
        m = read_multiset_region(s0, tuple(slice(0, s) for s in labels.shape))
        np.testing.assert_array_equal(
            m.argmax.reshape(labels.shape), labels
        )
        # scale-1: counts pool 2x2x2 children
        s1 = f["paintera/data/s1"]
        assert s1.shape == (8, 16, 16)
        assert s1.attrs["downsamplingFactors"] == [2.0, 2.0, 2.0]
        m1 = read_multiset_region(s1, (slice(0, 4), slice(0, 4), slice(0, 4)))
        ids, counts = m1.voxel_entries(0)
        assert counts.sum() == 8


class TestPainteraConversion:
    def test_conversion_container(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.paintera import read_label_block_mapping
        from cluster_tools_tpu.workflows.paintera import (
            PainteraConversionWorkflow,
        )

        labels = rng.integers(0, 20, (16, 32, 32)).astype("uint64")
        path = str(tmp_path / "pc.n5")
        ds = file_reader(path).create_dataset(
            "seg", data=labels, chunks=(8, 16, 16)
        )
        ds.attrs["maxId"] = int(labels.max())
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        wf = PainteraConversionWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="seg",
            output_path=path, label_group="paintera",
            scale_factors=[2],
            resolution=[40, 4, 4],
        )
        assert build([wf])
        f = file_reader(path, "r")
        g = f["paintera"]
        assert g.attrs["painteraData"] == {"type": "label"}
        assert g.attrs["maxId"] == int(labels.max())
        assert "scaleDatasetPattern" in g.attrs["labelBlockLookup"]
        assert f["paintera/data"].attrs["resolution"] == [4, 4, 40]

        # unique labels per block match a recompute
        uniq_ds = f["paintera/unique-labels/s0"]
        got = uniq_ds.read_chunk_varlen((0, 0, 0))
        want = np.unique(labels[:8, :16, :16])
        np.testing.assert_array_equal(got, want)

        # block mapping inverts the uniques
        mapping = read_label_block_mapping(
            path, "paintera/label-to-block-mapping/s0"
        )
        lab = int(labels[0, 0, 0])
        assert 0 in mapping[lab]

        # the declared per-scale lookup datasets exist for every level
        assert "paintera/unique-labels/s1" in f
        assert "paintera/label-to-block-mapping/s1" in f
        got1 = f["paintera/unique-labels/s1"].read_chunk_varlen((0, 0, 0))
        want1 = np.unique(labels[:16, :32, :32])  # s1 block covers all of s0
        np.testing.assert_array_equal(got1, want1)

    def test_bigcat_export(self, tmp_path, rng):
        h5py = pytest.importorskip("h5py")
        from cluster_tools_tpu.workflows.bigcat import BigcatWorkflow

        n = 50
        assignments = rng.integers(0, 5, n).astype("uint64")
        src = str(tmp_path / "assign.n5")
        file_reader(src).create_dataset(
            "assignments", data=assignments, chunks=(n,)
        )
        out = str(tmp_path / "bigcat.h5")
        with h5py.File(out, "w") as f:
            f.create_dataset("volumes/raw", data=rng.random((8, 8, 8)))
            f.create_dataset(
                "volumes/labels/fragments",
                data=rng.integers(0, n, (8, 8, 8)).astype("uint64"),
            )
        config_dir = str(tmp_path / "configs_b")
        tmp_folder = str(tmp_path / "tmp_b")
        cfg.write_global_config(config_dir, {"block_shape": [8, 8, 8]})
        wf = BigcatWorkflow(
            tmp_folder, config_dir,
            assignment_path=src, assignment_key="assignments",
            output_path=out, resolution=[40, 4, 4],
        )
        assert build([wf])
        with h5py.File(out, "r") as f:
            lut = f["fragment_segment_lut"][:]
            assert lut.shape == (2, n)
            np.testing.assert_array_equal(lut[0], np.arange(n))
            np.testing.assert_array_equal(lut[1], assignments + n)
            assert f.attrs["next_id"] == int(lut.max()) + 1
            assert list(f["volumes/raw"].attrs["resolution"]) == [40, 4, 4]
