"""tools/chip_session.derive_modes — the pin-derivation rules.

Pure-function tests: these decide the production kernel modes written to
chip_modes.json, so each rule is pinned (combined sweep total, pallas
gates requiring exactness AND a win, the slices-CC fallback, the batch
pin, and the all-errored-sweep guard upstream).
"""

import os
import sys

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "tools"),
)

from chip_session import derive_modes  # noqa: E402


def test_sweep_pinned_by_combined_total():
    # dtws prefers assoc, cc prefers seq; total favors assoc
    modes = derive_modes({
        "dtws_assoc_ms": 10.0, "dtws_seq_ms": 100.0,
        "cc_assoc_ms": 30.0, "cc_seq_ms": 20.0,
    })
    assert modes["CTT_SWEEP_MODE"] == "assoc"


def test_pallas_needs_exactness_and_win():
    base = {
        "dtws_assoc_ms": 10.0, "dtws_seq_ms": 12.0,
        "cc_assoc_ms": 10.0, "cc_seq_ms": 12.0,
    }
    assert "CTT_FLOOD_MODE" not in derive_modes(
        {**base, "pallas_flood_exact": True, "pallas_flood_wins": False})
    assert "CTT_FLOOD_MODE" not in derive_modes(
        {**base, "pallas_flood_exact": False, "pallas_flood_wins": True})
    assert derive_modes(
        {**base, "pallas_flood_exact": True, "pallas_flood_wins": True}
    )["CTT_FLOOD_MODE"] == "pallas"


def test_cc_slices_fallback_only_without_pallas():
    base = {
        "dtws_assoc_ms": 10.0, "dtws_seq_ms": 12.0,
        "cc_assoc_ms": 50.0, "cc_seq_ms": 60.0,
        "cc_slices_exact": True, "cc_slices_ms": 20.0,
    }
    assert derive_modes(base)["CTT_CC_MODE"] == "slices"
    # pallas wins take precedence
    won = derive_modes(
        {**base, "pallas_cc_exact": True, "pallas_cc_wins": True})
    assert won["CTT_CC_MODE"] == "pallas"
    # slices slower than the sweeps: no pin
    slow = derive_modes({**base, "cc_slices_ms": 80.0})
    assert "CTT_CC_MODE" not in slow


def test_batch_pin_passthrough():
    modes = derive_modes({
        "dtws_assoc_ms": 1.0, "dtws_seq_ms": 2.0,
        "cc_assoc_ms": 1.0, "cc_seq_ms": 2.0,
        "best_device_batch": 16,
    })
    assert modes["CTT_DEVICE_BATCH"] == "16"


def test_dtws_only_sweep_fallback():
    # without cc timings the sweep pin falls back to dtws alone
    assert derive_modes(
        {"dtws_assoc_ms": 5.0, "dtws_seq_ms": 9.0}
    )["CTT_SWEEP_MODE"] == "assoc"
    assert derive_modes(
        {"dtws_assoc_ms": 9.0, "dtws_seq_ms": 5.0}
    )["CTT_SWEEP_MODE"] == "seq"


def test_dtws_pallas_gate():
    base = {
        "dtws_assoc_ms": 10.0, "dtws_seq_ms": 12.0,
        "cc_assoc_ms": 10.0, "cc_seq_ms": 12.0,
    }
    assert derive_modes(
        {**base, "pallas_dtws_exact": True, "pallas_dtws_wins": True}
    )["CTT_DTWS_MODE"] == "pallas"
    assert "CTT_DTWS_MODE" not in derive_modes(
        {**base, "pallas_dtws_exact": False, "pallas_dtws_wins": True})


def test_missing_measurements_pin_nothing():
    assert derive_modes({}) == {}


def test_hbm_stack_pin_requires_measured_win():
    # ctt-hbm aggregated dispatch: pinned only at >= 1.1x measured speedup
    base = {
        "dtws_assoc_ms": 1.0, "dtws_seq_ms": 2.0,
        "cc_assoc_ms": 1.0, "cc_seq_ms": 2.0,
    }
    won = derive_modes(
        {**base, "best_hbm_stack": 8, "hbm_stack_speedup": 1.35}
    )
    assert won["CTT_HBM_STACK"] == "8"
    # below the 1.1x gate: no pin (the per-batch dispatch shape stays)
    assert "CTT_HBM_STACK" not in derive_modes(
        {**base, "best_hbm_stack": 8, "hbm_stack_speedup": 1.05}
    )
    # tpu_validate records best_hbm_stack=1 when stacking lost outright
    assert "CTT_HBM_STACK" not in derive_modes(
        {**base, "best_hbm_stack": 1, "hbm_stack_speedup": 0.9}
    )
    assert "CTT_HBM_STACK" not in derive_modes(base)
