"""Affinity ops + tasks: label affinities, embedding distances, gradients,
insert_affinities."""

import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu.ops import affinities as aff_ops
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


class TestAffinityOps:
    def test_compute_affinities_oracle(self, rng):
        labels = rng.integers(0, 4, (6, 8, 8)).astype(np.int32)
        offsets = [[-1, 0, 0], [0, -1, 0], [0, 0, -2]]
        affs, mask = aff_ops.compute_affinities(labels, offsets)
        assert affs.shape == (3, 6, 8, 8)
        for c, off in enumerate(offsets):
            for idx in np.ndindex(*labels.shape):
                nb = tuple(i + o for i, o in zip(idx, off))
                if all(0 <= n < s for n, s in zip(nb, labels.shape)):
                    assert mask[c][idx] == 1
                    assert affs[c][idx] == float(labels[idx] == labels[nb])
                else:
                    assert mask[c][idx] == 0
                    assert affs[c][idx] == 0.0

    def test_compute_affinities_uint64_no_collision(self):
        # regression: uint64 ids colliding mod 2**32 must keep their boundary
        labels = np.zeros((2, 4, 4), dtype=np.uint64)
        labels[:, :, :2] = 5
        labels[:, :, 2:] = np.uint64(2**32 + 5)
        affs, mask = aff_ops.compute_affinities(labels, [[0, 0, -1]])
        assert affs[0, 0, 0, 2] == 0.0
        assert mask[0, 0, 0, 2] == 1

    def test_embedding_distances_l2(self, rng):
        emb = rng.random((4, 5, 6, 6)).astype(np.float32)
        offsets = [[0, -1, 0]]
        d = aff_ops.embedding_distances(emb, offsets, "l2")
        # interior oracle
        want = np.sqrt(((emb[:, :, 1:, :] - emb[:, :, :-1, :]) ** 2).sum(0) + 1e-12)
        # offset (0,-1,0): d[v] = dist(emb[v], emb[v + (0,-1,0)]); valid rows >= 1
        np.testing.assert_allclose(d[0][:, 1:, :], want, rtol=1e-5)
        assert (d[0][:, 0, :] == 0).all()

    def test_dilation_matches_scipy(self, rng):
        x = rng.random((8, 12, 12)) > 0.9
        for it in (1, 2):
            got = np.asarray(aff_ops.binary_dilation(x, it))
            want = ndimage.binary_dilation(x, iterations=it)
            np.testing.assert_array_equal(got, want)

    def test_dilation_2d(self, rng):
        x = np.zeros((3, 9, 9), dtype=bool)
        x[1, 4, 4] = True
        got = np.asarray(aff_ops.binary_dilation(x, 1, in_2d=True))
        assert got[1].sum() == 5  # cross in plane
        assert not got[0].any() and not got[2].any()  # no z growth

    def test_erosion_matches_scipy(self, rng):
        x = ndimage.binary_dilation(rng.random((8, 12, 12)) > 0.95, iterations=3)
        for it in (1, 2):
            got = np.asarray(aff_ops.binary_erosion(x, it))
            want = ndimage.binary_erosion(x, iterations=it)
            np.testing.assert_array_equal(got, want)

    def test_gradient_mean(self):
        x = np.linspace(0, 1, 8 * 8 * 8).reshape(8, 8, 8).astype(np.float32)
        got = np.asarray(aff_ops.gradient_mean(x))
        want = np.mean(np.stack(np.gradient(x)), axis=0)
        np.testing.assert_allclose(got, want, rtol=1e-4)


class TestAffinityTasks:
    def _setup(self, tmp_path, name):
        config_dir = str(tmp_path / f"configs_{name}")
        tmp_folder = str(tmp_path / f"tmp_{name}")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        return tmp_folder, config_dir

    def test_insert_affinities(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.affinities import InsertAffinitiesTask

        shape = (16, 32, 32)
        offsets = [[-1, 0, 0], [0, -1, 0], [0, 0, -1]]
        affs = np.full((3,) + shape, 0.9, dtype="float32")
        objs = np.zeros(shape, dtype="uint64")
        objs[4:12, 8:24, 8:15] = 1
        objs[4:12, 8:24, 17:24] = 2

        path = str(tmp_path / "ins.n5")
        f = file_reader(path)
        f.create_dataset("affs", data=affs, chunks=(1, 8, 16, 16))
        f.create_dataset("objs", data=objs, chunks=(8, 16, 16))

        tmp_folder, config_dir = self._setup(tmp_path, "ins")
        # no erosion: this checks raw boundary insertion (the default
        # erode_by=6 would shrink these small objects away)
        cfg.write_config(
            config_dir, "insert_affinities", {"erode_by": 0, "erode_3d": False}
        )
        task = InsertAffinitiesTask(
            tmp_folder, config_dir,
            input_path=path, input_key="affs",
            output_path=path, output_key="out",
            objects_path=path, objects_key="objs",
            offsets=offsets,
        )
        assert build([task])
        out = file_reader(path, "r")["out"][:]
        assert out.shape == affs.shape
        # inside objects away from boundaries affinities stay attractive-high
        assert out[2, 8, 16, 10] >= 0.9
        # the object boundary (x ~ 15..17) must now carry a repulsive x-response
        assert out[2, 8, 16, 17] == 1.0 or out[2, 8, 16, 16] == 1.0
        # background away from any object keeps the raw prediction (no
        # partition-dependent per-block renormalization)
        assert abs(out[2, 1, 1, 1] - 0.9) < 1e-5

    def test_embedding_distances_task(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.affinities import EmbeddingDistancesTask

        shape = (16, 32, 32)
        chans = [rng.random(shape).astype("float32") for _ in range(3)]
        path = str(tmp_path / "emb.n5")
        f = file_reader(path)
        for i, c in enumerate(chans):
            f.create_dataset(f"c{i}", data=c, chunks=(8, 16, 16))

        tmp_folder, config_dir = self._setup(tmp_path, "emb")
        offsets = [[-1, 0, 0], [0, 0, -1]]
        task = EmbeddingDistancesTask(
            tmp_folder, config_dir,
            input_paths=[path] * 3, input_keys=["c0", "c1", "c2"],
            output_path=path, output_key="dist",
            offsets=offsets,
        )
        assert build([task])
        got = file_reader(path, "r")["dist"][:]
        emb = np.stack(chans)
        want = aff_ops.embedding_distances(emb, offsets, "l2")
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_gradients_task(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.affinities import GradientsTask

        shape = (16, 32, 32)
        x = ndimage.gaussian_filter(rng.random(shape), 2.0).astype("float32")
        path = str(tmp_path / "g.n5")
        file_reader(path).create_dataset("x", data=x, chunks=(8, 16, 16))
        tmp_folder, config_dir = self._setup(tmp_path, "grad")
        task = GradientsTask(
            tmp_folder, config_dir,
            input_paths=[path], input_keys=["x"],
            output_path=path, output_key="grad",
        )
        assert build([task])
        got = file_reader(path, "r")["grad"][:]
        want = np.mean(np.stack(np.gradient(x)), axis=0)
        # interior matches (block borders use halo'd recompute)
        np.testing.assert_allclose(
            got[2:-2, 2:-2, 2:-2], want[2:-2, 2:-2, 2:-2], rtol=1e-3, atol=1e-5
        )

    def test_gradients_per_channel(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.affinities import GradientsTask

        shape = (16, 32, 32)
        chans = [rng.random(shape).astype("float32") for _ in range(2)]
        path = str(tmp_path / "gc.n5")
        f = file_reader(path)
        for i, c in enumerate(chans):
            f.create_dataset(f"x{i}", data=c, chunks=(8, 16, 16))
        tmp_folder, config_dir = self._setup(tmp_path, "gradc")
        cfg.write_config(config_dir, "gradients", {"average_gradient": False})
        task = GradientsTask(
            tmp_folder, config_dir,
            input_paths=[path] * 2, input_keys=["x0", "x1"],
            output_path=path, output_key="grads",
        )
        assert build([task])
        got = file_reader(path, "r")["grads"][:]
        assert got.shape == (2,) + shape
        for c, x in enumerate(chans):
            want = np.mean(np.stack(np.gradient(x)), axis=0)
            np.testing.assert_allclose(
                got[c][2:-2, 2:-2, 2:-2], want[2:-2, 2:-2, 2:-2],
                rtol=1e-3, atol=1e-5,
            )
