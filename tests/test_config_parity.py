"""Config-default parity vs the reference (VERDICT r2 item 6).

The reference's per-task ``default_task_config`` literals are frozen in
tests/data/reference_task_defaults.json (regenerate with
tools/extract_reference_defaults.py).  For every reference task with a
same-named counterpart here, every shared config key must carry the same
default value — a same-named config key with a silently different default is
a parity trap.  Intentional divergences must be whitelisted below with a
reason.
"""

import importlib
import json
import os
import pkgutil

import pytest

import cluster_tools_tpu.tasks as tasks_pkg

DATA = os.path.join(os.path.dirname(__file__), "data",
                    "reference_task_defaults.json")

# task_name → {key: reason} for intentional default divergences
WHITELIST = {
    "downscaling": {
        # reference default library is vigra; ours resamples on device
        "library": "jax resampling kernels replace vigra.sampling",
        "library_kwargs": "no vigra kwargs passthrough on the jax path",
    },
    "inference": {
        # reference defaults to a CUDA/pytorch stack; ours is jax-first
        "dtype": "uint8 quantization is opt-in here; float32 is the "
                 "lossless default for the jax predictor",
        "prep_model": "torch model-surgery hook names do not apply to "
                      "flax modules",
    },
    "upscaling": {
        "library": "jax interpolation replaces vigra.sampling here",
    },
}

# reference task_name → our task_name, for renamed components (none today)
ALIASES = {}


def _our_tasks_by_name():
    """Walk every tasks/ module and index task classes by task_name."""
    by_name = {}
    pkg_dir = os.path.dirname(tasks_pkg.__file__)
    # abstract bases share the placeholder name "task"; a *concrete* collision
    # would make this test silently check only one of the claimants
    placeholders = {"task"}
    for info in pkgutil.iter_modules([pkg_dir]):
        mod = importlib.import_module(f"{tasks_pkg.__name__}.{info.name}")
        for attr in vars(mod).values():
            if (
                isinstance(attr, type)
                and getattr(attr, "task_name", None)
                and hasattr(attr, "default_task_config")
                # only index classes defined in that module (skip re-imports)
                and attr.__module__ == mod.__name__
            ):
                name = attr.task_name
                if name in placeholders:
                    continue
                assert name not in by_name or by_name[name] is attr, (
                    f"task_name {name!r} claimed by both "
                    f"{by_name[name].__qualname__} and {attr.__qualname__}"
                )
                by_name[name] = attr
    return by_name


def _reference_records():
    with open(DATA) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def ours():
    return _our_tasks_by_name()


def _norm(v):
    """Value comparison up to list/tuple and int/float equivalence."""
    if isinstance(v, (list, tuple)):
        return tuple(_norm(x) for x in v)
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return float(v)
    return v


@pytest.mark.parametrize(
    "record",
    _reference_records(),
    ids=lambda r: r["task_name"],
)
def test_shared_defaults_match_reference(record, ours):
    name = ALIASES.get(record["task_name"], record["task_name"])
    cls = ours.get(name)
    if cls is None:
        pytest.skip(f"no same-named task for reference {record['task_name']} "
                    f"({record['source']})")
    mine = cls.default_task_config()
    diverged = {}
    for key, ref_val in record["defaults"].items():
        if key not in mine:
            continue  # key not exposed here: nothing to silently diverge
        if key in WHITELIST.get(record["task_name"], {}):
            continue
        if _norm(mine[key]) != _norm(ref_val):
            diverged[key] = (mine[key], ref_val)
    assert not diverged, (
        f"{record['task_name']} ({record['source']}): same-named config keys "
        f"with different defaults (ours, reference): {diverged} — fix or "
        f"whitelist with a reason"
    )


def test_whitelist_entries_are_live(ours):
    """Whitelisted keys must still exist on both sides, or the entry is
    stale and should be dropped."""
    by_name = {r["task_name"]: r for r in _reference_records()}
    for task_name, keys in WHITELIST.items():
        rec = by_name.get(task_name)
        assert rec is not None, f"whitelist names unknown task {task_name}"
        cls = ours.get(ALIASES.get(task_name, task_name))
        if cls is None:
            continue
        mine = cls.default_task_config()
        for key in keys:
            assert key in rec["defaults"], (
                f"whitelist {task_name}.{key}: key gone from the reference"
            )
            assert key in mine, (
                f"whitelist {task_name}.{key}: key not in our defaults"
            )
