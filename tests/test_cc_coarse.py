"""ctt-cc: coarse-to-fine CC + hierarchical flood contracts.

Three invariants, each BIT-exact (not just partition-equal):

  * every CC path — flat, coarse (any tile), sharded collective, tiled
    Pallas (interpret) — produces byte-identical labels to the
    ``connected_components_np`` scipy oracle, including the adversarial
    serpentine/spiral corridors, all connectivities × ``per_slice``, empty
    and all-foreground volumes, and non-tile-dividing shapes;
  * the coarse kernel's fixpoint rounds are tile-bounded: strictly fewer
    than the flat kernel's on the serpentine worst case (the tools/ci_check
    smoke repeats this against a fresh process);
  * the tile-warm-started flood reaches the exact ``seeded_watershed``
    fixpoint with no more global rounds than the flat flood.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from cluster_tools_tpu.ops import _backend
from cluster_tools_tpu.ops import cc as C


def _oracle(mask, connectivity=1, per_slice=False):
    """Scipy labels with the kernel's numbering (scan-order == ascending
    min flat index); per_slice labels each z-slice independently with ids
    continuing across slices (the kernel's 2d-mode contract)."""
    if not per_slice:
        return C.connected_components_np(mask, connectivity)
    out = np.zeros(mask.shape, np.int32)
    n = 0
    for z in range(mask.shape[0]):
        lab, k = C.connected_components_np(mask[z], connectivity)
        out[z] = np.where(lab > 0, lab + n, 0)
        n += k
    return out, n


def spiral_mask(shape):
    """Rectangular inward spiral corridor: Θ(min(H, W)) nested bends, the
    2d counterpart of ``serpentine_mask``'s banded worst case."""
    h, w = int(shape[-2]), int(shape[-1])
    m2 = np.zeros((h, w), dtype=bool)
    top, bot, left, right = 0, h - 1, 0, w - 1
    while top <= bot and left <= right:
        m2[top, left:right + 1] = True
        m2[top:bot + 1, right] = True
        m2[bot, left:right + 1] = True
        m2[bot:top:-1, left] = True
        top += 2
        bot -= 2
        left += 2
        right -= 2
    if len(shape) == 2:
        return m2
    return np.broadcast_to(m2, tuple(shape)).copy()


def _assert_all_paths_exact(mask, connectivity=1, per_slice=False,
                            tiles=((4, 8, 8),)):
    ref, n_ref = _oracle(mask, connectivity, per_slice)
    with _backend.force_cc_mode("flat"):
        flat, n_flat = C.connected_components(
            jnp.asarray(mask), connectivity, per_slice=per_slice
        )
    np.testing.assert_array_equal(np.asarray(flat), ref)
    assert int(n_flat) == n_ref
    for tile in tiles:
        tile = tile[-mask.ndim:]
        got, n = C.connected_components(
            jnp.asarray(mask), connectivity, per_slice=per_slice,
            coarse_tile=tile,
        )
        np.testing.assert_array_equal(np.asarray(got), ref)
        assert int(n) == n_ref


class TestCoarseParity:
    @pytest.mark.parametrize("connectivity", [1, 2, 3])
    @pytest.mark.parametrize("per_slice", [False, True])
    def test_random_all_modes(self, rng, connectivity, per_slice):
        mask = rng.random((12, 20, 18)) < 0.5
        _assert_all_paths_exact(
            mask, connectivity, per_slice, tiles=((4, 8, 8), (5, 7, 9))
        )

    def test_non_dividing_shape(self, rng):
        # tiles never divide the volume: the padding path must stay exact
        mask = rng.random((13, 17, 11)) < 0.5
        _assert_all_paths_exact(mask, tiles=((8, 8, 8),))

    def test_2d(self, rng):
        mask = rng.random((40, 33)) < 0.5
        _assert_all_paths_exact(mask, tiles=((8, 8), (16, 5)))

    def test_empty_and_full(self):
        for mask in (np.zeros((8, 16, 16), bool), np.ones((8, 16, 16), bool)):
            _assert_all_paths_exact(mask, tiles=((4, 8, 8),))

    def test_serpentine_and_spiral(self):
        for mask in (
            C.serpentine_mask((4, 40, 36)),
            C.serpentine_mask((48, 40)),
            spiral_mask((4, 41, 41)),
            spiral_mask((41, 41)),
        ):
            _assert_all_paths_exact(mask, tiles=((4, 8, 8), (8, 16, 16)))

    def test_partition_mode(self, rng):
        # CC within existing labels: coarse must match flat bit-exactly
        seg = (rng.random((10, 16, 14)) * 3).astype(np.int32)
        with _backend.force_cc_mode("flat"):
            want, n_want = C.connected_components_labels(jnp.asarray(seg))
        got, n_got = C.connected_components_labels(
            jnp.asarray(seg), coarse_tile=(4, 8, 8)
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert int(n_got) == int(n_want)

    def test_single_voxel_tiles(self, rng):
        # degenerate tile (1, 1, 1): every voxel is a tile, everything is
        # boundary merge — the pure union-find limit stays exact
        mask = rng.random((4, 6, 5)) < 0.6
        _assert_all_paths_exact(mask, tiles=((1, 1, 1),))


class TestIterationContract:
    def test_serpentine_tile_bounded_rounds(self):
        mask = jnp.asarray(C.serpentine_mask((4, 64, 64)))
        _, it_flat = C.connected_components_raw_with_iters(mask)
        _, stats = C.connected_components_coarse_raw(
            mask, 1, None, False, (4, 16, 16)
        )
        assert int(stats["fixpoint_iters"]) < int(it_flat)

    def test_live_mask_drops_background_tiles(self):
        # one busy corner in an otherwise empty volume: Σ live tiles per
        # round must be far below (rounds × tiles) — empty tiles drop out
        # after round one
        mask = np.zeros((16, 32, 32), bool)
        mask[:4, :8, :8] = C.serpentine_mask((4, 8, 8))[0]
        _, stats = C.connected_components_coarse_raw(
            jnp.asarray(mask), 1, None, False, (4, 8, 8)
        )
        n_tiles = 4 * 4 * 4
        rounds = int(stats["fixpoint_iters"])
        assert rounds >= 2
        assert int(stats["live_tile_rounds"]) < rounds * n_tiles


class TestValueTable:
    def test_merge_value_table_min_semantics(self):
        from cluster_tools_tpu.ops.unionfind import (
            apply_value_roots,
            merge_value_table,
        )

        # sparse ids: {3,7}, {12,41,100}, self-loop padding at 999
        a = jnp.asarray(np.array([7, 41, 100, 999, 999], np.int32))
        b = jnp.asarray(np.array([3, 12, 41, 999, 999], np.int32))
        vals, roots = merge_value_table(a, b)
        # resolution goes through apply_value_roots (searchsorted side='left'
        # → the canonical leftmost slot of duplicated values)
        x = jnp.asarray(np.array([1, 3, 7, 12, 41, 100, 55, 999], np.int32))
        out = np.asarray(apply_value_roots(x, vals, roots))
        # {3,7} → 3, {12,41,100} → 12, self-loop 999 → itself, absent
        # values (1, 55) pass through untouched
        np.testing.assert_array_equal(out, [1, 3, 3, 12, 12, 12, 55, 999])


class TestTileResolution:
    def test_parse_tile_spec(self):
        assert C.parse_tile_spec("8,64,64", 3) == (8, 64, 64)
        assert C.parse_tile_spec("32", 3) == (32, 32, 32)
        assert C.parse_tile_spec("8,64,64", 2) == (64, 64)
        assert C.parse_tile_spec("64", 2) == (64, 64)
        assert C.parse_tile_spec("4,64", 3) == (4, 4, 64)
        assert C.parse_tile_spec("nope", 3) is None
        assert C.parse_tile_spec("0,64,64", 3) is None
        assert C.parse_tile_spec("", 3) is None

    def test_env_pin_and_clip(self, monkeypatch):
        monkeypatch.setenv("CTT_CC_TILE", "4,8,8")
        assert C.resolve_coarse_tile((16, 16, 16)) == (4, 8, 8)
        # clipped to the volume
        assert C.resolve_coarse_tile((2, 4, 4)) == (2, 4, 4)

    def test_invalid_env_warns_and_defaults(self, monkeypatch):
        monkeypatch.setenv("CTT_CC_TILE", "banana")
        with pytest.warns(RuntimeWarning, match="CTT_CC_TILE"):
            tile = C.resolve_coarse_tile((64, 256, 256))
        assert tile == C.default_coarse_tile(3)

    def test_explicit_beats_env(self, monkeypatch):
        monkeypatch.setenv("CTT_CC_TILE", "4,8,8")
        assert C.resolve_coarse_tile((64, 64, 64), 16) == (16, 16, 16)
        assert C.resolve_coarse_tile((64, 64, 64), (8, 16, 32)) == (8, 16, 32)
        with pytest.raises(ValueError):
            C.resolve_coarse_tile((64, 64, 64), (8, 16))

    def test_mode_switch(self):
        # CPU backend defaults flat; explicit pins flip the default path
        assert not _backend.use_coarse_cc()
        with _backend.force_cc_mode("coarse"):
            assert _backend.use_coarse_cc()
        with _backend.force_cc_mode("flat"):
            assert not _backend.use_coarse_cc()


class TestObsCounters:
    def test_wrapper_emits_registered_counters(self, rng, tmp_path):
        from cluster_tools_tpu.obs import metrics, registry, trace

        for name in ("cc.fixpoint_iters", "cc.live_tiles", "cc.merge_pairs"):
            assert registry.is_known_counter(name)
        trace.enable(str(tmp_path / "trace"), "t_cc", export_env=False)
        try:
            metrics.reset()
            mask = rng.random((8, 16, 16)) < 0.5
            labels, n = C.connected_components_coarse(
                mask, coarse_tile=(4, 8, 8)
            )
            ref, n_ref = _oracle(mask)
            np.testing.assert_array_equal(np.asarray(labels), ref)
            assert int(n) == n_ref
            snap = metrics.snapshot()["counters"]
            assert snap.get("cc.fixpoint_iters", 0) >= 1
            assert snap.get("cc.merge_pairs", 0) >= 1
        finally:
            metrics.reset()
            trace.disable()


class TestShardedCoarse:
    def test_sharded_matches_flat_raw(self, rng):
        # the collective (local fixpoint + one all-gathered boundary table)
        # must keep the exact min-flat-index root contract, under BOTH local
        # labeling algorithms
        from cluster_tools_tpu.parallel.sharded import (
            sharded_connected_components,
        )

        mask = rng.random((16, 8, 8)) < 0.5
        ref = np.asarray(C.connected_components_raw(jnp.asarray(mask)))
        for mode in ("flat", "coarse"):
            with _backend.force_cc_mode(mode):
                got = np.asarray(sharded_connected_components(mask))
            np.testing.assert_array_equal(got, ref)

    def test_sharded_serpentine_spans_shards(self):
        # one corridor threading all 8 shards: the single boundary table
        # must resolve a chain of cross-shard equivalences transitively
        from cluster_tools_tpu.parallel.sharded import (
            sharded_connected_components,
        )

        mask = C.serpentine_mask((16, 16, 16))
        ref = np.asarray(C.connected_components_raw(jnp.asarray(mask)))
        with _backend.force_cc_mode("coarse"):
            got = np.asarray(sharded_connected_components(mask))
        np.testing.assert_array_equal(got, ref)


class TestPallasTiled:
    def test_tiled_kernel_interpret_parity(self, rng):
        from cluster_tools_tpu.ops.pallas_cc import (
            pallas_connected_components_tiled,
        )

        mask = rng.random((3, 16, 256)) < 0.5
        ref, n_ref = _oracle(np.asarray(mask))
        got, n = pallas_connected_components_tiled(
            jnp.asarray(mask), (8, 128), interpret=True
        )
        np.testing.assert_array_equal(np.asarray(got), ref)
        assert int(n) == n_ref

    def test_tile_chooser(self):
        from cluster_tools_tpu.ops.pallas_cc import pallas_cc_tile

        th, tw = pallas_cc_tile((4, 512, 1024))
        assert th % 8 == 0 and tw % 128 == 0
        assert 512 % th == 0 and 1024 % tw == 0
        assert pallas_cc_tile((4, 512, 100)) is None  # no aligned divisor


class TestHierFlood:
    def _fields(self, rng, shape=(12, 32, 24), n_seeds=30):
        from scipy import ndimage

        h = ndimage.gaussian_filter(
            rng.random(shape).astype(np.float32), 1.5
        ).astype(np.float32)
        seeds = np.zeros(shape, np.int32)
        pts = rng.integers(0, np.array(shape), size=(n_seeds, 3))
        for i, p in enumerate(pts):
            seeds[tuple(p)] = i + 1
        mask = rng.random(shape) < 0.92
        return jnp.asarray(h), jnp.asarray(seeds), jnp.asarray(mask)

    @pytest.mark.parametrize("per_slice", [False, True])
    def test_tiled_flood_exact(self, rng, per_slice):
        from cluster_tools_tpu.ops import watershed as W

        h, seeds, mask = self._fields(rng)
        want = np.asarray(
            W._seeded_watershed_scan(h, seeds, mask, per_slice=per_slice)
        )
        got, _, stats = W.flood_with_stats(
            h, seeds, mask, per_slice=per_slice, tile=(4, 8, 8)
        )
        np.testing.assert_array_equal(np.asarray(got), want)
        _, _, flat_stats = W.flood_with_stats(
            h, seeds, mask, per_slice=per_slice
        )
        # the warm start must never cost extra global rounds
        assert int(stats["flood_alt_iters"]) <= int(
            flat_stats["flood_alt_iters"]
        )
        assert int(stats["flood_assign_iters"]) <= int(
            flat_stats["flood_assign_iters"]
        )
        assert int(stats["flood_tile_iters"]) >= 1

    def test_seeded_watershed_coarse_tile_kwarg(self, rng):
        from cluster_tools_tpu.ops import watershed as W

        h, seeds, mask = self._fields(rng)
        want = np.asarray(W.seeded_watershed(h, seeds, mask))
        got = np.asarray(
            W.seeded_watershed(h, seeds, mask, coarse_tile=(4, 8, 8))
        )
        np.testing.assert_array_equal(got, want)

    def test_flood_tile_env_pin(self, rng, monkeypatch):
        from cluster_tools_tpu.ops import watershed as W

        h, seeds, mask = self._fields(rng, shape=(8, 16, 16))
        want = np.asarray(W.seeded_watershed(h, seeds, mask))
        monkeypatch.setenv("CTT_FLOOD_TILE", "4,8,8")
        jax.clear_caches()  # trace-time switch, like every CTT_* mode
        try:
            assert W.resolve_flood_tile(h.shape) == (4, 8, 8)
            got = np.asarray(W.seeded_watershed(h, seeds, mask))
        finally:
            jax.clear_caches()
        np.testing.assert_array_equal(got, want)
        monkeypatch.setenv("CTT_FLOOD_TILE", "garbage")
        with pytest.warns(RuntimeWarning, match="CTT_FLOOD_TILE"):
            assert W.resolve_flood_tile(h.shape) is None

    def test_hier_api_labels_and_merge_table(self, rng):
        from cluster_tools_tpu.ops import watershed as W

        h, seeds, mask = self._fields(rng)
        want = np.asarray(W.seeded_watershed(h, seeds, mask))
        labels, (a, b, s), stats = W.seeded_watershed_hier(
            h, seeds, mask, coarse_tile=(4, 8, 8)
        )
        np.testing.assert_array_equal(np.asarray(labels), want)
        a, b, s = np.asarray(a), np.asarray(b), np.asarray(s)
        real = a > 0
        assert real.any()
        # merge-table invariants: real slots pair distinct labels that are
        # truly tile-face adjacent, with finite saddle = max of the two
        # heights; padding slots are (0, 0, _BIG)
        assert (a[real] != b[real]).all()
        assert (s[real] < 1e38).all()
        assert (b[~real] == 0).all() and (s[~real] > 1e38).all()

    def test_pallas_flood_warm_interpret(self, rng):
        from cluster_tools_tpu.ops import watershed as W
        from cluster_tools_tpu.ops.pallas_flood import flood_tiles_warm

        shape = (3, 16, 256)
        h, seeds, mask = self._fields(rng, shape=shape, n_seeds=20)
        warm = flood_tiles_warm(h, seeds, mask, (8, 128), interpret=True)
        got = W._flood_scan_impl(
            h, seeds, mask, 0, False, (3, 8, 128), warm=warm
        )[0]
        want = W._seeded_watershed_scan(h, seeds, mask)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
