"""ctt-hbm tests: device-resident pipelines.

Covers the PR acceptance contract:
  * DeviceBufferCache hit/miss/eviction at the budget edge, with explicit
    ``.delete()`` on evicted device batches;
  * invalidation on store rewrite — POSIX (inode/mtime signature) and
    remote (ETag via the stub object store);
  * fused (stacked) dispatch byte parity vs the per-batch and per-block
    paths across the converted kernels (threshold, minfilter, linear,
    block CC, watershed);
  * double-buffered upload-stage determinism at depth/stack > 1;
  * serve two-job warm run: the second job on the same volume skips every
    upload (``device.uploads_skipped`` moves, ``device.upload_bytes``
    does not), byte-identical output;
  * disabled-overhead smoke — ``CTT_HBM_CACHE_MB=0`` (the default) plus
    ``prefetch: false`` restore the pre-hbm execution: no sources, no
    entries, no new counters.
"""

import os

import numpy as np
import pytest

from cluster_tools_tpu.obs import metrics as obs_metrics
from cluster_tools_tpu.obs import trace as obs_trace
from cluster_tools_tpu.runtime import build, config as cfg, hbm
from cluster_tools_tpu.utils import store
from cluster_tools_tpu.utils.store import file_reader


@pytest.fixture
def traced(tmp_path):
    obs_metrics.reset()
    obs_trace.enable(str(tmp_path / "trace"), "hbm_test", export_env=False)
    yield
    obs_trace.disable()
    obs_metrics.reset()


@pytest.fixture
def warm_cache(traced):
    """Arm the process device-buffer cache for one test (the conftest
    autouse fixture restores the disabled default afterwards)."""
    hbm.set_cache_budget(256 * 1024 * 1024)
    yield hbm.cache()


def _counters():
    return dict(obs_metrics.snapshot()["counters"])


def _delta(before, after, name):
    return after.get(name, 0) - before.get(name, 0)


# ---------------------------------------------------------------------------
# DeviceBufferCache unit behavior


class _FakeArray:
    def __init__(self):
        self.deleted = False

    def delete(self):
        self.deleted = True


def _entry(nbytes):
    arr = _FakeArray()
    return arr, hbm.DeviceBatch(arrays=(arr,), n=1, nbytes=nbytes)


class TestDeviceBufferCache:
    def test_hit_miss_eviction_at_budget_edge(self, traced):
        dc = hbm.DeviceBufferCache(100)
        a_arr, a = _entry(60)
        b_arr, b = _entry(40)
        c_arr, c = _entry(10)
        sa = hbm.BatchSource(key=("a",), sig=(1,))
        sb = hbm.BatchSource(key=("b",), sig=(1,))
        sc = hbm.BatchSource(key=("c",), sig=(1,))
        dc.put(sa, a)
        dc.put(sb, b)  # 60 + 40 = exactly at budget: both resident
        assert dc.get(sa) is a and dc.get(sb) is b
        assert dc.nbytes == 100 and len(dc) == 2
        # +10 pushes past the budget: LRU (a, refreshed least recently...
        # get() order above made a then b most recent, so a evicts first)
        dc.put(sc, c)
        assert dc.get(sa) is None
        assert a_arr.deleted, "eviction must .delete() the device arrays"
        assert dc.get(sb) is b and dc.get(sc) is c
        assert not b_arr.deleted and not c_arr.deleted

    def test_oversized_entry_never_stored(self, traced):
        dc = hbm.DeviceBufferCache(50)
        arr, batch = _entry(51)
        src = hbm.BatchSource(key=("big",), sig=())
        dc.put(src, batch)
        assert dc.get(src) is None and len(dc) == 0

    def test_signature_mismatch_is_miss_and_evicts(self, traced):
        dc = hbm.DeviceBufferCache(100)
        arr, batch = _entry(10)
        dc.put(hbm.BatchSource(key=("k",), sig=(1, 2)), batch)
        stale = dc.get(hbm.BatchSource(key=("k",), sig=(1, 3)))
        assert stale is None
        assert arr.deleted, "a rewritten source must drop the stale buffers"
        assert len(dc) == 0
        assert _counters().get("device.cache_evictions", 0) >= 1

    def test_clear_deletes(self, traced):
        dc = hbm.DeviceBufferCache(100)
        arr, batch = _entry(10)
        dc.put(hbm.BatchSource(key=("k",), sig=()), batch)
        dc.clear()
        assert arr.deleted and dc.nbytes == 0


class TestEvictionGuard:
    """ctt-hier follow-up to PR 11's hazard note: an eviction while any
    dispatch guard is active must DEFER the ``.delete()`` until the last
    guard exits — a concurrent job's in-flight dispatch can never lose
    the buffers it is reading."""

    def test_eviction_inside_guard_defers_delete(self, traced):
        dc = hbm.DeviceBufferCache(50)
        a_arr, a = _entry(40)
        sa = hbm.BatchSource(key=("a",), sig=(1,))
        dc.put(sa, a)
        b_arr, b = _entry(40)
        with hbm.use_guard():
            hit = dc.get(sa)
            assert hit is a
            # concurrent job inserts and evicts `a` mid-"dispatch"
            dc.put(hbm.BatchSource(key=("b",), sig=(1,)), b)
            assert dc.get(sa) is None, "evicted from the cache immediately"
            assert not a_arr.deleted, (
                "evicted .delete() must wait for the active dispatch guard"
            )
        assert a_arr.deleted, "the last guard exit drains deferred deletes"
        assert not b_arr.deleted
        assert _counters().get("device.deferred_deletes", 0) >= 1

    def test_nested_guards_drain_on_last_exit(self, traced):
        dc = hbm.DeviceBufferCache(50)
        a_arr, a = _entry(40)
        sa = hbm.BatchSource(key=("a",), sig=(1,))
        dc.put(sa, a)
        _, b = _entry(40)
        with hbm.use_guard():
            with hbm.use_guard():
                dc.put(hbm.BatchSource(key=("b",), sig=(1,)), b)
                assert not a_arr.deleted
            assert not a_arr.deleted, "inner exit must not drain"
        assert a_arr.deleted

    def test_delete_immediate_without_guard(self, traced):
        dc = hbm.DeviceBufferCache(50)
        a_arr, a = _entry(40)
        dc.put(hbm.BatchSource(key=("a",), sig=(1,)), a)
        dc.put(hbm.BatchSource(key=("b",), sig=(1,)), _entry(40)[1])
        assert a_arr.deleted, "no guard active: eviction frees immediately"

    def test_two_serve_jobs_one_entry_budget(self, tmp_path, rng):
        """Regression for the PR 11 race window: two concurrent serve
        jobs over DIFFERENT volumes at a budget that holds only one
        entry — every upload of one job evicts the other's, so without
        the guard an in-flight dispatch could lose its buffers (silent
        per-block fallback).  Both jobs must produce bytes identical to
        their cold-process runs, with zero block failures."""
        from cluster_tools_tpu.runtime.workflow import ExecutionContext
        from cluster_tools_tpu.serve import ServeClient, ServeDaemon

        was_on = obs_trace.enabled()
        if not was_on:
            obs_trace.enable(str(tmp_path / "trace"), "hbm_guard",
                             export_env=False)
        prev_ctx = ExecutionContext._PROCESS
        paths = {}
        for tag in ("a", "b"):
            p = str(tmp_path / f"vol_{tag}.n5")
            data = rng.random((8, 32, 32)).astype("float32")
            file_reader(p).create_dataset("bnd", data=data, chunks=(4, 8, 8))
            paths[tag] = p
        # one 4x8x8 float32 block batch is 8 KB: a ~0.02 MB budget holds
        # one entry (plus slack) — back-to-back uploads evict each other
        d = ServeDaemon(
            str(tmp_path / "state"),
            config={"concurrency": 2, "hbm_cache_mb": 0.02},
        )
        d.start()
        try:
            client = ServeClient(state_dir=str(tmp_path / "state"))
            jobs = {
                tag: client.submit(
                    "cluster_tools_tpu.tasks.threshold:ThresholdTask",
                    {
                        "tmp_folder": str(tmp_path / f"tmp_{tag}"),
                        "config_dir": str(tmp_path / f"configs_{tag}"),
                        "input_path": paths[tag], "input_key": "bnd",
                        "output_path": paths[tag], "output_key": "thr",
                    },
                    configs={"global": {"block_shape": [4, 8, 8],
                                        "target": "tpu", "devices": [0],
                                        "device_batch_size": 1,
                                        "pipeline_depth": 3}},
                )
                for tag in ("a", "b")
            }
            for tag, jid in jobs.items():
                state = client.wait(jid, timeout_s=300)
                assert state["result"]["ok"], (tag, state)
        finally:
            d.request_drain()
            if d._httpd is not None:
                d._httpd.shutdown()
                d._httpd.server_close()
            for t in d._threads:
                if t.name.startswith("ctt-serve-exec"):
                    t.join(timeout=30)
            ExecutionContext._PROCESS = prev_ctx
            if not was_on:
                obs_trace.disable()
            obs_metrics.reset()
        for tag in ("a", "b"):
            f = file_reader(paths[tag], "r")
            expect = (f["bnd"][:] > 0.5).astype("uint8")
            np.testing.assert_array_equal(f["thr"][:], expect, err_msg=tag)


# ---------------------------------------------------------------------------
# store-rewrite invalidation (POSIX + remote), via the real source probe


def _source_for(ds, path, block_shape, config=None):
    from cluster_tools_tpu.utils.blocking import Blocking

    blocking = Blocking(ds.shape, block_shape)
    return hbm.dataset_source(
        ds, path, "x", blocking, list(range(blocking.n_blocks)),
        (0, 0, 0), ("t",), config or {"target": "local"},
    )


class TestStoreRewriteInvalidation:
    def test_posix_rewrite_invalidates(self, tmp_path, warm_cache, rng):
        path = str(tmp_path / "v.n5")
        data = rng.random((8, 16, 16)).astype("float32")
        file_reader(path).create_dataset("x", data=data, chunks=(4, 8, 8))
        ds = file_reader(path, "a")["x"]
        src = _source_for(ds, path, (4, 8, 8))
        assert src is not None
        arr, batch = _entry(10)
        warm_cache.put(src, batch)
        assert warm_cache.get(_source_for(ds, path, (4, 8, 8))) is batch
        # rewrite one chunk: os.replace changes the inode -> new signature
        ds[0:4, 0:8, 0:8] = data[0:4, 0:8, 0:8] * 2.0
        src2 = _source_for(ds, path, (4, 8, 8))
        assert src2.sig != src.sig
        assert warm_cache.get(src2) is None
        assert arr.deleted

    def test_remote_etag_rewrite_invalidates(self, tmp_path, warm_cache,
                                             rng):
        objstub = pytest.importorskip("objstub")
        with objstub.StubObjectStore(str(tmp_path / "objroot")) as stub:
            url = f"{stub.url}/v.zarr"
            data = rng.random((8, 8, 8)).astype("float32")
            file_reader(url).create_dataset("x", data=data, chunks=(8, 8, 8))
            ds = file_reader(url, "r")["x"]
            src = _source_for(ds, url, (8, 8, 8))
            assert src is not None
            arr, batch = _entry(10)
            warm_cache.put(src, batch)
            assert warm_cache.get(_source_for(ds, url, (8, 8, 8))) is batch
            # foreign rewrite straight into the served tree: the ETag
            # (mtime_ns-size) changes, the resident upload must miss
            other = str(tmp_path / "other.zarr")
            file_reader(other).create_dataset(
                "x", data=(data * 2 + 1).astype("float32"), chunks=(8, 8, 8)
            )
            os.replace(
                os.path.join(other, "x", "0.0.0"),
                os.path.join(stub.root, "v.zarr", "x", "0.0.0"),
            )
            src2 = _source_for(ds, url, (8, 8, 8))
            assert src2.sig != src.sig
            assert warm_cache.get(src2) is None
            assert arr.deleted


# ---------------------------------------------------------------------------
# fused (stacked) dispatch parity across the converted kernels


def _write_vol(tmp_path, rng, shape=(8, 32, 32), chunks=(4, 8, 8)):
    path = str(tmp_path / "data.n5")
    if not os.path.exists(path):
        from scipy import ndimage

        raw = ndimage.gaussian_filter(rng.random(shape), (1.0, 2.0, 2.0))
        raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")
        file_reader(path).create_dataset("bnd", data=raw, chunks=chunks)
    return path


def _gconf(tmp_path, key, **over):
    config_dir = str(tmp_path / f"configs_{key}")
    conf = {"block_shape": [4, 8, 8], "target": "tpu",
            "device_batch_size": 1, "devices": [0], "pipeline_depth": 3}
    conf.update(over)
    cfg.write_global_config(config_dir, conf)
    return config_dir


def _task_cases(tmp_path, rng, key):
    """(name, task) pairs covering every converted kernel, writing to
    per-run output keys."""
    from cluster_tools_tpu.tasks.masking import MinfilterTask
    from cluster_tools_tpu.tasks.threshold import ThresholdTask
    from cluster_tools_tpu.tasks.thresholded_components import (
        BlockComponentsTask,
    )
    from cluster_tools_tpu.tasks.transformations import (
        LinearTransformationTask,
    )
    from cluster_tools_tpu.tasks.watershed import WatershedTask

    path = _write_vol(tmp_path, rng)
    mask_path = str(tmp_path / "mask.n5")
    if not store._exists(os.path.join(mask_path, "m")):
        file_reader(mask_path).create_dataset(
            "m", data=(rng.random((8, 32, 32)) > 0.05).astype("uint8"),
            chunks=(4, 8, 8),
        )
    trafo = str(tmp_path / "trafo.json")
    if not os.path.exists(trafo):
        import json

        with open(trafo, "w") as f:
            json.dump({"a": 1.5, "b": -0.1}, f)

    def mk(cls, cfg_name, conf, **kw):
        config_dir = _gconf(tmp_path, f"{key}_{cfg_name}",
                            **conf.pop("_global", {}))
        if conf:
            cfg.write_config(config_dir, cls.task_name, conf)
        return cls(
            str(tmp_path / f"tmp_{key}_{cfg_name}"), config_dir,
            input_path=path, input_key="bnd",
            output_path=path, output_key=f"{cfg_name}_{key}", **kw,
        )

    return [
        ("threshold", mk(ThresholdTask, "thr", {"threshold": 0.5})),
        ("minfilter", mk(MinfilterTask, "mf", {"filter_shape": [2, 4, 4]})),
        ("linear", mk(LinearTransformationTask, "lin", {},
                      transformation=trafo)),
        ("components", mk(BlockComponentsTask, "cc", {"threshold": 0.5})),
        ("watershed", mk(WatershedTask, "ws",
                         {"threshold": 0.5, "sigma_seeds": 1.6,
                          "size_filter": 10, "halo": [2, 4, 4]})),
    ]


class TestStackedDispatchParity:
    def test_fused_stack_byte_identical_per_kernel(self, tmp_path, traced,
                                                   rng):
        """hbm_stack=3 (aggregated dispatch) vs the per-block path (the
        byte oracle — the unstacked batch path is exercised by the rest
        of the suite): identical arrays for every converted kernel, and
        the aggregated run issues fewer dispatches than blocks."""
        path = _write_vol(tmp_path, rng)
        stacked = dict(_task_cases(tmp_path, rng, "stack"))
        perblock = dict(_task_cases(tmp_path, rng, "pb"))
        before = _counters()
        for name, t in stacked.items():
            # rewrite the global config with aggregation on
            cfg.write_global_config(t.config_dir, {
                "block_shape": [4, 8, 8], "target": "tpu",
                "device_batch_size": 1, "devices": [0],
                "pipeline_depth": 3, "hbm_stack": 3,
            })
            assert build([t])
        after = _counters()
        for name, t in perblock.items():
            cfg.write_global_config(t.config_dir, {
                "block_shape": [4, 8, 8], "target": "local", "max_jobs": 1,
            })
            assert build([t])
        f = file_reader(path, "r")
        for name in stacked:
            b = f[f"{_key_of(stacked, name)}"][:]
            c = f[f"{_key_of(perblock, name)}"][:]
            np.testing.assert_array_equal(b, c, err_msg=name)
        n_blocks = 2 * 4 * 4
        dispatches = _delta(before, after, "device.dispatches")
        assert 0 < dispatches < 5 * n_blocks
        assert _delta(before, after, "device.fused_blocks") > 0

    def test_monolithic_task_aggregated_chunks(self, tmp_path, traced,
                                               rng):
        """A task WITHOUT the split protocol (inference-style monolithic
        ``process_block_batch``) must still profit from ``hbm_stack``:
        the executor merges consecutive chunks and hands the monolithic
        fn one bigger id list.  Byte parity vs the unstacked run, fewer
        dispatches, and ``device.fused_blocks`` counts the merge."""
        from cluster_tools_tpu.tasks.threshold import ThresholdTask

        class MonoThreshold(ThresholdTask):
            read_batch = None  # hides the split protocol from _staged_fns

            def _run_batch(self, block_ids, blocking, config):
                ThresholdTask.write_batch(
                    self,
                    ThresholdTask.compute_batch(
                        self,
                        ThresholdTask.read_batch(
                            self, block_ids, blocking, config
                        ),
                        blocking, config,
                    ),
                    blocking, config,
                )

        path = _write_vol(tmp_path, rng)

        def run(key, **over):
            config_dir = _gconf(tmp_path, key, device_batch_size=2,
                                **over)
            cfg.write_config(config_dir, "threshold", {"threshold": 0.5})
            t = MonoThreshold(
                str(tmp_path / f"tmp_{key}"), config_dir,
                input_path=path, input_key="bnd",
                output_path=path, output_key=f"mono_{key}",
            )
            assert build([t])
            return file_reader(path, "r")[f"mono_{key}"][:]

        base = run("mono_plain")
        before = _counters()
        fused = run("mono_stacked", hbm_stack=2)
        after = _counters()
        np.testing.assert_array_equal(fused, base)
        # 32 blocks / batch 2 = 16 chunks, merged 2-at-a-time -> 8
        assert 0 < _delta(before, after, "device.dispatches") <= 8
        assert _delta(before, after, "device.fused_blocks") > 0


def _key_of(cases, name):
    return cases[name].output_key


# ---------------------------------------------------------------------------
# double-buffered upload stage


class TestUploadStage:
    def test_double_buffer_depth2_determinism(self, tmp_path, traced, rng):
        """The transfer stage (prefetch on, depth 3) must be run-to-run
        deterministic and identical to the serial pre-hbm path
        (prefetch: false)."""
        from cluster_tools_tpu.tasks.watershed import WatershedTask

        path = _write_vol(tmp_path, rng)
        outs = {}
        for tag, over in (
            ("up1", {}), ("up2", {}),
            ("plain", {"prefetch": False, "pipeline_depth": 1}),
        ):
            config_dir = _gconf(tmp_path, tag, **over)
            cfg.write_config(config_dir, "watershed",
                             {"threshold": 0.5, "sigma_seeds": 1.6,
                              "size_filter": 10, "halo": [2, 4, 4]})
            t = WatershedTask(
                str(tmp_path / f"tmp_{tag}"), config_dir,
                input_path=path, input_key="bnd",
                output_path=path, output_key=f"ws_{tag}",
            )
            assert build([t])
            outs[tag] = file_reader(path, "r")[f"ws_{tag}"][:]
        np.testing.assert_array_equal(outs["up1"], outs["up2"])
        np.testing.assert_array_equal(outs["up1"], outs["plain"])
        # the upload stage actually ran on its transfer thread
        assert _counters().get("executor.stage_upload_s", 0) > 0

    def test_warm_second_build_skips_uploads(self, tmp_path, warm_cache,
                                             rng):
        """Two builds over the same volume in one process: the second
        serves every batch from the warm buffer cache — zero new upload
        bytes, nonzero skips, identical bytes."""
        from cluster_tools_tpu.tasks.threshold import ThresholdTask

        path = _write_vol(tmp_path, rng)

        def run(tag):
            config_dir = _gconf(tmp_path, tag)
            t = ThresholdTask(
                str(tmp_path / f"tmp_{tag}"), config_dir,
                input_path=path, input_key="bnd",
                output_path=path, output_key=f"thr_{tag}",
            )
            assert build([t])

        # output readbacks happen AFTER both measured windows — they are
        # themselves codec reads and would drown the input accounting
        c0 = _counters()
        run("cold")
        c1 = _counters()
        run("warm")
        c2 = _counters()
        f = file_reader(path, "r")
        np.testing.assert_array_equal(f["thr_cold"][:], f["thr_warm"][:])
        assert _delta(c0, c1, "device.upload_bytes") > 0
        assert _delta(c1, c2, "device.upload_bytes") == 0
        assert _delta(c1, c2, "device.uploads_skipped") > 0
        # the warm run ALSO skipped the host input reads (probe-hit
        # stubs): zero codec misses beyond the advisory LRU prefetches,
        # which all hit the decoded-chunk LRU warmed by the cold run
        assert _delta(c1, c2, "store.chunk_cache_misses") == 0


# ---------------------------------------------------------------------------
# serve: two-job warm run


class TestServeWarm:
    def test_two_job_warm_run_skips_uploads(self, tmp_path, rng):
        from cluster_tools_tpu.runtime.workflow import ExecutionContext
        from cluster_tools_tpu.serve import ServeClient, ServeDaemon

        was_on = obs_trace.enabled()
        if not was_on:
            obs_trace.enable(str(tmp_path / "trace"), "hbm_serve",
                             export_env=False)
        prev_ctx = ExecutionContext._PROCESS
        d = ServeDaemon(str(tmp_path / "state"), config={"concurrency": 1})
        d.start()
        try:
            client = ServeClient(state_dir=str(tmp_path / "state"))
            path = _write_vol(tmp_path, rng)

            def submit(tag):
                return client.submit_and_wait(
                    "WatershedWorkflow",
                    {
                        "tmp_folder": str(tmp_path / f"tmp_{tag}"),
                        "config_dir": str(tmp_path / f"configs_s_{tag}"),
                        "input_path": path, "input_key": "bnd",
                        "output_path": path, "output_key": f"ws_{tag}",
                    },
                    configs={
                        "global": {"block_shape": [4, 8, 8],
                                   "target": "tpu", "devices": [0],
                                   "device_batch_size": 1,
                                   "pipeline_depth": 3},
                        "watershed": {"threshold": 0.5, "sigma_seeds": 1.6,
                                      "size_filter": 10, "halo": [2, 4, 4]},
                    },
                    timeout_s=300,
                )

            c0 = _counters()
            s1 = submit("j1")
            c1 = _counters()
            s2 = submit("j2")
            c2 = _counters()
            assert s1["result"]["ok"] and s2["result"]["ok"]
            f = file_reader(path, "r")
            np.testing.assert_array_equal(f["ws_j1"][:], f["ws_j2"][:])
            assert _delta(c0, c1, "device.upload_bytes") > 0
            assert _delta(c1, c2, "device.upload_bytes") == 0
            assert _delta(c1, c2, "device.uploads_skipped") >= 1
        finally:
            d.request_drain()
            if d._httpd is not None:
                d._httpd.shutdown()
                d._httpd.server_close()
            for t in d._threads:
                if t.name.startswith("ctt-serve-exec"):
                    t.join(timeout=30)
            ExecutionContext._PROCESS = prev_ctx
            if not was_on:
                obs_trace.disable()
            obs_metrics.reset()


# ---------------------------------------------------------------------------
# disabled-overhead smoke + watch line


class TestDisabledAndWatch:
    def test_disabled_no_sources_no_counters(self, tmp_path, traced, rng):
        """CTT_HBM_CACHE_MB=0 (the default): no batch sources, no cache
        entries, no device.upload/skip accounting — the pre-hbm shape."""
        from cluster_tools_tpu.parallel.dispatch import read_block_batch
        from cluster_tools_tpu.tasks.threshold import ThresholdTask
        from cluster_tools_tpu.utils.blocking import Blocking

        assert hbm.cache() is None
        path = _write_vol(tmp_path, rng)
        ds = file_reader(path, "r")["bnd"]
        batch = read_block_batch(
            ds, Blocking((8, 32, 32), (4, 8, 8)), [0, 1], dtype="float32",
            device_source=(path, "bnd", ("t",), {"target": "local"}),
        )
        assert batch.source is None and batch.device is None
        config_dir = _gconf(tmp_path, "off")
        t = ThresholdTask(
            str(tmp_path / "tmp_off"), config_dir,
            input_path=path, input_key="bnd",
            output_path=path, output_key="thr_off",
        )
        assert build([t])
        c = _counters()
        assert c.get("device.uploads_skipped", 0) == 0
        assert c.get("device.cache_evictions", 0) == 0

    def test_watch_renders_device_line(self, tmp_path):
        import json

        from cluster_tools_tpu.obs.live import LiveRun, format_watch

        run = str(tmp_path / "run")
        os.makedirs(run)
        with open(os.path.join(run, "metrics.p1.json"), "w") as f:
            json.dump({
                "counters": {
                    "device.upload_bytes": 2.5e6,
                    "device.uploads_skipped": 3,
                    "device.dispatches": 7, "device.fused_blocks": 12,
                    "device.cache_evictions": 1,
                },
                "gauges": {"device.cache_bytes": 1.5e6,
                           "device.inflight_uploads": 1},
            }, f)
        text = format_watch(LiveRun(run).poll())
        line = next(l for l in text.splitlines()
                    if l.strip().startswith("device:"))
        assert "uploaded 2.5 MB" in line
        assert "skipped 3" in line
        assert "dispatches 7" in line
        assert "fused blocks 12" in line
        assert "evictions 1" in line
        assert "cache 1.5 MB" in line and "inflight 1" in line
