"""ctt-diskless: object-store-native elastic fleet tests.

Covers the diskless hardening end to end against the local stub object
server (tests/objstub.py) in SigV4 mode:

  * request signing: AWS SigV4 roundtrips verified independently by the
    stub's own HMAC recompute; unsigned / wrong-key requests get 403 and
    surface as RETRYABLE auth errors (never FileNotFoundError — a silent
    auth downgrade would read as "no lease/no peer" and corrupt
    scheduling decisions); credential resolution order (env, then the
    shared credentials file);
  * multipart upload: oversized payloads (incl. remote ragged ``.npy``
    scratch) take initiate/parts/complete, survive seeded 5xx chaos via
    the per-part retry, and never leak staged parts into listings;
  * remote serve state dirs: the full JobQueue lifecycle and fleet
    beats over an object-store prefix, including the paginated-listing
    regression at ``list_page = 2`` (satellite: a fleet must not lose
    records past the first continuation page);
  * clock-skew robustness: a store whose clock runs BEHIND must never
    expire a live torn lease/beat early — remote mtime ages are capped
    by the local monotonic first-seen observation;
  * supervisor: spawn/drain/adopt decision rounds through injected
    spawn/drain seams, the min-floor, one action per round, and
    statelessness (a fresh supervisor re-adopts from beats alone);
  * conformance over a remote prefix: ``analysis conformance
    http://...`` judges a surviving diskless state dir exactly like a
    POSIX one.
"""

import json
import os
import time

import numpy as np
import pytest
from objstub import StubObjectStore

from cluster_tools_tpu.analysis.conformance import conformance_report
from cluster_tools_tpu.serve.fleet import FleetBeat, FleetView, read_peers
from cluster_tools_tpu.serve.jobs import JobQueue
from cluster_tools_tpu.serve.supervisor import Supervisor
from cluster_tools_tpu.utils import sigv4, store_backend
from cluster_tools_tpu.utils.store import RaggedDataset


@pytest.fixture(autouse=True)
def fresh_backends():
    """Remote backends cache per-origin (signing state, multipart
    threshold are read at construction) — tests vary that env, so every
    test starts from an empty cache."""
    with store_backend._REMOTE_LOCK:
        store_backend._REMOTE.clear()
    yield
    with store_backend._REMOTE_LOCK:
        store_backend._REMOTE.clear()


@pytest.fixture
def traced_metrics(tmp_path):
    from cluster_tools_tpu.obs import metrics as obs_metrics
    from cluster_tools_tpu.obs import trace as obs_trace

    was_on = obs_trace.enabled()
    if not was_on:
        obs_trace.enable(str(tmp_path / "trace"), "diskless_unit",
                         export_env=False)
    try:
        yield obs_metrics
    finally:
        if not was_on:
            obs_trace.disable()


AK, SK = "AKIDUNITTEST", "unit-secret-key"


@pytest.fixture
def signed_env(monkeypatch):
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", AK)
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", SK)
    monkeypatch.delenv("AWS_SESSION_TOKEN", raising=False)
    monkeypatch.setenv("CTT_S3_SIGN", "1")


@pytest.fixture
def signed_stub(tmp_path, signed_env):
    with StubObjectStore(str(tmp_path / "objroot"), sigv4=(AK, SK)) as srv:
        yield srv


# --------------------------------------------------------------------------
# SigV4 unit surface


class TestSigV4:
    def test_canonical_query_sorts_and_normalizes(self):
        assert sigv4.canonical_query(None) == ""
        assert sigv4.canonical_query("uploads") == "uploads="
        assert (
            sigv4.canonical_query("uploadId=x&partNumber=2")
            == "partNumber=2&uploadId=x"
        )

    def test_signature_is_deterministic_and_payload_bound(self):
        signer = sigv4.SigV4Signer(
            sigv4.Credentials(AK, SK), region="us-east-1"
        )
        kwargs = dict(method="PUT", key="/b/k.json", query=None,
                      host="127.0.0.1:9", amz_date="20260807T000000Z")
        a = signer.sign_headers(payload=b"one", **kwargs)
        b = signer.sign_headers(payload=b"one", **kwargs)
        c = signer.sign_headers(payload=b"two", **kwargs)
        assert a["authorization"] == b["authorization"]
        assert a["authorization"] != c["authorization"]
        assert a["x-amz-content-sha256"] != c["x-amz-content-sha256"]

    def test_resolve_credentials_env_then_file(self, tmp_path, monkeypatch):
        monkeypatch.setenv("AWS_ACCESS_KEY_ID", "envAK")
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "envSK")
        creds = sigv4.resolve_credentials()
        assert (creds.access_key, creds.secret_key) == ("envAK", "envSK")
        monkeypatch.delenv("AWS_ACCESS_KEY_ID")
        monkeypatch.delenv("AWS_SECRET_ACCESS_KEY")
        ini = tmp_path / "credentials"
        ini.write_text(
            "[default]\n"
            "aws_access_key_id = fileAK\n"
            "aws_secret_access_key = fileSK\n"
        )
        monkeypatch.setenv("AWS_SHARED_CREDENTIALS_FILE", str(ini))
        creds = sigv4.resolve_credentials()
        assert (creds.access_key, creds.secret_key) == ("fileAK", "fileSK")
        monkeypatch.setenv("AWS_SHARED_CREDENTIALS_FILE",
                           str(tmp_path / "absent"))
        assert sigv4.resolve_credentials() is None


# --------------------------------------------------------------------------
# signed requests against the verifying stub


class TestSignedRequests:
    def test_signed_roundtrip(self, signed_stub):
        backend = store_backend.backend_for(signed_stub.url)
        key = f"{signed_stub.url}/d/hello.json"
        backend.write_bytes(key, b'{"ok": true}')
        assert backend.read_bytes(key) == b'{"ok": true}'
        assert backend.exists(key)
        assert backend.listdir(f"{signed_stub.url}/d") == ["hello.json"]

    def test_unsigned_rejected_as_retryable_auth_error(
        self, tmp_path, monkeypatch, traced_metrics
    ):
        # signing NOT armed: no CTT_S3_SIGN, plain http:// origin — the
        # store demands signatures, so every verb must surface a
        # retryable OSError (EACCES), never a silent False/missing
        monkeypatch.delenv("CTT_S3_SIGN", raising=False)
        monkeypatch.setenv("CTT_IO_RETRIES", "1")
        monkeypatch.setenv("CTT_IO_BACKOFF_BASE_S", "0.001")
        with StubObjectStore(str(tmp_path / "objroot"),
                             sigv4=(AK, SK)) as srv:
            backend = store_backend.backend_for(srv.url)
            key = f"{srv.url}/d/k.json"
            for op in (
                lambda: backend.read_bytes(key),
                lambda: backend.write_bytes(key, b"x"),
                lambda: backend.exists(key),
                lambda: backend.listdir(f"{srv.url}/d"),
            ):
                with pytest.raises(OSError) as exc_info:
                    op()
                assert not isinstance(
                    exc_info.value, FileNotFoundError
                ), "auth rejection must not read as absence"
            counters = traced_metrics.snapshot()["counters"]
            assert counters.get("store.remote_auth_retries", 0) >= 4

    def test_wrong_key_rejected(self, tmp_path, monkeypatch):
        monkeypatch.setenv("AWS_ACCESS_KEY_ID", AK)
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "not-the-secret")
        monkeypatch.setenv("CTT_S3_SIGN", "1")
        monkeypatch.setenv("CTT_IO_RETRIES", "1")
        monkeypatch.setenv("CTT_IO_BACKOFF_BASE_S", "0.001")
        with StubObjectStore(str(tmp_path / "objroot"),
                             sigv4=(AK, SK)) as srv:
            backend = store_backend.backend_for(srv.url)
            with pytest.raises(OSError):
                backend.write_bytes(f"{srv.url}/d/k.json", b"x")

    def test_s3_scheme_alias(self, tmp_path, signed_env, monkeypatch):
        with StubObjectStore(str(tmp_path / "objroot"),
                             sigv4=(AK, SK)) as srv:
            monkeypatch.setenv("CTT_S3_ENDPOINT", srv.url)
            key = "s3://unit-bucket/prefix/obj.bin"
            assert store_backend.is_remote_path(key)
            backend = store_backend.backend_for(key)
            backend.write_bytes(key, b"via-alias")
            assert backend.read_bytes(key) == b"via-alias"
            # path-style mapping: the object landed under /unit-bucket/
            on_disk = (
                tmp_path / "objroot" / "unit-bucket" / "prefix" / "obj.bin"
            )
            assert on_disk.read_bytes() == b"via-alias"


# --------------------------------------------------------------------------
# multipart upload


class TestMultipart:
    @pytest.fixture
    def small_threshold(self, monkeypatch):
        monkeypatch.setenv("CTT_REMOTE_MULTIPART_MB", "0.002")  # ~2 KB

    def test_multipart_roundtrip_and_counter(
        self, signed_stub, small_threshold, traced_metrics
    ):
        backend = store_backend.backend_for(signed_stub.url)
        payload = os.urandom(11 * 1024)
        key = f"{signed_stub.url}/d/big.bin"
        backend.write_bytes(key, payload)
        assert backend.read_bytes(key) == payload
        counters = traced_metrics.snapshot()["counters"]
        assert counters.get("store.remote_multipart_uploads", 0) == 1
        # staged parts never pollute the served namespace
        assert backend.listdir(f"{signed_stub.url}/d") == ["big.bin"]

    def test_multipart_under_chaos(self, tmp_path, signed_env, monkeypatch):
        monkeypatch.setenv("CTT_REMOTE_MULTIPART_MB", "0.002")
        monkeypatch.setenv("CTT_IO_BACKOFF_BASE_S", "0.001")
        with StubObjectStore(str(tmp_path / "objroot"), sigv4=(AK, SK),
                             fail_rate=0.05, seed=11) as srv:
            backend = store_backend.backend_for(srv.url)
            payload = os.urandom(9 * 1024)
            key = f"{srv.url}/d/chaos.bin"
            backend.write_bytes(key, payload)
            assert backend.read_bytes(key) == payload

    def test_remote_ragged_dataset(
        self, signed_stub, small_threshold, traced_metrics
    ):
        root = f"{signed_stub.url}/scratch/ragged"
        ds = RaggedDataset.create(root, (2, 2), np.uint64)
        assert RaggedDataset.exists(root)
        big = np.arange(4096, dtype=np.uint64)  # 32 KB: multipart path
        ds.write_chunk((0, 1), big)
        ds.write_chunk((1, 0), np.array([7], dtype=np.uint64))
        again = RaggedDataset(root)
        np.testing.assert_array_equal(again.read_chunk((0, 1)), big)
        np.testing.assert_array_equal(
            again.read_chunk((1, 0)), np.array([7], dtype=np.uint64)
        )
        assert again.read_chunk((0, 0)) is None
        counters = traced_metrics.snapshot()["counters"]
        assert counters.get("store.remote_multipart_uploads", 0) >= 1


# --------------------------------------------------------------------------
# remote serve state: JobQueue + fleet beats (+ pagination regression)


class TestRemoteServeState:
    def test_jobqueue_lifecycle_paginated(self, signed_stub):
        backend = store_backend.backend_for(signed_stub.url)
        backend.list_page = 2  # satellite: multi-page listing regression
        q = JobQueue(f"{signed_stub.url}/state/jobs", lease_s=30.0,
                     daemon_id="d0")
        ids = [
            q.submit({"workflow": "w", "tenant": "t", "priority": 0})
            for _ in range(5)
        ]
        assert ids == [f"j{i:06d}" for i in range(1, 6)]
        assert q.stats()["queued"] == 5
        assert len(q.pending()) == 5
        claim = q.claim_next()
        assert claim is not None
        q.renew(claim)
        assert q.complete(claim, {"ok": True})
        rec = q.get(claim.job_id)
        assert rec["result"]["ok"] is True
        stats = q.stats()
        assert stats["queued"] == 4 and stats["running"] == 0

    def test_fleet_beats_paginated(self, signed_stub):
        state = f"{signed_stub.url}/state"
        backend = store_backend.backend_for(signed_stub.url)
        backend.list_page = 2
        for i in range(5):
            FleetBeat(state, f"d{i}", interval_s=30.0).beat()
        peers = read_peers(state)
        assert sorted(peers) == [f"d{i}" for i in range(5)]
        view = FleetView(state)
        assert sorted(view.live()) == [f"d{i}" for i in range(5)]


# --------------------------------------------------------------------------
# clock skew: a store clock running behind must never expire early


class TestClockSkew:
    def test_torn_beat_on_skewed_store_stays_live(
        self, tmp_path, signed_env
    ):
        # the store's clock runs ONE HOUR behind: Last-Modified makes
        # every object look an hour old.  A torn beat (mtime is its only
        # stamp) must still be judged by the local first-seen monotonic
        # cap — never declared dead the moment it appears.
        with StubObjectStore(str(tmp_path / "objroot"), sigv4=(AK, SK),
                             clock_skew_s=-3600.0) as srv:
            state = f"{srv.url}/state"
            beat = FleetBeat(state, "d0", interval_s=30.0)
            beat.beat()
            backend = store_backend.backend_for(srv.url)
            # tear the beat: unparsable JSON, mtime is all that is left
            backend.write_bytes(beat.path, b'{"id": "d0", "wal')
            view = FleetView(state, self_id="observer")
            assert view.is_dead("d0") is not True
            # and it does age out once the observation really is old
            with view._lock:
                first = view._torn_seen[beat.path]
                view._torn_seen[beat.path] = first - 3600.0
            view_peers = view.peers(refresh=True)
            assert "d0" in view_peers
            assert view.is_dead("d0") is True

    def test_torn_lease_on_skewed_store_not_reclaimed_early(
        self, tmp_path, signed_env
    ):
        with StubObjectStore(str(tmp_path / "objroot"), sigv4=(AK, SK),
                             clock_skew_s=-3600.0) as srv:
            q = JobQueue(f"{srv.url}/state/jobs", lease_s=5.0,
                         daemon_id="d0")
            q.submit({"workflow": "w", "tenant": "t"})
            claim = q.claim_next()
            assert claim is not None
            # tear the live lease: a second daemon judging it by the
            # skewed store mtime alone would reclaim instantly
            backend = store_backend.backend_for(srv.url)
            backend.write_bytes(claim.lease_path, b'{"daemon": "d0"')
            q2 = JobQueue(f"{srv.url}/state/jobs", lease_s=5.0,
                          daemon_id="d1")
            assert q2.claim_next() is None, (
                "torn lease on a skew-behind store must not expire early"
            )

    def test_sched_clock_skew_seam(self, signed_stub, monkeypatch):
        # CTT_SCHED_CLOCK_SKEW_S shifts the READER clock: a huge positive
        # skew makes a fresh lease look ancient — the seam the skew
        # tests drive (wall stamps parse fine here, no mtime involved)
        q = JobQueue(f"{signed_stub.url}/state/jobs", lease_s=5.0,
                     daemon_id="d0")
        q.submit({"workflow": "w", "tenant": "t"})
        assert q.claim_next() is not None
        monkeypatch.setenv("CTT_SCHED_CLOCK_SKEW_S", "9000")
        q2 = JobQueue(f"{signed_stub.url}/state/jobs", lease_s=5.0,
                      daemon_id="d1")
        claim2 = q2.claim_next()
        assert claim2 is not None and claim2.gen == 1


# --------------------------------------------------------------------------
# supervisor decision rounds (injected spawn/drain seams)


def _stamp_beat(state_dir, daemon_id, concurrency=1, draining=False):
    store_backend.backend_for(state_dir).makedirs(state_dir)
    FleetBeat(
        state_dir, daemon_id, interval_s=30.0,
        info_fn=lambda: {"concurrency": concurrency, "draining": draining},
    ).beat()


class _Seams:
    def __init__(self, state_dir):
        self.state_dir = state_dir
        self.spawned = []
        self.drained = []

    def spawn(self, daemon_id):
        self.spawned.append(daemon_id)
        _stamp_beat(self.state_dir, daemon_id)
        return object()  # opaque handle without poll(): never reaped

    def drain(self, daemon_id, rec):
        self.drained.append(daemon_id)


class TestSupervisor:
    def _supervisor(self, state_dir, **kw):
        seams = _Seams(state_dir)
        sup = Supervisor(
            state_dir, min_daemons=1, max_daemons=3, poll_s=0.05,
            spawn_fn=seams.spawn, drain_fn=seams.drain,
            supervisor_id="sup-test", **kw,
        )
        return sup, seams

    def test_min_floor_spawns_from_empty(self, tmp_path):
        sup, seams = self._supervisor(str(tmp_path / "state"))
        advice = sup.poll_once()
        assert advice["target"] == 1 and advice["acted"] == "spawn"
        assert len(seams.spawned) == 1

    def test_backlog_scales_up_one_per_round(self, tmp_path):
        state = str(tmp_path / "state")
        sup, seams = self._supervisor(state)
        _stamp_beat(state, "d0")
        q = JobQueue(os.path.join(state, "jobs"))
        for _ in range(6):
            q.submit({"workflow": "w", "tenant": "t"})
        advice = sup.poll_once()
        assert advice["acted"] == "spawn" and len(seams.spawned) == 1
        advice = sup.poll_once()  # backlog still over capacity: one more
        assert advice["acted"] == "spawn" and len(seams.spawned) == 2
        advice = sup.poll_once()  # at max_daemons=3: clamped, holds
        assert advice["target"] == 3
        assert advice["acted"] == "hold" and len(seams.spawned) == 2

    def test_idle_drains_to_floor(self, tmp_path):
        state = str(tmp_path / "state")
        sup, seams = self._supervisor(state)
        for i in range(3):
            _stamp_beat(state, f"d{i}")
        advice = sup.poll_once()
        assert advice["acted"] == "drain" and len(seams.drained) == 1

    def test_restarted_supervisor_adopts_from_beats(
        self, tmp_path, traced_metrics
    ):
        state = str(tmp_path / "state")
        for i in range(2):
            _stamp_beat(state, f"d{i}")
        before = traced_metrics.snapshot()["counters"].get(
            "serve.supervisor_adoptions", 0
        )
        sup, seams = self._supervisor(state)  # fresh: empty child table
        sup.poll_once()
        after = traced_metrics.snapshot()["counters"].get(
            "serve.supervisor_adoptions", 0
        )
        assert after - before == 2
        sup.poll_once()  # already known: no double-count
        assert traced_metrics.snapshot()["counters"].get(
            "serve.supervisor_adoptions", 0
        ) - before == 2

    def test_pending_spawn_counts_toward_target(self, tmp_path):
        state = str(tmp_path / "state")

        class _LiveHandle:
            def poll(self):
                return None  # provably-alive child (a real Popen would)

        spawned = []

        def spawn(daemon_id):  # alive, but its first beat has not landed
            spawned.append(daemon_id)
            return _LiveHandle()

        sup = Supervisor(
            state, min_daemons=1, max_daemons=3, poll_s=0.05,
            spawn_fn=spawn, drain_fn=lambda d, r: None,
            supervisor_id="sup-pend",
        )
        assert sup.poll_once()["acted"] == "spawn" and len(spawned) == 1
        # un-beating child is pending capacity: no overshoot spawn
        assert sup.poll_once()["acted"] == "hold" and len(spawned) == 1
        _stamp_beat(state, spawned[0])  # beat lands: pending -> live
        assert sup.poll_once()["acted"] == "hold" and len(spawned) == 1

    def test_beat_flicker_does_not_trigger_replacement(self, tmp_path):
        state = str(tmp_path / "state")
        sup, seams = self._supervisor(state)
        _stamp_beat(state, "d0")
        assert sup.poll_once()["acted"] == "hold" and not seams.spawned
        # the beat vanishes (stale read / loaded host), but d0 was seen
        # live moments ago: damped, not replaced
        os.unlink(os.path.join(state, "daemon.d0.json"))
        assert sup.poll_once()["acted"] == "hold" and not seams.spawned
        # past the flicker grace the silence is a real death: replace
        sup.flicker_grace_s = 0.0
        assert sup.poll_once()["acted"] == "spawn"
        assert len(seams.spawned) == 1

    def test_hung_spawn_past_grace_stops_counting(self, tmp_path):
        state = str(tmp_path / "state")

        class _LiveHandle:
            def poll(self):
                return None

        spawned = []

        def spawn(daemon_id):
            spawned.append(daemon_id)
            return _LiveHandle()

        sup = Supervisor(
            state, min_daemons=1, max_daemons=3, poll_s=0.05,
            spawn_fn=spawn, drain_fn=lambda d, r: None,
            supervisor_id="sup-hung",
        )
        sup.spawn_grace_s = 0.0  # a hung startup must not wedge scaling
        assert sup.poll_once()["acted"] == "spawn" and len(spawned) == 1
        assert sup.poll_once()["acted"] == "spawn" and len(spawned) == 2

    def test_publishes_schema_conformant_state(self, tmp_path):
        state = str(tmp_path / "state")
        sup, _ = self._supervisor(state)
        sup.poll_once()
        rec = json.loads(
            (tmp_path / "state" / "supervisor.sup-test.json").read_text()
        )
        for key in ("id", "pid", "wall", "mono", "interval_s", "seq",
                    "exiting", "target_daemons"):
            assert key in rec, key
        assert rec["id"] == "sup-test" and rec["exiting"] is False


# --------------------------------------------------------------------------
# conformance over a remote prefix


class TestRemoteConformance:
    def test_remote_state_dir_conforms(self, signed_stub):
        state = f"{signed_stub.url}/state"
        q = JobQueue(f"{state}/jobs", lease_s=30.0, daemon_id="d0")
        q.submit({"schema": 1, "workflow": "w", "tenant": "t"})
        claim = q.claim_next()
        q.complete(claim, {"ok": True})
        _stamp_beat(state, "d0")
        sup = Supervisor(state, spawn_fn=lambda d: object(),
                         drain_fn=lambda d, r: None,
                         supervisor_id="sup-conf")
        sup.poll_once()
        problems, warnings, recognized = conformance_report(state)
        assert problems == []
        assert recognized >= 4  # job, lease, result, beat, supervisor

    def test_remote_unknown_file_flagged(self, signed_stub):
        backend = store_backend.backend_for(signed_stub.url)
        state = f"{signed_stub.url}/state2"
        backend.write_bytes(f"{state}/bogus.dat", b"x")
        problems, _, _ = conformance_report(state)
        assert any("unknown file" in p for p in problems)
