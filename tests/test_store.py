import os

import numpy as np
import pytest

from cluster_tools_tpu.utils.store import File, RaggedDataset, file_reader


@pytest.mark.parametrize("ext", [".zarr", ".n5"])
@pytest.mark.parametrize(
    "dtype", [np.uint8, np.uint32, np.uint64, np.int64, np.float32, np.float64]
)
def test_roundtrip(tmp_path, rng, ext, dtype):
    path = str(tmp_path / f"data{ext}")
    shape, chunks = (40, 33, 17), (16, 16, 16)
    if np.issubdtype(dtype, np.floating):
        data = rng.random(shape).astype(dtype)
    else:
        data = rng.integers(0, 200, shape).astype(dtype)
    with file_reader(path) as f:
        ds = f.create_dataset("vol", shape=shape, dtype=dtype, chunks=chunks)
        ds[:] = data
    with file_reader(path, "r") as f:
        ds = f["vol"]
        assert ds.shape == shape and ds.chunks == chunks and ds.dtype == dtype
        np.testing.assert_array_equal(ds[:], data)
        # partial, non-chunk-aligned read
        np.testing.assert_array_equal(ds[3:29, 5:33, 2:17], data[3:29, 5:33, 2:17])


@pytest.mark.parametrize("ext", [".zarr", ".n5"])
def test_partial_write_rmw(tmp_path, rng, ext):
    path = str(tmp_path / f"data{ext}")
    shape = (20, 20)
    data = rng.integers(0, 100, shape).astype(np.uint32)
    f = file_reader(path)
    ds = f.create_dataset("x", shape=shape, dtype=np.uint32, chunks=(8, 8))
    ds[:] = data
    # overwrite an unaligned region and check the rest is intact
    patch = rng.integers(100, 200, (7, 9)).astype(np.uint32)
    ds[5:12, 3:12] = patch
    expected = data.copy()
    expected[5:12, 3:12] = patch
    np.testing.assert_array_equal(ds[:], expected)


def test_unwritten_chunks_read_as_fill(tmp_path):
    f = file_reader(str(tmp_path / "d.zarr"))
    ds = f.create_dataset("x", shape=(10, 10), dtype=np.float32, chunks=(4, 4))
    np.testing.assert_array_equal(ds[:], np.zeros((10, 10), dtype=np.float32))
    assert ds.read_chunk((0, 0)) is None


def test_chunk_level_io(tmp_path, rng):
    f = file_reader(str(tmp_path / "d.zarr"))
    ds = f.create_dataset("x", shape=(10, 10), dtype=np.uint64, chunks=(4, 4))
    edge = rng.integers(0, 9, (2, 2)).astype(np.uint64)  # clipped edge chunk
    ds.write_chunk((2, 2), edge)
    np.testing.assert_array_equal(ds.read_chunk((2, 2)), edge)
    np.testing.assert_array_equal(ds[8:10, 8:10], edge)


def test_groups_and_attrs(tmp_path):
    for ext in (".zarr", ".n5"):
        f = file_reader(str(tmp_path / f"g{ext}"))
        grp = f.require_group("volumes/seg")
        ds = grp.create_dataset("s0", shape=(8, 8), dtype=np.uint8, chunks=(4, 4))
        ds.attrs["maxId"] = 41
        f.attrs["global"] = [1, 2, 3]
        f2 = file_reader(str(tmp_path / f"g{ext}"), "r")
        assert "volumes" in f2
        assert f2["volumes/seg"]["s0"].attrs["maxId"] == 41
        assert f2.attrs["global"] == [1, 2, 3]
        assert f2["volumes/seg/s0"].shape == (8, 8)


def test_scalar_broadcast_assignment(tmp_path):
    f = file_reader(str(tmp_path / "d.zarr"))
    ds = f.create_dataset("x", shape=(6, 6), dtype=np.int32, chunks=(4, 4))
    ds[1:5, 1:5] = 7
    expected = np.zeros((6, 6), np.int32)
    expected[1:5, 1:5] = 7
    np.testing.assert_array_equal(ds[:], expected)


def test_ragged_dataset(tmp_path, rng):
    f = file_reader(str(tmp_path / "d.zarr"))
    rd = f.create_ragged_dataset("edges", grid_shape=(2, 2), dtype=np.int64)
    a = rng.integers(0, 100, 17).astype(np.int64)
    rd.write_chunk((0, 1), a)
    rd.write_chunk(3, np.array([], dtype=np.int64))
    # reopen through the group API
    rd2 = file_reader(str(tmp_path / "d.zarr"))["edges"]
    np.testing.assert_array_equal(rd2.read_chunk((0, 1)), a)
    assert rd2.read_chunk((1, 1)).size == 0
    assert rd2.read_chunk((0, 0)) is None


def test_n5_zarr_cross_metadata(tmp_path):
    # n5 metadata must be reversed relative to numpy
    import json, os

    f = file_reader(str(tmp_path / "d.n5"))
    f.create_dataset("x", shape=(10, 20, 30), dtype=np.uint16, chunks=(5, 10, 15))
    with open(tmp_path / "d.n5" / "x" / "attributes.json") as fh:
        meta = json.load(fh)
    assert meta["dimensions"] == [30, 20, 10]
    assert meta["blockSize"] == [15, 10, 5]
    assert meta["dataType"] == "uint16"


def test_readonly_mode_enforced(tmp_path):
    f = file_reader(str(tmp_path / "d.zarr"))
    f.create_dataset("x", shape=(4, 4), dtype=np.uint8, chunks=(4, 4))
    ro = file_reader(str(tmp_path / "d.zarr"), "r")
    with pytest.raises(PermissionError):
        ro.create_dataset("y", shape=(4, 4), dtype=np.uint8)
    with pytest.raises(PermissionError):
        ro["x"][:] = 1
    np.testing.assert_array_equal(ro["x"][:], np.zeros((4, 4), np.uint8))


def test_dimension_separator_slash(tmp_path):
    # zarr arrays written by other tools commonly use dimension_separator "/"
    import json, os

    root = tmp_path / "d.zarr" / "x"
    os.makedirs(root)
    meta = {
        "zarr_format": 2, "shape": [4, 4], "chunks": [2, 2], "dtype": "<u2",
        "compressor": None, "fill_value": 3, "order": "C", "filters": None,
        "dimension_separator": "/",
    }
    with open(root / ".zarray", "w") as fh:
        json.dump(meta, fh)
    os.makedirs(root / "1")
    chunk = np.arange(4, dtype="<u2").reshape(2, 2)
    with open(root / "1" / "0", "wb") as fh:
        fh.write(chunk.tobytes())
    with open(tmp_path / "d.zarr" / ".zgroup", "w") as fh:
        json.dump({"zarr_format": 2}, fh)
    ds = file_reader(str(tmp_path / "d.zarr"), "r")["x"]
    np.testing.assert_array_equal(ds[2:4, 0:2], chunk)
    # unwritten chunks honor fill_value
    assert (ds[0:2, 0:2] == 3).all()


def test_int_index_drops_axis(tmp_path, rng):
    f = file_reader(str(tmp_path / "d.zarr"))
    data = rng.integers(0, 99, (6, 5, 4)).astype(np.int32)
    ds = f.create_dataset("x", data=data, chunks=(3, 3, 3))
    np.testing.assert_array_equal(ds[2], data[2])
    np.testing.assert_array_equal(ds[-1], data[-1])
    np.testing.assert_array_equal(ds[1:3, -2], data[1:3, -2])
    with pytest.raises(IndexError):
        ds[7]


class TestH5FacadeDatasets:
    """The h5 façade's create/require_dataset: our compression vocabulary
    mapped onto h5py, scalar/empty datasets skip filters, dtype honored
    with data, loud dtype conformance on reuse."""

    def test_compression_vocabulary_and_scalars(self, tmp_path):
        pytest.importorskip("h5py")
        from cluster_tools_tpu.utils import store

        f = store.file_reader(str(tmp_path / "v.h5"), "a")
        f.create_dataset("scalar", data=np.bytes_("meta"))  # no filter crash
        f.create_dataset("empty", shape=(0,), dtype="uint64", chunks=(64,))
        d = f.create_dataset(
            "blosc_req", data=np.arange(32.0), compression="blosc"
        )
        assert d.compression == "gzip"  # house codecs map onto h5py's gzip
        r = f.create_dataset("raw", data=np.arange(8), compression="raw")
        assert r.compression is None

    def test_str_data_and_shape_with_data(self, tmp_path):
        """h5py semantics preserved: str stored as vlen string; an explicit
        shape reshapes the data."""
        pytest.importorskip("h5py")
        from cluster_tools_tpu.utils import store

        f = store.file_reader(str(tmp_path / "s.h5"), "a")
        f.create_dataset("s", data="hello")  # vlen string, no U-dtype crash
        assert f["s"][()] in (b"hello", "hello")
        d = f.create_dataset("r", shape=(2, 2), data=np.arange(4))
        assert d.shape == (2, 2)

    def test_dtype_with_data_and_reuse_conformance(self, tmp_path):
        pytest.importorskip("h5py")
        from cluster_tools_tpu.utils import store

        f = store.file_reader(str(tmp_path / "d.h5"), "a")
        d = f.create_dataset("typed", data=[1, 2, 3], dtype="uint32")
        assert d.dtype == np.uint32
        f.require_dataset("typed", shape=(3,), dtype="uint32")  # ok
        f.require_dataset("typed", shape=(3,), dtype="uint16")  # safe cast ok
        with pytest.raises(TypeError, match="dtype"):
            f.require_dataset("typed", shape=(3,), dtype="float64")
        with pytest.raises(ValueError, match="shape"):
            f.require_dataset("typed", shape=(5,), dtype="uint32")


class TestH5HandleCache:
    def test_same_file_read_then_write(self, tmp_path):
        """HDF5 refuses two opens with different modes per process; the
        cached-handle façade must let a task read its input and write its
        output in the same .h5 (ADVICE r2 follow-up)."""
        h5py = pytest.importorskip("h5py")
        from cluster_tools_tpu.utils import store

        path = str(tmp_path / "same.h5")
        f = store.file_reader(path, "a")
        f.create_dataset("in", data=np.arange(8.0))
        # hold a read handle open, then open for write — no OSError
        r = store.file_reader(path, "r")
        _ = r["in"][:]
        w = store.file_reader(path, "a")
        w.create_dataset("out", data=np.arange(8.0) * 2)
        np.testing.assert_array_equal(w["out"][:], np.arange(8.0) * 2)
        # `with` must not close the shared cached handle
        with store.file_reader(path, "r") as fh:
            np.testing.assert_array_equal(fh["in"][:], np.arange(8.0))
        np.testing.assert_array_equal(r["in"][:], np.arange(8.0))

    def test_read_first_then_write_keeps_datasets_live(self, tmp_path):
        """The order tasks/base.py uses: input_ds('r') before output 'a' on
        the same file — the read-only→writable reopen must not invalidate
        the dataset handed out earlier."""
        pytest.importorskip("h5py")
        from cluster_tools_tpu.utils import store

        path = str(tmp_path / "order.h5")
        store.file_reader(path, "a").create_dataset("in", data=np.arange(6.0))
        store.release_h5_handles()
        ds = store.file_reader(path, "r")["in"]  # read-only proxy
        w = store.file_reader(path, "a")         # triggers the reopen
        w.create_dataset("out", data=np.zeros(2))
        np.testing.assert_array_equal(ds[:], np.arange(6.0))  # still live

    def test_mode_w_refuses_while_cached(self, tmp_path):
        """Truncating a file that is open elsewhere in the process must stay
        a loud error (raw h5py raises there too), not a silent clobber."""
        pytest.importorskip("h5py")
        from cluster_tools_tpu.utils import store

        path = str(tmp_path / "trunc.h5")
        f = store.file_reader(path, "a")
        f.create_dataset("x", data=np.ones(4))
        with pytest.raises(OSError, match="open elsewhere"):
            store.file_reader(path, "w")
        # after releasing, truncation works
        store.release_h5_handles()
        f2 = store.file_reader(path, "w")
        assert "x" not in f2

    def test_last_close_releases_handle(self, tmp_path):
        """ADVICE r3: `with file_reader(...)` must really close the cached
        handle (and the HDF5 file lock) on the LAST close — while earlier
        closes over still-referenced handles only flush."""
        pytest.importorskip("h5py")
        from cluster_tools_tpu.utils import store

        path = str(tmp_path / "refs.h5")
        with store.file_reader(path, "a") as f:
            f.create_dataset("x", data=np.arange(4.0))
        assert os.path.abspath(path) not in store._H5_HANDLES  # really closed
        # nested opens: inner close keeps the handle, outer close releases
        a = store.file_reader(path, "r")
        with store.file_reader(path, "r") as b:
            _ = b["x"][:]
        assert os.path.abspath(path) in store._H5_HANDLES
        a.close()
        assert os.path.abspath(path) not in store._H5_HANDLES
        # double-close of one façade must not steal someone else's ref
        c = store.file_reader(path, "r")
        d = store.file_reader(path, "r")
        c.close()
        c.close()
        assert os.path.abspath(path) in store._H5_HANDLES
        d.close()
        assert os.path.abspath(path) not in store._H5_HANDLES
        # proxies re-resolve after the release
        ds = store.file_reader(path, "r")["x"]
        store.release_h5_handles()
        np.testing.assert_array_equal(ds[:], np.arange(4.0))

    def test_exclusive_create_semantics_preserved(self, tmp_path):
        pytest.importorskip("h5py")
        from cluster_tools_tpu.utils import store

        path = str(tmp_path / "excl.h5")
        store.file_reader(path, "a").create_dataset("x", data=np.ones(2))
        with pytest.raises(OSError):
            store.file_reader(path, "w-")  # cached handle → loud error
        store.release_h5_handles()
        with pytest.raises(Exception):
            store.file_reader(path, "w-")  # file exists → h5py raises


class TestThreadedRegionRead:
    def test_threaded_read_matches_serial(self, tmp_path, rng):
        from cluster_tools_tpu.utils import store

        data = rng.random((24, 24, 24)).astype("float32")
        path = str(tmp_path / "thr.n5")
        f = store.file_reader(path)
        f.create_dataset("x", data=data, chunks=(8, 8, 8))
        ds = store.file_reader(path, "r")["x"]
        serial = ds[2:22, 3:21, 0:24]
        store.set_read_threads(ds, 4)
        threaded = ds[2:22, 3:21, 0:24]
        np.testing.assert_array_equal(serial, threaded)
        np.testing.assert_array_equal(threaded, data[2:22, 3:21, 0:24])

    def test_set_read_threads_tolerates_h5(self, tmp_path):
        h5py = pytest.importorskip("h5py")
        from cluster_tools_tpu.utils import store

        path = str(tmp_path / "t.h5")
        f = store.file_reader(path, "a")
        f.create_dataset("x", data=np.ones(4))
        store.set_read_threads(f["x"], 4)  # raw h5py dataset: no-op, no raise
