"""Block-wise NN inference: flax U-Net forward, checkpoint round-trip,
InferenceTask with channel mapping / mask / uint8 quantization, torch compat."""

import os

import numpy as np
import pytest

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    import jax
    import jax.numpy as jnp

    from cluster_tools_tpu.models import UNet3D, save_checkpoint

    path = str(tmp_path_factory.mktemp("ckpt") / "unet")
    model_conf = {
        "model": "UNet3D",
        "out_channels": 2,
        "initial_features": 4,
        "depth": 2,
        "scale_factors": [[1, 2, 2]],
        "in_channels": 1,
    }
    model = UNet3D(
        out_channels=2, initial_features=4, depth=2, scale_factors=[[1, 2, 2]]
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 1, 8, 16, 16), jnp.float32)
    )
    save_checkpoint(path, params, model_conf)
    return path, model, params


class TestUNet:
    def test_forward_shape_and_range(self, checkpoint, rng):
        import jax.numpy as jnp

        path, model, params = checkpoint
        x = jnp.asarray(rng.random((1, 1, 8, 16, 16), dtype=np.float32))
        y = np.asarray(model.apply(params, x))
        assert y.shape == (1, 2, 8, 16, 16)
        assert 0.0 <= y.min() and y.max() <= 1.0  # sigmoid head

    def test_checkpoint_roundtrip(self, checkpoint, rng):
        import jax.numpy as jnp

        from cluster_tools_tpu.models import load_checkpoint

        path, model, params = checkpoint
        model2, params2 = load_checkpoint(path)
        x = jnp.asarray(rng.random((1, 1, 8, 16, 16), dtype=np.float32))
        np.testing.assert_allclose(
            np.asarray(model.apply(params, x)),
            np.asarray(model2.apply(params2, x)),
            rtol=1e-5, atol=1e-6,
        )


class TestInferenceTask:
    def _volume(self, tmp_path, rng, shape=(16, 32, 32)):
        path = str(tmp_path / "iv.n5")
        raw = rng.random(shape).astype("float32")
        file_reader(path).create_dataset("raw", data=raw, chunks=(8, 16, 16))
        return path, raw

    def test_inference_channels_and_quantization(self, tmp_path, rng, checkpoint):
        from cluster_tools_tpu.tasks.inference import InferenceTask

        ckpt, model, params = checkpoint
        path, raw = self._volume(tmp_path, rng)
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})

        halo = [2, 4, 4]
        task = InferenceTask(
            tmp_folder, config_dir,
            input_path=path, input_key="raw",
            output_path=path,
            output_key={"affs": [0, 2], "bmap": [0, 1]},
            checkpoint_path=ckpt,
            halo=halo,
            framework="jax",
        )
        assert build([task])
        f = file_reader(path, "r")
        affs = f["affs"]
        bmap = f["bmap"]
        assert affs.shape == (2, 16, 32, 32) and str(affs.dtype) == "uint8"
        assert bmap.shape == (16, 32, 32)

        # oracle: recompute one interior block through the raw predictor path
        from cluster_tools_tpu.tasks.frameworks import JaxPredictor
        from cluster_tools_tpu.tasks.inference import (
            load_input_with_halo,
            to_uint8,
        )
        from cluster_tools_tpu.tasks.frameworks import (
            preprocess_zero_mean_unit_variance,
        )

        pred = JaxPredictor(ckpt, halo)
        data = load_input_with_halo(f["raw"], (8, 16, 16), (8, 16, 16), halo)
        out = pred(preprocess_zero_mean_unit_variance(data))
        want = to_uint8(out)
        got = affs[(slice(None), slice(8, 16), slice(16, 32), slice(16, 32))]
        np.testing.assert_array_equal(got, want)

    def test_inference_respects_mask(self, tmp_path, rng, checkpoint):
        from cluster_tools_tpu.tasks.inference import InferenceTask

        ckpt, _, _ = checkpoint
        path, raw = self._volume(tmp_path, rng)
        mask = np.zeros((16, 32, 32), dtype="uint8")
        mask[:8] = 1  # only the upper half
        file_reader(path).create_dataset("mask", data=mask, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs_m")
        tmp_folder = str(tmp_path / "tmp_m")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        cfg.write_config(config_dir, "inference", {"dtype": "float32"})
        task = InferenceTask(
            tmp_folder, config_dir,
            input_path=path, input_key="raw",
            output_path=path, output_key={"pred": [0, 1]},
            checkpoint_path=ckpt, halo=[0, 0, 0],
            mask_path=path, mask_key="mask",
            framework="jax",
        )
        assert build([task])
        pred = file_reader(path, "r")["pred"][:]
        assert np.abs(pred[:8]).sum() > 0
        assert (pred[8:] == 0).all()  # masked-out blocks untouched

    def test_channel_accumulation(self, tmp_path, rng, checkpoint):
        from cluster_tools_tpu.tasks.inference import InferenceTask

        ckpt, _, _ = checkpoint
        path, raw = self._volume(tmp_path, rng)
        config_dir = str(tmp_path / "configs_a")
        tmp_folder = str(tmp_path / "tmp_a")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        cfg.write_config(
            config_dir, "inference",
            {"dtype": "float32", "channel_accumulation": "max"},
        )
        task = InferenceTask(
            tmp_folder, config_dir,
            input_path=path, input_key="raw",
            output_path=path, output_key={"acc": [0, 2]},
            checkpoint_path=ckpt, halo=[0, 0, 0],
            framework="jax",
        )
        assert build([task])
        acc = file_reader(path, "r")["acc"]
        assert acc.shape == (16, 32, 32)  # reduced over channels


class TestMultiscaleInference:
    def test_center_aligned_levels(self, tmp_path, rng, monkeypatch):
        from cluster_tools_tpu.tasks import frameworks
        from cluster_tools_tpu.tasks.multiscale_inference import (
            MultiscaleInferenceTask,
        )

        shape = (16, 32, 32)
        # coordinate field: value = x coordinate (physical units)
        vol = np.broadcast_to(
            np.arange(shape[2], dtype="float32"), shape
        ).copy()
        down = vol[::2, ::2, ::2] * 1.0  # scale-1: value = 2*x_coarse
        path = str(tmp_path / "ms.n5")
        f = file_reader(path)
        f.create_dataset("s0", data=vol, chunks=(8, 16, 16))
        f.create_dataset("s1", data=down.astype("float32"), chunks=(8, 16, 16))

        centers = []

        class Stub:
            def __init__(self, checkpoint_path, halo, **kw):
                self.halo = list(halo)

            def __call__(self, data):
                fine, coarse = data
                fc = fine[tuple(s // 2 for s in fine.shape)]
                cc = coarse[tuple(s // 2 for s in coarse.shape)]
                centers.append((float(fc), float(cc)))
                crop = tuple(
                    slice(h, s - h if h else None)
                    for h, s in zip(self.halo, fine.shape)
                )
                return fine[crop][None]

        monkeypatch.setitem(frameworks.PREDICTORS, "stub", Stub)

        config_dir = str(tmp_path / "configs_ms")
        tmp_folder = str(tmp_path / "tmp_ms")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        cfg.write_config(
            config_dir, "multiscale_inference",
            {"dtype": "float32", "preprocess": "none"},
        )
        task = MultiscaleInferenceTask(
            tmp_folder, config_dir,
            input_paths=[path, path], input_keys=["s0", "s1"],
            scale_factors=[[1, 1, 1], [2, 2, 2]],
            halos=[[2, 4, 4], [1, 2, 2]],
            output_path=path, output_key={"out": [0, 1]},
            checkpoint_path="unused", halo=[2, 4, 4],
            framework="stub",
        )
        assert build([task])
        # identity head: output equals the fine input
        out = file_reader(path, "r")["out"][:]
        np.testing.assert_allclose(out, vol, rtol=1e-6)
        # center alignment: the coarse center sees (almost) the same physical
        # x coordinate as the fine center
        assert centers
        # down[..., xc] = vol[..., 2*xc] = physical x, so both centers carry
        # physical coordinates directly
        for fc, cc in centers:
            assert abs(fc - cc) <= 2.0, (fc, cc)


class TestPytorchCompat:
    def test_torchscript_predictor(self, tmp_path, rng):
        torch = pytest.importorskip("torch")
        from cluster_tools_tpu.tasks.frameworks import PytorchPredictor

        class Tiny(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.conv = torch.nn.Conv3d(1, 2, 3, padding=1)

            def forward(self, x):
                return torch.sigmoid(self.conv(x))

        model = torch.jit.script(Tiny())
        ckpt = str(tmp_path / "tiny.pt")
        model.save(ckpt)

        pred = PytorchPredictor(ckpt, halo=[1, 1, 1])
        x = rng.random((8, 12, 12)).astype("float32")
        out = pred(x)
        assert out.shape == (2, 6, 10, 10)  # halo cropped
        assert 0.0 <= out.min() and out.max() <= 1.0


def _torch_test_models():
    """Module-level (hence picklable) torch test models, built lazily so the
    file imports without torch."""
    global _TinyTorch, _WrapperTorch
    import torch

    if "_TinyTorch" in globals():
        return _TinyTorch, _WrapperTorch

    class _TinyTorch(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.conv = torch.nn.Conv3d(1, 2, 3, padding=1)
            self.out_channels = 2

        def forward(self, x):
            return self.conv(x)

    class _WrapperTorch(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.unet = _TinyTorch()

        def forward(self, x):  # trainer wrapper does something else
            raise AssertionError("surgery should bypass the wrapper")

    # pickling by reference needs module-level qualnames (the classes are
    # defined inside this function; the `global` statement binds the names)
    _TinyTorch.__qualname__ = "_TinyTorch"
    _WrapperTorch.__qualname__ = "_WrapperTorch"
    return _TinyTorch, _WrapperTorch


class TestEagerTorchCheckpoints:
    """Non-torchscript checkpoint flavors (reference frameworks.py:76,145 +
    the state-dict loader the reference left as a TODO at :37) and the
    torch-side surgery hooks (prep_model.py:9-23)."""

    def _tiny(self, _torch=None):
        return _torch_test_models()[0]

    def test_state_dict_with_dotted_model_class(self, tmp_path, rng):
        torch = pytest.importorskip("torch")
        from cluster_tools_tpu.tasks.frameworks import PytorchPredictor

        model = torch.nn.Conv3d(1, 2, 3, padding=1)
        ckpt = str(tmp_path / "sd.pt")
        torch.save(model.state_dict(), ckpt)
        pred = PytorchPredictor(
            ckpt, halo=[0, 0, 0], model_class="torch.nn.Conv3d",
            model_kwargs={"in_channels": 1, "out_channels": 2,
                          "kernel_size": 3, "padding": 1},
        )
        x = rng.random((4, 8, 8)).astype("float32")
        want = model(torch.from_numpy(x)[None, None]).detach().numpy()[0]
        np.testing.assert_allclose(pred(x), want, rtol=1e-5, atol=1e-6)

    def test_nested_state_dict_add_sigmoid_mixed_precision(self, tmp_path, rng):
        torch = pytest.importorskip("torch")
        from cluster_tools_tpu.tasks.frameworks import PytorchPredictor

        Tiny = self._tiny(torch)
        model = Tiny()
        ckpt = str(tmp_path / "nested.pt")
        torch.save({"model_state_dict": model.state_dict()}, ckpt)
        pred = PytorchPredictor(
            ckpt, halo=[0, 0, 0], model_class=Tiny,
            prep_model="add_sigmoid", mixed_precision=True,
        )
        out = pred(rng.random((4, 8, 8)).astype("float32"))
        assert out.shape == (2, 4, 8, 8)
        assert 0.0 <= out.min() and out.max() <= 1.0  # sigmoid applied
        assert out.dtype == np.float32  # autocast output recast

    def test_pickled_module_extract_unet(self, tmp_path, rng):
        torch = pytest.importorskip("torch")
        from cluster_tools_tpu.tasks.frameworks import PytorchPredictor

        Wrapper = _torch_test_models()[1]
        ckpt = str(tmp_path / "wrapped.pt")
        torch.save(Wrapper(), ckpt)
        pred = PytorchPredictor(ckpt, halo=[0, 0, 0], prep_model="extract_unet")
        out = pred(rng.random((4, 8, 8)).astype("float32"))
        assert out.shape == (2, 4, 8, 8)

    def test_inferno_checkpoint_directory_use_best(self, tmp_path, rng):
        torch = pytest.importorskip("torch")
        from cluster_tools_tpu.tasks.frameworks import PytorchPredictor

        Tiny = self._tiny(torch)
        best, last = Tiny(), Tiny()
        wdir = tmp_path / "ckpt" / "Weights"
        wdir.mkdir(parents=True)
        torch.save({"model": best}, str(wdir / "best_checkpoint.pytorch"))
        torch.save({"model": last}, str(wdir / "checkpoint.pytorch"))
        x = rng.random((4, 8, 8)).astype("float32")
        for use_best, model in ((True, best), (False, last)):
            pred = PytorchPredictor(
                str(tmp_path / "ckpt"), halo=[0, 0, 0], use_best=use_best
            )
            want = model(torch.from_numpy(x)[None, None]).detach().numpy()[0]
            np.testing.assert_allclose(pred(x), want, rtol=1e-5, atol=1e-6)

    def test_state_dict_without_model_class_raises(self, tmp_path):
        torch = pytest.importorskip("torch")
        from cluster_tools_tpu.tasks.frameworks import PytorchPredictor

        ckpt = str(tmp_path / "bare.pt")
        torch.save(torch.nn.Conv3d(1, 1, 3).state_dict(), ckpt)
        with pytest.raises(ValueError, match="model_class"):
            PytorchPredictor(ckpt, halo=[0, 0, 0])


class TestMirrorTTA:
    def test_flip_sets(self):
        from cluster_tools_tpu.tasks.frameworks import mirror_flip_sets

        assert len(mirror_flip_sets(3)) == 8
        assert len(mirror_flip_sets(2)) == 4
        with pytest.raises(ValueError):
            mirror_flip_sets(1)

    def test_tta_identity_for_equivariant_forward(self, rng):
        """A flip-equivariant forward (elementwise) must be unchanged by TTA
        up to float accumulation."""
        from cluster_tools_tpu.tasks.frameworks import mirror_tta

        x = rng.random((1, 1, 4, 6, 6)).astype("float32")
        fwd = lambda d: d * 2.0 + 1.0
        np.testing.assert_allclose(mirror_tta(fwd, 3)(x), fwd(x), rtol=1e-6)

    def test_tta_averages_out_orientation_bias(self, rng):
        """A forward that leaks absolute position produces a symmetric output
        under TTA — the averaging cancels the bias."""
        from cluster_tools_tpu.tasks.frameworks import mirror_tta

        x = np.zeros((1, 1, 2, 4, 4), dtype="float32")

        def biased(d):
            out = d.copy()
            out[..., 0] += 1.0  # depends on absolute x position
            return out

        out = mirror_tta(biased, 3)(x)
        # averaged over flips, the +1 at x=0 spreads to x=0 and x=-1 equally
        np.testing.assert_allclose(out[..., 0], out[..., -1])
        assert np.allclose(out[..., 0], 0.5)


    def test_invalid_augmentation_mode_rejected(self, checkpoint):
        from cluster_tools_tpu.tasks.frameworks import JaxPredictor

        ckpt, model, params = checkpoint
        with pytest.raises(ValueError, match="augmentation_mode"):
            JaxPredictor(ckpt, [0, 0, 0], augmentation_mode="offsets")

    def test_jax_predictor_tta_matches_manual_average(self, checkpoint, rng):
        from cluster_tools_tpu.tasks.frameworks import (
            JaxPredictor,
            mirror_flip_sets,
        )

        ckpt, model, params = checkpoint
        x = rng.random((8, 16, 16)).astype("float32")
        plain = JaxPredictor(ckpt, [0, 0, 0])
        tta = JaxPredictor(ckpt, [0, 0, 0], augmentation_mode="all")
        got = tta(x)
        acc = None
        for axes in mirror_flip_sets(3):
            out = plain(np.ascontiguousarray(np.flip(x, axes) if axes else x))
            out = np.flip(out, axes) if axes else out
            acc = out.astype("float32") if acc is None else acc + out
        np.testing.assert_allclose(got, acc / 8, rtol=1e-5, atol=1e-6)

    def test_inference_task_with_tta_runs(self, tmp_path, rng, checkpoint):
        from cluster_tools_tpu.tasks.inference import InferenceTask

        ckpt, model, params = checkpoint
        path = str(tmp_path / "tta.n5")
        raw = rng.random((8, 16, 16)).astype("float32")
        file_reader(path).create_dataset("raw", data=raw, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs_tta")
        tmp_folder = str(tmp_path / "tmp_tta")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        cfg.write_config(
            config_dir, "inference",
            {"augmentation_mode": "all", "dtype": "float32"},
        )
        task = InferenceTask(
            tmp_folder, config_dir,
            input_path=path, input_key="raw",
            output_path=path, output_key={"bmap": [0, 1]},
            checkpoint_path=ckpt, halo=[0, 0, 0], framework="jax",
        )
        assert build([task])
        out = file_reader(path, "r")["bmap"][:]
        assert out.shape == raw.shape and np.isfinite(out).all()


class TestLinearTransformationWorkflow:
    def test_composite_and_in_place_default(self, tmp_path, rng):
        import json as _json

        from cluster_tools_tpu.workflows import LinearTransformationWorkflow

        path = str(tmp_path / "lt.n5")
        raw = rng.random((16, 16, 16)).astype("float32")
        f = file_reader(path)
        f.create_dataset("raw", data=raw, chunks=(8, 8, 8))
        f.create_dataset("raw2", data=raw, chunks=(8, 8, 8))
        trafo_file = str(tmp_path / "trafo.json")
        with open(trafo_file, "w") as fh:
            _json.dump({"a": 3.0, "b": 1.0}, fh)
        config_dir = str(tmp_path / "configs_lt")
        cfg.write_global_config(config_dir, {"block_shape": [8, 8, 8]})
        # explicit output
        wf = LinearTransformationWorkflow(
            str(tmp_path / "tmp_lt"), config_dir,
            input_path=path, input_key="raw",
            transformation=trafo_file,
            output_path=path, output_key="out",
        )
        assert build([wf])
        np.testing.assert_allclose(
            file_reader(path, "r")["out"][:], 3.0 * raw + 1.0, rtol=1e-5
        )
        # in-place when output is omitted (reference
        # transformation_workflows.py:21-24)
        wf2 = LinearTransformationWorkflow(
            str(tmp_path / "tmp_lt2"), config_dir,
            input_path=path, input_key="raw2",
            transformation=trafo_file,
        )
        assert build([wf2])
        np.testing.assert_allclose(
            file_reader(path, "r")["raw2"][:], 3.0 * raw + 1.0, rtol=1e-5
        )
        # config surface advertises the linear task
        assert "linear" in LinearTransformationWorkflow.get_config()


class TestMixedPrecision:
    def test_checkpoint_dtype_knob(self, tmp_path, rng):
        """model.json may pin the compute dtype: float32 runs full precision,
        the bfloat16 default is the MXU-native mixed mode — outputs agree to
        bf16 tolerance (reference frameworks.py:53-57 apex mixed precision)."""
        import jax
        import jax.numpy as jnp

        from cluster_tools_tpu.models import UNet3D, save_checkpoint
        from cluster_tools_tpu.tasks.frameworks import JaxPredictor

        x = rng.random((8, 16, 16)).astype("float32")
        outs = {}
        for dt in ("bfloat16", "float32"):
            conf = {
                "model": "UNet3D", "out_channels": 1, "initial_features": 4,
                "depth": 2, "scale_factors": [[1, 2, 2]], "in_channels": 1,
                "dtype": dt,
            }
            # construct the model FROM the sidecar dict so the saved config
            # and the tested model cannot diverge
            kwargs = {k: v for k, v in conf.items()
                      if k not in ("model", "in_channels")}
            kwargs["dtype"] = jnp.dtype(kwargs["dtype"])
            model = UNet3D(**kwargs)
            params = model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 1, 8, 16, 16), "float32")
            )
            path = str(tmp_path / f"ckpt_{dt}")
            save_checkpoint(path, params, conf)
            pred = JaxPredictor(path, [0, 0, 0])
            out = pred(x)
            assert out.dtype == np.float32  # outputs come back f32 either way
            outs[dt] = out
        np.testing.assert_allclose(
            outs["bfloat16"], outs["float32"], atol=0.05, rtol=0.05
        )
