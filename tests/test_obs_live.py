"""ctt-watch: incremental tailer, heartbeats, stragglers, OpenMetrics.

Covers the live-path contract:
  * cursor correctness across appends, including a torn trailing line
    (not consumed until the newline lands) and complete-but-corrupt lines
    (skipped + counted, never fatal — the watcher outlives bad records);
  * stale-heartbeat detection against a faked reader clock, and the
    ``exiting`` beat that distinguishes clean exit from death;
  * straggler flagging (in-flight block age vs k x median);
  * z-slab heatmap determinism (golden text);
  * OpenMetrics exposition validity (prometheus_client parser when
    importable, exposition-grammar regex fallback otherwise);
  * disabled-overhead smoke: no heartbeat thread / no files without
    ``CTT_TRACE_DIR``;
  * the ``watch`` CLI exit-code contract (0 progress / 1 none / 4 stall);
  * golden machine-readable output for ``summarize --json`` and
    ``diff --json`` (the bench/CI interface — satellite);
  * SIGTERM preemption flush (metrics + shards + final exiting beat).
"""

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

import pytest

from cluster_tools_tpu.obs import heartbeat, metrics, trace
from cluster_tools_tpu.obs.live import (
    LiveRun,
    format_heatmap,
    format_watch,
    render_openmetrics,
    resolve_live_dir,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WALL0, MONO0 = 1000.0, 10.0


def _obs_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "cluster_tools_tpu.obs", *args],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def _header(run_id="live", pid=1, tid=1, wall=WALL0, mono=MONO0):
    return json.dumps({
        "type": "header", "run": run_id, "pid": pid, "tid": tid,
        "host": "synth", "wall": wall, "mono": mono,
    })


def _block_span(sid, task, bid, t0, dur, name="block", kind="host",
                pid=1, tid=1, error=None, block_ids=None):
    attrs = {"task": task}
    if block_ids is not None:
        attrs["block_ids"] = block_ids
    else:
        attrs["block"] = bid
    if error:
        attrs["error"] = error
    return json.dumps({
        "type": "span", "id": sid, "parent": None, "name": name,
        "kind": kind, "t0": t0, "t1": t0 + dur, "pid": pid, "tid": tid,
        "attrs": attrs,
    })


def _task_span(sid, name, t0, dur, pid=1, tid=1):
    return json.dumps({
        "type": "span", "id": sid, "parent": None, "name": name,
        "kind": "task", "t0": t0, "t1": t0 + dur, "pid": pid, "tid": tid,
    })


def _write_hb(run_dir, pid, wall, mono=500.0, interval=1.0, exiting=False,
              task=None, total=0, done=0, failed=0, current=(),
              role="worker", job_id=None, grid=None, mem=None):
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, f"hb.p{pid}.json"), "w") as f:
        json.dump({
            "pid": pid, "host": "synth", "role": role, "job_id": job_id,
            "run": "live", "wall": wall, "mono": mono,
            "interval_s": interval, "seq": 1, "exiting": exiting,
            "task": task, "blocks_total": total, "blocks_done": done,
            "blocks_failed": failed, "blocks_retried": 0, "grid": grid,
            "current_blocks": [
                {"id": b, "start_mono": m} for b, m in current
            ],
            "device_mem_peak_bytes": mem,
        }, f)


# --------------------------------------------------------------------------
# incremental cursors


class TestIncrementalCursor:
    def test_appends_accumulate_across_polls(self, tmp_path):
        run = str(tmp_path / "r")
        os.makedirs(run)
        shard = os.path.join(run, "spans.p1.t1.jsonl")
        with open(shard, "w") as f:
            f.write(_header() + "\n")
            f.write(_block_span(1, "t", 0, 11.0, 1.0) + "\n")
            f.write(_block_span(2, "t", 1, 12.0, 1.0) + "\n")
        live = LiveRun(run)
        snap = live.poll()
        assert snap["run_id"] == "live"
        assert snap["tasks"]["t"]["blocks_done"] == 2
        size_after_first = os.path.getsize(shard)

        with open(shard, "a") as f:
            f.write(_block_span(3, "t", 2, 13.0, 1.0) + "\n")
        snap = live.poll()
        assert snap["tasks"]["t"]["blocks_done"] == 3
        # the cursor moved past everything consumed
        assert live._offsets[shard] == os.path.getsize(shard)
        assert live._offsets[shard] > size_after_first

    def test_torn_trailing_line_not_consumed_until_complete(self, tmp_path):
        run = str(tmp_path / "r")
        os.makedirs(run)
        shard = os.path.join(run, "spans.p1.t1.jsonl")
        full_line = _block_span(2, "t", 1, 12.0, 1.0)
        with open(shard, "w") as f:
            f.write(_header() + "\n")
            f.write(_block_span(1, "t", 0, 11.0, 1.0) + "\n")
            f.write(full_line[:25])  # a writer mid-write
        live = LiveRun(run)
        snap = live.poll()
        assert snap["tasks"]["t"]["blocks_done"] == 1
        assert snap["malformed_lines"] == 0  # torn != malformed
        offset_before = live._offsets[shard]

        # the writer finishes the line: the SAME bytes now parse
        with open(shard, "a") as f:
            f.write(full_line[25:] + "\n")
        snap = live.poll()
        assert snap["tasks"]["t"]["blocks_done"] == 2
        assert snap["malformed_lines"] == 0
        assert live._offsets[shard] > offset_before

    def test_complete_garbage_line_skipped_not_fatal(self, tmp_path):
        run = str(tmp_path / "r")
        os.makedirs(run)
        shard = os.path.join(run, "spans.p1.t1.jsonl")
        with open(shard, "w") as f:
            f.write(_header() + "\n")
            f.write("this is not json\n")
            f.write(_block_span(1, "t", 0, 11.0, 1.0) + "\n")
        snap = LiveRun(run).poll()
        # the watcher keeps going where the post-mortem exporter raises
        assert snap["malformed_lines"] == 1
        assert snap["tasks"]["t"]["blocks_done"] == 1

    def test_batch_spans_attribute_per_block(self, tmp_path):
        run = str(tmp_path / "r")
        os.makedirs(run)
        with open(os.path.join(run, "spans.p1.t1.jsonl"), "w") as f:
            f.write(_header() + "\n")
            f.write(_block_span(
                1, "t", None, 11.0, 2.0, name="block_batch", kind="device",
                block_ids=[0, 1, 2, 3],
            ) + "\n")
        live = LiveRun(run)
        snap = live.poll()
        assert snap["tasks"]["t"]["blocks_done"] == 4
        hm = live.heatmap("t")
        # the 2 s batch wall splits evenly over its 4 blocks
        assert hm["durations"] == {0: 0.5, 1: 0.5, 2: 0.5, 3: 0.5}

    def test_progress_and_eta(self, tmp_path):
        run = str(tmp_path / "r")
        os.makedirs(run)
        with open(os.path.join(run, "spans.p1.t1.jsonl"), "w") as f:
            f.write(_header() + "\n")
            for i in range(4):  # 4 blocks, 1 block/s
                f.write(_block_span(i + 1, "t", i, 11.0 + i, 1.0) + "\n")
        _write_hb(run, pid=1, wall=WALL0 + 5, task="t", total=8, done=4,
                  role="driver")
        snap = LiveRun(run).poll()
        row = snap["tasks"]["t"]
        assert row["blocks_total"] == 8
        assert row["blocks_done"] == 4
        assert row["throughput_bps"] == pytest.approx(1.0)
        assert row["eta_s"] == pytest.approx(4.0)
        assert snap["progress"] is True


# --------------------------------------------------------------------------
# heartbeat staleness + stragglers (faked reader clock)


class TestStaleAndStragglers:
    def test_stale_heartbeat_flags_suspected_dead(self, tmp_path, monkeypatch):
        run = str(tmp_path / "r")
        now = 2000.0
        monkeypatch.setattr("cluster_tools_tpu.obs.live._now_wall",
                            lambda: now)
        _write_hb(run, pid=7, wall=now - 10.0, interval=1.0, task="t",
                  job_id=2)
        snap = LiveRun(run).poll()
        assert snap["n_stale"] == 1
        (w,) = snap["stale_workers"]
        assert (w["pid"], w["job_id"]) == (7, 2)
        assert "STALE" in format_watch(snap)

    def test_fresh_and_exiting_heartbeats_are_not_stale(
        self, tmp_path, monkeypatch
    ):
        run = str(tmp_path / "r")
        now = 2000.0
        monkeypatch.setattr("cluster_tools_tpu.obs.live._now_wall",
                            lambda: now)
        _write_hb(run, pid=1, wall=now - 0.5, interval=1.0, task="t")
        # a clean exit beats `exiting` and then ages forever — never stale
        _write_hb(run, pid=2, wall=now - 500.0, interval=1.0, exiting=True)
        snap = LiveRun(run).poll()
        assert snap["n_stale"] == 0

    def test_stale_threshold_scales_with_promised_interval(
        self, tmp_path, monkeypatch
    ):
        run = str(tmp_path / "r")
        now = 2000.0
        monkeypatch.setattr("cluster_tools_tpu.obs.live._now_wall",
                            lambda: now)
        # 10 s old but the writer promised a 60 s cadence: healthy
        _write_hb(run, pid=1, wall=now - 10.0, interval=60.0, task="t")
        assert LiveRun(run).poll()["n_stale"] == 0

    def test_straggler_in_flight_beyond_k_median(self, tmp_path, monkeypatch):
        run = str(tmp_path / "r")
        os.makedirs(run)
        now = 2000.0
        monkeypatch.setattr("cluster_tools_tpu.obs.live._now_wall",
                            lambda: now)
        with open(os.path.join(run, "spans.p1.t1.jsonl"), "w") as f:
            f.write(_header() + "\n")
            for i in range(5):  # median completed duration = 1.0 s
                f.write(_block_span(i + 1, "t", i, 11.0 + i, 1.0) + "\n")
        # fresh heartbeat, but block 9 has been in flight 10 s > 4 x 1 s
        _write_hb(run, pid=3, wall=now, mono=500.0, interval=1.0, task="t",
                  total=8, done=5, current=[(9, 490.0)])
        snap = LiveRun(run).poll()
        (s,) = snap["stragglers"]
        assert (s["block"], s["pid"]) == (9, 3)
        assert s["in_flight_s"] == pytest.approx(10.0)
        assert s["median_s"] == pytest.approx(1.0)
        assert snap["tasks"]["t"]["stragglers"] == [s]
        # a straggler is NOT a stall: the worker still heartbeats
        assert snap["n_stale"] == 0
        assert "straggler" in format_watch(snap)

    def test_straggler_k_is_configurable(self, tmp_path, monkeypatch):
        run = str(tmp_path / "r")
        os.makedirs(run)
        now = 2000.0
        monkeypatch.setattr("cluster_tools_tpu.obs.live._now_wall",
                            lambda: now)
        with open(os.path.join(run, "spans.p1.t1.jsonl"), "w") as f:
            f.write(_header() + "\n")
            f.write(_block_span(1, "t", 0, 11.0, 1.0) + "\n")
        _write_hb(run, pid=3, wall=now, mono=500.0, interval=1.0, task="t",
                  current=[(9, 497.0)])  # 3 s in flight
        assert LiveRun(run, straggler_k=4.0).poll()["stragglers"] == []
        assert len(LiveRun(run, straggler_k=2.0).poll()["stragglers"]) == 1


# --------------------------------------------------------------------------
# heatmap


class TestHeatmap:
    def _run_with_grid(self, tmp_path, durs, grid=(2, 2, 2)):
        run = str(tmp_path / "r")
        os.makedirs(run)
        with open(os.path.join(run, "spans.p1.t1.jsonl"), "w") as f:
            f.write(_header() + "\n")
            for i, (bid, dur) in enumerate(durs):
                f.write(_block_span(i + 1, "t", bid, 11.0, dur) + "\n")
        _write_hb(run, pid=1, wall=WALL0, task="t", grid=list(grid),
                  total=8, done=len(durs))
        return run

    def test_z_slab_golden_and_deterministic(self, tmp_path):
        durs = [(i, 1.0 + 0.1 * i) for i in range(8)]
        run = self._run_with_grid(tmp_path, durs)
        live = LiveRun(run)
        live.poll()
        text = format_heatmap(live.heatmap("t"))
        expected = "\n".join([
            "task t  block-duration heatmap  (8 blocks, 1.000s..1.700s, "
            "' '=fastest '@'=slowest '_'=pending)",
            "z-slab 0:",
            "   .",
            "  -=",
            "z-slab 1:",
            "  +*",
            "  %@",
        ])
        assert text == expected
        # determinism: a second reader over the same files agrees exactly
        live2 = LiveRun(run)
        live2.poll()
        assert format_heatmap(live2.heatmap("t")) == expected

    def test_pending_blocks_render_as_underscore(self, tmp_path):
        durs = [(i, 1.0 + 0.1 * i) for i in range(8) if i != 3]
        run = self._run_with_grid(tmp_path, durs)
        live = LiveRun(run)
        live.poll()
        text = format_heatmap(live.heatmap("t"))
        assert text.splitlines()[3] == "  -_"  # block 3 missing

    def test_no_grid_falls_back_to_strip(self, tmp_path):
        run = str(tmp_path / "r")
        os.makedirs(run)
        with open(os.path.join(run, "spans.p1.t1.jsonl"), "w") as f:
            f.write(_header() + "\n")
            f.write(_block_span(1, "t", 0, 11.0, 1.0) + "\n")
            f.write(_block_span(2, "t", 1, 12.0, 2.0) + "\n")
        live = LiveRun(run)
        live.poll()
        text = format_heatmap(live.heatmap())
        assert text.splitlines()[1] == " @"


# --------------------------------------------------------------------------
# OpenMetrics exposition

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.+eEinfa]+$"
)
_META_RE = re.compile(r"^# (TYPE [a-zA-Z_:][a-zA-Z0-9_:]* \w+|HELP .+|EOF)$")


def _assert_valid_exposition(text: str):
    try:
        from prometheus_client.openmetrics.parser import (
            text_string_to_metric_families,
        )
    except ImportError:
        # grammar fallback: every line is metadata or a valid sample, and
        # the exposition terminates with # EOF
        lines = text.splitlines()
        assert lines[-1] == "# EOF"
        for line in lines:
            assert _SAMPLE_RE.match(line) or _META_RE.match(line), line
        return None
    return list(text_string_to_metric_families(text))


class TestOpenMetrics:
    def test_exposition_parses_and_carries_series(self, tmp_path, monkeypatch):
        run = str(tmp_path / "r")
        os.makedirs(run)
        now = 2000.0
        monkeypatch.setattr("cluster_tools_tpu.obs.live._now_wall",
                            lambda: now)
        with open(os.path.join(run, "spans.p1.t1.jsonl"), "w") as f:
            f.write(_header() + "\n")
            f.write(_block_span(1, "t", 0, 11.0, 1.0) + "\n")
        with open(os.path.join(run, "metrics.p1.json"), "w") as f:
            json.dump({
                "counters": {"store.bytes_read": 10,
                             "faults.injected.store.write": 2},
                "gauges": {"compile_cache.entries_at_enable": 3,
                           "textual_gauge": "skipped"},
            }, f)
        _write_hb(run, pid=5, wall=now - 100.0, interval=1.0, task="t",
                  total=4, done=1, job_id=1, mem=4096)
        text = render_openmetrics(LiveRun(run).poll())
        assert text.endswith("# EOF\n")
        fams = _assert_valid_exposition(text)
        if fams is not None:
            by_name = {f.name: f for f in fams}
            assert by_name["ctt_store_bytes_read"].type == "counter"
            (sample,) = by_name["ctt_store_bytes_read"].samples
            assert sample.value == 10.0
            (stale,) = by_name["ctt_worker_stale"].samples
            assert stale.labels == {"pid": "5", "role": "worker", "job": "1"}
            assert stale.value == 1.0  # 100 s old on a 1 s cadence
            (mem,) = by_name["ctt_worker_device_mem_peak_bytes"].samples
            assert mem.value == 4096.0
            (done,) = by_name["ctt_task_blocks_done"].samples
            assert done.labels == {"task": "t"} and done.value == 1.0

    def test_weird_counter_names_sanitize(self):
        snap = {
            "counters": {"weird name!": 1, "a.b-c/d": 2},
            "gauges": {}, "workers": [], "tasks": {}, "malformed_lines": 0,
        }
        text = render_openmetrics(snap)
        _assert_valid_exposition(text)
        assert "ctt_a_b_c_d_total 2.0" in text


# --------------------------------------------------------------------------
# disabled overhead: no thread, no files, no state


class TestDisabledOverhead:
    def test_heartbeat_never_starts_without_trace_dir(self, tmp_path):
        # earlier traced tests may have left the (inert) daemon thread
        # alive — clear it so this asserts "disabled never STARTS one"
        heartbeat.stop(final=False)
        assert not trace.enabled()
        assert heartbeat.ensure_started() is False
        assert heartbeat.running() is False
        assert "ctt-heartbeat" not in [
            t.name for t in threading.enumerate()
        ]
        # the note hooks are no-ops too
        heartbeat.note_task("t", 8)
        heartbeat.note_block_start(0)
        heartbeat.note_blocks_done()
        heartbeat.beat()
        heartbeat.stop()
        assert list(tmp_path.iterdir()) == []

    def test_executor_construction_stays_clean_when_disabled(self):
        from cluster_tools_tpu.runtime.executor import LocalExecutor

        heartbeat.stop(final=False)
        assert not trace.enabled()
        LocalExecutor({"max_jobs": 1})
        assert heartbeat.running() is False

    def test_heartbeat_starts_and_beats_when_enabled(self, tmp_path):
        metrics.reset()
        trace.enable(str(tmp_path / "trace"), "hb_run", export_env=False)
        try:
            assert heartbeat.ensure_started(role="driver") is True
            assert heartbeat.running() is True
            heartbeat.note_task("t", 4, grid=(2, 2))
            heartbeat.note_block_start(3)
            heartbeat.beat()
            hb_path = os.path.join(
                str(tmp_path / "trace"), "hb_run", f"hb.p{os.getpid()}.json"
            )
            with open(hb_path) as f:
                hb = json.load(f)
            assert hb["task"] == "t"
            assert hb["blocks_total"] == 4
            assert hb["grid"] == [2, 2]
            assert hb["current_blocks"][0]["id"] == 3
            assert hb["exiting"] is False
            heartbeat.stop(final=True)
            assert heartbeat.running() is False
            with open(hb_path) as f:
                assert json.load(f)["exiting"] is True
        finally:
            heartbeat.stop(final=False)
            trace.disable()
            metrics.reset()


# --------------------------------------------------------------------------
# watch CLI exit-code contract


class TestWatchCli:
    def test_once_progress_exits_zero(self, tmp_path):
        run = str(tmp_path / "r")
        os.makedirs(run)
        with open(os.path.join(run, "spans.p1.t1.jsonl"), "w") as f:
            f.write(_header() + "\n")
            f.write(_block_span(1, "t", 0, 11.0, 1.0) + "\n")
            f.write(_task_span(2, "t", 11.0, 1.0) + "\n")
        r = _obs_cli("watch", "--once", run)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "t" in r.stdout

    def test_once_no_progress_exits_one(self, tmp_path):
        run = str(tmp_path / "r")
        os.makedirs(run)
        with open(os.path.join(run, "spans.p1.t1.jsonl"), "w") as f:
            f.write(_header() + "\n")
        r = _obs_cli("watch", "--once", run)
        assert r.returncode == 1
        assert "no progress" in r.stdout

    def test_once_missing_dir_exits_one(self, tmp_path):
        r = _obs_cli("watch", "--once", str(tmp_path / "nope"))
        assert r.returncode == 1

    def test_fail_on_stall_exits_four(self, tmp_path):
        run = str(tmp_path / "r")
        os.makedirs(run)
        with open(os.path.join(run, "spans.p1.t1.jsonl"), "w") as f:
            f.write(_header() + "\n")
            f.write(_block_span(1, "t", 0, 11.0, 1.0) + "\n")
        _write_hb(run, pid=9, wall=time.time() - 3600.0, interval=1.0,
                  task="t", job_id=0)
        # progress exists, but the stale worker dominates the exit code
        r = _obs_cli("watch", "--once", "--fail-on-stall", run)
        assert r.returncode == 4
        assert "STALE" in r.stdout
        # without the flag the same state reports but exits 0
        assert _obs_cli("watch", "--once", run).returncode == 0

    def test_once_json_snapshot(self, tmp_path):
        run = str(tmp_path / "r")
        os.makedirs(run)
        with open(os.path.join(run, "spans.p1.t1.jsonl"), "w") as f:
            f.write(_header() + "\n")
            f.write(_block_span(1, "t", 0, 11.0, 1.0) + "\n")
        r = _obs_cli("watch", "--once", "--json", run)
        assert r.returncode == 0
        snap = json.loads(r.stdout)
        assert snap["tasks"]["t"]["blocks_done"] == 1
        assert snap["progress"] is True

    def test_prom_cli_round_trip(self, tmp_path):
        run = str(tmp_path / "r")
        os.makedirs(run)
        with open(os.path.join(run, "metrics.p1.json"), "w") as f:
            json.dump({"counters": {"store.bytes_read": 7}, "gauges": {}}, f)
        r = _obs_cli("prom", run)
        assert r.returncode == 0
        _assert_valid_exposition(r.stdout)
        assert "ctt_store_bytes_read_total 7.0" in r.stdout

    def test_resolve_descends_single_run(self, tmp_path):
        run = str(tmp_path / "trace" / "only")
        os.makedirs(run)
        with open(os.path.join(run, "spans.p1.t1.jsonl"), "w") as f:
            f.write(_header() + "\n")
        assert resolve_live_dir(str(tmp_path / "trace")) == run
        assert resolve_live_dir(run) == run
        # descent is one level only (the export.resolve_run_dir contract)
        assert resolve_live_dir(str(tmp_path)) is None
        assert resolve_live_dir(str(tmp_path / "missing")) is None


# --------------------------------------------------------------------------
# golden machine-readable output (satellite: summarize --json / diff --json)


def _write_task_run(run_dir, run_id, tasks, counters=None):
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "spans.p1.t1.jsonl"), "w") as f:
        f.write(_header(run_id=run_id) + "\n")
        t, sid = MONO0, 1
        for name, secs in tasks:
            f.write(_task_span(sid, name, t, secs) + "\n")
            t += secs
            sid += 1
    if counters:
        with open(os.path.join(run_dir, "metrics.p1.json"), "w") as f:
            json.dump({"counters": counters, "gauges": {}}, f)


_GOLDEN_ROW = {
    "collective_s": 0.0, "device_s": 0.0, "dispatch_wall_s": 0.0,
    "host_io_s": 0.0, "host_s": 0.0, "n_spans": 1,
    "overlap_hidden_s": 0.0,
}


class TestGoldenJsonOutput:
    def test_summarize_json_golden(self, tmp_path):
        run = str(tmp_path / "g")
        _write_task_run(run, "g", [("taskA", 1.0), ("taskB", 2.0)],
                        {"store.bytes_read": 10})
        r = _obs_cli("summarize", "--json", run)
        assert r.returncode == 0, r.stderr
        assert json.loads(r.stdout) == {
            "counters": {"store.bytes_read": 10.0},
            "gauges": {},
            "n_processes": 1,
            "n_task_spans": 2,
            "run_id": "g",
            "tasks": {
                "taskA": {**_GOLDEN_ROW, "wall_s": 1.0},
                "taskB": {**_GOLDEN_ROW, "wall_s": 2.0},
            },
        }

    def test_summarize_human_golden_stays_default(self, tmp_path):
        run = str(tmp_path / "g")
        _write_task_run(run, "g", [("taskA", 1.0), ("taskB", 2.0)],
                        {"store.bytes_read": 10})
        r = _obs_cli("summarize", run)
        assert r.returncode == 0, r.stderr
        assert r.stdout == (
            "run g  (2 task spans, 1 processes)\n"
            "task      wall_s  host_io_s   device_s  collective_s"
            "     host_s  overlap_hidden_s    n_spans\n"
            "taskB      2.000      0.000      0.000         0.000"
            "      0.000             0.000          1\n"
            "taskA      1.000      0.000      0.000         0.000"
            "      0.000             0.000          1\n"
            "counters:\n"
            "  store.bytes_read = 10\n"
        )

    def test_diff_json_golden(self, tmp_path):
        base = str(tmp_path / "g")
        cand = str(tmp_path / "h")
        _write_task_run(base, "g", [("taskA", 1.0), ("taskB", 2.0)])
        _write_task_run(cand, "h", [("taskA", 1.0), ("taskB", 3.0)])
        r = _obs_cli("diff", "--json", base, cand)
        assert r.returncode == 3  # regression → nonzero, json or not
        assert json.loads(r.stdout) == {
            "a": "g",
            "b": "h",
            "n_regressed": 1,
            "rows": [
                {"a_wall_s": 1.0, "b_wall_s": 1.0, "note": "",
                 "ratio": 1.0, "regressed": False, "task": "taskA"},
                {"a_wall_s": 2.0, "b_wall_s": 3.0, "note": "",
                 "ratio": 1.5, "regressed": True, "task": "taskB"},
            ],
            "threshold": 0.2,
        }

    def test_diff_human_golden_stays_default(self, tmp_path):
        base = str(tmp_path / "g")
        cand = str(tmp_path / "h")
        _write_task_run(base, "g", [("taskA", 1.0), ("taskB", 2.0)])
        _write_task_run(cand, "h", [("taskA", 1.0), ("taskB", 3.0)])
        r = _obs_cli("diff", base, cand)
        assert r.returncode == 3
        assert r.stdout == (
            "diff g -> h (threshold 20%)\n"
            "task      base_s     cand_s    ratio  flag\n"
            "taskA      1.000      1.000    1.00x\n"
            "taskB      2.000      3.000    1.50x  REGRESSED\n"
            "1 task(s) regressed beyond the threshold\n"
        )


# --------------------------------------------------------------------------
# SIGTERM preemption flush (satellite)


class TestSigtermFlush:
    def test_sigterm_flushes_metrics_trace_and_final_heartbeat(
        self, tmp_path
    ):
        trace_dir = str(tmp_path / "trace")
        script = str(tmp_path / "victim.py")
        with open(script, "w") as f:
            f.write(
                "import sys, time\n"
                "from cluster_tools_tpu.obs import heartbeat, metrics, trace\n"
                "heartbeat.install_sigterm_flush()\n"
                "heartbeat.ensure_started(role='worker', job_id=1)\n"
                "metrics.inc('store.bytes_read', 42)\n"
                "with trace.span('setup', kind='host'):\n"
                "    pass\n"
                "with trace.span('victim_task', kind='task'):\n"
                "    print('ready', flush=True)\n"
                "    time.sleep(60)\n"
            )
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "CTT_TRACE_DIR": trace_dir, "CTT_RUN_ID": "preempt",
               "CTT_HEARTBEAT_S": "0.1",
               "PYTHONPATH": REPO + os.pathsep
               + os.environ.get("PYTHONPATH", "")}
        proc = subprocess.Popen(
            [sys.executable, script], env=env, cwd=REPO,
            stdout=subprocess.PIPE, text=True,
        )
        assert proc.stdout.readline().strip() == "ready"
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=30)
        # default disposition re-raised: the exit says "killed by SIGTERM"
        assert proc.returncode == -signal.SIGTERM
        run_dir = os.path.join(trace_dir, "preempt")
        with open(os.path.join(
            run_dir, f"hb.p{proc.pid}.json"
        )) as f:
            hb = json.load(f)
        assert hb["exiting"] is True
        with open(os.path.join(
            run_dir, f"metrics.p{proc.pid}.json"
        )) as f:
            snap = json.load(f)
        assert snap["counters"]["store.bytes_read"] == 42
        # shard flushed: the completed span made it to disk (the open
        # victim_task span dies with the process — spans record at exit)
        (shard,) = [n for n in os.listdir(run_dir) if n.startswith("spans.")]
        with open(os.path.join(run_dir, shard)) as f:
            names = [json.loads(ln).get("name") for ln in f if ln.strip()]
        assert "setup" in names


# --------------------------------------------------------------------------
# end to end: a real traced workflow is watchable


@pytest.mark.timeout(120)
def test_traced_workflow_watch_heatmap_prom(tmp_path, rng, monkeypatch):
    import numpy as np

    from cluster_tools_tpu.runtime import build, config as cfg
    from cluster_tools_tpu.utils import file_reader
    from cluster_tools_tpu.workflows import UniqueWorkflow

    monkeypatch.setenv("CTT_HEARTBEAT_S", "0.2")
    metrics.reset()
    trace.enable(str(tmp_path / "trace"), "watch_e2e", export_env=False)
    try:
        labels = rng.integers(0, 100, (8, 16, 16)).astype(np.uint64)
        path = str(tmp_path / "d.n5")
        file_reader(path).create_dataset("seg", data=labels, chunks=(4, 8, 8))
        config_dir = str(tmp_path / "configs")
        cfg.write_global_config(
            config_dir, {"block_shape": [4, 8, 8], "target": "tpu"}
        )
        wf = UniqueWorkflow(
            str(tmp_path / "tmp"), config_dir,
            input_path=path, input_key="seg",
            output_path=path, output_key="u",
        )
        assert build([wf])
        trace.flush()
        heartbeat.beat()
        run_dir = os.path.join(str(tmp_path / "trace"), "watch_e2e")

        live = LiveRun(run_dir)
        snap = live.poll()
        assert snap["progress"] is True
        row = snap["tasks"]["find_uniques"]
        assert row["blocks_done"] == 8
        assert row["blocks_total"] == 8
        assert row["complete"] is True
        # the heartbeat carried the blocking geometry
        hm = live.heatmap("find_uniques")
        assert hm["grid"] == [2, 2, 2]
        assert sorted(hm["durations"]) == list(range(8))
        text = render_openmetrics(snap)
        _assert_valid_exposition(text)
        assert 'ctt_task_blocks_done{task="find_uniques"} 8.0' in text
    finally:
        heartbeat.stop(final=False)
        trace.disable()
        metrics.reset()
