"""ctt-lint: positive + negative unit coverage for every rule id, noqa
suppression semantics, the workflow-graph fixtures, and the CLI contract
(exit 0 on the real tree, non-zero on the malformed fixtures)."""

import os
import subprocess
import sys

import pytest

from cluster_tools_tpu.analysis import (
    REGISTRY,
    lint_source,
    parse_suppressions,
    registered_markers,
    validate_workflow_file,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "ctt_lint")
PYPROJECT = os.path.join(REPO, "pyproject.toml")


def ids(findings):
    return sorted({f.rule_id for f in findings})


def lint(src, path="cluster_tools_tpu/ops/fake.py", **kw):
    return lint_source(src, path, **kw)


def line_of(path, needle):
    with open(path) as f:
        for lineno, text in enumerate(f, start=1):
            if needle in text:
                return lineno
    raise AssertionError(f"{needle!r} not found in {path}")


# --------------------------------------------------------------------------
# registry / meta


class TestRegistry:
    def test_all_shipped_rules_registered(self):
        expect = {
            "CTT001", "CTT002", "CTT003", "CTT004", "CTT005", "CTT006",
            "CTT007", "CTT008", "CTT009", "CTT010", "CTT101", "CTT102",
            "CTT103", "CTT104", "CTT105",
        }
        assert expect <= REGISTRY.known_ids()
        assert len(expect) >= 8

    def test_report_format_is_path_line_rule(self):
        (f,) = lint("import jax\n@jax.jit\ndef f(x):\n    return x.item()\n")
        text = f.format()
        assert text.startswith("cluster_tools_tpu/ops/fake.py:4: CTT001 ")


# --------------------------------------------------------------------------
# CTT001 host calls in jit


class TestCTT001:
    def test_np_call_in_jit(self):
        src = (
            "import jax, numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.unique(x)\n"
        )
        (f,) = lint(src)
        assert f.rule_id == "CTT001"
        assert f.line == 4

    def test_partial_jit_and_block_until_ready(self):
        src = (
            "import jax\nfrom functools import partial\n"
            "@partial(jax.jit, static_argnames=())\n"
            "def f(x):\n"
            "    return (x + 1).block_until_ready()\n"
        )
        assert ids(lint(src)) == ["CTT001"]

    def test_device_get_in_shard_map(self):
        src = (
            "import jax\nfrom jax.experimental.shard_map import shard_map\n"
            "from functools import partial\n"
            "@partial(shard_map, mesh=None, in_specs=None, out_specs=None)\n"
            "def f(x):\n"
            "    return jax.device_get(x)\n"
        )
        assert ids(lint(src)) == ["CTT001"]

    def test_negative_outside_jit_and_trace_time_np(self):
        src = (
            "import jax, numpy as np\n"
            "def host(x):\n"
            "    return np.unique(x)\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    big = np.iinfo(np.int32).max\n"
            "    n = int(np.prod(x.shape))\n"
            "    return x + big + n\n"
        )
        assert lint(src) == []


# --------------------------------------------------------------------------
# CTT002 clock / randomness in jit


class TestCTT002:
    def test_time_in_jit(self):
        src = (
            "import jax, time\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x + time.time()\n"
        )
        (f,) = lint(src)
        assert (f.rule_id, f.line) == ("CTT002", 4)

    def test_np_random_in_jit(self):
        src = (
            "import jax, numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x + np.random.rand()\n"
        )
        assert ids(lint(src)) == ["CTT002"]

    def test_negative_time_outside_jit(self):
        src = "import time\ndef f():\n    return time.time()\n"
        assert lint(src) == []


# --------------------------------------------------------------------------
# CTT003 collectives outside parallel/


class TestCTT003:
    SRC = (
        "import jax\n"
        "def merge(x):\n"
        "    return jax.lax.psum(x, axis_name='data')\n"
    )

    def test_collective_in_ops(self):
        (f,) = lint(self.SRC, path="cluster_tools_tpu/ops/merge.py")
        assert (f.rule_id, f.line) == ("CTT003", 3)

    def test_negative_in_parallel(self):
        assert lint(self.SRC, path="cluster_tools_tpu/parallel/merge.py") == []

    def test_negative_unrelated_method_name(self):
        src = "def f(obj, x):\n    return obj.all_gather(x)\n"
        assert lint(src) == []


# --------------------------------------------------------------------------
# CTT004 wide dtypes


class TestCTT004:
    def test_jnp_wide_dtype_anywhere(self):
        src = "import jax.numpy as jnp\ndef f(x):\n    return x.astype(jnp.float64)\n"
        (f,) = lint(src)
        assert (f.rule_id, f.line) == ("CTT004", 3)

    def test_dtype_literal_kwarg_to_jnp(self):
        src = "import jax.numpy as jnp\ndef f():\n    return jnp.zeros(4, dtype='int64')\n"
        assert ids(lint(src)) == ["CTT004"]

    def test_np_wide_dtype_inside_jit(self):
        src = (
            "import jax, numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.astype(np.float64)\n"
        )
        assert ids(lint(src)) == ["CTT004"]

    def test_negative_host_numpy_and_plain_strings(self):
        src = (
            "import numpy as np\n"
            "SUPPORTED = ('float32', 'float64')\n"
            "def host(x):\n"
            "    return x.astype(np.float64)\n"
        )
        assert lint(src) == []


# --------------------------------------------------------------------------
# CTT005 set iteration


class TestCTT005:
    def test_for_over_set_variable(self):
        src = (
            "def f(edges):\n"
            "    nodes = set()\n"
            "    out = []\n"
            "    for n in nodes:\n"
            "        out.append(n)\n"
            "    return out\n"
        )
        (f,) = lint(src)
        assert (f.rule_id, f.line) == ("CTT005", 4)

    def test_list_over_set_call(self):
        src = "def f(xs):\n    return list(set(xs))\n"
        assert ids(lint(src)) == ["CTT005"]

    def test_comprehension_over_set(self):
        src = "def f(xs):\n    return [x for x in set(xs)]\n"
        assert ids(lint(src)) == ["CTT005"]

    def test_negative_sorted_membership_and_reassignment(self):
        src = (
            "def f(xs, d):\n"
            "    s = set(xs)\n"
            "    a = sorted(s)\n"
            "    b = [x for x in xs if x in s]\n"
            "    s = list(xs)\n"
            "    c = [x for x in s]\n"
            "    for k in d:\n"
            "        pass\n"
            "    return a, b, c\n"
        )
        assert lint(src) == []


# --------------------------------------------------------------------------
# CTT006 unregistered pytest markers


class TestCTT006:
    def test_unregistered_marker(self, tmp_path):
        pyproject = tmp_path / "pyproject.toml"
        pyproject.write_text(
            "[tool.pytest.ini_options]\nmarkers = [\n  'slow: slow tests',\n]\n"
        )
        src = (
            "import pytest\n"
            "@pytest.mark.gpu_only\n"
            "def test_x():\n"
            "    pass\n"
        )
        (f,) = lint_source(src, "tests/test_fake.py", str(pyproject))
        assert (f.rule_id, f.line) == ("CTT006", 2)
        assert "gpu_only" in f.message

    def test_negative_registered_and_builtin(self, tmp_path):
        pyproject = tmp_path / "pyproject.toml"
        pyproject.write_text(
            "[tool.pytest.ini_options]\nmarkers = [\n  'slow: slow tests',\n]\n"
        )
        src = (
            "import pytest\n"
            "@pytest.mark.slow\n"
            "@pytest.mark.parametrize('x', [1])\n"
            "def test_x(x):\n"
            "    pass\n"
        )
        assert lint_source(src, "tests/test_fake.py", str(pyproject)) == []

    def test_repo_pyproject_registers_used_markers(self):
        markers = registered_markers(PYPROJECT)
        assert "slow" in markers
        assert "timeout" in markers


# --------------------------------------------------------------------------
# CTT008 wall clock in duration/deadline math


class TestCTT008:
    def test_deadline_addition(self):
        src = (
            "import time\n"
            "def f(timeout):\n"
            "    deadline = time.time() + timeout\n"
            "    return deadline\n"
        )
        (f,) = lint(src, path="cluster_tools_tpu/runtime/fake.py")
        assert (f.rule_id, f.line) == ("CTT008", 3)
        assert "monotonic" in f.message

    def test_duration_subtraction_and_comparison(self):
        src = (
            "import time\n"
            "def f(t0, deadline):\n"
            "    if time.time() > deadline:\n"
            "        raise TimeoutError\n"
            "    return time.time() - t0\n"
        )
        fs = lint(src, path="cluster_tools_tpu/runtime/fake.py")
        assert [(f.rule_id, f.line) for f in fs] == [
            ("CTT008", 3), ("CTT008", 5),
        ]

    def test_negative_timestamp_only(self):
        src = (
            "import time\n"
            "def f(status):\n"
            "    status['recorded_at'] = time.time()\n"
            "    stamp = time.strftime('%H:%M:%S')\n"
            "    return status, stamp\n"
        )
        assert lint(src, path="cluster_tools_tpu/runtime/fake.py") == []

    def test_negative_monotonic_math_is_fine(self):
        src = (
            "import time\n"
            "def f(timeout):\n"
            "    deadline = time.monotonic() + timeout\n"
            "    return time.monotonic() > deadline\n"
        )
        assert lint(src, path="cluster_tools_tpu/runtime/fake.py") == []

    def test_obs_is_exempt(self):
        src = (
            "import time\n"
            "def align(wall_anchor, mono_anchor, t):\n"
            "    return wall_anchor + (t - mono_anchor) - time.time()\n"
        )
        assert lint(src, path="cluster_tools_tpu/obs/fake.py") == []

    def test_tests_are_exempt(self):
        src = (
            "import time\n"
            "def test_x():\n"
            "    t0 = time.time()\n"
            "    assert time.time() - t0 < 5.0\n"
        )
        assert lint_source(src, "tests/test_fake.py") == []

    def test_suppressible(self):
        src = (
            "import time\n"
            "def f(t0):\n"
            "    return time.time() - t0  # ctt: noqa[CTT008] wall on purpose\n"
        )
        assert lint(src, path="cluster_tools_tpu/runtime/fake.py") == []


# --------------------------------------------------------------------------
# CTT009 resilience hygiene: ad-hoc retry loops, swallowed exceptions


class TestCTT009:
    def test_adhoc_sleep_retry_loop(self):
        src = (
            "import time\n"
            "def fetch(path):\n"
            "    for attempt in range(5):\n"
            "        try:\n"
            "            return open(path).read()\n"
            "        except OSError:\n"
            "            time.sleep(2 ** attempt)\n"
        )
        (f,) = lint(src, path="cluster_tools_tpu/tasks/fake.py")
        assert (f.rule_id, f.line) == ("CTT009", 7)
        assert "io_retry" in f.message

    def test_while_retry_loop(self):
        src = (
            "import time\n"
            "def fetch(path):\n"
            "    while True:\n"
            "        try:\n"
            "            return open(path).read()\n"
            "        except OSError:\n"
            "            pass\n"
            "        time.sleep(1.0)\n"
        )
        fs = lint(src, path="cluster_tools_tpu/tasks/fake.py")
        assert ("CTT009", 8) in [(f.rule_id, f.line) for f in fs]

    def test_negative_poll_loop_without_try(self):
        # a plain poll loop (no exception handling) is not a retry loop
        src = (
            "import time\n"
            "def wait(done):\n"
            "    while not done():\n"
            "        time.sleep(1.0)\n"
        )
        assert lint(src, path="cluster_tools_tpu/runtime/fake.py") == []

    def test_negative_shared_helper_is_exempt(self):
        src = (
            "import time\n"
            "def io_retry(fn):\n"
            "    while True:\n"
            "        try:\n"
            "            return fn()\n"
            "        except OSError:\n"
            "            time.sleep(0.01)\n"
        )
        assert lint(src, path="cluster_tools_tpu/utils/retry.py") == []

    def test_swallowed_exception(self):
        src = (
            "def f():\n"
            "    try:\n"
            "        risky()\n"
            "    except Exception:\n"
            "        pass\n"
        )
        (f,) = lint(src, path="cluster_tools_tpu/runtime/fake.py")
        assert (f.rule_id, f.line) == ("CTT009", 4)
        assert "swallows" in f.message

    def test_bare_except_pass(self):
        src = (
            "def f():\n"
            "    try:\n"
            "        risky()\n"
            "    except:\n"
            "        pass\n"
        )
        assert ids(lint(src, path="cluster_tools_tpu/runtime/fake.py")) == [
            "CTT009"
        ]

    def test_negative_narrow_except_pass_is_fine(self):
        src = (
            "def f(ds, n):\n"
            "    try:\n"
            "        ds.n_threads = n\n"
            "    except (AttributeError, TypeError):\n"
            "        pass\n"
        )
        assert lint(src, path="cluster_tools_tpu/utils/fake.py") == []

    def test_negative_except_with_recording_body_is_fine(self):
        src = (
            "def f(status):\n"
            "    try:\n"
            "        risky()\n"
            "    except Exception:\n"
            "        status['failed'] = True\n"
        )
        assert lint(src, path="cluster_tools_tpu/runtime/fake.py") == []

    def test_tests_are_exempt(self):
        src = (
            "import time\n"
            "def test_retry():\n"
            "    while True:\n"
            "        try:\n"
            "            break\n"
            "        except OSError:\n"
            "            time.sleep(0.1)\n"
        )
        assert lint_source(src, "tests/test_fake.py") == []

    def test_suppressible(self):
        src = (
            "def f():\n"
            "    try:\n"
            "        risky()\n"
            "    except Exception:  # ctt: noqa[CTT009] best-effort teardown\n"
            "        pass\n"
        )
        assert lint(src, path="cluster_tools_tpu/runtime/fake.py") == []


# --------------------------------------------------------------------------
# CTT010 metric-name registry hygiene


class TestCTT010:
    def test_unknown_counter_literal(self):
        src = (
            "from cluster_tools_tpu.obs import metrics as obs_metrics\n"
            "def f():\n"
            "    obs_metrics.inc('store.bytes_raed', 10)\n"
        )
        (f,) = lint(src, path="cluster_tools_tpu/utils/fake.py")
        assert (f.rule_id, f.line) == ("CTT010", 3)
        assert "store.bytes_raed" in f.message
        assert "registry" in f.message

    def test_unknown_gauge_literal(self):
        src = (
            "from cluster_tools_tpu.obs import metrics\n"
            "def f():\n"
            "    metrics.set_gauge('compile_cache.entries', 3)\n"
        )
        (f,) = lint(src, path="cluster_tools_tpu/utils/fake.py")
        assert (f.rule_id, f.line) == ("CTT010", 3)

    def test_counter_name_used_as_gauge_is_flagged(self):
        # the registry is per-kind: inc'ing a gauge name is a typo too
        src = (
            "from cluster_tools_tpu.obs import metrics\n"
            "def f():\n"
            "    metrics.set_gauge('store.bytes_read', 1)\n"
        )
        (f,) = lint(src, path="cluster_tools_tpu/utils/fake.py")
        assert f.rule_id == "CTT010"

    def test_negative_registered_names(self):
        src = (
            "from cluster_tools_tpu.obs import metrics as obs_metrics\n"
            "def f(n):\n"
            "    obs_metrics.inc('store.bytes_read', n)\n"
            "    obs_metrics.inc('executor.stage_hidden_io_s', 0.5)\n"
            "    obs_metrics.set_gauge('compile_cache.entries_at_enable', n)\n"
        )
        assert lint(src, path="cluster_tools_tpu/utils/fake.py") == []

    def test_negative_dynamic_prefix_literal(self):
        src = (
            "from cluster_tools_tpu.obs import metrics\n"
            "def f():\n"
            "    metrics.inc('faults.injected.store.write')\n"
        )
        assert lint(src, path="cluster_tools_tpu/faults/fake.py") == []

    def test_negative_computed_names_are_the_dynamic_path(self):
        src = (
            "from cluster_tools_tpu.obs import metrics\n"
            "def f(site, counter):\n"
            "    metrics.inc(f'faults.injected.{site}')\n"
            "    metrics.inc(counter)\n"
        )
        assert lint(src, path="cluster_tools_tpu/faults/fake.py") == []

    def test_negative_non_metrics_receiver(self):
        # arbitrary objects with .inc()/.set_gauge() are not metric calls
        src = (
            "def f(counter_obj):\n"
            "    counter_obj.inc('anything.goes')\n"
        )
        assert lint(src, path="cluster_tools_tpu/utils/fake.py") == []

    def test_unknown_histogram_literal(self):
        # ctt-slo: hist.observe literals are checked against HISTOGRAMS
        src = (
            "from cluster_tools_tpu.obs import hist\n"
            "def f(dt):\n"
            "    hist.observe('serve.latency.e2e_typo', dt)\n"
        )
        (f,) = lint(src, path="cluster_tools_tpu/serve/fake.py")
        assert (f.rule_id, f.line) == ("CTT010", 3)
        assert "serve.latency.e2e_typo" in f.message
        assert "histogram" in f.message

    def test_counter_name_used_as_histogram_is_flagged(self):
        # per-kind check: observing a counter name is a typo too
        src = (
            "from cluster_tools_tpu.obs import hist\n"
            "def f(dt):\n"
            "    hist.observe('serve.jobs_done', dt)\n"
        )
        (f,) = lint(src, path="cluster_tools_tpu/serve/fake.py")
        assert f.rule_id == "CTT010"

    def test_negative_registered_histogram_names(self):
        src = (
            "from cluster_tools_tpu.obs import hist as obs_hist\n"
            "def f(dt, tenant, prio):\n"
            "    obs_hist.observe('serve.latency.e2e', dt, tenant=tenant,\n"
            "                     priority=prio)\n"
            "    obs_hist.observe('serve.latency.admission', dt)\n"
        )
        assert lint(src, path="cluster_tools_tpu/serve/fake.py") == []

    def test_negative_non_hist_observe_receiver(self):
        # arbitrary objects with .observe() (e.g. prometheus_client
        # metrics in user code) are not ctt histogram sites
        src = (
            "def f(summary):\n"
            "    summary.observe('whatever')\n"
        )
        assert lint(src, path="cluster_tools_tpu/utils/fake.py") == []

    def test_real_tree_call_sites_are_all_registered(self):
        # every literal inc/set_gauge in the shipped source must pass —
        # the registry and the call sites cannot drift apart
        import glob as _glob

        pkg = os.path.join(REPO, "cluster_tools_tpu")
        bad = []
        for path in _glob.glob(os.path.join(pkg, "**", "*.py"),
                               recursive=True):
            with open(path) as fh:
                src = fh.read()
            bad.extend(
                f for f in lint_source(src, path, PYPROJECT)
                if f.rule_id == "CTT010"
            )
        assert bad == [], [f.format() for f in bad]

    def test_suppressible(self):
        src = (
            "from cluster_tools_tpu.obs import metrics\n"
            "def f():\n"
            "    metrics.inc('exp.series')  # ctt: noqa[CTT010] experiment-only series\n"
        )
        assert lint(src, path="cluster_tools_tpu/utils/fake.py") == []


# --------------------------------------------------------------------------
# CTT007 noqa hygiene + suppression semantics


class TestCTT007AndSuppression:
    def test_unknown_rule_id_in_noqa(self):
        src = "x = 1  # ctt: noqa[CTT999]\n"
        (f,) = lint(src)
        assert (f.rule_id, f.line) == ("CTT007", 1)

    def test_empty_noqa_brackets(self):
        src = "x = 1  # ctt: noqa[]\n"
        assert ids(lint(src)) == ["CTT007"]

    def test_negative_known_id(self):
        src = "x = 1  # ctt: noqa[CTT005] documented reason\n"
        assert lint(src) == []

    def test_suppression_by_id_and_bare(self):
        base = "def f(xs):\n    return list(set(xs)){}\n"
        assert ids(lint(base.format(""))) == ["CTT005"]
        assert lint(base.format("  # ctt: noqa[CTT005] stable enough")) == []
        assert lint(base.format("  # ctt: noqa")) == []

    def test_suppression_is_per_rule(self):
        src = "def f(xs):\n    return list(set(xs))  # ctt: noqa[CTT001]\n"
        assert ids(lint(src)) == ["CTT005"]

    def test_parse_suppressions(self):
        supp = parse_suppressions(
        "a = 1\nb = 2  # ctt: noqa[CTT001, CTT005]\nc = 3  # ctt: noqa\n"
        )
        assert supp == {2: {"CTT001", "CTT005"}, 3: {"*"}}


# --------------------------------------------------------------------------
# workflow-graph fixtures (CTT101..CTT105)


class TestGraphValidator:
    def test_cycle_fixture(self):
        path = os.path.join(FIXTURES, "wf_cycle.py")
        (f,) = validate_workflow_file(path)
        assert f.rule_id == "CTT101"
        assert f.path == path
        assert f.line == line_of(path, "class CycleWorkflow")
        assert "cycle" in f.message.lower()

    def test_missing_input_fixture(self):
        path = os.path.join(FIXTURES, "wf_missing_input.py")
        (f,) = validate_workflow_file(path)
        assert f.rule_id == "CTT102"
        assert f.path == path
        assert f.line == line_of(path, "class MissingInputWorkflow")
        assert "fragments_interm" in f.message

    def test_config_typo_fixture(self):
        path = os.path.join(FIXTURES, "wf_config_typo.py")
        (f,) = validate_workflow_file(path)
        assert f.rule_id == "CTT103"
        assert f.path == path
        assert f.line == line_of(path, "block_shpae")
        assert "block_shpae" in f.message

    def test_slow_fixture_flags_only_unmarked(self):
        path = os.path.join(FIXTURES, "wf_slow.py")
        (f,) = validate_workflow_file(path)
        assert f.rule_id == "CTT104"
        assert f.line == line_of(path, "class UnmarkedSlowWorkflow")
        assert "MarkedSlowWorkflow" not in f.message

    def test_unbuildable_workflow(self, tmp_path):
        path = tmp_path / "wf_broken.py"
        path.write_text(
            "from cluster_tools_tpu.runtime.workflow import WorkflowBase\n"
            "class BrokenWorkflow(WorkflowBase):\n"
            "    task_name = 'fixture_broken'\n"
            "    def requires(self):\n"
            "        raise RuntimeError('cannot wire')\n"
        )
        (f,) = validate_workflow_file(str(path))
        assert f.rule_id == "CTT105"
        assert "cannot wire" in f.message

    def test_good_fixture_is_clean(self):
        assert validate_workflow_file(os.path.join(FIXTURES, "wf_good.py")) == []

    def test_stream_chain_good_fixture_is_clean(self):
        # a correctly declared fused chain (fusable split-protocol members,
        # elided product consumed in-chain via fused_read_batch) is silent
        assert validate_workflow_file(
            os.path.join(FIXTURES, "wf_stream_good.py")
        ) == []

    def test_stream_chain_bad_fixture(self):
        path = os.path.join(FIXTURES, "wf_stream_bad.py")
        findings = validate_workflow_file(path)
        assert ids(findings) == ["CTT011"]
        assert len(findings) == 3
        msgs = "\n".join(f.message for f in findings)
        # 1) member without the split protocol
        assert "_NoProtocolMember" in msgs
        # 2) in-chain consumer without fused_read_batch
        assert "fused_read_batch" in msgs
        # 3) out-of-chain consumer of the elided intermediate
        assert "_OutsideConsumer" in msgs and "elided" in msgs
        anchor = line_of(path, "class BadStreamWorkflow")
        assert all(f.line == anchor for f in findings)

    def test_shipped_workflows_are_clean(self):
        # the whole point: the real tree stays lint-clean
        from cluster_tools_tpu.analysis import validate_workflows_dir

        wf_dir = os.path.join(REPO, "cluster_tools_tpu", "workflows")
        assert validate_workflows_dir(wf_dir) == []


# --------------------------------------------------------------------------
# CLI contract


def run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "cluster_tools_tpu.analysis", *argv],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )


class TestCli:
    def test_list_rules(self):
        proc = run_cli("--list-rules")
        assert proc.returncode == 0
        for rid in ("CTT001", "CTT005", "CTT101", "CTT105"):
            assert rid in proc.stdout

    def test_real_tree_is_clean(self):
        proc = run_cli("--fail-on-findings")
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_bad_ast_fixture_fails(self):
        proc = run_cli(
            "--fail-on-findings", "--no-graph",
            "--paths", os.path.join(FIXTURES, "bad_ast.py"),
        )
        assert proc.returncode == 1
        for rid in ("CTT001", "CTT002", "CTT003", "CTT004", "CTT005", "CTT007"):
            assert rid in proc.stdout, rid

    def test_workflow_fixtures_fail(self):
        proc = run_cli(
            "--fail-on-findings", "--paths", "--workflows", FIXTURES,
        )
        assert proc.returncode == 1
        for rid in ("CTT101", "CTT102", "CTT103", "CTT104"):
            assert rid in proc.stdout, rid


# --------------------------------------------------------------------------
# bench env hardening (satellite)


class TestBenchDeadlineEnv:
    @pytest.fixture()
    def bench(self):
        sys.path.insert(0, REPO)
        try:
            import bench

            yield bench
        finally:
            sys.path.remove(REPO)

    def test_default_when_unset(self, bench):
        assert bench.parse_deadline_env({}) == bench.DEFAULT_BENCH_DEADLINE_S

    def test_valid_value(self, bench):
        assert bench.parse_deadline_env({"CTT_BENCH_DEADLINE_S": "120.5"}) == 120.5

    @pytest.mark.parametrize(
        "raw", ["abc", "", "-5", "0", "nan", "inf", "1e999"]
    )
    def test_invalid_falls_back_with_warning(self, bench, raw, capsys):
        got = bench.parse_deadline_env({"CTT_BENCH_DEADLINE_S": raw})
        assert got == bench.DEFAULT_BENCH_DEADLINE_S
        assert "invalid CTT_BENCH_DEADLINE_S" in capsys.readouterr().err
