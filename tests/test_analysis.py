"""Evaluation metrics, node labels, morphology, postprocess, stitching, MWS."""

import json
import os

import numpy as np
import pytest

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


class TestEvaluationOps:
    def test_perfect_segmentation(self, rng):
        from cluster_tools_tpu.ops.evaluation import evaluate_segmentation

        gt = rng.integers(1, 10, (20, 20)).astype(np.uint64)
        scores = evaluate_segmentation(gt.copy(), gt)
        assert scores["rand_index"] == pytest.approx(1.0)
        assert scores["adapted_rand_error"] == pytest.approx(0.0, abs=1e-12)
        assert scores["vi"] == pytest.approx(0.0, abs=1e-12)

    def test_split_increases_vi_split(self, rng):
        from cluster_tools_tpu.ops.evaluation import evaluate_segmentation

        gt = np.ones((16, 16), dtype=np.uint64)
        seg = np.ones((16, 16), dtype=np.uint64)
        seg[:, 8:] = 2  # over-segmentation
        s = evaluate_segmentation(seg, gt)
        assert s["vi_split"] > 0.5
        assert s["vi_merge"] == pytest.approx(0.0, abs=1e-12)
        # merge direction
        gt2 = seg.copy()
        seg2 = np.ones_like(gt2)
        s2 = evaluate_segmentation(seg2, gt2)
        assert s2["vi_merge"] > 0.5
        assert s2["vi_split"] == pytest.approx(0.0, abs=1e-12)

    def test_vi_matches_direct_formula(self, rng):
        from cluster_tools_tpu.ops.evaluation import evaluate_segmentation

        a = rng.integers(1, 6, 500).astype(np.uint64)
        b = rng.integers(1, 4, 500).astype(np.uint64)
        s = evaluate_segmentation(a, b, ignore_gt_zero=False)
        # direct entropy computation
        def entropy(x):
            _, c = np.unique(x, return_counts=True)
            p = c / c.sum()
            return -(p * np.log(p)).sum()

        joint = entropy(a.astype(np.uint64) * 7 + b)
        assert s["vi"] == pytest.approx(2 * joint - entropy(a) - entropy(b), abs=1e-9)

    def test_object_vi(self):
        from cluster_tools_tpu.ops.evaluation import object_vi

        gt = np.ones((8, 8), dtype=np.uint64)
        gt[4:] = 2
        seg = gt.copy()
        seg[:2] = 3  # split gt object 1
        scores = object_vi(seg, gt)
        assert scores[1][0] > 0  # split term for object 1
        assert scores[2][0] == pytest.approx(0.0, abs=1e-12)


class TestEvaluationWorkflow:
    def test_distributed_matches_direct(self, tmp_path, rng):
        from cluster_tools_tpu.ops.evaluation import evaluate_segmentation
        from cluster_tools_tpu.workflows import EvaluationWorkflow
        from cluster_tools_tpu.tasks.evaluation import load_measures

        shape = (24, 32, 32)
        gt = rng.integers(0, 8, shape).astype(np.uint64)
        seg = gt.copy()
        flip = rng.random(shape) < 0.1
        seg[flip] = rng.integers(1, 12, int(flip.sum())).astype(np.uint64)
        path = str(tmp_path / "d.n5")
        f = file_reader(path)
        f.create_dataset("seg", data=seg, chunks=(12, 16, 16))
        f.create_dataset("gt", data=gt, chunks=(12, 16, 16))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [12, 16, 16]})
        wf = EvaluationWorkflow(
            tmp_folder, config_dir,
            seg_path=path, seg_key="seg", gt_path=path, gt_key="gt",
        )
        assert build([wf])
        got = load_measures(tmp_folder)
        want = evaluate_segmentation(seg, gt)
        for k in ("rand_index", "adapted_rand_error", "vi_split", "vi_merge"):
            assert got[k] == pytest.approx(want[k], abs=1e-9), k


class TestMorphology:
    def test_workflow_matches_direct(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.morphology import load_morphology
        from cluster_tools_tpu.workflows import MorphologyWorkflow

        shape = (16, 24, 24)
        seg = rng.integers(0, 6, shape).astype(np.uint64)
        path = str(tmp_path / "d.n5")
        file_reader(path).create_dataset("seg", data=seg, chunks=(8, 12, 12))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 12, 12]})
        wf = MorphologyWorkflow(
            tmp_folder, config_dir, input_path=path, input_key="seg"
        )
        assert build([wf])
        table = load_morphology(tmp_folder)
        for row in table:
            sid = int(row[0])
            sel = seg == sid
            assert row[1] == sel.sum()
            com = np.argwhere(sel).mean(axis=0)
            np.testing.assert_allclose(row[2:5], com, atol=1e-6)
            coords = np.argwhere(sel)
            np.testing.assert_array_equal(row[5:8], coords.min(axis=0))
            np.testing.assert_array_equal(row[8:11], coords.max(axis=0) + 1)


class TestPostprocess:
    def test_graph_watershed_assignments(self):
        from cluster_tools_tpu.tasks.postprocess import graph_watershed_assignments

        # chain 0-1-2-3; seeds at ends; node 1 closer (stronger edge) to 0
        edges = np.array([[0, 1], [1, 2], [2, 3]])
        weights = np.array([0.9, 0.2, 0.8])
        seeds = np.array([1, 0, 0, 2])
        out = graph_watershed_assignments(edges, weights, seeds, 4)
        np.testing.assert_array_equal(out, [1, 1, 2, 2])


class TestMwsNativeParity:
    def test_native_matches_python_oracle_medium_graph(self, rng):
        """Regression for a use-after-free + stale-mutex-back-reference bug in
        the native mutex_watershed (solvers.cpp): only surfaced at realistic
        edge counts, so tiny workflow tests never caught it."""
        from cluster_tools_tpu import native
        from cluster_tools_tpu.ops.mws import _mws_python

        if not native.available():
            pytest.skip("native solvers unavailable")
        n_nodes = 3000
        n_edges = 30000
        uv = rng.integers(0, n_nodes, (n_edges, 2), dtype=np.int64)
        keep = uv[:, 0] != uv[:, 1]
        uv = uv[keep]
        weights = rng.random(uv.shape[0])
        attractive = (rng.random(uv.shape[0]) < 0.7).astype(np.uint8)
        got = native.mutex_watershed(n_nodes, uv, weights, attractive)
        want = _mws_python(n_nodes, uv, weights, attractive)
        # same partition (root ids may differ)
        pairs = np.unique(np.stack([got, want], axis=1), axis=0)
        assert len(pairs) == len(np.unique(got)) == len(np.unique(want))

    def test_grid_mws_realistic_size_no_crash(self):
        """The UAF repro shape: long-range offsets + strides on a real grid."""
        from scipy import ndimage

        from cluster_tools_tpu.ops.mws import compute_mws_segmentation

        offsets = [[-1, 0, 0], [0, -1, 0], [0, 0, -1],
                   [-2, 0, 0], [0, -4, 0], [0, 0, -4]]
        rng = np.random.default_rng(1)
        shape = (8, 64, 64)
        affs = ndimage.gaussian_filter(
            rng.random((len(offsets),) + shape).astype(np.float32), (0, 1, 2, 2)
        )
        seg = compute_mws_segmentation(affs, offsets, strides=[1, 2, 2])
        assert seg.shape == shape
        assert seg.max() > 0


class TestMwsWorkflow:
    def _make_affs(self, rng, shape=(16, 32, 32)):
        # two halves separated along y with strong repulsion; only the
        # y-direction long-range channel carries boundary evidence (zeroing the
        # x channel would install x-mutexes that legitimately shatter the halves)
        offsets = [[-1, 0, 0], [0, -1, 0], [0, 0, -1], [0, -4, 0], [0, 0, -4]]
        affs = np.full((len(offsets),) + shape, 0.9, dtype=np.float32)
        mid = shape[1] // 2
        affs[:3, :, mid - 1 : mid + 1, :] = 0.05   # attractive cut at boundary
        # y-repulsion: source rows [mid, mid+4) pair with [mid-4, mid) — every
        # mutex crosses the boundary, none lands within a half
        affs[3, :, mid : mid + 4, :] = 0.05
        return affs, offsets

    @pytest.mark.parametrize("target", ["local", "tpu"])
    def test_mws_workflow_stitches(self, tmp_path, rng, target):
        from cluster_tools_tpu.workflows import MwsWorkflow

        affs, offsets = self._make_affs(rng)
        path = str(tmp_path / "d.n5")
        file_reader(path).create_dataset(
            "affs", data=affs, chunks=(len(offsets), 8, 16, 16)
        )
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(
            config_dir, {"block_shape": [8, 16, 16], "target": target}
        )
        # dense mutexes: stride subsampling on this synthetic fixture drops all
        # mutexes on odd columns, legitimately letting weak attractions cross
        cfg.write_config(
            config_dir, "mws_blocks",
            {"offsets": offsets, "strides": [1, 1, 1], "halo": [2, 4, 4]},
        )
        wf = MwsWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="affs",
            output_path=path, output_key="seg",
        )
        assert build([wf])
        seg = file_reader(path, "r")["seg"][:]
        assert (seg > 0).all()
        # the two halves must each be stitched into a dominant segment, and the
        # dominant segments must differ across the repulsion boundary
        def dominant(x):
            ids, counts = np.unique(x, return_counts=True)
            return ids[counts.argmax()]

        top = seg[:, :10, :]
        bottom = seg[:, 22:, :]
        dom_top = dominant(top)
        dom_bottom = dominant(bottom)
        assert dom_top != dom_bottom
        assert (top == dom_top).mean() > 0.8
        assert (bottom == dom_bottom).mean() > 0.8
