"""ctt-ingest: streaming ingest of a growing source.

Covers the PR acceptance contract:

  * watcher edge cases over the control-dir protocol: torn (half-written)
    markers are invisible until whole, out-of-order landings park until
    the gap fills, duplicate re-landings are idempotent, a quiet source
    holds the frontier — which never regresses;
  * live-volume byte identity: an ingest run that consumes slabs WHILE a
    background writer lands them finishes byte-identical (array equality
    AND chunk-file digests) to the batch fused run over the finished
    volume;
  * suspend/resume: a drain-style suspension between slabs loses no work —
    a fresh runner restores the persisted carry, skips committed chunks,
    and the finished stream is still byte-identical (``ingest.resumes``
    counts the takeover);
  * frame-domain ingest: event building over a growing frame stack at
    exact batch/oracle parity with ZERO kernel recompiles after the batch
    warmup (the ``_CAP_HINT`` snapshot in the carry record);
  * ctt-cloud listing pagination: ``HttpBackend.listdir`` walks
    ``limit=``/``marker=`` continuation pages against the stub object
    server, and a seeded ``store.remote_list`` fault heals inside the
    per-page retry;
  * serve integration: a released lease (voluntary give-back) is
    reclaimable immediately and does not burn the poison-job budget, and
    a draining daemon releases a live ingest job mid-stream for a
    successor daemon to finish — byte-identical, resumes counted.
"""

import hashlib
import json
import os
import threading
import time

import numpy as np
import pytest
from objstub import StubObjectStore
from scipy import ndimage

from cluster_tools_tpu import faults
from cluster_tools_tpu.ingest import (
    GrowingSource,
    IngestRunner,
    IngestSuspended,
    IngestTask,
    install_suspend_check,
    publish_manifest,
    publish_slab,
)
from cluster_tools_tpu.ingest.runner import FRONTIER_NAME, carry_record_name
from cluster_tools_tpu.ingest.source import slab_marker_name
from cluster_tools_tpu.obs import metrics as obs_metrics
from cluster_tools_tpu.obs import trace as obs_trace
from cluster_tools_tpu.ops import events as events_ops
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.serve import ServeClient, ServeDaemon
from cluster_tools_tpu.serve.jobs import JobQueue
from cluster_tools_tpu.tasks.events import read_event_tables
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import (
    EventBuildingWorkflow,
    StreamingSegmentationWorkflow,
)

THRESHOLD = 0.55
WS_CONF = {
    "threshold": 0.5, "sigma_seeds": 1.6, "size_filter": 10,
    "halo": [2, 4, 4],
}
SHAPE = (24, 32, 32)
SLAB_DEPTH = 8  # one z block-slice per slab
GCONF_VOL = {
    "block_shape": [8, 16, 16], "target": "tpu",
    "device_batch_size": 4, "devices": [0], "max_num_retries": 0,
}


@pytest.fixture(autouse=True)
def _traced(tmp_path):
    """Counters drive most assertions; tracing scoped per test."""
    obs_metrics.reset()
    was_on = obs_trace.enabled()
    if not was_on:
        obs_trace.enable(str(tmp_path / "trace"), "ingest_test",
                         export_env=False)
    yield
    install_suspend_check(None)
    if not was_on:
        obs_trace.disable()
    obs_metrics.reset()


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _counters():
    return dict(obs_metrics.snapshot()["counters"])


def _delta(before, after):
    return {k: after.get(k, 0) - before.get(k, 0)
            for k in set(after) | set(before)}


def _volume(shape=SHAPE):
    rng = np.random.default_rng(7)
    raw = ndimage.gaussian_filter(rng.random(shape), 1.0)
    return ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")


def _digest_key(path, key):
    """Chunk-file digest of one dataset (directory tree under the key):
    the byte-identity gate compares stored bytes, not decoded arrays."""
    root = os.path.join(path, key)
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def _vol_config_dir(tmp_path, tag, watershed=True):
    config_dir = str(tmp_path / f"configs_{tag}")
    cfg.write_global_config(config_dir, dict(GCONF_VOL))
    cfg.write_config(config_dir, "threshold", {"threshold": THRESHOLD})
    if watershed:
        cfg.write_config(config_dir, "watershed", dict(WS_CONF))
    return config_dir


def _batch_reference(tmp_path, path, tag="batch", watershed=True):
    wf = StreamingSegmentationWorkflow(
        str(tmp_path / f"tmp_{tag}"), _vol_config_dir(tmp_path, tag,
                                                      watershed),
        input_path=path, input_key="raw",
        output_path=path, output_key=f"cc_{tag}",
        watershed=watershed,
    )
    assert build([wf]), f"batch reference failed ({tag})"
    return wf


def _stage_growing(tmp_path, vol, key="raw_live"):
    """The acquisition side: full-geometry dataset shell + control dir,
    no data landed yet."""
    path = str(tmp_path / "data.n5")
    f = file_reader(path)
    if "raw" not in f:
        f.create_dataset("raw", data=vol, chunks=(8, 16, 16))
    f.create_dataset(key, shape=vol.shape, dtype=vol.dtype,
                     chunks=(8, 16, 16))
    control = str(tmp_path / "control")
    assert publish_manifest(control, vol.shape, SLAB_DEPTH)
    return path, control


def _land(path, key, control, vol, slabs, slab_depth=SLAB_DEPTH):
    """Write each slab's data, THEN its marker (the protocol's commit
    order)."""
    ds = file_reader(path)[key]
    for s in slabs:
        z0, z1 = s * slab_depth, (s + 1) * slab_depth
        ds[z0:z1, :, :] = vol[z0:z1]
        publish_slab(control, s)


# ---------------------------------------------------------------------------
# watcher edge cases


class TestGrowingSource:
    def test_out_of_order_and_duplicate_landings(self, tmp_path):
        control = str(tmp_path / "ctl")
        assert publish_manifest(control, (12, 4, 4), 2)
        assert not publish_manifest(control, (12, 4, 4), 2)  # create-only
        src = GrowingSource(control)
        assert src.manifest()["slabs_total"] == 6
        assert src.poll() == 0

        publish_slab(control, 2)
        publish_slab(control, 0)
        assert src.poll() == 1          # slab 1 missing: 2 parks
        assert src.landed() == 2
        publish_slab(control, 1)
        assert src.poll() == 3          # the gap filled, both advance
        assert not publish_slab(control, 0)  # duplicate re-landing
        assert src.poll() == 3 and src.landed() == 3
        assert not src.complete()

    def test_torn_marker_invisible_until_whole(self, tmp_path):
        control = str(tmp_path / "ctl")
        os.makedirs(control)
        assert publish_manifest(control, (4, 4, 4), 2)
        src = GrowingSource(control)
        marker = os.path.join(control, slab_marker_name(0))
        with open(marker, "w") as f:
            f.write('{"slab": 0, "wa')  # half-uploaded JSON
        assert src.poll() == 0
        with open(marker, "w") as f:
            json.dump({"slab": 0, "wall": 1.0}, f)
        assert src.poll() == 1

    def test_quiet_source_holds_frontier_then_resumes(self, tmp_path):
        control = str(tmp_path / "ctl")
        assert publish_manifest(control, (8, 4, 4), 2)
        src = GrowingSource(control)
        publish_slab(control, 0)
        before = _counters()
        frontiers = [src.poll() for _ in range(4)]
        assert frontiers == [1, 1, 1, 1]  # quiet: held, never regressed
        assert _delta(before, _counters()).get("ingest.poll_rounds") == 4
        publish_slab(control, 1)
        assert src.poll() == 2

    def test_torn_manifest_retries(self, tmp_path):
        control = str(tmp_path / "ctl")
        os.makedirs(control)
        src = GrowingSource(control)
        assert src.manifest() is None
        with open(os.path.join(control, "ingest.manifest.json"), "w") as f:
            f.write('{"schema": 1, "sl')
        assert src.manifest() is None
        os.remove(os.path.join(control, "ingest.manifest.json"))
        assert publish_manifest(control, (4, 4, 4), 4)
        assert src.manifest() is not None


# ---------------------------------------------------------------------------
# volume-domain ingest: live writer, byte identity, suspend/resume


class TestVolumeIngest:
    def test_live_ingest_byte_identical_to_batch(self, tmp_path):
        vol = _volume()
        path, control = _stage_growing(tmp_path, vol)
        _batch_reference(tmp_path, path, "batch")

        writer = threading.Thread(
            target=_land, args=(path, "raw_live", control, vol, range(3)),
            kwargs={}, daemon=True,
        )
        task = IngestTask(
            str(tmp_path / "tmp_live"),
            control_dir=control,
            config_dir=_vol_config_dir(tmp_path, "live"),
            input_path=path, input_key="raw_live",
            output_path=path, output_key="cc_live",
            watershed=True, poll_s=0.02, timeout_s=120.0,
        )
        before = _counters()
        writer.start()
        try:
            assert build([task])
        finally:
            writer.join(timeout=30)
        d = _delta(before, _counters())

        f = file_reader(path, "r")
        np.testing.assert_array_equal(f["cc_live"][:], f["cc_batch"][:])
        np.testing.assert_array_equal(
            f["cc_live_ws"][:], f["cc_batch_ws"][:]
        )
        assert _digest_key(path, "cc_live") == _digest_key(path, "cc_batch")
        assert _digest_key(path, "cc_live_ws") == _digest_key(
            path, "cc_batch_ws"
        )
        assert d.get("ingest.slabs_ingested") == 3
        assert d.get("ingest.poll_rounds", 0) >= 1
        assert d.get("ingest.resumes", 0) == 0
        assert d.get("stream.chains") == 1
        frontier = json.load(open(os.path.join(control, FRONTIER_NAME)))
        assert frontier["slabs_done"] == frontier["slabs_total"] == 3

    def test_suspend_resume_mid_stream_byte_identical(self, tmp_path):
        vol = _volume()
        path, control = _stage_growing(tmp_path, vol)
        _batch_reference(tmp_path, path, "batch")
        _land(path, "raw_live", control, vol, range(3))  # fully landed

        config_dir = _vol_config_dir(tmp_path, "sus")
        wf = StreamingSegmentationWorkflow(
            str(tmp_path / "tmp_sus"), config_dir,
            input_path=path, input_key="raw_live",
            output_path=path, output_key="cc_sus",
            watershed=True,
        )
        chain = list(wf.fused_chains())[0]
        # suspend as soon as the first chunk's carry is committed — the
        # deterministic stand-in for a drain request landing mid-stream
        first_carry = os.path.join(control, carry_record_name(0))
        install_suspend_check(lambda: os.path.exists(first_carry))
        with pytest.raises(IngestSuspended):
            IngestRunner(chain, GrowingSource(control),
                         poll_s=0.01, timeout_s=60.0).run()
        install_suspend_check(None)
        assert os.path.exists(first_carry)

        before = _counters()
        IngestRunner(chain, GrowingSource(control),
                     poll_s=0.01, timeout_s=60.0).run()
        d = _delta(before, _counters())
        assert d.get("ingest.resumes") == 1
        assert d.get("ingest.slabs_ingested") == 2  # chunk 0 never re-ran
        assert build([wf])  # the non-fused tail (assignments + write)

        f = file_reader(path, "r")
        np.testing.assert_array_equal(f["cc_sus"][:], f["cc_batch"][:])
        np.testing.assert_array_equal(
            f["cc_sus_ws"][:], f["cc_batch_ws"][:]
        )
        assert _digest_key(path, "cc_sus") == _digest_key(path, "cc_batch")
        frontier = json.load(open(os.path.join(control, FRONTIER_NAME)))
        assert frontier["slabs_done"] == 3 and frontier["resumes"] == 1


# ---------------------------------------------------------------------------
# frame-domain ingest


GCONF_EV = {
    "block_shape": [2, 16, 16], "target": "tpu",
    "device_batch_size": 2, "devices": [0], "pipeline_depth": 2,
}


def _frame_stack(rng, n=10, h=16, w=16, density=0.9):
    """Detector-like frames: smooth blobs + isolated hot pixels (the
    tests/test_events.py generator, at the ingest block geometry)."""
    raw = ndimage.gaussian_filter(
        rng.random((n, h, w)), (0.0, 1.0, 1.0)
    ).astype("float32")
    frames = np.where(
        raw > np.quantile(raw, density), raw, 0.0
    ).astype("float32")
    hits = rng.random((n, h, w)) > 0.99
    frames[hits] = (rng.random(int(hits.sum())) + 1.0).astype("float32")
    return frames


class TestFramesIngest:
    def test_frames_parity_and_zero_recompiles(self, tmp_path, rng):
        frames = _frame_stack(rng)
        t = float(np.quantile(frames[frames > 0], 0.2)) if (
            frames > 0).any() else 0.0
        path = str(tmp_path / "frames.n5")
        f = file_reader(path)
        f.create_dataset("frames", data=frames, chunks=(2, 16, 16))
        f.create_dataset("frames_live", shape=frames.shape,
                         dtype=frames.dtype, chunks=(2, 16, 16))

        ref_cfg = str(tmp_path / "configs_ev_ref")
        cfg.write_global_config(ref_cfg, dict(GCONF_EV))
        cfg.write_config(ref_cfg, "events", {"threshold": t})
        wf = EventBuildingWorkflow(
            str(tmp_path / "tmp_ev_ref"), ref_cfg,
            input_path=path, input_key="frames",
            output_path=path, output_key="ev_ref",
        )
        assert build([wf])  # the warmup: compiles every frame bucket

        control = str(tmp_path / "ctl_frames")
        assert publish_manifest(control, frames.shape, 2, domain="frames")
        live_cfg = str(tmp_path / "configs_ev_live")
        cfg.write_global_config(live_cfg, dict(GCONF_EV))
        cfg.write_config(live_cfg, "events", {"threshold": t})
        task = IngestTask(
            str(tmp_path / "tmp_ev_live"),
            control_dir=control, config_dir=live_cfg, domain="frames",
            input_path=path, input_key="frames_live",
            output_path=path, output_key="ev_live",
            poll_s=0.02, timeout_s=120.0,
        )
        warm = events_ops.kernel_cache_size()
        writer = threading.Thread(
            target=_land,
            args=(path, "frames_live", control, frames, range(5)),
            kwargs={"slab_depth": 2}, daemon=True,
        )
        before = _counters()
        writer.start()
        try:
            assert build([task])
        finally:
            writer.join(timeout=30)
        # the acceptance gate: streamed frames reuse the warmed kernels
        assert events_ops.kernel_cache_size() == warm
        assert _delta(before, _counters()).get("ingest.slabs_ingested",
                                               0) >= 1

        fr = file_reader(path, "r")
        np.testing.assert_array_equal(fr["ev_live"][:], fr["ev_ref"][:])
        n_blocks = 5
        live_tab = read_event_tables(path, "ev_live", n_blocks)
        np.testing.assert_array_equal(
            live_tab, read_event_tables(path, "ev_ref", n_blocks)
        )
        ora_labels, ora_counts, _ = events_ops.build_events_np(
            frames, threshold=t
        )
        np.testing.assert_array_equal(fr["ev_live"][:], ora_labels)
        assert len(live_tab) == int(ora_counts.sum())


# ---------------------------------------------------------------------------
# ctt-cloud: listing pagination + the remote_list fault site


class TestRemoteListing:
    def test_paginated_listdir_and_remote_control_dir(self, tmp_path):
        with StubObjectStore(str(tmp_path / "objroot")) as srv:
            control = srv.url + "/ingest_ctl"
            assert publish_manifest(control, (20, 4, 4), 2)
            for s in (3, 1, 0, 2, 4, 9, 7, 8, 6, 5):  # shuffled landings
                assert publish_slab(control, s)
            src = GrowingSource(control)
            prev = src.backend.list_page
            src.backend.list_page = 3  # 11 entries -> 4 continuation pages
            try:
                assert src.poll() == 10
                names = src.backend.listdir(control)
            finally:
                src.backend.list_page = prev
            assert names == sorted(names)
            assert "ingest.manifest.json" in names
            assert sum(1 for n in names if n.startswith("slab.")) == 10

    def test_remote_list_fault_heals_in_page_retry(self, tmp_path):
        with StubObjectStore(str(tmp_path / "objroot")) as srv:
            control = srv.url + "/ingest_ctl"
            assert publish_manifest(control, (4, 4, 4), 2)
            publish_slab(control, 0)
            src = GrowingSource(control)
            before = _counters()
            faults.configure("store.remote_list:io_error:once;seed=2")
            try:
                assert src.poll() == 1  # the injected page fault healed
            finally:
                faults.reset()
            d = _delta(before, _counters())
            assert d.get("faults.injected", 0) >= 1
            assert d.get("store.remote_retries", 0) >= 1


# ---------------------------------------------------------------------------
# serve: released leases + drain-to-successor failover


def _dead_lease(path):
    """Backdate a lease stamp far past staleness AND backoff: the owner
    'died' long ago."""
    rec = json.load(open(path))
    rec["wall"] = rec["wall"] - 1000.0
    with open(path, "w") as f:
        json.dump(rec, f)


class TestServeRelease:
    def test_release_requeues_immediately(self, tmp_path):
        q = JobQueue(str(tmp_path / "jobs"), lease_s=30.0, daemon_id="dA",
                     max_job_gens=2)
        jid = q.submit({"workflow": "W", "tenant": "t"})
        for gen in range(3):
            claim = q.claim_next()
            assert claim is not None and claim.gen == gen
            q.release(claim)
            rec = json.load(open(claim.lease_path))
            assert rec["released"] is True and rec["wall"] == 0.0
        # three voluntary give-backs: no backoff wait, no quarantine
        claim = q.claim_next()
        assert claim is not None and claim.gen == 3
        assert not os.path.exists(
            os.path.join(q.dir, f"result.{jid}.json")
        )

    def test_only_dead_generations_burn_the_budget(self, tmp_path):
        q = JobQueue(str(tmp_path / "jobs"), lease_s=30.0, daemon_id="dA",
                     max_job_gens=2)
        jid = q.submit({"workflow": "W", "tenant": "t"})
        c0 = q.claim_next()
        q.release(c0)                     # gen 0: voluntary, free
        c1 = q.claim_next()
        assert c1.gen == 1
        _dead_lease(c1.lease_path)        # gen 1: death #1
        c2 = q.claim_next()
        assert c2 is not None and c2.gen == 2  # 1 burned < budget 2
        _dead_lease(c2.lease_path)        # gen 2: death #2 -> budget gone
        assert q.claim_next() is None
        result = json.load(
            open(os.path.join(q.dir, f"result.{jid}.json"))
        )
        assert result["quarantined"] is True


@pytest.fixture
def daemon_factory(tmp_path):
    daemons = []

    def make(state_dir, **conf):
        d = ServeDaemon(str(state_dir), config=conf)
        d.start()
        daemons.append(d)
        return d

    yield make
    for d in daemons:
        d.request_drain()
        if d._httpd is not None:
            d._httpd.shutdown()
            d._httpd.server_close()
        for t in d._threads:
            if t.name.startswith("ctt-serve-exec"):
                t.join(timeout=30)


def _wait_for(predicate, timeout_s=60.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return False


class TestServeIngest:
    def test_drain_releases_and_successor_finishes(self, tmp_path,
                                                   daemon_factory):
        vol = _volume()
        path, control = _stage_growing(tmp_path, vol)
        _batch_reference(tmp_path, path, "batch", watershed=False)
        _land(path, "raw_live", control, vol, range(2))  # slab 2 withheld

        state = tmp_path / "state"
        d1 = daemon_factory(state)
        client = ServeClient(state_dir=str(state))
        job = client.ingest(
            control_dir=control,
            input_path=path, input_key="raw_live",
            output_path=path, output_key="cc_srv",
            tmp_folder=str(tmp_path / "tmp_srv"),
            config_dir=str(tmp_path / "configs_srv"),
            watershed=False, poll_s=0.05, timeout_s=300.0,
            configs={"global": dict(GCONF_VOL),
                     "threshold": {"threshold": THRESHOLD}},
        )
        # mid-stream: at least one slab committed, the stream parked on
        # the withheld slab
        assert _wait_for(lambda: os.path.exists(
            os.path.join(control, carry_record_name(0))
        ))
        d1.request_drain()
        lease0 = os.path.join(str(state), "jobs", f"lease.{job}.g0.json")

        def _released():
            try:
                return json.load(open(lease0)).get("released") is True
            except (OSError, ValueError):
                return False

        assert _wait_for(_released), "drain did not release the lease"

        _land(path, "raw_live", control, vol, [2])
        daemon_factory(state)  # the successor; claims gen 1, resumes
        client2 = ServeClient(state_dir=str(state))
        result = client2.wait(job, timeout_s=300)
        assert result["result"]["ok"]
        assert result["result"]["gen"] == 1

        f = file_reader(path, "r")
        np.testing.assert_array_equal(f["cc_srv"][:], f["cc_batch"][:])
        assert _digest_key(path, "cc_srv") == _digest_key(path, "cc_batch")
        text = client2.metrics_text()
        lines = {
            parts[0]: float(parts[1])
            for parts in (ln.split() for ln in text.splitlines())
            if len(parts) == 2 and not parts[0].startswith("#")
        }
        assert lines.get("ctt_ingest_resumes_total", 0) >= 1
        assert lines.get("ctt_ingest_slabs_ingested_total", 0) >= 3
