"""Collective RAG feature accumulation vs the host oracle."""

import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu.ops.rag import HIST_BINS, boundary_edge_features
from cluster_tools_tpu.parallel.sharded_rag import (
    sharded_boundary_edge_features,
)


def _fixture(rng, shape=(16, 24, 24), n_seg=40):
    labels = rng.integers(1, n_seg, tuple(s // 4 for s in shape))
    labels = np.kron(labels, np.ones((4, 4, 4), dtype=np.int64)).astype(
        np.int32
    )
    values = ndimage.gaussian_filter(rng.random(shape), 1.0).astype(np.float32)
    values = (values - values.min()) / (values.max() - values.min())
    return labels, values


def test_sharded_rag_matches_host_oracle(rng):
    labels, values = _fixture(rng)
    edges, feats = sharded_boundary_edge_features(labels, values)

    want_edges, want = boundary_edge_features(
        labels.astype(np.uint64), values.astype(np.float64)
    )
    np.testing.assert_array_equal(edges, want_edges)
    # exact columns: mean, var, min, max, count
    np.testing.assert_allclose(
        feats[:, [0, 2, 8, 9]], want[:, [0, 2, 8, 9]], rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(feats[:, 1], want[:, 1], rtol=1e-4, atol=1e-5)
    # quantiles: one histogram bin (the block merge's own tolerance)
    tol = 1.0 / HIST_BINS + 1e-6
    assert (np.abs(feats[:, 3:8] - want[:, 3:8]) <= tol).all()


def test_sharded_rag_cross_shard_edges(rng):
    # two segments meeting exactly AT a shard boundary: the edge's samples
    # live in one shard (pair ownership) but merging must still be correct
    # when a segment pair also touches inside other shards
    labels = np.ones((16, 8, 8), dtype=np.int32)
    labels[8:] = 2  # boundary at z=8 == shard boundary on the 8-device mesh
    values = rng.random((16, 8, 8)).astype(np.float32)
    edges, feats = sharded_boundary_edge_features(labels, values)
    want_edges, want = boundary_edge_features(
        labels.astype(np.uint64), values.astype(np.float64)
    )
    np.testing.assert_array_equal(edges, want_edges)
    np.testing.assert_allclose(
        feats[:, [0, 2, 8, 9]], want[:, [0, 2, 8, 9]], rtol=1e-5, atol=1e-6
    )


def test_sharded_rag_rejects_bad_extent(rng):
    labels, values = _fixture(rng, shape=(12, 8, 8))
    with pytest.raises(ValueError, match="not divisible"):
        sharded_boundary_edge_features(labels, values)


def test_sharded_rag_overflow_fails_loudly(rng):
    # more distinct edges than max_edges in every shard: the lexicographic
    # tail would be dropped identically everywhere, so the merged count
    # alone cannot see it — the local-table guard must raise
    labels, values = _fixture(rng, shape=(16, 40, 8), n_seg=60)
    with pytest.raises(RuntimeError, match="overflow"):
        sharded_boundary_edge_features(labels, values, max_edges=32)


def test_sharded_problem_multicut_segmentation(tmp_path, rng):
    """MulticutSegmentationWorkflow(sharded_problem=True): the collective
    problem extraction feeds costs + global solve unchanged, and the
    segmentation partition matches the block-pipeline run."""
    from cluster_tools_tpu.ops.evaluation import same_partition
    from cluster_tools_tpu.runtime import build, config as cfg
    from cluster_tools_tpu.utils import file_reader
    from cluster_tools_tpu.workflows import MulticutSegmentationWorkflow

    raw = ndimage.gaussian_filter(rng.random((16, 32, 32)), (1.0, 2.0, 2.0))
    raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")
    path = str(tmp_path / "mc.n5")
    file_reader(path).create_dataset("bnd", data=raw, chunks=(8, 16, 16))

    segs = {}
    for name, sharded in [("blocks", False), ("collective", True)]:
        config_dir = str(tmp_path / f"configs_{name}")
        tmp_folder = str(tmp_path / f"tmp_{name}")
        cfg.write_global_config(
            config_dir, {"block_shape": [8, 16, 16], "target": "tpu"}
        )
        cfg.write_config(config_dir, "watershed", {
            "threshold": 0.4, "sigma_seeds": 1.0, "size_filter": 5,
            "apply_dt_2d": False, "apply_ws_2d": False, "halo": [2, 4, 4],
        })
        wf = MulticutSegmentationWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="bnd",
            ws_path=path, ws_key=f"ws_{name}",
            output_path=path, output_key=f"seg_{name}",
            sharded_problem=sharded,
        )
        assert build([wf])
        segs[name] = file_reader(path, "r")[f"seg_{name}"][:]

    # both runs share the watershed config -> identical fragments; features
    # differ only in sketch-quantile columns, and the default costs use the
    # mean column -> identical multicut partitions
    assert same_partition(segs["collective"], segs["blocks"])


def test_sharded_problem_uint8_and_affinity_guard(tmp_path, rng):
    from cluster_tools_tpu.runtime import build, config as cfg
    from cluster_tools_tpu.tasks.features import ShardedProblemTask
    from cluster_tools_tpu.utils import file_reader

    labels, values = _fixture(rng)
    path = str(tmp_path / "u8.n5")
    f = file_reader(path)
    f.create_dataset("seg", data=labels.astype("uint64"), chunks=(8, 12, 12))
    f.create_dataset(
        "bnd_u8", data=(values * 255).astype("uint8"), chunks=(8, 12, 12)
    )
    f.create_dataset(
        "affs", data=np.stack([values] * 3), chunks=(3, 8, 12, 12)
    )
    config_dir = str(tmp_path / "configs")
    tmp_folder = str(tmp_path / "tmp")
    cfg.write_global_config(config_dir, {"block_shape": [8, 12, 12]})

    # uint8 boundary maps normalize by /255 (the block path's convention):
    # mean features must land in [0, 1]
    task = ShardedProblemTask(
        tmp_folder, config_dir,
        input_path=path, input_key="bnd_u8",
        labels_path=path, labels_key="seg",
    )
    assert build([task])
    feats = file_reader(
        tmp_folder + "/data.zarr", "r"
    )["features/edges"][:]
    assert feats.shape[1] == 10 and feats[:, 0].max() <= 1.0

    # 4d affinity inputs fail loudly instead of sharding the channel axis
    bad = ShardedProblemTask(
        str(tmp_path / "tmp2"), config_dir,
        input_path=path, input_key="affs",
        labels_path=path, labels_key="seg",
    )
    with pytest.raises(Exception, match="3d boundary maps"):
        bad.run()


def test_sharded_problem_signed_labels_wrap_like_uint64_cast(tmp_path, rng):
    """An int64 segmentation with a negative (ignore-style) label must build
    the same node table the old full-volume uint64 cast produced: -1 wraps
    to 2**64-1 and stays in the graph, sorted last."""
    from cluster_tools_tpu.runtime import build, config as cfg
    from cluster_tools_tpu.tasks.features import ShardedProblemTask
    from cluster_tools_tpu.utils import file_reader

    labels = rng.integers(1, 6, (8, 8, 16)).astype("int64")
    labels[:, :, :4] = -1  # signed ignore label
    values = rng.random((8, 8, 16)).astype("float32")
    path = str(tmp_path / "signed.n5")
    f = file_reader(path)
    f.create_dataset("seg", data=labels, chunks=(4, 8, 16))
    f.create_dataset("bnd", data=values, chunks=(4, 8, 16))
    config_dir = str(tmp_path / "configs_signed")
    tmp_folder = str(tmp_path / "tmp_signed")
    cfg.write_global_config(config_dir, {"block_shape": [4, 8, 16]})
    task = ShardedProblemTask(
        tmp_folder, config_dir,
        input_path=path, input_key="bnd",
        labels_path=path, labels_key="seg",
    )
    assert build([task])
    store = file_reader(tmp_folder + "/data.zarr", "r")
    nodes = store["graph/nodes"][:]
    edges = store["graph/edges"][:]
    wrapped = np.uint64(np.iinfo(np.uint64).max)  # -1 as uint64
    assert nodes[-1] == wrapped  # present AND sorted last
    assert (np.sort(nodes) == nodes).all()
    # every edge endpoint indexes into the node table
    assert edges.max() < nodes.size
    # the wrapped label borders the positive ones: at least one edge
    touches = (nodes[edges] == wrapped).any(axis=1)
    assert touches.any()


def test_packed_sort_key_bit_identical(rng):
    """The single-int32-key RAG sort (packed=True, used whenever the compact
    label space fits 15 bits) must be bit-identical to the 3-key path."""
    import jax.numpy as jnp
    from scipy import ndimage

    from cluster_tools_tpu.ops import rag

    raw = ndimage.gaussian_filter(rng.random((6, 32, 64)), (1, 3, 3))
    raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype(np.float32)
    gz, gy, gx = np.meshgrid(
        np.arange(6) // 2, np.arange(32) // 8, np.arange(64) // 8,
        indexing="ij",
    )
    lab = (1 + gz * 32 + gy * 8 + gx).astype(np.int32)
    # a zero-label hole exercises the background skip in both paths
    lab[2:4, 10:20, 30:40] = 0
    for owner in (None, (4, 24, 48)):
        outs = {}
        for packed in (False, True):
            outs[packed] = tuple(
                np.asarray(x)
                for x in rag.boundary_edge_features_device(
                    jnp.asarray(lab), jnp.asarray(raw),
                    max_edges=2048, packed=packed, owner_shape=owner,
                )
            )
        for a, b in zip(outs[False], outs[True]):
            assert np.array_equal(a, b)
    # the host wrapper picks packed automatically and must match numpy
    edges, feats = rag.boundary_edge_features_tpu(lab.astype(np.uint64), raw)
    e2, f2 = rag.boundary_edge_features(lab.astype(np.uint64), raw)
    assert np.array_equal(edges, e2)
    assert np.allclose(feats, f2, rtol=1e-4, atol=1e-5)


def test_sharded_rag_unpacked_fallback_with_large_ids(rng):
    """A label id past the 15-bit packing bound forces the 3-key sort path;
    results must still match the host oracle (the packed path is covered by
    the small-id tests above, where the gate selects it automatically)."""
    labels, values = _fixture(rng)
    big = labels.copy()
    big[big == big.max()] = 40000  # > 32767: packing gate must decline
    edges, feats = sharded_boundary_edge_features(big, values)
    want_edges, want = boundary_edge_features(
        big.astype(np.uint64), values.astype(np.float64)
    )
    np.testing.assert_array_equal(edges, want_edges)
    np.testing.assert_allclose(
        feats[:, [0, 2, 8, 9]], want[:, [0, 2, 8, 9]], rtol=1e-5, atol=1e-6
    )
