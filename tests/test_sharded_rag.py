"""Collective RAG feature accumulation vs the host oracle."""

import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu.ops.rag import HIST_BINS, boundary_edge_features
from cluster_tools_tpu.parallel.sharded_rag import (
    sharded_boundary_edge_features,
)


def _fixture(rng, shape=(16, 24, 24), n_seg=40):
    labels = rng.integers(1, n_seg, tuple(s // 4 for s in shape))
    labels = np.kron(labels, np.ones((4, 4, 4), dtype=np.int64)).astype(
        np.int32
    )
    values = ndimage.gaussian_filter(rng.random(shape), 1.0).astype(np.float32)
    values = (values - values.min()) / (values.max() - values.min())
    return labels, values


def test_sharded_rag_matches_host_oracle(rng):
    labels, values = _fixture(rng)
    edges, feats = sharded_boundary_edge_features(labels, values)

    want_edges, want = boundary_edge_features(
        labels.astype(np.uint64), values.astype(np.float64)
    )
    np.testing.assert_array_equal(edges, want_edges)
    # exact columns: mean, var, min, max, count
    np.testing.assert_allclose(
        feats[:, [0, 2, 8, 9]], want[:, [0, 2, 8, 9]], rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(feats[:, 1], want[:, 1], rtol=1e-4, atol=1e-5)
    # quantiles: one histogram bin (the block merge's own tolerance)
    tol = 1.0 / HIST_BINS + 1e-6
    assert (np.abs(feats[:, 3:8] - want[:, 3:8]) <= tol).all()


def test_sharded_rag_cross_shard_edges(rng):
    # two segments meeting exactly AT a shard boundary: the edge's samples
    # live in one shard (pair ownership) but merging must still be correct
    # when a segment pair also touches inside other shards
    labels = np.ones((16, 8, 8), dtype=np.int32)
    labels[8:] = 2  # boundary at z=8 == shard boundary on the 8-device mesh
    values = rng.random((16, 8, 8)).astype(np.float32)
    edges, feats = sharded_boundary_edge_features(labels, values)
    want_edges, want = boundary_edge_features(
        labels.astype(np.uint64), values.astype(np.float64)
    )
    np.testing.assert_array_equal(edges, want_edges)
    np.testing.assert_allclose(
        feats[:, [0, 2, 8, 9]], want[:, [0, 2, 8, 9]], rtol=1e-5, atol=1e-6
    )


def test_sharded_rag_rejects_bad_extent(rng):
    labels, values = _fixture(rng, shape=(12, 8, 8))
    with pytest.raises(ValueError, match="not divisible"):
        sharded_boundary_edge_features(labels, values)


def test_sharded_rag_overflow_fails_loudly(rng):
    # more distinct edges than max_edges in every shard: the lexicographic
    # tail would be dropped identically everywhere, so the merged count
    # alone cannot see it — the local-table guard must raise
    labels, values = _fixture(rng, shape=(16, 40, 8), n_seg=60)
    with pytest.raises(RuntimeError, match="overflow"):
        sharded_boundary_edge_features(labels, values, max_edges=32)
