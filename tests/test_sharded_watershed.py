"""Collective DT-watershed vs the single-device fused kernel."""

import jax.numpy as jnp
import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu.ops.watershed import dt_watershed
from cluster_tools_tpu.parallel.sharded_watershed import sharded_dt_watershed


def _bijection(a, b):
    """Same foreground partition with a label bijection (shared oracle)."""
    from cluster_tools_tpu.ops.evaluation import same_partition

    return same_partition(np.asarray(a), np.asarray(b))


def _volume(rng, shape=(24, 24, 24)):
    raw = ndimage.gaussian_filter(rng.random(shape), (1.5, 2.0, 2.0))
    return ((raw - raw.min()) / (raw.max() - raw.min())).astype(np.float32)


@pytest.mark.parametrize("size_filter", [0, 12])
def test_sharded_dtws_matches_single_device_partition(rng, size_filter):
    raw = _volume(rng)
    kwargs = dict(
        threshold=0.6, sigma_seeds=1.0, sigma_weights=1.0,
        alpha=0.8, size_filter=size_filter,
    )
    ref, n_ref = dt_watershed(
        jnp.asarray(raw), apply_dt_2d=False, apply_ws_2d=False, **kwargs
    )
    ref = np.asarray(ref)
    got, n_got = sharded_dt_watershed(raw, **kwargs)
    assert n_got == int(n_ref)
    assert (got > 0).sum() == (ref > 0).sum()
    assert _bijection(got, ref), "partition differs from single-device kernel"


def test_sharded_dtws_no_smoothing(rng):
    # sigma 0 path: no halo convs at all
    raw = _volume(rng, shape=(16, 16, 16))
    ref, _ = dt_watershed(
        jnp.asarray(raw), apply_dt_2d=False, apply_ws_2d=False,
        threshold=0.55, sigma_seeds=0.0, sigma_weights=0.0, size_filter=0,
    )
    got, _ = sharded_dt_watershed(
        raw, threshold=0.55, sigma_seeds=0.0, sigma_weights=0.0, size_filter=0
    )
    assert _bijection(got, np.asarray(ref))


def test_sharded_dtws_non_divisible_z(rng):
    """z=25 on the 8-device mesh: internal foreground-side padding, mirrors
    at the TRUE boundary, pad excluded from seeds/flood/counts — the result
    still matches the unpadded single-device kernel exactly (a border
    fragment must not survive the size filter via padded copies)."""
    raw = _volume(rng, shape=(25, 16, 16))
    kwargs = dict(threshold=0.6, sigma_seeds=1.0, sigma_weights=1.0,
                  alpha=0.8, size_filter=12)
    ref, n_ref = dt_watershed(
        jnp.asarray(raw), apply_dt_2d=False, apply_ws_2d=False, **kwargs
    )
    got, n_got = sharded_dt_watershed(raw, **kwargs)
    assert got.shape == raw.shape
    assert n_got == int(n_ref)
    assert _bijection(got, np.asarray(ref))


def test_sharded_dtws_deep_halo_smoothing(rng):
    # sigma 2 -> gaussian radius 8 > z_local 3: multi-hop halos AND
    # out-of-volume reflection on shards NEAR (not at) the volume edge
    raw = _volume(rng)
    kwargs = dict(threshold=0.6, sigma_seeds=2.0, sigma_weights=2.0,
                  alpha=0.8, size_filter=20)
    ref, n_ref = dt_watershed(
        jnp.asarray(raw), apply_dt_2d=False, apply_ws_2d=False, **kwargs
    )
    got, n_got = sharded_dt_watershed(raw, **kwargs)
    assert n_got == int(n_ref)
    assert _bijection(got, np.asarray(ref))


class TestPerSlice2d:
    """The collective per-slice mode (sharded_dt_watershed_2d): z-slices
    are independent, so each slab runs the identical single-device kernel —
    the partition must equal the whole-volume 2d kernel's exactly; label
    values are slab-local + the shard plane offset (globally unique)."""

    @pytest.mark.parametrize("size_filter", [0, 12])
    def test_partition_matches_single_device(self, rng, size_filter):
        from cluster_tools_tpu.parallel.sharded_watershed import (
            sharded_dt_watershed_2d,
        )

        raw = _volume(rng)
        kwargs = dict(threshold=0.6, sigma_seeds=1.0, sigma_weights=1.0,
                      alpha=0.8, size_filter=size_filter)
        ref, _ = dt_watershed(
            jnp.asarray(raw), apply_dt_2d=True, apply_ws_2d=True, **kwargs
        )
        ref = np.asarray(ref)
        got, n_got = sharded_dt_watershed_2d(raw, **kwargs)
        assert ((got > 0) == (ref > 0)).all()
        assert _bijection(got, ref)
        # n is the summed per-slab max: exact distinct count unfiltered,
        # an upper bound once the size filter removes ids
        distinct = len(np.unique(got[got > 0]))
        if size_filter == 0:
            assert n_got == distinct == len(np.unique(ref[ref > 0]))
        else:
            assert n_got >= distinct > 0

    def test_non_divisible_z_pad_produces_no_labels(self, rng):
        from cluster_tools_tpu.parallel.sharded_watershed import (
            sharded_dt_watershed_2d,
        )

        raw = _volume(rng, shape=(21, 16, 16))
        kwargs = dict(threshold=0.6, sigma_seeds=1.0, sigma_weights=1.0,
                      alpha=0.8, size_filter=8)
        ref, _ = dt_watershed(
            jnp.asarray(raw), apply_dt_2d=True, apply_ws_2d=True, **kwargs
        )
        got, _ = sharded_dt_watershed_2d(raw, **kwargs)
        assert got.shape == raw.shape  # pad planes cropped, no pad labels
        assert ((got > 0) == (np.asarray(ref) > 0)).all()
        assert _bijection(got, np.asarray(ref))

    def test_task_mode_dispatch(self, tmp_path, rng):
        """ShardedWatershedTask with apply_dt_2d/ws_2d=True routes to the
        per-slice kernel; mixed modes are refused."""
        from cluster_tools_tpu.runtime import build, config as cfg
        from cluster_tools_tpu.tasks.watershed import ShardedWatershedTask
        from cluster_tools_tpu.utils import file_reader

        raw = _volume(rng)
        path = str(tmp_path / "d2.n5")
        file_reader(path).create_dataset("bnd", data=raw, chunks=(12, 12, 12))
        config_dir = str(tmp_path / "configs2")
        cfg.write_global_config(
            config_dir, {"block_shape": [12, 12, 12], "target": "tpu"}
        )
        cfg.write_config(
            config_dir, "sharded_watershed",
            {"threshold": 0.6, "sigma_seeds": 1.0, "size_filter": 10,
             "apply_dt_2d": True, "apply_ws_2d": True},
        )
        task = ShardedWatershedTask(
            str(tmp_path / "tmp2"), config_dir,
            input_path=path, input_key="bnd",
            output_path=path, output_key="ws2d",
        )
        assert build([task])
        ws = file_reader(path, "r")["ws2d"][:]
        ref, _ = dt_watershed(
            jnp.asarray(raw), apply_dt_2d=True, apply_ws_2d=True,
            threshold=0.6, sigma_seeds=1.0, sigma_weights=2.0, size_filter=10,
        )
        assert _bijection(ws, np.asarray(ref))
        ids = np.unique(ws)
        assert ids[0] == 0 and (np.diff(ids) == 1).all()  # consecutive

        cfg.write_config(
            config_dir, "sharded_watershed",
            {"threshold": 0.6, "apply_dt_2d": True, "apply_ws_2d": False},
        )
        bad = ShardedWatershedTask(
            str(tmp_path / "tmp3"), config_dir,
            input_path=path, input_key="bnd",
            output_path=path, output_key="wsbad",
        )
        with pytest.raises(Exception, match="apply_dt_2d == apply_ws_2d"):
            bad.run()


def test_sharded_watershed_workflow(tmp_path, rng):
    """WatershedWorkflow(sharded=True): one collective task, globally
    consistent fragments (no block-offset id ranges), consecutive ids."""
    from cluster_tools_tpu.runtime import build, config as cfg
    from cluster_tools_tpu.utils import file_reader
    from cluster_tools_tpu.workflows.watershed import WatershedWorkflow

    raw = _volume(rng)
    path = str(tmp_path / "d.n5")
    file_reader(path).create_dataset("bnd", data=raw, chunks=(12, 12, 12))
    config_dir = str(tmp_path / "configs")
    tmp_folder = str(tmp_path / "tmp")
    cfg.write_global_config(
        config_dir, {"block_shape": [12, 12, 12], "target": "tpu"}
    )
    cfg.write_config(
        config_dir, "sharded_watershed",
        {"threshold": 0.6, "sigma_seeds": 1.0, "size_filter": 10},
    )
    wf = WatershedWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="bnd",
        output_path=path, output_key="ws",
        sharded=True,
    )
    assert build([wf])
    ws = file_reader(path, "r")["ws"][:]

    # partition equals the single-device fused kernel's
    ref, _ = dt_watershed(
        jnp.asarray(raw), apply_dt_2d=False, apply_ws_2d=False,
        threshold=0.6, sigma_seeds=1.0, sigma_weights=2.0, size_filter=10,
    )
    assert _bijection(ws, np.asarray(ref))
    ids = np.unique(ws)
    assert ids[0] == 0 and (np.diff(ids) == 1).all()  # consecutive

    # unsupported combinations fail loudly
    with pytest.raises(ValueError, match="mask"):
        WatershedWorkflow(
            tmp_folder, config_dir, input_path=path, input_key="bnd",
            output_path=path, output_key="x", mask_path=path, mask_key="m",
            sharded=True,
        ).requires()
    with pytest.raises(ValueError, match="globally consistent"):
        WatershedWorkflow(
            tmp_folder, config_dir, input_path=path, input_key="bnd",
            output_path=path, output_key="x", sharded=True, two_pass=True,
        ).requires()
