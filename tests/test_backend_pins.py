"""Mode-pin precedence in ops._backend: forced > env > pin file > default.

The pin file (tools/chip_modes.json, CTT_MODES_FILE to relocate) carries
on-chip measured mode choices; it must apply only when the running backend
matches its tag so TPU pins never leak into CPU runs.
"""

import json

import jax
import pytest

from cluster_tools_tpu.ops import _backend

# the running backend, whatever the host provides (cpu under conftest's
# virtual mesh) — tests tag pin files with it so they hold on any host
HERE = jax.default_backend()
OTHER = "tpu" if HERE != "tpu" else "cpu"


@pytest.fixture
def pin_file(tmp_path, monkeypatch):
    path = tmp_path / "chip_modes.json"

    def write(payload):
        path.write_text(json.dumps(payload))
        monkeypatch.setenv("CTT_MODES_FILE", str(path))
        _backend._PINS_CACHE.clear()
        return path

    yield write
    _backend._PINS_CACHE.clear()


def test_matching_backend_pins_apply(pin_file, monkeypatch):
    monkeypatch.delenv("CTT_FLOOD_MODE", raising=False)
    pin_file({"backend": HERE, "modes": {"CTT_FLOOD_MODE": "pallas"}})
    assert _backend.use_pallas_flood()


def test_mismatched_backend_pins_ignored(pin_file, monkeypatch):
    monkeypatch.delenv("CTT_FLOOD_MODE", raising=False)
    pin_file({"backend": OTHER, "modes": {"CTT_FLOOD_MODE": "pallas"}})
    assert not _backend.use_pallas_flood()


def test_env_overrides_pin_file(pin_file, monkeypatch):
    pin_file({"backend": HERE, "modes": {"CTT_SWEEP_MODE": "assoc"}})
    monkeypatch.setenv("CTT_SWEEP_MODE", "seq")
    assert not _backend.use_assoc()


def test_forced_overrides_everything(pin_file, monkeypatch):
    monkeypatch.delenv("CTT_CC_MODE", raising=False)
    pin_file({"backend": HERE, "modes": {"CTT_CC_MODE": "pallas"}})
    with _backend.force_cc_mode("xla"):
        assert not _backend.use_pallas_cc()
    assert _backend.use_pallas_cc()


def test_untagged_flat_file_is_rejected(pin_file, monkeypatch):
    # a pin file without a backend tag carries measurements of unknown
    # provenance — never apply it (cross-backend leak risk)
    monkeypatch.delenv("CTT_FLOOD_MODE", raising=False)
    pin_file({"CTT_FLOOD_MODE": "pallas"})
    assert not _backend.use_pallas_flood()


def test_missing_or_bad_file_falls_through(pin_file, monkeypatch):
    monkeypatch.delenv("CTT_FLOOD_MODE", raising=False)
    monkeypatch.setenv("CTT_MODES_FILE", "/nonexistent/chip_modes.json")
    _backend._PINS_CACHE.clear()
    assert not _backend.use_pallas_flood()
    _backend._PINS_CACHE.clear()
