"""Sharded whole-volume kernels on the 8-virtual-device mesh.

The collective path (ppermute halo exchange + psum convergence inside one
jit) must reproduce the single-device oracles exactly — the same program
runs on a real ICI mesh.
"""

import jax
import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu.ops.cc import connected_components_raw
from cluster_tools_tpu.parallel.mesh import get_mesh
from cluster_tools_tpu.parallel.sharded import (
    halo_exchange,
    sharded_connected_components,
    sharded_seeded_watershed,
)


def _cc_partition_equal(raw_labels, ref):
    """Sharded-CC output (root ids, -1 = background) vs an oracle labeling:
    shift to the same_partition convention (background 0, ids >= 1)."""
    from cluster_tools_tpu.ops.evaluation import same_partition

    shifted = np.where(raw_labels < 0, 0, raw_labels.astype(np.int64) + 1)
    return same_partition(shifted, ref)


@pytest.mark.parametrize("connectivity", [1, 3])
def test_sharded_cc_matches_oracle(rng, connectivity):
    mesh = get_mesh()
    n = mesh.shape["data"]
    assert n == 8
    mask = rng.random((24, 16, 16)) < 0.4

    got = np.asarray(
        sharded_connected_components(mask, mesh=mesh, connectivity=connectivity)
    )
    structure = ndimage.generate_binary_structure(3, connectivity)
    ref, _ = ndimage.label(mask, structure=structure)

    assert (got[~mask] == -1).all()
    assert _cc_partition_equal(got, ref)


def test_sharded_cc_root_ids_match_single_device(rng):
    # root = min global flat index, identical to connected_components_raw
    mask = rng.random((16, 8, 8)) < 0.5
    got = np.asarray(sharded_connected_components(mask))
    ref = np.asarray(connected_components_raw(mask, connectivity=1))
    np.testing.assert_array_equal(got, ref)


def test_sharded_cc_cross_all_shards(rng):
    # a snake spanning every shard: label info must cross 7 boundaries
    mask = np.zeros((24, 8, 8), dtype=bool)
    mask[:, 4, 4] = True  # one column through the whole volume
    mask[0, 4, :] = True
    got = np.asarray(sharded_connected_components(mask))
    ids = np.unique(got[mask])
    assert ids.size == 1  # single component across all 8 shards


def test_halo_exchange_roundtrip(rng):
    from functools import partial

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cluster_tools_tpu.parallel.sharded import shard_map

    mesh = get_mesh()
    x = np.arange(24 * 4 * 4, dtype=np.float32).reshape(24, 4, 4)
    xd = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("data")))

    fn = shard_map(
        partial(halo_exchange, halo=1, axis_name="data", fill=-1.0),
        mesh=mesh, in_specs=P("data"), out_specs=P("data"),
    )
    out = np.asarray(jax.jit(fn)(xd))  # (24 + 8*2, 4, 4) re-stacked
    out = out.reshape(8, 5, 4, 4)  # per-shard extended blocks (3+2 planes)
    for s in range(8):
        lo = out[s, 0]
        core = out[s, 1:4]
        hi = out[s, 4]
        np.testing.assert_array_equal(core, x[3 * s : 3 * s + 3])
        if s == 0:
            assert (lo == -1.0).all()
        else:
            np.testing.assert_array_equal(lo, x[3 * s - 1])
        if s == 7:
            assert (hi == -1.0).all()
        else:
            np.testing.assert_array_equal(hi, x[3 * s + 3])


def test_sharded_cc_single_plane_shards(rng):
    # z extent == mesh size: every shard holds ONE plane, which is both of
    # its boundary planes (regression: carry-shape crash in boundary_merge)
    mask = rng.random((8, 8, 8)) < 0.5
    got = np.asarray(sharded_connected_components(mask))
    ref = np.asarray(connected_components_raw(mask, connectivity=1))
    np.testing.assert_array_equal(got, ref)


def test_halo_exchange_multi_hop():
    # halo deeper than one shard: planes chain through multiple neighbors
    from functools import partial

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cluster_tools_tpu.parallel.sharded import shard_map

    mesh = get_mesh()
    x = np.arange(16 * 2 * 2, dtype=np.float32).reshape(16, 2, 2)  # Zl = 2
    xd = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("data")))
    halo = 5  # needs 3 hops at z_local = 2
    fn = shard_map(
        partial(halo_exchange, halo=halo, axis_name="data", fill=-1.0),
        mesh=mesh, in_specs=P("data"), out_specs=P("data"),
    )
    out = np.asarray(jax.jit(fn)(xd)).reshape(8, 2 * halo + 2, 2, 2)
    for s in range(8):
        z0 = 2 * s
        np.testing.assert_array_equal(out[s, halo : halo + 2], x[z0 : z0 + 2])
        for k in range(halo):
            src = z0 - halo + k
            want = x[src] if src >= 0 else np.full((2, 2), -1.0)
            np.testing.assert_array_equal(out[s, k], want)
            src = z0 + 2 + k
            want = x[src] if src < 16 else np.full((2, 2), -1.0)
            np.testing.assert_array_equal(out[s, halo + 2 + k], want)


class TestShardedFlood:
    def _setup(self, rng, shape=(24, 16, 16)):
        import jax.numpy as jnp
        from scipy import ndimage as ndi

        from cluster_tools_tpu.ops.dt import distance_transform
        from cluster_tools_tpu.ops.watershed import dt_seeds

        raw = ndi.gaussian_filter(rng.random(shape), (1.0, 2.0, 2.0))
        raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")
        fg = raw < 0.6
        dt = distance_transform(jnp.asarray(fg))
        seeds, _ = dt_seeds(dt, sigma=1.0)
        return raw, seeds, fg

    def test_matches_single_device_flood_exactly(self, rng):
        import jax.numpy as jnp

        from cluster_tools_tpu.ops.watershed import seeded_watershed
        from cluster_tools_tpu.parallel.sharded import sharded_seeded_watershed

        hmap, seeds, fg = self._setup(rng)
        ref = np.asarray(
            seeded_watershed(jnp.asarray(hmap), seeds, jnp.asarray(fg))
        )
        got = np.asarray(sharded_seeded_watershed(hmap, seeds, mask=fg))
        np.testing.assert_array_equal(got, ref)

    def test_flood_crosses_all_shards(self):
        # single seed at the top, open corridor: the flood must descend
        # through every shard boundary
        hmap = np.full((24, 8, 8), 0.5, dtype=np.float32)
        seeds = np.zeros((24, 8, 8), dtype=np.int32)
        seeds[0, 4, 4] = 7
        got = np.asarray(sharded_seeded_watershed(hmap, seeds))
        assert (got == 7).all()

    def test_single_plane_shards(self, rng):
        import jax.numpy as jnp

        from cluster_tools_tpu.ops.watershed import seeded_watershed
        from cluster_tools_tpu.parallel.sharded import sharded_seeded_watershed

        hmap, seeds, fg = self._setup(rng, shape=(8, 12, 12))
        ref = np.asarray(
            seeded_watershed(jnp.asarray(hmap), seeds, jnp.asarray(fg))
        )
        got = np.asarray(sharded_seeded_watershed(hmap, seeds, mask=fg))
        np.testing.assert_array_equal(got, ref)
