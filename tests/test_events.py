"""ctt-events: high-rate event building tests.

Covers the PR acceptance contract:

  * kernel parity vs the scipy oracle (``ndimage.label`` + numpy
    reductions): EXACT label equality (the device kernel reproduces
    scipy's raster first-encounter order), exact counts, close props —
    across connectivity 1/2, empty frames, single hot pixels, a blob
    spanning a whole frame (frames must stay independent), ragged
    per-frame cluster counts, and capacity overflow auto-growth;
  * pow2 bucketing: a ragged stream of frame counts compiles one program
    per shape BUCKET, not per shape (``kernel_cache_size`` deltas);
  * serve ``event_batch`` e2e: daemon output byte-identical to an
    in-process ``build()`` run, event tables match the oracle, and
    ``ctt_events_frames_total`` shows up nonzero in /metrics;
  * mini-soak at the tenant-quota edge ("millions of users" shape): a
    burst of ~1k submissions gets clean 429s past capacity, every
    accepted job completes, no lease-renewer threads leak, and the
    process returns to thread/fd baseline (the per-request allocation
    audit's assertion).
"""

import os
import threading
import time

import numpy as np
import pytest

from cluster_tools_tpu.obs import metrics as obs_metrics
from cluster_tools_tpu.obs import trace as obs_trace
from cluster_tools_tpu.ops import events as events_ops
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.serve import QuotaRejected, ServeClient, ServeDaemon
from cluster_tools_tpu.tasks.events import read_event_tables
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import EventBuildingWorkflow


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _frame_stack(rng, n=10, h=24, w=20, density=0.9):
    """Detector-like frames: smooth blobs + isolated hot pixels, ragged
    cluster counts across frames."""
    from scipy import ndimage

    raw = ndimage.gaussian_filter(
        rng.random((n, h, w)), (0.0, 1.0, 1.0)
    ).astype("float32")
    frames = np.where(
        raw > np.quantile(raw, density), raw, 0.0
    ).astype("float32")
    # sprinkle single-pixel hits
    hits = rng.random((n, h, w)) > 0.99
    frames[hits] = (rng.random(int(hits.sum())) + 1.0).astype("float32")
    return frames


def _assert_parity(frames, threshold=0.0, connectivity=2, **kw):
    labels, counts, props = events_ops.build_events(
        frames, threshold=threshold, connectivity=connectivity, **kw
    )
    ref_l, ref_c, ref_p = events_ops.build_events_np(
        frames, threshold=threshold, connectivity=connectivity
    )
    np.testing.assert_array_equal(counts, ref_c)
    np.testing.assert_array_equal(labels, ref_l)
    for f in range(len(counts)):
        k = int(counts[f])
        np.testing.assert_allclose(
            props[f, :k], ref_p[f, :k], rtol=1e-4, atol=1e-4,
            err_msg=f"frame {f}",
        )
    return labels, counts, props


class TestKernelParity:
    @pytest.mark.parametrize("connectivity", [1, 2])
    def test_random_frames(self, rng, connectivity):
        frames = _frame_stack(rng)
        _assert_parity(frames, connectivity=connectivity)

    def test_empty_frames(self):
        frames = np.zeros((5, 16, 16), np.float32)
        labels, counts, props = _assert_parity(frames)
        assert counts.sum() == 0 and labels.max() == 0
        assert props.shape == (5, 0, events_ops.N_PROPS)

    def test_single_hot_pixel(self):
        frames = np.zeros((3, 16, 16), np.float32)
        frames[1, 7, 9] = 2.5
        labels, counts, props = _assert_parity(frames)
        assert counts.tolist() == [0, 1, 0]
        size, energy, cy, cx = props[1, 0, :4]
        assert (size, energy, cy, cx) == (1.0, 2.5, 7.0, 9.0)

    def test_frame_spanning_blob_stays_per_frame(self):
        # every pixel above threshold: ONE cluster per frame, and
        # adjacent frames must NOT merge (frames are independent events)
        frames = np.ones((4, 8, 8), np.float32)
        labels, counts, _ = _assert_parity(frames)
        assert counts.tolist() == [1, 1, 1, 1]
        assert (labels == 1).all()

    def test_ragged_counts_and_nonsquare(self, rng):
        frames = _frame_stack(rng, n=7, h=17, w=33, density=0.85)
        frames[3] = 0.0  # one empty frame mid-stack
        _, counts, _ = _assert_parity(frames)
        assert counts[3] == 0 and len(set(counts.tolist())) > 1

    def test_capacity_overflow_grows_and_matches(self, rng):
        frames = _frame_stack(rng, n=4, density=0.8)  # dense: many clusters
        _, counts, _ = _assert_parity(frames, max_clusters=2)
        assert counts.max() > 2  # growth actually happened

    def test_zero_frames(self):
        labels, counts, props = events_ops.build_events(
            np.zeros((0, 8, 8), np.float32)
        )
        assert labels.shape == (0, 8, 8) and counts.size == 0

    def test_2d_promotes_to_single_frame(self, rng):
        frame = _frame_stack(rng, n=1)[0]
        labels, counts, _ = events_ops.build_events(frame)
        assert labels.shape == (1,) + frame.shape and counts.shape == (1,)


class TestCompileBuckets:
    def test_ragged_stream_compiles_per_bucket(self, rng):
        """Frame counts 3..8 over a (16, 64) frame pad to TWO pow2
        buckets (4 and 8 frames) — two compiles, and a repeat of the
        whole ragged stream compiles nothing."""
        stacks = {
            n: _frame_stack(rng, n=n, h=16, w=64, density=0.97)
            for n in (3, 4, 5, 7, 8)
        }
        before = events_ops.kernel_cache_size()
        for n, frames in stacks.items():
            events_ops.build_events(frames, max_clusters=32)
        first = events_ops.kernel_cache_size() - before
        assert first == 2, f"expected 2 shape buckets, compiled {first}"
        for n, frames in stacks.items():
            events_ops.build_events(frames, max_clusters=32)
        assert events_ops.kernel_cache_size() - before == first


# ---------------------------------------------------------------------------
# serve: event_batch jobs


GCONF = {
    "block_shape": [2, 16, 16], "target": "tpu",
    "device_batch_size": 2, "devices": [0], "pipeline_depth": 2,
}


@pytest.fixture
def daemon_factory(tmp_path):
    """In-process daemons with tracing scoped to this test (mirrors
    tests/test_serve.py — the serve counters need the trace switch)."""
    was_on = obs_trace.enabled()
    if not was_on:
        obs_trace.enable(str(tmp_path / "trace"), "events_test",
                         export_env=False)
    daemons = []

    def make(state_dir, **conf):
        d = ServeDaemon(str(state_dir), config=conf)
        d.start()
        daemons.append(d)
        return d

    yield make
    for d in daemons:
        d.request_drain()
        if d._httpd is not None:
            d._httpd.shutdown()
            d._httpd.server_close()
        for t in d._threads:
            if t.name.startswith("ctt-serve-exec"):
                t.join(timeout=30)
    if not was_on:
        obs_trace.disable()


def _write_frames(tmp_path, rng, n=10, h=16, w=16, tag="frames"):
    path = str(tmp_path / f"{tag}.n5")
    frames = _frame_stack(rng, n=n, h=h, w=w)
    file_reader(path).create_dataset(
        "frames", data=frames, chunks=(2, h, w)
    )
    return path, frames


def _no_leaked_renewers(timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.name == "ctt-serve-lease" and t.is_alive()]
        if not alive:
            return True
        time.sleep(0.05)
    return False


class TestServeEvents:
    def test_event_batch_e2e_byte_parity(self, tmp_path, daemon_factory,
                                         rng):
        path, frames = _write_frames(tmp_path, rng)
        t = float(np.quantile(frames[frames > 0], 0.2)) if (
            frames > 0).any() else 0.0

        # in-process reference build
        ref_cfg = str(tmp_path / "configs_ref")
        cfg.write_global_config(ref_cfg, GCONF)
        cfg.write_config(ref_cfg, "events", {"threshold": t})
        wf = EventBuildingWorkflow(
            str(tmp_path / "tmp_ref"), ref_cfg,
            input_path=path, input_key="frames",
            output_path=path, output_key="ev_ref",
        )
        assert build([wf])

        daemon_factory(tmp_path / "state")
        client = ServeClient(state_dir=str(tmp_path / "state"))
        job = client.event_batch(
            input_path=path, input_key="frames",
            output_path=path, output_key="ev_srv",
            tmp_folder=str(tmp_path / "tmp_srv"),
            config_dir=str(tmp_path / "configs_srv"),
            threshold=t,
            configs={"global": GCONF},
        )
        state = client.wait(job, timeout_s=300)
        assert state["result"]["ok"]

        f = file_reader(path, "r")
        srv_labels = f["ev_srv"][:]
        np.testing.assert_array_equal(srv_labels, f["ev_ref"][:])
        n_blocks = (len(frames) + GCONF["block_shape"][0] - 1) \
            // GCONF["block_shape"][0]
        srv_tab = read_event_tables(path, "ev_srv", n_blocks)
        ref_tab = read_event_tables(path, "ev_ref", n_blocks)
        np.testing.assert_array_equal(srv_tab, ref_tab)

        # oracle: per-frame labels and per-frame table row counts
        ora_labels, ora_counts, _ = events_ops.build_events_np(
            frames, threshold=t
        )
        np.testing.assert_array_equal(srv_labels, ora_labels)
        assert len(srv_tab) == int(ora_counts.sum())

        # the events counters surface on the daemon's /metrics
        text = client.metrics_text()
        lines = {
            parts[0]: float(parts[1])
            for parts in (ln.split() for ln in text.splitlines())
            if len(parts) == 2 and not parts[0].startswith("#")
        }
        assert lines.get("ctt_events_frames_total", 0) >= len(frames)
        assert lines.get("ctt_events_clusters_total", 0) > 0
        try:
            from prometheus_client.openmetrics.parser import (
                text_string_to_metric_families,
            )
            assert list(text_string_to_metric_families(text))
        except ImportError:
            pass

    def test_soak_quota_edge_no_leaks(self, tmp_path, daemon_factory,
                                      rng):
        """Sustained submission well past capacity: clean 429s, every
        accepted job finishes, and the process holds thread/fd baseline
        across ~1k requests — the serve-path allocation audit."""
        path, frames = _write_frames(tmp_path, rng, n=4, tag="soak")
        daemon_factory(
            tmp_path / "state", tenant_quota=2, max_queue_depth=4
        )
        client = ServeClient(state_dir=str(tmp_path / "state"))

        def submit(i):
            return client.event_batch(
                input_path=path, input_key="frames",
                output_path=path, output_key=f"soak_{i}",
                tmp_folder=str(tmp_path / f"tmp_soak_{i}"),
                config_dir=str(tmp_path / f"configs_soak_{i}"),
                configs={"global": GCONF},
            )

        # warm-up: one full job (compiles, pool threads, store handles)
        # so the baseline below measures steady state, not cold start
        assert client.wait(submit(0), timeout_s=300)["result"]["ok"]
        assert _no_leaked_renewers()
        threads_before = threading.active_count()
        fds_before = len(os.listdir("/proc/self/fd"))

        accepted, rejected = [], 0
        for i in range(1, 1001):
            try:
                accepted.append(submit(i))
            except QuotaRejected:
                rejected += 1
        assert rejected >= 500, f"only {rejected} rejections in the burst"
        assert accepted, "the burst starved ALL submissions"
        for job in accepted:
            assert client.wait(job, timeout_s=300)["result"]["ok"]

        # the 429s are accounted, not silent
        obs_metrics.flush()
        text = client.metrics_text()
        assert any(
            ln.split()[0] == "ctt_serve_quota_rejections_total"
            and float(ln.split()[1]) >= rejected
            for ln in text.splitlines() if ln and not ln.startswith("#")
        )

        # zero leaks: lease renewers dead, thread + fd baseline restored
        assert _no_leaked_renewers()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            threads_ok = threading.active_count() <= threads_before
            fds_ok = len(os.listdir("/proc/self/fd")) <= fds_before
            if threads_ok and fds_ok:
                break
            time.sleep(0.1)
        assert threading.active_count() <= threads_before, (
            f"thread growth: {threads_before} -> "
            f"{threading.active_count()}: "
            f"{[t.name for t in threading.enumerate()]}"
        )
        assert len(os.listdir("/proc/self/fd")) <= fds_before, (
            f"fd growth: {fds_before} -> "
            f"{len(os.listdir('/proc/self/fd'))}"
        )
