"""Lifted multicut tests: ops oracles (brute-force energy, native-vs-python)
and the end-to-end lifted segmentation workflow."""

import os

import numpy as np
import pytest

from cluster_tools_tpu.ops.lifted import (
    _lifted_gaec_python,
    lifted_costs_from_node_labels,
    lifted_multicut_energy,
    lifted_neighborhood,
    merge_lifted_problems,
    solve_lifted_multicut,
)
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import LiftedMulticutSegmentationWorkflow


def _partitions(n):
    """All set partitions of range(n) as restricted-growth label vectors."""
    lab = np.zeros(n, dtype=int)

    def rec(i, k):
        if i == n:
            yield lab.copy()
            return
        for c in range(k + 1):
            lab[i] = c
            yield from rec(i + 1, max(k, c + 1))

    yield from rec(0, 0)


class TestLiftedOps:
    def test_neighborhood_matches_bfs_oracle(self, rng):
        n = 30
        edges = np.unique(
            np.sort(rng.integers(0, n, (60, 2)), axis=1), axis=0
        )
        edges = edges[edges[:, 0] != edges[:, 1]].astype(np.int64)
        part = rng.random(n) < 0.7
        depth = 3
        got = lifted_neighborhood(n, edges, part, depth=depth)
        # oracle: per-source BFS
        adj = [[] for _ in range(n)]
        for u, v in edges:
            adj[u].append(v)
            adj[v].append(u)
        want = set()
        for s in range(n):
            if not part[s]:
                continue
            dist = {s: 0}
            frontier = [s]
            for d in range(1, depth + 1):
                nxt = []
                for u in frontier:
                    for v in adj[u]:
                        if v not in dist:
                            dist[v] = d
                            nxt.append(v)
                frontier = nxt
            for t, d in dist.items():
                if d >= 2 and part[t] and s < t:
                    want.add((s, t))
        assert {tuple(e) for e in got} == want

    def test_neighborhood_high_degree_hub(self):
        # regression: >=128 parallel 2-paths through intermediates must not
        # overflow the path-count dtype and drop the (0, 199) lifted pair
        n = 200
        inter = np.arange(1, 131)
        edges = np.concatenate(
            [
                np.stack([np.zeros_like(inter), inter], axis=1),
                np.stack([inter, np.full_like(inter, n - 1)], axis=1),
            ]
        ).astype(np.int64)
        part = np.zeros(n, dtype=bool)
        part[0] = part[n - 1] = True
        got = lifted_neighborhood(n, edges, part, depth=2)
        np.testing.assert_array_equal(got, [[0, n - 1]])

    def test_solver_beats_trivial_on_brute_force(self, rng):
        # 7-node random problems: lifted-GAEC energy must match or come close
        # to the brute-force optimum, and never lose to merge-all/split-all
        for seed in range(5):
            r = np.random.default_rng(seed)
            n = 7
            uv = np.array(
                [(i, j) for i in range(n) for j in range(i + 1, n)
                 if r.random() < 0.5], dtype=np.int64
            )
            if uv.shape[0] == 0:
                continue
            costs = r.normal(0, 2, uv.shape[0])
            lifted_uv = np.array([[0, n - 1], [1, n - 2]], dtype=np.int64)
            lifted_costs = r.normal(0, 4, 2)
            labels = solve_lifted_multicut(n, uv, costs, lifted_uv, lifted_costs)
            e_sol = lifted_multicut_energy(uv, costs, lifted_uv, lifted_costs, labels)
            # brute force over all set partitions (Bell(7) = 877 restricted-
            # growth strings, not 7^7 label vectors)
            best = np.inf
            for lab in _partitions(n):
                e = lifted_multicut_energy(uv, costs, lifted_uv, lifted_costs, lab)
                best = min(best, e)
            e_merge = lifted_multicut_energy(
                uv, costs, lifted_uv, lifted_costs, np.zeros(n, int)
            )
            e_split = lifted_multicut_energy(
                uv, costs, lifted_uv, lifted_costs, np.arange(n)
            )
            assert e_sol <= min(e_merge, e_split) + 1e-9
            assert e_sol <= best + 0.5 * abs(best) + 1e-9  # greedy ≈ optimum

    def test_native_matches_python(self, rng):
        from cluster_tools_tpu import native

        if not native.available():
            pytest.skip("native solvers unavailable")
        n = 40
        uv = np.unique(
            np.sort(rng.integers(0, n, (150, 2)), axis=1), axis=0
        )
        uv = uv[uv[:, 0] != uv[:, 1]].astype(np.int64)
        costs = rng.normal(0.5, 1.5, uv.shape[0])
        lifted_uv = np.unique(
            np.sort(rng.integers(0, n, (30, 2)), axis=1), axis=0
        )
        lifted_uv = lifted_uv[lifted_uv[:, 0] != lifted_uv[:, 1]].astype(np.int64)
        lifted_costs = rng.normal(-1.0, 2.0, lifted_uv.shape[0])
        lab_nat = solve_lifted_multicut(
            n, uv, costs, lifted_uv, lifted_costs, use_native=True
        )
        lab_py = _lifted_gaec_python(n, uv, costs, lifted_uv, lifted_costs)
        _, lab_py = np.unique(lab_py, return_inverse=True)
        e_nat = lifted_multicut_energy(uv, costs, lifted_uv, lifted_costs, lab_nat)
        e_py = lifted_multicut_energy(uv, costs, lifted_uv, lifted_costs, lab_py)
        assert e_nat == pytest.approx(e_py, abs=1e-6)

    def test_costs_from_node_labels(self):
        uv = np.array([[0, 1], [1, 2], [0, 3]], dtype=np.int64)
        labels = np.array([5, 5, 7, 0])
        out_uv, costs = lifted_costs_from_node_labels(
            uv, labels, same_cost=2.0, different_cost=-3.0, ignore_label=0
        )
        np.testing.assert_array_equal(out_uv, [[0, 1], [1, 2]])
        np.testing.assert_array_equal(costs, [2.0, -3.0])

    def test_merge_lifted_problems(self):
        p1 = (np.array([[0, 1], [1, 2]], dtype=np.int64), np.array([1.0, 2.0]))
        p2 = (np.array([[1, 2], [3, 4]], dtype=np.int64), np.array([0.5, -1.0]))
        uv, costs = merge_lifted_problems([p1, p2])
        np.testing.assert_array_equal(uv, [[0, 1], [1, 2], [3, 4]])
        np.testing.assert_allclose(costs, [1.0, 2.5, -1.0])


@pytest.fixture
def cells_with_classes(tmp_path, rng):
    """Voronoi cells + boundary ridges + a 2-class semantic prior volume."""
    shape = (24, 48, 48)
    pts = rng.integers(0, 48, (24, 3))
    pts[:, 0] = pts[:, 0] % shape[0]
    zz, yy, xx = np.mgrid[: shape[0], : shape[1], : shape[2]]
    d = np.full(shape, 1e9)
    second = np.full(shape, 1e9)
    gt = np.zeros(shape, dtype=np.uint64)
    for i, p in enumerate(pts):
        dist = (zz - p[0]) ** 2 + (yy - p[1]) ** 2 + (xx - p[2]) ** 2
        newmin = dist < d
        second = np.where(newmin, d, np.minimum(second, dist))
        gt = np.where(newmin, i + 1, gt)
        d = np.where(newmin, dist, d)
    bnd = np.exp(-((np.sqrt(second) - np.sqrt(d)) ** 2) / 8.0).astype("float32")
    # semantic classes: left half class 1, right half class 2 (x-split)
    classes = np.where(xx < shape[2] // 2, 1, 2).astype("uint64")
    path = str(tmp_path / "d.n5")
    f = file_reader(path)
    f.create_dataset("bnd", data=bnd, chunks=(12, 24, 24))
    f.create_dataset("gt", data=gt, chunks=(12, 24, 24))
    f.create_dataset("classes", data=classes, chunks=(12, 24, 24))
    return path, bnd, gt, classes


@pytest.mark.parametrize("target", ["local", "tpu"])
def test_lifted_segmentation_workflow(tmp_path, cells_with_classes, target):
    path, bnd, gt, classes = cells_with_classes
    config_dir = str(tmp_path / f"configs_{target}")
    tmp_folder = str(tmp_path / f"tmp_{target}")
    cfg.write_global_config(
        config_dir, {"block_shape": [12, 24, 24], "target": target}
    )
    cfg.write_config(
        config_dir, "watershed",
        {"threshold": 0.4, "sigma_seeds": 1.6, "size_filter": 10,
         "apply_dt_2d": False, "apply_ws_2d": False, "halo": [2, 4, 4]},
    )
    cfg.write_config(
        config_dir, "costs_from_node_labels",
        {"same_cost": 4.0, "different_cost": -4.0},
    )
    wf = LiftedMulticutSegmentationWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="bnd",
        ws_path=path, ws_key="ws_lmc",
        labels_path=path, labels_key="classes",
        output_path=path, output_key="seg_lmc",
        n_scales=1,
    )
    assert build([wf])
    seg = file_reader(path, "r")["seg_lmc"][:]
    assert seg.shape == gt.shape
    ids = np.unique(seg[seg > 0])
    assert ids.size > 5
    # the lifted prior (repulsive across classes) keeps segments from
    # straddling the class boundary: most segments live in one class
    straddle = 0
    for i in ids:
        cls = np.unique(classes[seg == i])
        straddle += int(cls.size > 1)
    assert straddle / ids.size < 0.5
    # lifted problem artifacts exist
    assert os.path.exists(
        os.path.join(tmp_folder, "lifted_problem_lifted.npz")
    )
