"""ctt-hier tests: one-flood hierarchical segmentation.

Covers the PR acceptance contract:
  * merge-table determinism across the flood sweep modes (flat assoc/seq
    + the Pallas path where available) — bit-exact tables, not just
    labels (the flood_merge_table saddle-semantics satellite);
  * global hierarchy vs brute force: re-segmenting at k thresholds
    equals the full-adjacency union-find oracle (label-partition
    equality, RI == 1.0);
  * monotonicity: segment count non-increasing in the threshold;
  * block-face stitching on the serpentine fixture (a region snaking
    across many blocks must merge through face edges);
  * warm sweep: a second re-cut in one process reads NO input chunks and
    uploads NO bytes (the ctt-hbm DeviceBufferCache holds the labels);
  * serve ``resegment`` job e2e byte parity vs an in-process run, plus
    the protocol normalization/validation;
  * disabled/fallback paths: unfused build (CTT_STREAM_FUSION=0) and the
    local target produce byte-identical artifacts and volumes.
"""

import os

import numpy as np
import pytest

from cluster_tools_tpu.obs import metrics as obs_metrics
from cluster_tools_tpu.obs import trace as obs_trace
from cluster_tools_tpu.ops import _backend
from cluster_tools_tpu.ops import hier as hier_ops
from cluster_tools_tpu.ops import watershed as ws_ops
from cluster_tools_tpu.ops.evaluation import rand_scores
from cluster_tools_tpu.ops.segment import contingency_table
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import HierarchyWorkflow, ResegmentWorkflow

BLOCK_SHAPE = [4, 16, 16]
GCONF = {
    "block_shape": BLOCK_SHAPE, "target": "tpu",
    "device_batch_size": 1, "devices": [0], "pipeline_depth": 2,
}
BLOCKS_CONF = {"threshold": 0.5, "sigma_seeds": 1.6, "size_filter": 10}


def _volume(rng, shape=(8, 32, 32)):
    from scipy import ndimage

    raw = ndimage.gaussian_filter(rng.random(shape), (1.0, 2.0, 2.0))
    return (
        (raw - raw.min()) / (raw.max() - raw.min())
    ).astype("float32")


def _build_hierarchy(tmp_path, raw, tag="h", gconf=None, blocks_conf=None):
    path = str(tmp_path / f"{tag}.n5")
    file_reader(path).create_dataset(
        "bnd", data=raw, chunks=tuple(BLOCK_SHAPE)
    )
    config_dir = str(tmp_path / f"configs_{tag}")
    cfg.write_global_config(config_dir, gconf or GCONF)
    cfg.write_config(
        config_dir, "hierarchy_blocks", blocks_conf or BLOCKS_CONF
    )
    wf = HierarchyWorkflow(
        str(tmp_path / f"tmp_{tag}"), config_dir,
        input_path=path, input_key="bnd",
        output_path=path, output_key="seg",
    )
    assert build([wf])
    return path, config_dir


def _resegment(tmp_path, path, config_dir, threshold, tag):
    rs_dir = str(tmp_path / f"configs_rs_{tag}")
    cfg.write_global_config(rs_dir, GCONF)
    cfg.write_config(rs_dir, "resegment", {"threshold": float(threshold)})
    wf = ResegmentWorkflow(
        str(tmp_path / f"tmp_rs_{tag}"), rs_dir,
        labels_path=path, labels_key="seg",
        output_path=path, output_key=f"seg_{tag}",
    )
    assert build([wf])
    return file_reader(path, "r")[f"seg_{tag}"][:]


def _partition_ri(a, b) -> float:
    ids_a, ids_b, counts = contingency_table(
        np.asarray(a, np.uint64), np.asarray(b, np.uint64)
    )
    return rand_scores(ids_a, ids_b, counts)["rand_index"]


@pytest.fixture(scope="module")
def built(tmp_path_factory):
    """ONE hierarchy build shared by the read-only tests (the build is
    the expensive part; re-cuts are cheap)."""
    rng = np.random.default_rng(42)
    tmp_path = tmp_path_factory.mktemp("hier")
    raw = _volume(rng)
    path, config_dir = _build_hierarchy(tmp_path, raw)
    return tmp_path, path, config_dir, raw


# ---------------------------------------------------------------------------
# merge-table semantics across flood paths (the sweep-mode satellite)


class TestMergeTableSweepModes:
    def test_hier_table_bit_exact_across_sweep_modes(self, rng):
        """seeded_watershed_hier under CTT_SWEEP_MODE=assoc vs seq: the
        labels are bit-identical by the flood contract — the merge table
        must be too (it is a pure function of (labels, heights), so any
        drift means the saddle semantics leaked backend state)."""
        from scipy import ndimage

        raw = ndimage.gaussian_filter(
            rng.random((4, 32, 32)), (0.5, 2.0, 2.0)
        ).astype(np.float32)
        seeds = np.zeros(raw.shape, np.int32)
        seeds[0, 4, 4] = 1
        seeds[1, 16, 24] = 2
        seeds[3, 28, 8] = 3
        mask = raw < np.quantile(raw, 0.8)
        outs = {}
        for mode in ("assoc", "seq"):
            with _backend.force_sweep_mode(mode):
                labels, (a, b, s), _ = ws_ops.seeded_watershed_hier(
                    raw, seeds, mask, coarse_tile=(2, 8, 8)
                )
                outs[mode] = (
                    np.asarray(labels), np.asarray(a), np.asarray(b),
                    np.asarray(s),
                )
        for part in range(4):
            np.testing.assert_array_equal(
                outs["assoc"][part], outs["seq"][part],
                err_msg=f"part {part} differs between sweep modes",
            )

    def test_block_merge_table_matches_host_adjacency(self, rng):
        """The device full-adjacency table reduces to exactly the host
        oracle's edge set with identical min saddles."""
        labels = np.zeros((4, 8, 8), np.int32)
        labels[:, :4, :] = 1
        labels[:, 5:, :] = 2
        labels[2:, 4:5, :4] = 3  # a region touching both
        h = rng.random((4, 8, 8)).astype(np.float32)
        a, b, s = hier_ops.block_merge_table(labels, h)
        pairs, saddles = hier_ops.reduce_merge_table(a, b, s)
        # host reference
        ref = {}
        from cluster_tools_tpu.ops.cc import _canonical_offsets

        for off in _canonical_offsets(3, 1, False):
            src = tuple(
                slice(None, -o) if o > 0 else slice(-o, None) for o in off
            )
            dst = tuple(
                slice(o, None) if o > 0 else slice(None, o or None)
                for o in off
            )
            la, lb = labels[src], labels[dst]
            ok = (la > 0) & (lb > 0) & (la != lb)
            sad = np.maximum(h[src], h[dst])
            for pa, pb, ps in zip(la[ok], lb[ok], sad[ok]):
                key = (min(pa, pb), max(pa, pb))
                ref[key] = min(ref.get(key, np.inf), ps)
        got = {tuple(p): s for p, s in zip(pairs, saddles)}
        assert set(got) == set(ref)
        for k in ref:
            assert np.isclose(got[k], ref[k]), k


# ---------------------------------------------------------------------------
# artifact schema


class TestArtifact:
    def test_roundtrip_sorted_and_schema_guard(self, tmp_path):
        pairs = np.array([[3, 5], [1, 2], [2, 7]], np.int64)
        saddles = np.array([0.9, 0.1, 0.5], np.float32)
        p = str(tmp_path / "h.npz")
        hier_ops.save_hierarchy(p, pairs, saddles, 7, (8, 8, 8), (4, 4, 4))
        art = hier_ops.load_hierarchy(p)
        assert (np.diff(art["saddle"]) >= 0).all()
        assert art["a"].tolist() == [1, 2, 3]
        assert int(art["n_labels"]) == 7
        # schema guard: a foreign npz is refused loudly
        bad = str(tmp_path / "bad.npz")
        np.savez(bad, a=pairs[:, 0], b=pairs[:, 1], saddle=saddles)
        with pytest.raises(ValueError, match="schema"):
            hier_ops.load_hierarchy(bad)


# ---------------------------------------------------------------------------
# global hierarchy vs brute force + monotonicity (module-shared build)


class TestHierarchyCorrectness:
    def test_recut_matches_bruteforce_at_k_thresholds(self, built,
                                                      tmp_path):
        _, path, config_dir, raw = built
        f = file_reader(path, "r")
        seg = f["seg"][:].astype(np.int64)
        art = hier_ops.load_hierarchy(
            os.path.join(path, "seg_hierarchy.npz")
        )
        qs = np.quantile(art["saddle"], [0.15, 0.5, 0.85])
        for i, t in enumerate(qs):
            out = _resegment(tmp_path, path, config_dir, t, f"bf{i}")
            oracle = hier_ops.resegment_np(seg, raw, float(t))
            ri = _partition_ri(out, oracle)
            assert ri == 1.0, f"threshold {t}: RI {ri} != 1.0"

    def test_segment_count_monotone_in_threshold(self, built, tmp_path):
        _, path, config_dir, _ = built
        art = hier_ops.load_hierarchy(
            os.path.join(path, "seg_hierarchy.npz")
        )
        ts = np.quantile(art["saddle"], [0.1, 0.35, 0.6, 0.95])
        counts = [
            np.unique(
                _resegment(tmp_path, path, config_dir, t, f"mono{i}")
            ).size
            for i, t in enumerate(ts)
        ]
        assert counts == sorted(counts, reverse=True), counts
        # the top cut must actually merge something
        assert counts[-1] < counts[0]

    def test_table_mode_matches_volume_mode(self, built, tmp_path):
        """``write_volume: false`` persists only the relabel table; the
        client-side application of that table must equal the volume-mode
        gather bit for bit (and no output volume is created)."""
        _, path, config_dir, _ = built
        art = hier_ops.load_hierarchy(
            os.path.join(path, "seg_hierarchy.npz")
        )
        t = float(np.quantile(art["saddle"], 0.5))
        vol_out = _resegment(tmp_path, path, config_dir, t, "tm_vol")
        rs_dir = str(tmp_path / "configs_tm")
        cfg.write_global_config(rs_dir, GCONF)
        cfg.write_config(
            rs_dir, "resegment",
            {"threshold": t, "write_volume": False},
        )
        wf = ResegmentWorkflow(
            str(tmp_path / "tmp_tm"), rs_dir,
            labels_path=path, labels_key="seg",
            output_path=path, output_key="seg_tm",
        )
        assert build([wf])
        assert not os.path.exists(os.path.join(path, "seg_tm")), (
            "table mode must not create an output volume"
        )
        cut = hier_ops.load_cut_table(
            os.path.join(path, "seg_tm_cut.npz")
        )
        assert float(cut["threshold"]) == t
        seg = file_reader(path, "r")["seg"][:]
        applied = hier_ops.apply_cut_np(seg, cut["vals"], cut["roots"])
        np.testing.assert_array_equal(applied.astype(np.uint64), vol_out)

    def test_identity_cut_below_all_saddles(self, built, tmp_path):
        _, path, config_dir, _ = built
        f = file_reader(path, "r")
        out = _resegment(tmp_path, path, config_dir, -1.0, "ident")
        np.testing.assert_array_equal(out, f["seg"][:])


# ---------------------------------------------------------------------------
# block-face stitching (serpentine fixture)


class TestFaceStitching:
    def test_serpentine_region_merges_across_blocks(self, tmp_path):
        """A serpentine low-boundary corridor spanning every block: at a
        threshold above the corridor's values all its watershed fragments
        (which the halo-less block flood split at every block border)
        must merge into ONE segment — pure face-edge stitching."""
        from cluster_tools_tpu.ops.cc import serpentine_mask

        shape = (4, 32, 32)
        corridor = np.asarray(serpentine_mask((32, 32)))
        raw = np.full(shape, 0.9, np.float32)
        raw[:, corridor] = 0.1
        path = str(tmp_path / "serp.n5")
        file_reader(path).create_dataset(
            "bnd", data=raw, chunks=tuple(BLOCK_SHAPE)
        )
        config_dir = str(tmp_path / "configs_serp")
        cfg.write_global_config(config_dir, GCONF)
        cfg.write_config(
            config_dir, "hierarchy_blocks",
            {"threshold": 0.5, "sigma_seeds": 1.0, "size_filter": 0},
        )
        wf = HierarchyWorkflow(
            str(tmp_path / "tmp_serp"), config_dir,
            input_path=path, input_key="bnd",
            output_path=path, output_key="seg",
        )
        assert build([wf])
        f = file_reader(path, "r")
        seg = f["seg"][:]
        # the block flood fragments the corridor across block borders
        assert np.unique(seg[seg > 0]).size > 1
        out = _resegment(tmp_path, path, config_dir, 0.2, "serp")
        assert np.unique(out[out > 0]).size == 1, (
            "face edges must merge the serpentine corridor at t above "
            "its boundary values"
        )
        # and the merged support is exactly the fragmented support
        np.testing.assert_array_equal(out > 0, seg > 0)

    def test_face_saddle_height_decides_the_merge(self, tmp_path):
        """Two flat regions in z-adjacent blocks touching only through
        the block face: the face saddle is the max of the two touching
        planes — merged strictly above it, separate strictly below.
        3d flood mode, so each block is ONE region and the only
        hierarchy edge is the face edge."""
        shape = (8, 16, 16)
        raw = np.full(shape, 0.30, np.float32)
        raw[3, :, :] = 0.40   # lower block's face plane
        raw[4, :, :] = 0.45   # upper block's face plane -> saddle 0.45
        path = str(tmp_path / "face.n5")
        file_reader(path).create_dataset(
            "bnd", data=raw, chunks=(4, 16, 16)
        )
        config_dir = str(tmp_path / "configs_face")
        cfg.write_global_config(config_dir, GCONF)
        cfg.write_config(
            config_dir, "hierarchy_blocks",
            {"threshold": 0.5, "sigma_seeds": 1.0, "size_filter": 0,
             "apply_dt_2d": False, "apply_ws_2d": False},
        )
        wf = HierarchyWorkflow(
            str(tmp_path / "tmp_face"), config_dir,
            input_path=path, input_key="bnd",
            output_path=path, output_key="seg",
        )
        assert build([wf])
        art = hier_ops.load_hierarchy(
            os.path.join(path, "seg_hierarchy.npz")
        )
        assert art["saddle"].size >= 1
        below = _resegment(tmp_path, path, config_dir, 0.44, "below")
        above = _resegment(tmp_path, path, config_dir, 0.46, "above")
        assert np.unique(below[below > 0]).size == 2
        assert np.unique(above[above > 0]).size == 1


# ---------------------------------------------------------------------------
# warm sweep: zero input reads, zero upload bytes


class TestWarmSweep:
    def test_second_cut_zero_reads_zero_uploads(self, tmp_path, rng):
        from cluster_tools_tpu.runtime import hbm

        obs_metrics.reset()
        obs_trace.enable(str(tmp_path / "trace"), "hier_warm",
                         export_env=False)
        try:
            raw = _volume(rng)
            path, config_dir = _build_hierarchy(tmp_path, raw, tag="warm")
            hbm.set_cache_budget(256 * 1024 * 1024)
            art = hier_ops.load_hierarchy(
                os.path.join(path, "seg_hierarchy.npz")
            )
            t_lo, t_hi = np.quantile(art["saddle"], [0.3, 0.7])

            def counters():
                return dict(obs_metrics.snapshot()["counters"])

            def one_cut(t, tag):
                # no output readback inside the measured window — the
                # verification reads happen after c2
                rs_dir = str(tmp_path / f"configs_rs_{tag}")
                cfg.write_global_config(rs_dir, GCONF)
                cfg.write_config(
                    rs_dir, "resegment", {"threshold": float(t)}
                )
                wf = ResegmentWorkflow(
                    str(tmp_path / f"tmp_rs_{tag}"), rs_dir,
                    labels_path=path, labels_key="seg",
                    output_path=path, output_key=f"seg_{tag}",
                )
                assert build([wf])

            one_cut(t_lo, "w0")
            c1 = counters()
            one_cut(t_hi, "w1")
            c2 = counters()
            out = file_reader(path, "r")["seg_w1"][:]

            def delta(name):
                return c2.get(name, 0) - c1.get(name, 0)

            assert delta("device.upload_bytes") == 0, (
                "warm sweep must not re-upload the labels volume"
            )
            assert delta("device.uploads_skipped") > 0
            assert delta("store.chunks_read") == 0, (
                "warm sweep must not re-read input chunks"
            )
            # and it still computed the right thing
            seg = file_reader(path, "r")["seg"][:].astype(np.int64)
            ri = _partition_ri(
                out, hier_ops.resegment_np(seg, raw, float(t_hi))
            )
            assert ri == 1.0
        finally:
            obs_trace.disable()
            obs_metrics.reset()


# ---------------------------------------------------------------------------
# serve `resegment` job type


class TestServeResegment:
    def test_protocol_normalization_and_validation(self):
        from cluster_tools_tpu.serve import protocol

        rec = protocol.validate_submission({
            "type": "resegment",
            "hierarchy": "/x/seg_hierarchy.npz",
            "labels_path": "/x/d.n5", "labels_key": "seg",
            "output_path": "/x/d.n5", "output_key": "seg_t",
            "threshold": 0.25,
            "tmp_folder": "/x/tmp", "config_dir": "/x/cfg",
            "configs": {"global": {"block_shape": [4, 8, 8]}},
        })
        assert rec["type"] == "resegment"
        assert rec["workflow"] == protocol.RESEGMENT_TASK
        assert rec["kwargs"]["hierarchy_path"] == "/x/seg_hierarchy.npz"
        assert rec["kwargs"]["input_key"] == "seg"
        assert rec["configs"]["resegment"]["threshold"] == 0.25
        # the sweep signature ignores the threshold: every step after the
        # first is a warm job
        rec2 = protocol.validate_submission({
            "type": "resegment",
            "hierarchy": "/x/seg_hierarchy.npz",
            "labels_path": "/x/d.n5", "labels_key": "seg",
            "output_path": "/x/d.n5", "output_key": "seg_t2",
            "threshold": 0.75,
            "tmp_folder": "/x/tmp2", "config_dir": "/x/cfg2",
            "configs": {"global": {"block_shape": [4, 8, 8]}},
        })
        assert protocol.job_signature(rec) == protocol.job_signature(rec2)
        # validation is loud
        with pytest.raises(protocol.ProtocolError, match="threshold"):
            protocol.validate_submission({
                "type": "resegment", "hierarchy": "h",
                "labels_path": "p", "labels_key": "k",
                "output_path": "p", "output_key": "o",
                "tmp_folder": "t", "config_dir": "c",
            })
        with pytest.raises(protocol.ProtocolError, match="hierarchy"):
            protocol.validate_submission({
                "type": "resegment", "threshold": 0.5,
                "labels_path": "p", "labels_key": "k",
                "output_path": "p", "output_key": "o",
                "tmp_folder": "t", "config_dir": "c",
            })
        with pytest.raises(protocol.ProtocolError, match="job type"):
            protocol.validate_submission({"type": "sweep", "workflow": "X"})

    def test_serve_resegment_e2e_byte_parity(self, tmp_path, rng):
        from cluster_tools_tpu.runtime.workflow import ExecutionContext
        from cluster_tools_tpu.serve import ServeClient, ServeDaemon

        was_on = obs_trace.enabled()
        if not was_on:
            obs_trace.enable(str(tmp_path / "trace"), "hier_serve",
                             export_env=False)
        prev_ctx = ExecutionContext._PROCESS
        raw = _volume(rng)
        path, config_dir = _build_hierarchy(tmp_path, raw, tag="srv")
        art = os.path.join(path, "seg_hierarchy.npz")
        t = float(np.quantile(
            hier_ops.load_hierarchy(art)["saddle"], 0.5
        ))
        local = _resegment(tmp_path, path, config_dir, t, "srv_local")
        d = ServeDaemon(str(tmp_path / "state"),
                        config={"concurrency": 1})
        d.start()
        try:
            client = ServeClient(state_dir=str(tmp_path / "state"))
            c0 = dict(obs_metrics.snapshot()["counters"])
            job = client.resegment(
                hierarchy=art, labels_path=path, labels_key="seg",
                output_path=path, output_key="seg_srv",
                threshold=t,
                tmp_folder=str(tmp_path / "tmp_srv_job"),
                config_dir=str(tmp_path / "configs_srv_job"),
                configs={"global": dict(GCONF)},
            )
            state = client.wait(job, timeout_s=300)
            assert state["result"]["ok"], state
            c1 = dict(obs_metrics.snapshot()["counters"])
            assert c1.get("hier.resegment_jobs", 0) > c0.get(
                "hier.resegment_jobs", 0
            )
        finally:
            d.request_drain()
            if d._httpd is not None:
                d._httpd.shutdown()
                d._httpd.server_close()
            for th in d._threads:
                if th.name.startswith("ctt-serve-exec"):
                    th.join(timeout=30)
            ExecutionContext._PROCESS = prev_ctx
            if not was_on:
                obs_trace.disable()
            obs_metrics.reset()
        f = file_reader(path, "r")
        np.testing.assert_array_equal(f["seg_srv"][:], local)


# ---------------------------------------------------------------------------
# disabled / fallback paths


class TestFallbacks:
    def test_unfused_build_byte_identical(self, tmp_path, rng,
                                          monkeypatch):
        raw = _volume(rng)
        path_f, _ = _build_hierarchy(tmp_path, raw, tag="fused")
        monkeypatch.setenv("CTT_STREAM_FUSION", "0")
        path_u, _ = _build_hierarchy(tmp_path, raw, tag="unfused")
        fa = file_reader(path_f, "r")
        fb = file_reader(path_u, "r")
        np.testing.assert_array_equal(fa["seg"][:], fb["seg"][:])
        aa = hier_ops.load_hierarchy(
            os.path.join(path_f, "seg_hierarchy.npz")
        )
        ab = hier_ops.load_hierarchy(
            os.path.join(path_u, "seg_hierarchy.npz")
        )
        for k in ("a", "b", "saddle", "n_labels"):
            np.testing.assert_array_equal(aa[k], ab[k], err_msg=k)

    def test_local_target_recut_parity(self, built, tmp_path):
        _, path, config_dir, _ = built
        art = hier_ops.load_hierarchy(
            os.path.join(path, "seg_hierarchy.npz")
        )
        t = float(np.quantile(art["saddle"], 0.5))
        tpu_out = _resegment(tmp_path, path, config_dir, t, "fb_tpu")
        loc_dir = str(tmp_path / "configs_fb_local")
        cfg.write_global_config(
            loc_dir, {"block_shape": BLOCK_SHAPE, "target": "local",
                      "max_jobs": 1}
        )
        cfg.write_config(loc_dir, "resegment", {"threshold": t})
        wf = ResegmentWorkflow(
            str(tmp_path / "tmp_fb_local"), loc_dir,
            labels_path=path, labels_key="seg",
            output_path=path, output_key="seg_fb_local",
        )
        assert build([wf])
        np.testing.assert_array_equal(
            file_reader(path, "r")["seg_fb_local"][:], tpu_out
        )

    def test_host_relabel_fallback_parity(self, built, tmp_path,
                                          monkeypatch):
        """Hierarchies past 2^31 regions downgrade LOUDLY to the host
        int64 relabel path (the int32 device gather would wrap) — faked
        small here via the class-level limit.  The device cut builder is
        stubbed to explode so the test proves the host path really ran,
        and the output must be byte-identical to the device re-cut."""
        from cluster_tools_tpu.tasks.hier import ResegmentTask

        _, path, config_dir, _ = built
        art = hier_ops.load_hierarchy(
            os.path.join(path, "seg_hierarchy.npz")
        )
        t = float(np.quantile(art["saddle"], 0.5))
        ref = _resegment(tmp_path, path, config_dir, t, "hrl_dev")
        monkeypatch.setattr(ResegmentTask, "INT32_LIMIT", 1)

        def _no_device_cut(*a, **kw):
            raise AssertionError("device cut_table ran in host mode")

        monkeypatch.setattr(hier_ops, "cut_table", _no_device_cut)
        with pytest.warns(RuntimeWarning, match="HOST relabel"):
            out = _resegment(tmp_path, path, config_dir, t, "hrl_host")
        np.testing.assert_array_equal(out, ref)
