"""Downscaling family: resample ops, pyramid workflow, metadata, upscaling,
scale_to_boundaries."""

import os

import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu.ops import resample
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


class TestResampleOps:
    def test_downscale_shape(self):
        assert resample.downscale_shape((33, 64, 65), 2) == (17, 32, 33)
        assert resample.downscale_shape((10, 64, 64), [1, 2, 2]) == (10, 32, 32)

    def test_mean_pool_matches_reshape(self, rng):
        x = rng.random((16, 16, 16)).astype("float32")
        got = np.asarray(resample.downscale(x, 2, "mean"))
        want = x.reshape(8, 2, 8, 2, 8, 2).mean(axis=(1, 3, 5))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_nearest_is_strided(self, rng):
        labels = rng.integers(0, 100, (16, 16, 16)).astype("uint64")
        got = np.asarray(resample.downscale(labels, [1, 2, 2], "nearest"))
        np.testing.assert_array_equal(got, labels[:, ::2, ::2])

    def test_upscale_nearest_roundtrip(self, rng):
        labels = rng.integers(0, 50, (8, 8, 8)).astype("int32")
        up = np.asarray(resample.upscale(labels, (16, 16, 16), "nearest"))
        np.testing.assert_array_equal(up[::2, ::2, ::2], labels)

    def test_interpolate_constant_preserved(self):
        x = np.full((16, 16, 16), 0.7, dtype="float32")
        got = np.asarray(resample.downscale(x, 2, "interpolate"))
        np.testing.assert_allclose(got, 0.7, rtol=1e-5)


class TestDownscalingWorkflow:
    def test_paintera_pyramid(self, tmp_path, rng):
        from cluster_tools_tpu.workflows.downscaling import DownscalingWorkflow

        path = str(tmp_path / "d.n5")
        raw = ndimage.gaussian_filter(
            rng.random((32, 64, 64)), 1.0
        ).astype("float32")
        file_reader(path).create_dataset("raw", data=raw, chunks=(16, 32, 32))

        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [16, 32, 32]})

        wf = DownscalingWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="raw",
            scale_factors=[[1, 2, 2], 2],
            metadata_format="paintera",
            metadata_dict={"resolution": [40.0, 4.0, 4.0]},
            output_key_prefix="pyramid",
        )
        assert build([wf])

        f = file_reader(path, "r")
        s0 = f["pyramid/s0"]
        s1 = f["pyramid/s1"]
        s2 = f["pyramid/s2"]
        assert s0.shape == (32, 64, 64)
        assert s1.shape == (32, 32, 32)
        assert s2.shape == (16, 16, 16)
        # metadata: java-reversed cumulative factors
        assert s1.attrs["downsamplingFactors"] == [2, 2, 1]
        assert s2.attrs["downsamplingFactors"] == [4, 4, 2]
        g = f["pyramid"]
        assert g.attrs["multiScale"] is True
        assert g.attrs["resolution"] == [4.0, 4.0, 40.0]
        # content: s1 approximates the full-volume resize
        want = np.asarray(
            resample.downscale(raw, [1, 2, 2], "interpolate")
        )
        np.testing.assert_allclose(s1[:], want, atol=2e-2)

    def test_bdv_n5_metadata(self, tmp_path, rng):
        from cluster_tools_tpu.workflows.downscaling import DownscalingWorkflow

        path = str(tmp_path / "bdv.n5")
        raw = rng.random((16, 32, 32)).astype("float32")
        src = str(tmp_path / "src.n5")
        file_reader(src).create_dataset("raw", data=raw, chunks=(8, 16, 16))

        config_dir = str(tmp_path / "configs_bdv")
        tmp_folder = str(tmp_path / "tmp_bdv")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})

        wf = DownscalingWorkflow(
            tmp_folder, config_dir,
            input_path=src, input_key="raw",
            scale_factors=[2],
            metadata_format="bdv.n5",
            output_path=path,
        )
        assert build([wf])
        f = file_reader(path, "r")
        assert f["setup0/timepoint0/s0"].shape == (16, 32, 32)
        assert f["setup0/timepoint0/s1"].shape == (8, 16, 16)
        assert f["setup0"].attrs["downsamplingFactors"] == [[1, 1, 1], [2, 2, 2]]
        xml = os.path.splitext(path)[0] + ".xml"
        assert os.path.exists(xml)
        content = open(xml).read()
        assert "bdv.n5" in content and "32 32 16" in content


class TestBigLabels:
    def test_uint64_labels_survive_pyramid(self, tmp_path, rng):
        # regression: ids >= 2**32 (e.g. paintera's ignore label) must not be
        # truncated — nearest resampling stays on host (no x64 on device)
        from cluster_tools_tpu.tasks.downscaling import (
            DownscalingTask,
            UpscalingTask,
        )

        big = np.uint64(18446744073709550592)
        labels = rng.integers(0, 100, (16, 16, 16)).astype("uint64")
        labels[labels == 0] = big
        path = str(tmp_path / "big.n5")
        file_reader(path).create_dataset("seg", data=labels, chunks=(8, 8, 8))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 8, 8]})
        down = DownscalingTask(
            tmp_folder, config_dir,
            input_path=path, input_key="seg",
            output_path=path, output_key="s1",
            scale_factor=2,
        )
        assert build([down])
        s1 = file_reader(path, "r")["s1"][:]
        np.testing.assert_array_equal(s1, labels[::2, ::2, ::2])
        up = UpscalingTask(
            tmp_folder, config_dir,
            input_path=path, input_key="s1",
            output_path=path, output_key="up",
            scale_factor=2,
        )
        assert build([up])
        upv = file_reader(path, "r")["up"][:]
        assert big in np.unique(upv)


class TestUpscaling:
    def test_upscale_labels(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.downscaling import UpscalingTask

        path = str(tmp_path / "u.n5")
        labels = rng.integers(0, 9, (8, 16, 16)).astype("uint32")
        file_reader(path).create_dataset("seg", data=labels, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        cfg.write_config(
            config_dir, "upscaling", {"library_kwargs": {"order": 0}}
        )
        task = UpscalingTask(
            tmp_folder, config_dir,
            input_path=path, input_key="seg",
            output_path=path, output_key="up",
            scale_factor=2,
        )
        assert build([task])
        up = file_reader(path, "r")["up"][:]
        assert up.shape == (16, 32, 32)
        np.testing.assert_array_equal(up[::2, ::2, ::2], labels)
        # nearest upsampling only repeats values
        assert set(np.unique(up)) <= set(np.unique(labels))


class TestScaleToBoundaries:
    def test_objects_refit(self, tmp_path):
        from cluster_tools_tpu.tasks.downscaling import ScaleToBoundariesTask

        shape = (16, 32, 32)
        # two slabs split at x=16 with a boundary ridge
        gt = np.zeros(shape, dtype="uint64")
        gt[:, :, :16] = 1
        gt[:, :, 16:] = 2
        xx = np.mgrid[: shape[0], : shape[1], : shape[2]][2]
        bnd = np.exp(-((xx - 15.5) ** 2) / 4.0).astype("float32")
        # coarse objects at half resolution, slightly misaligned
        coarse = gt[::2, ::2, ::2].copy()

        path = str(tmp_path / "s.n5")
        f = file_reader(path)
        f.create_dataset("objs", data=coarse, chunks=(8, 16, 16))
        f.create_dataset("bnd", data=bnd, chunks=(8, 16, 16))

        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [16, 32, 32]})
        cfg.write_config(
            config_dir, "scale_to_boundaries", {"erode_by": 3}
        )
        task = ScaleToBoundariesTask(
            tmp_folder, config_dir,
            input_path=path, input_key="objs",
            boundaries_path=path, boundaries_key="bnd",
            output_path=path, output_key="fitted",
        )
        assert build([task])
        fitted = file_reader(path, "r")["fitted"][:]
        assert fitted.shape == shape
        # object ids survive and dominate their ground-truth side
        for obj in (1, 2):
            sel = gt == obj
            frac = (fitted[sel] == obj).mean()
            assert frac > 0.8, f"object {obj}: {frac}"
