"""Downscaling family: resample ops, pyramid workflow, metadata, upscaling,
scale_to_boundaries."""

import os

import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu.ops import resample
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


class TestResampleOps:
    def test_downscale_shape(self):
        assert resample.downscale_shape((33, 64, 65), 2) == (17, 32, 33)
        assert resample.downscale_shape((10, 64, 64), [1, 2, 2]) == (10, 32, 32)

    def test_mean_pool_matches_reshape(self, rng):
        x = rng.random((16, 16, 16)).astype("float32")
        got = np.asarray(resample.downscale(x, 2, "mean"))
        want = x.reshape(8, 2, 8, 2, 8, 2).mean(axis=(1, 3, 5))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_nearest_is_strided(self, rng):
        labels = rng.integers(0, 100, (16, 16, 16)).astype("uint64")
        got = np.asarray(resample.downscale(labels, [1, 2, 2], "nearest"))
        np.testing.assert_array_equal(got, labels[:, ::2, ::2])

    def test_upscale_nearest_roundtrip(self, rng):
        labels = rng.integers(0, 50, (8, 8, 8)).astype("int32")
        up = np.asarray(resample.upscale(labels, (16, 16, 16), "nearest"))
        np.testing.assert_array_equal(up[::2, ::2, ::2], labels)

    def test_interpolate_constant_preserved(self):
        x = np.full((16, 16, 16), 0.7, dtype="float32")
        got = np.asarray(resample.downscale(x, 2, "interpolate"))
        np.testing.assert_allclose(got, 0.7, rtol=1e-5)


class TestDownscalingWorkflow:
    def test_paintera_pyramid(self, tmp_path, rng):
        from cluster_tools_tpu.workflows.downscaling import DownscalingWorkflow

        path = str(tmp_path / "d.n5")
        raw = ndimage.gaussian_filter(
            rng.random((32, 64, 64)), 1.0
        ).astype("float32")
        file_reader(path).create_dataset("raw", data=raw, chunks=(16, 32, 32))

        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [16, 32, 32]})

        wf = DownscalingWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="raw",
            scale_factors=[[1, 2, 2], 2],
            metadata_format="paintera",
            metadata_dict={"resolution": [40.0, 4.0, 4.0]},
            output_key_prefix="pyramid",
        )
        assert build([wf])

        f = file_reader(path, "r")
        s0 = f["pyramid/s0"]
        s1 = f["pyramid/s1"]
        s2 = f["pyramid/s2"]
        assert s0.shape == (32, 64, 64)
        assert s1.shape == (32, 32, 32)
        assert s2.shape == (16, 16, 16)
        # metadata: java-reversed cumulative factors
        assert s1.attrs["downsamplingFactors"] == [2, 2, 1]
        assert s2.attrs["downsamplingFactors"] == [4, 4, 2]
        g = f["pyramid"]
        assert g.attrs["multiScale"] is True
        assert g.attrs["resolution"] == [4.0, 4.0, 40.0]
        # content: s1 approximates the full-volume resize
        want = np.asarray(
            resample.downscale(raw, [1, 2, 2], "interpolate")
        )
        np.testing.assert_allclose(s1[:], want, atol=2e-2)

    def test_bdv_n5_metadata(self, tmp_path, rng):
        from cluster_tools_tpu.workflows.downscaling import DownscalingWorkflow

        path = str(tmp_path / "bdv.n5")
        raw = rng.random((16, 32, 32)).astype("float32")
        src = str(tmp_path / "src.n5")
        file_reader(src).create_dataset("raw", data=raw, chunks=(8, 16, 16))

        config_dir = str(tmp_path / "configs_bdv")
        tmp_folder = str(tmp_path / "tmp_bdv")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})

        wf = DownscalingWorkflow(
            tmp_folder, config_dir,
            input_path=src, input_key="raw",
            scale_factors=[2],
            metadata_format="bdv.n5",
            output_path=path,
        )
        assert build([wf])
        f = file_reader(path, "r")
        assert f["setup0/timepoint0/s0"].shape == (16, 32, 32)
        assert f["setup0/timepoint0/s1"].shape == (8, 16, 16)
        assert f["setup0"].attrs["downsamplingFactors"] == [[1, 1, 1], [2, 2, 2]]
        xml = os.path.splitext(path)[0] + ".xml"
        assert os.path.exists(xml)
        content = open(xml).read()
        assert "bdv.n5" in content and "32 32 16" in content


class TestBigLabels:
    def test_uint64_labels_survive_pyramid(self, tmp_path, rng):
        # regression: ids >= 2**32 (e.g. paintera's ignore label) must not be
        # truncated — nearest resampling stays on host (no x64 on device)
        from cluster_tools_tpu.tasks.downscaling import (
            DownscalingTask,
            UpscalingTask,
        )

        big = np.uint64(18446744073709550592)
        labels = rng.integers(0, 100, (16, 16, 16)).astype("uint64")
        labels[labels == 0] = big
        path = str(tmp_path / "big.n5")
        file_reader(path).create_dataset("seg", data=labels, chunks=(8, 8, 8))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 8, 8]})
        down = DownscalingTask(
            tmp_folder, config_dir,
            input_path=path, input_key="seg",
            output_path=path, output_key="s1",
            scale_factor=2,
        )
        assert build([down])
        s1 = file_reader(path, "r")["s1"][:]
        np.testing.assert_array_equal(s1, labels[::2, ::2, ::2])
        up = UpscalingTask(
            tmp_folder, config_dir,
            input_path=path, input_key="s1",
            output_path=path, output_key="up",
            scale_factor=2,
        )
        assert build([up])
        upv = file_reader(path, "r")["up"][:]
        assert big in np.unique(upv)


class TestUpscaling:
    def test_upscale_labels(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.downscaling import UpscalingTask

        path = str(tmp_path / "u.n5")
        labels = rng.integers(0, 9, (8, 16, 16)).astype("uint32")
        file_reader(path).create_dataset("seg", data=labels, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        cfg.write_config(
            config_dir, "upscaling", {"library_kwargs": {"order": 0}}
        )
        task = UpscalingTask(
            tmp_folder, config_dir,
            input_path=path, input_key="seg",
            output_path=path, output_key="up",
            scale_factor=2,
        )
        assert build([task])
        up = file_reader(path, "r")["up"][:]
        assert up.shape == (16, 32, 32)
        np.testing.assert_array_equal(up[::2, ::2, ::2], labels)
        # nearest upsampling only repeats values
        assert set(np.unique(up)) <= set(np.unique(labels))


class TestScaleToBoundaries:
    def test_objects_refit(self, tmp_path):
        from cluster_tools_tpu.tasks.downscaling import ScaleToBoundariesTask

        shape = (16, 32, 32)
        # two slabs split at x=16 with a boundary ridge
        gt = np.zeros(shape, dtype="uint64")
        gt[:, :, :16] = 1
        gt[:, :, 16:] = 2
        xx = np.mgrid[: shape[0], : shape[1], : shape[2]][2]
        bnd = np.exp(-((xx - 15.5) ** 2) / 4.0).astype("float32")
        # coarse objects at half resolution, slightly misaligned
        coarse = gt[::2, ::2, ::2].copy()

        path = str(tmp_path / "s.n5")
        f = file_reader(path)
        f.create_dataset("objs", data=coarse, chunks=(8, 16, 16))
        f.create_dataset("bnd", data=bnd, chunks=(8, 16, 16))

        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [16, 32, 32]})
        cfg.write_config(
            config_dir, "scale_to_boundaries", {"erode_by": 3}
        )
        task = ScaleToBoundariesTask(
            tmp_folder, config_dir,
            input_path=path, input_key="objs",
            boundaries_path=path, boundaries_key="bnd",
            output_path=path, output_key="fitted",
        )
        assert build([task])
        fitted = file_reader(path, "r")["fitted"][:]
        assert fitted.shape == shape
        # object ids survive and dominate their ground-truth side
        for obj in (1, 2):
            sel = gt == obj
            frac = (fitted[sel] == obj).mean()
            assert frac > 0.8, f"object {obj}: {frac}"


class TestBdvH5AndPainteraToBdv:
    """VERDICT r3 item 5: bdv.hdf5 metadata variant + PainteraToBdvWorkflow
    (reference downscaling_workflow.py:42-88, :272-330)."""

    def _paintera_pyramid(self, tmp_path, rng, name="p2b"):
        from cluster_tools_tpu.workflows.downscaling import DownscalingWorkflow

        path = str(tmp_path / f"{name}.n5")
        raw = (rng.random((16, 32, 32)) * 255).astype("uint8")
        file_reader(path).create_dataset("raw", data=raw, chunks=(8, 16, 16))
        config_dir = str(tmp_path / f"configs_{name}")
        tmp_folder = str(tmp_path / f"tmp_{name}")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        wf = DownscalingWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="raw",
            scale_factors=[2, 2],
            metadata_format="paintera",
            output_key_prefix="paintera",
            metadata_dict={"resolution": [40.0, 4.0, 4.0]},
        )
        assert build([wf])
        return path, raw, config_dir, tmp_folder

    def test_direct_bdv_h5_pyramid(self, tmp_path, rng):
        """DownscalingWorkflow with metadata_format='bdv.hdf5' writes the
        classic layout: t00000/s00/<scale>/cells + s00/resolutions +
        s00/subdivisions (xyz order) + XML sidecar."""
        pytest.importorskip("h5py")
        from cluster_tools_tpu.workflows.downscaling import DownscalingWorkflow

        src = str(tmp_path / "src.n5")
        raw = (rng.random((16, 32, 32)) * 255).astype("uint8")
        file_reader(src).create_dataset("raw", data=raw, chunks=(8, 16, 16))
        out = str(tmp_path / "direct.h5")
        config_dir = str(tmp_path / "configs_direct")
        tmp_folder = str(tmp_path / "tmp_direct")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        wf = DownscalingWorkflow(
            tmp_folder, config_dir,
            input_path=src, input_key="raw",
            scale_factors=[2, [1, 2, 2]],
            metadata_format="bdv.hdf5",
            output_path=out,
        )
        assert build([wf])
        f = file_reader(out, "r")
        s0 = f["t00000/s00/0/cells"][:]
        np.testing.assert_array_equal(s0, raw)
        assert f["t00000/s00/1/cells"].shape == (8, 16, 16)
        assert f["t00000/s00/2/cells"].shape == (8, 8, 8)
        res = f["s00/resolutions"][:]
        np.testing.assert_allclose(
            res, [[1, 1, 1], [2, 2, 2], [4, 4, 2]]  # xyz (reversed zyx)
        )
        subs = f["s00/subdivisions"][:]
        assert subs.shape == (3, 3) and subs.dtype == np.int32
        xml = open(os.path.splitext(out)[0] + ".xml").read()
        assert 'format="bdv.hdf5"' in xml and "direct.h5" in xml

        # extend the pyramid from scale_offset=2: existing rows are kept and
        # the new level accumulates on the last existing factor row
        wf2 = DownscalingWorkflow(
            str(tmp_path / "tmp_direct2"), config_dir,
            input_path=src, input_key="raw",
            scale_factors=[2],
            metadata_format="bdv.hdf5",
            output_path=out,
            scale_offset=2,
        )
        assert build([wf2])
        res2 = file_reader(out, "r")["s00/resolutions"][:]
        np.testing.assert_allclose(
            res2, [[1, 1, 1], [2, 2, 2], [4, 4, 2], [8, 8, 4]]
        )
        assert file_reader(out, "r")["t00000/s00/3/cells"].shape == (4, 4, 4)

    def test_format_extension_validation(self, tmp_path):
        from cluster_tools_tpu.workflows.downscaling import DownscalingWorkflow

        with pytest.raises(ValueError, match="needs an .h5"):
            DownscalingWorkflow(
                str(tmp_path / "t"), str(tmp_path / "c"),
                input_path="x.n5", input_key="raw",
                scale_factors=[2], metadata_format="bdv.hdf5",
            )
        with pytest.raises(ValueError, match="n5/zarr"):
            DownscalingWorkflow(
                str(tmp_path / "t"), str(tmp_path / "c"),
                input_path="x.h5", input_key="raw",
                scale_factors=[2], metadata_format="bdv.n5",
            )

    def test_paintera_to_bdv_h5_roundtrip(self, tmp_path, rng):
        pytest.importorskip("h5py")
        from cluster_tools_tpu.workflows.downscaling import PainteraToBdvWorkflow

        path, raw, config_dir, _ = self._paintera_pyramid(tmp_path, rng)
        out = str(tmp_path / "conv.h5")
        wf = PainteraToBdvWorkflow(
            str(tmp_path / "tmp_conv"), config_dir,
            input_path=path, input_key_prefix="paintera",
            output_path=out,
        )
        assert build([wf])
        fin = file_reader(path, "r")
        f = file_reader(out, "r")
        for scale in (0, 1, 2):
            a = fin[f"paintera/s{scale}"][:]
            b = f[f"t00000/s00/{scale}/cells"][:]
            np.testing.assert_array_equal(a, b)
        res = f["s00/resolutions"][:]
        np.testing.assert_allclose(res, [[1, 1, 1], [2, 2, 2], [4, 4, 4]])
        xml = open(os.path.splitext(out)[0] + ".xml").read()
        assert 'format="bdv.hdf5"' in xml
        # resolution inherited from the paintera group attrs (xyz → zyx →
        # xyz again on the way out)
        assert "<size>4.0 4.0 40.0</size>" in xml

    def test_paintera_to_bdv_n5_roundtrip(self, tmp_path, rng):
        from cluster_tools_tpu.workflows.downscaling import PainteraToBdvWorkflow

        path, raw, config_dir, _ = self._paintera_pyramid(
            tmp_path, rng, name="p2bn5"
        )
        out = str(tmp_path / "conv.n5")
        wf = PainteraToBdvWorkflow(
            str(tmp_path / "tmp_convn5"), config_dir,
            input_path=path, input_key_prefix="paintera",
            output_path=out,
        )
        assert build([wf])
        fin = file_reader(path, "r")
        f = file_reader(out, "r")
        for scale in (0, 1, 2):
            a = fin[f"paintera/s{scale}"][:]
            b = f[f"setup0/timepoint0/s{scale}"][:]
            np.testing.assert_array_equal(a, b)
        factors = f["setup0"].attrs["downsamplingFactors"]
        np.testing.assert_allclose(
            factors, [[1, 1, 1], [2, 2, 2], [4, 4, 4]]
        )
        xml = open(os.path.splitext(out)[0] + ".xml").read()
        assert 'format="bdv.n5"' in xml
