"""ctt-cloud: object-store backend + async prefetch read stage.

Covers the StoreBackend seam end to end against the local stub object
server (tests/objstub.py): container/dataset roundtrips over HTTP with
byte parity to POSIX, the remote-signature decoded-chunk LRU (warm/cold
accounting, ETag-change invalidation), CorruptChunk classification of
truncated responses, request-level retry under injected 5xx chaos, the
executor's async-prefetch lookahead stage, and the watershed e2e
byte-identity acceptance gate.
"""

import hashlib
import json
import os

import numpy as np
import pytest
from objstub import StubObjectStore

from cluster_tools_tpu.utils import store
from cluster_tools_tpu.utils.store import CorruptChunk, file_reader


@pytest.fixture
def stub(tmp_path):
    with StubObjectStore(str(tmp_path / "objroot")) as srv:
        yield srv


@pytest.fixture
def traced_metrics(tmp_path):
    """Counters are live only while tracing is enabled (the one ctt-obs
    switch); flip it on for tests asserting store.remote_* movement."""
    from cluster_tools_tpu.obs import metrics as obs_metrics
    from cluster_tools_tpu.obs import trace as obs_trace

    was_on = obs_trace.enabled()
    if not was_on:
        obs_trace.enable(str(tmp_path / "trace"), "cloud_unit",
                         export_env=False)
    try:
        yield obs_metrics
    finally:
        if not was_on:
            obs_trace.disable()


def _digest_tree(root):
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def _fresh_cache():
    """Clear the process-global decoded-chunk LRU between scenarios."""
    store.set_chunk_cache_budget(None)


# --------------------------------------------------------------------------
# backend roundtrips


class TestRemoteRoundtrip:
    @pytest.mark.parametrize("ext,compression", [
        ("zarr", "default"), ("n5", "gzip"), ("zarr", None),
    ])
    def test_byte_parity_with_posix(self, tmp_path, stub, rng, ext,
                                    compression):
        """The same create/write through the HTTP backend produces the
        SAME chunk files (digests included) as the POSIX backend — the
        stub serves a real directory, so the comparison is exact."""
        _fresh_cache()
        data = rng.random((20, 33, 17)).astype("float32")
        local = str(tmp_path / f"local.{ext}")
        file_reader(local).create_dataset(
            "x", data=data, chunks=(8, 16, 8), compression=compression
        )
        remote_url = f"{stub.url}/remote.{ext}"
        file_reader(remote_url).create_dataset(
            "x", data=data, chunks=(8, 16, 8), compression=compression
        )
        assert _digest_tree(os.path.join(local, "x")) == _digest_tree(
            os.path.join(stub.root, f"remote.{ext}", "x")
        )
        back = file_reader(remote_url, "r")["x"][:]
        assert np.array_equal(back, data)
        # region RMW write through the remote path
        f = file_reader(remote_url)
        f["x"][2:10, 5:20, 3:9] = 7.0
        assert np.all(
            file_reader(remote_url, "r")["x"][2:10, 5:20, 3:9] == 7.0
        )

    def test_group_navigation_attrs_and_keys(self, tmp_path, stub, rng):
        _fresh_cache()
        url = f"{stub.url}/vol.zarr"
        f = file_reader(url)
        grp = f.require_group("seg")
        ds = grp.create_dataset(
            "labels", data=rng.integers(0, 9, (8, 8, 8), dtype="uint32"),
            chunks=(4, 4, 4),
        )
        ds.attrs["maxId"] = 8
        f2 = file_reader(url, "r")
        assert "seg" in f2
        assert f2["seg"].keys() == ["labels"]
        assert f2["seg"]["labels"].attrs["maxId"] == 8
        with pytest.raises(KeyError):
            f2["missing"]
        with pytest.raises(FileNotFoundError):
            file_reader(f"{stub.url}/absent.zarr", "r")

    def test_varlen_chunks_remote(self, tmp_path, stub):
        _fresh_cache()
        url = f"{stub.url}/scratch.n5"
        ds = file_reader(url).create_dataset(
            "edges", shape=(64,), dtype="uint64", chunks=(16,),
            compression="gzip",
        )
        payload = np.arange(37, dtype="uint64")
        ds.write_chunk_varlen((1,), payload)
        back = file_reader(url, "r")["edges"].read_chunk_varlen((1,))
        assert np.array_equal(back, payload)

    def test_remote_h5_is_rejected(self, stub):
        with pytest.raises(ValueError, match="hdf5"):
            file_reader(f"{stub.url}/vol.h5")

    def test_ragged_stays_posix_only(self, stub):
        from cluster_tools_tpu.utils.store import RaggedDataset

        with pytest.raises(NotImplementedError, match="POSIX-only"):
            RaggedDataset.create(f"{stub.url}/ragged", (4,), "uint64")


# --------------------------------------------------------------------------
# remote decoded-chunk LRU


class TestRemoteChunkLRU:
    def test_warm_vs_cold_hit_accounting(self, tmp_path, stub, rng,
                                         traced_metrics):
        """Cold read: ONE conditional GET + one miss per chunk (the old
        HEAD-then-GET pair is folded into the GET).  Warm read: every
        chunk an LRU hit revalidated by a 304 — still one request per
        chunk, but zero payload bytes cross the wire and nothing reaches
        the codec boundary (the latency shield, ctt-cloud follow-up)."""
        _fresh_cache()
        data = rng.random((16, 16, 16)).astype("float32")
        url = f"{stub.url}/lru.zarr"
        file_reader(url).create_dataset("x", data=data, chunks=(8, 8, 8))
        ds = file_reader(url, "r")["x"]

        def snap():
            return dict(traced_metrics.snapshot()["counters"])

        b0 = snap()
        assert np.array_equal(ds[:], data)
        b1 = snap()

        def delta(a, b, name):
            return b.get(name, 0) - a.get(name, 0)

        assert delta(b0, b1, "store.chunk_cache_misses") == 8
        assert delta(b0, b1, "store.chunks_read") == 8
        # the HEAD fold: a cold chunk costs exactly ONE wire request
        assert delta(b0, b1, "store.remote_reads") == 8
        assert np.array_equal(ds[:], data)
        b2 = snap()
        assert delta(b1, b2, "store.chunk_cache_hits") == 8
        # warm: one 304 revalidation per chunk, zero chunk payloads
        # crossed the codec boundary, zero body bytes crossed the wire
        assert delta(b1, b2, "store.remote_reads") == 8
        assert delta(b1, b2, "store.chunks_read") == 0
        assert delta(b1, b2, "store.remote_bytes_read") == 0

    def test_etag_change_invalidates(self, tmp_path, stub, rng):
        """An out-of-band rewrite (another process, another host) changes
        the HEAD signature, so the next read re-fetches — freshness
        degrades to a re-decode, never to stale data."""
        _fresh_cache()
        data = rng.random((8, 8, 8)).astype("float32")
        url = f"{stub.url}/inv.zarr"
        file_reader(url).create_dataset("x", data=data, chunks=(8, 8, 8))
        ds = file_reader(url, "r")["x"]
        assert np.array_equal(ds[:], data)  # cached
        # rewrite the object BEHIND the backend: straight into the stub's
        # served tree, the way a foreign writer would
        other = str(tmp_path / "other.zarr")
        new = (data * 2.0 + 1.0).astype("float32")
        file_reader(other).create_dataset("x", data=new, chunks=(8, 8, 8))
        src = os.path.join(other, "x", "0.0.0")
        dst = os.path.join(stub.root, "inv.zarr", "x", "0.0.0")
        os.replace(src, dst)
        assert np.array_equal(ds[:], new)

    def test_prefetch_warms_lru_and_counts(self, tmp_path, stub, rng,
                                           traced_metrics):
        _fresh_cache()
        data = rng.random((16, 32, 16)).astype("float32")
        url = f"{stub.url}/pf.zarr"
        file_reader(url).create_dataset("x", data=data, chunks=(8, 16, 8))
        ds = file_reader(url, "r")["x"]
        n = ds.prefetch(np.s_[0:16, 0:32, 0:16])
        assert n == 8
        before = traced_metrics.snapshot()["counters"]
        assert np.array_equal(ds[:], data)
        after = traced_metrics.snapshot()["counters"]
        assert after.get("store.chunk_cache_hits", 0) - before.get(
            "store.chunk_cache_hits", 0
        ) == 8
        # disabled LRU: prefetch is a no-op by contract
        prev = store.set_chunk_cache_budget(0)
        try:
            assert ds.prefetch(np.s_[0:16, 0:32, 0:16]) == 0
        finally:
            store.set_chunk_cache_budget(None)
            del prev


# --------------------------------------------------------------------------
# resilience: truncation + injected request failures


class TestRemoteResilience:
    def test_truncated_response_classifies_corrupt_and_heals(
        self, tmp_path, stub, rng, traced_metrics
    ):
        """A truncated object body (full Content-Length, half the bytes)
        must classify exactly like a torn POSIX chunk: CorruptChunk →
        transient → the retry re-fetches and the read heals
        byte-identically."""
        _fresh_cache()
        data = rng.random((8, 8, 8)).astype("float32")
        url = f"{stub.url}/trunc.zarr"
        file_reader(url).create_dataset("x", data=data, chunks=(8, 8, 8))
        ds = file_reader(url, "r")["x"]
        before = traced_metrics.snapshot()["counters"]
        stub.truncate_next("x/0.0.0", times=1)
        healed = ds.read_chunk((0, 0, 0))
        assert np.array_equal(healed, data)
        after = traced_metrics.snapshot()["counters"]
        assert after.get("store.remote_retries", 0) > before.get(
            "store.remote_retries", 0
        )
        assert stub.policy.truncations == 1

    def test_persistent_truncation_raises_corrupt_chunk(
        self, tmp_path, stub, rng, monkeypatch
    ):
        _fresh_cache()
        monkeypatch.setenv("CTT_IO_RETRIES", "1")
        data = rng.random((8, 8, 8)).astype("float32")
        url = f"{stub.url}/trunc2.zarr"
        file_reader(url).create_dataset("x", data=data, chunks=(8, 8, 8))
        ds = file_reader(url, "r")["x"]
        stub.truncate_next("x/0.0.0", times=10)
        with pytest.raises(CorruptChunk):
            ds.read_chunk((0, 0, 0))

    def test_5xx_chaos_roundtrip_is_byte_identical(self, tmp_path, rng,
                                                   traced_metrics):
        """A flaky gateway (8% of ALL requests 503) is absorbed by the
        request-level backoff: writes and reads both land byte-identical
        to the fault-free POSIX reference, with store.remote_retries > 0
        recording the recoveries."""
        _fresh_cache()
        data = rng.random((16, 16, 16)).astype("float32")
        local = str(tmp_path / "ref.n5")
        file_reader(local).create_dataset(
            "x", data=data, chunks=(4, 8, 8), compression="gzip"
        )
        with StubObjectStore(
            str(tmp_path / "chaosroot"), fail_rate=0.08, seed=11,
            slow_s=0.02, slow_rate=0.1,  # latency spikes ride along
        ) as srv:
            url = f"{srv.url}/chaos.n5"
            file_reader(url).create_dataset(
                "x", data=data, chunks=(4, 8, 8), compression="gzip"
            )
            assert np.array_equal(file_reader(url, "r")["x"][:], data)
            assert srv.policy.failures > 0, (
                "chaos never fired — the test certifies nothing"
            )
            assert _digest_tree(os.path.join(local, "x")) == _digest_tree(
                os.path.join(srv.root, "chaos.n5", "x")
            )
        counters = traced_metrics.snapshot()["counters"]
        assert counters.get("store.remote_retries", 0) > 0

    def test_remote_fault_sites_fire(self, tmp_path, stub, rng):
        from cluster_tools_tpu import faults

        _fresh_cache()
        data = rng.random((8, 8, 8)).astype("float32")
        url = f"{stub.url}/faults.zarr"
        file_reader(url).create_dataset("x", data=data, chunks=(8, 8, 8))
        ds = file_reader(url, "r")["x"]
        faults.configure(
            "store.remote_read:io_error:times=1;seed=3"
        )
        try:
            # the injected request error is transient: the read retries
            # through it and still returns the data
            assert np.array_equal(ds[:], data)
            assert faults.decision_log()
        finally:
            faults.reset()


# --------------------------------------------------------------------------
# registry + watch line


class TestRemoteObservability:
    def test_remote_metrics_registered(self):
        from cluster_tools_tpu.obs import registry

        for name in (
            "store.remote_reads", "store.remote_writes",
            "store.remote_retries", "store.remote_bytes_read",
            "store.remote_bytes_written", "executor.prefetch_batches",
            "executor.stage_prefetch_s",
        ):
            assert registry.is_known_counter(name), name
        assert registry.is_known_gauge("store.remote_inflight")

    def test_watch_renders_remote_line(self, tmp_path):
        from cluster_tools_tpu.obs.live import LiveRun, format_watch

        run = str(tmp_path / "run")
        os.makedirs(run)
        with open(os.path.join(run, "metrics.p1.json"), "w") as f:
            json.dump({
                "counters": {
                    "store.remote_reads": 120, "store.remote_writes": 30,
                    "store.remote_retries": 2,
                    "store.remote_bytes_read": 5.0e6,
                    "store.remote_bytes_written": 2.5e6,
                },
                "gauges": {"store.remote_inflight": 4},
            }, f)
        text = format_watch(LiveRun(run).poll())
        assert "remote: reads 120, writes 30, retries 2" in text
        assert "read 5.0 MB" in text and "written 2.5 MB" in text
        assert "inflight 4" in text


# --------------------------------------------------------------------------
# executor integration: async prefetch + e2e acceptance


class TestRemotePipeline:
    @staticmethod
    def _subprocess_stub(td, root, fail_rate, seed):
        """The stub as a SUBPROCESS: in-process server threads would share
        the GIL with jax host compute and bleed server time into the
        executor's stage walls — the e2e stage accounting must measure
        the client side only (and a separate process is what production
        looks like anyway)."""
        import subprocess
        import sys as _sys
        import time as _time

        port_file = os.path.join(td, "stub.port")
        proc = subprocess.Popen([
            _sys.executable,
            os.path.join(os.path.dirname(__file__), "objstub.py"),
            "--root", root, "--port-file", port_file,
            "--fail-rate", str(fail_rate), "--seed", str(seed),
        ])
        deadline = _time.monotonic() + 30
        while not os.path.exists(port_file):
            assert proc.poll() is None, "stub server died on startup"
            assert _time.monotonic() < deadline, "stub server never came up"
            _time.sleep(0.02)
        with open(port_file) as f:
            port = int(f.read())
        return proc, f"http://127.0.0.1:{port}"

    def _ws_run(self, td, tag, data_path, out_key="ws", depth=3):
        from cluster_tools_tpu.runtime import build, config as cfg
        from cluster_tools_tpu.workflows import WatershedWorkflow

        config_dir = os.path.join(td, f"configs_{tag}")
        cfg.write_global_config(config_dir, {
            "block_shape": [8, 32, 32], "target": "tpu",
            "pipeline_depth": depth,
        })
        cfg.write_config(config_dir, "watershed", {
            "threshold": 0.5, "sigma_seeds": 1.6, "size_filter": 10,
            "halo": [2, 4, 4],
        })
        wf = WatershedWorkflow(
            os.path.join(td, f"tmp_{tag}"), config_dir,
            input_path=data_path, input_key="bnd",
            output_path=data_path, output_key=out_key,
        )
        assert build([wf]), tag

    def test_ws_e2e_remote_chaos_byte_identical_and_prefetched(
        self, tmp_path, rng, traced_metrics, monkeypatch
    ):
        """The acceptance gate: watershed against the stub object store
        with 5% injected request failures is byte-identical (chunk
        digests included) to the POSIX run, the async-prefetch stage ran,
        and the read stage is not the critical path at depth 3."""
        from scipy import ndimage

        _fresh_cache()
        # 32 blocks of (8, 32, 32): with the 8-virtual-device batch of 8
        # that is 4 dispatch chunks — enough for the depth-3 read window
        # AND a lookahead prefetch beyond it (the stage is a no-op when
        # every chunk fits inside the read window)
        base = ndimage.gaussian_filter(
            rng.random((16, 256, 64)), (1.0, 2.0, 2.0)
        )
        vol = (
            (base - base.min()) / (base.max() - base.min())
        ).astype("float32")
        td = str(tmp_path)
        local = os.path.join(td, "local.n5")
        file_reader(local).create_dataset(
            "bnd", data=vol, chunks=(8, 32, 32), compression="gzip"
        )
        self._ws_run(td, "local", local)
        # retry sleeps are real wall in the read stage; the chaos run must
        # absorb 5% failures without its backoff dominating the accounting
        monkeypatch.setenv("CTT_IO_BACKOFF_BASE_S", "0.001")
        root = os.path.join(td, "objroot")
        os.makedirs(root)
        served = os.path.join(root, "data.n5")
        file_reader(served).create_dataset(
            "bnd", data=vol, chunks=(8, 32, 32), compression="gzip"
        )
        proc, url = self._subprocess_stub(td, root, fail_rate=0.05, seed=7)
        try:
            before = dict(traced_metrics.snapshot()["counters"])
            self._ws_run(td, "remote", f"{url}/data.n5")
            mid = dict(traced_metrics.snapshot()["counters"])
            # warm-LRU rerun (same input volume, fresh scratch): reads are
            # 304 conditional-GET revalidations + LRU hits — the
            # latency-shield run
            self._ws_run(td, "remote_warm", f"{url}/data.n5",
                         out_key="ws2")
            after = dict(traced_metrics.snapshot()["counters"])
            # byte-identity including chunk digests, both runs
            assert _digest_tree(os.path.join(local, "ws")) == _digest_tree(
                os.path.join(served, "ws")
            )
            a = file_reader(local, "r")["ws"][:]
            b = file_reader(served, "r")["ws"][:]
            b2 = file_reader(served, "r")["ws2"][:]
            assert np.array_equal(a, b)
            assert np.array_equal(a, b2)
        finally:
            proc.terminate()
            proc.wait(timeout=30)

        def cold(name):
            return mid.get(name, 0.0) - before.get(name, 0.0)

        def warm(name):
            return after.get(name, 0.0) - mid.get(name, 0.0)

        assert cold("store.remote_reads") > 0
        assert cold("store.remote_writes") > 0
        assert cold("store.remote_retries") > 0
        # the lookahead stage really issued prefetches...
        assert cold("executor.prefetch_batches") > 0
        # ...and on the warm-LRU run host reads are hidden behind device
        # compute (the acceptance gate at pipeline_depth >= 3).  Stage
        # seconds are OCCUPANCY — read-stage walls overlap compute by
        # design and absorb its GIL time — so the critical-path claim is
        # asserted through the metric built for it: more IO seconds were
        # hidden behind the serialized compute stage than the entire read
        # stage occupied, hence the read stage cannot be the critical path.
        assert warm("store.chunk_cache_hits") > 0
        assert warm("executor.stage_hidden_io_s") > warm(
            "executor.stage_read_s"
        )
