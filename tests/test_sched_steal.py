"""ctt-steal: dynamic work-stealing block scheduler tests.

Covers the lease protocol end to end:

  * lease/manifest/result file grammar + renewal semantics;
  * the claim race between two REAL processes (os.link exclusivity:
    every block computed exactly once, never lost);
  * expiry → requeue after a ``CTT_FAULTS`` worker kill, with output
    byte-identical to a fault-free run and ZERO task-level retry rounds;
  * an elastic late-joining worker draining the queue;
  * straggler duplicate dispatch with first-writer-wins results;
  * ``CTT_SCHED=static`` byte-identity with the frozen round-robin split
    (and the disabled-overhead contract: no queue directory at all);
  * aggregation attribution from ownership records, not frozen slices.
"""

import hashlib
import json
import os
import stat
import subprocess
import sys
import time

import numpy as np
import pytest

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.runtime.queue import (
    STALE_INTERVALS, Claim, WorkQueue, drain, publish_once, resolve_sched,
)
from cluster_tools_tpu.utils import file_reader

PKG_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(cfg.__file__)))
)


@pytest.fixture
def traced_metrics(tmp_path):
    """Counters are live only while tracing is enabled (the one ctt-obs
    switch); flip it on for tests asserting sched.* metric movement."""
    from cluster_tools_tpu.obs import metrics as obs_metrics
    from cluster_tools_tpu.obs import trace as obs_trace

    was_on = obs_trace.enabled()
    if not was_on:
        obs_trace.enable(str(tmp_path / "trace"), "sched_unit",
                         export_env=False)
    try:
        yield obs_metrics
    finally:
        if not was_on:
            obs_trace.disable()


def _write_stub_scheduler(folder):
    """Synchronous sbatch/squeue stand-in (the fake-scheduler seam)."""
    os.makedirs(folder, exist_ok=True)
    submit = os.path.join(folder, "stub_submit")
    with open(submit, "w") as f:
        f.write(
            "#!/bin/bash\n"
            'script="${@: -1}"\n'
            'bash "$script" > /dev/null 2>&1\n'
            'echo "Submitted batch job 1"\n'
        )
    queue = os.path.join(folder, "stub_queue")
    with open(queue, "w") as f:
        f.write("#!/bin/bash\nexit 0\n")
    for p in (submit, queue):
        os.chmod(p, os.stat(p).st_mode | stat.S_IEXEC)
    return submit, queue


WORKER_ENV = {
    "PALLAS_AXON_POOL_IPS": "",
    "JAX_PLATFORMS": "cpu",
}


def _digest_tree(root):
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


# --------------------------------------------------------------------------
# queue-layer unit tests


class TestLeaseGrammar:
    def test_manifest_items_and_claim_lease_schema(self, tmp_path):
        q = WorkQueue.create(
            str(tmp_path / "q"), "ws", list(range(7)), 3, 0.5
        )
        assert q.items == [[0, 1, 2], [3, 4, 5], [6]]
        m = json.load(open(str(tmp_path / "q" / "manifest.json")))
        assert m["task"] == "ws" and m["lease_s"] == 0.5 and m["duplicate"]

        claim = q.claim(job_id=2)
        assert claim.item == 0 and claim.block_ids == [0, 1, 2]
        assert claim.gen == 0 and not claim.duplicate
        lease = json.load(open(claim.lease_path))
        assert lease["item"] == 0 and lease["gen"] == 0
        assert lease["blocks"] == [0, 1, 2]
        assert lease["owner_pid"] == os.getpid() and lease["job_id"] == 2
        assert lease["claim_wall"] <= lease["wall"]
        assert "mono" in lease and "host" in lease

    def test_renew_restamps_wall_keeps_claim_wall(self, tmp_path):
        q = WorkQueue.create(str(tmp_path / "q"), "t", [0, 1], 1, 0.5)
        claim = q.claim(job_id=0)
        before = json.load(open(claim.lease_path))
        time.sleep(0.05)
        q.renew(claim, job_id=0)
        after = json.load(open(claim.lease_path))
        assert after["wall"] > before["wall"]
        assert after["claim_wall"] == pytest.approx(before["claim_wall"])

    def test_result_publish_first_writer_wins(self, tmp_path):
        q = WorkQueue.create(str(tmp_path / "q"), "t", [0, 1], 2, 0.5)
        claim = q.claim(job_id=0)
        assert q.complete(claim, [0, 1], [], {}, 0.1, job_id=0)
        # a racing duplicate loses the result slot; the record keeps the
        # first writer's attribution
        dup = Claim(item=0, block_ids=[0, 1], gen=0, lease_path=None,
                    duplicate=True)
        assert not q.complete(dup, [0, 1], [], {}, 0.2, job_id=9)
        rec = json.load(open(str(tmp_path / "q" / "result.0.json")))
        assert rec["job_id"] == 0 and not rec["duplicate"]
        assert q.all_resolved()

    def test_publish_once_is_exclusive_and_atomic(self, tmp_path):
        p = str(tmp_path / "slot")
        assert publish_once(p, b"first")
        assert not publish_once(p, b"second")
        assert open(p, "rb").read() == b"first"
        # no tmp litter
        assert os.listdir(str(tmp_path)) == ["slot"]

    def test_resolve_sched_defaults_and_guards(self):
        class Retryable:
            allow_retry = True

        class Fragile:
            allow_retry = False

        assert resolve_sched({}, Retryable(), 3) == "steal"
        assert resolve_sched({}, Retryable(), 1) == "static"
        # requeue/duplication re-run blocks: non-retryable tasks keep the
        # frozen split even when steal is requested
        assert resolve_sched({}, Fragile(), 3) == "static"
        assert resolve_sched({"sched": "steal"}, Fragile(), 3) == "static"
        assert resolve_sched({"sched": "static"}, Retryable(), 3) == "static"
        with pytest.raises(ValueError, match="unknown scheduler mode"):
            resolve_sched({"sched": "steel"}, Retryable(), 3)

    def test_sched_metrics_registered(self):
        from cluster_tools_tpu.obs import registry

        for name in (
            "sched.leases_claimed", "sched.leases_expired",
            "sched.leases_requeued", "sched.leases_stolen",
            "sched.driver_drain_blocks",
        ):
            assert registry.is_known_counter(name), name
        assert registry.is_known_gauge("sched.queue_depth")


class TestExpiryAndRequeue:
    def test_expired_lease_requeues_at_next_generation(
        self, tmp_path, traced_metrics
    ):
        # injected reader clock (WorkQueue._now) instead of real sleeps:
        # with a sub-second cadence a loaded CI host could age the fresh
        # lease past 3x BEFORE the freshness assertion ran — the timing
        # flake this test used to carry.  A wide cadence makes "fresh"
        # unbreakable and the advanced clock makes "expired" exact.
        obs_metrics = traced_metrics
        lease_s = 30.0
        q = WorkQueue.create(str(tmp_path / "q"), "t", [0, 1], 2, lease_s)
        dead = q.claim(job_id=0)  # owner "dies": never renews, never completes
        assert dead is not None
        before = obs_metrics.snapshot()["counters"]
        assert q.claim(job_id=1) is None  # lease still fresh
        q._now = lambda: time.time() + STALE_INTERVALS * lease_s + 1.0
        takeover = q.claim(job_id=1)
        assert takeover is not None
        assert takeover.item == dead.item and takeover.gen == 1
        after = obs_metrics.snapshot()["counters"]
        assert after.get("sched.leases_expired", 0) > before.get(
            "sched.leases_expired", 0
        )
        assert after.get("sched.leases_requeued", 0) > before.get(
            "sched.leases_requeued", 0
        )
        # both generations remain as ownership history
        names = sorted(os.listdir(str(tmp_path / "q")))
        assert "lease.0.g0.json" in names and "lease.0.g1.json" in names

    def test_torn_lease_still_expires_via_mtime(self, tmp_path):
        from cluster_tools_tpu import faults

        lease_s = 30.0
        q = WorkQueue.create(str(tmp_path / "q"), "t", [0], 1, lease_s)
        faults.configure("sched.write:torn:bytes=5;seed=1")
        try:
            torn = q.claim(job_id=0)
        finally:
            faults.reset()
        # the lease payload was truncated mid-write
        raw = open(torn.lease_path, "rb").read()
        assert len(raw) == 5
        with pytest.raises(json.JSONDecodeError):
            json.loads(raw)
        # torn leases age from file mtime; the injected reader clock
        # (WorkQueue._now) expires it without sleeping 3x the cadence
        assert q.claim(job_id=1) is None  # still fresh by mtime
        q._now = lambda: time.time() + STALE_INTERVALS * lease_s + 1.0
        takeover = q.claim(job_id=1)
        assert takeover is not None and takeover.gen == 1

    def test_unresolved_item_attributed_to_real_owner(self, tmp_path):
        """Satellite: aggregation blames the ACTUAL lease owner, not the
        job a frozen round-robin slice would have assigned the blocks."""
        q = WorkQueue.create(str(tmp_path / "q"), "t", [0, 1, 2, 3], 2, 0.5)
        a = q.claim(job_id=7)     # job 7 owns item 0 ... and dies
        b = q.claim(job_id=1)     # job 1 completes item 1
        q.complete(b, b.block_ids, [], {}, 0.01, job_id=1)
        done, failed, errors, owners = q.aggregate()
        assert sorted(done) == [2, 3]
        assert failed == [0, 1]
        assert "job 7" in errors[0] and "never produced a result" in errors[0]
        assert owners[a.item]["job_id"] == 7
        assert owners[b.item]["job_id"] == 1


class TestStragglerDuplication:
    def test_duplicate_oldest_inflight_first_writer_wins(
        self, tmp_path, traced_metrics
    ):
        obs_metrics = traced_metrics
        q = WorkQueue.create(
            str(tmp_path / "q"), "t", list(range(8)), 2, 60.0
        )
        straggler = q.claim(job_id=0)  # holds item 0, runs "forever"
        fast = WorkQueue(str(tmp_path / "q"))
        for _ in range(3):
            c = fast.claim(job_id=1)
            fast.complete(c, c.block_ids, [], {}, 0.01, job_id=1)
        # nothing unclaimed, lease fresh, claim too young -> no duplicate yet
        assert fast.claim(job_id=1) is None
        # age the straggler's CLAIM (not its renewal stamp: the lease is
        # alive, its owner just isn't finishing) beyond 4 x median
        lease = json.load(open(straggler.lease_path))
        lease["claim_wall"] -= 3600.0
        with open(straggler.lease_path, "w") as f:
            json.dump(lease, f)
        before = obs_metrics.snapshot()["counters"]
        dup = fast.claim(job_id=1)
        assert dup is not None and dup.duplicate and dup.item == 0
        assert dup.lease_path is None  # duplication takes no lease
        after = obs_metrics.snapshot()["counters"]
        assert after.get("sched.leases_stolen", 0) > before.get(
            "sched.leases_stolen", 0
        )
        # the same client never duplicates the same item twice
        assert fast.claim(job_id=1, skip_duplicates={0}) is None
        # first writer (the duplicate) wins the result slot; the straggling
        # owner's late publish is a no-op
        assert fast.complete(dup, dup.block_ids, [], {}, 0.01, job_id=1)
        assert not q.complete(
            straggler, straggler.block_ids, [], {}, 99.0, job_id=0
        )
        done, failed, errors, owners = q.aggregate()
        assert failed == [] and sorted(done) == list(range(8))
        assert owners[0]["job_id"] == 1 and owners[0]["duplicate"]

    def test_duplicate_fires_from_live_median_before_first_result(
        self, tmp_path, traced_metrics
    ):
        """Lease-aware straggler thresholds (ROADMAP item 1 follow-up,
        landed with ctt-serve): when the live trace already carries
        completed block durations for this task, duplication uses
        obs.live's per-task median (scaled by the item's block count) —
        so it can fire before ANY item result record exists, where the
        queue's own median was previously blind."""
        from cluster_tools_tpu.obs import trace as obs_trace

        q = WorkQueue.create(
            str(tmp_path / "q"), "t", list(range(4)), 2, 60.0
        )
        straggler = q.claim(job_id=0)   # item 0, runs "forever"
        fast = WorkQueue(str(tmp_path / "q"))
        other = fast.claim(job_id=1)    # item 1, also in flight
        assert other is not None and other.item == 1
        # zero results and no trace data: no baseline, no duplicate
        assert fast.claim(job_id=1) is None
        # completed block spans land in the live trace (the obs watch
        # straggler baseline): median block 0.01 s
        run_dir = obs_trace.run_dir()
        os.makedirs(run_dir, exist_ok=True)
        with open(os.path.join(run_dir, "spans.p1.t1.jsonl"), "w") as f:
            f.write(json.dumps({
                "type": "header", "run": "sched_unit", "pid": 1, "tid": 1,
                "host": "synth", "wall": 1000.0, "mono": 10.0,
            }) + "\n")
            for i in range(5):
                f.write(json.dumps({
                    "type": "span", "id": i + 1, "parent": None,
                    "name": "block", "kind": "host",
                    "t0": 10.0 + i, "t1": 10.01 + i, "pid": 1, "tid": 1,
                    "attrs": {"task": "t", "block": 100 + i},
                }) + "\n")
        # age the straggler's CLAIM well past 4 x (median x item blocks)
        lease = json.load(open(straggler.lease_path))
        lease["claim_wall"] -= 3600.0
        with open(straggler.lease_path, "w") as f:
            json.dump(lease, f)
        dup = WorkQueue(str(tmp_path / "q")).claim(job_id=1)
        assert dup is not None and dup.duplicate and dup.item == 0
        # a different task's spans are not a baseline for this queue
        q2 = WorkQueue.create(
            str(tmp_path / "q2"), "other_task", [0, 1], 1, 60.0
        )
        s2 = q2.claim(job_id=0)
        assert q2.claim(job_id=0) is not None  # item 1 also in flight
        lease = json.load(open(s2.lease_path))
        lease["claim_wall"] -= 3600.0
        with open(s2.lease_path, "w") as f:
            json.dump(lease, f)
        assert WorkQueue(str(tmp_path / "q2")).claim(job_id=1) is None


# --------------------------------------------------------------------------
# real-process tests: claim race + elastic late joiner

_WORKER_SCRIPT = """\
import json, os, sys, time
sys.path.insert(0, {pkg_root!r})
from cluster_tools_tpu.runtime.queue import WorkQueue, drain

queue_dir, job_id, sleep_s, out = (
    sys.argv[1], int(sys.argv[2]), float(sys.argv[3]), sys.argv[4]
)
q = WorkQueue(queue_dir)


def run_item(claim):
    if sleep_s:
        time.sleep(sleep_s)
    return list(claim.block_ids), [], {{}}


stats = drain(q, run_item, job_id=job_id)
with open(out, "w") as f:
    json.dump(stats, f)
"""


def _spawn_worker(tmp_path, queue_dir, job_id, sleep_s, extra_env=None):
    script = str(tmp_path / "queue_worker.py")
    if not os.path.exists(script):
        with open(script, "w") as f:
            f.write(_WORKER_SCRIPT.format(pkg_root=PKG_ROOT))
    out = str(tmp_path / f"stats_{job_id}.json")
    env = dict(os.environ)
    env.update(WORKER_ENV)
    env.pop("CTT_TRACE_DIR", None)
    if extra_env:
        env.update(extra_env)
    proc = subprocess.Popen(
        [sys.executable, script, queue_dir, str(job_id), str(sleep_s), out],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    return proc, out


class TestRealProcesses:
    def test_claim_race_exactly_once_never_lost(self, tmp_path):
        """Two real processes hammer the same queue (with injected claim
        stalls widening the selection→link window): os.link exclusivity
        must hand every item to exactly one owner, and every block must
        land in exactly one result."""
        n_blocks = 30
        q = WorkQueue.create(
            str(tmp_path / "q"), "t", list(range(n_blocks)), 2, 5.0,
            duplicate=False,
        )
        race_env = {"CTT_FAULTS": "sched.claim:stall:p=0.4,s=0.02;seed=3"}
        procs = [
            _spawn_worker(tmp_path, q.dir, j, 0.0, extra_env=race_env)
            for j in range(2)
        ]
        stats = []
        for proc, out in procs:
            _, err = proc.communicate(timeout=120)
            assert proc.returncode == 0, err.decode()[-2000:]
            stats.append(json.load(open(out)))
        all_done = stats[0]["done"] + stats[1]["done"]
        assert sorted(all_done) == list(range(n_blocks))  # exactly once
        assert not set(stats[0]["items"]) & set(stats[1]["items"])
        # one gen-0 lease per item, no requeues, one result per item
        names = os.listdir(q.dir)
        leases = [n for n in names if n.startswith("lease.")]
        assert len(leases) == len(q.items)
        assert all(n.endswith(".g0.json") for n in leases)
        assert len([n for n in names if n.startswith("result.")]) == len(
            q.items
        )
        done, failed, errors, _ = q.aggregate()
        assert failed == [] and errors == {}

    def test_elastic_late_joiner_drains_queue(self, tmp_path):
        """A process pointed at the queue AFTER the run started just
        starts pulling — no registration, no resubmission."""
        q = WorkQueue.create(
            str(tmp_path / "q"), "t", list(range(12)), 1, 5.0,
            duplicate=False,
        )
        early, early_out = _spawn_worker(tmp_path, q.dir, 0, 0.15)
        time.sleep(1.0)  # the early worker is mid-drain by now
        late, late_out = _spawn_worker(tmp_path, q.dir, 1, 0.0)
        for proc, out in ((early, early_out), (late, late_out)):
            _, err = proc.communicate(timeout=120)
            assert proc.returncode == 0, err.decode()[-2000:]
        s_early = json.load(open(early_out))
        s_late = json.load(open(late_out))
        assert s_late["items"], "late joiner pulled nothing"
        assert s_early["items"], "early worker pulled nothing"
        assert sorted(s_early["done"] + s_late["done"]) == list(range(12))
        assert q.all_resolved()


# --------------------------------------------------------------------------
# integration: stub-scheduler workflows


def _threshold_run(tmp_path, rng_data, tag, *, sched=None, faults_spec=None,
                   state_dir=None, trace_run=None, max_jobs=3,
                   extra_global=None):
    """One ThresholdTask run through the stub scheduler; returns the n5
    output dataset dir (for byte digests) and the task status path."""
    from cluster_tools_tpu.tasks.threshold import ThresholdTask

    submit, queue = _write_stub_scheduler(str(tmp_path / f"sched_{tag}"))
    path = str(tmp_path / f"{tag}.n5")
    file_reader(path).create_dataset(
        "x", data=rng_data, chunks=(4, 16, 16)
    )
    config_dir = str(tmp_path / f"configs_{tag}")
    gconf = {
        "block_shape": [4, 16, 16],
        "target": "slurm",
        "max_jobs": max_jobs,
        "max_num_retries": 2,
        "retry_failure_fraction": 0.9,
        "poll_interval_s": 0.05,
        # a full-second cadence (expiry at 3 s): the renewer stamps every
        # 0.5 s, so ~6 consecutive starved renewals would be needed for a
        # LIVE lease to expire spuriously — the worker-kill test was flaky
        # under full-suite load at 0.2 s (PR 9 review)
        "steal_lease_s": 1.0,
        "steal_batch_size": 2,
        "sbatch_cmd": submit,
        "squeue_cmd": queue,
        "worker_env": dict(WORKER_ENV),
    }
    if sched is not None:
        gconf["sched"] = sched
    if extra_global:
        gconf.update(extra_global)
    cfg.write_global_config(config_dir, gconf)
    cfg.write_config(config_dir, "threshold", {"threshold": 0.5})
    env_keys = {}
    if faults_spec is not None:
        env_keys["CTT_FAULTS"] = faults_spec
        env_keys["CTT_FAULT_STATE_DIR"] = state_dir
    if trace_run is not None:
        env_keys["CTT_TRACE_DIR"] = str(tmp_path / "trace")
        env_keys["CTT_RUN_ID"] = trace_run
    old = {k: os.environ.get(k) for k in env_keys}
    os.environ.update(env_keys)
    try:
        task = ThresholdTask(
            str(tmp_path / f"tmp_{tag}"), config_dir, max_jobs=max_jobs,
            input_path=path, input_key="x",
            output_path=path, output_key="y",
        )
        assert build([task])
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    status = json.load(open(os.path.join(
        str(tmp_path / f"tmp_{tag}"), "status", "threshold.status.json"
    )))
    return os.path.join(path, "y"), status, str(tmp_path / f"tmp_{tag}")


@pytest.fixture
def vol(rng):
    return rng.random((16, 32, 32)).astype("float32")


class TestStubSchedulerIntegration:
    def test_static_steal_byte_identical_and_static_overhead(
        self, tmp_path, vol
    ):
        """CTT_SCHED=static is the pre-PR frozen split, byte-identical to
        the stealing path; static runs build no queue at all (disabled-
        overhead contract)."""
        out_static, st_static, tmp_static = _threshold_run(
            tmp_path, vol, "static", sched="static"
        )
        out_steal, st_steal, tmp_steal = _threshold_run(
            tmp_path, vol, "steal", sched="steal"
        )
        assert _digest_tree(out_static) == _digest_tree(out_steal)
        assert st_static["complete"] and st_steal["complete"]
        # static: frozen round-robin recorded in the job configs, no queue
        job_dir = os.path.join(tmp_static, "cluster_jobs", "threshold")
        ids = sorted(st_static["done"])
        for jf in sorted(os.listdir(job_dir)):
            if jf.startswith("job_") and jf.endswith(".json") \
                    and "status" not in jf:
                job_id = int(jf.split("_")[1].split(".")[0])
                conf = json.load(open(os.path.join(job_dir, jf)))
                assert conf["block_ids"] == ids[job_id::3]
                assert "queue_dir" not in conf
        assert not os.path.isdir(os.path.join(job_dir, "queue"))
        # steal: queue manifest + results exist, job statuses say so
        steal_q = os.path.join(
            tmp_steal, "cluster_jobs", "threshold", "queue"
        )
        assert os.path.exists(os.path.join(steal_q, "manifest.json"))
        assert any(
            n.startswith("result.") for n in os.listdir(steal_q)
        )

    def test_worker_kill_selfheals_via_requeue_byte_identical(
        self, tmp_path, vol
    ):
        """A worker hard-killed mid-item (executor.block kill) loses its
        lease; a surviving worker requeues it after expiry.  The run
        completes in ONE dispatch round (zero task-level retries) and the
        output is byte-identical to a fault-free run.

        Duplication is disabled for the chaos run: straggler duplication
        and lease expiry RACE to recover a killed item (both are correct,
        first writer wins), so with it enabled the ``leases_expired >= 1``
        assertion was a coin flip under load — the PR 9 tier-1 flake.
        With ``steal_duplicate: false`` the expiry path is the only
        recovery route and the assertion is deterministic.

        The remaining flake was the expiry wait itself: the surviving
        worker must age the dead lease past ``3 x steal_lease_s`` of REAL
        time, racing its own drain give-up against CI load.  The chaos
        workers therefore run with ``CTT_SCHED_CLOCK_SKEW_S`` (the
        injected-clock seam from the PR 10 review) beyond the staleness
        horizon, so a dead lease is expired on the very first scan.  The
        skew shifts only the reader clock of those subprocesses; stamps
        stay real, and a worker never scans while holding a live lease
        (``drain`` is claim->execute->complete, jobs are sequential under
        the stub scheduler), so no live lease can be mis-expired."""
        out_ref, _, _ = _threshold_run(tmp_path, vol, "ref", sched="steal")
        out_chaos, status, tmp_chaos = _threshold_run(
            tmp_path, vol, "chaos", sched="steal",
            faults_spec="executor.block:kill:ids=5,once;seed=11",
            state_dir=str(tmp_path / "fault_state"),
            trace_run="steal_chaos",
            extra_global={
                "steal_duplicate": False,
                # > stale_after_s = 3 * steal_lease_s (1.0 s above)
                "worker_env": dict(
                    WORKER_ENV, CTT_SCHED_CLOCK_SKEW_S="4.0"
                ),
            },
        )
        assert _digest_tree(out_ref) == _digest_tree(out_chaos)
        # the kill really fired (cross-process latch)
        latches = os.listdir(str(tmp_path / "fault_state"))
        assert any(l.startswith("executor.block") for l in latches), latches
        # zero task-level retry rounds: one dispatch, nothing re-submitted
        assert status["complete"]
        assert len(status["block_runtimes"]) == 1
        # recovery is visible: a worker recorded the expiry + requeue
        totals = {}
        run_dir = str(tmp_path / "trace" / "steal_chaos")
        for name in os.listdir(run_dir):
            if name.startswith("metrics.p"):
                with open(os.path.join(run_dir, name)) as f:
                    for k, v in json.load(f)["counters"].items():
                        totals[k] = totals.get(k, 0) + v
        assert totals.get("sched.leases_expired", 0) >= 1, totals
        assert totals.get("sched.leases_requeued", 0) >= 1, totals
        assert totals.get("task.blocks_retried", 0) == 0, totals


class TestAggregationAttribution:
    def test_static_aggregate_uses_recorded_assignment(self, tmp_path):
        """Satellite fix: a statusless job's blocks come from its RECORDED
        job_N.json assignment, not a re-derived slice — so attribution
        stays truthful if the formation rule and the aggregation ever
        disagree."""
        from cluster_tools_tpu.runtime.cluster_executor import SlurmExecutor

        job_dir = str(tmp_path / "jobs")
        os.makedirs(job_dir)
        # deliberately NOT the round-robin slice of [1, 5, 7]
        with open(os.path.join(job_dir, "job_0.json"), "w") as f:
            json.dump({"block_ids": [5, 7]}, f)
        with open(os.path.join(job_dir, "job_1.json"), "w") as f:
            json.dump({"block_ids": [1]}, f)
        with open(os.path.join(job_dir, "job_1.status.json"), "w") as f:
            json.dump({"done": [1], "failed": [], "errors": {}}, f)
        ex = SlurmExecutor({})
        done, failed, errors = ex._aggregate(job_dir, 2, [1, 5, 7])
        assert done == [1]
        assert failed == [5, 7]
        # the no-status diagnostic anchors on job 0's REAL first block (5);
        # the frozen slice would have blamed block 1, which job 1 finished
        assert 5 in errors and "job 0" in errors[5]
        assert 1 not in errors
