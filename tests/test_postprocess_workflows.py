"""Postprocess workflow composites
(reference postprocess_workflow.py:24-412 equivalents)."""

import numpy as np
import pytest

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


@pytest.fixture
def seg_volume(tmp_path, rng):
    """Segmentation with two large segments and several tiny fragments."""
    shape = (16, 32, 32)
    seg = np.ones(shape, dtype="uint64")
    seg[:, :, 16:] = 2
    # tiny fragments (8 voxels each) embedded in segment 1
    seg[2:4, 2:4, 2:4] = 3
    seg[8:10, 8:10, 8:10] = 4
    hmap = np.zeros(shape, dtype="float32")
    hmap[:, :, 15:17] = 1.0
    path = str(tmp_path / "pp.n5")
    f = file_reader(path)
    f.create_dataset("seg", data=seg, chunks=(8, 16, 16))
    f.create_dataset("hmap", data=hmap, chunks=(8, 16, 16))
    return path, seg


def _env(tmp_path, name):
    config_dir = str(tmp_path / f"configs_{name}")
    tmp_folder = str(tmp_path / f"tmp_{name}")
    cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
    return tmp_folder, config_dir


def test_size_filter_workflow_background(tmp_path, seg_volume):
    from cluster_tools_tpu.workflows import SizeFilterWorkflow

    path, seg = seg_volume
    tmp_folder, config_dir = _env(tmp_path, "sfb")
    wf = SizeFilterWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="seg",
        output_path=path, output_key="filtered_bg",
        min_size=100,
    )
    assert build([wf])
    got = file_reader(path, "r")["filtered_bg"][:]
    assert set(np.unique(got)) == {0, 1, 2}  # tiny ids 3,4 -> background
    assert (got[seg == 3] == 0).all()


def test_size_filter_workflow_filling_and_relabel(tmp_path, seg_volume):
    from cluster_tools_tpu.workflows import SizeFilterWorkflow

    path, seg = seg_volume
    tmp_folder, config_dir = _env(tmp_path, "sff")
    wf = SizeFilterWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="seg",
        output_path=path, output_key="filtered_fill",
        min_size=100, hmap_path=path, hmap_key="hmap", relabel=True,
    )
    assert build([wf])
    got = file_reader(path, "r")["filtered_fill"][:]
    # tiny fragments re-flooded from survivors: no background introduced
    assert (got > 0).all()
    ids = np.unique(got)
    assert (np.diff(ids) == 1).all() and ids[0] == 1  # relabeled consecutive
    assert len(ids) == 2


def test_filter_labels_workflow(tmp_path, seg_volume):
    from cluster_tools_tpu.workflows import FilterLabelsWorkflow

    path, seg = seg_volume
    tmp_folder, config_dir = _env(tmp_path, "fl")
    wf = FilterLabelsWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="seg",
        output_path=path, output_key="filtered_ids",
        filter_labels=[2, 4],
    )
    assert build([wf])
    got = file_reader(path, "r")["filtered_ids"][:]
    np.testing.assert_array_equal(
        got, np.where(np.isin(seg, [2, 4]), 0, seg)
    )


def test_filter_by_threshold_workflow(tmp_path, seg_volume):
    from cluster_tools_tpu.workflows import FilterByThresholdWorkflow

    path, seg = seg_volume
    # intensity map: segment 2 bright, everything else dark
    intensity = np.where(seg == 2, 0.9, 0.1).astype("float32")
    file_reader(path).create_dataset(
        "intensity", data=intensity, chunks=(8, 16, 16)
    )
    tmp_folder, config_dir = _env(tmp_path, "ft")
    wf = FilterByThresholdWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="intensity",
        seg_path=path, seg_key="seg",
        output_path=path, output_key="filtered_dark",
        threshold=0.5, threshold_mode="less",  # drop DARK segments
    )
    assert build([wf])
    got = file_reader(path, "r")["filtered_dark"][:]
    assert set(np.unique(got)) == {0, 2}  # only the bright segment survives


def test_filter_orphans_workflow(tmp_path):
    from cluster_tools_tpu.workflows import FilterOrphansWorkflow

    # chain 1-2-3: 1 and 3 are orphans (single neighbor) and adopt 2
    labels = np.zeros((8, 8, 24), dtype="uint64")
    labels[:, :, :8] = 1
    labels[:, :, 8:16] = 2
    labels[:, :, 16:] = 3
    path = str(tmp_path / "orph.n5")
    file_reader(path).create_dataset("seg", data=labels, chunks=(8, 8, 8))
    tmp_folder, config_dir = _env(tmp_path, "orph")
    cfg.write_global_config(config_dir, {"block_shape": [8, 8, 24]})
    wf = FilterOrphansWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="seg",
        output_path=path, output_key="no_orphans",
    )
    assert build([wf])
    got = file_reader(path, "r")["no_orphans"][:]
    assert set(np.unique(got)) == {2}


def test_connected_components_workflow(tmp_path):
    from cluster_tools_tpu.workflows import ConnectedComponentsWorkflow

    # touching segments 1|2 and a detached segment 5
    labels = np.zeros((8, 8, 24), dtype="uint64")
    labels[:, :, :8] = 1
    labels[:, :, 8:12] = 2
    labels[:, :, 16:] = 5
    path = str(tmp_path / "gcc.n5")
    file_reader(path).create_dataset("seg", data=labels, chunks=(8, 8, 8))
    tmp_folder, config_dir = _env(tmp_path, "gcc")
    cfg.write_global_config(config_dir, {"block_shape": [8, 8, 24]})
    wf = ConnectedComponentsWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="seg",
        output_path=path, output_key="graph_cc",
    )
    assert build([wf])
    got = file_reader(path, "r")["graph_cc"][:]
    # 1 and 2 share a face -> one component; 5 stays its own; bg preserved
    c1 = np.unique(got[labels == 1])
    c2 = np.unique(got[labels == 2])
    c5 = np.unique(got[labels == 5])
    assert len(c1) == len(c2) == len(c5) == 1
    assert c1[0] == c2[0] != c5[0]
    assert (got[labels == 0] == 0).all()


def test_size_filter_graph_watershed_workflow(tmp_path, rng):
    from cluster_tools_tpu.tasks.costs import ProbsToCostsTask
    from cluster_tools_tpu.workflows import (
        EdgeFeaturesWorkflow,
        GraphWorkflow,
        SizeFilterAndGraphWatershedWorkflow,
    )

    # 1|tiny|2 along x: the tiny middle fragment is below min_size and must
    # re-attach to its strongest-connected neighbor (weak boundary to 1)
    shape = (8, 16, 24)
    labels = np.zeros(shape, dtype="uint64")
    labels[:, :, :10] = 1
    labels[:, :, 10:12] = 7  # tiny fragment: 8*16*2 = 256 vox
    labels[:, :, 12:] = 2
    bnd = np.zeros(shape, dtype="float32")
    bnd[:, :, 9:11] = 0.1   # WEAK boundary 1|7
    bnd[:, :, 11:13] = 0.9  # STRONG boundary 7|2
    path = str(tmp_path / "gw.n5")
    f = file_reader(path)
    f.create_dataset("seg", data=labels, chunks=(8, 8, 8))
    f.create_dataset("bnd", data=bnd, chunks=(8, 8, 8))
    tmp_folder, config_dir = _env(tmp_path, "gw")
    cfg.write_global_config(config_dir, {"block_shape": [8, 8, 24]})

    # problem pipeline (graph + features + costs) in the same tmp_folder —
    # the reference's problem_path
    graph = GraphWorkflow(
        tmp_folder, config_dir, input_path=path, input_key="seg"
    )
    feats = EdgeFeaturesWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="bnd",
        labels_path=path, labels_key="seg",
        dependencies=[graph],
    )
    costs = ProbsToCostsTask(tmp_folder, config_dir, dependencies=[feats])
    assert build([costs])

    wf = SizeFilterAndGraphWatershedWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="seg",
        output_path=path, output_key="gw_filtered",
        min_size=1000, relabel=True,
    )
    assert build([wf])
    got = file_reader(path, "r")["gw_filtered"][:]
    # the tiny fragment adopted segment 1's label (weak shared boundary)
    assert (np.unique(got[labels == 7]) == np.unique(got[labels == 1])).all()
    assert (np.unique(got[labels == 2]) != np.unique(got[labels == 1])).all()
    ids = np.unique(got)
    assert ids[0] >= 1 and len(ids) == 2  # relabeled, tiny id gone
