"""The device-resident watershed→RAG fusion (ShardedWsProblemTask).

Parity contract: the fused task must produce EXACTLY what the split
pipeline (ShardedWatershedTask → ShardedProblemTask) produces — same ws
dataset, same node table, same edges, same features — while uploading the
boundary volume once and never re-reading it from the store.
"""

import numpy as np
import pytest
from scipy import ndimage

import jax

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader

N_DEV = 8

WS_CONF = {"threshold": 0.6, "sigma_seeds": 1.0, "size_filter": 10,
           "max_edges": 4096}


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _require_devices():
    if jax.device_count() < N_DEV:
        pytest.skip(f"needs {N_DEV} devices, have {jax.device_count()}")


def _volume(rng, shape=(24, 32, 32)):
    raw = ndimage.gaussian_filter(rng.random(shape), (1.0, 2.0, 2.0))
    raw = (raw - raw.min()) / (raw.max() - raw.min())
    return raw.astype("float32")


def _scratch(tmp_folder):
    from cluster_tools_tpu.tasks.base import scratch_store_path

    return file_reader(scratch_store_path(tmp_folder), "r")


def _run_split(path, tmp_path, tag):
    from cluster_tools_tpu.tasks.features import ShardedProblemTask
    from cluster_tools_tpu.tasks.watershed import ShardedWatershedTask

    config_dir = str(tmp_path / f"configs_{tag}")
    tmp_folder = str(tmp_path / f"tmp_{tag}")
    cfg.write_global_config(
        config_dir, {"block_shape": [12, 16, 16], "target": "tpu"}
    )
    cfg.write_config(config_dir, "sharded_watershed", dict(WS_CONF))
    cfg.write_config(config_dir, "sharded_problem", dict(WS_CONF))
    ws = ShardedWatershedTask(
        tmp_folder, config_dir,
        input_path=path, input_key="bnd",
        output_path=path, output_key=f"ws_{tag}",
    )
    problem = ShardedProblemTask(
        tmp_folder, config_dir, dependencies=[ws],
        input_path=path, input_key="bnd",
        labels_path=path, labels_key=f"ws_{tag}",
    )
    assert build([problem])
    return tmp_folder


def _run_fused(path, tmp_path, tag):
    from cluster_tools_tpu.tasks.features import ShardedWsProblemTask

    config_dir = str(tmp_path / f"configs_{tag}")
    tmp_folder = str(tmp_path / f"tmp_{tag}")
    cfg.write_global_config(
        config_dir, {"block_shape": [12, 16, 16], "target": "tpu"}
    )
    cfg.write_config(config_dir, "sharded_ws_problem", dict(WS_CONF))
    task = ShardedWsProblemTask(
        tmp_folder, config_dir,
        input_path=path, input_key="bnd",
        output_path=path, output_key=f"ws_{tag}",
    )
    assert build([task])
    return tmp_folder


def test_fused_matches_split_pipeline(tmp_path, rng):
    _require_devices()
    raw = _volume(rng)
    path = str(tmp_path / "d.n5")
    file_reader(path).create_dataset("bnd", data=raw, chunks=(12, 16, 16))

    split_tmp = _run_split(path, tmp_path, "split")
    fused_tmp = _run_fused(path, tmp_path, "fused")

    f = file_reader(path, "r")
    ws_split = f["ws_split"][:]
    ws_fused = f["ws_fused"][:]
    np.testing.assert_array_equal(ws_fused, ws_split)
    assert len(np.unique(ws_fused)) > 2  # a real fragmentation

    a, b = _scratch(split_tmp), _scratch(fused_tmp)
    np.testing.assert_array_equal(a["graph/nodes"][:], b["graph/nodes"][:])
    np.testing.assert_array_equal(a["graph/edges"][:], b["graph/edges"][:])
    np.testing.assert_allclose(
        a["features/edges"][:], b["features/edges"][:], rtol=1e-5, atol=1e-6
    )
    assert (
        a["graph/edges"].attrs["n_nodes"] == b["graph/edges"].attrs["n_nodes"]
    )


def test_full_workflow_with_sharded_ws(tmp_path, rng):
    """MulticutSegmentationWorkflow(sharded_problem=True, sharded_ws=True)
    end-to-end: one fused front task, global solve, written segmentation."""
    from cluster_tools_tpu.workflows import MulticutSegmentationWorkflow

    _require_devices()
    raw = _volume(rng)
    path = str(tmp_path / "d.n5")
    file_reader(path).create_dataset("bnd", data=raw, chunks=(12, 16, 16))
    config_dir = str(tmp_path / "configs_wf")
    tmp_folder = str(tmp_path / "tmp_wf")
    cfg.write_global_config(
        config_dir, {"block_shape": [12, 16, 16], "target": "tpu"}
    )
    cfg.write_config(config_dir, "sharded_ws_problem", dict(WS_CONF))
    wf = MulticutSegmentationWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="bnd",
        ws_path=path, ws_key="ws_wf",
        output_path=path, output_key="seg_wf",
        sharded_problem=True, sharded_ws=True,
    )
    assert build([wf])
    f = file_reader(path, "r")
    seg = f["seg_wf"][:]
    ws = f["ws_wf"][:]
    assert seg.shape == raw.shape
    # the multicut merges fragments: a coarsening of the ws partition
    n_seg = len(np.unique(seg[seg > 0]))
    n_ws = len(np.unique(ws[ws > 0]))
    assert 0 < n_seg <= n_ws
    # background is preserved
    np.testing.assert_array_equal(seg == 0, ws == 0)


def test_sharded_ws_flag_validation(tmp_path):
    from cluster_tools_tpu.workflows import MulticutSegmentationWorkflow

    with pytest.raises(ValueError, match="sharded_problem"):
        MulticutSegmentationWorkflow(
            str(tmp_path / "t"), str(tmp_path / "c"),
            input_path="x.n5", input_key="bnd",
            ws_path="x.n5", ws_key="ws",
            output_path="x.n5", output_key="seg",
            sharded_ws=True,
        ).requires()
    with pytest.raises(ValueError, match="mask"):
        MulticutSegmentationWorkflow(
            str(tmp_path / "t"), str(tmp_path / "c"),
            input_path="x.n5", input_key="bnd",
            ws_path="x.n5", ws_key="ws",
            output_path="x.n5", output_key="seg",
            mask_path="x.n5", mask_key="m",
            sharded_problem=True, sharded_ws=True,
        ).requires()
    # a precomputed watershed must never be silently overwritten
    with pytest.raises(ValueError, match="skip_ws"):
        MulticutSegmentationWorkflow(
            str(tmp_path / "t"), str(tmp_path / "c"),
            input_path="x.n5", input_key="bnd",
            ws_path="x.n5", ws_key="ws",
            output_path="x.n5", output_key="seg",
            skip_ws=True, sharded_problem=True, sharded_ws=True,
        ).requires()
