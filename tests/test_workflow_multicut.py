"""Graph/features/multicut pipeline tests.

Idioms from the reference suite (SURVEY.md §4): recompute-and-compare for the
graph (test/graph/test_graph.py), invariants + segment-count sanity for the
multicut workflow (test/workflows/multicut_workflow.py:19-28)."""

import os

import numpy as np
import pytest

from cluster_tools_tpu.ops.rag import block_edges, boundary_edge_features
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import (
    GraphWorkflow,
    MulticutSegmentationWorkflow,
)


@pytest.fixture
def cells_volume(tmp_path, rng):
    """Voronoi cells with gaussian boundary ridges — ground truth known."""
    shape = (24, 48, 48)
    pts = rng.integers(0, 48, (30, 3))
    pts[:, 0] = pts[:, 0] % shape[0]
    zz, yy, xx = np.mgrid[: shape[0], : shape[1], : shape[2]]
    d = np.full(shape, 1e9)
    second = np.full(shape, 1e9)
    gt = np.zeros(shape, dtype=np.uint64)
    for i, p in enumerate(pts):
        dist = (zz - p[0]) ** 2 + (yy - p[1]) ** 2 + (xx - p[2]) ** 2
        newmin = dist < d
        second = np.where(newmin, d, np.minimum(second, dist))
        gt = np.where(newmin, i + 1, gt)
        d = np.where(newmin, dist, d)
    bnd = np.exp(-((np.sqrt(second) - np.sqrt(d)) ** 2) / 8.0).astype("float32")
    path = str(tmp_path / "d.n5")
    f = file_reader(path)
    f.create_dataset("bnd", data=bnd, chunks=(12, 24, 24))
    f.create_dataset("gt", data=gt, chunks=(12, 24, 24))
    return path, bnd, gt


class TestRagOps:
    def test_block_edges_oracle(self, rng):
        labels = rng.integers(0, 5, (10, 10, 10)).astype(np.uint64)
        edges = block_edges(labels)
        # oracle: brute-force neighbor scan
        want = set()
        for axis in range(3):
            for idx in np.ndindex(*[s - (1 if a == axis else 0)
                                    for a, s in enumerate(labels.shape)]):
                p = labels[idx]
                q_idx = tuple(i + (1 if a == axis else 0) for a, i in enumerate(idx))
                q = labels[q_idx]
                if p != q and p != 0 and q != 0:
                    want.add((min(p, q), max(p, q)))
        got = {tuple(e) for e in edges}
        assert got == want

    def test_boundary_features_stats(self):
        labels = np.zeros((4, 4), dtype=np.uint64)
        labels[:, :2] = 1
        labels[:, 2:] = 2
        values = np.zeros((4, 4))
        values[:, 1] = 0.25  # left side of the face
        values[:, 2] = 0.75  # right side
        edges, feats = boundary_edge_features(labels, values)
        assert edges.shape == (1, 2) and tuple(edges[0]) == (1, 2)
        mean, var, mn, *qs, mx, count = feats[0]
        assert mean == pytest.approx(0.5)
        assert mn == pytest.approx(0.25) and mx == pytest.approx(0.75)
        assert count == 8  # 4 faces x 2 sides


class TestFeatureMergeAccuracy:
    def test_affinity_owner_mask_keeps_cross_block_pairs(self):
        """Cross-face pairs of negative offsets must be owned by the lower
        block (min-corner rule), not dropped by the src-voxel mask."""
        from cluster_tools_tpu.ops.rag import affinity_edge_features

        labels = np.zeros((1, 1, 4), dtype=np.uint64)
        labels[..., :2] = 1
        labels[..., 2:] = 2
        affs = np.full((1, 1, 1, 4), 0.7, dtype=np.float64)
        offsets = [[0, 0, -1]]
        # whole-volume oracle
        edges_all, feats_all = affinity_edge_features(labels, affs, offsets)
        assert tuple(edges_all[0]) == (1, 2) and feats_all[0, 9] == 1
        # two x-blocks of width 2, each read with a +1 upper halo
        total = np.zeros(0)
        counts = 0.0
        for begin in (0, 2):
            end = min(begin + 3, 4)  # +1 halo, clipped
            lab = labels[..., begin:end]
            aff = affs[..., begin:end]
            edges, feats = affinity_edge_features(
                lab, aff, offsets, owner_shape=(1, 1, 2)
            )
            if edges.shape[0]:
                assert tuple(edges[0]) == (1, 2)
                counts += feats[0, 9]
        assert counts == 1.0  # seen exactly once across blocks

    def test_out_of_range_values_fall_back_gracefully(self):
        """Float data outside [0,1] must not collapse quantiles to min
        (the histogram sketch's bin domain check)."""
        from cluster_tools_tpu.ops.rag import (
            HIST_BINS,
            boundary_edge_features,
            merge_edge_features,
        )

        labels = np.zeros((1, 2, 4), dtype=np.uint64)
        labels[:, 0] = 1
        labels[:, 1] = 2
        values = np.zeros((1, 2, 4))
        values[:, 0] = [10.0, 50.0, 100.0, 240.0]
        values[:, 1] = [10.0, 50.0, 100.0, 240.0]
        edges, feats, hists = boundary_edge_features(
            labels, values, hist_bins=HIST_BINS
        )
        merged = merge_edge_features(
            [np.zeros(len(edges), dtype=np.int64)], [feats], 1, [hists]
        )
        # q50 must stay in the data's interior, not collapse to min
        assert 10.0 < merged[0, 5] < 240.0
    def test_device_kernel_matches_numpy(self, rng):
        """The fused device RAG accumulator must agree with the numpy path:
        identical edges/counts/min/max/quantiles, moments to f32 tolerance."""
        from cluster_tools_tpu.ops.rag import (
            HIST_BINS,
            boundary_edge_features,
            boundary_edge_features_tpu,
        )

        labels = rng.integers(0, 25, (12, 24, 24)).astype(np.uint64) * 100
        values = rng.random((12, 24, 24)).astype(np.float32)
        want_edges, want = boundary_edge_features(
            labels, values.astype(np.float64)
        )
        got_edges, got, got_hist = boundary_edge_features_tpu(
            labels, values, hist_bins=HIST_BINS
        )
        np.testing.assert_array_equal(got_edges, want_edges)
        # exact columns: count; near-exact: min/max/quantiles (f32 rounding)
        np.testing.assert_array_equal(got[:, 9], want[:, 9])
        np.testing.assert_allclose(got[:, 2], want[:, 2], atol=1e-6)
        np.testing.assert_allclose(got[:, 8], want[:, 8], atol=1e-6)
        np.testing.assert_allclose(got[:, 3:8], want[:, 3:8], atol=1e-6)
        np.testing.assert_allclose(got[:, 0], want[:, 0], rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(got[:, 1], want[:, 1], rtol=1e-3, atol=1e-4)
        # histogram sketch identical to the numpy-side sketch
        _, _, want_hist = boundary_edge_features(
            labels, values.astype(np.float64), hist_bins=HIST_BINS
        )
        np.testing.assert_array_equal(got_hist, want_hist)

    def test_device_kernel_sample_compaction(self, rng):
        """Pre-sort compaction (max_samples) must be invisible in the
        results, report the TRUE sample count, and only drop rows when the
        cap is deliberately undersized."""
        import jax.numpy as jnp

        from cluster_tools_tpu.ops.rag import (
            boundary_edge_features_device,
            count_boundary_samples,
            sample_capacity,
        )

        labels = rng.integers(0, 20, (8, 16, 16)).astype(np.int32)
        values = rng.random((8, 16, 16)).astype(np.float32)
        n_valid = count_boundary_samples(labels)
        assert n_valid > 0
        ref = boundary_edge_features_device(
            jnp.asarray(labels), jnp.asarray(values), max_edges=1024
        )
        cap = sample_capacity(n_valid)
        assert cap >= n_valid
        got = boundary_edge_features_device(
            jnp.asarray(labels), jnp.asarray(values), max_edges=1024,
            max_samples=cap,
        )
        for r, g in zip(ref, got):
            np.testing.assert_allclose(np.asarray(r), np.asarray(g), atol=1e-6)
        assert int(got[5]) == n_valid  # n_samples is the pre-compaction truth
        # undersized cap: the true count still comes back larger than the
        # cap, so a caller can detect the dropped rows
        small = boundary_edge_features_device(
            jnp.asarray(labels), jnp.asarray(values), max_edges=1024,
            max_samples=max(n_valid // 2, 1),
        )
        assert int(small[5]) == n_valid > n_valid // 2

    def test_device_kernel_uint64_ids_no_background(self, rng):
        """Blocks without label 0 and with block-offset-scale uint64 ids must
        keep exact uint64 edge ids (a bare [0]-prepend would promote the id
        table to float64 and round ids >= 2^53)."""
        from cluster_tools_tpu.ops.rag import boundary_edge_features_tpu

        base = np.uint64(2**60)
        labels = (
            rng.integers(1, 9, (6, 8, 8)).astype(np.uint64) + base
        )
        values = rng.random((6, 8, 8)).astype(np.float32)
        edges, feats = boundary_edge_features_tpu(labels, values)
        assert edges.dtype == np.uint64
        assert (edges > base).all()

    def test_device_kernel_owner_mask_matches_numpy(self, rng):
        from cluster_tools_tpu.ops.rag import (
            HIST_BINS,
            boundary_edge_features,
            boundary_edge_features_tpu,
        )

        labels = rng.integers(0, 15, (9, 17, 17)).astype(np.uint64)
        values = rng.random((9, 17, 17)).astype(np.float32)
        owner = (8, 16, 16)  # +1 upper halo read
        want_edges, want = boundary_edge_features(
            labels, values.astype(np.float64), owner_shape=owner
        )
        got_edges, got, _ = boundary_edge_features_tpu(
            labels, values, hist_bins=HIST_BINS, owner_shape=owner
        )
        np.testing.assert_array_equal(got_edges, want_edges)
        np.testing.assert_array_equal(got[:, 9], want[:, 9])
        np.testing.assert_allclose(got[:, 0], want[:, 0], rtol=1e-4, atol=1e-5)

    def test_feature_workflow_device_accumulation_parity(self, tmp_path, rng):
        """The device_accumulation knob must produce the same merged features
        as the numpy path (counts exact, moments to f32 tolerance)."""
        from cluster_tools_tpu.workflows import (
            EdgeFeaturesWorkflow,
            GraphWorkflow,
        )

        labels = rng.integers(1, 30, (16, 24, 24)).astype(np.uint64)
        bnd = rng.random((16, 24, 24)).astype(np.float32)
        path = str(tmp_path / "d.n5")
        f = file_reader(path)
        f.create_dataset("seg", data=labels, chunks=(8, 12, 12))
        f.create_dataset("bnd", data=bnd, chunks=(8, 12, 12))
        merged = {}
        for device in (False, True):
            config_dir = str(tmp_path / f"configs{device}")
            tmp_folder = str(tmp_path / f"tmp{device}")
            cfg.write_global_config(config_dir, {"block_shape": [8, 12, 12]})
            cfg.write_config(
                config_dir, "block_edge_features",
                {"device_accumulation": device},
            )
            graph = GraphWorkflow(
                tmp_folder, config_dir, input_path=path, input_key="seg"
            )
            wf = EdgeFeaturesWorkflow(
                tmp_folder, config_dir,
                input_path=path, input_key="bnd",
                labels_path=path, labels_key="seg",
                dependencies=[graph],
            )
            assert build([wf])
            store = file_reader(os.path.join(tmp_folder, "data.zarr"), "r")
            merged[device] = store["features/edges"][:]
        np.testing.assert_array_equal(merged[False][:, 9], merged[True][:, 9])
        np.testing.assert_allclose(
            merged[False], merged[True], rtol=1e-3, atol=1e-5
        )

    def test_blocked_quantiles_match_single_shot(self, tmp_path, rng):
        """VERDICT item 7: the blocked+merged 10-feature vectors must track a
        single-shot whole-volume recompute — exact for count/mean/var/min/max,
        < 1 histogram bin (plus interpolation slack) on every quantile."""
        from cluster_tools_tpu.ops.rag import HIST_BINS, boundary_edge_features
        from cluster_tools_tpu.runtime import build, config as cfg
        from cluster_tools_tpu.utils import file_reader
        from cluster_tools_tpu.workflows import (
            EdgeFeaturesWorkflow,
            GraphWorkflow,
        )

        shape = (24, 48, 48)
        labels = rng.integers(1, 60, (6, 12, 12)).astype(np.uint64)
        labels = np.kron(labels, np.ones((4, 4, 4), dtype=np.uint64))
        bnd = rng.random(shape).astype(np.float32)
        path = str(tmp_path / "d.n5")
        f = file_reader(path)
        f.create_dataset("seg", data=labels, chunks=(8, 16, 16))
        f.create_dataset("bnd", data=bnd, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        graph = GraphWorkflow(
            tmp_folder, config_dir, input_path=path, input_key="seg"
        )
        wf = EdgeFeaturesWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="bnd",
            labels_path=path, labels_key="seg",
            dependencies=[graph],
        )
        assert build([wf])
        store = file_reader(os.path.join(tmp_folder, "data.zarr"), "r")
        nodes = store["graph/nodes"][:]
        edges = store["graph/edges"][:]
        merged = store["features/edges"][:]

        want_edges, want = boundary_edge_features(
            labels, bnd.astype(np.float64)
        )
        by_pair = {tuple(e): i for i, e in enumerate(want_edges)}
        tol = 1.0 / HIST_BINS + 1e-6
        checked = 0
        for gid, (ui, vi) in enumerate(edges):
            i = by_pair[(nodes[ui], nodes[vi])]
            # exact columns
            np.testing.assert_allclose(
                merged[gid, [0, 1, 2, 8, 9]],
                want[i, [0, 1, 2, 8, 9]],
                rtol=1e-9, atol=1e-9,
                err_msg=f"edge {gid} exact columns",
            )
            # quantiles within one histogram bin of the exact sample quantile
            drift = np.abs(merged[gid, 3:8] - want[i, 3:8])
            assert (drift <= tol).all(), (
                f"edge {gid} quantile drift {drift} > {tol}"
            )
            checked += 1
        assert checked == len(edges) == len(want_edges)


class TestGraphWorkflow:
    def test_graph_matches_recompute(self, tmp_path, rng):
        path = str(tmp_path / "g.n5")
        labels = rng.integers(1, 40, (16, 32, 32)).astype(np.uint64)
        file_reader(path).create_dataset("seg", data=labels, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        wf = GraphWorkflow(
            tmp_folder, config_dir, input_path=path, input_key="seg"
        )
        assert build([wf])
        store = file_reader(os.path.join(tmp_folder, "data.zarr"), "r")
        nodes = store["graph/nodes"][:]
        edges = store["graph/edges"][:]
        # recompute on the full volume
        want_edges = block_edges(labels)
        want_nodes = np.unique(labels)
        np.testing.assert_array_equal(nodes, want_nodes)
        got_label_edges = nodes[edges]
        got = {tuple(e) for e in got_label_edges}
        want = {tuple(e) for e in want_edges}
        assert got == want

    def test_scale_pyramid_merge_matches_flat(self, tmp_path, rng):
        """VERDICT item 8: n_scales=2 pyramid merge must produce the identical
        global graph as the flat single merge (and as the recompute oracle)."""
        from cluster_tools_tpu.ops.rag import block_edges

        labels = rng.integers(1, 40, (16, 32, 32)).astype(np.uint64)
        path = str(tmp_path / "g.n5")
        file_reader(path).create_dataset("seg", data=labels, chunks=(4, 8, 8))
        results = {}
        for n_scales in (1, 3):
            config_dir = str(tmp_path / f"configs{n_scales}")
            tmp_folder = str(tmp_path / f"tmp{n_scales}")
            cfg.write_global_config(config_dir, {"block_shape": [4, 8, 8]})
            wf = GraphWorkflow(
                tmp_folder, config_dir, input_path=path, input_key="seg",
                n_scales=n_scales,
            )
            assert build([wf])
            store = file_reader(os.path.join(tmp_folder, "data.zarr"), "r")
            results[n_scales] = (
                store["graph/nodes"][:], store["graph/edges"][:]
            )
        np.testing.assert_array_equal(results[1][0], results[3][0])
        np.testing.assert_array_equal(results[1][1], results[3][1])
        want = {tuple(e) for e in block_edges(labels)}
        nodes, edges = results[3]
        assert {tuple(e) for e in nodes[edges]} == want

    def test_graph_keeps_isolated_fragments(self, tmp_path):
        # a fragment fully surrounded by background has no RAG edge but must
        # still be a graph node, or downstream writes drop it to 0
        labels = np.zeros((16, 32, 32), dtype=np.uint64)
        labels[2:6, 2:8, 2:8] = 1
        labels[2:6, 8:14, 2:8] = 2   # touches 1
        labels[10:14, 20:26, 20:26] = 7  # isolated
        path = str(tmp_path / "g.n5")
        file_reader(path).create_dataset("seg", data=labels, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        wf = GraphWorkflow(tmp_folder, config_dir, input_path=path, input_key="seg")
        assert build([wf])
        store = file_reader(os.path.join(tmp_folder, "data.zarr"), "r")
        nodes = store["graph/nodes"][:]
        edges = store["graph/edges"][:]
        np.testing.assert_array_equal(nodes, [1, 2, 7])
        np.testing.assert_array_equal(nodes[edges], [[1, 2]])


class TestMulticutWorkflow:
    def test_two_scale_matches_single_scale(self, tmp_path, cells_volume):
        # regression for the scale>=1 id-space bug: edges at scale s are in
        # scale-s cluster coordinates; double-mapping them through
        # node_labeling corrupted the hierarchy.  On this easy volume the
        # 2-scale hierarchical solve must reproduce the 1-scale partition.
        path, bnd, gt = cells_volume
        segs = {}
        for n_scales in (1, 2):
            config_dir = str(tmp_path / f"c{n_scales}")
            tmp_folder = str(tmp_path / f"t{n_scales}")
            cfg.write_global_config(config_dir, {"block_shape": [12, 24, 24]})
            cfg.write_config(
                config_dir, "watershed",
                {"threshold": 0.4, "sigma_seeds": 1.0, "size_filter": 5,
                 "apply_dt_2d": False, "apply_ws_2d": False, "halo": [2, 4, 4]},
            )
            wf = MulticutSegmentationWorkflow(
                tmp_folder, config_dir,
                input_path=path, input_key="bnd",
                ws_path=path, ws_key=f"mws{n_scales}",
                output_path=path, output_key=f"mseg{n_scales}",
                n_scales=n_scales,
            )
            assert build([wf])
            segs[n_scales] = file_reader(path, "r")[f"mseg{n_scales}"][:]
        a, b = segs[1], segs[2]
        fg = (a > 0) & (b > 0)
        pairs = np.unique(np.stack([a[fg], b[fg]], axis=1), axis=0)
        n_a = len(np.unique(a[fg]))
        n_b = len(np.unique(b[fg]))
        assert len(pairs) == n_a == n_b  # identical partitions

    @pytest.mark.parametrize(
        "n_scales,target", [(1, "local"), (2, "local"), (1, "tpu")]
    )
    def test_segmentation_quality(self, tmp_path, cells_volume, n_scales, target):
        path, bnd, gt = cells_volume
        config_dir = str(tmp_path / f"configs{n_scales}{target}")
        tmp_folder = str(tmp_path / f"tmp{n_scales}{target}")
        cfg.write_global_config(
            config_dir, {"block_shape": [12, 24, 24], "target": target}
        )
        cfg.write_config(
            config_dir, "watershed",
            {"threshold": 0.4, "sigma_seeds": 1.0, "size_filter": 5,
             "apply_dt_2d": False, "apply_ws_2d": False, "halo": [2, 4, 4]},
        )
        wf = MulticutSegmentationWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="bnd",
            ws_path=path, ws_key=f"ws{n_scales}{target}",
            output_path=path, output_key=f"seg{n_scales}{target}",
            n_scales=n_scales,
        )
        assert build([wf])
        seg = file_reader(path, "r")[f"seg{n_scales}{target}"][:]
        ws = file_reader(path, "r")[f"ws{n_scales}{target}"][:]
        n_ws = len(np.unique(ws[ws > 0]))
        n_seg = len(np.unique(seg[seg > 0]))
        # reference idiom: multicut merges fragments, keeps >some segments
        assert 3 < n_seg < n_ws
        # quality: majority of gt cells map to a dominant segment (purity)
        from cluster_tools_tpu.ops.segment import max_overlap_assignment

        # only labeled voxels count — boundary ridges above the ws threshold
        # legitimately stay 0 (they are outside the flood mask)
        labeled = seg > 0
        votes = max_overlap_assignment(np.where(labeled, gt, 0), seg)
        purity = []
        for cell, dom in votes.items():
            sel = (gt == cell) & labeled
            purity.append((seg[sel] == dom).mean())
        assert np.mean(purity) > 0.6


class TestProblemAndSolutionComposites:
    """VERDICT r2 item 7: standalone ProblemWorkflow, sanity_checks wiring,
    and the SubSolutions/ReducedSolution composites
    (reference workflows.py:28,61-72; multicut_workflow.py:70,103)."""

    def test_problem_workflow_with_sanity_checks(self, tmp_path, cells_volume):
        from cluster_tools_tpu.tasks.watershed import WatershedTask
        from cluster_tools_tpu.workflows import ProblemWorkflow

        path, bnd, gt = cells_volume
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [12, 24, 24]})
        cfg.write_config(
            config_dir, "watershed",
            {"threshold": 0.4, "sigma_seeds": 1.0, "size_filter": 5},
        )
        ws = WatershedTask(
            tmp_folder, config_dir,
            input_path=path, input_key="bnd",
            output_path=path, output_key="pws",
        )
        wf = ProblemWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="bnd",
            ws_path=path, ws_key="pws",
            sanity_checks=True,
            dependencies=[ws],
        )
        assert build([wf])
        # costs were produced
        assert os.path.exists(os.path.join(tmp_folder, "costs.npy"))
        # and the sanity check actually ran (its status target is complete)
        status = os.path.join(tmp_folder, "status", "check_sub_graphs.status.json")
        assert os.path.exists(status)

    def test_problem_workflow_compute_costs_false(self, tmp_path, rng):
        from cluster_tools_tpu.workflows import ProblemWorkflow

        labels = rng.integers(1, 20, (8, 16, 16)).astype("uint64")
        bnd = rng.random((8, 16, 16)).astype("float32")
        path = str(tmp_path / "nc.n5")
        f = file_reader(path)
        f.create_dataset("seg", data=labels, chunks=(4, 8, 8))
        f.create_dataset("bnd", data=bnd, chunks=(4, 8, 8))
        config_dir = str(tmp_path / "configs_nc")
        tmp_folder = str(tmp_path / "tmp_nc")
        cfg.write_global_config(config_dir, {"block_shape": [4, 8, 8]})
        wf = ProblemWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="bnd",
            ws_path=path, ws_key="seg",
            compute_costs=False,
        )
        assert build([wf])
        store = file_reader(os.path.join(tmp_folder, "data.zarr"), "r")
        assert store["features/edges"][:].shape[0] > 0
        assert not os.path.exists(os.path.join(tmp_folder, "costs.npy"))

    def test_segmentation_workflow_sanity_checks_flag(
        self, tmp_path, cells_volume
    ):
        path, bnd, gt = cells_volume
        config_dir = str(tmp_path / "configs_sc")
        tmp_folder = str(tmp_path / "tmp_sc")
        cfg.write_global_config(config_dir, {"block_shape": [12, 24, 24]})
        cfg.write_config(
            config_dir, "watershed",
            {"threshold": 0.4, "sigma_seeds": 1.0, "size_filter": 5},
        )
        wf = MulticutSegmentationWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="bnd",
            ws_path=path, ws_key="scws",
            output_path=path, output_key="scseg",
            sanity_checks=True,
        )
        assert build([wf])
        assert os.path.exists(
            os.path.join(tmp_folder, "status", "check_sub_graphs.status.json")
        )
        seg = file_reader(path, "r")["scseg"][:]
        assert len(np.unique(seg[seg > 0])) > 3

    def _solved_problem(self, tmp_path, cells_volume, name):
        from cluster_tools_tpu.tasks.watershed import WatershedTask
        from cluster_tools_tpu.workflows import ProblemWorkflow

        path, bnd, gt = cells_volume
        config_dir = str(tmp_path / f"configs_{name}")
        tmp_folder = str(tmp_path / f"tmp_{name}")
        cfg.write_global_config(config_dir, {"block_shape": [12, 24, 24]})
        cfg.write_config(
            config_dir, "watershed",
            {"threshold": 0.4, "sigma_seeds": 1.0, "size_filter": 5},
        )
        ws = WatershedTask(
            tmp_folder, config_dir,
            input_path=path, input_key="bnd",
            output_path=path, output_key=f"ws_{name}",
        )
        problem = ProblemWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="bnd",
            ws_path=path, ws_key=f"ws_{name}",
            dependencies=[ws],
        )
        return path, config_dir, tmp_folder, problem

    def test_sub_solutions_workflow(self, tmp_path, cells_volume):
        from cluster_tools_tpu.workflows import SubSolutionsWorkflow

        path, config_dir, tmp_folder, problem = self._solved_problem(
            tmp_path, cells_volume, "ss"
        )
        wf = SubSolutionsWorkflow(
            tmp_folder, config_dir,
            ws_path=path, ws_key="ws_ss",
            output_path=path, output_key="subsol",
            n_scales=1, dependencies=[problem],
        )
        assert build([wf])
        sub = file_reader(path, "r")["subsol"][:]
        ws = file_reader(path, "r")["ws_ss"][:]
        assert sub.shape == ws.shape and sub.max() > 0
        # within one block, a ws fragment maps to exactly one sub-solution id
        blk = (slice(0, 12), slice(0, 24), slice(0, 24))
        frag = ws[blk] == ws[6, 12, 12]
        assert len(np.unique(sub[blk][frag])) == 1

    def test_reduced_solution_workflow(self, tmp_path, cells_volume):
        from cluster_tools_tpu.workflows import ReducedSolutionWorkflow

        path, config_dir, tmp_folder, problem = self._solved_problem(
            tmp_path, cells_volume, "rs"
        )
        wf = ReducedSolutionWorkflow(
            tmp_folder, config_dir,
            ws_path=path, ws_key="ws_rs",
            output_path=path, output_key="redsol",
            n_scales=1, dependencies=[problem],
        )
        assert build([wf])
        red = file_reader(path, "r")["redsol"][:]
        ws = file_reader(path, "r")["ws_rs"][:]
        fg = ws > 0
        # the reduced labeling is a coarsening of the fragments: every ws
        # fragment maps to exactly one reduced id
        pairs = np.unique(np.stack([ws[fg], red[fg]], axis=1), axis=0)
        assert len(pairs) == len(np.unique(ws[fg]))
        # and it merged something (scale-1 reduce ran) but kept >1 segment
        n_red = len(np.unique(red[fg]))
        assert 1 < n_red < len(np.unique(ws[fg]))
