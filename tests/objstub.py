"""Local stub object server for ctt-cloud tests, CI, and the bench.

Serves a directory tree over the small object-store HTTP subset the
``HttpBackend`` speaks (the wire schema is documented in
``cluster_tools_tpu/utils/store_backend.py``):

  ``GET /key``     → 200 + bytes; ``Range: bytes=a-b`` → 206 +
                     ``Content-Range``; ``If-None-Match`` matching the
                     current ETag → 304 with no body (the warm-hit
                     revalidation); a directory returns a JSON array
                     of child names with ``X-CTT-Dir: 1`` — paginated
                     with ``?limit=&marker=`` (names strictly after
                     ``marker``, ``X-CTT-List-Next`` on a clipped page);
                     404 if absent.
  ``HEAD /key``    → headers only: ``ETag`` (mtime_ns-size, changes on
                     every atomic replace), ``Last-Modified``,
                     ``Content-Length``, ``X-CTT-Dir`` for directories.
  ``PUT /key``     → atomic write (tmp + rename), parents created; 201.
                     With ``If-None-Match: *`` the PUT is create-only
                     (hard link, first writer wins): 412 when the key
                     already exists — the ``publish_once`` analog the
                     cross-host work-stealing leases ride.
  ``DELETE /key``  → unlink file / remove tree; 204 (404 if absent).

Chaos injection (hermetic flaky-network simulation, seeded so CI runs
are reproducible):

  * ``fail_rate`` — each request independently 503s with this
    probability (the client's backoff retry must absorb it);
  * ``slow_s`` — failed-coin requests stall this long before answering
    (latency spikes instead of hard errors) when ``slow_rate`` hits;
  * ``truncate_next(substr, times)`` — the next ``times`` GET responses
    whose path contains ``substr`` advertise the full ``Content-Length``
    but send only half the body and drop the connection — the truncated
    object read that must classify as ``CorruptChunk`` downstream.

ctt-diskless twin features:

  * **SigV4 verification mode** (``sigv4=(access, secret)`` /
    ``--sigv4-access-key``/``--sigv4-secret-key``): every request must
    carry a valid AWS Signature V4 ``Authorization`` header or it is
    rejected 403 (``AccessDenied``) — the signature is *recomputed here
    from the raw request*, independently of the client-side signer in
    ``cluster_tools_tpu/utils/sigv4.py``, so canonicalization drift
    between the two fails loudly in CI rather than silently matching.
  * **Multipart upload**: ``POST /key?uploads`` → ``UploadId`` XML;
    ``PUT /key?partNumber=N&uploadId=I`` stores parts (staged OUTSIDE
    the served root, so half-done uploads never appear in listings);
    ``POST /key?uploadId=I`` assembles parts in number order and
    atomically publishes the object; ``DELETE /key?uploadId=I`` aborts.
  * **Clock skew** (``clock_skew_s`` / ``--clock-skew-s``): shifts every
    ``Last-Modified`` header by the given seconds — a store whose wall
    clock disagrees with the readers', for exercising the remote-mtime
    staleness guards (a skewed-to-the-past store must never make a
    reader expire a live lease early).

Run in-process (``StubObjectStore(root, ...)`` context manager) or as a
subprocess for shell harnesses::

    python tests/objstub.py --root DIR --port-file F [--fail-rate 0.05]
                            [--seed 7] [--slow-s 0.05] [--slow-rate 0.0]
                            [--sigv4-access-key AK --sigv4-secret-key SK]
                            [--clock-skew-s -3600]

The subprocess writes ``<port>`` to ``--port-file`` once listening and
serves until SIGTERM.
"""

from __future__ import annotations

import argparse
import email.utils
import hashlib
import hmac
import json
import os
import random
import re
import shutil
import signal
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_RANGE_RE = re.compile(r"bytes=(\d+)-(\d*)$")
_AUTH_RE = re.compile(
    r"AWS4-HMAC-SHA256 Credential=([^/]+)/(\d{8})/([^/]+)/([^/]+)"
    r"/aws4_request,\s*SignedHeaders=([^,]+),\s*Signature=([0-9a-f]{64})$"
)


class _Policy:
    """Seeded chaos decisions shared by all handler threads."""

    def __init__(self, fail_rate=0.0, seed=0, slow_s=0.0, slow_rate=0.0):
        self.fail_rate = float(fail_rate)
        self.slow_s = float(slow_s)
        self.slow_rate = float(slow_rate)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._truncate = []  # [substr, remaining] pairs
        self.requests = 0
        self.failures = 0
        self.truncations = 0

    def decide(self, method: str, path: str):
        """(fail_503, slow, truncate) for one request."""
        with self._lock:
            self.requests += 1
            fail = (
                self.fail_rate > 0.0
                and self._rng.random() < self.fail_rate
            )
            slow = (
                self.slow_rate > 0.0
                and self._rng.random() < self.slow_rate
            )
            truncate = False
            if method == "GET" and not fail:
                for pair in self._truncate:
                    if pair[1] > 0 and pair[0] in path:
                        pair[1] -= 1
                        truncate = True
                        self.truncations += 1
                        break
            if fail:
                self.failures += 1
            return fail, slow, truncate

    def truncate_next(self, substr: str, times: int = 1) -> None:
        with self._lock:
            self._truncate.append([substr, int(times)])


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "ctt-objstub/1"

    # -- helpers -------------------------------------------------------------

    def _fs_path(self):
        """The served filesystem path for the request target, confined to
        the root (traversal-safe)."""
        raw = self.path.split("?", 1)[0].split("#", 1)[0]
        from urllib.parse import unquote

        rel = os.path.normpath(unquote(raw).lstrip("/"))
        if rel.startswith(".."):
            return None
        return os.path.join(self.server.root, rel)

    def _send(self, status, body=b"", headers=(), include_body=True):
        self.send_response(status)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if include_body and body:
            self.wfile.write(body)

    def _object_headers(self, p):
        st = os.stat(p)
        # clock_skew_s simulates a store wall clock that disagrees with
        # the readers' — only Last-Modified shifts (staleness input); the
        # ETag stays a pure content-version token
        return [
            ("ETag", f'"{st.st_mtime_ns:x}-{st.st_size:x}"'),
            ("Last-Modified", email.utils.formatdate(
                st.st_mtime + self.server.clock_skew_s, usegmt=True
            )),
        ]

    def _query(self):
        return urllib.parse.parse_qs(
            urllib.parse.urlsplit(self.path).query, keep_blank_values=True
        )

    # -- sigv4 verification (independent of the client-side signer) ----------

    def _reject_auth(self, reason):
        length = int(self.headers.get("Content-Length", 0))
        if length:
            self.rfile.read(length)  # keep-alive hygiene before failing
        body = (
            f"<Error><Code>AccessDenied</Code>"
            f"<Message>{reason}</Message></Error>"
        ).encode()
        self.send_response(403)
        self.send_header("Content-Type", "application/xml")
        self.send_header("Content-Length", str(len(body)))
        self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)
        self.close_connection = True
        return False

    def _verify_sigv4(self):
        """True when verification is off or the request signature checks
        out; otherwise answers 403 and returns False.  Recomputes the
        SigV4 signature from the RAW request (path, query, received
        headers) with its own hashing code — the independent twin of the
        client signer, so canonicalization drift fails loudly."""
        creds = self.server.sigv4_creds
        if creds is None:
            return True
        m = _AUTH_RE.match(self.headers.get("Authorization", "").strip())
        if m is None:
            return self._reject_auth("missing or malformed Authorization")
        access, datestamp, region, service, signed_names, signature = (
            m.groups()
        )
        if access != creds["access_key"]:
            return self._reject_auth("unknown access key")
        names = signed_names.split(";")
        if not {"host", "x-amz-content-sha256", "x-amz-date"} <= set(names):
            return self._reject_auth("required headers not signed")
        raw_path, _, raw_query = self.path.partition("?")
        params = [
            p if "=" in p else p + "="
            for p in raw_query.split("&") if p
        ]
        canonical = "\n".join([
            self.command,
            raw_path,
            "&".join(sorted(params)),
            "".join(
                f"{n}:{(self.headers.get(n) or '').strip()}\n"
                for n in names
            ),
            signed_names,
            self.headers.get("x-amz-content-sha256") or "",
        ])
        scope = f"{datestamp}/{region}/{service}/aws4_request"
        string_to_sign = "\n".join([
            "AWS4-HMAC-SHA256",
            self.headers.get("x-amz-date") or "",
            scope,
            hashlib.sha256(canonical.encode()).hexdigest(),
        ])
        key = ("AWS4" + creds["secret_key"]).encode()
        for step in (datestamp, region, service, "aws4_request"):
            key = hmac.new(key, step.encode(), hashlib.sha256).digest()
        expected = hmac.new(
            key, string_to_sign.encode(), hashlib.sha256
        ).hexdigest()
        if not hmac.compare_digest(expected, signature):
            return self._reject_auth("signature mismatch")
        return True

    # -- multipart upload (parts staged OUTSIDE the served root) -------------

    def _mpu_dir(self, upload_id, create=False):
        d = os.path.join(self.server.mpu_root, os.path.basename(upload_id))
        if create:
            os.makedirs(d, exist_ok=True)
        return d if os.path.isdir(d) else None

    def _chaos(self, drain: bool = False):
        fail, slow, truncate = self.server.policy.decide(
            self.command, self.path
        )
        if slow:
            time.sleep(self.server.policy.slow_s)
        if fail:
            if drain:
                # consume the request body before failing it: an unread
                # PUT payload on a keep-alive socket would otherwise be
                # parsed as the NEXT request line
                length = int(self.headers.get("Content-Length", 0))
                if length:
                    self.rfile.read(length)
            self.send_response(503)
            self.send_header("Content-Length", "0")
            self.send_header("Connection", "close")
            self.end_headers()
            self.close_connection = True
            return True, truncate
        return False, truncate

    # -- verbs ---------------------------------------------------------------

    def do_GET(self):  # noqa: N802 (http.server naming)
        failed, truncate = self._chaos()
        if failed:
            return
        if not self._verify_sigv4():
            return
        p = self._fs_path()
        if p is None or not os.path.exists(p):
            self._send(404, b"not found")
            return
        if os.path.isdir(p):
            # listing with ?limit=&marker= continuation: names strictly
            # after ``marker``, at most ``limit`` per page, the last name
            # of a clipped page echoed back as X-CTT-List-Next
            params = urllib.parse.parse_qs(
                urllib.parse.urlsplit(self.path).query
            )
            names = sorted(os.listdir(p))
            marker = params.get("marker", [None])[0]
            if marker is not None:
                names = [n for n in names if n > marker]
            headers = [
                ("Content-Type", "application/json"), ("X-CTT-Dir", "1"),
            ]
            try:
                limit = int(params.get("limit", [0])[0])
            except ValueError:
                limit = 0
            if limit > 0 and len(names) > limit:
                names = names[:limit]
                headers.append(("X-CTT-List-Next", names[-1]))
            self._send(200, json.dumps(names).encode(), headers=headers)
            return
        headers = self._object_headers(p)
        # conditional GET: a matching If-None-Match answers 304 with no
        # body — the warm-hit revalidation the client's decoded-chunk LRU
        # rides instead of a separate HEAD probe
        inm = self.headers.get("If-None-Match")
        if inm and inm.strip() == dict(headers)["ETag"]:
            self._send(304, headers=headers)
            return
        with open(p, "rb") as f:
            data = f.read()
        status = 200
        rng = self.headers.get("Range")
        if rng:
            m = _RANGE_RE.match(rng.strip())
            if m:
                lo = int(m.group(1))
                hi = int(m.group(2)) if m.group(2) else len(data) - 1
                hi = min(hi, len(data) - 1)
                if lo <= hi:
                    headers.append((
                        "Content-Range", f"bytes {lo}-{hi}/{len(data)}"
                    ))
                    data = data[lo: hi + 1]
                    status = 206
        if truncate and len(data) > 1:
            # advertise the full length, deliver half, drop the socket:
            # the truncated-object read the client must classify
            self.send_response(status)
            for k, v in headers:
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(data)))
            self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(data[: len(data) // 2])
            self.close_connection = True
            return
        self._send(status, data, headers=headers)

    def do_HEAD(self):  # noqa: N802
        failed, _ = self._chaos()
        if failed:
            return
        if not self._verify_sigv4():
            return
        p = self._fs_path()
        if p is None or not os.path.exists(p):
            self._send(404)
            return
        if os.path.isdir(p):
            self._send(200, headers=[("X-CTT-Dir", "1")])
            return
        st = os.stat(p)
        self.send_response(200)
        for k, v in self._object_headers(p):
            self.send_header(k, v)
        self.send_header("Content-Length", str(st.st_size))
        self.end_headers()

    def do_PUT(self):  # noqa: N802
        failed, _ = self._chaos(drain=True)
        if failed:
            return
        if not self._verify_sigv4():
            return
        p = self._fs_path()
        if p is None:
            self._send(404, b"not found")
            return
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else b""
        query = self._query()
        part_number = query.get("partNumber", [None])[0]
        upload_id = query.get("uploadId", [None])[0]
        if part_number is not None and upload_id is not None:
            updir = self._mpu_dir(upload_id)
            if updir is None:
                self._send(404, b"no such upload")
                return
            try:
                number = int(part_number)
            except ValueError:
                self._send(400, b"bad partNumber")
                return
            tmp = os.path.join(updir, f"part.{number:06d}.tmp")
            with open(tmp, "wb") as f:
                f.write(body)
            os.replace(tmp, os.path.join(updir, f"part.{number:06d}"))
            self._send(200, headers=[("ETag", f'"{number}"')])
            return
        if self.headers.get("If-None-Match", "").strip() == "*":
            # create-only PUT: the publish_once analog — first writer
            # stores, every later writer gets 412 (body already drained,
            # keep-alive hygiene)
            os.makedirs(os.path.dirname(p), exist_ok=True)
            tmp = p + f".put{threading.get_ident()}"
            with open(tmp, "wb") as f:
                f.write(body)
            try:
                os.link(tmp, p)
            except FileExistsError:
                self._send(412)
                return
            finally:
                os.unlink(tmp)
            self._send(201)
            return
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + f".put{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(body)
        os.replace(tmp, p)
        self._send(201)

    def do_POST(self):  # noqa: N802
        failed, _ = self._chaos(drain=True)
        if failed:
            return
        if not self._verify_sigv4():
            return
        p = self._fs_path()
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else b""
        del body  # the complete manifest is advisory here: parts are
        # assembled in partNumber order, the stub's source of truth
        if p is None:
            self._send(404, b"not found")
            return
        query = self._query()
        if "uploads" in query:
            upload_id = f"{time.time_ns():x}-{threading.get_ident():x}"
            self._mpu_dir(upload_id, create=True)
            xml = (
                "<InitiateMultipartUploadResult>"
                f"<UploadId>{upload_id}</UploadId>"
                "</InitiateMultipartUploadResult>"
            )
            self._send(200, xml.encode(),
                       headers=[("Content-Type", "application/xml")])
            return
        upload_id = query.get("uploadId", [None])[0]
        if upload_id is not None:
            updir = self._mpu_dir(upload_id)
            if updir is None:
                self._send(404, b"no such upload")
                return
            parts = sorted(
                n for n in os.listdir(updir)
                if n.startswith("part.") and not n.endswith(".tmp")
            )
            os.makedirs(os.path.dirname(p), exist_ok=True)
            tmp = p + f".mpu{threading.get_ident()}"
            with open(tmp, "wb") as out:
                for name in parts:
                    with open(os.path.join(updir, name), "rb") as part:
                        shutil.copyfileobj(part, out)
            os.replace(tmp, p)
            shutil.rmtree(updir, ignore_errors=True)
            self._send(200, b"<CompleteMultipartUploadResult/>",
                       headers=[("Content-Type", "application/xml")])
            return
        self._send(400, b"bad request")

    def do_DELETE(self):  # noqa: N802
        failed, _ = self._chaos()
        if failed:
            return
        if not self._verify_sigv4():
            return
        upload_id = self._query().get("uploadId", [None])[0]
        if upload_id is not None:
            updir = self._mpu_dir(upload_id)
            if updir is not None:
                shutil.rmtree(updir, ignore_errors=True)
            self._send(204)
            return
        p = self._fs_path()
        if p is None or not os.path.exists(p):
            self._send(404)
            return
        if os.path.isdir(p):
            shutil.rmtree(p)
        else:
            os.unlink(p)
        self._send(204)

    def log_message(self, fmt, *args):  # quiet by default
        if os.environ.get("CTT_OBJSTUB_LOG"):
            BaseHTTPRequestHandler.log_message(self, fmt, *args)


class StubObjectStore:
    """In-process stub server: ``with StubObjectStore(root) as url: ...``
    where ``url`` is the origin (``http://127.0.0.1:<port>``)."""

    def __init__(self, root, fail_rate=0.0, seed=0, slow_s=0.0,
                 slow_rate=0.0, sigv4=None, clock_skew_s=0.0):
        os.makedirs(root, exist_ok=True)
        self.root = os.path.abspath(root)
        self.policy = _Policy(fail_rate, seed, slow_s, slow_rate)
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        self.httpd.daemon_threads = True
        self.httpd.root = self.root
        self.httpd.policy = self.policy
        # sigv4: None (open store) or (access_key, secret_key) — every
        # request must then carry a valid V4 signature or gets 403
        self.httpd.sigv4_creds = (
            {"access_key": sigv4[0], "secret_key": sigv4[1]}
            if sigv4 else None
        )
        self.httpd.clock_skew_s = float(clock_skew_s)
        # multipart parts stage in a sibling dir, never inside the
        # served root (half-done uploads must not pollute listings)
        self.httpd.mpu_root = self.root + ".mpu"
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="ctt-objstub", daemon=True
        )

    def start(self) -> "StubObjectStore":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    def truncate_next(self, substr: str, times: int = 1) -> None:
        self.policy.truncate_next(substr, times)

    def __enter__(self) -> "StubObjectStore":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", required=True)
    ap.add_argument("--port-file", required=True)
    ap.add_argument("--fail-rate", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slow-s", type=float, default=0.0)
    ap.add_argument("--slow-rate", type=float, default=0.0)
    ap.add_argument("--sigv4-access-key", default=None)
    ap.add_argument("--sigv4-secret-key", default=None)
    ap.add_argument("--clock-skew-s", type=float, default=0.0)
    args = ap.parse_args()
    sigv4 = (
        (args.sigv4_access_key, args.sigv4_secret_key)
        if args.sigv4_access_key and args.sigv4_secret_key else None
    )
    store = StubObjectStore(
        args.root, fail_rate=args.fail_rate, seed=args.seed,
        slow_s=args.slow_s, slow_rate=args.slow_rate,
        sigv4=sigv4, clock_skew_s=args.clock_skew_s,
    ).start()
    tmp = args.port_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(store.port))
    os.replace(tmp, args.port_file)
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: done.set())
    signal.signal(signal.SIGINT, lambda *a: done.set())
    done.wait()
    store.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
