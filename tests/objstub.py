"""Local stub object server for ctt-cloud tests, CI, and the bench.

Serves a directory tree over the small object-store HTTP subset the
``HttpBackend`` speaks (the wire schema is documented in
``cluster_tools_tpu/utils/store_backend.py``):

  ``GET /key``     → 200 + bytes; ``Range: bytes=a-b`` → 206 +
                     ``Content-Range``; ``If-None-Match`` matching the
                     current ETag → 304 with no body (the warm-hit
                     revalidation); a directory returns a JSON array
                     of child names with ``X-CTT-Dir: 1`` — paginated
                     with ``?limit=&marker=`` (names strictly after
                     ``marker``, ``X-CTT-List-Next`` on a clipped page);
                     404 if absent.
  ``HEAD /key``    → headers only: ``ETag`` (mtime_ns-size, changes on
                     every atomic replace), ``Last-Modified``,
                     ``Content-Length``, ``X-CTT-Dir`` for directories.
  ``PUT /key``     → atomic write (tmp + rename), parents created; 201.
                     With ``If-None-Match: *`` the PUT is create-only
                     (hard link, first writer wins): 412 when the key
                     already exists — the ``publish_once`` analog the
                     cross-host work-stealing leases ride.
  ``DELETE /key``  → unlink file / remove tree; 204 (404 if absent).

Chaos injection (hermetic flaky-network simulation, seeded so CI runs
are reproducible):

  * ``fail_rate`` — each request independently 503s with this
    probability (the client's backoff retry must absorb it);
  * ``slow_s`` — failed-coin requests stall this long before answering
    (latency spikes instead of hard errors) when ``slow_rate`` hits;
  * ``truncate_next(substr, times)`` — the next ``times`` GET responses
    whose path contains ``substr`` advertise the full ``Content-Length``
    but send only half the body and drop the connection — the truncated
    object read that must classify as ``CorruptChunk`` downstream.

Run in-process (``StubObjectStore(root, ...)`` context manager) or as a
subprocess for shell harnesses::

    python tests/objstub.py --root DIR --port-file F [--fail-rate 0.05]
                            [--seed 7] [--slow-s 0.05] [--slow-rate 0.0]

The subprocess writes ``<port>`` to ``--port-file`` once listening and
serves until SIGTERM.
"""

from __future__ import annotations

import argparse
import email.utils
import json
import os
import random
import re
import shutil
import signal
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_RANGE_RE = re.compile(r"bytes=(\d+)-(\d*)$")


class _Policy:
    """Seeded chaos decisions shared by all handler threads."""

    def __init__(self, fail_rate=0.0, seed=0, slow_s=0.0, slow_rate=0.0):
        self.fail_rate = float(fail_rate)
        self.slow_s = float(slow_s)
        self.slow_rate = float(slow_rate)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._truncate = []  # [substr, remaining] pairs
        self.requests = 0
        self.failures = 0
        self.truncations = 0

    def decide(self, method: str, path: str):
        """(fail_503, slow, truncate) for one request."""
        with self._lock:
            self.requests += 1
            fail = (
                self.fail_rate > 0.0
                and self._rng.random() < self.fail_rate
            )
            slow = (
                self.slow_rate > 0.0
                and self._rng.random() < self.slow_rate
            )
            truncate = False
            if method == "GET" and not fail:
                for pair in self._truncate:
                    if pair[1] > 0 and pair[0] in path:
                        pair[1] -= 1
                        truncate = True
                        self.truncations += 1
                        break
            if fail:
                self.failures += 1
            return fail, slow, truncate

    def truncate_next(self, substr: str, times: int = 1) -> None:
        with self._lock:
            self._truncate.append([substr, int(times)])


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "ctt-objstub/1"

    # -- helpers -------------------------------------------------------------

    def _fs_path(self):
        """The served filesystem path for the request target, confined to
        the root (traversal-safe)."""
        raw = self.path.split("?", 1)[0].split("#", 1)[0]
        from urllib.parse import unquote

        rel = os.path.normpath(unquote(raw).lstrip("/"))
        if rel.startswith(".."):
            return None
        return os.path.join(self.server.root, rel)

    def _send(self, status, body=b"", headers=(), include_body=True):
        self.send_response(status)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if include_body and body:
            self.wfile.write(body)

    def _object_headers(self, p):
        st = os.stat(p)
        return [
            ("ETag", f'"{st.st_mtime_ns:x}-{st.st_size:x}"'),
            ("Last-Modified", email.utils.formatdate(
                st.st_mtime, usegmt=True
            )),
        ]

    def _chaos(self, drain: bool = False):
        fail, slow, truncate = self.server.policy.decide(
            self.command, self.path
        )
        if slow:
            time.sleep(self.server.policy.slow_s)
        if fail:
            if drain:
                # consume the request body before failing it: an unread
                # PUT payload on a keep-alive socket would otherwise be
                # parsed as the NEXT request line
                length = int(self.headers.get("Content-Length", 0))
                if length:
                    self.rfile.read(length)
            self.send_response(503)
            self.send_header("Content-Length", "0")
            self.send_header("Connection", "close")
            self.end_headers()
            self.close_connection = True
            return True, truncate
        return False, truncate

    # -- verbs ---------------------------------------------------------------

    def do_GET(self):  # noqa: N802 (http.server naming)
        failed, truncate = self._chaos()
        if failed:
            return
        p = self._fs_path()
        if p is None or not os.path.exists(p):
            self._send(404, b"not found")
            return
        if os.path.isdir(p):
            # listing with ?limit=&marker= continuation: names strictly
            # after ``marker``, at most ``limit`` per page, the last name
            # of a clipped page echoed back as X-CTT-List-Next
            params = urllib.parse.parse_qs(
                urllib.parse.urlsplit(self.path).query
            )
            names = sorted(os.listdir(p))
            marker = params.get("marker", [None])[0]
            if marker is not None:
                names = [n for n in names if n > marker]
            headers = [
                ("Content-Type", "application/json"), ("X-CTT-Dir", "1"),
            ]
            try:
                limit = int(params.get("limit", [0])[0])
            except ValueError:
                limit = 0
            if limit > 0 and len(names) > limit:
                names = names[:limit]
                headers.append(("X-CTT-List-Next", names[-1]))
            self._send(200, json.dumps(names).encode(), headers=headers)
            return
        headers = self._object_headers(p)
        # conditional GET: a matching If-None-Match answers 304 with no
        # body — the warm-hit revalidation the client's decoded-chunk LRU
        # rides instead of a separate HEAD probe
        inm = self.headers.get("If-None-Match")
        if inm and inm.strip() == dict(headers)["ETag"]:
            self._send(304, headers=headers)
            return
        with open(p, "rb") as f:
            data = f.read()
        status = 200
        rng = self.headers.get("Range")
        if rng:
            m = _RANGE_RE.match(rng.strip())
            if m:
                lo = int(m.group(1))
                hi = int(m.group(2)) if m.group(2) else len(data) - 1
                hi = min(hi, len(data) - 1)
                if lo <= hi:
                    headers.append((
                        "Content-Range", f"bytes {lo}-{hi}/{len(data)}"
                    ))
                    data = data[lo: hi + 1]
                    status = 206
        if truncate and len(data) > 1:
            # advertise the full length, deliver half, drop the socket:
            # the truncated-object read the client must classify
            self.send_response(status)
            for k, v in headers:
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(data)))
            self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(data[: len(data) // 2])
            self.close_connection = True
            return
        self._send(status, data, headers=headers)

    def do_HEAD(self):  # noqa: N802
        failed, _ = self._chaos()
        if failed:
            return
        p = self._fs_path()
        if p is None or not os.path.exists(p):
            self._send(404)
            return
        if os.path.isdir(p):
            self._send(200, headers=[("X-CTT-Dir", "1")])
            return
        st = os.stat(p)
        self.send_response(200)
        for k, v in self._object_headers(p):
            self.send_header(k, v)
        self.send_header("Content-Length", str(st.st_size))
        self.end_headers()

    def do_PUT(self):  # noqa: N802
        failed, _ = self._chaos(drain=True)
        if failed:
            return
        p = self._fs_path()
        if p is None:
            self._send(404, b"not found")
            return
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else b""
        if self.headers.get("If-None-Match", "").strip() == "*":
            # create-only PUT: the publish_once analog — first writer
            # stores, every later writer gets 412 (body already drained,
            # keep-alive hygiene)
            os.makedirs(os.path.dirname(p), exist_ok=True)
            tmp = p + f".put{threading.get_ident()}"
            with open(tmp, "wb") as f:
                f.write(body)
            try:
                os.link(tmp, p)
            except FileExistsError:
                self._send(412)
                return
            finally:
                os.unlink(tmp)
            self._send(201)
            return
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + f".put{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(body)
        os.replace(tmp, p)
        self._send(201)

    def do_DELETE(self):  # noqa: N802
        failed, _ = self._chaos()
        if failed:
            return
        p = self._fs_path()
        if p is None or not os.path.exists(p):
            self._send(404)
            return
        if os.path.isdir(p):
            shutil.rmtree(p)
        else:
            os.unlink(p)
        self._send(204)

    def log_message(self, fmt, *args):  # quiet by default
        if os.environ.get("CTT_OBJSTUB_LOG"):
            BaseHTTPRequestHandler.log_message(self, fmt, *args)


class StubObjectStore:
    """In-process stub server: ``with StubObjectStore(root) as url: ...``
    where ``url`` is the origin (``http://127.0.0.1:<port>``)."""

    def __init__(self, root, fail_rate=0.0, seed=0, slow_s=0.0,
                 slow_rate=0.0):
        os.makedirs(root, exist_ok=True)
        self.root = os.path.abspath(root)
        self.policy = _Policy(fail_rate, seed, slow_s, slow_rate)
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        self.httpd.daemon_threads = True
        self.httpd.root = self.root
        self.httpd.policy = self.policy
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="ctt-objstub", daemon=True
        )

    def start(self) -> "StubObjectStore":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    def truncate_next(self, substr: str, times: int = 1) -> None:
        self.policy.truncate_next(substr, times)

    def __enter__(self) -> "StubObjectStore":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", required=True)
    ap.add_argument("--port-file", required=True)
    ap.add_argument("--fail-rate", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slow-s", type=float, default=0.0)
    ap.add_argument("--slow-rate", type=float, default=0.0)
    args = ap.parse_args()
    store = StubObjectStore(
        args.root, fail_rate=args.fail_rate, seed=args.seed,
        slow_s=args.slow_s, slow_rate=args.slow_rate,
    ).start()
    tmp = args.port_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(store.port))
    os.replace(tmp, args.port_file)
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: done.set())
    signal.signal(signal.SIGINT, lambda *a: done.set())
    done.wait()
    store.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
