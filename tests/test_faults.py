"""ctt-fault chaos suite: deterministic fault injection + the resilience it
validates.

Covers the acceptance contract of the fault subsystem:

  * spec grammar (loud on malformed specs) + deterministic seeded schedules
    — identical injection sequence across two real processes;
  * CTT_FAULTS unset ⇒ the injection sites are the no-op fast path;
  * store IO faults (transient errors, torn chunk writes) heal through the
    shared backoff retry / CorruptChunk classification — outputs stay
    byte-identical to a fault-free run, recovery visible in obs counters;
  * the executor's soft-deadline watchdog converts hung blocks into failed
    blocks that the task retry loop re-runs;
  * a killed scheduler job (no status file) recovers through resubmission,
    and a corrupt task.pkl/job config writes a machine-readable failed
    status instead of dying silently;
  * collective-init failure degrades sharded kernels to the single-device
    local kernel with identical output (never a silent wrong answer).
"""

import hashlib
import json
import os
import pickle
import stat
import subprocess
import sys
import time

import numpy as np
import pytest

from cluster_tools_tpu import faults
from cluster_tools_tpu.obs import metrics as obs_metrics
from cluster_tools_tpu.obs import trace as obs_trace
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends with the harness disarmed."""
    faults.reset()
    yield
    faults.reset()


@pytest.fixture
def obs_run(tmp_path):
    """Enable tracing (counters only count when obs is on) without exporting
    the env vars to other tests."""
    obs_metrics.reset()
    obs_trace.enable(str(tmp_path / "_trace"), "faults_test",
                     export_env=False)
    yield
    obs_trace.disable()
    obs_metrics.reset()


def counters():
    return obs_metrics.snapshot()["counters"]


# --------------------------------------------------------------------------
# spec grammar + determinism


class TestSpec:
    def test_example_spec_parses(self):
        entries, seed = faults.parse_spec(
            "store.write:io_error:p=0.05;worker.job:kill:ids=1;"
            "collective.init:fail:once;seed=42"
        )
        assert seed == 42
        assert [(e.site, e.action) for e in entries] == [
            ("store.write", "io_error"),
            ("worker.job", "kill"),
            ("collective.init", "fail"),
        ]
        assert entries[0].p == 0.05
        assert entries[1].ids == frozenset({1})
        assert entries[2].times == 1

    @pytest.mark.parametrize("spec", [
        "nosuch.site:fail",              # unknown site
        "store.write:explode",           # unknown action
        "store.write:io_error:p=nan2",   # malformed param
        "store.write:io_error:p=1.5",    # out-of-range probability
        "store.read:torn",               # torn is write-only
        "store.write",                   # missing action
        "seed=7",                        # no entries at all
    ])
    def test_malformed_specs_are_loud(self, spec):
        with pytest.raises(faults.FaultSpecError):
            faults.parse_spec(spec)

    def test_ids_and_after_gate_firing(self):
        faults.configure("executor.block:fail:ids=2|5,after=1;seed=0")
        fired = []
        for rnd in range(3):
            for bid in range(6):
                try:
                    faults.check("executor.block", id=bid)
                except faults.FaultInjected:
                    fired.append((rnd, bid))
        # ids gate to blocks 2 and 5; after=1 skips each entry's first match
        assert (0, 2) not in fired and (0, 5) in fired
        assert (1, 2) in fired and (2, 5) in fired

    def test_same_seed_same_schedule_in_process(self):
        def run():
            faults.configure("store.write:io_error:p=0.4;seed=11")
            out = []
            for _ in range(32):
                try:
                    faults.check("store.write")
                    out.append(0)
                except OSError:
                    out.append(1)
            return out
        a, b = run(), run()
        assert a == b and 0 < sum(a) < 32

    def test_determinism_across_two_processes(self, tmp_path):
        """Same CTT_FAULTS spec + seed ⇒ identical injection sequence in two
        real interpreter instances (the cross-process chaos contract)."""
        script = (
            "from cluster_tools_tpu import faults\n"
            "for i in range(40):\n"
            "    try:\n"
            "        faults.check('store.write', id=i % 4)\n"
            "    except OSError:\n"
            "        pass\n"
            "    faults.mangle('store.write', b'x' * 64)\n"
            "print(faults.decision_log())\n"
        )
        env = {
            **os.environ,
            "CTT_FAULTS": (
                "store.write:io_error:p=0.3;store.write:torn:p=0.2;seed=13"
            ),
            "JAX_PLATFORMS": "cpu",
        }
        env.pop("CTT_FAULT_STATE_DIR", None)
        outs = [
            subprocess.run(
                [sys.executable, "-c", script], capture_output=True,
                text=True, env=env, cwd=REPO,
            )
            for _ in range(2)
        ]
        for proc in outs:
            assert proc.returncode == 0, proc.stderr
        assert outs[0].stdout == outs[1].stdout
        assert "store.write" in outs[0].stdout  # something actually fired


class TestNoopFastPath:
    def test_unset_means_disabled(self, monkeypatch):
        monkeypatch.delenv(faults.ENV_SPEC, raising=False)
        faults.configure()
        assert not faults.enabled()
        assert faults.check("store.read") is None
        assert faults.mangle("store.write", b"abc") is None
        assert faults.decision_log() == []

    def test_disabled_overhead_smoke(self):
        """The no-op path is one global load + compare: 100k site checks
        must cost (generously) under a second — no measurable cost to a
        block batch's handful of checks."""
        assert not faults.enabled()
        t0 = time.perf_counter()
        for _ in range(100_000):
            faults.check("store.write")
        assert time.perf_counter() - t0 < 1.0


# --------------------------------------------------------------------------
# store resilience


def _roundtrip(tmp_path, name, data, chunks=(4, 8, 8)):
    path = str(tmp_path / name)
    file_reader(path).create_dataset("x", data=data, chunks=chunks)
    return path


class TestStoreResilience:
    def test_transient_write_errors_retry_to_byte_identical(
        self, tmp_path, rng, obs_run
    ):
        data = rng.integers(0, 1000, (16, 16, 16)).astype("uint32")
        ref = _roundtrip(tmp_path, "ref.n5", data)
        faults.configure("store.write:io_error:p=0.3;seed=1")
        chaos = _roundtrip(tmp_path, "chaos.n5", data)
        faults.reset()
        np.testing.assert_array_equal(
            file_reader(chaos, "r")["x"][:], file_reader(ref, "r")["x"][:]
        )
        assert counters().get("store.io_retries", 0) > 0
        assert counters().get("faults.injected.store.write", 0) > 0

    def test_transient_read_errors_retry(
        self, tmp_path, rng, obs_run, monkeypatch
    ):
        data = rng.integers(0, 1000, (16, 16, 16)).astype("uint32")
        path = _roundtrip(tmp_path, "r.zarr", data)
        # deep retry budget: at p=0.4 a 4-attempt default can (seeded,
        # deterministically) exhaust on one of the 8 chunks
        monkeypatch.setenv("CTT_IO_RETRIES", "8")
        monkeypatch.setenv("CTT_IO_BACKOFF_BASE_S", "0.001")
        faults.configure("store.read:io_error:p=0.4;seed=2")
        got = file_reader(path, "r")["x"][:]
        faults.reset()
        np.testing.assert_array_equal(got, data)
        assert counters().get("store.io_retries", 0) > 0

    def test_torn_write_is_rewritten(self, tmp_path, rng, obs_run):
        """The torn action truncates the payload on disk and raises
        CorruptChunk; the shared retry rewrites the chunk in full."""
        data = rng.integers(0, 1000, (16, 16, 16)).astype("uint32")
        faults.configure("store.write:torn:once;seed=3")
        path = _roundtrip(tmp_path, "t.n5", data)
        faults.reset()
        np.testing.assert_array_equal(file_reader(path, "r")["x"][:], data)
        assert counters().get("faults.injected.store.write", 0) == 1
        assert counters().get("store.io_retries", 0) > 0

    def test_torn_chunk_on_disk_reads_as_corrupt_chunk(
        self, tmp_path, rng, monkeypatch
    ):
        """A truly torn chunk (crashed writer, no rewrite coming) fails the
        read as CorruptChunk — a clean, retryable block failure, not a
        numpy shape error deep in decode."""
        from cluster_tools_tpu.utils.store import CorruptChunk

        monkeypatch.setenv("CTT_IO_RETRIES", "1")
        monkeypatch.setenv("CTT_IO_BACKOFF_BASE_S", "0.001")
        data = rng.integers(0, 1000, (8, 8, 8)).astype("uint32")
        path = _roundtrip(tmp_path, "c.zarr", data, chunks=(8, 8, 8))
        chunk = os.path.join(path, "x", "0.0.0")
        payload = open(chunk, "rb").read()
        with open(chunk, "wb") as f:
            f.write(payload[: max(1, len(payload) // 3)])
        ds = file_reader(path, "r")["x"]
        with pytest.raises(CorruptChunk):
            ds.read_chunk((0, 0, 0))

    def test_atomic_write_unlinks_tmp_on_failure(self, tmp_path, monkeypatch):
        from cluster_tools_tpu.utils.store import atomic_write_bytes

        target = str(tmp_path / "meta.json")

        def boom(src, dst):
            raise OSError("replace failed")

        monkeypatch.setattr(os, "replace", boom)
        with pytest.raises(OSError):
            atomic_write_bytes(target, b"{}")
        monkeypatch.undo()
        # failed writes must not litter .tmpPID.TID files in shared stores
        assert os.listdir(str(tmp_path)) == []

    def test_atomic_write_fsyncs_tmp(self, tmp_path, monkeypatch):
        from cluster_tools_tpu.utils import store as store_mod

        synced = []
        monkeypatch.setattr(os, "fsync", lambda fd: synced.append(fd))
        monkeypatch.setattr(store_mod, "_FSYNC", True)
        store_mod.atomic_write_bytes(str(tmp_path / "s.json"), b"{}")
        assert synced, "tmp file must be fsynced before os.replace"


# --------------------------------------------------------------------------
# executor watchdog


class TestWatchdog:
    def test_hung_block_becomes_failed_block_then_retries(
        self, tmp_path, obs_run
    ):
        from cluster_tools_tpu.runtime.task import BlockTask

        class Hang(BlockTask):
            task_name = "hang"

            def get_shape(self):
                return (16, 16, 16)

            def process_block(self, block_id, blocking, config):
                pass  # the stall is injected at the executor.block site

        cfg.write_global_config(
            str(tmp_path / "configs"),
            {"block_shape": [8, 16, 16], "max_num_retries": 2,
             "retry_failure_fraction": 0.9, "block_deadline_s": 0.4},
        )
        # one stalled block must trip the watchdog (blocks queued behind
        # the hung worker may time out too — they all feed the retry loop),
        # then everything succeeds on retry (the `once` is consumed)
        faults.configure("executor.block:stall:ids=1,once,s=3;seed=5")
        t0 = time.monotonic()
        assert build([Hang(str(tmp_path / "tmp"), str(tmp_path / "configs"))])
        elapsed = time.monotonic() - t0
        assert elapsed < 3.0, "watchdog must not wait out the hung block"
        assert counters().get("executor.blocks_timed_out", 0) >= 1
        assert counters().get("task.blocks_retried", 0) >= 1
        status = json.load(open(
            str(tmp_path / "tmp" / "status" / "hang.status.json")
        ))
        assert status["complete"] and len(status["done"]) == 2

    def test_deadline_off_by_default(self):
        from cluster_tools_tpu.runtime.executor import block_deadline_s

        assert block_deadline_s({}) == 0.0
        assert block_deadline_s({"block_deadline_s": "garbage"}) == 0.0
        assert block_deadline_s({"block_deadline_s": 2.5}) == 2.5


# --------------------------------------------------------------------------
# peer barrier


class TestBarrier:
    def test_barrier_stall_is_survived_until_timeout(self, tmp_path):
        from cluster_tools_tpu.runtime.task import (
            FailedBlocksError, Target, Task,
        )

        class D(Task):
            task_name = "d"

        t = D(str(tmp_path / "tmp"))
        missing = Target(str(tmp_path / "tmp/status/peer.status.json"))
        faults.configure("task.barrier:stall:s=0.2,times=2;seed=0")
        t0 = time.monotonic()
        with pytest.raises(FailedBlocksError, match="timed out"):
            t._peer_wait([missing], 0.3, "peer that never comes")
        # both stalls fired before the (monotonic) deadline tripped
        assert time.monotonic() - t0 >= 0.4
        assert [s for s, _, _ in faults.decision_log()] == [
            "task.barrier", "task.barrier"
        ]


# --------------------------------------------------------------------------
# cluster: killed jobs + corrupt control files


def _write_stub_scheduler(folder):
    os.makedirs(folder, exist_ok=True)
    submit = os.path.join(folder, "stub_submit")
    with open(submit, "w") as f:
        f.write(
            "#!/bin/bash\n"
            'script="${@: -1}"\n'
            'bash "$script" > /dev/null 2>&1\n'
            'echo "Submitted batch job 1"\n'
        )
    queue = os.path.join(folder, "stub_queue")
    with open(queue, "w") as f:
        f.write("#!/bin/bash\nexit 0\n")
    for p in (submit, queue):
        os.chmod(p, os.stat(p).st_mode | stat.S_IEXEC)
    return submit, queue


WORKER_ENV = {
    "PALLAS_AXON_POOL_IPS": "",
    "JAX_PLATFORMS": "cpu",
}


class TestClusterChaos:
    def test_killed_job_recovers_via_resubmission(
        self, tmp_path, rng, monkeypatch
    ):
        """worker.job:kill dies before the status write (hard os._exit).
        The submitter's no-status branch marks the job's blocks failed and
        the task retry resubmits them; the cross-process once-latch
        (CTT_FAULT_STATE_DIR) keeps the resubmitted job alive."""
        from cluster_tools_tpu.workflows import UniqueWorkflow

        state_dir = str(tmp_path / "fault_state")
        monkeypatch.setenv(
            "CTT_FAULTS", "worker.job:kill:ids=0,once;seed=9"
        )
        monkeypatch.setenv("CTT_FAULT_STATE_DIR", state_dir)
        submit, queue = _write_stub_scheduler(str(tmp_path / "sched"))
        labels = rng.integers(0, 100, (16, 24, 24)).astype(np.uint64)
        path = str(tmp_path / "d.n5")
        file_reader(path).create_dataset(
            "seg", data=labels, chunks=(8, 12, 12)
        )
        config_dir = str(tmp_path / "configs")
        cfg.write_global_config(
            config_dir,
            {
                "block_shape": [8, 12, 12],
                "target": "slurm",
                "max_jobs": 3,
                "max_num_retries": 2,
                "retry_failure_fraction": 0.6,
                "poll_interval_s": 0.05,
                "sbatch_cmd": submit,
                "squeue_cmd": queue,
                "worker_env": WORKER_ENV,
            },
        )
        wf = UniqueWorkflow(
            str(tmp_path / "tmp"), config_dir, max_jobs=3,
            input_path=path, input_key="seg",
            output_path=path, output_key="uniques",
        )
        assert build([wf])
        np.testing.assert_array_equal(
            file_reader(path, "r")["uniques"][:], np.unique(labels)
        )
        # the kill really fired exactly once (latched across processes)
        latches = os.listdir(state_dir)
        assert latches == ["worker.job.0.fired0"]

    def test_corrupt_task_pkl_writes_failed_status(self, tmp_path):
        from cluster_tools_tpu.runtime.cluster_worker import (
            job_paths, run_job,
        )

        job_dir = str(tmp_path / "jobs")
        os.makedirs(job_dir)
        task_path, config_path, status_path = job_paths(job_dir, 0)
        with open(task_path, "wb") as f:
            f.write(b"this is not a pickle")
        with open(config_path, "w") as f:
            f.write('{"block_ids": [0], "shape": [8], "block_shape": [8]}')
        assert run_job(job_dir, 0) == 1
        status = json.load(open(status_path))
        assert status["setup_failed"] is True
        assert status["done"] == []
        assert "Traceback" in status["errors"]["setup"]

    def test_corrupt_job_config_writes_failed_status(self, tmp_path):
        from cluster_tools_tpu.runtime.cluster_worker import (
            job_paths, run_job,
        )

        job_dir = str(tmp_path / "jobs")
        os.makedirs(job_dir)
        task_path, config_path, status_path = job_paths(job_dir, 0)
        with open(task_path, "wb") as f:
            f.write(pickle.dumps("any picklable placeholder"))
        with open(config_path, "w") as f:
            f.write('{"block_ids": [0], TORN')
        assert run_job(job_dir, 0) == 1
        status = json.load(open(status_path))
        assert status["setup_failed"] is True and status["done"] == []

    def test_aggregate_surfaces_setup_error_on_job_blocks(self, tmp_path):
        from cluster_tools_tpu.runtime.cluster_executor import SlurmExecutor
        from cluster_tools_tpu.runtime.cluster_worker import job_paths

        job_dir = str(tmp_path / "jobs")
        os.makedirs(job_dir)
        _, _, status_path = job_paths(job_dir, 0)
        with open(status_path, "w") as f:
            json.dump({
                "done": [], "failed": [],
                "errors": {"setup": "Traceback: corrupt task.pkl"},
                "setup_failed": True,
            }, f)
        done, failed, errors = SlurmExecutor({})._aggregate(
            job_dir, 1, [3, 7]
        )
        assert done == [] and failed == [3, 7]
        assert "corrupt task.pkl" in errors[3]


# --------------------------------------------------------------------------
# collective fallback


class TestCollectiveFallback:
    def test_cc_falls_back_to_identical_local_labels(self, rng, obs_run):
        from cluster_tools_tpu.parallel.sharded import (
            sharded_connected_components,
        )

        mask = rng.random((16, 8, 8)) > 0.5
        ref = np.asarray(sharded_connected_components(mask, connectivity=1))
        faults.configure("collective.init:fail:once;seed=0")
        with pytest.warns(RuntimeWarning, match="falling back"):
            got = np.asarray(
                sharded_connected_components(mask, connectivity=1)
            )
        np.testing.assert_array_equal(got, ref)
        assert counters().get("sharded.fallback_local", 0) == 1
        assert counters().get("faults.injected.collective.init", 0) == 1

    def test_watershed_falls_back_to_identical_labels(self, rng, obs_run):
        from cluster_tools_tpu.parallel.sharded import (
            sharded_seeded_watershed,
        )

        hmap = rng.random((16, 8, 8)).astype("float32")
        seeds = np.zeros((16, 8, 8), dtype="int32")
        seeds[2, 2, 2] = 1
        seeds[12, 5, 5] = 2
        ref = np.asarray(sharded_seeded_watershed(hmap, seeds))
        faults.configure("collective.init:fail:once;seed=0")
        with pytest.warns(RuntimeWarning, match="falling back"):
            got = np.asarray(sharded_seeded_watershed(hmap, seeds))
        np.testing.assert_array_equal(got, ref)
        assert counters().get("sharded.fallback_local", 0) == 1

    def test_collective_execute_failure_is_loud(self, rng):
        from cluster_tools_tpu.parallel.sharded import (
            sharded_connected_components,
        )

        mask = rng.random((16, 8, 8)) > 0.5
        faults.configure("collective.execute:fail:once;seed=0")
        # a failure INSIDE the collective never silently degrades — peers
        # may already be in the program; it propagates to the task layer
        with pytest.raises(faults.FaultInjected):
            sharded_connected_components(mask, connectivity=1)


# --------------------------------------------------------------------------
# chaos end-to-end: workflow under seeded faults, byte-identical output


def _dir_digest(root):
    """Order-stable digest of every file under ``root`` (relpath + bytes):
    byte-identity of the chunk store, not just array equality."""
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


class TestChaosEndToEnd:
    def test_watershed_under_store_faults_is_byte_identical(
        self, tmp_path, rng, obs_run
    ):
        """The acceptance run: seeded store IO errors + one torn chunk
        write + one injected block failure, against the watershed
        workflow — output byte-identical to the fault-free run, recovery
        visible in the obs counters."""
        from scipy import ndimage

        from cluster_tools_tpu.workflows.watershed import WatershedWorkflow

        raw = ndimage.gaussian_filter(
            rng.random((24, 48, 48)), (1.0, 2.0, 2.0)
        )
        raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")

        def run_ws(key, spec=None):
            path = str(tmp_path / f"{key}.n5")
            file_reader(path).create_dataset(
                "bnd", data=raw, chunks=(12, 24, 24)
            )
            config_dir = str(tmp_path / f"configs_{key}")
            cfg.write_global_config(
                config_dir,
                {"block_shape": [12, 24, 24], "max_num_retries": 3,
                 "retry_failure_fraction": 0.9},
            )
            cfg.write_config(config_dir, "watershed", {
                "threshold": 0.5, "sigma_seeds": 1.6,
                "size_filter": 10, "halo": [2, 6, 6],
            })
            wf = WatershedWorkflow(
                str(tmp_path / f"tmp_{key}"), config_dir,
                input_path=path, input_key="bnd",
                output_path=path, output_key="ws",
            )
            if spec:
                faults.configure(spec)
            try:
                assert build([wf])
            finally:
                faults.reset()
            return path

        ref_path = run_ws("ref")
        chaos_path = run_ws(
            "chaos",
            "store.write:io_error:p=0.05;store.read:io_error:p=0.02;"
            "store.write:torn:once;executor.block:fail:once;seed=1234",
        )

        ref = file_reader(ref_path, "r")["ws"][:]
        got = file_reader(chaos_path, "r")["ws"][:]
        np.testing.assert_array_equal(got, ref)
        # byte-identity of the stored output, chunk files included
        assert _dir_digest(os.path.join(chaos_path, "ws")) == _dir_digest(
            os.path.join(ref_path, "ws")
        )
        c = counters()
        assert c.get("faults.injected", 0) > 0
        assert c.get("store.io_retries", 0) > 0
        assert c.get("task.blocks_retried", 0) >= 1
