"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax is imported.

Multi-chip sharding is validated on virtual CPU devices (no multi-chip TPU hardware
in CI); the real-TPU path is exercised by bench.py.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# this image injects a TPU platform plugin via sitecustomize that pre-imports jax
# and pins JAX_PLATFORMS=axon; the env var alone is too late, force it via config
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _hbm_isolated():
    """ctt-hbm: a test that arms the warm device-buffer cache (directly,
    or by starting an in-process serve daemon whose context installs one
    process-wide) must not leak resident entries — or an enabled budget —
    into later tests' store-traffic accounting.  Restore the environment
    resolution (default 0 = disabled) and drop cached device arrays."""
    yield
    from cluster_tools_tpu.runtime.workflow import ExecutionContext

    ctx = ExecutionContext._PROCESS
    if ctx is not None and ctx._device_cache is not None:
        from cluster_tools_tpu.runtime import hbm

        ctx._device_cache.max_bytes = hbm.cache_budget_bytes()
        ctx._device_cache.clear()


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def tmp_env(tmp_path):
    """tmp_folder + config_dir pair with a default global config written."""
    from cluster_tools_tpu.runtime import config as cfg

    tmp_folder = str(tmp_path / "tmp")
    config_dir = str(tmp_path / "configs")
    os.makedirs(tmp_folder, exist_ok=True)
    cfg.write_global_config(config_dir, {"block_shape": [16, 32, 32]})
    return tmp_folder, config_dir


def boundary_from_gt(gt, rng, sigma=1.0, noise=0.05):
    """Smoothed gt-edge boundary map + noise — the synthetic boundary
    evidence recipe shared by the learning/quantile tests."""
    from scipy import ndimage

    bnd = np.zeros(gt.shape, dtype=bool)
    for axis in range(gt.ndim):
        a = [slice(None)] * gt.ndim
        b = [slice(None)] * gt.ndim
        a[axis] = slice(1, None)
        b[axis] = slice(None, -1)
        edge = gt[tuple(a)] != gt[tuple(b)]
        bnd[tuple(a)] |= edge
        bnd[tuple(b)] |= edge
    bnd = ndimage.gaussian_filter(bnd.astype("float32"), sigma)
    return bnd + noise * rng.random(gt.shape).astype("float32")
