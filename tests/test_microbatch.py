"""ctt-microbatch: cross-tenant job aggregation tests.

Covers the PR acceptance contract:

  * aggregation: a mixed-tenant burst of same-signature ``event_batch``
    jobs coalesces into stacked dispatches
    (``serve.microbatch_batches``/``serve.microbatch_jobs_batched``),
    every result carries the ``microbatch`` annotation, and the outputs
    are byte-identical — labels, event tables, chunk digests — to a
    window-0 daemon (exact per-job dispatch);
  * priority: a higher-priority job arriving DURING an open window joins
    the batch ahead of lower-priority queue residents (it gets batch
    index 0);
  * poison isolation (fail): an ``executor.block:fail`` member drops out
    of the batch, re-dispatches individually (``serve.microbatch_splits``),
    and fails ALONE — its batchmates publish ok from the same window;
  * poison isolation (kill, subprocess, slow): an ``executor.block:kill``
    member takes the daemon down mid-batch; across respawns the
    batchmates publish ok at gen 1 while only the culprit burns its
    retry budget and quarantines.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from cluster_tools_tpu import faults
from cluster_tools_tpu.obs import metrics as obs_metrics
from cluster_tools_tpu.obs import trace as obs_trace
from cluster_tools_tpu.serve import JobQueue, ServeClient, ServeDaemon
from cluster_tools_tpu.serve.protocol import microbatch_signature
from cluster_tools_tpu.tasks.events import read_event_tables
from cluster_tools_tpu.utils import file_reader

from test_serve import _digest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GCONF = {
    "block_shape": [2, 16, 16], "target": "tpu",
    "device_batch_size": 2, "devices": [0], "pipeline_depth": 2,
}
# the poison tests run members on the local executor: its per-block
# ``executor.block`` fault seam fires on BOTH the stacked member pass and
# the solo re-dispatch, so a poisoned member fails (or kills) the same
# way wherever it runs
GCONF_LOCAL = {"block_shape": [2, 16, 16], "target": "local"}

THRESHOLD = 0.1


@pytest.fixture
def rng():
    return np.random.default_rng(11)


@pytest.fixture
def daemon_factory(tmp_path):
    """In-process daemons with tracing scoped to this test (mirrors
    tests/test_serve.py — the serve counters need the trace switch)."""
    obs_metrics.reset()
    was_on = obs_trace.enabled()
    if not was_on:
        obs_trace.enable(str(tmp_path / "trace"), "microbatch_test",
                         export_env=False)
    daemons = []

    def make(state_dir, **conf):
        d = ServeDaemon(str(state_dir), config=conf)
        d.start()
        daemons.append(d)
        return d

    yield make
    for d in daemons:
        d.request_drain()
        if d._httpd is not None:
            d._httpd.shutdown()
            d._httpd.server_close()
        for t in d._threads:
            if t.name.startswith("ctt-serve-exec"):
                t.join(timeout=30)
    if not was_on:
        obs_trace.disable()
    obs_metrics.reset()


def _frames(rng, n=4, h=16, w=16):
    from scipy import ndimage

    raw = ndimage.gaussian_filter(
        rng.random((n, h, w)), (0.0, 1.0, 1.0)
    ).astype("float32")
    frames = np.where(raw > np.quantile(raw, 0.9), raw, 0.0)
    return frames.astype("float32")


def _write_frames(tmp_path, rng, tag, n=4):
    path = str(tmp_path / f"{tag}.n5")
    file_reader(path).create_dataset(
        "frames", data=_frames(rng, n=n), chunks=(2, 16, 16)
    )
    return path


def _submit_event(client, path, td, tag, gconf=GCONF, **kw):
    return client.event_batch(
        input_path=path, input_key="frames",
        output_path=path, output_key=f"ev_{tag}",
        tmp_folder=os.path.join(td, f"tmp_{tag}"),
        config_dir=os.path.join(td, f"configs_{tag}"),
        threshold=THRESHOLD,
        configs={"global": gconf},
        **kw,
    )


def _counters():
    return dict(obs_metrics.snapshot()["counters"])


def _delta(before, after, name):
    return after.get(name, 0.0) - before.get(name, 0.0)


def _wait_state(client, job_id, state, timeout_s=60.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if client.status(job_id)["state"] == state:
            return
        time.sleep(0.02)
    raise AssertionError(
        f"job {job_id} never reached {state!r}: "
        f"{client.status(job_id)['state']}"
    )


class TestSignature:
    def test_cross_tenant_same_signature(self):
        base = {
            "type": "event_batch", "workflow": "W", "configs": {},
            "kwargs": {"input_path": "/a"},
        }
        a = microbatch_signature({**base, "tenant": "alice"})
        b = microbatch_signature({**base, "tenant": "bob",
                                  "kwargs": {"input_path": "/b"}})
        assert a is not None and a == b, (
            "aggregation must be kwargs- and tenant-blind"
        )
        assert microbatch_signature({**base, "microbatch": False}) is None
        assert microbatch_signature({**base, "type": "ingest"}) is None
        assert (
            microbatch_signature({**base, "configs": {"global": {"x": 1}}})
            != a
        ), "different configs must never stack"


class TestAggregation:
    def test_burst_aggregates_and_stays_byte_identical(
        self, tmp_path, daemon_factory, rng
    ):
        """The tentpole gate: a 4-job mixed-tenant burst coalesces into
        stacked dispatches, and output bytes (labels, event tables,
        chunk digests) match a window-0 daemon exactly."""
        path = _write_frames(tmp_path, rng, "burst")
        td = str(tmp_path)
        n_blocks = 4 // GCONF["block_shape"][0]

        daemon_factory(tmp_path / "state_mb",
                       microbatch_window_s=2.0, microbatch_max_jobs=4)
        client = ServeClient(state_dir=str(tmp_path / "state_mb"))
        before = _counters()
        jobs = [
            _submit_event(client, path, td, f"mb{i}", tenant=f"t{i % 2}")
            for i in range(4)
        ]
        states = [client.wait(j, timeout_s=300) for j in jobs]
        after = _counters()

        annotations = []
        for st in states:
            assert st["result"]["ok"], st
            note = st["result"].get("microbatch")
            assert note is not None, (
                "an aggregated job's result must carry the microbatch "
                f"annotation: {st['result']}"
            )
            annotations.append((note["jobs"], note["index"]))
        assert any(jobs_n >= 2 for jobs_n, _ in annotations), annotations
        assert _delta(before, after, "serve.microbatch_batches") >= 1
        assert _delta(before, after, "serve.microbatch_jobs_batched") >= 2
        # per-member accounting: every member counted toward jobs_done,
        # exactly one burst member paid the cold compile
        assert _delta(before, after, "serve.jobs_done") == 4
        assert _delta(before, after, "serve.cold_compile_jobs") >= 1

        # control: window 0 = exact pre-aggregation behavior
        daemon_factory(tmp_path / "state_solo", microbatch_window_s=0.0)
        solo_client = ServeClient(state_dir=str(tmp_path / "state_solo"))
        b2 = _counters()
        solo_jobs = [
            _submit_event(solo_client, path, td, f"solo{i}",
                          tenant=f"t{i % 2}")
            for i in range(4)
        ]
        for j in solo_jobs:
            st = solo_client.wait(j, timeout_s=300)
            assert st["result"]["ok"]
            assert "microbatch" not in st["result"], (
                "window 0 must not annotate results"
            )
        assert _delta(b2, _counters(), "serve.microbatch_batches") == 0

        f = file_reader(path, "r")
        ref_labels = f["ev_solo0"][:]
        ref_tab = read_event_tables(path, "ev_solo0", n_blocks)
        for i in range(4):
            np.testing.assert_array_equal(f[f"ev_mb{i}"][:], ref_labels)
            np.testing.assert_array_equal(
                read_event_tables(path, f"ev_mb{i}", n_blocks), ref_tab
            )
            assert _digest(os.path.join(path, f"ev_mb{i}")) == _digest(
                os.path.join(path, f"ev_solo{i}")
            ), "stacked dispatch output chunks not byte-identical"

        # observability satellites: the counters ride /metrics and the
        # watch surface renders the batch: line
        text = client.metrics_text()
        vals = {
            ln.split(" ")[0]: float(ln.split(" ")[1])
            for ln in text.splitlines()
            if ln and not ln.startswith("#") and " " in ln
        }
        assert vals.get("ctt_serve_microbatch_batches_total", 0) >= 1
        assert vals.get("ctt_serve_microbatch_jobs_batched_total", 0) >= 2
        from cluster_tools_tpu.obs.live import LiveRun, format_watch

        obs_metrics.flush()
        watch = format_watch(LiveRun(obs_trace.run_dir()).poll())
        assert "serve:" in watch and "batch:" in watch
        assert "jobs/dispatch" in watch

    def test_priority_arrival_joins_window_ahead_of_residents(
        self, tmp_path, daemon_factory, rng
    ):
        """Members are claimed at window CLOSE in (-priority, seq)
        order: a high-priority job submitted while the window is open
        beats the lower-priority jobs already queued — batch index 0."""
        path = _write_frames(tmp_path, rng, "prio")
        td = str(tmp_path)
        # max_jobs 8 keeps early-fill out of reach: the window closes on
        # its deadline, after every submission below has landed
        daemon_factory(tmp_path / "state",
                       microbatch_window_s=2.0, microbatch_max_jobs=8)
        client = ServeClient(state_dir=str(tmp_path / "state"))
        first = _submit_event(client, path, td, "first", priority=0)
        # "running" == claimed == the window is open
        _wait_state(client, first, "running")
        lows = [
            _submit_event(client, path, td, f"low{i}", priority=0)
            for i in range(2)
        ]
        high = _submit_event(client, path, td, "high", priority=10)

        st_high = client.wait(high, timeout_s=300)
        note = st_high["result"].get("microbatch")
        assert note is not None and note["jobs"] == 4, st_high["result"]
        assert note["index"] == 0, (
            "the high-priority window arrival must head the batch: "
            f"{note}"
        )
        st_first = client.wait(first, timeout_s=300)
        assert st_first["result"]["microbatch"]["index"] == 1
        for j in lows:
            assert client.wait(j, timeout_s=300)["result"]["ok"]


class TestPoisonIsolation:
    def test_failed_member_splits_and_fails_alone(
        self, tmp_path, daemon_factory, rng
    ):
        """One member poisoned with ``executor.block:fail`` drops out of
        the batch at its own fault seam, re-dispatches individually
        (``serve.microbatch_splits``), and publishes the ONLY failure —
        both batchmates publish ok from the same window."""
        td = str(tmp_path)
        # culprit: 6 frames = blocks 0..2 (the fault targets id 2);
        # batchmates: 2 frames = block 0 only — the fault cannot touch them
        culprit_path = _write_frames(tmp_path, rng, "culprit", n=6)
        mate_path = _write_frames(tmp_path, rng, "mates", n=2)
        daemon_factory(tmp_path / "state",
                       microbatch_window_s=2.0, microbatch_max_jobs=3)
        client = ServeClient(state_dir=str(tmp_path / "state"))
        faults.configure("executor.block:fail:ids=2")
        try:
            before = _counters()
            culprit = _submit_event(client, culprit_path, td, "culprit",
                                    gconf=GCONF_LOCAL, tenant="bad")
            mates = [
                _submit_event(client, mate_path, td, f"mate{i}",
                              gconf=GCONF_LOCAL, tenant=f"t{i}")
                for i in range(2)
            ]
            st_bad = client.wait(culprit, timeout_s=300,
                                 raise_on_failure=False)
            assert st_bad["state"] == "failed", (
                "the poisoned member must fail its individual re-dispatch"
            )
            note = st_bad["result"].get("microbatch")
            assert note and note.get("split") is True, st_bad["result"]
            assert st_bad["result"]["error"], st_bad["result"]
            for j in mates:
                st = client.wait(j, timeout_s=300)
                assert st["result"]["ok"], (
                    f"batchmate caught the culprit's fault: {st}"
                )
                mate_note = st["result"].get("microbatch")
                assert mate_note and "split" not in mate_note, st["result"]
            after = _counters()
            assert _delta(before, after, "serve.microbatch_splits") >= 1
            assert _delta(before, after, "serve.jobs_failed") == 1
            assert _delta(before, after, "serve.jobs_done") == 2
        finally:
            faults.reset()


# --------------------------------------------------------------------------
# kill-poison quarantine across respawns (real daemon processes)


def _spawn_daemon(state_dir, daemon_id, extra_env=None):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": "", "CTT_HEARTBEAT_S": "0.2"}
    env.pop("CTT_TRACE_DIR", None)
    env.pop("CTT_RUN_ID", None)
    if extra_env:
        env.update(extra_env)
    proc = subprocess.Popen(
        [sys.executable, "-m", "cluster_tools_tpu.serve",
         "--state-dir", str(state_dir), "--lease-s", "5",
         "--daemon-id", daemon_id, "--max-job-gens", "2",
         "--microbatch-window-s", "2.0", "--microbatch-max-jobs", "3"],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    proc.stdout.readline()  # listening banner
    ep_line = proc.stdout.readline()
    if not ep_line:
        raise AssertionError(
            f"daemon {daemon_id} died at startup:\n{proc.stderr.read()}"
        )
    ep = json.loads(ep_line)
    client = ServeClient(endpoint=f"http://{ep['host']}:{ep['port']}",
                         token=ep["token"])
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            client.healthz()
            return proc, client
        except Exception:
            if proc.poll() is not None:
                raise AssertionError(
                    f"daemon {daemon_id} died:\n{proc.stderr.read()}"
                ) from None
            time.sleep(0.1)
    proc.kill()
    raise AssertionError(f"daemon {daemon_id} never became healthy")


@pytest.mark.slow
@pytest.mark.timeout(600)
class TestKillPoisonQuarantine:
    def test_culprit_quarantines_alone_batchmates_publish_ok(
        self, tmp_path
    ):
        """The acceptance gate: a member that KILLS the daemon mid-batch
        (``executor.block:kill``) burns only its own retry budget.  The
        shared crash costs every member one generation, after which the
        fresh-gen-only rule makes everyone re-run SOLO: both batchmates
        publish ok at gen 1 while the culprit kills its next daemon too
        and quarantines at the budget."""
        state = tmp_path / "state"
        td = str(tmp_path)
        rng = np.random.default_rng(13)
        culprit_path = _write_frames(tmp_path, rng, "kculprit", n=6)
        mate_path = _write_frames(tmp_path, rng, "kmates", n=2)
        poison_env = {"CTT_FAULTS": "executor.block:kill:ids=2"}
        proc = None
        try:
            proc, client = _spawn_daemon(state, "m0", extra_env=poison_env)
            culprit = _submit_event(client, culprit_path, td, "kculprit",
                                    gconf=GCONF_LOCAL, tenant="bad")
            # higher priority: the respawned daemon re-runs the
            # batchmates before the culprit gets the chance to kill it
            mates = [
                _submit_event(client, mate_path, td, f"kmate{i}",
                              gconf=GCONF_LOCAL, tenant=f"t{i}",
                              priority=5)
                for i in range(2)
            ]
            # gen 0: the batch forms, the culprit's fault seam fires
            # mid-batch and takes the whole daemon down (exit 17)
            assert proc.wait(timeout=120) == 17
            # gen 1 (still poisoned): every member is requeued solo —
            # batchmates finish ok, then the culprit kills this one too
            proc, client = _spawn_daemon(state, "m1", extra_env=poison_env)
            assert proc.wait(timeout=120) == 17
            # budget burned: a healthy daemon quarantines the culprit
            # instead of executing it
            proc, client = _spawn_daemon(state, "m2")
            deadline = time.monotonic() + 120
            res = None
            while time.monotonic() < deadline:
                st = client.status(culprit)
                if st["state"] == "failed":
                    res = st["result"]
                    break
                time.sleep(0.2)
            assert res is not None, "poison member never quarantined"
            assert res["quarantined"] is True
            assert [e["gen"] for e in res["failure_log"]] == [0, 1]
            q = JobQueue(str(state / "jobs"), lease_s=5.0)
            for jid in mates:
                st = client.wait(jid, timeout_s=180)
                assert st["result"]["ok"], (
                    f"batchmate lost to the culprit's kill: {st}"
                )
                r = q.get(jid)["result"]
                assert r["gen"] == 1, (
                    "a batchmate burned more than the one shared-crash "
                    f"generation: {r}"
                )
        finally:
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
