"""VERDICT r2 item 9: quantify the histogram-sketch quantile error through
the RF-learning consumer.

The blocked feature merge reconstructs q10..q90 from a fixed-bin histogram
(ops/rag.py HIST_BINS) where the reference's merge is exact
(merge_edge_features.py:141).  These tests bound the effect where it
matters: RF edge probabilities predicted from blocked-merged features must
match probabilities from exactly recomputed single-shot features — no
decision flip at 0.5 on any edge, and a small probability drift.
"""

import os

import numpy as np
import pytest
from scipy import ndimage

pytest.importorskip("sklearn")

from cluster_tools_tpu.ops.rag import boundary_edge_features
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader

from conftest import boundary_from_gt


@pytest.fixture
def rf_problem(tmp_path, rng):
    """Cells volume + gt + blocked problem features + exact recompute."""
    from cluster_tools_tpu.workflows import (
        EdgeFeaturesWorkflow,
        GraphWorkflow,
    )

    shape = (24, 48, 48)
    gt = np.kron(
        rng.integers(1, 9, (6, 12, 12)).astype("uint64"),
        np.ones((4, 4, 4), dtype=np.uint64),
    )
    # fragments: gt cells split in halves → RF must merge within cells
    ws = (gt * 2 + (np.arange(shape[0]) % 8 >= 4)[:, None, None]).astype(
        "uint64"
    )
    bnd = boundary_from_gt(gt, rng, noise=0.1)
    bnd = (bnd / bnd.max()).astype("float32")

    path = str(tmp_path / "q.n5")
    f = file_reader(path)
    f.create_dataset("ws", data=ws, chunks=(8, 16, 16))
    f.create_dataset("bnd", data=bnd, chunks=(8, 16, 16))
    config_dir = str(tmp_path / "configs")
    tmp_folder = str(tmp_path / "tmp")
    cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
    graph = GraphWorkflow(
        tmp_folder, config_dir, input_path=path, input_key="ws"
    )
    feats_wf = EdgeFeaturesWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="bnd",
        labels_path=path, labels_key="ws",
        dependencies=[graph],
    )
    assert build([feats_wf])
    store = file_reader(os.path.join(tmp_folder, "data.zarr"), "r")
    nodes = store["graph/nodes"][:]
    edges = store["graph/edges"][:]
    blocked = store["features/edges"][:]

    exact_edges, exact = boundary_edge_features(ws, bnd.astype(np.float64))
    by_pair = {tuple(e): i for i, e in enumerate(exact_edges)}
    order = np.array([by_pair[tuple(p)] for p in nodes[edges]])
    exact_aligned = exact[order]

    # edge gt labels: cut (1) when the fragments belong to different cells
    frag_to_cell = {}
    for frag in np.unique(ws):
        sel = ws == frag
        frag_to_cell[frag] = np.bincount(gt[sel].astype(np.int64)).argmax()
    pairs = nodes[edges]
    labels = np.array(
        [frag_to_cell[u] != frag_to_cell[v] for u, v in pairs], dtype=int
    )
    return blocked, exact_aligned, labels


class TestQuantileSketchRFImpact:
    def test_probabilities_track_exact_and_no_decision_flip(self, rf_problem):
        from sklearn.ensemble import RandomForestClassifier

        blocked, exact, labels = rf_problem
        assert blocked.shape == exact.shape and len(labels) == len(blocked)
        assert labels.sum() > 5 and (1 - labels).sum() > 5

        # train on the EXACT features (the oracle condition: a model fit on
        # ground-truth-quality features, evaluated on sketched ones)
        rf = RandomForestClassifier(n_estimators=50, random_state=0)
        rf.fit(exact, labels)
        p_exact = rf.predict_proba(exact)[:, 1]
        p_blocked = rf.predict_proba(blocked)[:, 1]

        drift = np.abs(p_exact - p_blocked)
        # no edge may flip its decision at the 0.5 boundary
        flips = (p_exact > 0.5) != (p_blocked > 0.5)
        assert not flips.any(), (
            f"{flips.sum()} RF decisions flipped; max drift {drift.max():.4f}"
        )
        # and the probability drift stays small in aggregate
        assert drift.mean() < 0.02, f"mean drift {drift.mean():.4f}"
        assert drift.max() < 0.2, f"max drift {drift.max():.4f}"

    def test_feature_columns_drift_bounded(self, rf_problem):
        """Column-wise: exact columns identical, quantiles within one
        histogram bin (the sketch's documented bound)."""
        from cluster_tools_tpu.ops.rag import HIST_BINS

        blocked, exact, _ = rf_problem
        # mean, var, min, max, count exact (f64 reductions)
        np.testing.assert_allclose(
            blocked[:, [0, 1, 2, 8, 9]], exact[:, [0, 1, 2, 8, 9]],
            rtol=1e-9, atol=1e-9,
        )
        tol = 1.0 / HIST_BINS + 1e-6
        drift = np.abs(blocked[:, 3:8] - exact[:, 3:8])
        assert drift.max() <= tol, f"quantile drift {drift.max():.4f} > {tol}"
