"""ctt-proto: positive + negative coverage for every CTT2xx shared-state
protocol rule (exact rule id + file:line), the artifact registry and its
non-drift contracts (obs/trace.py docstring, README fault-site table,
KNOWN_SITES coverage), and the ``analysis conformance <dir>`` exit-code
contract (0 clean / 1 empty / 2 malformed)."""

import ast
import json
import os
import subprocess
import sys

import pytest

from cluster_tools_tpu import faults
from cluster_tools_tpu.analysis import (
    REGISTRY,
    SCHEMAS,
    check_docstring_sync,
    check_fault_site_coverage,
    conformance_report,
    lint_source,
    run_conformance,
    schema_for_filename,
)
from cluster_tools_tpu.analysis.proto_rules import check_proto_rules
from cluster_tools_tpu.analysis.protocols import ArtifactSchema

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "ctt_proto")
PKG = os.path.join(REPO, "cluster_tools_tpu")

# a producer module with no registry producer/consumer sites of its own:
# CTT201/202 scoping is active, CTT206 stays silent
PRODUCER_PATH = "cluster_tools_tpu/runtime/task.py"
# a LEASE_MODULES member with no registry sites: wrapper CTT203 is active
LEASE_PATH = "cluster_tools_tpu/runtime/cluster_executor.py"
NEUTRAL_PATH = "cluster_tools_tpu/ops/fake.py"


def lint(src, path=NEUTRAL_PATH, **kw):
    return lint_source(src, path, **kw)


def only(findings, rule_id):
    return [f for f in findings if f.rule_id == rule_id]


def line_of(path, needle):
    with open(path) as f:
        for lineno, text in enumerate(f, start=1):
            if needle in text:
                return lineno
    raise AssertionError(f"{needle!r} not found in {path}")


def run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "cluster_tools_tpu.analysis", *argv],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )


# --------------------------------------------------------------------------
# registry / meta


class TestRegistry:
    def test_ctt2xx_rules_registered(self):
        expect = {"CTT201", "CTT202", "CTT203", "CTT204", "CTT205",
                  "CTT206"}
        assert expect <= REGISTRY.known_ids()

    def test_schema_patterns_disambiguate(self):
        # queue vs serve artifacts share prefixes; the j-id keeps them apart
        assert schema_for_filename("lease.3.g1.json").name == "queue_lease"
        assert schema_for_filename("lease.j000001.g0.json").name \
            == "serve_lease"
        assert schema_for_filename("result.12.json").name == "queue_result"
        assert schema_for_filename("result.j000012.json").name \
            == "serve_result"
        assert schema_for_filename("spans.p9.t140.jsonl").name \
            == "trace_spans"
        assert schema_for_filename("daemon.host-1.json").name == "fleet_beat"
        assert schema_for_filename("global.config").name == "config_file"
        assert schema_for_filename("not_an_artifact.bin") is None

    def test_every_schema_site_names_an_existing_function(self):
        """The registry must not rot: every declared producer/consumer
        (and merge producer) function still exists in its module."""
        for schema in SCHEMAS:
            sites = (schema.producers + schema.merge_producers
                     + schema.consumers)
            for mod, fn in sites:
                src_path = os.path.join(PKG, *mod.split("/")[-2:])
                with open(src_path) as f:
                    tree = ast.parse(f.read())
                names = {
                    n.name for n in ast.walk(tree)
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                }
                assert fn in names, (
                    f"{schema.name}: {mod} has no function `{fn}`"
                )

    def test_finding_format_is_path_line_rule(self):
        src = (
            "import json\n"
            "def write_thing(path, rec):\n"
            "    with open(path, \"w\") as f:\n"
            "        json.dump(rec, f)\n"
        )
        (f,) = only(lint(src, path=PRODUCER_PATH), "CTT201")
        assert f.format().startswith(f"{PRODUCER_PATH}:3: CTT201 ")


# --------------------------------------------------------------------------
# CTT201: bare write-mode open() in producer modules


class TestCTT201:
    def test_bare_write_open_in_producer_module(self):
        src = (
            "import json\n"
            "def write_thing(path, rec):\n"
            "    with open(path, \"w\") as f:\n"
            "        json.dump(rec, f)\n"
        )
        (f,) = only(lint(src, path=PRODUCER_PATH), "CTT201")
        assert f.line == 3

    def test_mode_keyword_and_binary(self):
        src = (
            "def write_thing(path, data):\n"
            "    f = open(path, mode=\"wb\")\n"
            "    f.write(data)\n"
        )
        (f,) = only(lint(src, path=PRODUCER_PATH), "CTT201")
        assert f.line == 2

    def test_negative_inline_tmp_replace_idiom(self):
        src = (
            "import json, os\n"
            "def write_thing(path, rec):\n"
            "    tmp = path + \".tmp\"\n"
            "    with open(tmp, \"w\") as f:\n"
            "        json.dump(rec, f)\n"
            "    os.replace(tmp, path)\n"
        )
        assert only(lint(src, path=PRODUCER_PATH), "CTT201") == []

    def test_negative_append_mode(self):
        src = (
            "def log_line(path, line):\n"
            "    with open(path, \"a\") as f:\n"
            "        f.write(line)\n"
        )
        assert only(lint(src, path=PRODUCER_PATH), "CTT201") == []

    def test_negative_outside_producer_modules(self):
        src = (
            "def write_thing(path, text):\n"
            "    with open(path, \"w\") as f:\n"
            "        f.write(text)\n"
        )
        assert only(lint(src, path=NEUTRAL_PATH), "CTT201") == []


# --------------------------------------------------------------------------
# CTT202: exists()-then-write on the same path


class TestCTT202:
    def test_exists_guarded_write_same_path(self):
        src = (
            "import os\n"
            "def publish(path, data):\n"
            "    if not os.path.exists(path):\n"
            "        atomic_write_bytes(path, data)\n"
        )
        (f,) = only(lint(src, path=PRODUCER_PATH), "CTT202")
        assert f.line == 4

    def test_else_branch_is_checked_too(self):
        src = (
            "import os\n"
            "def publish(path, data):\n"
            "    if os.path.isfile(path):\n"
            "        pass\n"
            "    else:\n"
            "        write_bytes(path, data)\n"
        )
        (f,) = only(lint(src, path=PRODUCER_PATH), "CTT202")
        assert f.line == 6

    def test_negative_write_to_other_path(self):
        src = (
            "import os\n"
            "def publish(path, marker, data):\n"
            "    if os.path.exists(marker):\n"
            "        atomic_write_bytes(path, data)\n"
        )
        assert only(lint(src, path=PRODUCER_PATH), "CTT202") == []

    def test_negative_unconditional_atomic_write(self):
        src = (
            "def publish(path, data):\n"
            "    atomic_write_bytes(path, data)\n"
        )
        assert only(lint(src, path=PRODUCER_PATH), "CTT202") == []


# --------------------------------------------------------------------------
# CTT203: discarded publish_once-family returns


class TestCTT203:
    def test_publish_once_return_discarded(self):
        src = (
            "def park(path, payload):\n"
            "    publish_once(path, payload)\n"
        )
        (f,) = only(lint(src), "CTT203")
        assert f.line == 2
        assert "publish_once" in f.message

    def test_wrapper_discarded_in_lease_module(self):
        src = (
            "def reap(self, jid):\n"
            "    self._try_claim(jid, 0)\n"
        )
        (f,) = only(lint(src, path=LEASE_PATH), "CTT203")
        assert f.line == 2

    def test_negative_branched_return(self):
        src = (
            "def park(path, payload):\n"
            "    won = publish_once(path, payload)\n"
            "    if not won:\n"
            "        return False\n"
            "    return True\n"
        )
        assert only(lint(src), "CTT203") == []

    def test_negative_wrapper_outside_lease_modules(self):
        src = (
            "def reap(self, jid):\n"
            "    self.complete(jid)\n"
        )
        assert only(lint(src, path=NEUTRAL_PATH), "CTT203") == []

    def test_noqa_suppresses_with_reason(self):
        src = (
            "def park(path, payload):\n"
            "    publish_once(path, payload)"
            "  # ctt: noqa[CTT203] fixture: terminal either way\n"
        )
        assert only(lint(src), "CTT203") == []


# --------------------------------------------------------------------------
# CTT204: staleness literals outside the shared constants


class TestCTT204:
    def test_literal_cadence_multiple_in_comparison(self):
        src = (
            "def is_stale(age, lease_s):\n"
            "    return age > 3.0 * lease_s\n"
        )
        (f,) = only(lint(src), "CTT204")
        assert f.line == 2
        assert "STALE_INTERVALS" in f.message

    def test_reversed_operands_and_interval_token(self):
        src = (
            "def is_dead(age, beat_interval_s):\n"
            "    return beat_interval_s * 4 < age\n"
        )
        (f,) = only(lint(src), "CTT204")
        assert f.line == 2

    def test_parameter_redeclares_constant(self):
        src = (
            "def policy(stale_intervals=3.0):\n"
            "    return stale_intervals\n"
        )
        (f,) = only(lint(src), "CTT204")
        assert "stale_intervals" in f.message

    def test_negative_shared_constant_multiplier(self):
        src = (
            "from cluster_tools_tpu.runtime.queue import STALE_INTERVALS\n"
            "def is_stale(age, lease_s):\n"
            "    return age > STALE_INTERVALS * lease_s\n"
        )
        assert only(lint(src), "CTT204") == []

    def test_negative_non_cadence_names_and_scaling(self):
        src = (
            "def grow(n_retries, backoff_s):\n"
            "    if n_retries > 5 * 2:\n"
            "        return backoff_s\n"
            "    return 2 * backoff_s\n"
        )
        assert only(lint(src), "CTT204") == []

    def test_negative_constant_default_from_import(self):
        src = (
            "from cluster_tools_tpu.runtime.queue import STRAGGLER_K\n"
            "def policy(straggler_k=STRAGGLER_K):\n"
            "    return straggler_k\n"
        )
        assert only(lint(src), "CTT204") == []


# --------------------------------------------------------------------------
# CTT205: fault-site literals vs faults.KNOWN_SITES


class TestCTT205:
    def test_unknown_site_literal(self):
        src = (
            "from cluster_tools_tpu import faults\n"
            "def fire():\n"
            "    faults.check(\"sched.not_a_site\")\n"
        )
        (f,) = only(lint(src), "CTT205")
        assert f.line == 3
        assert "sched.not_a_site" in f.message

    def test_mangle_is_checked_too(self):
        src = (
            "from cluster_tools_tpu import faults\n"
            "def mangle(payload):\n"
            "    return faults.mangle(\"store.nope\", payload)\n"
        )
        (f,) = only(lint(src), "CTT205")
        assert f.line == 3

    def test_negative_known_site_and_foreign_check(self):
        src = (
            "from cluster_tools_tpu import faults\n"
            "def fire(validator):\n"
            "    faults.check(\"sched.claim\", id=3)\n"
            "    validator.check(\"not.a.fault.site\")\n"
        )
        assert only(lint(src), "CTT205") == []

    def test_coverage_clean_on_real_package(self):
        assert check_fault_site_coverage([PKG]) == []

    def test_coverage_flags_dead_sites(self, tmp_path):
        # a tree with no call sites at all: every KNOWN_SITES entry is
        # dead weight, anchored at its SITE_DOCS line
        (tmp_path / "empty.py").write_text("x = 1\n")
        findings = check_fault_site_coverage([str(tmp_path)])
        assert {f.rule_id for f in findings} == {"CTT205"}
        assert len(findings) == len(faults.KNOWN_SITES)
        faults_path = os.path.abspath(faults.__file__)
        by_site = {f.message.split("'")[1]: f for f in findings}
        f = by_site["store.read"]
        assert f.path == faults_path
        assert f.line == line_of(faults_path, '"store.read"')

    def test_coverage_counts_conditional_site_idiom(self, tmp_path):
        # `site = "a" if ... else "b"; faults.check(site)` — the literals
        # count as live call sites because the module fires injections
        src = (
            "from cluster_tools_tpu import faults\n"
            "def roundtrip(method):\n"
            "    site = (\"store.remote_write\" if method == \"PUT\"\n"
            "            else \"store.remote_read\")\n"
            "    faults.check(site)\n"
        )
        (tmp_path / "remote.py").write_text(src)
        findings = check_fault_site_coverage([str(tmp_path)])
        missing = {f.message.split("'")[1] for f in findings}
        assert "store.remote_write" not in missing
        assert "store.remote_read" not in missing
        assert "store.read" in missing  # everything else is still dead


# --------------------------------------------------------------------------
# CTT206: producer/consumer key drift against the registry


FAKE_SCHEMA = ArtifactSchema(
    name="fake_rec",
    pattern=r"^fake\.json$",
    description="fixture artifact",
    required={"a": "int", "b": "str"},
    optional={"c": "bool"},
    producers=(("ops/fake.py", "make"),),
    consumers=(("ops/fake.py", "read"),),
)


def drift(src, schemas=(FAKE_SCHEMA,), path=NEUTRAL_PATH):
    findings = []
    check_proto_rules(ast.parse(src), path, findings, schemas=list(schemas))
    return only(findings, "CTT206")


class TestCTT206:
    def test_producer_missing_required_key(self):
        src = (
            "def make():\n"
            "    return {\"a\": 1}\n"
        )
        (f,) = drift(src)
        assert f.line == 1
        assert '"b"' in f.message and "fake_rec" in f.message

    def test_producer_renamed_away(self):
        src = "def build():\n    return {\"a\": 1, \"b\": \"x\"}\n"
        (f,) = drift(src)
        assert "`make`" in f.message and f.line == 1

    def test_consumer_reads_undeclared_key(self):
        src = (
            "def read(rec):\n"
            "    return rec[\"a\"], rec.get(\"z\")\n"
        )
        src = "def make():\n    d = {}\n    d[\"a\"] = 1\n" \
              "    d.setdefault(\"b\", \"x\")\n    return d\n" + src
        (f,) = drift(src)
        assert f.line == 7
        assert '"z"' in f.message

    def test_negative_clean_producer_and_consumer(self):
        src = (
            "def make():\n"
            "    return {\"a\": 1, \"b\": \"x\", \"c\": True}\n"
            "def read(rec):\n"
            "    return rec[\"a\"] if rec.get(\"c\") else rec[\"b\"]\n"
        )
        assert drift(src) == []

    def test_negative_module_without_registry_sites(self):
        src = "def make():\n    return {}\n"
        assert drift(src, path="cluster_tools_tpu/ops/other.py") == []

    def test_real_tree_has_no_key_drift(self):
        """Every registry-declared producer/consumer in the live package
        agrees with its schema (the drift the rule exists to catch)."""
        modules = {mod for schema in SCHEMAS
                   for mod, _ in schema.producers + schema.consumers}
        for mod in sorted(modules):
            src_path = os.path.join(PKG, *mod.split("/"))
            with open(src_path) as f:
                findings = []
                check_proto_rules(
                    ast.parse(f.read()), src_path, findings
                )
            assert only(findings, "CTT206") == [], mod


# --------------------------------------------------------------------------
# non-drift contracts: docstring, README table


class TestNonDrift:
    def test_trace_docstring_matches_registry(self):
        assert check_docstring_sync() == []

    def test_readme_fault_table_is_generated(self):
        with open(os.path.join(REPO, "README.md")) as f:
            readme = f.read()
        begin = "<!-- ctt-fault-sites:begin -->"
        end = "<!-- ctt-fault-sites:end -->"
        assert begin in readme and end in readme
        table = readme.split(begin)[1].split(end)[0].strip()
        assert table == faults.sites_markdown_table()


# --------------------------------------------------------------------------
# conformance: exit-code contract over synthetic state dirs


def _write(dirpath, name, obj):
    path = os.path.join(str(dirpath), name)
    with open(path, "w") as f:
        if isinstance(obj, str):
            f.write(obj)
        else:
            json.dump(obj, f)
    return path


def _valid_queue_dir(dirpath):
    _write(dirpath, "manifest.json", {
        "task": "t", "items": [[0, 1]], "lease_s": 1.0,
        "duplicate": True, "created_wall": 1.0,
    })
    _write(dirpath, "lease.0.g0.json", {
        "item": 0, "gen": 0, "blocks": [0, 1], "owner_pid": 1,
        "job_id": "0", "host": "h", "claim_wall": 1.0, "wall": 1.0,
        "mono": 2.0,
    })
    _write(dirpath, "result.0.json", {
        "item": 0, "gen": 0, "done": [0, 1], "failed": [], "errors": {},
        "pid": 1, "job_id": "0", "duplicate": False, "seconds": 0.1,
        "wall": 1.0,
    })
    _write(dirpath, "metrics.p1.json", {"counters": {"x": 1}, "gauges": {}})
    _write(dirpath, "spans.p1.t2.jsonl", (
        '{"type": "header", "run": "r", "pid": 1, "tid": 2,'
        ' "host": "h", "wall": 1.0, "mono": 2.0}\n'
        '{"type": "span", "id": 1, "name": "n", "t0": 0.0, "t1": 1.0}\n'
    ))


def _valid_serve_dir(dirpath):
    _write(dirpath, "serve.json", {
        "host": "h", "port": 1, "pid": 2, "daemon_id": "d",
        "started_wall": 1.0, "run_id": None, "token": "x",
    })
    _write(dirpath, "job.j000001.json", {
        "id": "j000001", "seq": 1, "schema": 1, "workflow": "w",
        "tenant": "t", "submit_wall": 1.0, "admitted": True,
    })
    _write(dirpath, "admit.j000001.json",
           {"id": "j000001", "wall": 1.0, "daemon": "d"})
    _write(dirpath, "lease.j000001.g0.json", {
        "job": "j000001", "gen": 0, "owner_pid": 2, "daemon": "d",
        "claim_wall": 1.0, "wall": 1.0, "mono": 2.0,
    })
    _write(dirpath, "result.j000001.json", {
        "id": "j000001", "gen": 0, "ok": True, "pid": 2, "daemon": "d",
        "finished_wall": 1.0,
    })
    _write(dirpath, "daemon.d1.json", {
        "id": "d1", "pid": 2, "wall": 1.0, "mono": 2.0,
        "interval_s": 1.0, "seq": 1, "exiting": False, "queued": 0,
    })


class TestConformance:
    def test_clean_queue_dir_exits_0(self, tmp_path, capsys):
        _valid_queue_dir(tmp_path)
        assert run_conformance(str(tmp_path)) == 0
        problems, warnings, recognized = conformance_report(str(tmp_path))
        assert problems == [] and warnings == [] and recognized == 5

    def test_clean_serve_dir_exits_0(self, tmp_path):
        _valid_serve_dir(tmp_path)
        problems, warnings, recognized = conformance_report(str(tmp_path))
        assert problems == [], problems
        assert recognized == 6
        assert run_conformance(str(tmp_path)) == 0

    def test_empty_dir_exits_1(self, tmp_path):
        assert run_conformance(str(tmp_path)) == 1

    def test_missing_dir_exits_2(self, tmp_path):
        assert run_conformance(str(tmp_path / "nope")) == 2

    def test_unknown_file_exits_2(self, tmp_path):
        _valid_queue_dir(tmp_path)
        _write(tmp_path, "garbage.bin", "not an artifact")
        problems, _, _ = conformance_report(str(tmp_path))
        assert any("unknown file" in p for p in problems)
        assert run_conformance(str(tmp_path)) == 2

    def test_missing_required_key_and_wrong_type(self, tmp_path):
        _valid_queue_dir(tmp_path)
        _write(tmp_path, "result.1.json", {
            "item": "one", "gen": 0, "done": [], "failed": [],
            "errors": {}, "pid": 1, "job_id": None, "duplicate": False,
            "seconds": 0.1,  # "wall" missing; "item" is a str
        })
        problems, _, _ = conformance_report(str(tmp_path))
        assert any('missing required key "wall"' in p for p in problems)
        assert any('"item"' in p and "is not int" in p for p in problems)
        assert run_conformance(str(tmp_path)) == 2

    def test_closed_schema_rejects_unknown_keys(self, tmp_path):
        _valid_queue_dir(tmp_path)
        _write(tmp_path, "metrics.p2.json",
               {"counters": {}, "gauges": {}, "histograms": {}})
        problems, _, _ = conformance_report(str(tmp_path))
        assert any('unknown key "histograms"' in p for p in problems)

    def test_torn_lease_degrades_to_warning(self, tmp_path):
        _valid_queue_dir(tmp_path)
        _write(tmp_path, "lease.1.g0.json", '{"item": 1, "gen"')
        problems, warnings, _ = conformance_report(str(tmp_path))
        assert problems == []
        assert any("torn record" in w for w in warnings)
        assert run_conformance(str(tmp_path)) == 0

    def test_torn_non_torn_ok_record_is_a_problem(self, tmp_path):
        _valid_queue_dir(tmp_path)
        _write(tmp_path, "result.1.json", '{"item": 1, "gen"')
        problems, _, _ = conformance_report(str(tmp_path))
        assert any("unparsable JSON" in p for p in problems)
        assert run_conformance(str(tmp_path)) == 2

    def test_torn_span_tail_line_is_a_warning(self, tmp_path):
        _valid_queue_dir(tmp_path)
        _write(tmp_path, "spans.p3.t4.jsonl", (
            '{"type": "header", "run": null, "pid": 3, "tid": 4,'
            ' "host": "h", "wall": 1.0, "mono": 2.0}\n'
            '{"type": "span", "id": 2, "t0": 0.0, "t'
        ))
        problems, warnings, _ = conformance_report(str(tmp_path))
        assert problems == []
        assert any("torn tail line" in w for w in warnings)

    def test_tmp_staging_debris_is_skipped(self, tmp_path):
        _valid_queue_dir(tmp_path)
        _write(tmp_path, "metrics.p9.json.tmp12345", "{half a rec")
        assert run_conformance(str(tmp_path)) == 0

    def test_serve_job_gap_and_seq_mismatch(self, tmp_path):
        _valid_serve_dir(tmp_path)
        _write(tmp_path, "job.j000003.json", {
            "id": "j000003", "seq": 2, "schema": 1, "workflow": "w",
            "tenant": "t", "submit_wall": 1.0,
        })
        problems, _, _ = conformance_report(str(tmp_path))
        assert any("gaps at j000002" in p for p in problems)
        assert any("seq 2 does not match" in p for p in problems)
        assert run_conformance(str(tmp_path)) == 2

    def test_cli_verb_exit_codes(self, tmp_path):
        clean = tmp_path / "clean"
        clean.mkdir()
        _valid_queue_dir(clean)
        empty = tmp_path / "empty"
        empty.mkdir()
        bad = tmp_path / "bad"
        bad.mkdir()
        _write(bad, "garbage.bin", "x")
        assert run_cli("conformance", str(clean)).returncode == 0
        assert run_cli("conformance", str(empty)).returncode == 1
        proc = run_cli("conformance", str(bad))
        assert proc.returncode == 2
        assert "unknown file" in proc.stdout


# --------------------------------------------------------------------------
# CLI contract: fixtures fail, the real tree is clean


class TestCli:
    def test_bad_proto_fixture_fails(self):
        proc = run_cli(
            "--fail-on-findings", "--no-graph",
            "--paths", os.path.join(FIXTURES, "bad_proto.py"),
        )
        assert proc.returncode == 1
        for rid in ("CTT203", "CTT204", "CTT205"):
            assert rid in proc.stdout, rid

    def test_good_proto_fixture_is_clean(self):
        proc = run_cli(
            "--fail-on-findings", "--no-graph",
            "--paths", os.path.join(FIXTURES, "good_proto.py"),
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_real_tree_is_clean_with_proto_rules(self):
        proc = run_cli("--fail-on-findings")
        assert proc.returncode == 0, proc.stdout + proc.stderr
