"""ctt-io tests: store fast paths + the three-stage executor pipeline.

Covers the PR-3 acceptance contract:
  * chunk-aligned region writes round-trip byte-identically vs the RMW
    slow path (zarr + n5, across available codecs);
  * the decoded-chunk LRU absorbs repeated decodes under overlapping
    halo'd reads (hit counter asserted) and is invalidated by writes;
  * pipeline determinism — depth 1 vs depth 3 produce identical outputs
    for a staged task and for the halo'd two-pass watershed (whose pass 2
    is ``pipeline_safe = False``);
  * stage occupancy counters are populated by a staged depth-3 dispatch;
  * blosc hardening (decode-size clamp, shuffle validation at read_meta).
"""

import os
import threading

import numpy as np
import pytest

from cluster_tools_tpu.obs import metrics as obs_metrics
from cluster_tools_tpu.obs import trace as obs_trace
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.tasks.threshold import ThresholdTask
from cluster_tools_tpu.utils import blosc as blosc_mod
from cluster_tools_tpu.utils import store


COMPRESSIONS = [None, "gzip"] + (["blosc"] if blosc_mod.available() else [])


@pytest.fixture
def traced(tmp_path):
    """Enable tracing (metrics on) for one test, process-locally."""
    obs_metrics.reset()
    obs_trace.enable(str(tmp_path / "trace"), "io_test", export_env=False)
    yield
    obs_trace.disable()
    obs_metrics.reset()


def _chunk_files(ds_path):
    """{relpath: bytes} of every chunk file under a dataset directory."""
    out = {}
    for dp, _, fs in os.walk(ds_path):
        for f in fs:
            if f.startswith(".") or f == "attributes.json":
                continue
            p = os.path.join(dp, f)
            with open(p, "rb") as fh:
                out[os.path.relpath(p, ds_path)] = fh.read()
    return out


# ---------------------------------------------------------------------------
# chunk-aligned write fast path


@pytest.mark.parametrize("ext", [".zarr", ".n5"])
@pytest.mark.parametrize("compression", COMPRESSIONS)
def test_aligned_write_byte_identical_vs_rmw(tmp_path, ext, compression):
    """The same data written through the chunk-aligned fast path (one
    aligned region write) and through the RMW slow path (two misaligned
    partial writes) must produce byte-identical chunk files."""
    shape, chunks = (8, 16, 16), (4, 8, 8)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 1000, shape).astype("uint64")

    f_fast = store.file_reader(str(tmp_path / ("fast" + ext)))
    ds_fast = f_fast.create_dataset(
        "x", shape=shape, dtype="uint64", chunks=chunks,
        compression=compression,
    )
    ds_fast[:] = data  # every chunk fully covered -> aligned fast path

    f_slow = store.file_reader(str(tmp_path / ("slow" + ext)))
    ds_slow = f_slow.create_dataset(
        "x", shape=shape, dtype="uint64", chunks=chunks,
        compression=compression,
    )
    ds_slow[0:3] = data[0:3]  # partial cover -> RMW
    ds_slow[3:8] = data[3:8]  # partial cover over the same chunks -> RMW

    np.testing.assert_array_equal(ds_fast[:], data)
    np.testing.assert_array_equal(ds_slow[:], data)
    fast_files = _chunk_files(os.path.join(str(tmp_path / ("fast" + ext)), "x"))
    slow_files = _chunk_files(os.path.join(str(tmp_path / ("slow" + ext)), "x"))
    assert fast_files and fast_files.keys() == slow_files.keys()
    assert fast_files == slow_files


def test_aligned_write_counter_and_rmw_preserves_content(tmp_path, traced):
    ds = store.file_reader(str(tmp_path / "d.zarr")).create_dataset(
        "x", shape=(8, 16, 16), dtype="uint16", chunks=(4, 8, 8),
        compression="gzip",
    )
    base = np.arange(8 * 16 * 16, dtype="uint16").reshape(8, 16, 16)
    ds[:] = base
    aligned = obs_metrics.snapshot()["counters"].get(
        "store.aligned_chunk_writes", 0
    )
    assert aligned == 8  # (8,16,16)/(4,8,8) -> every chunk took the fast path
    # a misaligned write goes through RMW and must preserve the rest
    ds[2:5, 3:9, 3:9] = 7
    expect = base.copy()
    expect[2:5, 3:9, 3:9] = 7
    np.testing.assert_array_equal(ds[:], expect)
    after = obs_metrics.snapshot()["counters"].get(
        "store.aligned_chunk_writes", 0
    )
    assert after == aligned  # no chunk of the partial write was aligned


def test_threaded_region_write_matches_serial(tmp_path):
    data = np.random.default_rng(1).random((8, 16, 16)).astype("float32")
    for n_threads, name in ((1, "serial"), (4, "threaded")):
        ds = store.file_reader(str(tmp_path / f"{name}.n5")).create_dataset(
            "x", shape=data.shape, dtype="float32", chunks=(4, 8, 8),
            compression="gzip",
        )
        store.set_read_threads(ds, n_threads)
        ds[:] = data
    s = _chunk_files(str(tmp_path / "serial.n5" / "x"))
    t = _chunk_files(str(tmp_path / "threaded.n5" / "x"))
    assert s == t


# ---------------------------------------------------------------------------
# decoded-chunk LRU


def test_chunk_cache_hits_under_overlapping_halo_reads(tmp_path, traced):
    store._CHUNK_CACHE.clear()
    ds = store.file_reader(str(tmp_path / "d.n5")).create_dataset(
        "x", shape=(8, 16, 16), dtype="uint32", chunks=(4, 8, 8),
        compression="gzip",
    )
    data = np.arange(8 * 16 * 16, dtype="uint32").reshape(8, 16, 16)
    ds[:] = data
    obs_metrics.reset()
    # two halo'd reads of neighboring blocks: the four chunks their outer
    # boxes share must decode once and hit the cache on the second read
    a = ds[0:6, 0:12, 0:16]
    b = ds[2:8, 4:16, 0:16]
    np.testing.assert_array_equal(a, data[0:6, 0:12, 0:16])
    np.testing.assert_array_equal(b, data[2:8, 4:16, 0:16])
    counters = obs_metrics.snapshot()["counters"]
    assert counters.get("store.chunk_cache_hits", 0) >= 4
    # a second identical read is served fully from cache
    before = counters.get("store.chunks_read", 0)
    np.testing.assert_array_equal(ds[0:6, 0:12, 0:16], a)
    counters = obs_metrics.snapshot()["counters"]
    assert counters.get("store.chunks_read", 0) == before


def test_chunk_cache_invalidated_by_write(tmp_path, traced):
    store._CHUNK_CACHE.clear()
    ds = store.file_reader(str(tmp_path / "d.zarr")).create_dataset(
        "x", shape=(4, 8, 8), dtype="uint8", chunks=(4, 8, 8),
        compression="gzip",
    )
    ds[:] = np.ones((4, 8, 8), dtype="uint8")
    assert int(ds[:].sum()) == 4 * 8 * 8  # populates the cache
    ds[:] = np.full((4, 8, 8), 3, dtype="uint8")
    np.testing.assert_array_equal(ds[:], np.full((4, 8, 8), 3, "uint8"))


def test_chunk_cache_cross_instance_freshness(tmp_path):
    """A second Dataset handle over the same path (or another process —
    same mechanism: the stat signature changes on os.replace) must never
    see stale cached content."""
    store._CHUNK_CACHE.clear()
    path = str(tmp_path / "d.n5")
    ds1 = store.file_reader(path).create_dataset(
        "x", shape=(4, 8, 8), dtype="int32", chunks=(4, 8, 8),
        compression=None,
    )
    ds1[:] = np.full((4, 8, 8), 1, "int32")
    assert int(ds1[0, 0, 0]) == 1
    ds2 = store.file_reader(path)["x"]
    ds2[:] = np.full((4, 8, 8), 2, "int32")
    np.testing.assert_array_equal(ds1[:], np.full((4, 8, 8), 2, "int32"))


# ---------------------------------------------------------------------------
# blosc hardening (satellites)


def test_normalize_blosc_shuffle_validation():
    # numcodecs AUTOSHUFFLE (-1) resolves like numcodecs does: byte shuffle
    # for multi-byte types, none for single-byte
    assert store._normalize_blosc({"shuffle": -1}, itemsize=8)["shuffle"] == 1
    assert store._normalize_blosc({"shuffle": -1}, itemsize=1)["shuffle"] == 0
    for ok in (0, 1, 2):
        assert store._normalize_blosc({"shuffle": ok})["shuffle"] == ok
    with pytest.raises(ValueError):
        store._normalize_blosc({"shuffle": 5})


@pytest.mark.parametrize("itemsize,expect", [(8, 1), (1, 0)])
def test_read_meta_maps_autoshuffle(tmp_path, itemsize, expect):
    """A zarr array written by numcodecs with shuffle=-1 must read back
    with a writable ({0,1,2}) shuffle value."""
    import json

    path = str(tmp_path / "ext.zarr")
    os.makedirs(path)
    dtype = "<u8" if itemsize == 8 else "|u1"
    with open(os.path.join(path, ".zarray"), "w") as f:
        json.dump({
            "zarr_format": 2, "shape": [4, 4], "chunks": [4, 4],
            "dtype": dtype, "fill_value": 0, "order": "C", "filters": None,
            "compressor": {"id": "blosc", "cname": "lz4", "clevel": 5,
                           "shuffle": -1, "blocksize": 0},
        }, f)
    spec = store._ZarrFormat.read_meta(path)
    assert spec["compression"]["shuffle"] == expect


@pytest.mark.skipif(not blosc_mod.available(), reason="no system libblosc")
def test_blosc_decompress_expected_nbytes_clamp():
    raw = bytes(range(256)) * 64  # 16 KiB
    frame = blosc_mod.compress(raw, typesize=1)
    assert blosc_mod.decompress(frame, expected_nbytes=len(raw)) == raw
    with pytest.raises(ValueError, match="expected at most"):
        blosc_mod.decompress(frame, expected_nbytes=len(raw) // 2)


@pytest.mark.skipif(not blosc_mod.available(), reason="no system libblosc")
def test_blosc_pre116_fallback_clamps(monkeypatch):
    """Force the no-validate (pre-1.16) branch: the header-claimed nbytes
    must still be bounded by expected_nbytes."""
    real = blosc_mod._load()

    class _NoValidate:
        def __getattr__(self, name):
            if name == "blosc_cbuffer_validate":
                raise AttributeError(name)
            return getattr(real, name)

    monkeypatch.setattr(blosc_mod, "_lib", _NoValidate())
    monkeypatch.setattr(blosc_mod, "_lib_checked", True)
    raw = b"x" * 4096
    frame = blosc_mod.compress(raw, typesize=1)
    assert blosc_mod.decompress(frame, expected_nbytes=4096) == raw
    with pytest.raises(ValueError, match="expected at most"):
        blosc_mod.decompress(frame, expected_nbytes=100)


# ---------------------------------------------------------------------------
# three-stage executor pipeline


def _run_threshold(tmp_path, key, depth):
    path = str(tmp_path / "data.n5")
    if not os.path.exists(path):
        rng = np.random.default_rng(3)
        store.file_reader(path).create_dataset(
            "x", data=rng.random((16, 32, 32)).astype("float32"),
            chunks=(4, 8, 8),
        )
    config_dir = str(tmp_path / f"configs_{key}")
    cfg.write_global_config(
        config_dir,
        {"block_shape": [4, 8, 8], "target": "tpu", "device_batch_size": 2,
         "devices": [0], "pipeline_depth": depth},
    )
    t = ThresholdTask(
        str(tmp_path / f"tmp_{key}"), config_dir,
        input_path=path, input_key="x",
        output_path=path, output_key=key,
    )
    assert build([t])
    return store.file_reader(path, "r")[key][:], t


def test_staged_pipeline_depth_determinism(tmp_path):
    """depth 1 (serial loop) and depth 3 (three-stage pipeline) must write
    identical outputs, and the depth-3 run must populate the per-stage
    records."""
    out1, _ = _run_threshold(tmp_path, "d1", 1)
    out3, t3 = _run_threshold(tmp_path, "d3", 3)
    np.testing.assert_array_equal(out1, out3)
    labels = {r["label"] for r in t3.output().read()["timings"]}
    assert {"stage_read_total", "stage_compute_total",
            "stage_write_total"} <= labels


def test_staged_pipeline_stage_counters(tmp_path, traced):
    _run_threshold(tmp_path, "ctr", 3)
    counters = obs_metrics.snapshot()["counters"]
    for key in ("executor.stage_batches", "executor.stage_read_s",
                "executor.stage_compute_s", "executor.stage_write_s"):
        assert counters.get(key, 0) > 0, (key, counters)


def test_staged_pipeline_overlaps_stages(tmp_env):
    """Read/write stages really run off the compute thread at depth > 1."""
    from cluster_tools_tpu.runtime.task import BlockTask

    tmp_folder, config_dir = tmp_env
    cfg.write_global_config(
        config_dir,
        {"block_shape": [4, 32, 32], "target": "tpu",
         "device_batch_size": 1, "devices": [0], "pipeline_depth": 3},
    )
    seen = {"read": set(), "compute": set(), "write": set()}

    class StagedTask(BlockTask):
        task_name = "staged_probe"

        def get_shape(self):
            return (32, 32, 32)

        def read_batch(self, block_ids, blocking, config):
            seen["read"].add(threading.get_ident())
            return list(block_ids)

        def compute_batch(self, payload, blocking, config):
            seen["compute"].add(threading.get_ident())
            return payload

        def write_batch(self, result, blocking, config):
            seen["write"].add(threading.get_ident())

        def process_block_batch(self, block_ids, blocking, config):
            self.write_batch(
                self.compute_batch(
                    self.read_batch(block_ids, blocking, config),
                    blocking, config),
                blocking, config)

        def process_block(self, block_id, blocking, config):
            self.process_block_batch([block_id], blocking, config)

    t = StagedTask(tmp_folder, config_dir)
    assert build([t])
    assert len(t.output().read()["done"]) == 8
    assert len(seen["compute"]) == 1  # serialized compute stage
    assert not (seen["read"] & seen["compute"])
    assert not (seen["write"] & seen["compute"])


def test_staged_poisoned_batch_falls_back_per_block(tmp_env):
    from cluster_tools_tpu.runtime.task import BlockTask

    tmp_folder, config_dir = tmp_env
    cfg.write_global_config(
        config_dir,
        {"block_shape": [4, 32, 32], "target": "tpu",
         "device_batch_size": 2, "devices": [0], "pipeline_depth": 2},
    )

    class PoisonStagedTask(BlockTask):
        task_name = "poison_staged"

        def __init__(self, *args, out=None, **kw):
            super().__init__(*args, **kw)
            self.out = out if out is not None else {}

        def get_shape(self):
            return (32, 32, 32)

        def read_batch(self, block_ids, blocking, config):
            return list(block_ids)

        def compute_batch(self, payload, blocking, config):
            if 2 in payload:
                raise RuntimeError("poisoned staged batch")
            return payload

        def write_batch(self, result, blocking, config):
            self.out.setdefault("written", []).extend(result)

        def process_block(self, block_id, blocking, config):
            # per-block fallback path (also poisoned for block 3)
            if block_id == 3:
                raise RuntimeError("block 3 is truly broken")
            self.out.setdefault("written", []).append(block_id)

    out = {}
    t = PoisonStagedTask(tmp_folder, config_dir, out=out)
    from cluster_tools_tpu.runtime.task import FailedBlocksError

    with pytest.raises(FailedBlocksError):
        build([t])
    status = t.output().read()
    assert status["failed"] == [3]
    assert sorted(set(out["written"])) == [b for b in range(8) if b != 3]


def test_unsafe_task_serializes_on_tpu_executor(tmp_env):
    """pipeline_safe=False forces the strictly serial loop even when the
    task implements the split protocol and depth > 1."""
    from cluster_tools_tpu.runtime.task import BlockTask

    tmp_folder, config_dir = tmp_env
    cfg.write_global_config(
        config_dir,
        {"block_shape": [4, 32, 32], "target": "tpu",
         "device_batch_size": 1, "devices": [0], "pipeline_depth": 3},
    )
    threads = set()

    class UnsafeStagedTask(BlockTask):
        task_name = "unsafe_staged"
        pipeline_safe = False

        def get_shape(self):
            return (32, 32, 32)

        def read_batch(self, block_ids, blocking, config):
            threads.add(threading.get_ident())
            return list(block_ids)

        def compute_batch(self, payload, blocking, config):
            threads.add(threading.get_ident())
            return payload

        def write_batch(self, result, blocking, config):
            threads.add(threading.get_ident())

        def process_block_batch(self, block_ids, blocking, config):
            self.write_batch(
                self.compute_batch(
                    self.read_batch(block_ids, blocking, config),
                    blocking, config),
                blocking, config)

        def process_block(self, block_id, blocking, config):
            self.process_block_batch([block_id], blocking, config)

    t = UnsafeStagedTask(tmp_folder, config_dir)
    assert build([t])
    assert len(t.output().read()["done"]) == 8
    assert len(threads) == 1  # everything on the dispatching thread


@pytest.mark.timeout(600)
def test_two_pass_watershed_depth_determinism(tmp_path, rng):
    """The halo'd two-pass watershed — pass 2 reads what same-dispatch
    neighbors wrote (``pipeline_safe = False``) — must produce identical
    outputs at pipeline_depth 1 and 3."""
    from scipy import ndimage

    from cluster_tools_tpu.workflows.watershed import WatershedWorkflow

    raw = ndimage.gaussian_filter(rng.random((24, 48, 48)), (1.0, 2.0, 2.0))
    raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")
    path = str(tmp_path / "d.n5")
    store.file_reader(path).create_dataset(
        "bnd", data=raw, chunks=(12, 24, 24)
    )
    conf = {"threshold": 0.5, "sigma_seeds": 1.6, "size_filter": 10,
            "halo": [4, 8, 8], "apply_dt_2d": False, "apply_ws_2d": False}

    def run(depth):
        config_dir = str(tmp_path / f"configs_{depth}")
        cfg.write_global_config(
            config_dir,
            {"block_shape": [12, 24, 24], "target": "tpu",
             "device_batch_size": 1, "devices": [0],
             "pipeline_depth": depth},
        )
        cfg.write_config(config_dir, "two_pass_watershed", conf)
        wf = WatershedWorkflow(
            str(tmp_path / f"tmp_{depth}"), config_dir,
            input_path=path, input_key="bnd",
            output_path=path, output_key=f"ws_{depth}",
            two_pass=True,
        )
        assert build([wf])
        return store.file_reader(path, "r")[f"ws_{depth}"][:]

    np.testing.assert_array_equal(run(1), run(3))
