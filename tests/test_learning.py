"""Learning component: edge labels, RF train/predict, region features,
image filter."""

import os
import pickle

import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


@pytest.fixture
def training_volume(tmp_path, rng):
    """Blocky GT segmentation + noisy boundary map + watershed-ish
    oversegmentation whose fragments respect GT boundaries."""
    shape = (16, 32, 32)
    gt = np.zeros(shape, dtype="uint64")
    gt[:, :16, :16] = 1
    gt[:, :16, 16:] = 2
    gt[:, 16:, :16] = 3
    gt[:, 16:, 16:] = 4
    # oversegmentation: split each gt quadrant in z halves
    ws = (gt * 2 + (np.arange(shape[0]) >= 8)[:, None, None]).astype("uint64")
    # boundary map: high on gt edges
    from conftest import boundary_from_gt

    bnd = boundary_from_gt(gt, rng, noise=0.05)
    path = str(tmp_path / "train.n5")
    f = file_reader(path)
    f.create_dataset("gt", data=gt, chunks=(8, 16, 16))
    f.create_dataset("ws", data=ws, chunks=(8, 16, 16))
    f.create_dataset("bnd", data=bnd, chunks=(8, 16, 16))
    return path


class TestLearningWorkflow:
    def test_rf_learns_boundaries(self, tmp_path, training_volume):
        from cluster_tools_tpu.tasks.costs import ProbsToCostsTask
        from cluster_tools_tpu.tasks.learning import (
            EDGE_LABELS_NAME,
            EDGE_PROBS_NAME,
            PredictEdgeProbabilitiesTask,
        )
        from cluster_tools_tpu.workflows.learning import LearningWorkflow

        path = training_volume
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        rf_path = str(tmp_path / "rf.pkl")

        wf = LearningWorkflow(
            tmp_folder, config_dir,
            input_dict={"ds0": (path, "bnd")},
            labels_dict={"ds0": (path, "ws")},
            groundtruth_dict={"ds0": (path, "gt")},
            output_path=rf_path,
        )
        assert build([wf])
        assert os.path.exists(rf_path)
        with open(rf_path, "rb") as f:
            rf = pickle.load(f)

        sub = os.path.join(tmp_folder, "ds0")
        labels = np.load(os.path.join(sub, EDGE_LABELS_NAME))
        assert set(np.unique(labels)) <= {0, 1}
        assert (labels == 1).sum() > 0 and (labels == 0).sum() > 0

        # predict on the training problem: the RF must separate the classes
        predict = PredictEdgeProbabilitiesTask(
            sub, config_dir, rf_path=rf_path,
            input_path=path, input_key="ws",
        )
        assert build([predict])
        probs = np.load(os.path.join(sub, EDGE_PROBS_NAME))
        assert probs.shape == labels.shape
        assert probs[labels == 1].mean() > 0.7
        assert probs[labels == 0].mean() < 0.3

        # costs from RF probabilities: repulsive on boundaries
        costs_task = ProbsToCostsTask(
            sub, config_dir,
            probs_path=os.path.join(sub, EDGE_PROBS_NAME),
        )
        assert build([costs_task])
        costs = np.load(os.path.join(sub, "costs.npy"))
        assert (costs[labels == 1] < 0).mean() > 0.9
        assert (costs[labels == 0] > 0).mean() > 0.9


class TestRegionFeatures:
    def test_matches_numpy_groupby(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.region_features import (
            MergeRegionFeaturesTask,
            RegionFeaturesTask,
            load_region_features,
        )

        shape = (16, 32, 32)
        labels = rng.integers(1, 20, shape).astype("uint64")
        values = rng.random(shape).astype("float32")
        path = str(tmp_path / "rf.n5")
        f = file_reader(path)
        f.create_dataset("seg", data=labels, chunks=(8, 16, 16))
        f.create_dataset("raw", data=values, chunks=(8, 16, 16))

        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        block = RegionFeaturesTask(
            tmp_folder, config_dir,
            input_path=path, input_key="raw",
            labels_path=path, labels_key="seg",
        )
        merge = MergeRegionFeaturesTask(
            tmp_folder, config_dir, dependencies=[block],
            input_path=path, input_key="raw",
        )
        assert build([merge])
        feats = load_region_features(tmp_folder)
        for seg_id in range(1, 20):
            sel = labels == seg_id
            np.testing.assert_allclose(feats[seg_id, 0], sel.sum(), rtol=1e-6)
            np.testing.assert_allclose(
                feats[seg_id, 1], values[sel].mean(), rtol=1e-4
            )
            np.testing.assert_allclose(
                feats[seg_id, 2], values[sel].min(), rtol=1e-5
            )
            np.testing.assert_allclose(
                feats[seg_id, 3], values[sel].max(), rtol=1e-5
            )


class TestImageFilter:
    def test_gaussian_response(self, tmp_path, rng):
        from cluster_tools_tpu.ops import filters as filter_ops
        from cluster_tools_tpu.tasks.region_features import ImageFilterTask

        import jax.numpy as jnp

        shape = (16, 32, 32)
        raw = rng.random(shape).astype("float32")
        path = str(tmp_path / "if.n5")
        file_reader(path).create_dataset("raw", data=raw, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        task = ImageFilterTask(
            tmp_folder, config_dir,
            input_path=path, input_key="raw",
            output_path=path, output_key="smoothed",
            filter_name="gaussianSmoothing", sigma=1.5,
        )
        assert build([task])
        got = file_reader(path, "r")["smoothed"][:]
        want = np.asarray(filter_ops.gaussian(jnp.asarray(raw), 1.5))
        c = 8  # away from volume borders where halo padding differs
        np.testing.assert_allclose(
            got[4:-4, c:-c, c:-c], want[4:-4, c:-c, c:-c], rtol=1e-3, atol=1e-4
        )

    def test_hessian_multichannel(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.region_features import ImageFilterTask

        shape = (8, 16, 16)
        raw = rng.random(shape).astype("float32")
        path = str(tmp_path / "ih.n5")
        file_reader(path).create_dataset("raw", data=raw, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs_h")
        tmp_folder = str(tmp_path / "tmp_h")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        task = ImageFilterTask(
            tmp_folder, config_dir,
            input_path=path, input_key="raw",
            output_path=path, output_key="hess",
            filter_name="hessianOfGaussianEigenvalues", sigma=1.0,
        )
        assert build([task])
        hess = file_reader(path, "r")["hess"]
        assert hess.shape == (3,) + shape
        got = hess[:]
        # eigenvalues sorted descending along the channel axis
        assert (got[0] >= got[1]).all() and (got[1] >= got[2]).all()
