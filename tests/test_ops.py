"""Kernel tests vs host oracles (scipy) — the recompute-and-compare idiom of the
reference test suite (SURVEY.md §4)."""

import numpy as np
import pytest
from scipy import ndimage

import jax.numpy as jnp


class TestFilters:
    def test_gaussian_matches_scipy(self, rng):
        from cluster_tools_tpu.ops.filters import gaussian

        x = rng.random((20, 30)).astype(np.float32)
        got = np.asarray(gaussian(x, 1.5))
        want = ndimage.gaussian_filter(x, 1.5, mode="reflect", truncate=4.0)
        np.testing.assert_allclose(got, want, atol=5e-3)

    def test_gaussian_anisotropic(self, rng):
        from cluster_tools_tpu.ops.filters import gaussian

        x = rng.random((8, 24, 24)).astype(np.float32)
        got = np.asarray(gaussian(x, (0.0, 2.0, 2.0)))
        want = np.stack(
            [ndimage.gaussian_filter(s, 2.0, mode="reflect") for s in x]
        )
        np.testing.assert_allclose(got, want, atol=5e-3)

    def test_min_max_filter(self, rng):
        from cluster_tools_tpu.ops.filters import maximum_filter, minimum_filter

        x = rng.random((16, 16, 16)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(minimum_filter(x, 3)),
            ndimage.minimum_filter(x, 3, mode="reflect"),
        )
        np.testing.assert_allclose(
            np.asarray(maximum_filter(x, 3)),
            ndimage.maximum_filter(x, 3, mode="reflect"),
        )

    def test_normalize(self, rng):
        from cluster_tools_tpu.ops.filters import normalize

        x = (rng.random((10, 10)) * 100 + 5).astype(np.float32)
        y = np.asarray(normalize(x))
        assert y.min() == pytest.approx(0.0, abs=1e-5)
        assert y.max() == pytest.approx(1.0, abs=1e-4)


class TestCC:
    @pytest.mark.parametrize("connectivity", [1, 3])
    def test_matches_scipy_random(self, rng, connectivity):
        from cluster_tools_tpu.ops.cc import connected_components

        mask = rng.random((12, 12, 12)) > 0.65
        got, n_got = connected_components(jnp.asarray(mask), connectivity)
        got = np.asarray(got)
        structure = ndimage.generate_binary_structure(3, connectivity)
        want, n_want = ndimage.label(mask, structure=structure)
        assert int(n_got) == n_want
        # same partition: bijection between label sets
        pairs = np.unique(
            np.stack([got[mask], want[mask]], axis=1), axis=0
        )
        assert len(pairs) == n_want
        assert len(np.unique(pairs[:, 0])) == n_want
        assert len(np.unique(pairs[:, 1])) == n_want
        assert (got[~mask] == 0).all()

    def test_snake(self):
        # a long winding 1-voxel path — worst case for naive propagation,
        # pointer jumping must converge fast
        from cluster_tools_tpu.ops.cc import connected_components

        mask = np.zeros((1, 16, 16), dtype=bool)
        for i in range(16):
            mask[0, i, :] = True if i % 2 == 0 else False
            if i % 4 == 1:
                mask[0, i, -1] = True
            if i % 4 == 3:
                mask[0, i, 0] = True
        got, n = connected_components(jnp.asarray(mask), 1)
        want, n_want = ndimage.label(mask)
        assert int(n) == n_want == 1

    def test_empty_and_full(self):
        from cluster_tools_tpu.ops.cc import connected_components

        empty = np.zeros((8, 8), dtype=bool)
        labels, n = connected_components(jnp.asarray(empty), 1)
        assert int(n) == 0 and (np.asarray(labels) == 0).all()
        full = np.ones((8, 8), dtype=bool)
        labels, n = connected_components(jnp.asarray(full), 1)
        assert int(n) == 1 and (np.asarray(labels) == 1).all()


class TestDT:
    @pytest.mark.parametrize("shape", [(24, 24), (10, 18, 14)])
    def test_matches_scipy(self, rng, shape):
        from cluster_tools_tpu.ops.dt import distance_transform

        fg = rng.random(shape) > 0.3
        got = np.asarray(distance_transform(jnp.asarray(fg)))
        want = ndimage.distance_transform_edt(fg)
        np.testing.assert_allclose(got, want, atol=1e-3)

    def test_anisotropic(self, rng):
        from cluster_tools_tpu.ops.dt import distance_transform

        fg = rng.random((10, 16, 16)) > 0.3
        pitch = (2.0, 1.0, 1.0)
        got = np.asarray(distance_transform(jnp.asarray(fg), pixel_pitch=pitch))
        want = ndimage.distance_transform_edt(fg, sampling=pitch)
        np.testing.assert_allclose(got, want, atol=1e-3)

    def test_2d_stack_mode(self, rng):
        from cluster_tools_tpu.ops.dt import distance_transform_2d_stack

        fg = rng.random((6, 20, 20)) > 0.3
        got = np.asarray(distance_transform_2d_stack(jnp.asarray(fg)))
        want = np.stack([ndimage.distance_transform_edt(s) for s in fg])
        np.testing.assert_allclose(got, want, atol=1e-3)

    def test_all_foreground_saturates(self):
        from cluster_tools_tpu.ops.dt import distance_transform

        fg = np.ones((8, 8), dtype=bool)
        got = np.asarray(distance_transform(jnp.asarray(fg)))
        assert (got > 1e4).all()  # no background → distance saturates at BIG


class TestWatershed:
    def test_two_basin_flood(self):
        from cluster_tools_tpu.ops.watershed import seeded_watershed

        # height map with a ridge in the middle: two seeds flood their halves
        h = np.zeros((9, 9), dtype=np.float32)
        h[:, 4] = 1.0
        seeds = np.zeros((9, 9), dtype=np.int32)
        seeds[4, 1] = 1
        seeds[4, 7] = 2
        labels = np.asarray(seeded_watershed(jnp.asarray(h), jnp.asarray(seeds)))
        assert (labels[:, :4] == 1).all()
        assert (labels[:, 5:] == 2).all()
        assert set(np.unique(labels[:, 4])) <= {1, 2}

    def test_full_coverage_and_seed_preservation(self, rng):
        from cluster_tools_tpu.ops.watershed import seeded_watershed

        h = rng.random((12, 12, 12)).astype(np.float32)
        seeds = np.zeros_like(h, dtype=np.int32)
        pts = [(2, 2, 2), (9, 9, 9), (2, 9, 5)]
        for i, p in enumerate(pts):
            seeds[p] = i + 1
        labels = np.asarray(
            seeded_watershed(jnp.asarray(h), jnp.asarray(seeds))
        )
        assert (labels > 0).all()  # every voxel flooded
        for i, p in enumerate(pts):
            assert labels[p] == i + 1
        # each label region is connected (watershed invariant,
        # reference test_watershed.py:23-42 idiom)
        for i in range(1, 4):
            _, n = ndimage.label(labels == i)
            assert n == 1

    def test_cc_sweep_and_propagate_agree(self, rng):
        """Sweep-based CC (TPU path) must match neighbor-propagation CC and
        the scipy oracle across connectivities and modes."""
        import jax

        from cluster_tools_tpu.ops import _backend
        from cluster_tools_tpu.ops import cc as C

        mask = rng.random((10, 20, 20)) > 0.55
        results = {}
        for mode in ("seq", "assoc"):
            with _backend.force_sweep_mode(mode):
                for conn in (1, 3):
                    for per_slice in (False, True):
                        labels, n = C.connected_components(
                            jnp.asarray(mask), connectivity=conn,
                            per_slice=per_slice,
                        )
                        results[(mode, conn, per_slice)] = (
                            np.asarray(labels), int(n)
                        )
        for key in [k for k in results if k[0] == "seq"]:
            got, n_got = results[("assoc",) + key[1:]]
            want, n_want = results[key]
            np.testing.assert_array_equal(got, want)
            assert n_got == n_want
        # oracle
        want, n_want = C.connected_components_np(mask, connectivity=1)
        got, n_got = results[("assoc", 1, False)]
        assert n_got == n_want
        pairs = np.unique(
            np.stack([got[mask], want[mask]], axis=1), axis=0
        )
        assert len(pairs) == n_want

    def test_assoc_and_seq_sweeps_agree(self, rng):
        """The associative-scan sweep pair (TPU default) must compute the same
        fixpoint as the sequential lax.scan pair (CPU default): both evaluate
        the identical Gauss–Seidel carry chain, one in log-depth, one
        sequentially."""
        import jax

        from cluster_tools_tpu.ops import _backend
        from cluster_tools_tpu.ops import watershed as W

        h = rng.random((10, 24, 24)).astype(np.float32)
        seeds = np.zeros_like(h, dtype=np.int32)
        for i, p in enumerate([(2, 3, 3), (8, 20, 20), (5, 3, 20), (1, 20, 4)]):
            seeds[p] = i + 1
        mask = rng.random(h.shape) > 0.05
        seeds[~mask] = 0
        results = {}
        for mode in ("seq", "assoc"):
            with _backend.force_sweep_mode(mode):
                for per_slice in (False, True):
                    results[(mode, per_slice)] = np.asarray(
                        W.seeded_watershed(
                            jnp.asarray(h), jnp.asarray(seeds),
                            mask=jnp.asarray(mask), per_slice=per_slice,
                        )
                    )
        for per_slice in (False, True):
            np.testing.assert_array_equal(
                results[("seq", per_slice)], results[("assoc", per_slice)]
            )

    def test_all_regions_connected_realistic(self, rng):
        # ghost-label regression: every watershed region must be connected,
        # including under plateaus/ties on a realistic smoothed boundary map
        from cluster_tools_tpu.ops import dt, filters, watershed

        raw = rng.random((12, 40, 40)).astype(np.float32)
        bnd = np.asarray(filters.gaussian(jnp.asarray(raw), (1.0, 3.0, 3.0)))
        bnd = (bnd - bnd.min()) / (bnd.max() - bnd.min())
        x = jnp.asarray(bnd)
        fg = x < 0.5
        d = dt.distance_transform(fg)
        seeds, n_seeds = watershed.dt_seeds(d, sigma=2.0)
        hm = watershed.make_hmap(x, d, alpha=0.8)
        lab = np.asarray(watershed.seeded_watershed(hm, seeds, mask=fg))
        # tiny unseeded fragments may stay 0 (as in the reference); the bulk floods
        assert (lab[np.asarray(fg)] > 0).mean() > 0.95
        ids = np.unique(lab)
        for i in ids[ids > 0]:
            _, n = ndimage.label(lab == i)
            assert n == 1, f"label {i} split into {n} components"

    def test_mask_respected(self, rng):
        from cluster_tools_tpu.ops.watershed import seeded_watershed

        h = rng.random((10, 10)).astype(np.float32)
        mask = np.zeros((10, 10), dtype=bool)
        mask[:, :5] = True
        seeds = np.zeros((10, 10), dtype=np.int32)
        seeds[5, 2] = 1
        labels = np.asarray(
            seeded_watershed(jnp.asarray(h), jnp.asarray(seeds), jnp.asarray(mask))
        )
        assert (labels[:, 5:] == 0).all()
        assert (labels[:, :5] == 1).all()

    def test_dt_seeds_blobs(self):
        from cluster_tools_tpu.ops.dt import distance_transform
        from cluster_tools_tpu.ops.watershed import dt_seeds

        # two separated discs → exactly two seeds
        fg = np.zeros((32, 32), dtype=bool)
        yy, xx = np.mgrid[:32, :32]
        fg |= (yy - 8) ** 2 + (xx - 8) ** 2 < 25
        fg |= (yy - 24) ** 2 + (xx - 24) ** 2 < 25
        dt = distance_transform(jnp.asarray(fg))
        seeds, n = dt_seeds(dt, sigma=1.0)
        assert int(n) == 2

    def test_size_filter(self, rng):
        from cluster_tools_tpu.ops.watershed import apply_size_filter

        labels = np.zeros((10, 10), dtype=np.int32)
        labels[:5] = 1          # 50 voxels
        labels[5:, :8] = 2      # 40 voxels
        labels[5:, 8:] = 3      # 10 voxels — should be absorbed
        h = rng.random((10, 10)).astype(np.float32)
        out = np.asarray(
            apply_size_filter(jnp.asarray(labels), jnp.asarray(h), 20, 4)
        )
        assert set(np.unique(out)) == {1, 2}
        assert (out > 0).all()


class TestSegmentOps:
    def test_moments(self, rng):
        from cluster_tools_tpu.ops.segment import segment_moments

        labels = rng.integers(0, 5, 1000).astype(np.int32)
        values = rng.random(1000).astype(np.float32)
        c, mean, var = segment_moments(
            jnp.asarray(labels), jnp.asarray(values), 5
        )
        for i in range(5):
            sel = values[labels == i]
            assert int(c[i]) == sel.size
            assert float(mean[i]) == pytest.approx(sel.mean(), abs=1e-5)
            assert float(var[i]) == pytest.approx(sel.var(), abs=1e-5)

    def test_bounding_boxes_and_com(self):
        from cluster_tools_tpu.ops.segment import (
            segment_bounding_boxes,
            segment_center_of_mass,
        )

        labels = np.zeros((8, 8), dtype=np.int32)
        labels[2:5, 3:7] = 1
        begin, end = segment_bounding_boxes(jnp.asarray(labels), 2, 2)
        assert tuple(np.asarray(begin[1])) == (2, 3)
        assert tuple(np.asarray(end[1])) == (5, 7)
        com = np.asarray(segment_center_of_mass(jnp.asarray(labels), 2, 2))
        np.testing.assert_allclose(com[1], [3.0, 4.5], atol=1e-5)

    def test_contingency(self, rng):
        from cluster_tools_tpu.ops.segment import contingency_table

        a = rng.integers(0, 4, (10, 10)).astype(np.uint64)
        b = rng.integers(0, 3, (10, 10)).astype(np.uint64)
        ia, ib, counts = contingency_table(a, b)
        assert counts.sum() == 100
        for x, y, c in zip(ia, ib, counts):
            assert ((a == x) & (b == y)).sum() == c


class TestRelabel:
    def test_device_relabel(self, rng):
        from cluster_tools_tpu.ops.relabel import relabel_consecutive

        labels = rng.choice([0, 5, 17, 99, 1000], size=(64,)).astype(np.int32)
        out, n = relabel_consecutive(jnp.asarray(labels), max_labels=16)
        out = np.asarray(out)
        uniq_in = np.unique(labels)
        nz = uniq_in[uniq_in > 0]
        assert int(n) == len(nz)
        assert (out[labels == 0] == 0).all()
        got_uniq = np.unique(out)
        assert got_uniq.max() == len(nz)
        # order preserved
        for i, v in enumerate(sorted(nz)):
            assert (out[labels == v] == i + 1).all()

    def test_assignment_table(self):
        from cluster_tools_tpu.ops.relabel import apply_assignment_table_np

        labels = np.array([[1, 2], [3, 9]], dtype=np.uint64)
        table = np.array([[1, 10], [2, 20], [3, 30]], dtype=np.uint64)
        out = apply_assignment_table_np(labels, table)
        np.testing.assert_array_equal(out, [[10, 20], [30, 0]])


class TestUnionFind:
    def test_device_matches_host(self, rng):
        from cluster_tools_tpu.ops.unionfind import (
            merge_assignments_device,
            merge_assignments_np,
        )

        n = 500
        pairs = rng.integers(1, n, size=(200, 2)).astype(np.int64)
        a_np, n_np = merge_assignments_np(n, pairs)
        a_dev, n_dev = merge_assignments_device(n, pairs)
        assert n_np == n_dev
        np.testing.assert_array_equal(a_np, a_dev)

    def test_device_empty_pairs(self):
        from cluster_tools_tpu.ops.unionfind import merge_assignments_device

        a, n_new = merge_assignments_device(5, np.zeros((0, 2), dtype=np.int64))
        np.testing.assert_array_equal(a, [0, 1, 2, 3, 4])
        assert n_new == 4


class TestDTSweepModes:
    @pytest.mark.parametrize("pitch", [None, (3.0, 1.0, 2.0)])
    def test_line_scan_assoc_matches_seq(self, rng, pitch):
        """The log-depth EDT line scan must equal the sequential one,
        including anisotropic pitch (pitch enters the assoc index
        arithmetic)."""
        from cluster_tools_tpu.ops import _backend
        from cluster_tools_tpu.ops.dt import distance_transform

        fg = rng.random((8, 20, 20)) > 0.3
        results = {}
        for mode in ("seq", "assoc"):
            with _backend.force_sweep_mode(mode):
                results[mode] = np.asarray(
                    distance_transform(jnp.asarray(fg), pixel_pitch=pitch)
                )
        np.testing.assert_allclose(results["seq"], results["assoc"], atol=1e-4)
        want = ndimage.distance_transform_edt(fg, sampling=pitch)
        np.testing.assert_allclose(results["assoc"], want, atol=1e-3)


class TestDtWatershedValid:
    def test_padding_does_not_inflate_size_filter(self):
        """A small border fragment of a clipped edge block must not survive
        the size filter just because its edge-replicated pad copies inflate
        the voxel count (dt_watershed ``valid`` semantics)."""
        import jax.numpy as jnp

        from cluster_tools_tpu.ops.watershed import dt_watershed

        h, w = 16, 40
        pad_w = 24  # block clipped at the volume border, padded to w + pad_w
        x = np.ones((2, h, w + pad_w), dtype=np.float32)
        # a 2x3=6-voxel foreground pocket touching the clipped border (per
        # slice); edge replication extends it across all 24 pad columns
        x[:, 6:8, w - 3 : w] = 0.0
        x[:, :, w:] = x[:, :, w - 1 : w]  # edge-replicate by hand
        valid = np.zeros(x.shape, dtype=bool)
        valid[:, :, :w] = True

        # without valid: the pocket spans 6 + 2*24 = 54 voxels >= 25 -> kept
        labels_no_valid, _ = dt_watershed(
            jnp.asarray(x), apply_dt_2d=True, apply_ws_2d=True,
            threshold=0.5, sigma_seeds=0.0, size_filter=25,
        )
        assert np.asarray(labels_no_valid)[:, :, : w].max() > 0

        # with valid: true size 6 < 25 -> removed, and no labels in padding
        labels, _ = dt_watershed(
            jnp.asarray(x), valid=jnp.asarray(valid),
            apply_dt_2d=True, apply_ws_2d=True,
            threshold=0.5, sigma_seeds=0.0, size_filter=25,
        )
        labels = np.asarray(labels)
        assert labels.max() == 0
        assert (labels[:, :, w:] == 0).all()


def test_cc_slices_mode_identical(rng):
    """CTT_CC_MODE=slices (per-slice XLA sweeps + z-merge) must produce the
    identical labeling to the default whole-volume propagation."""
    import jax.numpy as jnp
    from scipy import ndimage

    from cluster_tools_tpu.ops import _backend
    from cluster_tools_tpu.ops.cc import connected_components

    mask = rng.random((10, 32, 48)) < 0.45
    mask[3, :, :] = False  # z-disconnected layer exercises the merge
    want_l, want_n = connected_components(jnp.asarray(mask))
    with _backend.force_cc_mode("slices"):
        got_l, got_n = connected_components(jnp.asarray(mask))
    assert int(want_n) == int(got_n)
    assert np.array_equal(np.asarray(want_l), np.asarray(got_l))
    ref_n = ndimage.label(mask)[1]
    assert int(got_n) == ref_n
