"""Watershed workflow tests — invariant idiom of the reference
(test/watershed/test_watershed.py:23-42: shape, foreground coverage, mask
zeroing, per-label connectivity)."""

import os

import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows.watershed import WatershedWorkflow


@pytest.fixture
def boundary_volume(tmp_path, rng):
    raw = ndimage.gaussian_filter(rng.random((24, 48, 48)), (1.0, 2.0, 2.0))
    raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")
    path = str(tmp_path / "d.n5")
    file_reader(path).create_dataset("bnd", data=raw, chunks=(12, 24, 24))
    return path, raw


def _run_ws(tmp_path, path, ws_config, two_pass=False, key="ws", gconf=None):
    config_dir = str(tmp_path / f"configs_{key}")
    tmp_folder = str(tmp_path / f"tmp_{key}")
    cfg.write_global_config(
        config_dir, {"block_shape": [12, 24, 24], **(gconf or {})}
    )
    task_name = "two_pass_watershed" if two_pass else "watershed"
    cfg.write_config(config_dir, task_name, ws_config)
    wf = WatershedWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="bnd",
        output_path=path, output_key=key,
        two_pass=two_pass,
    )
    assert build([wf])
    return file_reader(path, "r")[key][:]


BASE_CONFIG = {
    "threshold": 0.5,
    "sigma_seeds": 1.6,
    "size_filter": 10,
    "halo": [2, 6, 6],
}


def test_watershed_invariants_2d_mode(tmp_path, boundary_volume):
    path, raw = boundary_volume
    ws = _run_ws(tmp_path, path, BASE_CONFIG, key="ws2d")
    fg = raw < 0.5
    assert ws.shape == raw.shape
    assert (ws[fg] > 0).mean() > 0.95
    assert (ws[~fg] == 0).all()
    # 2d mode: each label lives in one z-slice and is connected there
    ids = np.unique(ws)
    for i in ids[ids > 0][::7]:
        zs = np.unique(np.nonzero(ws == i)[0])
        assert len(zs) == 1
        _, n = ndimage.label(ws[zs[0]] == i)
        assert n == 1


@pytest.mark.parametrize("target", ["local", "tpu"])
def test_watershed_invariants_3d_mode(tmp_path, boundary_volume, target):
    path, raw = boundary_volume
    conf = {**BASE_CONFIG, "apply_dt_2d": False, "apply_ws_2d": False}
    ws = _run_ws(
        tmp_path, path, conf, key=f"ws3d_{target}", gconf={"target": target}
    )
    fg = raw < 0.5
    assert (ws[fg] > 0).mean() > 0.95
    assert (ws[~fg] == 0).all()
    # per-label 3d connectivity (sampled)
    ids = np.unique(ws)
    for i in ids[ids > 0][::5]:
        _, n = ndimage.label(ws == i)
        assert n == 1


def test_watershed_block_offsets_disjoint(tmp_path, boundary_volume):
    # single-pass labels of different blocks must live in disjoint id ranges
    path, raw = boundary_volume
    conf = {**BASE_CONFIG, "apply_dt_2d": False, "apply_ws_2d": False}
    ws = _run_ws(tmp_path, path, conf, key="wsoff")
    offset_unit = 12 * 24 * 24
    for bi, z in enumerate(range(0, 24, 12)):
        block_ids = np.unique(ws[z : z + 12, :24, :24])
        block_ids = block_ids[block_ids > 0]
        grid_pos = bi * 4  # block (bi,0,0) in a (2,2,2) grid
        lo = grid_pos * offset_unit
        hi = (grid_pos + 1) * offset_unit
        assert ((block_ids > lo) & (block_ids <= hi)).all()


@pytest.mark.parametrize("target", ["local", "tpu"])
def test_two_pass_boundary_consistency(tmp_path, boundary_volume, target):
    path, raw = boundary_volume
    conf = {**BASE_CONFIG, "apply_dt_2d": False, "apply_ws_2d": False,
            "halo": [4, 8, 8]}
    gconf = {"target": target}
    ws_two = _run_ws(tmp_path, path, conf, two_pass=True,
                     key=f"ws_twopass_{target}", gconf=gconf)
    ws_one = _run_ws(tmp_path, path, conf, two_pass=False,
                     key=f"ws_onepass_{target}", gconf=gconf)

    fg = raw < 0.5
    assert (ws_two[fg] > 0).mean() > 0.9

    def cross_boundary_agreement(ws):
        agree, total = 0, 0
        for z in (12,):  # block boundary plane along axis 0
            a, b = ws[z - 1], ws[z]
            sel = (a > 0) & (b > 0)
            total += sel.sum()
            agree += (a[sel] == b[sel]).sum()
        return agree / max(total, 1)

    # single pass: block-offset labels never agree across the boundary;
    # two-pass: pass-2 blocks continue their neighbors' labels
    assert cross_boundary_agreement(ws_one) == 0.0
    assert cross_boundary_agreement(ws_two) > 0.5


def test_two_pass_with_mask(tmp_path, boundary_volume, rng):
    # pass-2 blocks must respect the mask exactly like pass-1 blocks do —
    # otherwise masked regions get checkerboard-patterned spurious labels
    path, raw = boundary_volume
    f = file_reader(path)
    mask = np.zeros(raw.shape, dtype="uint8")
    mask[:, :24, :] = 1
    f.create_dataset("mask", data=mask, chunks=(12, 24, 24))
    config_dir = str(tmp_path / "configs_tpmask")
    tmp_folder = str(tmp_path / "tmp_tpmask")
    cfg.write_global_config(config_dir, {"block_shape": [12, 24, 24]})
    cfg.write_config(
        config_dir, "two_pass_watershed",
        {**BASE_CONFIG, "halo": [4, 8, 8], "apply_dt_2d": False,
         "apply_ws_2d": False},
    )
    wf = WatershedWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="bnd",
        output_path=path, output_key="ws_tpmask",
        mask_path=path, mask_key="mask",
        two_pass=True,
    )
    assert build([wf])
    ws = file_reader(path, "r")["ws_tpmask"][:]
    assert (ws[:, 24:, :] == 0).all()
    fg = (raw < 0.5) & (mask > 0)
    assert (ws[fg] > 0).mean() > 0.9


def test_watershed_with_mask(tmp_path, boundary_volume, rng):
    path, raw = boundary_volume
    f = file_reader(path)
    mask = np.zeros(raw.shape, dtype="uint8")
    mask[:, :24, :] = 1
    f.create_dataset("mask", data=mask, chunks=(12, 24, 24))
    config_dir = str(tmp_path / "configs_mask")
    tmp_folder = str(tmp_path / "tmp_mask")
    cfg.write_global_config(config_dir, {"block_shape": [12, 24, 24]})
    cfg.write_config(config_dir, "watershed", BASE_CONFIG)
    wf = WatershedWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="bnd",
        output_path=path, output_key="ws_masked",
        mask_path=path, mask_key="mask",
    )
    assert build([wf])
    ws = file_reader(path, "r")["ws_masked"][:]
    assert (ws[:, 24:, :] == 0).all()
    fg = (raw < 0.5) & (mask > 0)
    assert (ws[fg] > 0).mean() > 0.9
