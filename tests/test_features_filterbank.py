"""Filter-bank edge features + exact quantile merge (VERDICT r3 items 2/6).

Oracle idiom (SURVEY.md §4): blocked-and-merged features must reproduce a
single-shot whole-volume recompute — exactly for count/mean/var/min/max, and
exactly for quantiles too when the exact raw-sample merge is active
(reference block_edge_features.py:151-238 filter path; merge is exact as in
merge_edge_features.py:141)."""

import os

import numpy as np
import pytest

from cluster_tools_tpu.ops.rag import (
    boundary_edge_features,
    filter_edge_features,
    merge_edge_features_multi,
)
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader

FILTERS = ["gaussianSmoothing", "gaussianGradientMagnitude"]
SIGMAS = [1.0]


def _apply_bank(data, filters=FILTERS, sigmas=SIGMAS, apply_in_2d=False):
    import jax.numpy as jnp

    from cluster_tools_tpu.ops import filters as F

    x = jnp.asarray(data.astype(np.float32))
    responses = []
    for name in filters:
        for sigma in sigmas:
            resp = np.asarray(
                F.apply_filter(x, name, sigma, apply_in_2d=apply_in_2d),
                dtype=np.float64,
            )
            if resp.ndim == 4:
                responses.extend(resp[..., c] for c in range(resp.shape[-1]))
            else:
                responses.append(resp)
    return responses


@pytest.fixture
def volume(rng):
    labels = rng.integers(1, 20, (4, 8, 8)).astype(np.uint64)
    labels = np.kron(labels, np.ones((4, 4, 4), dtype=np.uint64))
    data = rng.random(labels.shape).astype(np.float32)
    return labels, data


class TestFilterFeatureOps:
    def test_single_group_matches_default_path(self, volume):
        """G=1 filter layout must equal the classic 10-column accumulation on
        the same response."""
        labels, data = volume
        resp = data.astype(np.float64)
        edges_f, feats_f = filter_edge_features(labels, [resp])
        edges_b, feats_b = boundary_edge_features(labels, resp)
        np.testing.assert_array_equal(edges_f, edges_b)
        np.testing.assert_allclose(feats_f, feats_b, rtol=1e-12)

    def test_multichannel_column_count(self, volume):
        """hessianOfGaussianEigenvalues contributes ndim channels → 9*ndim
        columns plus the shared count column."""
        labels, data = volume
        responses = _apply_bank(
            data, filters=["hessianOfGaussianEigenvalues"], sigmas=[1.0]
        )
        assert len(responses) == 3
        edges, feats = filter_edge_features(labels, responses)
        assert feats.shape[1] == 9 * 3 + 1

    def test_blocked_merge_exact_vs_single_shot(self, volume, rng):
        """Blocked partials + exact-sample merge ≡ whole-volume recompute,
        bit-for-bit, on precomputed (identical) responses."""
        labels, data = volume
        responses = _apply_bank(data)
        want_edges, want = filter_edge_features(labels, responses)
        key_of = {tuple(e): i for i, e in enumerate(want_edges)}

        ids_list, feats_list, samples_list = [], [], []
        zb = 8
        for z0 in range(0, labels.shape[0], zb):
            z1 = min(z0 + zb + 1, labels.shape[0])  # +1 upper halo
            lab = labels[z0:z1]
            resp_blk = [r[z0:z1] for r in responses]
            owner = (min(zb, labels.shape[0] - z0),) + labels.shape[1:]
            e, f, s = filter_edge_features(
                lab, resp_blk, owner_shape=owner, return_samples=True
            )
            ids_list.append(
                np.array([key_of[tuple(x)] for x in e], dtype=np.int64)
            )
            feats_list.append(f)
            samples_list.append(s)
        merged = merge_edge_features_multi(
            ids_list, feats_list, len(want_edges), samples_list
        )
        np.testing.assert_allclose(merged, want, rtol=1e-12, atol=1e-12)

    def test_merge_without_samples_degrades_not_crashes(self, volume):
        labels, data = volume
        responses = _apply_bank(data)
        edges, feats = filter_edge_features(labels, responses)
        ids = np.arange(len(edges), dtype=np.int64)
        merged = merge_edge_features_multi([ids], [feats], len(edges), None)
        # single block: weighted average of one partial = the partial
        np.testing.assert_allclose(merged, feats, rtol=1e-12)


class TestFilterFeatureWorkflow:
    def _run(self, tmp_path, labels, data, task_conf, name):
        from cluster_tools_tpu.workflows import (
            EdgeFeaturesWorkflow,
            GraphWorkflow,
        )

        path = str(tmp_path / f"{name}.n5")
        f = file_reader(path)
        f.create_dataset("seg", data=labels, chunks=(8, 16, 16))
        f.create_dataset("bnd", data=data, chunks=(8, 16, 16))
        config_dir = str(tmp_path / f"configs_{name}")
        tmp_folder = str(tmp_path / f"tmp_{name}")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        if task_conf:
            cfg.write_config(config_dir, "block_edge_features", task_conf)
        graph = GraphWorkflow(
            tmp_folder, config_dir, input_path=path, input_key="seg"
        )
        wf = EdgeFeaturesWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="bnd",
            labels_path=path, labels_key="seg",
            dependencies=[graph],
        )
        assert build([wf])
        store = file_reader(os.path.join(tmp_folder, "data.zarr"), "r")
        return (
            store["graph/nodes"][:],
            store["graph/edges"][:],
            store["features/edges"][:],
            store["features/edges"].attrs.get("n_features"),
        )

    def test_filter_bank_blocked_equals_single_shot(self, tmp_path, rng):
        """The workflow with filters/sigmas/halo config must reproduce the
        whole-volume filter-feature recompute (halo ≥ kernel radius so the
        blocked responses match the global ones in the accumulated region)."""
        labels = rng.integers(1, 20, (4, 8, 8)).astype(np.uint64)
        labels = np.kron(labels, np.ones((4, 4, 4), dtype=np.uint64))
        data = rng.random(labels.shape).astype(np.float32)
        nodes, edges, merged, n_feats = self._run(
            tmp_path, labels, data,
            {"filters": FILTERS, "sigmas": SIGMAS, "halo": [4, 4, 4]},
            "fb",
        )
        assert n_feats == merged.shape[1] == 9 * len(FILTERS) * len(SIGMAS) + 1

        responses = _apply_bank(data)
        want_edges, want = filter_edge_features(labels, responses)
        by_pair = {tuple(e): i for i, e in enumerate(want_edges)}
        assert len(edges) == len(want_edges)
        for gid, (ui, vi) in enumerate(edges):
            i = by_pair[(nodes[ui], nodes[vi])]
            np.testing.assert_allclose(
                merged[gid], want[i], rtol=1e-4, atol=1e-6,
                err_msg=f"edge {gid}",
            )

    def test_exact_quantile_mode_default_path(self, tmp_path, rng):
        """VERDICT item 6: quantile_mode='exact' on the classic boundary path
        → zero quantile drift vs the single-shot recompute."""
        labels = rng.integers(1, 30, (4, 8, 8)).astype(np.uint64)
        labels = np.kron(labels, np.ones((4, 4, 4), dtype=np.uint64))
        data = rng.random(labels.shape).astype(np.float32)
        nodes, edges, merged, _ = self._run(
            tmp_path, labels, data, {"quantile_mode": "exact"}, "exact"
        )
        want_edges, want = boundary_edge_features(
            labels, data.astype(np.float64)
        )
        by_pair = {tuple(e): i for i, e in enumerate(want_edges)}
        assert len(edges) == len(want_edges)
        for gid, (ui, vi) in enumerate(edges):
            i = by_pair[(nodes[ui], nodes[vi])]
            np.testing.assert_allclose(
                merged[gid], want[i], rtol=1e-12, atol=1e-12,
                err_msg=f"edge {gid}",
            )

    def test_mode_switch_does_not_poison_merge(self, tmp_path, rng):
        """A sketch-mode rerun in a tmp folder that previously ran exact mode
        must not consume the stale sample chunks (code-review finding): the
        blocks rewrite features/samples with empty chunks and the merge
        rejects the exact path."""
        import shutil

        from cluster_tools_tpu.workflows import (
            EdgeFeaturesWorkflow,
            GraphWorkflow,
        )

        labels = rng.integers(1, 20, (4, 8, 8)).astype(np.uint64)
        labels = np.kron(labels, np.ones((4, 4, 4), dtype=np.uint64))
        data = rng.random(labels.shape).astype(np.float32)
        path = str(tmp_path / "ms.n5")
        f = file_reader(path)
        f.create_dataset("seg", data=labels, chunks=(8, 16, 16))
        f.create_dataset("bnd", data=data, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs_ms")
        tmp_folder = str(tmp_path / "tmp_ms")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        merged = {}
        for mode in ("exact", "sketch"):
            cfg.write_config(
                config_dir, "block_edge_features", {"quantile_mode": mode}
            )
            # force a rerun over the same scratch store (resume would skip)
            shutil.rmtree(os.path.join(tmp_folder, "status"),
                          ignore_errors=True)
            graph = GraphWorkflow(
                tmp_folder, config_dir, input_path=path, input_key="seg"
            )
            wf = EdgeFeaturesWorkflow(
                tmp_folder, config_dir,
                input_path=path, input_key="bnd",
                labels_path=path, labels_key="seg",
                dependencies=[graph],
            )
            assert build([wf])
            store = file_reader(os.path.join(tmp_folder, "data.zarr"), "r")
            merged[mode] = store["features/edges"][:]
        # a fresh sketch-only run is the oracle for the post-switch result
        nodes, edges, fresh, _ = self._run(
            tmp_path, labels, data, {"quantile_mode": "sketch"}, "fresh"
        )
        np.testing.assert_allclose(merged["sketch"], fresh, rtol=1e-12)
        # and it genuinely differs from the exact run's quantile columns
        assert not np.allclose(merged["sketch"][:, 3:8], merged["exact"][:, 3:8])

    def test_filter_bank_feeds_costs(self, tmp_path, rng):
        """Costs must consume the wide layout (count = last column)."""
        from cluster_tools_tpu.tasks.costs import ProbsToCostsTask

        labels = rng.integers(1, 20, (4, 8, 8)).astype(np.uint64)
        labels = np.kron(labels, np.ones((4, 4, 4), dtype=np.uint64))
        data = rng.random(labels.shape).astype(np.float32)
        nodes, edges, merged, _ = self._run(
            tmp_path, labels, data,
            {"filters": FILTERS, "sigmas": SIGMAS, "halo": [4, 4, 4]},
            "costs",
        )
        tmp_folder = str(tmp_path / "tmp_costs")
        config_dir = str(tmp_path / "configs_costs")
        task = ProbsToCostsTask(tmp_folder, config_dir)
        assert build([task])
        costs = np.load(os.path.join(tmp_folder, "costs.npy"))
        assert costs.shape[0] == len(edges)
        assert np.isfinite(costs).all()
