"""Runtime tests: task lifecycle, resume, retry with fault injection.

The fault-injection pattern mirrors the reference's test/retry/failing_task.py
(odd blocks fail on the first attempt; the retry machinery must re-run exactly
those and converge).
"""

import json
import os

import numpy as np
import pytest

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.runtime.task import BlockTask, FailedBlocksError
from cluster_tools_tpu.runtime.workflow import WorkflowBase


class RecordingTask(BlockTask):
    task_name = "recording"

    def __init__(self, *args, shape=(32, 32, 32), out=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.shape = shape
        self.out = out if out is not None else {}

    def get_shape(self):
        return self.shape

    def process_block(self, block_id, blocking, config):
        self.out.setdefault("calls", []).append(block_id)


class FailingTask(RecordingTask):
    task_name = "failing"

    def process_block(self, block_id, blocking, config):
        attempts = self.out.setdefault("attempts", {})
        n = attempts.get(block_id, 0)
        attempts[block_id] = n + 1
        if block_id % 2 == 1 and n == 0:
            raise RuntimeError(f"injected failure for block {block_id}")
        self.out.setdefault("calls", []).append(block_id)


def test_block_task_runs_all_blocks(tmp_env):
    tmp_folder, config_dir = tmp_env
    out = {}
    t = RecordingTask(tmp_folder, config_dir, out=out)
    build([t])
    assert sorted(out["calls"]) == [0, 1]  # (32,32,32) / (16,32,32) = 2 blocks
    status = t.output().read()
    assert status["complete"] and len(status["done"]) == len(out["calls"])


def test_retry_reruns_only_failed_blocks(tmp_env):
    tmp_folder, config_dir = tmp_env
    cfg.write_global_config(
        config_dir,
        {"block_shape": [8, 16, 16], "max_num_retries": 2,
         # half the blocks fail on attempt 1; allow retry anyway
         "retry_failure_fraction": 0.6},
    )
    out = {}
    t = FailingTask(tmp_folder, config_dir, shape=(32, 32, 32), out=out)
    build([t])
    n_blocks = 4 * 2 * 2
    assert sorted(out["calls"]) == list(range(n_blocks))
    # odd blocks ran twice, even blocks once
    for bid, n in out["attempts"].items():
        assert n == (2 if bid % 2 == 1 else 1)


def test_no_retry_raises(tmp_env):
    tmp_folder, config_dir = tmp_env
    cfg.write_global_config(
        config_dir, {"block_shape": [8, 16, 16], "max_num_retries": 0,
                     "retry_failure_fraction": 0.9}
    )
    t = FailingTask(tmp_folder, config_dir, shape=(32, 32, 32), out={})
    with pytest.raises(FailedBlocksError):
        build([t])
    # status file records the failed blocks for inspection
    status = t.output().read()
    assert status["failed"] and not status["complete"]


def test_resume_skips_done_blocks(tmp_env):
    tmp_folder, config_dir = tmp_env
    cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
    out = {}
    t = RecordingTask(tmp_folder, config_dir, shape=(32, 32, 32), out=out)
    build([t])
    first = len(out["calls"])
    # a second build must skip the completed task entirely
    build([RecordingTask(tmp_folder, config_dir, shape=(32, 32, 32), out=out)])
    assert len(out["calls"]) == first


def test_partial_resume_after_failure(tmp_env):
    tmp_folder, config_dir = tmp_env
    cfg.write_global_config(
        config_dir, {"block_shape": [8, 16, 16], "max_num_retries": 0,
                     "retry_failure_fraction": 0.9}
    )
    out = {}
    t = FailingTask(tmp_folder, config_dir, shape=(32, 32, 32), out=out)
    with pytest.raises(FailedBlocksError):
        build([t])
    done_first = set(t.output().read()["done"])
    # re-running processes only the blocks that had failed
    t2 = FailingTask(tmp_folder, config_dir, shape=(32, 32, 32), out=out)
    build([t2])
    assert set(t2.output().read()["done"]) == set(range(16))
    reran = [b for b, n in out["attempts"].items() if n > 1]
    assert set(reran).isdisjoint(done_first)


def test_workflow_chain_order(tmp_env):
    tmp_folder, config_dir = tmp_env
    calls = []

    class A(RecordingTask):
        task_name = "task_a"

        def process_block(self, block_id, blocking, config):
            calls.append(("a", block_id))

    class B(RecordingTask):
        task_name = "task_b"

        def process_block(self, block_id, blocking, config):
            calls.append(("b", block_id))

    class WF(WorkflowBase):
        task_name = "wf"

        def requires(self):
            a = A(self.tmp_folder, self.config_dir)
            b = B(self.tmp_folder, self.config_dir, dependencies=[a])
            return [b]

    wf = WF(tmp_folder, config_dir)
    build([wf])
    names = [c[0] for c in calls]
    assert set(names) == {"a", "b"}
    assert names.index("b") > names.index("a")  # all a's before any b
    assert names == sorted(names)
    assert wf.complete()


def test_status_records_per_dispatch_timings(tmp_env):
    """VERDICT item 9: per-block (local) / per-batch (tpu) device timings
    land in the status file."""
    tmp_folder, config_dir = tmp_env
    t = RecordingTask(tmp_folder, config_dir, out={})
    build([t])
    timings = t.output().read()["timings"]
    # local executor: one aggregate + one max record per dispatch round
    # (per-block records would make the status JSON O(n_blocks))
    by_label = {rec["label"]: rec for rec in timings}
    assert by_label["blocks_total"]["blocks"] == 2
    assert by_label["blocks_total"]["seconds"] >= 0.0
    assert by_label["block_max"]["blocks"] == 1


def test_profile_dir_writes_trace(tmp_env, tmp_path):
    """profile_dir config knob captures a jax profiler trace around the
    dispatches."""
    tmp_folder, config_dir = tmp_env
    profile_dir = str(tmp_path / "prof")
    cfg.write_config(config_dir, "recording", {"profile_dir": profile_dir})
    t = RecordingTask(tmp_folder, config_dir, out={})
    build([t])
    assert os.path.isdir(profile_dir)
    found = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(profile_dir)
        for f in fs
    ]
    assert found  # trace artifacts written


class BatchRecordingTask(RecordingTask):
    """Task with a batch path that records which thread ran each batch."""

    task_name = "batch_recording"

    def process_block_batch(self, block_ids, blocking, config):
        import threading
        import time as _t

        self.out.setdefault("batches", []).append(
            (threading.get_ident(), tuple(block_ids))
        )
        _t.sleep(0.05)  # widen the overlap window
        self.out.setdefault("calls", []).extend(block_ids)


@pytest.mark.parametrize("depth", [1, 3])
def test_tpu_executor_pipelines_batches(tmp_env, depth):
    """pipeline_depth batches run concurrently on the tpu target (host IO of
    batch i+1 overlaps device compute of batch i) with identical completion
    records; depth 1 restores the serial loop."""
    tmp_folder, config_dir = tmp_env
    cfg.write_global_config(
        config_dir,
        {"block_shape": [4, 32, 32], "target": "tpu",
         "device_batch_size": 1, "devices": [0],  # 8 blocks -> 8 batches
         "pipeline_depth": depth},
    )
    out = {}
    t = BatchRecordingTask(tmp_folder, config_dir, out=out)
    build([t])
    assert sorted(out["calls"]) == list(range(8))
    status = t.output().read()
    assert status["complete"] and len(status["done"]) == 8
    threads = {tid for tid, _ in out["batches"]}
    if depth == 1:
        assert len(threads) == 1
    else:
        assert len(threads) > 1  # really ran on a pipeline pool


def test_pipeline_batch_failure_falls_back_per_block(tmp_env):
    """A poisoned batch inside the pipeline still falls back to per-block
    execution and only truly-failing blocks are recorded as failed."""
    tmp_folder, config_dir = tmp_env
    cfg.write_global_config(
        config_dir,
        {"block_shape": [4, 32, 32], "target": "tpu",
         "device_batch_size": 2, "devices": [0], "pipeline_depth": 2},
    )

    class PoisonBatchTask(RecordingTask):
        task_name = "poison_batch"

        def process_block_batch(self, block_ids, blocking, config):
            if 2 in block_ids:
                raise RuntimeError("poisoned batch")
            self.out.setdefault("calls", []).extend(block_ids)

    out = {}
    t = PoisonBatchTask(tmp_folder, config_dir, out=out)
    build([t])
    status = t.output().read()
    assert status["complete"] and sorted(status["done"]) == list(range(8))


def test_local_executor_honors_pipeline_safe(tmp_env):
    """pipeline_safe=False serializes the LocalExecutor thread pool too (the
    MWS pass-2 path has no batch dispatch and runs through LocalExecutor)."""
    import threading

    tmp_folder, config_dir = tmp_env
    cfg.write_global_config(
        config_dir, {"block_shape": [4, 32, 32], "max_jobs": 4}
    )

    class UnsafeTask(RecordingTask):
        task_name = "unsafe"
        pipeline_safe = False

        def process_block(self, block_id, blocking, config):
            self.out.setdefault("threads", set()).add(threading.get_ident())
            self.out.setdefault("calls", []).append(block_id)

    out = {}
    t = UnsafeTask(tmp_folder, config_dir, out=out)
    build([t])
    assert sorted(out["calls"]) == list(range(8))
    assert len(out["threads"]) == 1


def test_device_batch_size_pin_resolution(tmp_env, monkeypatch):
    """device_batch_size: null resolves CTT_DEVICE_BATCH (env, then the
    backend-tagged pin file) before the backend default."""
    import json

    from cluster_tools_tpu.ops import _backend

    tmp_folder, config_dir = tmp_env
    cfg.write_global_config(
        config_dir,
        {"block_shape": [4, 32, 32], "target": "tpu",
         "device_batch_size": None, "devices": [0]},
    )

    # env pin: 8 blocks at batch 4 -> 2 batches
    monkeypatch.setenv("CTT_DEVICE_BATCH", "4")
    out = {}
    t = BatchRecordingTask(tmp_folder, config_dir, out=out)
    build([t])
    assert sorted(out["calls"]) == list(range(8))
    assert sorted(len(b) for _, b in out["batches"]) == [4, 4]

    # pin file (backend-tagged): batch 2 -> 4 batches
    monkeypatch.delenv("CTT_DEVICE_BATCH")
    import jax

    pin_path = os.path.join(tmp_folder, "modes.json")
    with open(pin_path, "w") as f:
        json.dump({"backend": jax.default_backend(),
                   "modes": {"CTT_DEVICE_BATCH": "2"}}, f)
    monkeypatch.setenv("CTT_MODES_FILE", pin_path)
    _backend._PINS_CACHE.clear()
    out2 = {}
    t2 = BatchRecordingTask(
        tmp_folder + "_pin", config_dir, out=out2)
    try:
        build([t2])
    finally:
        _backend._PINS_CACHE.clear()
    assert sorted(len(b) for _, b in out2["batches"]) == [2, 2, 2, 2]
