"""Multi-device execution through the *production* framework path.

The conftest forces an 8-device virtual CPU mesh; these tests drive real
workflows with ``target='tpu'`` and assert (a) output parity with the
``local`` oracle target and (b) that the task batches were actually
partitioned over all devices (``parallel.mesh.last_batch_sharding``) — the
framework analog of the reference's N-independent-scheduler-jobs scale
mechanism (reference cluster_tasks.py:331,388-624).
"""

import numpy as np
import pytest
from scipy import ndimage

import jax

from cluster_tools_tpu.parallel import mesh as mesh_mod
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import ThresholdedComponentsWorkflow
from cluster_tools_tpu.workflows.watershed import WatershedWorkflow

N_DEV = 8


def _require_devices():
    if jax.device_count() < N_DEV:
        pytest.skip(f"needs {N_DEV} devices, have {jax.device_count()}")


def _make_volume(tmp_path, rng, shape=(32, 64, 64)):
    path = str(tmp_path / "data.n5")
    raw = ndimage.gaussian_filter(rng.random(shape), (1.0, 2.0, 2.0))
    raw = (raw - raw.min()) / (raw.max() - raw.min())
    f = file_reader(path)
    f.create_dataset("raw", data=raw.astype("float32"), chunks=(16, 32, 32))
    return path, raw


def _run_components(path, tmp_path, target, devices=None):
    tmp_folder = str(tmp_path / f"tmp_{target}")
    config_dir = str(tmp_path / f"configs_{target}")
    cfg.write_global_config(
        config_dir,
        {
            "block_shape": [16, 32, 32],
            "target": target,
            "device_batch_size": 1,
            "devices": devices,
        },
    )
    cfg.write_config(config_dir, "block_components", {"threshold": 0.55})
    wf = ThresholdedComponentsWorkflow(
        tmp_folder,
        config_dir,
        input_path=path,
        input_key="raw",
        output_path=path,
        output_key=f"components_{target}",
    )
    assert build([wf])
    return file_reader(path, "r")[f"components_{target}"][:]


def test_components_workflow_shards_over_all_devices(tmp_path, rng):
    """A full workflow with target='tpu' must run with its block batches
    sharded over the whole mesh and agree with the local oracle."""
    _require_devices()
    path, raw = _make_volume(tmp_path, rng)

    got_local = _run_components(path, tmp_path, "local")
    mesh_mod._LAST_BATCH_SHARDING = None
    got_tpu = _run_components(path, tmp_path, "tpu")

    sharding = mesh_mod.last_batch_sharding()
    assert sharding is not None, "tpu path never placed a batch"
    assert len(sharding.device_set) == N_DEV, (
        f"batch landed on {len(sharding.device_set)} device(s), expected {N_DEV}"
    )

    # same partition (component ids may differ, the partition must not)
    from cluster_tools_tpu.ops.evaluation import same_partition

    assert same_partition(got_tpu, got_local)


def test_components_device_subset(tmp_path, rng):
    """The ``devices`` config knob restricts the mesh to the given devices."""
    _require_devices()
    path, _ = _make_volume(tmp_path, rng, shape=(64, 32, 32))  # 4 blocks
    mesh_mod._LAST_BATCH_SHARDING = None
    _run_components(path, tmp_path, "tpu", devices=[0, 1, 2, 3])
    sharding = mesh_mod.last_batch_sharding()
    assert sharding is not None
    assert len(sharding.device_set) == 4


def test_watershed_workflow_tpu_matches_local(tmp_path, rng):
    """The flagship DT-watershed runs device-batched + sharded and produces
    exactly the local result (same kernels, so bitwise parity holds)."""
    _require_devices()
    path, _ = _make_volume(tmp_path, rng)

    outs = {}
    for target in ("local", "tpu"):
        tmp_folder = str(tmp_path / f"ws_tmp_{target}")
        config_dir = str(tmp_path / f"ws_configs_{target}")
        cfg.write_global_config(
            config_dir,
            {"block_shape": [16, 32, 32], "target": target,
             "device_batch_size": 1},
        )
        cfg.write_config(
            config_dir,
            "watershed",
            {"threshold": 0.6, "sigma_seeds": 1.5, "size_filter": 10},
        )
        if target == "tpu":
            mesh_mod._LAST_BATCH_SHARDING = None
        wf = WatershedWorkflow(
            tmp_folder,
            config_dir,
            input_path=path,
            input_key="raw",
            output_path=path,
            output_key=f"ws_{target}",
        )
        assert build([wf])
        outs[target] = file_reader(path, "r")[f"ws_{target}"][:]

    sharding = mesh_mod.last_batch_sharding()
    assert sharding is not None and len(sharding.device_set) == N_DEV
    assert outs["tpu"].max() > 0
    np.testing.assert_array_equal(outs["tpu"], outs["local"])


def test_masked_components_batch_path(tmp_path, rng):
    """Regression: the device-batched mask branch must write into a writable
    host copy (np.asarray of a jit output is read-only)."""
    _require_devices()
    path, raw = _make_volume(tmp_path, rng, shape=(16, 32, 32))
    mask = np.zeros(raw.shape, dtype="uint8")
    mask[:, :16, :] = 1
    file_reader(path).create_dataset("mask", data=mask, chunks=(16, 16, 16))

    tmp_folder = str(tmp_path / "tmp_masked")
    config_dir = str(tmp_path / "configs_masked")
    cfg.write_global_config(
        config_dir,
        {"block_shape": [8, 16, 16], "target": "tpu", "device_batch_size": 1},
    )
    cfg.write_config(config_dir, "block_components", {"threshold": 0.55})
    wf = ThresholdedComponentsWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="raw",
        output_path=path, output_key="cc_masked",
        mask_path=path, mask_key="mask",
    )
    assert build([wf])
    got = file_reader(path, "r")["cc_masked"][:]
    assert (got[:, 16:, :] == 0).all()
    assert got.max() > 0
