"""Volume-ops tasks: copy_volume, linear transformation, masking.

Oracles are single-shot numpy/scipy recomputations over the whole volume
(the reference test style, SURVEY.md §4 idiom 2).
"""

import json
import os

import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


def _env(tmp_path, name, block_shape=(16, 16, 16), **extra):
    tmp_folder = str(tmp_path / f"tmp_{name}")
    config_dir = str(tmp_path / f"configs_{name}")
    cfg.write_global_config(
        config_dir, {"block_shape": list(block_shape), **extra}
    )
    return tmp_folder, config_dir


class TestCopyVolume:
    def _data(self, tmp_path, rng, shape=(32, 32, 32)):
        path = str(tmp_path / "data.n5")
        raw = rng.random(shape).astype("float32")
        file_reader(path).create_dataset("raw", data=raw, chunks=(16, 16, 16))
        return path, raw

    def test_plain_copy_and_cast(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.copy_volume import CopyVolumeTask

        path, raw = self._data(tmp_path, rng)
        tmp_folder, config_dir = _env(tmp_path, "copy")
        task = CopyVolumeTask(
            tmp_folder, config_dir,
            input_path=path, input_key="raw",
            output_path=path, output_key="copy",
            dtype="uint8",
        )
        assert build([task])
        out = file_reader(path, "r")["copy"]
        assert str(out.dtype) == "uint8"
        got = out[:]
        # uint8 cast normalizes per block then scales to 255 (the reference's
        # cast_type applies vu.normalize to block data) — order is preserved
        # within each block
        assert got.shape == raw.shape
        block = (slice(0, 16),) * 3
        flat_r = raw[block].ravel()
        flat_g = got[block].ravel()
        idx = np.argsort(flat_r)
        assert (np.diff(flat_g[idx].astype(np.int32)) >= 0).all()
        assert flat_g.min() == 0 and flat_g.max() == 255

    def test_offset_value_list_insert(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.copy_volume import CopyVolumeTask

        path = str(tmp_path / "labels.n5")
        seg = rng.integers(0, 5, size=(32, 32, 32)).astype("uint64")
        f = file_reader(path)
        f.create_dataset("seg", data=seg, chunks=(16, 16, 16))
        base = np.full(seg.shape, 7, dtype="uint64")
        f.create_dataset("out", data=base, chunks=(16, 16, 16))

        tmp_folder, config_dir = _env(tmp_path, "copy2")
        cfg.write_config(
            config_dir, "copy_volume",
            {"value_list": [1, 2], "offset": 100, "insert_mode": True},
        )
        task = CopyVolumeTask(
            tmp_folder, config_dir,
            input_path=path, input_key="seg",
            output_path=path, output_key="out",
        )
        assert build([task])
        got = file_reader(path, "r")["out"][:]
        keep = np.isin(seg, [1, 2])
        assert (got[keep] == seg[keep] + 100).all()
        assert (got[~keep] == 7).all()  # insert mode keeps previous data

    def test_reduce_channels(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.copy_volume import CopyVolumeTask

        path = str(tmp_path / "affs.n5")
        affs = rng.random((3, 32, 32, 32)).astype("float32")
        file_reader(path).create_dataset(
            "affs", data=affs, chunks=(1, 16, 16, 16)
        )
        tmp_folder, config_dir = _env(tmp_path, "copy3")
        cfg.write_config(config_dir, "copy_volume", {"reduce_channels": "max"})
        task = CopyVolumeTask(
            tmp_folder, config_dir,
            input_path=path, input_key="affs",
            output_path=path, output_key="bmap",
        )
        assert build([task])
        got = file_reader(path, "r")["bmap"][:]
        assert got.shape == affs.shape[1:]
        np.testing.assert_allclose(got, affs.max(axis=0), rtol=1e-6)


class TestLinearTransformation:
    def test_global_trafo(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.transformations import LinearTransformationTask

        path = str(tmp_path / "data.n5")
        raw = rng.random((32, 32, 32)).astype("float32")
        file_reader(path).create_dataset("raw", data=raw, chunks=(16, 16, 16))
        trafo_file = str(tmp_path / "trafo.json")
        with open(trafo_file, "w") as f:
            json.dump({"a": 2.0, "b": -0.5}, f)

        tmp_folder, config_dir = _env(tmp_path, "linear")
        task = LinearTransformationTask(
            tmp_folder, config_dir,
            input_path=path, input_key="raw",
            output_path=path, output_key="out",
            transformation=trafo_file,
        )
        assert build([task])
        got = file_reader(path, "r")["out"][:]
        np.testing.assert_allclose(got, 2.0 * raw - 0.5, rtol=1e-5)

    def test_per_slice_trafo_with_mask(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.transformations import LinearTransformationTask

        shape = (32, 32, 32)
        path = str(tmp_path / "data.n5")
        raw = rng.random(shape).astype("float32")
        mask = (rng.random(shape) > 0.5)
        f = file_reader(path)
        f.create_dataset("raw", data=raw, chunks=(16, 16, 16))
        f.create_dataset(
            "mask", data=mask.astype("uint8"), chunks=(16, 16, 16)
        )
        trafo = {str(z): {"a": 1.0 + 0.1 * z, "b": 0.01 * z}
                 for z in range(shape[0])}
        trafo_file = str(tmp_path / "trafo.json")
        with open(trafo_file, "w") as f2:
            json.dump(trafo, f2)

        tmp_folder, config_dir = _env(tmp_path, "linear2")
        task = LinearTransformationTask(
            tmp_folder, config_dir,
            input_path=path, input_key="raw",
            output_path=path, output_key="out",
            transformation=trafo_file,
            mask_path=path, mask_key="mask",
        )
        assert build([task])
        got = file_reader(path, "r")["out"][:]
        a = (1.0 + 0.1 * np.arange(shape[0]))[:, None, None].astype("float32")
        b = (0.01 * np.arange(shape[0]))[:, None, None].astype("float32")
        want = np.where(mask, a * raw + b, raw)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestMasking:
    def test_blocks_from_mask(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.masking import BlocksFromMaskTask

        shape = (32, 64, 64)
        # low-res mask: only the first octant is active
        mask = np.zeros((16, 32, 32), dtype="uint8")
        mask[:8, :16, :16] = 1
        path = str(tmp_path / "mask.n5")
        file_reader(path).create_dataset("mask", data=mask, chunks=(8, 16, 16))

        tmp_folder, config_dir = _env(tmp_path, "bfm")
        out_path = str(tmp_path / "blocks.json")
        task = BlocksFromMaskTask(
            tmp_folder, config_dir,
            mask_path=path, mask_key="mask",
            shape=shape, output_path=out_path,
        )
        assert build([task])
        with open(out_path) as f:
            block_list = json.load(f)
        # full grid is (2, 4, 4) = 32 blocks of [16,16,16]; active octant =
        # z blocks 0 (z<16), y blocks 0-1 (y<32), x blocks 0-1 → 4 blocks
        assert sorted(block_list) == [0, 1, 4, 5]

    def test_minfilter_matches_scipy(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.masking import MinfilterTask

        shape = (32, 32, 32)
        mask = (ndimage.gaussian_filter(rng.random(shape), 2.0) > 0.5)
        path = str(tmp_path / "mask.n5")
        file_reader(path).create_dataset(
            "mask", data=mask.astype("uint8"), chunks=(16, 16, 16)
        )
        tmp_folder, config_dir = _env(tmp_path, "minf")
        filter_shape = [5, 5, 5]
        cfg.write_config(config_dir, "minfilter", {"filter_shape": filter_shape})
        task = MinfilterTask(
            tmp_folder, config_dir,
            input_path=path, input_key="mask",
            output_path=path, output_key="min_mask",
        )
        assert build([task])
        got = file_reader(path, "r")["min_mask"][:]
        want = ndimage.minimum_filter(
            mask.astype("float32"), size=filter_shape, mode="reflect"
        ).astype("uint8")
        np.testing.assert_array_equal(got, want)
