"""Pallas per-slice CC + device z-merge vs the XLA CC and scipy.

Mirrors tests/test_pallas_flood.py: the Mosaic lowering itself can only be
exercised on hardware (tools/tpu_validate.py); here the kernel runs in the
CPU interpreter, which executes identical kernel logic."""

import numpy as np
import pytest

from cluster_tools_tpu.ops.cc import (
    connected_components,
    connected_components_np,
)
from cluster_tools_tpu.ops.pallas_cc import (
    cc_slices,
    pallas_cc_available,
    pallas_connected_components,
)


def _random_mask(rng, shape, p=0.5):
    return rng.random(shape) < p


class TestPallasCC:
    @pytest.mark.parametrize("p", [0.2, 0.5, 0.8])
    def test_matches_scipy_partition(self, rng, p):
        mask = _random_mask(rng, (6, 16, 128), p)
        labels, n = pallas_connected_components(mask, interpret=True)
        labels = np.asarray(labels)
        want, n_want = connected_components_np(mask, connectivity=1)
        assert int(n) == n_want
        # identical partitions
        fg = mask
        pairs = np.unique(
            np.stack([labels[fg], want[fg]], axis=1), axis=0
        )
        assert len(pairs) == n_want
        assert (labels[~fg] == 0).all()

    def test_matches_xla_cc_exactly(self, rng):
        """Not just the partition: the consecutive numbering (minimal-flat-
        index root order) must be identical, so the paths are drop-in
        interchangeable mid-pipeline."""
        mask = _random_mask(rng, (4, 8, 128), 0.55)
        want, n_want = connected_components(mask, connectivity=1)
        got, n_got = pallas_connected_components(mask, interpret=True)
        assert int(n_got) == int(n_want)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_serpentine_corridor_converges(self):
        """A row-serpentine in one slice plus a z-bridge: full rows joined by
        alternating single-cell connectors."""
        mask = np.zeros((2, 16, 128), dtype=bool)
        for r in range(0, 16, 2):
            mask[0, r, :] = True
        for r in range(1, 16, 2):
            mask[0, r, 0 if (r // 2) % 2 == 0 else 127] = True
        mask[1] = mask[0]  # z-bridge everywhere
        labels, n = pallas_connected_components(mask, interpret=True)
        want, n_want = connected_components_np(mask, connectivity=1)
        assert int(n) == n_want == 1

    def test_banded_serpentine_needs_many_rounds(self):
        """The adversarial case that breaks any H+W-style round cap: bands
        of vertical serpentines chained into ONE component that needs
        Θ(H·W) propagation rounds, plus a separate isolated cell whose
        numbering must not be disturbed."""
        h, w = 16, 128
        mask = np.zeros((1, h, w), dtype=bool)
        # vertical columns, connected alternately at top/bottom: a
        # column-serpentine spanning the whole slice
        for c in range(0, w - 2, 2):
            mask[0, :, c] = True
            mask[0, 0 if (c // 2) % 2 else h - 1, c + 1] = True
        # isolated cell far away in the last column
        mask[0, h // 2, w - 1] = True
        labels, n = pallas_connected_components(mask, interpret=True)
        want, n_want = connected_components_np(mask[0], connectivity=1)
        assert int(n) == n_want == 2
        labels = np.asarray(labels)[0]
        fg = mask[0]
        pairs = np.unique(np.stack([labels[fg], want[fg]], axis=1), axis=0)
        assert len(pairs) == 2

    def test_slice_kernel_labels_are_minimal_flat_ids(self, rng):
        mask = _random_mask(rng, (3, 8, 128), 0.5)
        sliced = np.asarray(cc_slices(mask, interpret=True))
        n, h, w = mask.shape
        flat = np.arange(n * h * w, dtype=np.int64).reshape(mask.shape)
        for z in range(n):
            want, n_want = connected_components_np(mask[z], connectivity=1)
            for comp in range(1, n_want + 1):
                sel = want == comp
                ids = np.unique(sliced[z][sel])
                assert ids.size == 1
                assert ids[0] == flat[z][sel].min()
        assert (sliced[~mask] == -1).all()

    def test_availability_gating(self):
        from cluster_tools_tpu.ops import _backend

        shape = (6, 16, 128)
        # off by default
        assert not pallas_cc_available(shape, 1, False)
        with _backend.force_cc_mode("pallas"):
            import jax

            on_tpu = jax.default_backend() == "tpu"
            assert pallas_cc_available(shape, 1, False) == on_tpu
            # never for per-slice / higher connectivity / misaligned
            assert not pallas_cc_available(shape, 1, True)
            assert not pallas_cc_available(shape, 3, False)
            assert not pallas_cc_available((6, 16, 100), 1, False)
            assert not pallas_cc_available((16, 128), 1, False)
            # VMEM budget (ADVICE r3): oversized slices take the XLA path
            assert not pallas_cc_available((4, 1024, 1024), 1, False)

    def test_empty_and_full(self):
        for mask in (
            np.zeros((2, 8, 128), dtype=bool),
            np.ones((2, 8, 128), dtype=bool),
        ):
            labels, n = pallas_connected_components(mask, interpret=True)
            want, n_want = connected_components_np(mask, connectivity=1)
            assert int(n) == n_want
