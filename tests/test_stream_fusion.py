"""ctt-stream: cross-task fused streaming execution.

Contract under test (ISSUE 7): a declared threshold → thresholded-components
→ watershed chain executes as ONE streaming pass — byte-identical to the
task-at-a-time pipeline (zarr + n5, with halos, local + device-sharded
targets, under injected faults), with the threshold mask elided, the
merge-offsets/block-faces outputs produced from carried state, strictly
lower store read traffic, and a zero-overhead fallback path.
"""

import os

import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu import faults
from cluster_tools_tpu.obs import metrics as obs_metrics, trace as obs_trace
from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.tasks.thresholded_components import (
    FACES_KEY,
    MAX_IDS_KEY,
    OFFSETS_NAME,
)
from cluster_tools_tpu.utils import file_reader, store as store_mod
from cluster_tools_tpu.workflows import StreamingSegmentationWorkflow

THRESHOLD = 0.55
WS_CONF = {
    "threshold": 0.5, "sigma_seeds": 1.6, "size_filter": 10,
    "halo": [2, 4, 4],
}


@pytest.fixture(autouse=True)
def _traced(tmp_path):
    """Metrics/tracing on (counters drive the assertions), chunk LRU off
    (byte counts must reflect codec-boundary traffic), clean slate."""
    obs_metrics.reset()
    prev = store_mod.set_chunk_cache_budget(0)
    obs_trace.enable(str(tmp_path / "trace"), "stream_test", export_env=False)
    yield
    obs_trace.disable()
    store_mod.set_chunk_cache_budget(prev)
    obs_metrics.reset()


def _volume(shape=(24, 32, 32)):
    rng = np.random.default_rng(7)
    raw = ndimage.gaussian_filter(rng.random(shape), 1.0)
    return ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")


def _stage(tmp_path, ext="n5", shape=(24, 32, 32), chunks=(8, 16, 16)):
    path = str(tmp_path / f"data.{ext}")
    file_reader(path).create_dataset("raw", data=_volume(shape), chunks=chunks)
    return path


def _run(tmp_path, path, tag, fused=True, target="tpu", extra_global=None,
         watershed=True, max_retries=0):
    config_dir = str(tmp_path / f"configs_{tag}")
    gconf = {
        "block_shape": [8, 16, 16], "target": target,
        "stream_fusion": fused, "device_batch_size": 4,
        "max_num_retries": max_retries,
    }
    gconf.update(extra_global or {})
    cfg.write_global_config(config_dir, gconf)
    cfg.write_config(config_dir, "threshold", {"threshold": THRESHOLD})
    cfg.write_config(config_dir, "watershed", dict(WS_CONF))
    wf = StreamingSegmentationWorkflow(
        str(tmp_path / f"tmp_{tag}"), config_dir,
        input_path=path, input_key="raw",
        output_path=path, output_key=f"cc_{tag}",
        watershed=watershed,
    )
    before = obs_metrics.snapshot()["counters"]
    assert build([wf]), f"workflow failed ({tag})"
    after = obs_metrics.snapshot()["counters"]
    delta = {
        k: after.get(k, 0) - before.get(k, 0)
        for k in set(after) | set(before)
    }
    return wf, delta


def _read_scratch(tmp_folder, n_blocks):
    from cluster_tools_tpu.tasks.base import scratch_store_path

    store = file_reader(scratch_store_path(tmp_folder), "r")
    max_ids = [store[MAX_IDS_KEY].read_chunk((b,)) for b in range(n_blocks)]
    faces = [store[FACES_KEY].read_chunk((b,)) for b in range(n_blocks)]
    with np.load(os.path.join(tmp_folder, OFFSETS_NAME)) as f:
        offsets = {k: f[k] for k in f.files}
    return max_ids, faces, offsets


@pytest.mark.parametrize("ext", ["n5", "zarr"])
@pytest.mark.parametrize("target", ["local", "tpu"])
def test_fused_parity(tmp_path, ext, target):
    """Fused vs task-at-a-time: byte-identical final volumes AND carried
    merge state (max-id chunks, face-equivalence chunks, offsets npz)."""
    path = _stage(tmp_path, ext)
    _, d_fused = _run(tmp_path, path, "fused", fused=True, target=target)
    _, d_un = _run(tmp_path, path, "plain", fused=False, target=target)

    f = file_reader(path, "r")
    np.testing.assert_array_equal(f["cc_fused"][:], f["cc_plain"][:])
    np.testing.assert_array_equal(f["cc_fused_ws"][:], f["cc_plain_ws"][:])

    # recompute oracle: the merged components match scipy on the raw volume
    from cluster_tools_tpu.ops.evaluation import same_partition

    raw = f["raw"][:]
    want, n_want = ndimage.label(raw > THRESHOLD)
    assert n_want > 3
    assert same_partition(f["cc_fused"][:], want)

    # the threshold mask is elided on the fused path only
    assert "cc_fused_mask" not in f
    assert "cc_plain_mask" in f

    # carried merge state is byte-identical to the task-at-a-time scratch
    n_blocks = 12
    mi_f, fc_f, off_f = _read_scratch(str(tmp_path / "tmp_fused"), n_blocks)
    mi_p, fc_p, off_p = _read_scratch(str(tmp_path / "tmp_plain"), n_blocks)
    for a, b in zip(mi_f, mi_p):
        np.testing.assert_array_equal(a, b)
    assert any(c is not None and c.size for c in fc_f)
    for a, b in zip(fc_f, fc_p):
        np.testing.assert_array_equal(a, b)
    for k in off_p:
        np.testing.assert_array_equal(off_f[k], off_p[k])

    # stream accounting fired exactly once, on the fused run
    assert d_fused.get("stream.chains") == 1
    assert d_fused.get("stream.slabs", 0) >= 1
    assert d_fused.get("stream.elided_bytes", 0) > 0
    assert d_un.get("stream.chains", 0) == 0


def test_store_read_reduction(tmp_path):
    """The acceptance criterion: fused store.bytes_read at most half of the
    task-at-a-time run's (the raw volume crosses the codec boundary once,
    as batch superslabs; the mask round-trip and the faces re-read are
    gone)."""
    path = _stage(tmp_path, "n5", shape=(32, 64, 64), chunks=(8, 32, 32))
    _, d_fused = _run(
        tmp_path, path, "fused", fused=True,
        extra_global={"block_shape": [8, 32, 32]},
    )
    _, d_un = _run(
        tmp_path, path, "plain", fused=False,
        extra_global={"block_shape": [8, 32, 32]},
    )
    read_f = d_fused.get("store.bytes_read", 0)
    read_u = d_un.get("store.bytes_read", 0)
    assert read_f > 0 and read_u > 0
    assert read_u >= 2 * read_f, (read_u, read_f)
    assert d_un.get("store.bytes_written", 0) > d_fused.get(
        "store.bytes_written", 0
    )


def test_chaos_mid_slab_retry(tmp_path):
    """A mid-slab injected compute failure retries the whole batch without
    corrupting carried state: output stays byte-identical to a clean run."""
    path = _stage(tmp_path, "n5")
    _, d_clean = _run(tmp_path, path, "clean", fused=True)
    faults.configure("executor.stage_compute:fail:once;seed=3")
    try:
        _, d_chaos = _run(tmp_path, path, "chaos", fused=True, max_retries=2)
    finally:
        faults.reset()
    f = file_reader(path, "r")
    np.testing.assert_array_equal(f["cc_chaos"][:], f["cc_clean"][:])
    np.testing.assert_array_equal(f["cc_chaos_ws"][:], f["cc_clean_ws"][:])
    assert d_chaos.get("faults.injected", 0) > 0
    assert d_chaos.get("task.blocks_retried", 0) > 0
    assert d_chaos.get("stream.chains") == 1

    n_blocks = 12
    mi_a, fc_a, off_a = _read_scratch(str(tmp_path / "tmp_clean"), n_blocks)
    mi_b, fc_b, off_b = _read_scratch(str(tmp_path / "tmp_chaos"), n_blocks)
    for a, b in zip(fc_a, fc_b):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(off_a["offsets"], off_b["offsets"])


def test_store_fault_heals_inside_chain(tmp_path):
    """Transient store IO faults during the streaming pass ride the shared
    retry machinery exactly as in task-at-a-time runs."""
    path = _stage(tmp_path, "n5")
    _, _ = _run(tmp_path, path, "ref", fused=True)
    faults.configure("store.read:io_error:p=0.05;store.write:io_error:p=0.1;seed=5")
    try:
        _, d = _run(tmp_path, path, "heal", fused=True, max_retries=2)
    finally:
        faults.reset()
    f = file_reader(path, "r")
    np.testing.assert_array_equal(f["cc_heal"][:], f["cc_ref"][:])
    np.testing.assert_array_equal(f["cc_heal_ws"][:], f["cc_ref_ws"][:])
    assert d.get("store.io_retries", 0) > 0


def test_opt_out_config(tmp_path):
    """stream_fusion=false runs members task-at-a-time: the mask
    materializes and no stream counters fire."""
    path = _stage(tmp_path, "n5")
    _, delta = _run(tmp_path, path, "off", fused=False)
    assert "cc_off_mask" in file_reader(path, "r")
    assert delta.get("stream.chains", 0) == 0
    assert delta.get("stream.slabs", 0) == 0


def test_opt_out_env(tmp_path, monkeypatch):
    """CTT_STREAM_FUSION=0 is the process-wide kill switch."""
    monkeypatch.setenv("CTT_STREAM_FUSION", "0")
    path = _stage(tmp_path, "n5")
    _, delta = _run(tmp_path, path, "env", fused=True)
    assert "cc_env_mask" in file_reader(path, "r")
    assert delta.get("stream.chains", 0) == 0
    assert delta.get("stream.fallbacks", 0) >= 1


def test_partial_progress_falls_back(tmp_path):
    """A chain whose member already has task-at-a-time progress declines
    (resume safety) and the build completes unfused, same outputs."""
    from cluster_tools_tpu.tasks.threshold import ThresholdTask

    path = _stage(tmp_path, "n5")
    config_dir = str(tmp_path / "configs_pre")
    cfg.write_global_config(
        config_dir, {"block_shape": [8, 16, 16], "target": "tpu"}
    )
    cfg.write_config(config_dir, "threshold", {"threshold": THRESHOLD})
    tmp_folder = str(tmp_path / "tmp_resume")
    pre = ThresholdTask(
        tmp_folder, config_dir,
        input_path=path, input_key="raw",
        output_path=path, output_key="cc_resume_mask",
    )
    assert build([pre])

    config_dir2 = str(tmp_path / "configs_resume")
    cfg.write_global_config(
        config_dir2,
        {"block_shape": [8, 16, 16], "target": "tpu", "stream_fusion": True},
    )
    cfg.write_config(config_dir2, "threshold", {"threshold": THRESHOLD})
    cfg.write_config(config_dir2, "watershed", dict(WS_CONF))
    wf = StreamingSegmentationWorkflow(
        tmp_folder, config_dir2,
        input_path=path, input_key="raw",
        output_path=path, output_key="cc_resume",
    )
    before = obs_metrics.snapshot()["counters"]
    assert build([wf])
    after = obs_metrics.snapshot()["counters"]
    assert after.get("stream.fallbacks", 0) > before.get("stream.fallbacks", 0)
    assert after.get("stream.chains", 0) == before.get("stream.chains", 0)

    _, _ = _run(tmp_path, path, "oracle", fused=False)
    f = file_reader(path, "r")
    np.testing.assert_array_equal(f["cc_resume"][:], f["cc_oracle"][:])


def test_disabled_overhead_smoke(tmp_path):
    """No chain declared → the PR 3 codepath runs untouched: staged
    pipeline counters fire, stream counters do not."""
    from cluster_tools_tpu.tasks.threshold import ThresholdTask

    path = _stage(tmp_path, "n5")
    config_dir = str(tmp_path / "configs_plain_task")
    cfg.write_global_config(
        config_dir,
        {"block_shape": [8, 16, 16], "target": "tpu",
         "device_batch_size": 1, "devices": [0], "pipeline_depth": 3},
    )
    t = ThresholdTask(
        str(tmp_path / "tmp_plain_task"), config_dir,
        input_path=path, input_key="raw",
        output_path=path, output_key="mask_plain",
    )
    before = obs_metrics.snapshot()["counters"]
    assert build([t])
    after = obs_metrics.snapshot()["counters"]
    delta = {k: after.get(k, 0) - before.get(k, 0) for k in after}
    assert delta.get("executor.stage_batches", 0) > 0
    assert not any(k.startswith("stream.") for k, v in delta.items() if v)


def test_block_read_cache_serves_crops(tmp_path):
    """Unit: the batch cache serves sub-boxes of the superslab read
    byte-identically; non-box requests fall through to the store."""
    from cluster_tools_tpu.parallel.dispatch import (
        BlockReadCache,
        CachedDataset,
    )
    from cluster_tools_tpu.utils.blocking import Blocking

    path = _stage(tmp_path, "n5")
    ds = file_reader(path, "r")["raw"]
    blocking = Blocking((24, 32, 32), (8, 16, 16))
    cache = BlockReadCache()
    cache.prefetch(ds, path, "raw", blocking, [0, 1, 2, 3], (2, 4, 4))
    wrapped = CachedDataset(ds, cache, path, "raw")
    for bid in (0, 3):
        bh = blocking.block_with_halo(bid, (2, 4, 4))
        np.testing.assert_array_equal(
            wrapped[bh.outer.slicing], ds[bh.outer.slicing]
        )
        np.testing.assert_array_equal(
            wrapped[bh.inner.slicing], ds[bh.inner.slicing]
        )
    # out-of-prefetch region and non-box indexing both delegate
    np.testing.assert_array_equal(wrapped[20:24, :, :], ds[20:24, :, :])
    np.testing.assert_array_equal(wrapped[3], ds[3])
    assert wrapped.shape == ds.shape and wrapped.dtype == ds.dtype


def test_components_only_chain(tmp_path):
    """watershed=False: the two-member chain (threshold → components)
    fuses and matches scipy."""
    path = _stage(tmp_path, "n5")
    _, delta = _run(tmp_path, path, "two", fused=True, watershed=False)
    assert delta.get("stream.chains") == 1
    f = file_reader(path, "r")
    from cluster_tools_tpu.ops.evaluation import same_partition

    want, _ = ndimage.label(f["raw"][:] > THRESHOLD)
    assert same_partition(f["cc_two"][:], want)
    assert "cc_two_mask" not in f


def test_sharded_device_threshold_parity(tmp_path):
    """ctt-stream under the sharded collective: device-side threshold
    fused into the collective CC program matches the host-threshold
    ingest path exactly."""
    from cluster_tools_tpu.workflows import ThresholdedComponentsWorkflow

    path = _stage(tmp_path, "n5")
    outs = {}
    for tag, dev_thr in (("host", False), ("dev", True)):
        config_dir = str(tmp_path / f"configs_sh_{tag}")
        cfg.write_global_config(
            config_dir, {"block_shape": [8, 16, 16], "target": "tpu"}
        )
        cfg.write_config(
            config_dir, "sharded_components",
            {"threshold": THRESHOLD, "device_threshold": dev_thr},
        )
        wf = ThresholdedComponentsWorkflow(
            str(tmp_path / f"tmp_sh_{tag}"), config_dir,
            input_path=path, input_key="raw",
            output_path=path, output_key=f"sh_{tag}",
            sharded=True,
        )
        assert build([wf])
        outs[tag] = file_reader(path, "r")[f"sh_{tag}"][:]
    np.testing.assert_array_equal(outs["dev"], outs["host"])
