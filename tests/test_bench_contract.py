"""The driver bench contract must be unlosable.

Rounds 3 and 4 both ended with no parseable perf number (dead tunnel /
driver-budget mismatch, VERDICT r4 item 1).  The contract is now:

  * bench.py (driver mode) prints the merged JSON line after EVERY config
    (flushed; last stdout line wins), so a kill mid-run keeps everything
    measured so far;
  * a global wall-clock deadline enforced inside bench.py
    (``CTT_BENCH_DEADLINE_S``) skips configs that no longer fit and still
    exits 0 with a valid final JSON line.

These tests drive bench.py exactly as the driver does (subprocess,
``timeout``-style budget) with a deadline small enough that every config is
forcibly over budget — the contract must survive.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run(env_extra, args=(), timeout=120):
    env = dict(os.environ)
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, BENCH, "--platform", "cpu", "--quick", *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


def _contract_lines(stdout):
    lines = [ln for ln in stdout.strip().splitlines() if ln.strip()]
    parsed = [json.loads(ln) for ln in lines]
    for p in parsed:
        assert set(p) == {"metric", "value", "unit", "vs_baseline", "extra"}
        assert p["metric"] == "dt_watershed_throughput_per_chip"
        assert p["unit"] == "Mvox/s"
    return parsed


@pytest.mark.timeout(180)
def test_contract_survives_zero_budget():
    """Every config over budget -> still exit 0 with a valid JSON line."""
    out = _run({"CTT_BENCH_DEADLINE_S": "1"})
    assert out.returncode == 0, out.stderr[-2000:]
    parsed = _contract_lines(out.stdout)
    assert parsed, "no JSON contract emitted"
    # every config must have been skipped by the deadline, not attempted
    assert out.stderr.count("skipped:") == 8, out.stderr[-2000:]


@pytest.mark.timeout(180)
def test_contract_checkpointed_incrementally():
    """The merged line exists from second zero (before any config runs):
    the first stdout line is already a parseable contract."""
    out = _run({"CTT_BENCH_DEADLINE_S": "1"})
    assert out.returncode == 0
    first = _contract_lines(out.stdout)[0]
    assert first["value"] is None  # null contract, but structurally valid
