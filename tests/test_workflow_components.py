"""End-to-end ThresholdedComponentsWorkflow test: the first full slice
(SURVEY.md §7 minimum end-to-end slice) with a recompute oracle — the result
must be the same partition scipy.ndimage.label produces on the whole volume."""

import os

import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import RelabelWorkflow, ThresholdedComponentsWorkflow


def _make_volume(tmp_path, rng, shape=(40, 40, 40)):
    path = str(tmp_path / "data.n5")
    # smooth random field → nontrivial components crossing block borders
    raw = ndimage.gaussian_filter(rng.random(shape), 1.0)
    raw = (raw - raw.min()) / (raw.max() - raw.min())
    f = file_reader(path)
    f.create_dataset("raw", data=raw.astype("float32"), chunks=(16, 16, 16))
    return path, raw


def _assert_same_partition(got, want):
    from cluster_tools_tpu.ops.evaluation import same_partition

    assert same_partition(got, want)


@pytest.mark.parametrize("target", ["local", "tpu"])
def test_thresholded_components_matches_scipy(tmp_path, rng, target):
    path, raw = _make_volume(tmp_path, rng)
    tmp_folder = str(tmp_path / f"tmp_{target}")
    config_dir = str(tmp_path / f"configs_{target}")
    cfg.write_global_config(
        config_dir, {"block_shape": [16, 16, 16], "target": target}
    )
    threshold = 0.55
    cfg.write_config(config_dir, "block_components", {"threshold": threshold})

    wf = ThresholdedComponentsWorkflow(
        tmp_folder,
        config_dir,
        input_path=path,
        input_key="raw",
        output_path=path,
        output_key="components",
    )
    assert build([wf])

    got = file_reader(path, "r")["components"][:]
    want, n_want = ndimage.label(raw > threshold)
    assert n_want > 5  # fixture sanity: nontrivial component structure
    _assert_same_partition(got, want)


def test_sharded_components_workflow_matches_block_pipeline(tmp_path, rng):
    """sharded=True routes through ONE collective task (z-sharded volume +
    ICI boundary exchange) and must produce the block pipeline's partition."""
    path, raw = _make_volume(tmp_path, rng)
    threshold = 0.55
    outs = {}
    for name, sharded in [("blocks", False), ("sharded", True)]:
        tmp_folder = str(tmp_path / f"tmp_{name}")
        config_dir = str(tmp_path / f"configs_{name}")
        cfg.write_global_config(
            config_dir, {"block_shape": [16, 16, 16], "target": "tpu"}
        )
        cfg.write_config(config_dir, "block_components", {"threshold": threshold})
        cfg.write_config(config_dir, "sharded_components", {"threshold": threshold})
        wf = ThresholdedComponentsWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="raw",
            output_path=path, output_key=f"components_{name}",
            sharded=sharded,
        )
        assert build([wf])
        outs[name] = file_reader(path, "r")[f"components_{name}"][:]

    want, _ = ndimage.label(raw > threshold)
    _assert_same_partition(outs["sharded"], want)
    _assert_same_partition(outs["sharded"], outs["blocks"])
    # consecutive uint64 ids, background preserved
    ids = np.unique(outs["sharded"])
    assert ids[0] == 0 and (np.diff(ids) == 1).all()


def test_relabel_workflow_makes_consecutive(tmp_path, rng):
    path = str(tmp_path / "data.zarr")
    labels = rng.choice([0, 7, 1000, 123456789], size=(24, 24, 24)).astype("uint64")
    f = file_reader(path)
    f.create_dataset("seg", data=labels, chunks=(12, 12, 12))
    tmp_folder = str(tmp_path / "tmp")
    config_dir = str(tmp_path / "configs")
    cfg.write_global_config(config_dir, {"block_shape": [12, 12, 12]})

    wf = RelabelWorkflow(
        tmp_folder,
        config_dir,
        input_path=path,
        input_key="seg",
        output_path=path,
        output_key="seg_relabeled",
    )
    assert build([wf])
    out = file_reader(path, "r")["seg_relabeled"][:]
    assert set(np.unique(out)) == {0, 1, 2, 3}
    # same partition as input
    for old, new in [(7, None), (1000, None), (123456789, None)]:
        vals = np.unique(out[labels == old])
        assert len(vals) == 1 and vals[0] > 0
    assert (out[labels == 0] == 0).all()


def test_components_workflow_is_resumable(tmp_path, rng):
    path, raw = _make_volume(tmp_path, rng, shape=(32, 32, 32))
    tmp_folder = str(tmp_path / "tmp")
    config_dir = str(tmp_path / "configs")
    cfg.write_global_config(config_dir, {"block_shape": [16, 16, 16]})
    wf = ThresholdedComponentsWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="raw",
        output_path=path, output_key="components",
    )
    assert build([wf])
    # completed workflow: a fresh build() call must be a no-op (complete targets)
    assert wf.complete()
    assert build([wf])


def test_sharded_components_streaming_mask_and_nondivisible_z(tmp_path, rng):
    """The sigma=0 streaming path (per-shard store reads + device
    threshold) with a store-backed mask and a z extent the 8-device mesh
    does not divide must match scipy exactly."""
    from scipy import ndimage

    from cluster_tools_tpu.tasks.thresholded_components import (
        ShardedComponentsTask,
    )

    shape = (13, 16, 16)  # 13 % 8 != 0 → internal pad slab
    raw = rng.random(shape).astype("float32")
    m = rng.random(shape) < 0.8
    path = str(tmp_path / "s.n5")
    f = file_reader(path)
    f.create_dataset("raw", data=raw, chunks=(8, 16, 16))
    f.create_dataset("m", data=m.astype("uint8"), chunks=(8, 16, 16))
    config_dir = str(tmp_path / "configs")
    cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
    cfg.write_config(
        config_dir, "sharded_components",
        {"threshold": 0.5, "threshold_mode": "less"},
    )
    task = ShardedComponentsTask(
        str(tmp_path / "tmp"), config_dir,
        input_path=path, input_key="raw",
        output_path=path, output_key="cc",
        mask_path=path, mask_key="m",
    )
    assert build([task])
    got = file_reader(path, "r")["cc"][:]
    want, n_want = ndimage.label((raw < 0.5) & m)
    _assert_same_partition(got, want)
    assert int(file_reader(path, "r")["cc"].attrs["n_labels"]) == n_want
