"""Debugging workflows, per-object VI, sub-solutions."""

import os

import numpy as np
import pytest

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


class TestCheckSubGraphs:
    def test_valid_graph_passes(self, tmp_path, rng):
        from cluster_tools_tpu.workflows import CheckSubGraphsWorkflow

        labels = rng.integers(1, 20, (16, 32, 32)).astype("uint64")
        path = str(tmp_path / "c.n5")
        file_reader(path).create_dataset("ws", data=labels, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        wf = CheckSubGraphsWorkflow(
            tmp_folder, config_dir, ws_path=path, ws_key="ws"
        )
        assert build([wf])

    def test_corrupted_serialization_fails(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.debugging import CheckSubGraphsTask
        from cluster_tools_tpu.tasks.graph import SUB_NODES_KEY
        from cluster_tools_tpu.workflows import GraphWorkflow

        labels = rng.integers(1, 20, (16, 32, 32)).astype("uint64")
        path = str(tmp_path / "cc.n5")
        file_reader(path).create_dataset("ws", data=labels, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs2")
        tmp_folder = str(tmp_path / "tmp2")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        graph = GraphWorkflow(
            tmp_folder, config_dir, input_path=path, input_key="ws"
        )
        assert build([graph])
        # corrupt one block's serialized node list
        store = file_reader(os.path.join(tmp_folder, "data.zarr"), "a")
        ds = store[SUB_NODES_KEY]
        ds.write_chunk((0,), np.asarray([999999], dtype="uint64"))
        check = CheckSubGraphsTask(
            tmp_folder, config_dir, input_path=path, input_key="ws"
        )
        with pytest.raises(RuntimeError):
            build([check], raise_on_failure=True)


class TestCheckComponents:
    def test_fragmented_label_flagged(self, tmp_path):
        from cluster_tools_tpu.tasks.debugging import (
            VIOLATING_IDS_NAME,
            CheckComponentsTask,
        )

        # label 7 appears in every block; others are local
        labels = np.zeros((16, 32, 32), dtype="uint64")
        labels[::4] = 7
        labels[1, :16, :16] = 2
        path = str(tmp_path / "f.n5")
        file_reader(path).create_dataset("seg", data=labels, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        task = CheckComponentsTask(
            tmp_folder, config_dir,
            input_path=path, input_key="seg",
            max_blocks_per_label=4,
        )
        assert build([task])
        violating = np.load(os.path.join(tmp_folder, VIOLATING_IDS_NAME))
        assert 7 in violating[:, 0]
        assert 2 not in violating[:, 0]


class TestObjectVi:
    def test_perfect_segmentation_scores_zero(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.evaluation import load_object_vi
        from cluster_tools_tpu.tasks.evaluation import ObjectViTask
        from cluster_tools_tpu.tasks.node_labels import (
            BlockNodeLabelsTask,
            MergeNodeLabelsTask,
        )

        gt = rng.integers(1, 8, (16, 32, 32)).astype("uint64")
        path = str(tmp_path / "ov.n5")
        f = file_reader(path)
        f.create_dataset("seg", data=gt, chunks=(8, 16, 16))
        f.create_dataset("gt", data=gt, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        overlaps = BlockNodeLabelsTask(
            tmp_folder, config_dir,
            input_path=path, input_key="seg",
            labels_path=path, labels_key="gt",
        )
        merge = MergeNodeLabelsTask(
            tmp_folder, config_dir, dependencies=[overlaps],
            input_path=path, input_key="seg",
        )
        ovi = ObjectViTask(tmp_folder, config_dir, dependencies=[merge])
        assert build([ovi])
        scores = load_object_vi(tmp_folder)
        assert set(scores) == set(range(1, 8))
        for split, merge_s in scores.values():
            assert split == pytest.approx(0.0, abs=1e-9)
            assert merge_s == pytest.approx(0.0, abs=1e-9)


class TestSubSolutions:
    def test_sub_solutions_written(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.multicut import SubSolutionsTask
        from cluster_tools_tpu.workflows import (
            EdgeFeaturesWorkflow,
            GraphWorkflow,
        )
        from cluster_tools_tpu.tasks.costs import ProbsToCostsTask

        from scipy import ndimage

        labels = rng.integers(1, 30, (16, 32, 32)).astype("uint64")
        bnd = ndimage.gaussian_filter(
            rng.random((16, 32, 32)), 1.0
        ).astype("float32")
        path = str(tmp_path / "ss.n5")
        f = file_reader(path)
        f.create_dataset("ws", data=labels, chunks=(8, 16, 16))
        f.create_dataset("bnd", data=bnd, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        graph = GraphWorkflow(
            tmp_folder, config_dir, input_path=path, input_key="ws"
        )
        feats = EdgeFeaturesWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="bnd",
            labels_path=path, labels_key="ws",
            dependencies=[graph],
        )
        costs = ProbsToCostsTask(tmp_folder, config_dir, dependencies=[feats])
        sub = SubSolutionsTask(
            tmp_folder, config_dir,
            dependencies=[costs],
            input_path=path, input_key="ws",
            output_path=path, output_key="subsol",
        )
        assert build([sub])
        seg = file_reader(path, "r")["subsol"][:]
        assert seg.shape == labels.shape
        assert seg.max() > 0
        # within a block, voxels of one ws fragment share one sub-solution id
        frag_mask = labels[:8, :16, :16] == labels[0, 0, 0]
        vals = np.unique(seg[:8, :16, :16][frag_mask])
        assert vals.size == 1
