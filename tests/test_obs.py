"""ctt-obs: span recorder, cross-process shard merge, CLI contract.

Covers the subsystem's hard requirements:
  * disabled fast path records nothing and allocates nothing;
  * a two-REAL-process workflow run (mirroring test_cluster_executor's
    multi-host test) merges into ONE run with a consistent run id and
    non-overlapping span ids;
  * summarize exits 0 with >= 1 task span, 1 with none, 2 on malformed
    shards; diff exits 3 on regression beyond the threshold;
  * the record_timing bridge leaves the status-file schema untouched.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from cluster_tools_tpu.obs import metrics, trace
from cluster_tools_tpu.obs.export import (
    TraceFormatError,
    diff,
    load_run,
    summarize,
    to_chrome_trace,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def traced(tmp_path):
    """Enable tracing into a tmp dir for one test, restore cleanly."""
    metrics.reset()
    run_id = trace.enable(str(tmp_path / "trace"), "t_run", export_env=False)
    yield os.path.join(str(tmp_path / "trace"), run_id)
    trace.disable()
    metrics.reset()


def _obs_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "cluster_tools_tpu.obs", *args],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


# --------------------------------------------------------------------------
# disabled fast path


def test_disabled_is_noop_and_allocation_free(tmp_path):
    assert not trace.enabled()
    s1 = trace.span("a", kind="task")
    s2 = trace.span("b", kind="device", blocks=8)
    # the disabled path returns ONE shared singleton: no per-call objects,
    # no clock reads, no file IO
    assert s1 is s2
    with s1:
        s1.set(anything="goes")
    trace.event("x", "timing", 1.0)
    metrics.inc("store.bytes_read", 100)
    assert metrics.snapshot() == {"counters": {}, "gauges": {}}
    trace.flush()
    assert not (tmp_path / "trace").exists()


def test_disabled_overhead_smoke():
    import timeit as _timeit

    # 50k no-op spans in well under a second: the enabled-check fast path
    # (one global load + one identity return) cannot cost more
    secs = _timeit.timeit(lambda: trace.span("x", kind="host"), number=50_000)
    assert secs < 1.0, f"disabled span() path too slow: {secs:.3f}s"


# --------------------------------------------------------------------------
# in-process recording + export


def test_span_nesting_buckets_and_chrome_export(traced):
    with trace.span("mytask", kind="task"):
        with trace.span("dispatch", kind="dispatch", task="mytask"):
            with trace.span("read", kind="host_io"):
                pass
            with trace.span("batch", kind="device"):
                with trace.span("read2", kind="host_io"):
                    pass
    trace.flush()
    run = load_run(traced)
    s = summarize(run)
    assert s["run_id"] == "t_run"
    assert s["n_task_spans"] == 1
    row = s["tasks"]["mytask"]
    # distinct buckets exist and nested host_io is not double-counted
    # into device (self-time accounting)
    for col in ("wall_s", "host_io_s", "device_s", "collective_s", "host_s"):
        assert col in row
    assert row["n_spans"] == 5
    assert row["wall_s"] >= row["device_s"]

    chrome = to_chrome_trace(run)
    events = chrome["traceEvents"]
    assert any(e["ph"] == "X" and e["cat"] == "device" for e in events)
    # valid trace_event JSON: every X event carries ts/dur/pid/tid
    for e in events:
        if e["ph"] == "X":
            assert {"ts", "dur", "pid", "tid", "name"} <= set(e)
    json.dumps(chrome)  # serializable end to end


def test_error_inside_span_is_recorded(traced):
    with pytest.raises(ValueError):
        with trace.span("boom", kind="task"):
            raise ValueError("x")
    trace.flush()
    (span,) = load_run(traced)["spans"]
    assert span["attrs"]["error"] == "ValueError"


def test_parent_links_within_thread(traced):
    with trace.span("outer", kind="task"):
        with trace.span("inner", kind="host"):
            pass
    trace.flush()
    spans = {s["name"]: s for s in load_run(traced)["spans"]}
    assert spans["inner"]["parent"] == spans["outer"]["id"]
    assert spans["outer"]["parent"] is None


# --------------------------------------------------------------------------
# traced workflow run: task spans, record_timing bridge, schema stability


def test_traced_workflow_status_schema_and_task_spans(tmp_path, rng, traced):
    from cluster_tools_tpu.runtime import build, config as cfg
    from cluster_tools_tpu.utils import file_reader
    from cluster_tools_tpu.workflows import UniqueWorkflow

    labels = rng.integers(0, 100, (16, 24, 24)).astype(np.uint64)
    path = str(tmp_path / "d.n5")
    file_reader(path).create_dataset("seg", data=labels, chunks=(8, 12, 12))
    config_dir = str(tmp_path / "configs")
    tmp_folder = str(tmp_path / "tmp")
    cfg.write_global_config(
        config_dir, {"block_shape": [8, 12, 12], "target": "tpu"}
    )
    wf = UniqueWorkflow(
        tmp_folder, config_dir,
        input_path=path, input_key="seg",
        output_path=path, output_key="uniques",
    )
    assert build([wf])

    # satellite: the status-file schema is UNCHANGED by the span bridge —
    # resume/retry keep reading these exact keys
    status = json.load(
        open(os.path.join(tmp_folder, "status", "find_uniques.status.json"))
    )
    assert status["complete"] is True
    assert set(status) >= {
        "task", "n_blocks", "done", "failed", "block_runtimes", "timings",
        "blocks_done", "complete",
    }
    for t in status["timings"]:
        assert set(t) == {"label", "blocks", "seconds"}

    run = load_run(traced)
    s = summarize(run)
    assert s["n_task_spans"] >= 1
    assert "find_uniques" in s["tasks"]
    # _timings bridge: the same dispatch labels appear as timing spans
    timing_names = {
        sp["name"] for sp in run["spans"] if sp["kind"] == "timing"
    }
    assert {t["label"] for t in status["timings"]} <= timing_names
    # store counters flowed through metrics
    assert run["counters"].get("store.chunks_read", 0) > 0


# --------------------------------------------------------------------------
# cross-process merge: two real OS processes, one run


def test_two_process_run_merges_into_one_trace(tmp_path, rng):
    from cluster_tools_tpu.runtime import config as cfg
    from cluster_tools_tpu.utils import file_reader

    labels = rng.integers(0, 500, (16, 24, 24)).astype(np.uint64) * 3
    path = str(tmp_path / "d.n5")
    file_reader(path).create_dataset("seg", data=labels, chunks=(4, 12, 12))
    config_dir = str(tmp_path / "configs")
    tmp_folder = str(tmp_path / "tmp")
    trace_dir = str(tmp_path / "trace")
    cfg.write_global_config(
        config_dir,
        {"block_shape": [4, 12, 12], "num_processes": 2,
         "peer_wait_timeout_s": 120.0},
    )
    script = str(tmp_path / "driver.py")
    with open(script, "w") as f:
        f.write(
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "from cluster_tools_tpu.runtime import build\n"
            "from cluster_tools_tpu.workflows import UniqueWorkflow\n"
            f"wf = UniqueWorkflow({tmp_folder!r}, {config_dir!r},\n"
            f"    input_path={path!r}, input_key='seg',\n"
            f"    output_path={path!r}, output_key='uniques')\n"
            "assert build([wf])\n"
        )
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["CTT_TRACE_DIR"] = trace_dir
    env["CTT_RUN_ID"] = "two_proc"
    pkg_root = os.path.dirname(REPO)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    procs = []
    for pid in range(2):
        penv = dict(env)
        penv["CTT_PROCESS_ID"] = str(pid)
        procs.append(subprocess.Popen(
            [sys.executable, script], env=penv,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        ))
    for p in procs:
        _, err = p.communicate(timeout=300)
        assert p.returncode == 0, err.decode()[-2000:]

    run = load_run(os.path.join(trace_dir, "two_proc"))
    # one consistent run id across every shard (load_run rejects mixes)
    assert run["run_id"] == "two_proc"
    pids = {h["pid"] for h in run["headers"]}
    assert len(pids) == 2
    # non-overlapping span ids across processes
    ids = [s["id"] for s in run["spans"]]
    assert len(ids) == len(set(ids))
    # both processes recorded task spans (p1 ran its block shard)
    task_pids = {s["pid"] for s in run["spans"] if s["kind"] == "task"}
    assert task_pids == pids
    # and the merge barrier is visible from the waiting process
    assert any(s["kind"] == "barrier" for s in run["spans"])

    r = _obs_cli("summarize", os.path.join(trace_dir, "two_proc"))
    assert r.returncode == 0, r.stderr
    assert "find_uniques" in r.stdout


# --------------------------------------------------------------------------
# CLI contract


def _write_synthetic_run(run_dir, run_id, tasks):
    """Minimal hand-rolled run: one shard, one task span per (name, secs)."""
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "spans.p1.t1.jsonl"), "w") as f:
        f.write(json.dumps({
            "type": "header", "run": run_id, "pid": 1, "tid": 1,
            "host": "synth", "wall": 1000.0, "mono": 10.0,
        }) + "\n")
        t, sid = 10.0, 1
        for name, secs in tasks:
            f.write(json.dumps({
                "type": "span", "id": sid, "parent": None, "name": name,
                "kind": "task", "t0": t, "t1": t + secs, "pid": 1, "tid": 1,
            }) + "\n")
            t += secs
            sid += 1


def test_cli_summarize_exit_codes(tmp_path):
    run = str(tmp_path / "r1")
    _write_synthetic_run(run, "r1", [("taskA", 1.0)])
    r = _obs_cli("summarize", run)
    assert r.returncode == 0
    assert "taskA" in r.stdout

    # no task spans -> exit 1 (a run that recorded nothing must not pass CI)
    empty = str(tmp_path / "r_empty")
    os.makedirs(empty)
    with open(os.path.join(empty, "spans.p1.t1.jsonl"), "w") as f:
        f.write(json.dumps({
            "type": "header", "run": "r_empty", "pid": 1, "tid": 1,
            "host": "synth", "wall": 1000.0, "mono": 10.0,
        }) + "\n")
        f.write(json.dumps({
            "type": "span", "id": 1, "parent": None, "name": "io",
            "kind": "host_io", "t0": 10.0, "t1": 11.0, "pid": 1, "tid": 1,
        }) + "\n")
    assert _obs_cli("summarize", empty).returncode == 1


def test_cli_malformed_event_file_exits_nonzero(tmp_path):
    run = str(tmp_path / "bad")
    _write_synthetic_run(run, "bad", [("taskA", 1.0)])
    with open(os.path.join(run, "spans.p1.t1.jsonl"), "a") as f:
        f.write("this is not json\n")
    with pytest.raises(TraceFormatError):
        load_run(run)
    r = _obs_cli("summarize", run)
    assert r.returncode == 2
    assert "malformed" in r.stderr


def test_cli_diff_flags_regression(tmp_path):
    base = str(tmp_path / "base")
    fast = str(tmp_path / "fast")
    slow = str(tmp_path / "slow")
    _write_synthetic_run(base, "base", [("taskA", 1.0), ("taskB", 2.0)])
    _write_synthetic_run(fast, "fast", [("taskA", 1.05), ("taskB", 1.9)])
    _write_synthetic_run(slow, "slow", [("taskA", 1.0), ("taskB", 3.0)])

    ok = _obs_cli("diff", base, fast, "--threshold", "0.2")
    assert ok.returncode == 0, ok.stdout + ok.stderr

    bad = _obs_cli("diff", base, slow, "--threshold", "0.2")
    assert bad.returncode == 3
    assert "REGRESSED" in bad.stdout
    assert "taskB" in bad.stdout

    # programmatic API agrees
    d = diff(load_run(base), load_run(slow), threshold=0.2)
    assert d["n_regressed"] == 1
    (reg,) = [r for r in d["rows"] if r["regressed"]]
    assert reg["task"] == "taskB"


def test_diff_absolute_floor_ignores_jitter(tmp_path):
    a = str(tmp_path / "a")
    b = str(tmp_path / "b")
    # 10x relative growth but only 90 µs absolute — jitter, not regression
    _write_synthetic_run(a, "a", [("tiny", 1e-5)])
    _write_synthetic_run(b, "b", [("tiny", 1e-4)])
    d = diff(load_run(a), load_run(b), threshold=0.2, min_seconds=0.01)
    assert d["n_regressed"] == 0


def test_resolve_single_run_from_trace_dir(tmp_path):
    run = str(tmp_path / "trace" / "only_run")
    _write_synthetic_run(run, "only_run", [("taskA", 1.0)])
    # passing the parent trace dir resolves to the single run inside
    assert summarize(load_run(str(tmp_path / "trace")))["run_id"] == "only_run"
